"""Nemesis: drive a FaultPlan on the single-seed asyncio runtime.

The batched engine executes fault plans as pre-seeded pool rows
(engine/core.py); this module is the dual-mode twin — the same
:class:`~madsim_tpu.chaos.plan.FaultPlan`, compiled for the runtime's
seed into the same concrete event list, applied at the same virtual
times through the public chaos surface: ``Handle.kill/restart/pause/
resume``, ``NetSim.clog_*``/``slow_link``/``set_duplicate``, and
``Handle.set_clock_skew``. A workload checked in both execution modes
therefore faces the *same* fault trajectory in both (dual-mode parity
at the event level; timing within events follows each mode's own
latency model).

Usage, from inside ``Runtime.block_on``::

    nemesis = Nemesis(plan)          # seed defaults to the runtime's
    task = spawn(nemesis.run())      # or: await nemesis.run()
    ...
    print(nemesis.log)               # [(t_ns, FaultEvent), ...] applied
"""

from __future__ import annotations

from ..engine.core import (
    KIND_CLOG,
    KIND_CLOG_1W,
    KIND_CLOG_NODE,
    KIND_DUP_OFF,
    KIND_DUP_ON,
    KIND_KILL,
    KIND_PAUSE,
    KIND_RESTART,
    KIND_RESUME,
    KIND_SKEW,
    KIND_SLOW_LINK,
    KIND_SYNC_LOSS,
    KIND_SYNC_OK,
    KIND_TORN_OFF,
    KIND_TORN_ON,
    KIND_UNCLOG,
    KIND_UNCLOG_1W,
    KIND_UNCLOG_NODE,
    KIND_UNSLOW,
)
from ..runtime import context
from .plan import FaultEvent

__all__ = ["Nemesis"]


class Nemesis:
    """Applies a compiled fault plan to the current simulation.

    ``nodes`` optionally maps plan node indices to runtime node ids (or
    NodeHandles); by default plan node ``i`` is the ``i``-th CREATED
    node in creation order (runtime ids start at 1 — id 0 is the main
    supervisor node, which the engine's node axis does not model and
    which cannot be killed)."""

    def __init__(self, plan, handle=None, nodes=None, seed=None):
        self._plan = plan
        self._handle = handle
        self._nodes = list(nodes) if nodes is not None else None
        self._seed = seed
        self.log: list[tuple[int, FaultEvent]] = []

    def _resolve_handle(self):
        return self._handle if self._handle is not None else context.current_handle()

    def _node(self, handle, i: int):
        if self._nodes is not None:
            node = self._nodes[i]
            return node if isinstance(node, int) else node.id
        from ..runtime.task import MAIN_NODE_ID

        # default: plan node i = the i-th created node, creation order
        # (ids are allocated sequentially from 1; the main node is the
        # supervisor, not a plan target)
        ids = sorted(n for n in handle.executor.nodes if n != MAIN_NODE_ID)
        if i >= len(ids):
            raise ValueError(
                f"plan targets node index {i} but the runtime has only "
                f"{len(ids)} created node(s); pass nodes= to map "
                f"plan indices explicitly"
            )
        return ids[i]

    def _targets(self, handle, i: int) -> list:
        """Resolve a fault target to runtime node ids: the disk-fault
        kinds allow ``-1`` = every node (engine/core.py 251-254), which
        must broadcast here too — Python negative indexing through
        ``_node`` would silently hit only the LAST created node and
        break dual-mode parity."""
        if i >= 0:
            return [self._node(handle, i)]
        if self._nodes is not None:
            return [n if isinstance(n, int) else n.id for n in self._nodes]
        from ..runtime.task import MAIN_NODE_ID

        return sorted(n for n in handle.executor.nodes if n != MAIN_NODE_ID)

    def events(self) -> list[FaultEvent]:
        """The concrete trajectory this nemesis will apply, time order."""
        handle = self._resolve_handle()
        seed = self._seed if self._seed is not None else handle.seed
        return sorted(self._plan.compile(int(seed)), key=lambda e: e.t)

    async def run(self) -> list[tuple[int, FaultEvent]]:
        """Sleep-and-inject every plan event; returns the applied log."""
        handle = self._resolve_handle()
        time = handle.time
        for ev in self.events():
            if ev.t > time.now_ns():
                await time.sleep_until_ns(ev.t)
            self._apply(handle, ev)
            self.log.append((time.now_ns(), ev))
        return self.log

    def _apply(self, handle, ev: FaultEvent) -> None:
        from ..engine.core import FIRST_EXT_KIND, FIRST_USER_KIND
        from ..net.netsim import NetSim

        if FIRST_USER_KIND <= ev.kind < FIRST_EXT_KIND:
            raise ValueError(
                f"nemesis cannot apply user kind {ev.kind}: client-army "
                f"ops (chaos.ClientArmy) are a batched-engine load "
                f"surface — and any chaos.RetryPolicy attached to one is "
                f"a batched-engine timer (engine.RetrySpec), not an "
                f"injectable event; on the asyncio runtime drive load "
                f"(and retries) with real client tasks instead"
            )
        netsim = handle.simulator(NetSim)
        # dup toggles carry no node; disk-fault kinds resolve their own
        # targets (a0 may be -1 = every node)
        a = self._node(handle, ev.a0) if ev.kind not in (
            KIND_DUP_ON, KIND_DUP_OFF, KIND_SYNC_LOSS, KIND_SYNC_OK,
            KIND_TORN_ON, KIND_TORN_OFF,
        ) else 0
        if ev.kind == KIND_KILL:
            handle.kill(a)
        elif ev.kind == KIND_RESTART:
            handle.restart(a)
        elif ev.kind == KIND_PAUSE:
            handle.pause(a)
        elif ev.kind == KIND_RESUME:
            handle.resume(a)
        elif ev.kind == KIND_CLOG:
            netsim.clog_link(a, self._node(handle, ev.a1))
        elif ev.kind == KIND_UNCLOG:
            netsim.unclog_link(a, self._node(handle, ev.a1))
        elif ev.kind == KIND_CLOG_NODE:
            netsim.clog_node(a)
        elif ev.kind == KIND_UNCLOG_NODE:
            netsim.unclog_node(a)
        elif ev.kind == KIND_CLOG_1W:
            netsim.clog_link_one_way(a, self._node(handle, ev.a1))
        elif ev.kind == KIND_UNCLOG_1W:
            netsim.unclog_link_one_way(a, self._node(handle, ev.a1))
        elif ev.kind in (KIND_SLOW_LINK, KIND_UNSLOW):
            from ..engine.core import unpack_slow_arg

            b, mult = unpack_slow_arg(ev.a1)
            mult = max(mult, 1) if ev.kind == KIND_SLOW_LINK else 1
            if b < 0:
                netsim.slow_node(a, mult)
            else:
                netsim.slow_link(a, self._node(handle, b), mult)
        elif ev.kind == KIND_DUP_ON:
            netsim.set_duplicate(True)
        elif ev.kind == KIND_DUP_OFF:
            netsim.set_duplicate(False)
        elif ev.kind == KIND_SKEW:
            handle.set_clock_skew(a, ev.a1)
        elif ev.kind in (KIND_SYNC_LOSS, KIND_SYNC_OK):
            # storage faults land on FsSim — the dual of the engine's
            # sync-discipline state (fs.py injectable-fault hooks).
            # a1 is the window mode: 0 = silent lie (sync_all lies),
            # 1 = observable EIO (writes raise OSError(EIO), the dual
            # of the engine's ctx.sync_err). SYNC_OK ends both.
            from ..fs import FsSim

            sim = handle.simulator(FsSim)
            on = ev.kind == KIND_SYNC_LOSS
            eio = bool(ev.a1 == 1)
            for nid in self._targets(handle, ev.a0):
                if not on:
                    sim.set_sync_loss(nid, False)
                    sim.set_fail_writes(nid, False)
                elif eio:
                    sim.set_fail_writes(nid, True)
                else:
                    sim.set_sync_loss(nid, True)
        elif ev.kind in (KIND_TORN_ON, KIND_TORN_OFF):
            from ..fs import FsSim

            sim = handle.simulator(FsSim)
            for nid in self._targets(handle, ev.a0):
                sim.set_torn(nid, ev.kind == KIND_TORN_ON)
        else:
            raise ValueError(f"nemesis cannot apply kind {ev.kind}")
