"""Failing-schedule shrinking: ddmin over injected fault events.

A nemesis search hands back ``(seed, config, plan)`` — but the plan that
*found* a violation usually injects far more chaos than the violation
*needs*. This module delta-debugs the compiled fault trajectory (Zeller
& Hildebrandt's ddmin over plan slots) down to a locally-minimal event
subset that still reproduces the failure, and returns it as a replayable
:class:`~madsim_tpu.chaos.plan.LiteralPlan`.

The batched engine is the whole trick: every ddmin round tests ALL its
candidate subsets as one vmapped batch — the same seed replicated B
times, each row with a different validity mask over the plan's pool
rows. One XLA program (shapes are static: the batch is padded to a fixed
width) serves every round, so a shrink costs one compile plus a handful
of batched runs, not hundreds of single-seed reruns.

Exact-replay guarantee: candidates keep the full plan's pool layout and
merely invalidate rows, so the minimal subset's trajectory — including
pop-order tie-breaks on equal event times — is identical between the
shrink search and a later ``search_seeds(plan=result.plan)`` replay.
``ShrinkResult.trace`` records the trace hash that replay must (and
does) reproduce.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax

from ..engine.core import (
    _T32_LIMIT,
    EngineConfig,
    SimState,
    Workload,
    _resolve_time32,
    make_init,
    make_run_while,
)
from .plan import LiteralPlan

__all__ = ["ShrinkResult", "shrink_plan"]


@dataclasses.dataclass
class ShrinkResult:
    """A locally-minimal failing fault schedule."""

    seed: int
    config_hash: str
    plan: LiteralPlan  # masked literal plan: replays the exact trajectory
    events: tuple  # the enabled (minimal) events, slot order
    trace: int  # uint64 trace hash of the minimal failing run
    rounds: int  # ddmin rounds
    tested: int  # candidate subsets executed
    original_events: int

    def banner(self) -> str:
        lines = [
            f"shrunk seed {self.seed}: {self.original_events} -> "
            f"{len(self.events)} fault event(s) "
            f"({self.rounds} ddmin rounds, {self.tested} candidates)",
            f"  repro: seed={self.seed} config_hash={self.config_hash} "
            f"plan_hash={self.plan.hash()} trace={self.trace:#x}",
        ]
        lines += [f"  {ev}" for ev in sorted(self.events, key=lambda e: e.t)]
        return "\n".join(lines)


def _split(items: list, n: int) -> list[list]:
    """n near-equal contiguous chunks (ddmin's partition)."""
    out, start = [], 0
    for i in range(n):
        end = start + (len(items) - start) // (n - i)
        out.append(items[start:end])
        start = end
    return [c for c in out if c]


def shrink_plan(
    wl: Workload,
    cfg: EngineConfig,
    seed: int,
    plan,
    *,
    invariant=None,
    history_invariant=None,
    max_steps: int = 1000,
    layout: str | None = None,
    require_halt: bool = False,
    latency=None,
    retry=None,
) -> ShrinkResult:
    """ddmin a failing ``(seed, plan)`` to a minimal fault-event subset.

    ``invariant`` / ``history_invariant`` follow the ``search_seeds``
    contract (view dict / BatchHistory -> per-row bool, True = clean); a
    candidate "still fails" when the predicate flags it on a trustworthy
    run (no pool or history overflow). ``require_halt`` defaults to
    False — unlike a search, a shrink should chase the recorded
    *violation*, not liveness: otherwise removing a fault's healing
    event (a restart, an unclog) strands the run un-halted and ddmin
    happily "minimizes" to a different failure mode. Set it True only
    when shrinking a liveness failure.

    ``latency`` (an ``engine.LatencySpec``) compiles the tail-latency
    tap into the shrink runs — required when the invariant is an SLO
    check (``check.slo_bounded``) reading ``lat_hist``: shrinking a
    latency violation needs the sketch it judges. Plans holding
    ``ClientArmy`` slots shrink like any other — ddmin drops the client
    ops a breach does not need right alongside the faults.

    ``retry`` (an ``engine.RetrySpec``) arms the client-retry timers in
    the shrink runs — when None it defaults to the plan's own
    ``retry_spec()`` if it carries a policied army, so a retry-amplified
    violation shrinks under the same policy that found it (exact replay
    includes the re-sent attempts).

    Raises ValueError if the full plan does not fail on ``seed`` (a
    shrink needs a failing input).
    """
    if invariant is None and history_invariant is None:
        raise ValueError("need an invariant, a history_invariant, or both")
    if history_invariant is not None and wl.history is None:
        raise ValueError(
            f"history_invariant needs histories, but workload {wl.name!r} "
            f"has Workload.history=None"
        )
    seed = int(seed)
    events = plan.compile(seed)
    if not events:
        raise ValueError(f"plan compiles to no events for seed {seed}")
    p = len(events)
    # the candidate batch is padded to a fixed width so ONE compiled
    # program serves every ddmin round (2*granularity <= 2*p candidates)
    b = max(2 * p, 2)
    base = LiteralPlan(events=tuple(events)).compile_batch(
        np.full((b,), seed, np.uint64)
    )
    if _resolve_time32(wl, cfg, None):
        # same guard as search_seeds(plan=...): under the int32 offset
        # representation an over-horizon event time would silently wrap
        lim = _T32_LIMIT - cfg.proc_max_ns - 1
        worst = max(e.t for e in events)
        if worst > lim:
            raise ValueError(
                f"fault-plan event at t={worst} ns exceeds the int32 "
                f"time horizon ({lim} ns) active for this (workload, "
                f"config); shrink the plan windows or disable time32"
            )
    dup = plan.uses_dup()
    if retry is None and hasattr(plan, "retry_spec"):
        retry = plan.retry_spec()
    init = make_init(wl, cfg, plan_slots=p, latency=latency, retry=retry)
    run = jax.jit(make_run_while(
        wl, cfg, max_steps, layout=layout, dup_rows=dup, latency=latency,
        retry=retry,
    ))
    seeds_b = np.full((b,), seed, np.uint64)
    tested = 0

    def _fails(masks: np.ndarray):
        """(nb, p) candidate masks -> (nb,) still-fails + (nb,) traces."""
        nonlocal tested
        nb = masks.shape[0]
        tested += nb
        rows = dataclasses.replace(base, valid=np.zeros((b, p), bool))
        rows.valid[:nb] = masks
        out = jax.block_until_ready(run(init(seeds_b, rows)))
        view = {
            f.name: np.asarray(getattr(out, f.name))
            for f in dataclasses.fields(SimState)
        }
        ok = (
            np.asarray(invariant(view), bool)
            if invariant is not None
            else np.ones((b,), bool)
        )
        over = view["overflow"] > 0
        if history_invariant is not None:
            from ..check.history import BatchHistory

            bh = BatchHistory.from_view(view)
            over = over | (np.asarray(bh.drop) > 0)
            ok = ok & np.asarray(history_invariant(bh), bool)
        if wl.history is not None:
            over = over | (view["hist_drop"] > 0)
        if require_halt:
            ok = ok & view["halted"]
        fails = ~ok & ~over
        return fails[:nb], view["trace"][:nb]

    full = np.ones((1, p), bool)
    f0, _ = _fails(full)
    if not bool(f0[0]):
        raise ValueError(
            f"seed {seed} does not fail under the full plan "
            f"(plan_hash={plan.hash()}); shrink needs a failing input"
        )

    current = list(range(p))
    granularity = min(2, p)
    rounds = 0
    while len(current) >= 2:
        rounds += 1
        chunks = _split(current, granularity)
        subsets = chunks
        chunk_sets = [set(c) for c in chunks]
        complements = [
            [i for i in current if i not in cs] for cs in chunk_sets
        ]
        cands = subsets + [c for c in complements if c]
        masks = np.zeros((len(cands), p), bool)
        for row, cand in enumerate(cands):
            masks[row, cand] = True
        fails, _ = _fails(masks)
        hit = None
        for row, cand in enumerate(cands):
            if fails[row]:
                hit = (row, cand)
                break
        if hit is not None:
            row, cand = hit
            current = cand
            granularity = 2 if row < len(subsets) else max(granularity - 1, 2)
            granularity = min(granularity, len(current))
        elif granularity < len(current):
            granularity = min(2 * granularity, len(current))
        else:
            break  # 1-minimal at this granularity: done

    mask = np.zeros((p,), bool)
    mask[current] = True
    fails, traces = _fails(mask[None, :])
    assert bool(fails[0]), "ddmin invariant: the kept subset must fail"
    minimal = LiteralPlan(
        events=tuple(events),
        enabled=tuple(bool(x) for x in mask),
        name=f"{getattr(plan, 'name', 'plan')}-shrunk",
    )
    return ShrinkResult(
        seed=seed,
        config_hash=cfg.hash(),
        plan=minimal,
        events=tuple(events[i] for i in current),
        trace=int(traces[0]),
        rounds=rounds,
        tested=tested,
        original_events=p,
    )
