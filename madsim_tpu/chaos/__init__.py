"""madsim_tpu.chaos — declarative nemesis fault plans, both modes.

MadSim's pitch is *amplified* chaos: the simulator doesn't merely
tolerate faults, it schedules them from the seed stream. Before this
package that chaos was hand-rolled inside each model's handlers; now it
is a layer:

* **FaultPlan** (chaos/plan.py) — a declarative spec of composable fault
  generators (crash-restart storms, pause storms, symmetric/asymmetric/
  partial partitions, gray-failure slow links, message duplication,
  clock skew, and DiskFault storage chaos: torn-write and sync-lie
  windows for sync-discipline workloads). Compilation draws counter-based
  threefry randomness
  keyed ``(seed, plan-slot)``, so each seed gets a distinct, exactly
  reproducible fault trajectory and the whole seed batch compiles in
  one vectorized pass.
* **Batched execution** — ``engine.search_seeds(plan=...)`` turns the
  compiled plan into pre-seeded event-pool rows; the new engine kinds
  (slow-link, duplication, skew, one-way clog) carry the fault classes
  the original engine lacked. ``(seed, config, plan)`` is the complete
  repro key.
* **Asyncio execution** (chaos/nemesis.py) — ``Nemesis`` drives the same
  plan through ``Handle``/``NetSim`` hooks on the single-seed runtime:
  the same fault trajectory in both execution modes.
* **Shrinking** (chaos/shrink.py) — ``shrink_plan`` delta-debugs a
  failing ``(seed, plan)`` to a locally-minimal event subset, testing
  each ddmin round as one vmapped batch, and returns a replayable
  ``LiteralPlan`` whose trace hash the replay reproduces exactly.
"""

from .plan import (  # noqa: F401
    ClientArmy,
    ClockSkew,
    CrashStorm,
    DiskFault,
    Duplicate,
    FaultEvent,
    FaultPlan,
    FlappingPartition,
    GrayFailure,
    LiteralPlan,
    Partition,
    PauseStorm,
    RetryPolicy,
    SlotTemplate,
    kind_name,
    stack_plan_rows,
)
from .nemesis import Nemesis  # noqa: F401
from .shrink import ShrinkResult, shrink_plan  # noqa: F401

__all__ = [
    "ClientArmy",
    "ClockSkew",
    "CrashStorm",
    "DiskFault",
    "Duplicate",
    "FaultEvent",
    "FaultPlan",
    "FlappingPartition",
    "GrayFailure",
    "LiteralPlan",
    "Nemesis",
    "Partition",
    "PauseStorm",
    "RetryPolicy",
    "ShrinkResult",
    "SlotTemplate",
    "kind_name",
    "shrink_plan",
    "stack_plan_rows",
]
