"""Declarative nemesis fault plans, compiled per seed.

The reference ecosystem hand-rolls chaos inside each test (a kill here,
a clog there — madsim's tests and every model in madsim_tpu/models did
the same inside their ``on_init``). A :class:`FaultPlan` lifts that into
a declarative layer every workload gets for free: a tuple of composable
fault *specs* — crash-restart storms, pause storms, partitions
(symmetric, asymmetric, partial), gray failures (per-link latency
multipliers), message duplication, per-node clock skew — each of which
compiles, for any seed, into a concrete list of timed fault events.

Randomization is counter-based, exactly like the engine's RNG
(engine/rng.py): every draw is ``threefry2x32(seed, draw-index,
PURPOSE_PLAN + plan-slot)`` — a pure function of its coordinates, so

* each **seed** gets a distinct, exactly reproducible fault trajectory
  (the BatchRNG varying-parameter-stream shape: one logical stream per
  (seed, plan-slot) pair, no serial state anywhere);
* compilation is a vectorized numpy pass over the whole seed batch
  (``compile_batch``), feeding the batched engine's pre-seeded pool rows
  (``engine.make_init(plan_slots=...)``);
* the same plan drives the single-seed asyncio runtime byte-identically
  at the event level (chaos/nemesis.py) — dual-mode parity.

``(seed, config, plan)`` is a complete repro key: the plan participates
in the search banner via :meth:`FaultPlan.hash`.
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from ..engine.core import (
    KIND_CLOG,
    KIND_CLOG_1W,
    KIND_DUP_OFF,
    KIND_DUP_ON,
    KIND_KILL,
    KIND_PAUSE,
    KIND_RESTART,
    KIND_RESUME,
    KIND_SKEW,
    KIND_SLOW_LINK,
    KIND_UNCLOG,
    KIND_UNCLOG_1W,
    KIND_UNSLOW,
    PlanRows,
    pack_slow_arg,
    unpack_slow_arg,
)
from ..engine.rng import PURPOSE_PLAN, chance_threshold

__all__ = [
    "FaultEvent",
    "FaultPlan",
    "LiteralPlan",
    "CrashStorm",
    "PauseStorm",
    "Partition",
    "GrayFailure",
    "Duplicate",
    "ClockSkew",
    "kind_name",
]

_KIND_NAMES = {
    KIND_KILL: "kill",
    KIND_RESTART: "restart",
    KIND_PAUSE: "pause",
    KIND_RESUME: "resume",
    KIND_CLOG: "clog",
    KIND_UNCLOG: "unclog",
    KIND_CLOG_1W: "clog-1w",
    KIND_UNCLOG_1W: "unclog-1w",
    KIND_SLOW_LINK: "slow",
    KIND_UNSLOW: "unslow",
    KIND_DUP_ON: "dup-on",
    KIND_DUP_OFF: "dup-off",
    KIND_SKEW: "skew",
}


def kind_name(kind: int) -> str:
    return _KIND_NAMES.get(kind, f"kind{kind}")


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One concrete injected fault: an engine event at an absolute time."""

    t: int  # ns from simulation start
    kind: int  # engine / extended-chaos kind id
    a0: int = 0
    a1: int = 0

    def __str__(self) -> str:
        name = kind_name(self.kind)
        ms = self.t / 1e6
        if self.kind in (KIND_SLOW_LINK, KIND_UNSLOW):
            b, mult = unpack_slow_arg(self.a1)
            peer = f"n{b}" if b >= 0 else "*"
            return f"{ms:8.2f}ms {name} n{self.a0}<->{peer} x{max(mult, 1)}"
        if self.kind in (KIND_CLOG, KIND_UNCLOG):
            return f"{ms:8.2f}ms {name} n{self.a0}<->n{self.a1}"
        if self.kind in (KIND_CLOG_1W, KIND_UNCLOG_1W):
            return f"{ms:8.2f}ms {name} n{self.a0}->n{self.a1}"
        if self.kind == KIND_SKEW:
            return f"{ms:8.2f}ms {name} n{self.a0} {self.a1}ns"
        if self.kind in (KIND_DUP_ON, KIND_DUP_OFF):
            return f"{ms:8.2f}ms {name}"
        return f"{ms:8.2f}ms {name} n{self.a0}"


# ---------------------------------------------------------------------------
# counter-based plan randomness (vectorized numpy threefry)
# ---------------------------------------------------------------------------

_ROTATIONS = (13, 15, 26, 6, 17, 29, 16, 24)
_PARITY = np.uint32(0x1BD11BDA)


def _vthreefry(k0, k1, x0, x1):
    """Array form of engine.rng.np_threefry2x32 (same function, ufunc
    ops instead of scalar casts so the whole seed batch goes at once)."""
    k0 = np.asarray(k0, np.uint32)
    k1 = np.asarray(k1, np.uint32)
    x0 = np.asarray(x0, np.uint32)
    x1 = np.asarray(x1, np.uint32)
    with np.errstate(over="ignore"):
        ks = (k0, k1, (k0 ^ k1 ^ _PARITY).astype(np.uint32))
        x0 = (x0 + ks[0]).astype(np.uint32)
        x1 = (x1 + ks[1]).astype(np.uint32)
        for chunk in range(5):
            rots = _ROTATIONS[:4] if chunk % 2 == 0 else _ROTATIONS[4:]
            for r in rots:
                x0 = (x0 + x1).astype(np.uint32)
                x1 = ((x1 << np.uint32(r)) | (x1 >> np.uint32(32 - r))).astype(
                    np.uint32
                )
                x1 = (x1 ^ x0).astype(np.uint32)
            x0 = (x0 + ks[(chunk + 1) % 3]).astype(np.uint32)
            x1 = (x1 + ks[(chunk + 2) % 3] + np.uint32(chunk + 1)).astype(
                np.uint32
            )
    return x0, x1


class _Stream:
    """The (seed, plan-slot) draw stream: ``bits(j)`` is draw j of this
    slot for every seed at once — order-independent coordinates, same
    discipline as the engine's per-event draws."""

    def __init__(self, seeds: np.ndarray, slot: int):
        seeds = np.asarray(seeds, np.uint64)
        self._k0 = (seeds & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        self._k1 = (seeds >> np.uint64(32)).astype(np.uint32)
        self._x1 = np.uint32((PURPOSE_PLAN + slot) & 0xFFFFFFFF)

    def bits(self, j: int) -> np.ndarray:
        a, _ = _vthreefry(self._k0, self._k1, np.uint32(j), self._x1)
        return a

    def uniform(self, lo: int, hi: int, j: int) -> np.ndarray:
        """Uniform int64 in [lo, hi) — the engine's modulo reduction."""
        span = np.uint32(max(int(hi) - int(lo), 1))
        return np.int64(lo) + (self.bits(j) % span).astype(np.int64)

    def pick(self, options, j: int) -> np.ndarray:
        opts = np.asarray(options, np.int64)
        return opts[self.bits(j) % np.uint32(len(opts))]

    def chance(self, p: float, j: int) -> np.ndarray:
        thresh = chance_threshold(p)
        if thresh >= (1 << 32):
            return np.ones(self._k0.shape, bool)
        return self.bits(j) < np.uint32(thresh)


# ---------------------------------------------------------------------------
# fault specs
# ---------------------------------------------------------------------------


def _empty(s: int, p: int):
    return (
        np.zeros((s, p), np.int64),
        np.zeros((s, p), np.int32),
        np.zeros((s, p, 2), np.int32),
        np.zeros((s, p), bool),
    )


def _check_window(lo: int, hi: int, what: str) -> None:
    if not 0 <= lo <= hi:
        raise ValueError(f"{what} window [{lo}, {hi}] is invalid")
    # draws are 32-bit (the engine's reduction discipline): a span that
    # doesn't fit uint32 would wrap/overflow in _Stream.uniform — same
    # constraint EngineConfig enforces on its latency ranges
    if hi - lo >= (1 << 32):
        raise ValueError(
            f"{what} span {hi - lo} ns does not fit uint32 "
            f"(max {(1 << 32) - 1} ns, ~4.29 s)"
        )


@dataclasses.dataclass(frozen=True)
class CrashStorm:
    """``n`` kill/restart pairs: each kill hits a random target node at a
    random time in [t_min, t_max) and the victim restarts after a random
    downtime in [down_min, down_max). Kills may overlap (two victims down
    at once) — exactly the storm shape a majority protocol must survive."""

    targets: tuple
    n: int = 1
    t_min_ns: int = 20_000_000
    t_max_ns: int = 400_000_000
    down_min_ns: int = 50_000_000
    down_max_ns: int = 400_000_000

    def __post_init__(self):
        if not self.targets:
            raise ValueError("CrashStorm needs at least one target node")
        if self.n < 1:
            raise ValueError(f"n must be >= 1, got {self.n}")
        _check_window(self.t_min_ns, self.t_max_ns, "kill-time")
        _check_window(self.down_min_ns, self.down_max_ns, "downtime")

    _KIND_ON = KIND_KILL
    _KIND_OFF = KIND_RESTART

    @property
    def slots(self) -> int:
        return 2 * self.n

    def compile_batch(self, seeds, slot: int):
        s = len(seeds)
        time, kind, args, valid = _empty(s, self.slots)
        st = _Stream(seeds, slot)
        for i in range(self.n):
            who = st.pick(self.targets, 3 * i)
            at = st.uniform(self.t_min_ns, self.t_max_ns, 3 * i + 1)
            down = st.uniform(self.down_min_ns, self.down_max_ns, 3 * i + 2)
            time[:, 2 * i] = at
            kind[:, 2 * i] = self._KIND_ON
            args[:, 2 * i, 0] = who
            valid[:, 2 * i] = True
            time[:, 2 * i + 1] = at + down
            kind[:, 2 * i + 1] = self._KIND_OFF
            args[:, 2 * i + 1, 0] = who
            valid[:, 2 * i + 1] = True
        return time, kind, args, valid



@dataclasses.dataclass(frozen=True)
class PauseStorm(CrashStorm):
    """CrashStorm's non-destructive sibling: pause/resume instead of
    kill/restart — the victim keeps its state and its pending events are
    held, the classic long-GC-stall fault."""

    _KIND_ON = KIND_PAUSE
    _KIND_OFF = KIND_RESUME


@dataclasses.dataclass(frozen=True)
class Partition:
    """One network cut: a random nonempty proper subset of ``targets``
    is separated from the rest at a random time and healed after a
    random duration.

    ``asymmetric=True`` clogs each cut edge in ONE random direction only
    (messages flow the other way — the split-brain-inducing half-open
    failure). ``partial_p < 1`` clogs each edge only with that
    probability (a partial partition: some paths across the cut
    survive, routing around the damage stays possible)."""

    targets: tuple
    t_min_ns: int = 20_000_000
    t_max_ns: int = 400_000_000
    dur_min_ns: int = 50_000_000
    dur_max_ns: int = 400_000_000
    asymmetric: bool = False
    partial_p: float = 1.0

    def __post_init__(self):
        if len(self.targets) < 2:
            raise ValueError("Partition needs at least two target nodes")
        if len(self.targets) > 30:
            raise ValueError("Partition subset draw supports <= 30 targets")
        if not 0.0 < self.partial_p <= 1.0:
            raise ValueError(f"partial_p must be in (0, 1], got {self.partial_p}")
        _check_window(self.t_min_ns, self.t_max_ns, "cut-time")
        _check_window(self.dur_min_ns, self.dur_max_ns, "cut-duration")

    @property
    def slots(self) -> int:
        t = len(self.targets)
        return 2 * (t * (t - 1) // 2)

    def compile_batch(self, seeds, slot: int):
        s = len(seeds)
        time, kind, args, valid = _empty(s, self.slots)
        st = _Stream(seeds, slot)
        t = len(self.targets)
        full = (1 << t) - 1
        # nonempty proper subset: remap 32 uniform bits into [1, full-1]
        side = 1 + (st.bits(0) % np.uint32(full - 1)).astype(np.int64)
        at = st.uniform(self.t_min_ns, self.t_max_ns, 1)
        dur = st.uniform(self.dur_min_ns, self.dur_max_ns, 2)
        clog_k = KIND_CLOG_1W if self.asymmetric else KIND_CLOG
        unclog_k = KIND_UNCLOG_1W if self.asymmetric else KIND_UNCLOG
        q = 0
        for i in range(t):
            for j in range(i + 1, t):
                word = st.bits(3 + q)
                crosses = ((side >> i) & 1) != ((side >> j) & 1)
                keep = crosses
                if self.partial_p < 1.0:
                    keep = keep & (
                        (word & np.uint32(0xFFFF))
                        < np.uint32(int(self.partial_p * 0x10000))
                    )
                # asymmetric: bit 16 of the edge word picks the blocked
                # direction (independent of the partial-keep low bits)
                fwd = ((word >> np.uint32(16)) & 1).astype(bool)
                a = np.where(
                    fwd | (not self.asymmetric),
                    self.targets[i],
                    self.targets[j],
                ).astype(np.int64)
                b = np.where(
                    fwd | (not self.asymmetric),
                    self.targets[j],
                    self.targets[i],
                ).astype(np.int64)
                time[:, 2 * q] = at
                kind[:, 2 * q] = clog_k
                args[:, 2 * q, 0] = a
                args[:, 2 * q, 1] = b
                valid[:, 2 * q] = keep
                time[:, 2 * q + 1] = at + dur
                kind[:, 2 * q + 1] = unclog_k
                args[:, 2 * q + 1, 0] = a
                args[:, 2 * q + 1, 1] = b
                valid[:, 2 * q + 1] = keep
                q += 1
        return time, kind, args, valid



@dataclasses.dataclass(frozen=True)
class GrayFailure:
    """``n_links`` random links turn slow (latency x mult in
    [mult_min, mult_max]) for a random window — the gray failure of the
    runtime-variability literature: nothing is *down*, some paths are
    just an order of magnitude slower, which readiness-oblivious
    protocols mistake for loss and retry into."""

    targets: tuple
    n_links: int = 1
    t_min_ns: int = 20_000_000
    t_max_ns: int = 400_000_000
    dur_min_ns: int = 50_000_000
    dur_max_ns: int = 400_000_000
    mult_min: int = 4
    mult_max: int = 32

    def __post_init__(self):
        if len(self.targets) < 2:
            raise ValueError("GrayFailure needs at least two target nodes")
        if self.n_links < 1:
            raise ValueError(f"n_links must be >= 1, got {self.n_links}")
        if not 1 <= self.mult_min <= self.mult_max:
            raise ValueError(
                f"multiplier range [{self.mult_min}, {self.mult_max}] invalid"
            )
        if self.mult_max >= (1 << 23):
            raise ValueError("multiplier must fit the packed args word (<2^23)")
        _check_window(self.t_min_ns, self.t_max_ns, "slow-time")
        _check_window(self.dur_min_ns, self.dur_max_ns, "slow-duration")

    @property
    def slots(self) -> int:
        return 2 * self.n_links

    def compile_batch(self, seeds, slot: int):
        s = len(seeds)
        time, kind, args, valid = _empty(s, self.slots)
        st = _Stream(seeds, slot)
        t = len(self.targets)
        opts = np.asarray(self.targets, np.int64)
        for i in range(self.n_links):
            ai = st.bits(5 * i) % np.uint32(t)
            # peer drawn from the other t-1 targets: a != b always
            bi = (ai + 1 + st.bits(5 * i + 1) % np.uint32(t - 1)) % np.uint32(t)
            a = opts[ai]
            b = opts[bi]
            at = st.uniform(self.t_min_ns, self.t_max_ns, 5 * i + 2)
            dur = st.uniform(self.dur_min_ns, self.dur_max_ns, 5 * i + 3)
            mult = st.uniform(self.mult_min, self.mult_max + 1, 5 * i + 4)
            time[:, 2 * i] = at
            kind[:, 2 * i] = KIND_SLOW_LINK
            args[:, 2 * i, 0] = a
            args[:, 2 * i, 1] = pack_slow_arg(b, mult)
            valid[:, 2 * i] = True
            time[:, 2 * i + 1] = at + dur
            kind[:, 2 * i + 1] = KIND_UNSLOW
            args[:, 2 * i + 1, 0] = a
            args[:, 2 * i + 1, 1] = pack_slow_arg(b, np.int64(1))
            valid[:, 2 * i + 1] = True
        return time, kind, args, valid



@dataclasses.dataclass(frozen=True)
class Duplicate:
    """Message duplication for one random window: every send delivers a
    second copy with its own latency/loss draw. Requires the engine's
    ``dup_rows`` path, which search/shrink enable automatically when a
    plan contains this spec."""

    t_min_ns: int = 20_000_000
    t_max_ns: int = 400_000_000
    dur_min_ns: int = 50_000_000
    dur_max_ns: int = 400_000_000

    def __post_init__(self):
        _check_window(self.t_min_ns, self.t_max_ns, "dup-time")
        _check_window(self.dur_min_ns, self.dur_max_ns, "dup-duration")

    @property
    def slots(self) -> int:
        return 2

    def compile_batch(self, seeds, slot: int):
        s = len(seeds)
        time, kind, args, valid = _empty(s, self.slots)
        st = _Stream(seeds, slot)
        at = st.uniform(self.t_min_ns, self.t_max_ns, 0)
        dur = st.uniform(self.dur_min_ns, self.dur_max_ns, 1)
        time[:, 0] = at
        kind[:, 0] = KIND_DUP_ON
        valid[:, 0] = True
        time[:, 1] = at + dur
        kind[:, 1] = KIND_DUP_OFF
        valid[:, 1] = True
        return time, kind, args, valid



@dataclasses.dataclass(frozen=True)
class ClockSkew:
    """``n`` random nodes get a random clock skew (what their handlers
    observe as ``ctx.now``; the asyncio runtime skews ``SystemTime``).
    Skews persist to the end of the run — drifted clocks don't heal
    themselves."""

    targets: tuple
    n: int = 1
    t_min_ns: int = 0
    t_max_ns: int = 100_000_000
    skew_min_ns: int = -500_000_000
    skew_max_ns: int = 500_000_000

    def __post_init__(self):
        if not self.targets:
            raise ValueError("ClockSkew needs at least one target node")
        if self.n < 1:
            raise ValueError(f"n must be >= 1, got {self.n}")
        if self.skew_min_ns > self.skew_max_ns:
            raise ValueError("skew range is empty")
        # strict lower bound: the span (max+1 - min) must also fit the
        # uint32 draw reduction, which -2^31..2^31-1 would overflow
        lim = 2**31
        if not (-lim < self.skew_min_ns and self.skew_max_ns < lim):
            raise ValueError("skew must fit int32 nanoseconds (~±2.1 s)")
        _check_window(self.t_min_ns, self.t_max_ns, "skew-time")

    @property
    def slots(self) -> int:
        return self.n

    def compile_batch(self, seeds, slot: int):
        s = len(seeds)
        time, kind, args, valid = _empty(s, self.slots)
        st = _Stream(seeds, slot)
        for i in range(self.n):
            who = st.pick(self.targets, 3 * i)
            at = st.uniform(self.t_min_ns, self.t_max_ns, 3 * i + 1)
            skew = st.uniform(self.skew_min_ns, self.skew_max_ns + 1, 3 * i + 2)
            time[:, i] = at
            kind[:, i] = KIND_SKEW
            args[:, i, 0] = who
            args[:, i, 1] = skew
            valid[:, i] = True
        return time, kind, args, valid



# ---------------------------------------------------------------------------
# plans
# ---------------------------------------------------------------------------


def _validate_targets(specs, wl) -> None:
    n = wl.n_nodes
    for spec in specs:
        for node in getattr(spec, "targets", ()):
            if not 0 <= int(node) < n:
                raise ValueError(
                    f"{type(spec).__name__} targets node {node}, but "
                    f"workload {wl.name!r} has n_nodes={n}"
                )


class _PlanBase:
    """Shared surface of FaultPlan and LiteralPlan (what search/shrink
    consume): ``slots``, ``uses_dup()``, ``hash()``, ``compile_batch``,
    ``compile``."""

    def compile(self, seed: int) -> list[FaultEvent]:
        """The concrete fault trajectory of one seed, in slot order."""
        rows = self.compile_batch(np.asarray([seed], np.uint64))
        out = []
        for j in range(rows.time.shape[1]):
            if bool(rows.valid[0, j]):
                out.append(
                    FaultEvent(
                        t=int(rows.time[0, j]),
                        kind=int(rows.kind[0, j]),
                        a0=int(rows.args[0, j, 0]),
                        a1=int(rows.args[0, j, 1]),
                    )
                )
        return out

    def describe(self, seed: int) -> str:
        lines = [f"plan {self.hash()} @ seed {seed}:"]
        lines += [f"  {ev}" for ev in sorted(self.compile(seed), key=lambda e: e.t)]
        return "\n".join(lines)


@dataclasses.dataclass(frozen=True)
class FaultPlan(_PlanBase):
    """A declarative nemesis: a tuple of fault specs, compiled per seed.

    ::

        plan = FaultPlan((
            CrashStorm(targets=(1, 2, 3, 4), n=2),
            GrayFailure(targets=(0, 1, 2, 3, 4)),
        ))
        report = search_seeds(wl, cfg, inv, plan=plan, ...)
        print(plan.describe(int(report.failing_seeds[0])))
    """

    specs: tuple
    name: str = "nemesis"

    def __post_init__(self):
        if not self.specs:
            raise ValueError("FaultPlan needs at least one fault spec")

    @property
    def slots(self) -> int:
        return sum(s.slots for s in self.specs)

    def uses_dup(self) -> bool:
        return any(isinstance(s, Duplicate) for s in self.specs)

    def hash(self) -> str:
        """Stable hex id of the plan (EngineConfig.hash analog): the
        spec tuple fully determines every compiled trajectory."""
        return hashlib.sha256(repr(self.specs).encode()).hexdigest()[:16]


    def compile_batch(self, seeds, wl=None) -> PlanRows:
        """Compile the whole seed batch to engine pool rows (S, slots).

        Spec ``i`` draws from plan slots ``[offset_i, offset_i +
        spec.slots)``, so adding a spec never re-randomizes the ones
        before it."""
        if wl is not None:
            _validate_targets(self.specs, wl)
        seeds = np.asarray(seeds, np.uint64)
        parts = []
        off = 0
        for spec in self.specs:
            parts.append(spec.compile_batch(seeds, off))
            off += spec.slots
        return PlanRows(
            time=np.concatenate([p[0] for p in parts], axis=1),
            kind=np.concatenate([p[1] for p in parts], axis=1),
            args=np.concatenate([p[2] for p in parts], axis=1),
            valid=np.concatenate([p[3] for p in parts], axis=1),
        )


@dataclasses.dataclass(frozen=True)
class LiteralPlan(_PlanBase):
    """An explicit, seed-independent event list — the replayable form the
    shrinker emits.

    ``enabled`` masks individual slots while keeping the pool layout (and
    therefore the trajectory, including argmin tie-breaks on equal event
    times) identical to the run that was shrunk: a disabled slot stays
    reserved-but-invalid exactly as it was during ddmin. ``compile``
    returns only the enabled events."""

    events: tuple
    enabled: tuple = ()
    name: str = "literal"

    def __post_init__(self):
        if self.enabled and len(self.enabled) != len(self.events):
            raise ValueError("enabled mask length must match events")

    @property
    def slots(self) -> int:
        return len(self.events)

    def _mask(self) -> np.ndarray:
        if self.enabled:
            return np.asarray(self.enabled, bool)
        return np.ones((len(self.events),), bool)

    def uses_dup(self) -> bool:
        return any(
            e.kind in (KIND_DUP_ON, KIND_DUP_OFF)
            for e, on in zip(self.events, self._mask())
            if on
        )

    def hash(self) -> str:
        payload = repr((self.events, tuple(self._mask().tolist())))
        return hashlib.sha256(payload.encode()).hexdigest()[:16]


    def compile_batch(self, seeds, wl=None) -> PlanRows:
        seeds = np.asarray(seeds, np.uint64)
        s, p = len(seeds), len(self.events)
        time = np.array([e.t for e in self.events], np.int64)
        kind = np.array([e.kind for e in self.events], np.int32)
        args = np.array([(e.a0, e.a1) for e in self.events], np.int32).reshape(
            p, 2
        )
        return PlanRows(
            time=np.broadcast_to(time, (s, p)).copy(),
            kind=np.broadcast_to(kind, (s, p)).copy(),
            args=np.broadcast_to(args, (s, p, 2)).copy(),
            valid=np.broadcast_to(self._mask(), (s, p)).copy(),
        )
