"""Declarative nemesis fault plans, compiled per seed.

The reference ecosystem hand-rolls chaos inside each test (a kill here,
a clog there — madsim's tests and every model in madsim_tpu/models did
the same inside their ``on_init``). A :class:`FaultPlan` lifts that into
a declarative layer every workload gets for free: a tuple of composable
fault *specs* — crash-restart storms, pause storms, partitions
(symmetric, asymmetric, partial), gray failures (per-link latency
multipliers), message duplication, per-node clock skew — each of which
compiles, for any seed, into a concrete list of timed fault events.

Randomization is counter-based, exactly like the engine's RNG
(engine/rng.py): every draw is ``threefry2x32(seed, draw-index,
PURPOSE_PLAN + plan-slot)`` — a pure function of its coordinates, so

* each **seed** gets a distinct, exactly reproducible fault trajectory
  (the BatchRNG varying-parameter-stream shape: one logical stream per
  (seed, plan-slot) pair, no serial state anywhere);
* compilation is a vectorized numpy pass over the whole seed batch
  (``compile_batch``), feeding the batched engine's pre-seeded pool rows
  (``engine.make_init(plan_slots=...)``);
* the same plan drives the single-seed asyncio runtime byte-identically
  at the event level (chaos/nemesis.py) — dual-mode parity.

``(seed, config, plan)`` is a complete repro key: the plan participates
in the search banner via :meth:`FaultPlan.hash`.
"""

from __future__ import annotations

import dataclasses
import hashlib
import warnings

import numpy as np

import jax.numpy as jnp

from ..engine.core import (
    FIRST_EXT_KIND,
    FIRST_USER_KIND,
    KIND_CLOG,
    KIND_CLOG_1W,
    KIND_DUP_OFF,
    KIND_DUP_ON,
    KIND_KILL,
    KIND_PAUSE,
    KIND_RESTART,
    KIND_RESUME,
    KIND_SKEW,
    KIND_SLOW_LINK,
    KIND_SYNC_LOSS,
    KIND_SYNC_OK,
    KIND_TORN_OFF,
    KIND_TORN_ON,
    KIND_UNCLOG,
    KIND_UNCLOG_1W,
    KIND_UNSLOW,
    PlanRows,
    RetrySpec,
    SLOW_MULT_MAX,
    pack_slow_arg,
    unpack_slow_arg,
)
from ..engine.rng import (
    DRAW_SPAN_MAX,
    PURPOSE_CLIENT,
    PURPOSE_PLAN,
    chance_threshold,
    np_threefry2x32v,
    threefry2x32,
)

__all__ = [
    "FaultEvent",
    "FaultPlan",
    "LiteralPlan",
    "SlotTemplate",
    "ClientArmy",
    "RetryPolicy",
    "CrashStorm",
    "PauseStorm",
    "Partition",
    "FlappingPartition",
    "GrayFailure",
    "Duplicate",
    "ClockSkew",
    "DiskFault",
    "kind_name",
    "stack_plan_rows",
]

_KIND_NAMES = {
    KIND_KILL: "kill",
    KIND_RESTART: "restart",
    KIND_PAUSE: "pause",
    KIND_RESUME: "resume",
    KIND_CLOG: "clog",
    KIND_UNCLOG: "unclog",
    KIND_CLOG_1W: "clog-1w",
    KIND_UNCLOG_1W: "unclog-1w",
    KIND_SLOW_LINK: "slow",
    KIND_UNSLOW: "unslow",
    KIND_DUP_ON: "dup-on",
    KIND_DUP_OFF: "dup-off",
    KIND_SKEW: "skew",
    KIND_SYNC_LOSS: "sync-loss",
    KIND_SYNC_OK: "sync-ok",
    KIND_TORN_ON: "torn-on",
    KIND_TORN_OFF: "torn-off",
}


def kind_name(kind: int) -> str:
    return _KIND_NAMES.get(kind, f"kind{kind}")


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One concrete injected event: an engine (or, for client-army
    load, user) event at an absolute time. ``node`` is the pool row's
    target — engine kinds ignore it (they act through args), user-kind
    rows (ClientArmy ops) are delivered to it."""

    t: int  # ns from simulation start
    kind: int  # engine / extended-chaos / user kind id
    a0: int = 0
    a1: int = 0
    node: int = 0

    def __str__(self) -> str:
        name = kind_name(self.kind)
        ms = self.t / 1e6
        if FIRST_USER_KIND <= self.kind < FIRST_EXT_KIND:
            # a client-army op: user kind delivered to its target node
            return (
                f"{ms:8.2f}ms client-op user[{self.kind - FIRST_USER_KIND}]"
                f"(id={self.a0}, arg={self.a1}) -> n{self.node}"
            )
        if self.kind in (KIND_SLOW_LINK, KIND_UNSLOW):
            b, mult = unpack_slow_arg(self.a1)
            peer = f"n{b}" if b >= 0 else "*"
            return f"{ms:8.2f}ms {name} n{self.a0}<->{peer} x{max(mult, 1)}"
        if self.kind in (KIND_CLOG, KIND_UNCLOG):
            return f"{ms:8.2f}ms {name} n{self.a0}<->n{self.a1}"
        if self.kind in (KIND_CLOG_1W, KIND_UNCLOG_1W):
            return f"{ms:8.2f}ms {name} n{self.a0}->n{self.a1}"
        if self.kind == KIND_SKEW:
            return f"{ms:8.2f}ms {name} n{self.a0} {self.a1}ns"
        if self.kind in (KIND_DUP_ON, KIND_DUP_OFF):
            return f"{ms:8.2f}ms {name}"
        return f"{ms:8.2f}ms {name} n{self.a0}"


# ---------------------------------------------------------------------------
# counter-based plan randomness. One implementation, two array backends:
# xp=np is the host path (the historical default), xp=jnp compiles the
# whole materialization on device — 10^6-seed sweeps never ship (S, P)
# plan arrays over PCIe. Both run the identical threefry and reduction
# arithmetic, so the two paths are bit-identical (tests pin it).
# ---------------------------------------------------------------------------

# back-compat alias: the vectorized numpy threefry now lives in
# engine.rng next to its scalar sibling
_vthreefry = np_threefry2x32v


class _Stream:
    """The (seed, plan-slot) draw stream: ``bits(j)`` is draw j of this
    slot for every seed at once — order-independent coordinates, same
    discipline as the engine's per-event draws."""

    def __init__(self, seeds, slot: int, xp=np, purpose: int = PURPOSE_PLAN):
        self._xp = xp
        if xp is np:
            seeds = np.asarray(seeds, np.uint64)
        else:
            seeds = jnp.asarray(seeds, jnp.uint64)
        self._k0 = (seeds & xp.uint64(0xFFFFFFFF)).astype(xp.uint32)
        self._k1 = (seeds >> xp.uint64(32)).astype(xp.uint32)
        self._x1 = np.uint32((purpose + slot) & 0xFFFFFFFF)

    def bits(self, j: int):
        if self._xp is np:
            a, _ = np_threefry2x32v(self._k0, self._k1, np.uint32(j), self._x1)
        else:
            a, _ = threefry2x32(
                self._k0, self._k1, jnp.uint32(j), jnp.uint32(self._x1)
            )
        return a

    def uniform(self, lo: int, hi: int, j: int):
        """Uniform int64 in [lo, hi) — the engine's modulo reduction."""
        xp = self._xp
        span = xp.uint32(max(int(hi) - int(lo), 1))
        return xp.int64(lo) + (self.bits(j) % span).astype(xp.int64)

    def pick(self, options, j: int):
        xp = self._xp
        opts = xp.asarray(options, xp.int64)
        return opts[self.bits(j) % xp.uint32(len(opts))]

    def chance(self, p: float, j: int):
        xp = self._xp
        thresh = chance_threshold(p)
        if thresh >= (1 << 32):
            return xp.ones(self._k0.shape, bool)
        return self.bits(j) < xp.uint32(thresh)


# ---------------------------------------------------------------------------
# fault specs
# ---------------------------------------------------------------------------


def _pack_slots(xp, s: int, rows):
    """Stack per-slot ``(time, kind, a0, a1, valid[, node])`` rows into
    the (S, P[, 2]) column arrays ``compile_batch`` returns. Scalars
    broadcast over the seed axis; works on both array backends. The
    optional sixth entry is the pool row's target node (client-army
    ops); absent = node 0, which engine kinds ignore."""

    def col(v, dtype):
        a = xp.asarray(v, dtype)
        if a.ndim == 0:
            a = xp.broadcast_to(a, (s,))
        return a.astype(dtype)

    time = xp.stack([col(r[0], xp.int64) for r in rows], axis=1)
    kind = xp.stack([col(r[1], xp.int32) for r in rows], axis=1)
    a0 = xp.stack([col(r[2], xp.int32) for r in rows], axis=1)
    a1 = xp.stack([col(r[3], xp.int32) for r in rows], axis=1)
    valid = xp.stack([col(r[4], xp.bool_) for r in rows], axis=1)
    node = xp.stack(
        [col(r[5] if len(r) > 5 else 0, xp.int32) for r in rows], axis=1
    )
    return time, kind, xp.stack([a0, a1], axis=2), valid, node


@dataclasses.dataclass(frozen=True)
class SlotTemplate:
    """Mutation metadata for ONE plan slot (the madsim_tpu.explore
    hook): the window a retimed event may land in, the node set a
    retargeted event may hit, and how its args word is drawn. Specs
    expose one template per slot via ``slot_templates()`` so the
    exploration mutators can perturb a compiled plan without knowing
    any spec's internals."""

    kind: int  # the slot's event kind
    t_min_ns: int  # retime/add draw window (absolute ns)
    t_max_ns: int
    targets: tuple = ()  # candidate nodes (empty = args not node-valued)
    # how retarget draws the args: "node" (a0 = one target), "pair"
    # (a0, a1 = two distinct targets — clog/unclog edges), "slow"
    # (a0 = node, a1 = pack_slow_arg(peer, mult)), "skew" (a0 = node,
    # a1 = skew ns), "none" (args fixed, e.g. dup toggles)
    arg_kind: str = "node"
    mult_min: int = 1
    mult_max: int = 1
    skew_min_ns: int = 0
    skew_max_ns: int = 0


def _check_window(lo: int, hi: int, what: str) -> None:
    if not 0 <= lo <= hi:
        raise ValueError(f"{what} window [{lo}, {hi}] is invalid")
    # draws are 32-bit (the engine's reduction discipline): a span that
    # doesn't fit uint32 would wrap/overflow in _Stream.uniform — the
    # same DRAW_SPAN_MAX contract EngineConfig enforces on its latency
    # ranges and the absint range contracts assume (engine/rng.py owns
    # the constant, so this validator and the prover cannot drift)
    if hi - lo > DRAW_SPAN_MAX:
        raise ValueError(
            f"{what} span {hi - lo} ns does not fit uint32 "
            f"(max {DRAW_SPAN_MAX} ns, ~4.29 s)"
        )


@dataclasses.dataclass(frozen=True)
class CrashStorm:
    """``n`` kill/restart pairs: each kill hits a random target node at a
    random time in [t_min, t_max) and the victim restarts after a random
    downtime in [down_min, down_max). Kills may overlap (two victims down
    at once) — exactly the storm shape a majority protocol must survive."""

    targets: tuple
    n: int = 1
    t_min_ns: int = 20_000_000
    t_max_ns: int = 400_000_000
    down_min_ns: int = 50_000_000
    down_max_ns: int = 400_000_000

    def __post_init__(self):
        if not self.targets:
            raise ValueError("CrashStorm needs at least one target node")
        if self.n < 1:
            raise ValueError(f"n must be >= 1, got {self.n}")
        _check_window(self.t_min_ns, self.t_max_ns, "kill-time")
        _check_window(self.down_min_ns, self.down_max_ns, "downtime")

    _KIND_ON = KIND_KILL
    _KIND_OFF = KIND_RESTART

    @property
    def slots(self) -> int:
        return 2 * self.n

    def compile_batch(self, seeds, slot: int, xp=np):
        st = _Stream(seeds, slot, xp)
        rows = []
        for i in range(self.n):
            who = st.pick(self.targets, 3 * i)
            at = st.uniform(self.t_min_ns, self.t_max_ns, 3 * i + 1)
            down = st.uniform(self.down_min_ns, self.down_max_ns, 3 * i + 2)
            rows.append((at, self._KIND_ON, who, 0, True))
            rows.append((at + down, self._KIND_OFF, who, 0, True))
        return _pack_slots(xp, len(seeds), rows)

    def slot_templates(self) -> tuple:
        out = []
        for _ in range(self.n):
            out.append(SlotTemplate(
                kind=self._KIND_ON, t_min_ns=self.t_min_ns,
                t_max_ns=self.t_max_ns, targets=self.targets,
            ))
            out.append(SlotTemplate(
                kind=self._KIND_OFF,
                t_min_ns=self.t_min_ns + self.down_min_ns,
                t_max_ns=self.t_max_ns + self.down_max_ns,
                targets=self.targets,
            ))
        return tuple(out)



@dataclasses.dataclass(frozen=True)
class PauseStorm(CrashStorm):
    """CrashStorm's non-destructive sibling: pause/resume instead of
    kill/restart — the victim keeps its state and its pending events are
    held, the classic long-GC-stall fault."""

    _KIND_ON = KIND_PAUSE
    _KIND_OFF = KIND_RESUME


@dataclasses.dataclass(frozen=True)
class Partition:
    """One network cut: a random nonempty proper subset of ``targets``
    is separated from the rest at a random time and healed after a
    random duration.

    ``asymmetric=True`` clogs each cut edge in ONE random direction only
    (messages flow the other way — the split-brain-inducing half-open
    failure). ``partial_p < 1`` clogs each edge only with that
    probability (a partial partition: some paths across the cut
    survive, routing around the damage stays possible)."""

    targets: tuple
    t_min_ns: int = 20_000_000
    t_max_ns: int = 400_000_000
    dur_min_ns: int = 50_000_000
    dur_max_ns: int = 400_000_000
    asymmetric: bool = False
    partial_p: float = 1.0

    def __post_init__(self):
        if len(self.targets) < 2:
            raise ValueError("Partition needs at least two target nodes")
        if len(self.targets) > 30:
            raise ValueError("Partition subset draw supports <= 30 targets")
        if not 0.0 < self.partial_p <= 1.0:
            raise ValueError(f"partial_p must be in (0, 1], got {self.partial_p}")
        _check_window(self.t_min_ns, self.t_max_ns, "cut-time")
        _check_window(self.dur_min_ns, self.dur_max_ns, "cut-duration")

    @property
    def slots(self) -> int:
        t = len(self.targets)
        return 2 * (t * (t - 1) // 2)

    def compile_batch(self, seeds, slot: int, xp=np):
        st = _Stream(seeds, slot, xp)
        t = len(self.targets)
        full = (1 << t) - 1
        # nonempty proper subset: remap 32 uniform bits into [1, full-1]
        side = 1 + (st.bits(0) % xp.uint32(full - 1)).astype(xp.int64)
        at = st.uniform(self.t_min_ns, self.t_max_ns, 1)
        dur = st.uniform(self.dur_min_ns, self.dur_max_ns, 2)
        rows = _partition_edge_rows(
            xp, st, self.targets, self.asymmetric, self.partial_p,
            side, at, dur, 3,
        )
        return _pack_slots(xp, len(seeds), rows)

    def slot_templates(self) -> tuple:
        return _partition_slot_templates(
            self.targets, self.asymmetric,
            self.t_min_ns, self.t_max_ns, self.dur_min_ns, self.dur_max_ns,
        )



def _partition_edge_rows(xp, st, targets, asymmetric, partial_p,
                         side, at, dur, draw0):
    """Per-edge clog/unclog slot rows of one cut — shared by Partition
    (one cut per plan) and FlappingPartition (one call per cycle).
    Edge q draws its word at ``draw0 + q``."""
    t = len(targets)
    clog_k = KIND_CLOG_1W if asymmetric else KIND_CLOG
    unclog_k = KIND_UNCLOG_1W if asymmetric else KIND_UNCLOG
    rows = []
    q = 0
    for i in range(t):
        for j in range(i + 1, t):
            word = st.bits(draw0 + q)
            crosses = ((side >> i) & 1) != ((side >> j) & 1)
            keep = crosses
            if partial_p < 1.0:
                keep = keep & (
                    (word & xp.uint32(0xFFFF))
                    < xp.uint32(int(partial_p * 0x10000))
                )
            # asymmetric: bit 16 of the edge word picks the blocked
            # direction (independent of the partial-keep low bits)
            fwd = ((word >> xp.uint32(16)) & 1).astype(xp.bool_)
            pick_fwd = fwd | (not asymmetric)
            a = xp.where(pick_fwd, targets[i], targets[j]).astype(xp.int64)
            b = xp.where(pick_fwd, targets[j], targets[i]).astype(xp.int64)
            rows.append((at, clog_k, a, b, keep))
            rows.append((at + dur, unclog_k, a, b, keep))
            q += 1
    return rows


def _partition_slot_templates(targets, asymmetric, t_min, t_max,
                              dur_min, dur_max) -> tuple:
    t = len(targets)
    clog_k = KIND_CLOG_1W if asymmetric else KIND_CLOG
    unclog_k = KIND_UNCLOG_1W if asymmetric else KIND_UNCLOG
    out = []
    for _ in range(t * (t - 1) // 2):
        out.append(SlotTemplate(
            kind=clog_k, t_min_ns=t_min, t_max_ns=t_max,
            targets=targets, arg_kind="pair",
        ))
        out.append(SlotTemplate(
            kind=unclog_k, t_min_ns=t_min + dur_min, t_max_ns=t_max + dur_max,
            targets=targets, arg_kind="pair",
        ))
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class FlappingPartition:
    """Route instability: ``n_cycles`` cut/heal cycles, each cutting a
    FRESHLY drawn nonempty proper subset of ``targets`` — sides AND
    timing re-randomize every cycle, the flapping-route failure a
    single :class:`Partition` cut cannot express. Cycle 0 cuts at a
    random time in [t_min, t_max); every cut holds for a duration in
    [dur_min, dur_max) and the next cut follows the heal after a gap in
    [up_min, up_max). ``asymmetric``/``partial_p`` apply per cycle,
    exactly as in :class:`Partition`."""

    targets: tuple
    n_cycles: int = 2
    t_min_ns: int = 20_000_000
    t_max_ns: int = 400_000_000
    dur_min_ns: int = 50_000_000
    dur_max_ns: int = 300_000_000
    up_min_ns: int = 20_000_000
    up_max_ns: int = 200_000_000
    asymmetric: bool = False
    partial_p: float = 1.0

    def __post_init__(self):
        if len(self.targets) < 2:
            raise ValueError("FlappingPartition needs at least two target nodes")
        if len(self.targets) > 30:
            raise ValueError(
                "FlappingPartition subset draw supports <= 30 targets"
            )
        if self.n_cycles < 1:
            raise ValueError(f"n_cycles must be >= 1, got {self.n_cycles}")
        if not 0.0 < self.partial_p <= 1.0:
            raise ValueError(
                f"partial_p must be in (0, 1], got {self.partial_p}"
            )
        _check_window(self.t_min_ns, self.t_max_ns, "first-cut-time")
        _check_window(self.dur_min_ns, self.dur_max_ns, "cut-duration")
        _check_window(self.up_min_ns, self.up_max_ns, "heal-gap")

    @property
    def _edges(self) -> int:
        t = len(self.targets)
        return t * (t - 1) // 2

    @property
    def slots(self) -> int:
        return self.n_cycles * 2 * self._edges

    def compile_batch(self, seeds, slot: int, xp=np):
        st = _Stream(seeds, slot, xp)
        t = len(self.targets)
        full = (1 << t) - 1
        rows = []
        heal = None
        # each cycle's draw block: side, duration, start-offset, then
        # one word per edge — appending a cycle never re-randomizes the
        # ones before it (the spec-offset rule applied within the spec)
        block = 3 + self._edges
        for c in range(self.n_cycles):
            base = c * block
            side = 1 + (st.bits(base) % xp.uint32(full - 1)).astype(xp.int64)
            dur = st.uniform(self.dur_min_ns, self.dur_max_ns, base + 1)
            if c == 0:
                at = st.uniform(self.t_min_ns, self.t_max_ns, base + 2)
            else:
                at = heal + st.uniform(self.up_min_ns, self.up_max_ns, base + 2)
            rows += _partition_edge_rows(
                xp, st, self.targets, self.asymmetric, self.partial_p,
                side, at, dur, base + 3,
            )
            heal = at + dur
        return _pack_slots(xp, len(seeds), rows)

    def slot_templates(self) -> tuple:
        out = []
        for c in range(self.n_cycles):
            # cycle c's cut lands after c earlier (duration + gap) spans
            lo = self.t_min_ns + c * (self.dur_min_ns + self.up_min_ns)
            hi = self.t_max_ns + c * (self.dur_max_ns + self.up_max_ns)
            out += _partition_slot_templates(
                self.targets, self.asymmetric, lo, hi,
                self.dur_min_ns, self.dur_max_ns,
            )
        return tuple(out)


@dataclasses.dataclass(frozen=True)
class GrayFailure:
    """``n_links`` random links turn slow (latency x mult in
    [mult_min, mult_max]) for a random window — the gray failure of the
    runtime-variability literature: nothing is *down*, some paths are
    just an order of magnitude slower, which readiness-oblivious
    protocols mistake for loss and retry into."""

    targets: tuple
    n_links: int = 1
    t_min_ns: int = 20_000_000
    t_max_ns: int = 400_000_000
    dur_min_ns: int = 50_000_000
    dur_max_ns: int = 400_000_000
    mult_min: int = 4
    mult_max: int = 32

    def __post_init__(self):
        if len(self.targets) < 2:
            raise ValueError("GrayFailure needs at least two target nodes")
        if self.n_links < 1:
            raise ValueError(f"n_links must be >= 1, got {self.n_links}")
        if not 1 <= self.mult_min <= self.mult_max:
            raise ValueError(
                f"multiplier range [{self.mult_min}, {self.mult_max}] invalid"
            )
        if self.mult_max > SLOW_MULT_MAX:
            # engine.SLOW_MULT_MAX owns the packed-args-word bound AND
            # the absint slow-column range contract: one declaration
            raise ValueError(
                f"multiplier must fit the packed args word "
                f"(engine.SLOW_MULT_MAX = {SLOW_MULT_MAX})"
            )
        _check_window(self.t_min_ns, self.t_max_ns, "slow-time")
        _check_window(self.dur_min_ns, self.dur_max_ns, "slow-duration")

    @property
    def slots(self) -> int:
        return 2 * self.n_links

    def compile_batch(self, seeds, slot: int, xp=np):
        st = _Stream(seeds, slot, xp)
        t = len(self.targets)
        opts = xp.asarray(self.targets, xp.int64)
        one = xp.int64(1)
        rows = []
        for i in range(self.n_links):
            ai = st.bits(5 * i) % xp.uint32(t)
            # peer drawn from the other t-1 targets: a != b always
            bi = (ai + 1 + st.bits(5 * i + 1) % xp.uint32(t - 1)) % xp.uint32(t)
            a = opts[ai]
            b = opts[bi]
            at = st.uniform(self.t_min_ns, self.t_max_ns, 5 * i + 2)
            dur = st.uniform(self.dur_min_ns, self.dur_max_ns, 5 * i + 3)
            mult = st.uniform(self.mult_min, self.mult_max + 1, 5 * i + 4)
            rows.append((at, KIND_SLOW_LINK, a, pack_slow_arg(b, mult), True))
            rows.append((at + dur, KIND_UNSLOW, a, pack_slow_arg(b, one), True))
        return _pack_slots(xp, len(seeds), rows)

    def slot_templates(self) -> tuple:
        out = []
        for _ in range(self.n_links):
            out.append(SlotTemplate(
                kind=KIND_SLOW_LINK, t_min_ns=self.t_min_ns,
                t_max_ns=self.t_max_ns, targets=self.targets,
                arg_kind="slow", mult_min=self.mult_min,
                mult_max=self.mult_max,
            ))
            out.append(SlotTemplate(
                kind=KIND_UNSLOW,
                t_min_ns=self.t_min_ns + self.dur_min_ns,
                t_max_ns=self.t_max_ns + self.dur_max_ns,
                targets=self.targets, arg_kind="slow",
            ))
        return tuple(out)



@dataclasses.dataclass(frozen=True)
class Duplicate:
    """Message duplication for one random window: every send delivers a
    second copy with its own latency/loss draw. Requires the engine's
    ``dup_rows`` path, which search/shrink enable automatically when a
    plan contains this spec."""

    t_min_ns: int = 20_000_000
    t_max_ns: int = 400_000_000
    dur_min_ns: int = 50_000_000
    dur_max_ns: int = 400_000_000

    def __post_init__(self):
        _check_window(self.t_min_ns, self.t_max_ns, "dup-time")
        _check_window(self.dur_min_ns, self.dur_max_ns, "dup-duration")

    @property
    def slots(self) -> int:
        return 2

    def compile_batch(self, seeds, slot: int, xp=np):
        st = _Stream(seeds, slot, xp)
        at = st.uniform(self.t_min_ns, self.t_max_ns, 0)
        dur = st.uniform(self.dur_min_ns, self.dur_max_ns, 1)
        rows = [
            (at, KIND_DUP_ON, 0, 0, True),
            (at + dur, KIND_DUP_OFF, 0, 0, True),
        ]
        return _pack_slots(xp, len(seeds), rows)

    def slot_templates(self) -> tuple:
        return (
            SlotTemplate(
                kind=KIND_DUP_ON, t_min_ns=self.t_min_ns,
                t_max_ns=self.t_max_ns, arg_kind="none",
            ),
            SlotTemplate(
                kind=KIND_DUP_OFF,
                t_min_ns=self.t_min_ns + self.dur_min_ns,
                t_max_ns=self.t_max_ns + self.dur_max_ns, arg_kind="none",
            ),
        )



@dataclasses.dataclass(frozen=True)
class ClockSkew:
    """``n`` random nodes get a random clock skew (what their handlers
    observe as ``ctx.now``; the asyncio runtime skews ``SystemTime``).
    Skews persist to the end of the run — drifted clocks don't heal
    themselves."""

    targets: tuple
    n: int = 1
    t_min_ns: int = 0
    t_max_ns: int = 100_000_000
    skew_min_ns: int = -500_000_000
    skew_max_ns: int = 500_000_000

    def __post_init__(self):
        if not self.targets:
            raise ValueError("ClockSkew needs at least one target node")
        if self.n < 1:
            raise ValueError(f"n must be >= 1, got {self.n}")
        if self.skew_min_ns > self.skew_max_ns:
            raise ValueError("skew range is empty")
        # strict lower bound: skews land in the int32 skew column AND
        # the span (max+1 - min) must fit the uint32 draw reduction —
        # the ±(2^31 - 1) bound makes the maximal inclusive span
        # exactly DRAW_SPAN_MAX (the shared engine/rng.py contract),
        # so this one check enforces both
        lim = 2**31
        if not (-lim < self.skew_min_ns and self.skew_max_ns < lim):
            raise ValueError("skew must fit int32 nanoseconds (~±2.1 s)")
        _check_window(self.t_min_ns, self.t_max_ns, "skew-time")

    @property
    def slots(self) -> int:
        return self.n

    def compile_batch(self, seeds, slot: int, xp=np):
        st = _Stream(seeds, slot, xp)
        rows = []
        for i in range(self.n):
            who = st.pick(self.targets, 3 * i)
            at = st.uniform(self.t_min_ns, self.t_max_ns, 3 * i + 1)
            skew = st.uniform(self.skew_min_ns, self.skew_max_ns + 1, 3 * i + 2)
            rows.append((at, KIND_SKEW, who, skew, True))
        return _pack_slots(xp, len(seeds), rows)

    def slot_templates(self) -> tuple:
        return tuple(
            SlotTemplate(
                kind=KIND_SKEW, t_min_ns=self.t_min_ns,
                t_max_ns=self.t_max_ns, targets=self.targets,
                arg_kind="skew", skew_min_ns=self.skew_min_ns,
                skew_max_ns=self.skew_max_ns,
            )
            for _ in range(self.n)
        )



@dataclasses.dataclass(frozen=True)
class DiskFault:
    """Storage chaos for ``Workload.durable_sync`` workloads: the
    FoundationDB/sled disk-fault repertoire as composable windows.

    ``n_torn`` torn-write windows arm a random target node's torn-write
    mode for a random duration — a KILL landing inside the window
    persists only a drawn *prefix* of the node's last uncommitted
    durable write (the power-failure tear). ``n_sync_loss`` sync-lie
    windows make the node's disk silently drop sync commits — the
    firmware-lies-about-fsync fault; note a lying disk breaks the
    assumptions raft-class protocols are allowed to make, so clean-model
    certificates run torn-only windows and use sync-loss as the
    positive control for the recovery-safety detector. ``n_eio``
    windows make the node's disk fail *observably*: syncs stop
    committing AND the node's handlers see ``ctx.sync_err`` for the
    duration — the batched ``FsSim.set_fail_writes`` ``OSError(EIO)``.
    Unlike a lie, an EIO is a fault correct code is expected to
    SURVIVE (withhold the ack you could not persist), so EIO windows
    belong in clean-model certificates. On workloads without the sync
    discipline every window is a no-op (the identity-defaults rule of
    the other extended kinds)."""

    targets: tuple
    n_torn: int = 1
    n_sync_loss: int = 0
    n_eio: int = 0
    t_min_ns: int = 20_000_000
    t_max_ns: int = 400_000_000
    dur_min_ns: int = 50_000_000
    dur_max_ns: int = 400_000_000

    def __post_init__(self):
        if not self.targets:
            raise ValueError("DiskFault needs at least one target node")
        if self.n_torn < 0 or self.n_sync_loss < 0 or self.n_eio < 0:
            raise ValueError("window counts must be >= 0")
        if self.n_torn + self.n_sync_loss + self.n_eio < 1:
            raise ValueError(
                "DiskFault needs at least one torn, sync-loss or EIO "
                "window"
            )
        _check_window(self.t_min_ns, self.t_max_ns, "disk-fault-time")
        _check_window(self.dur_min_ns, self.dur_max_ns, "disk-fault-duration")

    @property
    def slots(self) -> int:
        return 2 * (self.n_torn + self.n_sync_loss + self.n_eio)

    def _windows(self):
        """(on-kind, off-kind, on-mode) per window, torn windows first,
        then sync-loss, then EIO — the spec-offset rule: growing a
        later count never re-randomizes the windows before it. The
        mode word is KIND_SYNC_LOSS's args[1]: 0 = silent lie, 1 =
        observable EIO (ctx.sync_err)."""
        return (
            [(KIND_TORN_ON, KIND_TORN_OFF, 0)] * self.n_torn
            + [(KIND_SYNC_LOSS, KIND_SYNC_OK, 0)] * self.n_sync_loss
            + [(KIND_SYNC_LOSS, KIND_SYNC_OK, 1)] * self.n_eio
        )

    def compile_batch(self, seeds, slot: int, xp=np):
        st = _Stream(seeds, slot, xp)
        rows = []
        for i, (k_on, k_off, mode) in enumerate(self._windows()):
            who = st.pick(self.targets, 3 * i)
            at = st.uniform(self.t_min_ns, self.t_max_ns, 3 * i + 1)
            dur = st.uniform(self.dur_min_ns, self.dur_max_ns, 3 * i + 2)
            rows.append((at, k_on, who, mode, True))
            rows.append((at + dur, k_off, who, 0, True))
        return _pack_slots(xp, len(seeds), rows)

    def slot_templates(self) -> tuple:
        out = []
        for k_on, k_off, _mode in self._windows():
            out.append(SlotTemplate(
                kind=k_on, t_min_ns=self.t_min_ns, t_max_ns=self.t_max_ns,
                targets=self.targets,
            ))
            out.append(SlotTemplate(
                kind=k_off,
                t_min_ns=self.t_min_ns + self.dur_min_ns,
                t_max_ns=self.t_max_ns + self.dur_max_ns,
                targets=self.targets,
            ))
        return tuple(out)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """A client-side timeout/backoff retry policy for a :class:`ClientArmy`.

    The reference leaves retries to user tokio code; here they are a
    MODELED, seed-pure policy the engine itself executes: each delivered
    op arms a response-deadline timer in the pool, and on expiry the op
    is re-offered with an incremented attempt id (packed into the op
    token) unless a response was recorded meanwhile. ``max_attempts``
    counts total deliveries; backoff before attempt ``a >= 1`` is
    ``backoff_base_ns * backoff_mult**(a-1)``, jittered by a fresh
    ``PURPOSE_RETRY`` threefry draw scaled to ``[0, jitter]`` of the
    backoff — every re-send time is a pure function of the seed, so a
    retry-amplified trajectory replays exactly like any other.

    Attach with ``ClientArmy(..., retry=RetryPolicy(timeout_ns=...))``
    (the model helpers forward a ``retry=`` keyword), then build the
    engine with ``retry=plan.retry_spec(wl)``.
    """

    timeout_ns: int
    max_attempts: int = 3
    backoff_base_ns: int = 0
    backoff_mult: float = 2.0
    jitter: float = 0.0

    def __post_init__(self):
        # the full validation lives on the compiled engine spec; run it
        # here too so a bad policy fails at PLAN build time, with the
        # army-independent fields stubbed to valid values
        RetrySpec(
            kind=FIRST_USER_KIND, node=0, op_base=0, n_ops=1,
            timeout_ns=self.timeout_ns, max_attempts=self.max_attempts,
            backoff_base_ns=self.backoff_base_ns,
            backoff_mult=self.backoff_mult, jitter=self.jitter,
        )


@dataclasses.dataclass(frozen=True)
class ClientArmy:
    """Open-loop client load: ``n_ops`` user-kind pool rows delivered to
    ``node`` at threefry-drawn arrival times (madsim_tpu.obs latency).

    The open-loop property is structural: arrivals are *compiled* from
    ``(seed, PURPOSE_CLIENT + slot)`` coordinates into pre-seeded pool
    rows, so the offered load is a pure function of the seed — the same
    arrival schedule hits the protocol whatever the faults do to it,
    which is what makes tail latency a measurable property instead of a
    feedback artifact (a closed-loop client slows down exactly when the
    system does, hiding the queueing the SLO cares about).

    Each op's row carries ``args = (op_base + i, arg word)``: the op id
    indexes the engine's latency columns (``LatencySpec.ops`` must cover
    ``op_base + n_ops``), and the arg word is a uniform draw in
    [0, ``arg_hi``) for workloads whose client surface wants a key or
    value (0 when ``arg_hi`` is 0). ``kind`` is the workload's client
    handler (``engine.user_kind(...)``) — the models export bound
    helpers (``models.kvchaos.client_army`` / ``models.raftlog
    .client_army``) so callers never hand-pick handler ids.

    A ClientArmy composes into a :class:`FaultPlan` like any fault spec
    (same slot/offset/mutation discipline), so chaos windows and client
    load live in ONE plan: the hunt can retime a gray-failure window
    INTO the arrival window, and ddmin can shrink away the ops that
    don't matter to a breach.
    """

    node: int  # target node (the workload's client surface)
    kind: int  # user kind id of the client handler (engine.user_kind)
    n_ops: int = 256
    t_min_ns: int = 20_000_000
    t_max_ns: int = 400_000_000
    arg_hi: int = 0  # args[1] drawn uniform in [0, arg_hi); 0 = constant 0
    op_base: int = 0  # first op id (several armies share the lat columns)
    # timeout/backoff retry policy (None = the historical fire-and-
    # forget army: every compiled row is bit-identical either way —
    # attempt-0 tokens ARE plain op ids, the policy only changes the
    # engine build through retry_spec())
    retry: "RetryPolicy | None" = None

    def __post_init__(self):
        if self.node < 0:
            raise ValueError(f"ClientArmy node must be >= 0, got {self.node}")
        if not FIRST_USER_KIND <= self.kind < FIRST_EXT_KIND:
            raise ValueError(
                f"ClientArmy.kind={self.kind} is not a user kind "
                f"(engine.user_kind range [{FIRST_USER_KIND}, "
                f"{FIRST_EXT_KIND})) — pass user_kind(handler_index)"
            )
        if self.n_ops < 1:
            raise ValueError(f"n_ops must be >= 1, got {self.n_ops}")
        if self.arg_hi < 0:
            raise ValueError(f"arg_hi must be >= 0, got {self.arg_hi}")
        if self.op_base < 0:
            raise ValueError(f"op_base must be >= 0, got {self.op_base}")
        if self.retry is not None:
            if not isinstance(self.retry, RetryPolicy):
                raise TypeError(
                    f"ClientArmy.retry must be a RetryPolicy or None, "
                    f"got {type(self.retry).__name__}"
                )
            # build the engine spec once for its validations (op-range
            # vs token packing, attempt-bit bounds): fail at plan time
            self.retry_spec()
        _check_window(self.t_min_ns, self.t_max_ns, "arrival")

    def retry_spec(self) -> "RetrySpec":
        """The compiled engine-side spec of this army's retry policy
        (``engine.make_step(retry=...)``). Raises when no policy is
        attached — callers use :meth:`FaultPlan.retry_spec` which maps
        None-policy plans to None."""
        if self.retry is None:
            raise ValueError("this ClientArmy has no RetryPolicy attached")
        r = self.retry
        return RetrySpec(
            kind=self.kind, node=self.node, op_base=self.op_base,
            n_ops=self.n_ops, timeout_ns=r.timeout_ns,
            max_attempts=r.max_attempts,
            backoff_base_ns=r.backoff_base_ns,
            backoff_mult=r.backoff_mult, jitter=r.jitter,
        )

    @property
    def targets(self) -> tuple:
        """The node this army addresses (the plan target validation
        surface every spec exposes)."""
        return (self.node,)

    @property
    def slots(self) -> int:
        return self.n_ops

    def compile_batch(self, seeds, slot: int, xp=np):
        # the client stream is namespaced under PURPOSE_CLIENT (above
        # PURPOSE_PLAN/PURPOSE_EXPLORE): arrival draws can never alias
        # a chaos spec's draws even inside one composed plan
        st = _Stream(seeds, slot, xp, purpose=PURPOSE_CLIENT)
        rows = []
        for i in range(self.n_ops):
            at = st.uniform(self.t_min_ns, self.t_max_ns, 2 * i)
            if self.arg_hi:
                word = st.uniform(0, self.arg_hi, 2 * i + 1)
            else:
                word = 0
            rows.append(
                (at, self.kind, self.op_base + i, word, True, self.node)
            )
        return _pack_slots(xp, len(seeds), rows)

    def slot_templates(self) -> tuple:
        # mutation surface: retime within the arrival window (shift load
        # toward/away from a fault), drop/add ops; args are fixed — the
        # op id IS the latency slot, retargeting it would corrupt the
        # measurement
        return tuple(
            SlotTemplate(
                kind=self.kind, t_min_ns=self.t_min_ns,
                t_max_ns=self.t_max_ns, arg_kind="none",
            )
            for _ in range(self.n_ops)
        )


# ---------------------------------------------------------------------------
# plans
# ---------------------------------------------------------------------------


def _check_user_kind(kind: int, wl, what: str) -> None:
    """User-kind plan rows must name a REAL handler of this workload:
    the engine's dispatch clamps out-of-range user kinds to the last
    handler (a documented no-crash rule for emit-time corruption), so
    an army row aimed at a workload without the client surface would
    silently dispatch the wrong handler instead of erroring."""
    if not FIRST_USER_KIND <= kind < FIRST_EXT_KIND:
        return
    n_handlers = len(wl.handlers)
    if kind - FIRST_USER_KIND >= n_handlers:
        raise ValueError(
            f"{what} injects user kind {kind} (handler index "
            f"{kind - FIRST_USER_KIND}), but workload {wl.name!r} has "
            f"only {n_handlers} handlers — a client army needs the "
            f"workload built with its client surface enabled "
            f"(e.g. make_kvchaos(army=True))"
        )


def _validate_targets(specs, wl) -> None:
    n = wl.n_nodes
    for spec in specs:
        for node in getattr(spec, "targets", ()):
            if not 0 <= int(node) < n:
                raise ValueError(
                    f"{type(spec).__name__} targets node {node}, but "
                    f"workload {wl.name!r} has n_nodes={n}"
                )
        kind = getattr(spec, "kind", None)
        if isinstance(kind, int):
            _check_user_kind(kind, wl, type(spec).__name__)


class _PlanBase:
    """Shared surface of FaultPlan and LiteralPlan (what search/shrink
    consume): ``slots``, ``uses_dup()``, ``hash()``, ``compile_batch``,
    ``compile``."""

    def compile(self, seed: int) -> list[FaultEvent]:
        """The concrete fault trajectory of one seed, in slot order."""
        rows = self.compile_batch(np.asarray([seed], np.uint64))
        # both plan forms always materialize the node column; only
        # hand-built PlanRows (the make_init boundary) may carry None
        node = rows.node
        out = []
        for j in range(rows.time.shape[1]):
            if bool(rows.valid[0, j]):
                out.append(
                    FaultEvent(
                        t=int(rows.time[0, j]),
                        kind=int(rows.kind[0, j]),
                        a0=int(rows.args[0, j, 0]),
                        a1=int(rows.args[0, j, 1]),
                        node=int(node[0, j]),
                    )
                )
        return out

    def describe(self, seed: int) -> str:
        lines = [f"plan {self.hash()} @ seed {seed}:"]
        lines += [f"  {ev}" for ev in sorted(self.compile(seed), key=lambda e: e.t)]
        return "\n".join(lines)


@dataclasses.dataclass(frozen=True)
class FaultPlan(_PlanBase):
    """A declarative nemesis: a tuple of fault specs, compiled per seed.

    ::

        plan = FaultPlan((
            CrashStorm(targets=(1, 2, 3, 4), n=2),
            GrayFailure(targets=(0, 1, 2, 3, 4)),
        ))
        report = search_seeds(wl, cfg, inv, plan=plan, ...)
        print(plan.describe(int(report.failing_seeds[0])))
    """

    specs: tuple
    name: str = "nemesis"

    def __post_init__(self):
        if not self.specs:
            raise ValueError("FaultPlan needs at least one fault spec")

    @property
    def slots(self) -> int:
        return sum(s.slots for s in self.specs)

    def uses_dup(self) -> bool:
        return any(isinstance(s, Duplicate) for s in self.specs)

    def retry_spec(self) -> "RetrySpec | None":
        """The engine retry build parameter this plan implies: the
        attached ClientArmy's compiled :class:`RetrySpec`, or None when
        no army carries a policy (the historical build). The engine's
        retry mechanism tracks ONE op range, so two policied armies in
        one plan are refused — split the load across plans instead."""
        specs = [
            s for s in self.specs
            if isinstance(s, ClientArmy) and s.retry is not None
        ]
        if not specs:
            return None
        if len(specs) > 1:
            raise ValueError(
                f"plan {self.name!r} attaches RetryPolicy to "
                f"{len(specs)} client armies; the engine tracks one "
                f"retried op range per build"
            )
        return specs[0].retry_spec()

    def hash(self) -> str:
        """Stable hex id of the plan (EngineConfig.hash analog): the
        spec tuple fully determines every compiled trajectory."""
        return hashlib.sha256(repr(self.specs).encode()).hexdigest()[:16]

    def min_pool_size(self, wl, headroom: int = 16, tile_align: bool = True) -> int:
        """Smallest ``EngineConfig.pool_size`` this plan's pre-seeded
        rows fit into: one on_init row per node + every plan slot +
        ``headroom`` for in-flight protocol traffic per pending op.

        ``tile_align=True`` (default) rounds up to the next readiness-
        index tile multiple (``engine.pool_tile``), so an army-scale
        pool sized through here is never locked OUT of the O(ready)
        indexed pop by a missing tile divisor — client armies are
        exactly the pools where the flat O(E) scan hurts (ROADMAP
        items 2/4). The index still engages only past the measured
        auto threshold (pools > 1024 slots; below it the flat lowering
        is the faster program — pass ``pool_index=True`` explicitly to
        override). Headroom is a floor, not a proof: run the sweep
        once and check ``overflow == 0`` (the bench rule) before
        trusting a sizing.
        """
        base = wl.n_nodes + self.slots + max(int(headroom), 0)
        if not tile_align:
            return base
        from ..engine.core import POOL_TILE_CANDIDATES

        tile = POOL_TILE_CANDIDATES[0]
        return ((base + tile - 1) // tile) * tile

    def validate_windows(self, time_limit_ns: int, warn: bool = True):
        """Specs whose fire window opens at-or-after ``time_limit_ns``.

        The default CrashStorm/PauseStorm windows (20-400 ms) were tuned
        for long chaos runs; a short workload (raft halts its scenario
        in ~200-300 ms, or ``cfg.time_limit_ns`` caps the clock) can
        halt before a late window ever opens, silently turning the storm
        into a no-op — the sweep then certifies the UNFAULTED protocol.
        ``search_seeds`` calls this automatically when the config sets a
        time limit; ``warn=True`` (default) emits one UserWarning naming
        the dead specs. Returns the offending spec list (empty = fine).
        Use :meth:`clamped` to shrink the windows instead.
        """
        late = [
            s
            for s in self.specs
            if getattr(s, "t_min_ns", None) is not None
            and s.t_min_ns >= time_limit_ns
        ]
        if late and warn:
            names = ", ".join(
                f"{type(s).__name__}(t_min_ns={s.t_min_ns})" for s in late
            )
            warnings.warn(
                f"fault plan {self.name!r}: {names} cannot fire before "
                f"the {time_limit_ns} ns time limit — the run will see "
                f"no such fault (shrink the window, or use "
                f"plan.clamped(time_limit_ns))",
                UserWarning,
                stacklevel=3,
            )
        return late

    def clamped(self, time_limit_ns: int) -> "FaultPlan":
        """A copy with every spec's fire window intersected with
        ``[0, time_limit_ns)`` — the warn-or-clamp companion of
        :meth:`validate_windows`. Durations are untouched (a fault may
        legitimately heal after the limit); specs without a time window
        pass through. NOTE: clamping changes the spec tuple, so the
        plan hash (and every compiled trajectory) changes with it."""
        if time_limit_ns <= 0:
            raise ValueError(f"time_limit_ns must be > 0, got {time_limit_ns}")
        specs = []
        for s in self.specs:
            t_min = getattr(s, "t_min_ns", None)
            t_max = getattr(s, "t_max_ns", None)
            if t_min is None or t_max is None:
                specs.append(s)
                continue
            new_min = min(t_min, max(time_limit_ns - 1, 0))
            new_max = max(min(t_max, time_limit_ns), new_min)
            specs.append(
                dataclasses.replace(s, t_min_ns=new_min, t_max_ns=new_max)
            )
        return dataclasses.replace(self, specs=tuple(specs))

    def compile_batch(self, seeds, wl=None, device: bool = False) -> PlanRows:
        """Compile the whole seed batch to engine pool rows (S, slots).

        Spec ``i`` draws from plan slots ``[offset_i, offset_i +
        spec.slots)``, so adding a spec never re-randomizes the ones
        before it.

        ``device=True`` materializes on the accelerator (jnp arrays,
        jit/vmap-traceable): 10^6-seed sweeps compile their plans where
        the simulation runs instead of shipping (S, P) arrays from the
        host. Bit-identical to the numpy path (the parity test pins it).
        """
        if wl is not None:
            _validate_targets(self.specs, wl)
        xp = jnp if device else np
        seeds = xp.asarray(seeds, xp.uint64)
        parts = []
        off = 0
        for spec in self.specs:
            parts.append(spec.compile_batch(seeds, off, xp))
            off += spec.slots
        return PlanRows(
            time=xp.concatenate([p[0] for p in parts], axis=1),
            kind=xp.concatenate([p[1] for p in parts], axis=1),
            args=xp.concatenate([p[2] for p in parts], axis=1),
            valid=xp.concatenate([p[3] for p in parts], axis=1),
            node=xp.concatenate([p[4] for p in parts], axis=1),
        )

    def slot_templates(self) -> tuple:
        """One :class:`SlotTemplate` per plan slot, spec order — the
        mutation surface madsim_tpu.explore perturbs."""
        out = []
        for spec in self.specs:
            out += list(spec.slot_templates())
        return tuple(out)

    def literalize(self, seed: int, wl=None) -> "LiteralPlan":
        """This seed's compiled trajectory as a :class:`LiteralPlan`
        with the SAME pool layout: every slot is kept (invalid slots
        become disabled-but-reserved entries), so the literal replays
        the FaultPlan run bit-identically — the corpus-entry form of
        madsim_tpu.explore."""
        rows = self.compile_batch(np.asarray([seed], np.uint64), wl=wl)
        node = rows.node
        events = tuple(
            FaultEvent(
                t=int(rows.time[0, j]),
                kind=int(rows.kind[0, j]),
                a0=int(rows.args[0, j, 0]),
                a1=int(rows.args[0, j, 1]),
                node=int(node[0, j]),
            )
            for j in range(rows.time.shape[1])
        )
        enabled = tuple(bool(x) for x in rows.valid[0])
        return LiteralPlan(
            events=events, enabled=enabled, name=f"{self.name}@{int(seed)}"
        )


@dataclasses.dataclass(frozen=True)
class LiteralPlan(_PlanBase):
    """An explicit, seed-independent event list — the replayable form the
    shrinker emits.

    ``enabled`` masks individual slots while keeping the pool layout (and
    therefore the trajectory, including argmin tie-breaks on equal event
    times) identical to the run that was shrunk: a disabled slot stays
    reserved-but-invalid exactly as it was during ddmin. ``compile``
    returns only the enabled events."""

    events: tuple
    enabled: tuple = ()
    name: str = "literal"

    def __post_init__(self):
        if self.enabled and len(self.enabled) != len(self.events):
            raise ValueError("enabled mask length must match events")

    @property
    def slots(self) -> int:
        return len(self.events)

    def _mask(self) -> np.ndarray:
        if self.enabled:
            return np.asarray(self.enabled, bool)
        return np.ones((len(self.events),), bool)

    def uses_dup(self) -> bool:
        return any(
            e.kind in (KIND_DUP_ON, KIND_DUP_OFF)
            for e, on in zip(self.events, self._mask())
            if on
        )

    def hash(self) -> str:
        payload = repr((self.events, tuple(self._mask().tolist())))
        return hashlib.sha256(payload.encode()).hexdigest()[:16]


    def compile_batch(self, seeds, wl=None, device: bool = False) -> PlanRows:
        if wl is not None:
            for e, on in zip(self.events, self._mask()):
                if on:
                    _check_user_kind(e.kind, wl, "LiteralPlan event")
        xp = jnp if device else np
        seeds = xp.asarray(seeds, xp.uint64)
        s, p = len(seeds), len(self.events)
        time = xp.asarray([e.t for e in self.events], xp.int64)
        kind = xp.asarray([e.kind for e in self.events], xp.int32)
        args = xp.asarray(
            [(e.a0, e.a1) for e in self.events], xp.int32
        ).reshape(p, 2)
        node = xp.asarray([e.node for e in self.events], xp.int32)
        mask = xp.asarray(self._mask()) if device else self._mask()
        if device:
            return PlanRows(
                time=xp.broadcast_to(time, (s, p)),
                kind=xp.broadcast_to(kind, (s, p)),
                args=xp.broadcast_to(args, (s, p, 2)),
                valid=xp.broadcast_to(mask, (s, p)),
                node=xp.broadcast_to(node, (s, p)),
            )
        # numpy rows stay writable copies: the shrinker masks them in place
        return PlanRows(
            time=np.broadcast_to(time, (s, p)).copy(),
            kind=np.broadcast_to(kind, (s, p)).copy(),
            args=np.broadcast_to(args, (s, p, 2)).copy(),
            valid=np.broadcast_to(mask, (s, p)).copy(),
            node=np.broadcast_to(node, (s, p)).copy(),
        )

    def to_dict(self) -> dict:
        """JSON-ready form (the exploration corpus/artifact format).
        The node word is appended only when some event targets one, so
        pre-army artifacts stay byte-identical."""
        if any(e.node for e in self.events):
            events = [[e.t, e.kind, e.a0, e.a1, e.node] for e in self.events]
        else:
            events = [[e.t, e.kind, e.a0, e.a1] for e in self.events]
        return {
            "name": self.name,
            "events": events,
            "enabled": [bool(x) for x in self._mask()],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LiteralPlan":
        return cls(
            events=tuple(
                FaultEvent(
                    t=int(row[0]), kind=int(row[1]), a0=int(row[2]),
                    a1=int(row[3]),
                    node=int(row[4]) if len(row) > 4 else 0,
                )
                for row in d["events"]
            ),
            enabled=tuple(bool(x) for x in d.get("enabled", ())),
            name=d.get("name", "literal"),
        )


def stack_plan_rows(plans) -> PlanRows:
    """Stack per-row :class:`LiteralPlan` objects (equal slot counts)
    into one batch: row ``i`` of the returned :class:`PlanRows` carries
    ``plans[i]``. This is the heterogeneous form a mutated exploration
    generation needs — ``compile_batch`` broadcasts ONE plan over every
    seed, while here every seed runs its own mutant."""
    if not plans:
        raise ValueError("stack_plan_rows needs at least one plan")
    p = plans[0].slots
    for pl in plans:
        if pl.slots != p:
            raise ValueError(
                f"all plans must share one slot count; got {pl.slots} != {p}"
            )
    return PlanRows(
        time=np.array(
            [[e.t for e in pl.events] for pl in plans], np.int64
        ).reshape(len(plans), p),
        kind=np.array(
            [[e.kind for e in pl.events] for pl in plans], np.int32
        ).reshape(len(plans), p),
        args=np.array(
            [[(e.a0, e.a1) for e in pl.events] for pl in plans], np.int32
        ).reshape(len(plans), p, 2),
        valid=np.array([pl._mask() for pl in plans], bool).reshape(
            len(plans), p
        ),
        node=np.array(
            [[e.node for e in pl.events] for pl in plans], np.int32
        ).reshape(len(plans), p),
    )
