"""Host-side model of recorded operation histories.

The engine records histories as fixed-size on-device columns (the trace
discipline, engine/core.py): per seed, ``hist_count`` rows of
``hist_word`` = (op, key, arg, client, ok) int32 words and ``hist_t`` =
int64 sim-time ns, append-ordered by dispatch time. This module is the
numpy side: :class:`BatchHistory` wraps the whole seed batch zero-copy,
and :meth:`BatchHistory.ops` pairs one seed's raw records into
:class:`Op` operations for the linearizability checker.

Record convention (what handlers write via ``EmitBuilder.record`` and
apps via ``check.Recorder``):

* ``ok == OK_PENDING`` (-1): the *invoke* of an operation — the moment
  the client commits to attempting it (e.g. first send of a write).
* ``ok == OK_OK`` (1) / ``OK_FAIL`` (0): a *response*. It closes the
  oldest pending invoke of the same (client, op, key) — FIFO, which is
  exact for clients with one outstanding op per (op, key) (all in-repo
  models, by construction). With several ops concurrently open on one
  (client, op, key) FIFO can mis-pair out-of-order responses, swapping
  their values/intervals — record distinct keys or clients in that
  case, or use ``check.Recorder`` (host-side, token pairing, exact).
  A response with no pending invoke is an *instantaneous* event
  (invoke == response time): the natural encoding for things like
  election wins.

Why two records per op instead of one row with both timestamps: node
state and payloads are int32, so a handler cannot carry an int64 invoke
timestamp to the response site; two append-ordered records need no
state at all, and pairing is a host-side O(n) pass.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = [
    "COL_OP",
    "COL_KEY",
    "COL_ARG",
    "COL_CLIENT",
    "COL_OK",
    "OK_PENDING",
    "OK_FAIL",
    "OK_OK",
    "OP_WRITE",
    "OP_READ",
    "OP_USER",
    "SHARD_EPOCH_SHIFT",
    "SHARD_GROUP_SHIFT",
    "SHARD_GROUP_MASK",
    "SHARD_VER_MASK",
    "pack_shard_own",
    "Op",
    "BatchHistory",
    "HistoryError",
]

# hist_word column layout (engine/core.py history append)
COL_OP, COL_KEY, COL_ARG, COL_CLIENT, COL_OK = range(5)

OK_PENDING = -1  # invoke record of a still-open operation
OK_FAIL = 0  # response: the operation definitely failed
OK_OK = 1  # response: the operation definitely succeeded

# op-kind namespace: the two kinds the built-in checkers understand,
# then a user range for workload-specific events (e.g. raft's ELECT)
OP_WRITE = 1
OP_READ = 2
OP_USER = 16

# Packed arg layout of a shard OWNERSHIP record (models/shardkv.py
# installs, audited by check.shard_coverage): one int32 arg word
# carrying (config epoch, owning group, adopted version). This module
# owns the layout so the recording model and both detector forms
# (numpy + jnp) cannot drift. epoch <= 2047 and version <= 0xFFFF keep
# the word positive in int32.
SHARD_EPOCH_SHIFT = 20
SHARD_GROUP_SHIFT = 16
SHARD_GROUP_MASK = 0xF
SHARD_VER_MASK = 0xFFFF


def pack_shard_own(epoch, group, version):
    """Pack an ownership record's arg word. Works on Python ints,
    numpy arrays (detectors, tests) and traced values (the model)."""
    return (
        (epoch << SHARD_EPOCH_SHIFT)
        | (group << SHARD_GROUP_SHIFT)
        | (version & SHARD_VER_MASK)
    )


class HistoryError(ValueError):
    """A history that violates the recording convention itself."""


@dataclasses.dataclass(frozen=True)
class Op:
    """One paired operation of a single seed's history.

    ``ok == OK_PENDING`` means the invoke never saw a response within
    the recorded window — the op may or may not have taken effect, and
    the linearizability checker treats it as optional.
    """

    client: int
    op: int
    key: int
    arg_inv: int  # invoke-record arg (the input, e.g. the written value)
    arg_res: int  # response-record arg (the output, e.g. the read value)
    ok: int  # OK_OK / OK_FAIL / OK_PENDING
    t_inv: int  # invoke sim-time ns
    t_res: int | None  # response sim-time ns; None while pending
    # buffer indices of the two records: the engine appends in dispatch
    # order (and in record-call order within one handler), so these are
    # a strict refinement of the timestamps — the linearizability
    # checker orders by index, which resolves same-sim-time ties (e.g. a
    # write response and a read invoke recorded by the same handler)
    # exactly instead of conservatively treating them as concurrent
    idx_inv: int = 0
    idx_res: int | None = None


@dataclasses.dataclass
class BatchHistory:
    """Zero-copy numpy view of every seed's recorded history at once.

    The vectorized checkers (check/vectorized.py) consume the raw
    columns directly; :meth:`ops` materializes one seed for the exact
    (and per-seed) linearizability checker.
    """

    word: np.ndarray  # (S, H, 5) int32
    t: np.ndarray  # (S, H) int64
    count: np.ndarray  # (S,) int32 records stored
    drop: np.ndarray  # (S,) int32 records dropped at capacity

    @classmethod
    def from_view(cls, view) -> "BatchHistory":
        """Build from a search/compact result view (field-name mapping)."""
        return cls(
            word=np.asarray(view["hist_word"]),
            t=np.asarray(view["hist_t"]),
            count=np.asarray(view["hist_count"]),
            drop=np.asarray(view["hist_drop"]),
        )

    @classmethod
    def from_state(cls, state) -> "BatchHistory":
        """Build from a batched ``SimState`` (attribute mapping)."""
        return cls(
            word=np.asarray(state.hist_word),
            t=np.asarray(state.hist_t),
            count=np.asarray(state.hist_count),
            drop=np.asarray(state.hist_drop),
        )

    def __len__(self) -> int:
        return int(self.count.shape[0])

    @property
    def n_seeds(self) -> int:
        return len(self)

    def valid(self) -> np.ndarray:
        """(S, H) bool — rows actually written (slot index < count)."""
        h = self.word.shape[1]
        return np.arange(h)[None, :] < self.count[:, None]

    def col(self, c: int) -> np.ndarray:
        """(S, H) int32 — one raw column (COL_* index)."""
        return self.word[:, :, c]

    def overflowed(self) -> np.ndarray:
        """(S,) bool — seeds whose buffer dropped records (verdicts on
        these seeds are unreliable; search_seeds quarantines them)."""
        return self.drop > 0

    def ops(self, s: int, strict: bool = True) -> list[Op]:
        """Pair seed ``s``'s records into operations, in invoke order.

        ``strict=True`` raises :class:`HistoryError` when the seed
        dropped records — a truncated history must not silently verify.
        """
        if strict and self.drop[s] > 0:
            raise HistoryError(
                f"seed index {s} dropped {int(self.drop[s])} history "
                f"records (capacity overflow): history is incomplete"
            )
        n = int(self.count[s])
        word = self.word[s, :n]
        t = self.t[s, :n]
        ops: list[Op] = []
        # open invokes per (client, op, key), FIFO: list of op indices
        pending: dict[tuple, list[int]] = {}
        for i in range(n):
            op_k, key, arg, client, ok = (int(x) for x in word[i])
            ts = int(t[i])
            if ok == OK_PENDING:
                pending.setdefault((client, op_k, key), []).append(len(ops))
                ops.append(
                    Op(client, op_k, key, arg, 0, OK_PENDING, ts, None,
                       idx_inv=i)
                )
            else:
                q = pending.get((client, op_k, key))
                if q:
                    j = q.pop(0)
                    o = ops[j]
                    ops[j] = dataclasses.replace(
                        o, arg_res=arg, ok=ok, t_res=ts, idx_res=i
                    )
                else:
                    # instantaneous event (no separate invoke record)
                    ops.append(Op(client, op_k, key, arg, arg, ok, ts, ts,
                                  idx_inv=i, idx_res=i))
        return ops
