"""Single-seed operation recorder for asyncio-level applications.

The batched engine records histories on-device (engine/core.py); apps
on the single-seed runtime (madsim_tpu.runtime — real coroutines, RPC,
fs) record them with this class instead, producing the *same* history
representation so the same checkers validate both execution modes:

    rec = check.Recorder()
    tok = rec.invoke(client=0, op=check.OP_WRITE, key=1, arg=42)
    r = await kv_put(...)            # the operation itself
    rec.respond(tok, ok=True, value=42)
    ...
    assert rec.check_kv().ok         # Wing–Gong over the full history

Timestamps default to the simulation's virtual clock
(``madsim_tpu.runtime.now_ns``), so histories are deterministic per
seed exactly like everything else in the runtime; pass ``clock=`` to
record outside a simulation.
"""

from __future__ import annotations

import dataclasses

from .history import (
    OK_FAIL,
    OK_OK,
    OK_PENDING,
    BatchHistory,
    Op,
)
from .linearize import LinResult, check_kv, check_register

__all__ = ["Recorder"]

import numpy as np


class Recorder:
    """Append-only history of (op, key, arg, client, ok, t) records.

    Mirrors the engine's on-device columns, unbounded (host memory is
    not a fixed-size arena, so there is no overflow path here).
    """

    def __init__(self, clock=None):
        if clock is None:
            from ..runtime import now_ns as clock  # virtual sim clock
        self._clock = clock
        self._rows: list[tuple[int, int, int, int, int, int]] = []
        self._open: set[int] = set()  # open tokens (= invoke row indices)
        self._pair: dict[int, int] = {}  # response row -> invoke row

    def _append(self, op, key, arg, client, ok) -> int:
        self._rows.append(
            (int(op), int(key), int(arg), int(client), int(ok),
             int(self._clock()))
        )
        return len(self._rows) - 1

    def invoke(self, client: int, op: int, key: int = 0, arg: int = 0) -> int:
        """Record an operation invocation; returns a token for respond()."""
        tok = self._append(op, key, arg, client, OK_PENDING)
        self._open.add(tok)
        return tok

    def respond(self, token: int, ok: bool = True, value: int = 0) -> None:
        """Record the response of a previously invoked operation."""
        if token not in self._open:
            raise ValueError(f"token {token} is not an open invocation")
        self._open.remove(token)
        op, key, _arg, client, _ok, _t = self._rows[token]
        i = self._append(op, key, value, client, OK_OK if ok else OK_FAIL)
        self._pair[i] = token

    def event(self, client: int, op: int, key: int = 0, arg: int = 0,
              ok: bool = True) -> None:
        """Record an instantaneous operation (invoke == response)."""
        self._append(op, key, arg, client, OK_OK if ok else OK_FAIL)

    # ---- checker bridge ------------------------------------------------
    def to_batch(self) -> BatchHistory:
        """This history as a 1-seed :class:`BatchHistory` (seed axis 0).

        Note ``BatchHistory.ops`` re-pairs by the engine's FIFO
        convention; the raw columns (what the vectorized checkers read)
        are exact either way. For exact pairing use :meth:`ops`.
        """
        n = len(self._rows)
        word = np.zeros((1, n, 5), np.int32)
        t = np.zeros((1, n), np.int64)
        for i, (op, key, arg, client, ok, ts) in enumerate(self._rows):
            word[0, i] = (op, key, arg, client, ok)
            t[0, i] = ts
        return BatchHistory(
            word=word, t=t,
            count=np.array([n], np.int32),
            drop=np.zeros((1,), np.int32),
        )

    def ops(self) -> list[Op]:
        """Paired operations, in invoke order.

        Unlike the engine columns (where handlers cannot carry a row
        index to the response site, so ``BatchHistory.ops`` pairs FIFO
        per (client, op, key)), the Recorder knows each response's
        invoke row from its token — pairing here is exact even with
        several concurrent ops on one (client, key)."""
        ops: list[Op] = []
        slot: dict[int, int] = {}  # invoke row index -> position in ops
        for i, (op, key, arg, client, ok, ts) in enumerate(self._rows):
            if ok == OK_PENDING:
                slot[i] = len(ops)
                ops.append(
                    Op(client, op, key, arg, 0, OK_PENDING, ts, None,
                       idx_inv=i)
                )
            elif i in self._pair:
                j = slot[self._pair[i]]
                ops[j] = dataclasses.replace(
                    ops[j], arg_res=arg, ok=ok, t_res=ts, idx_res=i
                )
            else:
                # instantaneous event() (invoke == response)
                ops.append(Op(client, op, key, arg, arg, ok, ts, ts,
                              idx_inv=i, idx_res=i))
        return ops

    def check_register(self, init: int = 0) -> LinResult:
        return check_register(self.ops(), init=init)

    def check_kv(self, init: int = 0) -> LinResult:
        return check_kv(self.ops(), init=init)

    def __len__(self) -> int:
        return len(self._rows)
