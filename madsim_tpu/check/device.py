"""Device-resident batch history detectors: the jnp port of
check/vectorized.py, traceable into the programs that *produce* the
histories.

The numpy detectors judge a sweep only after every seed's raw history
columns have crossed the device→host boundary — at 65k seeds that
transfer (S·H·5 int32 words + S·H int64 clocks) plus the serial numpy
passes is the slow half of a verified sweep. This module restates each
detector as a pure jnp kernel over the SAME on-device columns
(``hist_word``/``hist_t``/``hist_count``/``hist_drop``), vmapped over
the seed axis, so verification runs inside (or right next to) the
simulation program and the host receives **packed verdict words**
(one bit per seed) instead of columns. Three consumers:

* ``engine.search_seeds(device_check=...)`` — history sweeps that
  transfer verdict words plus the *flagged* seeds' full histories
  (the Wing–Gong escalation input) instead of every column;
* ``explore.run_device(history_check=...)`` — the detector joins the
  cached generation program, closing the host-driver-only
  ``history_invariant`` gap for guided hunts;
* ``engine.make_run_compacted(hist_screen=...)`` — bank-time
  **prefix-compaction**: responded (invoke, response) pairs a clean
  verdict has already covered fold out of the banked columns
  (:func:`fold_verified`, loud ``hist_fold`` accounting).

Verdict contract: **bit-identical to the numpy path.** Each kernel is
an algebraic restatement (O(H²) pairwise masks instead of per-(key,
client) python loops) of the corresponding ``check.vectorized``
function — same floor construction, same FIFO rank matching, same
three response shapes (paired invoke / bare response / malformed
invoke-after), same quarantine rule (a seed whose buffer dropped
records is judged as an EMPTY history; callers void its verdict via
``hist_drop`` exactly like the host path). tests/test_check_device.py
pins device == numpy per detector across scatter/dense/time32 and the
compacted runner, on clean and planted-mutant models.

The escalation discipline is unchanged: these screens are the cheap
batch layer; any seed they flag ships its *full* history to the host
for exact Wing–Gong confirmation (check/linearize.py) — the PR-1
cross-check rule. ``fold_verified`` preserves exactly that: flagged
seeds keep every record."""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .history import (
    COL_ARG,
    COL_CLIENT,
    COL_KEY,
    COL_OK,
    COL_OP,
    OK_FAIL,
    OK_OK,
    OK_PENDING,
    OP_READ,
    OP_USER,
    OP_WRITE,
    SHARD_EPOCH_SHIFT,
    SHARD_GROUP_MASK,
    SHARD_GROUP_SHIFT,
    SHARD_VER_MASK,
    BatchHistory,
)

__all__ = [
    "HistoryScreen",
    "as_screens",
    "collapse_retries_cols",
    "default_screens",
    "election_safety",
    "exactly_once",
    "fold_verified",
    "lease_safety",
    "monotonic_reads",
    "monotonic_reads_strict",
    "pack_verdicts",
    "pack_verdicts_host",
    "read_your_writes",
    "recovery_safety",
    "screen_ok",
    "screens_invariant",
    "shard_coverage",
    "slo_breaches",
    "stale_reads",
    "unpack_verdicts",
    "violation_cones",
]

_MIN = -(2**62)  # "no prior write" floor sentinel (vectorized._MIN)

# seed-axis chunk the batched kernels map over: the pairwise (H, H)
# masks are materialized per chunk, bounding peak memory to
# chunk·H²-scale booleans no matter how large the sweep is. Chunking
# is a pure evaluation schedule — verdicts are value-identical for any
# chunk size.
_CHUNK = 2048


def _cols(word):
    """(H,5) int32 row -> the five columns, arg widened like numpy."""
    return (
        word[:, COL_OP],
        word[:, COL_KEY],
        word[:, COL_ARG].astype(jnp.int64),
        word[:, COL_CLIENT],
        word[:, COL_OK],
    )


def _floor_ok(word, count, read_op: int, write_op: int, own_only: bool):
    """Per-seed core of stale_reads / read_your_writes / monotonic_reads:
    the invoke-interval-aware floor check of
    ``vectorized._read_floor_violations``, restated pairwise.

    For every successful read response j, its FIFO-rank-matched invoke
    is found (the r-th response of a (client, key) read group pairs the
    r-th invoke of the same group), and the read's value must be at
    least the newest completed write version as of that invoke — or as
    of the response's own buffer slot when no invoke record exists (a
    bare/instantaneous event), and unconstrained when the rank-matched
    invoke sits AFTER the response (malformed interleaving:
    under-flag, never false-flag). Returns () bool, True = clean.
    """
    h_dim = word.shape[0]
    if h_dim == 0:
        return jnp.bool_(True)
    idx = jnp.arange(h_dim, dtype=jnp.int32)
    valid = idx < count
    op, key, arg, client, ok = _cols(word)
    w_resp = valid & (op == write_op) & (ok == OK_OK)
    r_inv = valid & (op == read_op) & (ok == OK_PENDING)
    r_resp = valid & (op == read_op) & (ok == OK_OK)
    # same (client, key) read group — op is fixed by the masks
    grp = (client[:, None] == client[None, :]) & (key[:, None] == key[None, :])
    lt = idx[:, None] < idx[None, :]
    # rank of each invoke/response within its own group (count of
    # strictly-earlier group members) — vectorized's cumsum ranks
    inv_rank = jnp.sum(lt & r_inv[:, None] & grp, axis=0)
    resp_rank = jnp.sum(lt & r_resp[:, None] & grp, axis=0)
    # the rank-matched invoke of response j: the unique group invoke
    # whose rank equals j's response rank (anywhere in the buffer —
    # position sorts into the three shapes below), h_dim if none
    match = r_inv[:, None] & grp & (inv_rank[:, None] == resp_rank[None, :])
    has_inv = jnp.any(match, axis=0)
    inv_idx = jnp.where(has_inv, jnp.argmax(match, axis=0), h_dim).astype(
        jnp.int32
    )
    # floor sample position per response: the invoke's slot (paired op),
    # the response's own slot (no invoke ever), exclusive either way
    pos = jnp.where(has_inv, inv_idx, idx)
    sel_w = w_resp[:, None] & (key[:, None] == key[None, :])
    if own_only:
        sel_w = sel_w & (client[:, None] == client[None, :])
    before = idx[:, None] < pos[None, :]
    floor = jnp.max(
        jnp.where(sel_w & before, arg[:, None], jnp.int64(_MIN)), axis=0
    )
    # malformed interleaving (rank-matched invoke after the response):
    # no constraint
    floor = jnp.where(has_inv & (inv_idx > idx), jnp.int64(_MIN), floor)
    return ~jnp.any(r_resp & (arg < floor))


def _strict_ok(word, count, read_op: int):
    """Per-seed ``monotonic_reads_strict``: within a (client, key)
    group of successful reads, no later response returns a smaller
    value than ANY earlier one — equivalent to the numpy adjacent-pair
    pass over the (client, key)-sorted rows (a decreasing adjacent pair
    exists iff a decreasing pair exists at all)."""
    h_dim = word.shape[0]
    if h_dim == 0:
        return jnp.bool_(True)
    idx = jnp.arange(h_dim, dtype=jnp.int32)
    valid = idx < count
    op, key, arg, client, ok = _cols(word)
    m = valid & (op == read_op) & (ok == OK_OK)
    pair = (
        m[:, None] & m[None, :]
        & (idx[:, None] < idx[None, :])
        & (client[:, None] == client[None, :])
        & (key[:, None] == key[None, :])
    )
    return ~jnp.any(pair & (arg[None, :] < arg[:, None]))


def _election_ok(word, count, elect_op: int):
    """Per-seed ``election_safety``: no two successful elect records
    share a key (term) with different args (winners) — the same
    pairwise pass as the numpy detector."""
    h_dim = word.shape[0]
    if h_dim == 0:
        return jnp.bool_(True)
    idx = jnp.arange(h_dim, dtype=jnp.int32)
    valid = idx < count
    op, key, arg, client, ok = _cols(word)
    m = valid & (op == elect_op) & (ok == OK_OK)
    bad = (
        m[:, None] & m[None, :]
        & (key[:, None] == key[None, :])
        & (arg[:, None] != arg[None, :])
    )
    return ~jnp.any(bad)


def _recovery_ok(word, count, sync_op: int, recover_op: int):
    """Per-seed ``recovery_safety``: a recover record's arg is never
    below the SAME client's latest earlier sync arg (the last sync, not
    the running max — legitimate truncations re-sync)."""
    h_dim = word.shape[0]
    if h_dim == 0:
        return jnp.bool_(True)
    idx = jnp.arange(h_dim, dtype=jnp.int32)
    valid = idx < count
    op, key, arg, client, ok = _cols(word)
    sync_m = valid & (op == sync_op) & (ok == OK_OK)
    rec_m = valid & (op == recover_op) & (ok == OK_OK)
    same_c = client[:, None] == client[None, :]
    # latest same-client sync at-or-before each row (numpy's inclusive
    # running max over marked indices; -1 = none yet)
    cand = sync_m[:, None] & same_c & (idx[:, None] <= idx[None, :])
    last = jnp.max(
        jnp.where(cand, idx[:, None], jnp.int32(-1)), axis=0
    )
    floor = jnp.max(
        jnp.where(
            cand & (idx[:, None] == last[None, :]),
            arg[:, None],
            jnp.int64(_MIN),
        ),
        axis=0,
    )
    return ~jnp.any(rec_m & (last >= 0) & (arg < floor))


def _lease_ok(word, count, serve_op: int, lease_op: int):
    """Per-seed ``lease_safety``: no serve whose latest earlier
    lifecycle record (same lease) is an expiry, and no expiry below the
    latest earlier grant's deadline — the same inclusive-running-max
    construction as the numpy detector, restated pairwise (a serve row
    is never itself a lifecycle row and an expiry never a grant row, so
    at-or-before equals strictly-earlier, matching numpy exactly)."""
    h_dim = word.shape[0]
    if h_dim == 0:
        return jnp.bool_(True)
    idx = jnp.arange(h_dim, dtype=jnp.int32)
    valid = idx < count
    op, key, arg, client, ok = _cols(word)
    life = valid & (op == lease_op)
    grant = life & (ok == OK_OK)
    expire = life & (ok == OK_FAIL)
    serve = valid & (op == serve_op) & (ok == OK_OK)
    same_key = key[:, None] == key[None, :]
    at_or_before = idx[:, None] <= idx[None, :]
    # clause 1: the latest same-lease lifecycle record at-or-before
    # each row, and whether that record is an expiry
    cand = life[:, None] & same_key & at_or_before
    last = jnp.max(jnp.where(cand, idx[:, None], jnp.int32(-1)), axis=0)
    last_exp = jnp.max(
        jnp.where(cand & expire[:, None], idx[:, None], jnp.int32(-1)),
        axis=0,
    )
    c1 = serve & (last >= 0) & (last_exp == last)
    # clause 2: expiry clock vs the latest earlier grant's deadline
    gcand = grant[:, None] & same_key & at_or_before
    glast = jnp.max(jnp.where(gcand, idx[:, None], jnp.int32(-1)), axis=0)
    gfloor = jnp.max(
        jnp.where(
            gcand & (idx[:, None] == glast[None, :]),
            arg[:, None],
            jnp.int64(_MIN),
        ),
        axis=0,
    )
    c2 = expire & (glast >= 0) & (arg < gfloor)
    return ~(jnp.any(c1) | jnp.any(c2))


def _shard_ok(word, count, own_op: int, write_op: int):
    """Per-seed ``shard_coverage``: no two installs share (shard,
    epoch) with different groups, and every install's adopted version
    covers the running max of earlier committed writes for its shard —
    same packed-arg decode and same inclusive accumulate as numpy."""
    h_dim = word.shape[0]
    if h_dim == 0:
        return jnp.bool_(True)
    idx = jnp.arange(h_dim, dtype=jnp.int32)
    valid = idx < count
    op, key, arg, client, ok = _cols(word)
    own = valid & (op == own_op) & (ok == OK_OK)
    write = valid & (op == write_op) & (ok == OK_OK)
    epoch = arg >> SHARD_EPOCH_SHIFT
    group = (arg >> SHARD_GROUP_SHIFT) & SHARD_GROUP_MASK
    ver = arg & SHARD_VER_MASK
    same_key = key[:, None] == key[None, :]
    # clause 1: double-serve — pairwise (shard, epoch), groups differ
    c1 = (
        own[:, None] & own[None, :] & same_key
        & (epoch[:, None] == epoch[None, :])
        & (group[:, None] != group[None, :])
    )
    # clause 2: lost range — running max committed version per shard
    wcand = write[:, None] & same_key & (idx[:, None] <= idx[None, :])
    wmax = jnp.max(
        jnp.where(wcand, arg[:, None], jnp.int64(_MIN)), axis=0
    )
    c2 = own & (wmax > jnp.int64(_MIN)) & (ver < wmax)
    return ~(jnp.any(c1) | jnp.any(c2))


def _exactly_once_ok(word, count, apply_op: int):
    """Per-seed ``exactly_once``: no two successful apply records share
    (client, key) — the same off-diagonal pairwise pass as the numpy
    detector (key = the op id, attempt bits stripped at the recorder)."""
    h_dim = word.shape[0]
    if h_dim == 0:
        return jnp.bool_(True)
    idx = jnp.arange(h_dim, dtype=jnp.int32)
    valid = idx < count
    op, key, arg, client, ok = _cols(word)
    m = valid & (op == apply_op) & (ok == OK_OK)
    bad = (
        m[:, None] & m[None, :]
        & (key[:, None] == key[None, :])
        & (client[:, None] == client[None, :])
        & (idx[:, None] != idx[None, :])
    )
    return ~jnp.any(bad)


def collapse_retries_cols(word, count):
    """Device twin of ``check.vectorized.collapse_retries``: (S,H,5)
    int32 word columns + (S,) counts -> word columns with every retry
    re-send invoke's op code cleared to 0 (so it matches no kernel's op
    mask; row count and buffer order untouched). An invoke collapses
    iff an earlier invoke of the same (client, op, key) exists with no
    response of that group between them — the same pairwise formula as
    numpy, bit-identical by construction. Traceable; apply before
    :func:`screen_ok` when a model records one invoke per delivered
    retry attempt."""
    h_dim = word.shape[1]
    if h_dim == 0:
        return word

    def per_seed(w, c):
        idx = jnp.arange(h_dim, dtype=jnp.int32)
        valid = idx < c
        op, key, _arg, client, okc = _cols(w)
        inv = valid & (okc == OK_PENDING)
        resp = valid & (okc != OK_PENDING)
        same = (
            (key[:, None] == key[None, :])
            & (client[:, None] == client[None, :])
            & (op[:, None] == op[None, :])
        )
        lower = idx[:, None] > idx[None, :]  # [j, i]: i strictly earlier
        rcnt = jnp.sum(same & lower & resp[None, :], axis=1)
        collapsed = inv & jnp.any(
            same & lower & inv[None, :]
            & (rcnt[:, None] == rcnt[None, :]),
            axis=1,
        )
        return w.at[:, COL_OP].set(
            jnp.where(collapsed, 0, w[:, COL_OP])
        )

    return jax.vmap(per_seed)(word, jnp.asarray(count))


@dataclasses.dataclass(frozen=True)
class HistoryScreen:
    """One vectorized detector as a device kernel + its numpy oracle.

    Value-hashable (a frozen literal), so it can key the compiled-
    program caches (``engine.search._SCREEN_CACHE``,
    ``explore.device._GEN_CACHE``) — the *invariant identity* cache-key
    component. Build instances through the module constructors
    (:func:`stale_reads` etc.), which mirror the ``check.vectorized``
    names and defaults.

    ``op_a``/``op_b`` mean (read, write) for the floor detectors,
    (elect, -) for election safety, (sync, recover) for recovery
    safety, (serve, lease) for lease safety and (own, write) for shard
    coverage — exactly the positional ops of the numpy functions.
    """

    kind: str
    op_a: int = OP_READ
    op_b: int = OP_WRITE

    def __post_init__(self):
        if self.kind not in _KERNELS:
            raise ValueError(
                f"unknown screen kind {self.kind!r} "
                f"(one of {sorted(_KERNELS)})"
            )

    def seed_kernel(self, word, count):
        """Traceable per-seed verdict: (H,5) int32 word rows + () count
        -> () bool, True = clean. Vmap over seeds (or let
        :func:`screen_ok` do it, chunked)."""
        return _KERNELS[self.kind](word, count, self)

    def host(self, h: BatchHistory) -> np.ndarray:
        """The numpy oracle: the exact ``check.vectorized`` function
        this screen ports, on a host :class:`BatchHistory`."""
        from . import vectorized as v

        fn = {
            "stale_reads": lambda: v.stale_reads(h, self.op_a, self.op_b),
            "read_your_writes": lambda: v.read_your_writes(
                h, self.op_a, self.op_b
            ),
            "monotonic_reads": lambda: v.monotonic_reads(h, self.op_a),
            "monotonic_reads_strict": lambda: v.monotonic_reads_strict(
                h, self.op_a
            ),
            "election_safety": lambda: v.election_safety(h, self.op_a),
            "recovery_safety": lambda: v.recovery_safety(
                h, self.op_a, self.op_b
            ),
            "lease_safety": lambda: v.lease_safety(
                h, self.op_a, self.op_b
            ),
            "shard_coverage": lambda: v.shard_coverage(
                h, self.op_a, self.op_b
            ),
            "exactly_once": lambda: v.exactly_once(h, self.op_a),
        }[self.kind]
        return fn()


_KERNELS = {
    "stale_reads": lambda w, c, s: _floor_ok(
        w, c, s.op_a, s.op_b, own_only=False
    ),
    "read_your_writes": lambda w, c, s: _floor_ok(
        w, c, s.op_a, s.op_b, own_only=True
    ),
    "monotonic_reads": lambda w, c, s: _floor_ok(
        w, c, s.op_a, s.op_a, own_only=True
    ),
    "monotonic_reads_strict": lambda w, c, s: _strict_ok(w, c, s.op_a),
    "election_safety": lambda w, c, s: _election_ok(w, c, s.op_a),
    "recovery_safety": lambda w, c, s: _recovery_ok(w, c, s.op_a, s.op_b),
    "lease_safety": lambda w, c, s: _lease_ok(w, c, s.op_a, s.op_b),
    "shard_coverage": lambda w, c, s: _shard_ok(w, c, s.op_a, s.op_b),
    "exactly_once": lambda w, c, s: _exactly_once_ok(w, c, s.op_a),
}


def stale_reads(read_op: int = OP_READ, write_op: int = OP_WRITE):
    """Lost-write screen: ``check.vectorized.stale_reads`` on device."""
    return HistoryScreen("stale_reads", read_op, write_op)


def read_your_writes(read_op: int = OP_READ, write_op: int = OP_WRITE):
    return HistoryScreen("read_your_writes", read_op, write_op)


def monotonic_reads(read_op: int = OP_READ):
    """Invoke-interval-aware monotonic reads (the sound default)."""
    return HistoryScreen("monotonic_reads", read_op, read_op)


def monotonic_reads_strict(read_op: int = OP_READ):
    """Response-order monotonic reads (opt-in; unsound for pipelined
    reads — the ``check.vectorized`` caveat applies verbatim)."""
    return HistoryScreen("monotonic_reads_strict", read_op, read_op)


def election_safety(elect_op: int):
    return HistoryScreen("election_safety", elect_op, 0)


def recovery_safety(sync_op: int, recover_op: int):
    return HistoryScreen("recovery_safety", sync_op, recover_op)


def lease_safety(serve_op: int, lease_op: int):
    """Lease-service screen (models/leasekv.py): serve-after-expiry
    and early-expiry, ``check.vectorized.lease_safety`` on device."""
    return HistoryScreen("lease_safety", serve_op, lease_op)


def shard_coverage(own_op: int, write_op: int):
    """Shard-migration screen (models/shardkv.py): double-serve and
    lost-range, ``check.vectorized.shard_coverage`` on device."""
    return HistoryScreen("shard_coverage", own_op, write_op)


def exactly_once(apply_op: int):
    """At-most-once-apply screen (the client-retry safety property,
    models/shardkv.py army puts): ``check.vectorized.exactly_once`` on
    device — the detector that catches retried non-idempotent applies
    no final-state invariant can see."""
    return HistoryScreen("exactly_once", apply_op, 0)


def default_screens() -> tuple:
    """The generic screen set over the shared op namespace — every
    built-in detector at its conventional ops. Used by the lint
    ``CHECK_AXES`` row (taint structure is op-independent); real sweeps
    pass the model's own ops."""
    return (
        stale_reads(),
        read_your_writes(),
        monotonic_reads(),
        election_safety(OP_USER),
        recovery_safety(OP_USER + 2, OP_USER + 3),
    )


def as_screens(spec) -> tuple:
    """Normalize a screen spec (one screen or an iterable) to a tuple."""
    if isinstance(spec, HistoryScreen):
        return (spec,)
    screens = tuple(spec)
    if not screens or not all(
        isinstance(s, HistoryScreen) for s in screens
    ):
        raise ValueError(
            f"device check must be a HistoryScreen or a non-empty "
            f"iterable of them, got {spec!r}"
        )
    return screens


def _chunked_seed_map(per_seed, word, count):
    """vmap ``per_seed`` over the seed axis, mapping in ``_CHUNK``-seed
    chunks past the threshold (bounds the pairwise masks' memory to
    chunk-scale no matter the sweep size); a non-dividing batch is
    padded with empty histories (count 0 — trivially clean) and
    sliced back. Value-identical either way."""
    s_dim = word.shape[0]
    vm = jax.vmap(per_seed)
    if s_dim <= _CHUNK:
        return vm(word, count)
    pad = (-s_dim) % _CHUNK
    if pad:
        word = jnp.concatenate(
            [word, jnp.zeros((pad,) + word.shape[1:], word.dtype)]
        )
        count = jnp.concatenate([count, jnp.zeros((pad,), count.dtype)])
    n = word.shape[0]
    wr = word.reshape((n // _CHUNK, _CHUNK) + word.shape[1:])
    cr = count.reshape((n // _CHUNK, _CHUNK))
    out = lax.map(lambda xc: vm(*xc), (wr, cr)).reshape(n)
    return out[:s_dim] if pad else out


def screen_ok(screens, word, t, count, drop):
    """Batched device verdict: (S,H,5)/(S,H)/(S,)/(S,) history columns
    -> (S,) bool, True = every screen clean.

    Traceable (jit / vmap / shard_map); ``t`` rides along for signature
    symmetry with the column set (no built-in screen reads clocks —
    buffer order IS dispatch order). Seeds whose buffer dropped records
    are judged as EMPTY histories (trivially clean), matching the
    ``search_seeds`` quarantine: their verdicts are voided via
    ``hist_drop``, never trusted.
    """
    del t
    screens = as_screens(screens)
    count = jnp.where(drop > 0, 0, count)

    def per_seed(w, c):
        ok = jnp.bool_(True)
        for s in screens:
            ok = ok & s.seed_kernel(w, c)
        return ok

    return _chunked_seed_map(per_seed, word, count)


def screens_invariant(screens):
    """The host form of a screen set: a ``search_seeds``
    ``history_invariant`` callable running the numpy oracles — the
    bit-identical reference arm of every device == host pin, and the
    replay path for device-found history violations on the host
    driver."""
    screens = as_screens(screens)

    def invariant(h: BatchHistory) -> np.ndarray:
        ok = np.ones(len(h), bool)
        for s in screens:
            ok &= np.asarray(s.host(h), bool)
        return ok

    invariant.__name__ = "+".join(s.kind for s in screens)
    return invariant


# ---------------------------------------------------------------------------
# verdict words — the transfer format
# ---------------------------------------------------------------------------


def pack_verdicts(ok):
    """(S,) bool verdicts -> (ceil(S/32),) uint32 packed words (bit
    ``s % 32`` of word ``s // 32`` = seed s clean; pad bits 0). The
    per-seed transfer format of a device-checked sweep: 1 bit/seed
    instead of the full history columns."""
    ok = jnp.asarray(ok, jnp.bool_)
    s_dim = ok.shape[0]
    pad = (-s_dim) % 32
    if pad:
        ok = jnp.concatenate([ok, jnp.zeros((pad,), jnp.bool_)])
    bits = ok.reshape(-1, 32).astype(jnp.uint32) << jnp.arange(
        32, dtype=jnp.uint32
    )[None, :]
    # distinct bit positions per lane: sum == bitwise or
    return jnp.sum(bits, axis=1).astype(jnp.uint32)


def unpack_verdicts(words, n_seeds: int) -> np.ndarray:
    """Host inverse of :func:`pack_verdicts` -> (n_seeds,) bool."""
    w = np.asarray(words, np.uint32)
    bits = (w[:, None] >> np.arange(32, dtype=np.uint32)[None, :]) & 1
    return bits.reshape(-1)[:n_seeds].astype(bool)


def pack_verdicts_host(ok) -> np.ndarray:
    """Numpy mirror of :func:`pack_verdicts` (for verdicts that are
    already host-side, e.g. the compacted runner's banked ``hist_ok``)."""
    ok = np.asarray(ok, bool)
    pad = (-ok.shape[0]) % 32
    if pad:
        ok = np.concatenate([ok, np.zeros((pad,), bool)])
    bits = ok.reshape(-1, 32).astype(np.uint32) << np.arange(
        32, dtype=np.uint32
    )[None, :]
    return bits.sum(axis=1, dtype=np.uint32)


# ---------------------------------------------------------------------------
# history prefix-compaction
# ---------------------------------------------------------------------------


def _fifo_unmatched(inv, resp, grp, idx):
    """Invokes left pending by the exact FIFO pairing discipline of
    ``BatchHistory.ops``: each response closes the OLDEST still-open
    earlier invoke of its (client, op, key) group; a response with no
    open invoke is instantaneous and consumes nothing."""
    h_dim = inv.shape[0]

    def body(j, matched):
        cand = inv & ~matched & grp[:, j] & (idx < j)
        has = resp[j] & jnp.any(cand)
        first = jnp.argmax(cand)
        return matched.at[first].set(matched[first] | has)

    matched = lax.fori_loop(0, h_dim, body, jnp.zeros((h_dim,), jnp.bool_))
    return inv & ~matched


def fold_verified(word, t, count, drop, ok):
    """Bank-time history prefix-compaction (the ``make_run_compacted``
    ``hist_screen`` fold): for seeds a device screen judged CLEAN, the
    responded operations — every response record plus its FIFO-matched
    invoke — fold out of the columns; only still-pending invokes
    survive, compacted to the front in buffer order. Returns
    ``(word2, t2, count2, fold)`` with ``fold`` the per-seed folded
    record count (``hist_fold`` — the hist_drop-style loud accounting:
    original count == count2 + fold, always).

    The escalation path is untouched **by construction**: a flagged
    seed (``ok`` False) or an overflowed one (``drop`` > 0) keeps every
    record verbatim (fold == 0), so exact Wing–Gong confirmation always
    sees the full history.
    """
    h_dim = word.shape[1]
    if h_dim == 0:
        return word, t, count, jnp.zeros_like(count)

    def per_seed(w, tt, c, d, okv):
        idx = jnp.arange(h_dim, dtype=jnp.int32)
        valid = idx < c
        op, key, _arg, client, okc = _cols(w)
        inv = valid & (okc == OK_PENDING)
        resp = valid & (okc != OK_PENDING)
        grp = (
            (client[:, None] == client[None, :])
            & (op[:, None] == op[None, :])
            & (key[:, None] == key[None, :])
        )
        keep_f = _fifo_unmatched(inv, resp, grp, idx)
        do_fold = okv & (d == 0)
        keep = jnp.where(do_fold, keep_f, valid)
        # stable compaction: kept rows first, original order preserved
        order = jnp.argsort(~keep, stable=True)
        n_keep = jnp.sum(keep).astype(c.dtype)
        mask = idx < n_keep
        w2 = jnp.where(mask[:, None], w[order], 0)
        t2 = jnp.where(mask, tt[order], 0)
        return w2, t2, n_keep, (c - n_keep).astype(c.dtype)

    return jax.vmap(per_seed)(word, t, count, drop, ok)


def violation_cones(report, wl=None) -> dict:
    """Causal forensics over a device-screened search's escalation set.

    For every flagged seed in ``report.flagged_idx`` (the Wing–Gong
    escalation payload of ``search_seeds(device_check=...)``), compute
    the backward happens-before cone (``obs.causal.causal_slice``)
    anchored at the seed's last completed history record — the point
    where the screen's verdict crystallized. The sweep must have run
    with ``causal=True`` and ``timeline_cap > 0``; the cone then rides
    the escalation for free (the provenance columns are already in the
    report), so the host confirmer narrates/replays a small causal
    slice instead of the whole captured stream.

    Returns ``{seed_row: CausalCone}`` in flagged order. A flagged
    seed with no completed record anchors at its final dispatch.
    """
    from ..obs.causal import causal_slice

    if report.flagged_idx is None:
        raise ValueError(
            "report carries no escalation set — run the sweep with "
            "device_check=... so flagged seeds are identified"
        )
    if report.timeline is None:
        raise ValueError(
            "violation cones need the captured ring — run the sweep "
            "with timeline_cap > 0 (and causal=True)"
        )
    h = report.flagged_history
    cones = {}
    for j, row in enumerate(np.asarray(report.flagged_idx)):
        anchor = None
        for i in range(int(h.count[j]) - 1, -1, -1):
            if int(h.word[j, i, COL_OK]) != OK_PENDING:
                anchor = (
                    int(h.t[j, i]), int(h.word[j, i, COL_CLIENT])
                )
                break
        cones[int(row)] = causal_slice(
            report.timeline, seed=int(row), anchor=anchor, wl=wl
        )
    return cones


# ---------------------------------------------------------------------------
# the latency detector
# ---------------------------------------------------------------------------


def slo_breaches(lat_hist, bound_ns: int, q: float = 0.99,
                 min_ops: int = 16):
    """Device port of ``check.slo.slo_breaches``: (S, P, B) per-seed
    latency sketches -> (S,) bool, True = some window PROVABLY
    breaches (the quantile bucket's lower edge exceeds the bound — the
    under-flag-never-false-flag rule, same rank convention as
    ``obs.hist_quantile_bucket``). Traceable, so SLO verdicts can join
    a device-resident program like the history screens do."""
    from ..engine.core import LAT_EDGES_NS, N_LAT_BUCKETS

    if not 0.0 < q < 1.0:
        raise ValueError(f"q must be in (0, 1), got {q}")
    if min_ops < 1:
        raise ValueError(f"min_ops must be >= 1, got {min_ops}")
    h = jnp.asarray(lat_hist).astype(jnp.int64)
    if h.ndim != 3 or h.shape[2] != N_LAT_BUCKETS:
        raise ValueError(
            f"lat_hist must be (S, P, {N_LAT_BUCKETS}), got shape {h.shape}"
        )
    total = h.sum(axis=-1)  # (S, P)
    rank = jnp.maximum(
        jnp.ceil(q * total).astype(jnp.int64), jnp.int64(1)
    )
    cum = jnp.cumsum(h, axis=-1)
    bucket = jnp.argmax(cum >= rank[..., None], axis=-1)
    bucket = jnp.where(total > 0, bucket, -1)
    edges = jnp.asarray(LAT_EDGES_NS)
    bc = jnp.clip(bucket, 0, None)
    lo = jnp.where(
        bc <= 0,
        jnp.int64(0),
        edges[jnp.clip(bc - 1, 0, N_LAT_BUCKETS - 2)],
    )
    breach = (total >= min_ops) & (bucket >= 0) & (lo > jnp.int64(bound_ns))
    return jnp.any(breach, axis=-1)
