"""madsim_tpu.check — operation-history recording + workload checkers.

Final-state invariants (engine/search.py) can only judge where a run
*ended*; this package judges what the workload *observed along the
way* — the FoundationDB-style workload verification that catches a
committed write vanishing even when the final state looks plausible.

Three layers, one history representation:

* **Recording.** The batched engine appends fixed-size per-seed history
  columns on device (``Workload.history = HistorySpec(...)`` +
  ``EmitBuilder.record``, engine/core.py); asyncio-level apps use
  :class:`Recorder`. Both produce (op, key, arg, client, ok) rows with
  sim-timestamps, paired host-side into invoke/response operations
  (check/history.py).
* **Cheap batch checkers** (check/vectorized.py): monotonic reads,
  read-your-writes, stale/lost-write and election-safety detectors as
  numpy passes over the whole seed batch — the
  ``search_seeds(history_invariant=...)`` fast path.
* **Exact checker** (check/linearize.py): Wing–Gong/porcupine-style
  linearizability for register and KV histories, per seed.

A fourth detector judges *latency* instead of histories:
``slo_bounded`` (check/slo.py) flags seeds whose per-window tail
quantile breaches an SLO bound, read off the engine's latency sketches
(``search_seeds(latency=...)``) — an SLO breach is a violation like
any other, searchable, shrinkable and replayable.

The cheap batch layer also exists as **device kernels** (check/
device.py): every vectorized detector restated as a jitted jnp kernel
over the on-device history columns, vmapped over seeds and traceable
under ``shard_map`` — bit-identical verdicts, consumed by
``engine.search_seeds(device_check=...)``,
``explore.run_device(history_check=...)`` and the compacted runner's
history prefix-compaction. A :class:`HistoryScreen` is the hashable
spec naming one detector (the invariant identity the program caches
key on); ``device.screens_invariant`` turns a screen set back into the
numpy ``history_invariant`` for host-driver replays.

The history layers import nothing from the engine — they are pure
host-side consumers of the recorded columns, usable on engine results,
compacted search views, and Recorder histories alike (check/slo.py
reads only the engine's static bucket-ladder constants).
"""

from .history import (  # noqa: F401
    COL_ARG,
    COL_CLIENT,
    COL_KEY,
    COL_OK,
    COL_OP,
    OK_FAIL,
    OK_OK,
    OK_PENDING,
    OP_READ,
    OP_USER,
    OP_WRITE,
    BatchHistory,
    HistoryError,
    Op,
)
from . import device  # noqa: F401
from .device import HistoryScreen, violation_cones  # noqa: F401
from .linearize import LinResult, check_kv, check_register  # noqa: F401
from .recorder import Recorder  # noqa: F401
from .slo import slo_bounded, slo_breaches  # noqa: F401
from .vectorized import (  # noqa: F401
    collapse_retries,
    election_safety,
    exactly_once,
    lease_safety,
    monotonic_reads,
    monotonic_reads_strict,
    read_your_writes,
    recovery_safety,
    shard_coverage,
    stale_reads,
)

__all__ = [
    "COL_ARG",
    "COL_CLIENT",
    "COL_KEY",
    "COL_OK",
    "COL_OP",
    "OK_FAIL",
    "OK_OK",
    "OK_PENDING",
    "OP_READ",
    "OP_USER",
    "OP_WRITE",
    "BatchHistory",
    "HistoryError",
    "HistoryScreen",
    "LinResult",
    "device",
    "Op",
    "Recorder",
    "check_kv",
    "check_register",
    "collapse_retries",
    "election_safety",
    "exactly_once",
    "lease_safety",
    "monotonic_reads",
    "monotonic_reads_strict",
    "read_your_writes",
    "recovery_safety",
    "shard_coverage",
    "slo_bounded",
    "slo_breaches",
    "stale_reads",
    "violation_cones",
]
