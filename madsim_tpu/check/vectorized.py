"""Whole-batch history checkers: one numpy pass over every seed at once.

The linearizability checker (check/linearize.py) is exact but per-seed;
these detectors trade precision for a cost model that matches the
batched engine — O(S·H) array passes over the raw history columns (plus
a loop over the distinct clients/keys present, a small constant for the
in-repo models). Each returns an ``(S,)`` boolean array, True = clean,
i.e. exactly the ``history_invariant`` contract of
``engine.search_seeds``.

Scope (documented assumptions, not silent ones):

* **Versioned registers.** ``monotonic_reads`` / ``read_your_writes`` /
  ``stale_reads`` assume writes to a key carry strictly increasing
  int32 versions (kvchaos: the write seq). "Fresher" is then decidable
  per-record without a search. Non-versioned histories belong to the
  linearizability checker.
* ``monotonic_reads`` is invoke-interval aware (pipelined reads that
  legally complete out of order are tolerated); the response-order pass
  survives as the opt-in ``monotonic_reads_strict``.
* **FIFO invoke/response pairing** per (client, op, key), exact for
  clients with one outstanding op per key (all in-repo models) — same
  rule and same caveat as ``BatchHistory.ops``.
* Seeds whose history buffer overflowed are *not* judged here: callers
  (``search_seeds``) quarantine them via ``hist_drop``; these passes
  simply see the stored prefix.

This module is the **authoritative oracle**: every detector also
exists as a device-resident jnp kernel (check/device.py) whose
verdicts must match these bit for bit — the rank-matching guard paths
(paired invoke / bare response / malformed invoke-after) are pinned
per detector by the oracle table in tests/test_check_device.py, so a
change here without a matching kernel change fails the identity pins.
"""

from __future__ import annotations

import numpy as np

from .history import (
    COL_ARG,
    COL_CLIENT,
    COL_KEY,
    COL_OK,
    COL_OP,
    OK_FAIL,
    OK_OK,
    OK_PENDING,
    OP_READ,
    OP_WRITE,
    SHARD_EPOCH_SHIFT,
    SHARD_GROUP_MASK,
    SHARD_GROUP_SHIFT,
    SHARD_VER_MASK,
    BatchHistory,
)

__all__ = [
    "monotonic_reads",
    "monotonic_reads_strict",
    "read_your_writes",
    "stale_reads",
    "election_safety",
    "recovery_safety",
    "lease_safety",
    "shard_coverage",
    "exactly_once",
    "collapse_retries",
]

_MIN = np.int64(-(2**62))  # "no prior write" floor sentinel


def _cols(h: BatchHistory):
    valid = h.valid()
    return (
        valid,
        h.col(COL_OP),
        h.col(COL_KEY),
        h.col(COL_ARG).astype(np.int64),
        h.col(COL_CLIENT),
        h.col(COL_OK),
    )


def monotonic_reads_strict(h: BatchHistory, read_op: int = OP_READ) -> np.ndarray:
    """Per (client, key): successive successful read values never
    decrease **in response order**. Pure response-order property — no
    pairing needed — but UNSOUND for pipelined reads: two reads open
    concurrently may legally complete out of order, and this pass flags
    that. Opt-in for clients known to issue one read at a time; the
    default :func:`monotonic_reads` is the invoke-interval-aware form
    (the ROADMAP soundness fix)."""
    valid, op, key, arg, client, ok = _cols(h)
    m = valid & (op == read_op) & (ok == OK_OK)
    s_dim, h_dim = m.shape
    if h_dim == 0:
        return np.ones(s_dim, bool)
    # sort each seed's rows by (client, key), stable → buffer (= time)
    # order within each group; masked rows sort to a sentinel group
    big = np.int64(2**31)
    c_sort = np.where(m, client.astype(np.int64), big)
    k_sort = np.where(m, key.astype(np.int64), big)
    order = np.lexsort((k_sort, c_sort), axis=-1)
    cs = np.take_along_axis(c_sort, order, axis=1)
    ks = np.take_along_axis(k_sort, order, axis=1)
    vs = np.take_along_axis(np.where(m, arg, 0), order, axis=1)
    ms = np.take_along_axis(m, order, axis=1)
    same = (
        ms[:, 1:] & ms[:, :-1]
        & (cs[:, 1:] == cs[:, :-1]) & (ks[:, 1:] == ks[:, :-1])
    )
    viol = same & (vs[:, 1:] < vs[:, :-1])
    return ~viol.any(axis=1)


def _read_floor_violations(
    h: BatchHistory, read_op: int, write_op: int, own_writes_only: bool
) -> np.ndarray:
    """Shared core of read_your_writes / stale_reads: a successful read
    must return at least the newest version whose write had completed
    before the read was *invoked* (writes by the same client only, or by
    anyone). Floors are sampled at the read's invoke record and carried
    to its response by FIFO rank matching, so a write completing while
    the read is in flight never false-flags."""
    valid, op, key, arg, client, ok = _cols(h)
    s_dim, h_dim = valid.shape
    if h_dim == 0:
        return np.ones(s_dim, bool)
    rows = np.arange(s_dim)[:, None]
    w_resp = valid & (op == write_op) & (ok == OK_OK)
    r_inv = valid & (op == read_op) & (ok == OK_PENDING)
    r_resp = valid & (op == read_op) & (ok == OK_OK)
    viol = np.zeros(s_dim, bool)
    keys = np.unique(key[r_resp | r_inv | w_resp])
    clients = np.unique(client[r_resp | r_inv])

    def _excl_floor(sel_w):
        # exclusive running max of completed write versions, i.e. the
        # floor as of each row's dispatch
        wval = np.where(sel_w, arg, _MIN)
        excl = np.empty_like(wval)
        excl[:, 0] = _MIN
        np.maximum.accumulate(wval[:, :-1], axis=1, out=excl[:, 1:])
        return excl

    for k in keys:
        kw = w_resp & (key == k)
        if not own_writes_only:
            excl = _excl_floor(kw)  # client-independent: hoist
        for c in clients:
            if own_writes_only:
                excl = _excl_floor(kw & (client == c))
            inv = r_inv & (key == k) & (client == c)
            resp = r_resp & (key == k) & (client == c)
            # FIFO rank matching: the r-th response pairs the r-th invoke
            inv_rank = np.cumsum(inv, axis=1) - inv
            resp_rank = np.cumsum(resp, axis=1) - resp
            floor_by_rank = np.full((s_dim, h_dim + 1), _MIN)
            idx_by_rank = np.full((s_dim, h_dim + 1), h_dim)
            inv_slot = np.where(inv, inv_rank, h_dim)
            floor_by_rank[rows, inv_slot] = np.where(inv, excl, _MIN)
            idx_by_rank[rows, inv_slot] = np.where(
                inv, np.arange(h_dim)[None, :], h_dim
            )
            resp_slot = np.where(resp, resp_rank, h_dim)
            floor = floor_by_rank[rows, resp_slot]
            inv_idx = idx_by_rank[rows, resp_slot]
            own = np.arange(h_dim)[None, :]
            # three response shapes, by the rank-matched invoke's index:
            #   earlier invoke  -> floor sampled at the invoke (paired op)
            #   NO invoke ever  -> a bare/instantaneous event (history.py
            #     convention: invoke == response), so the floor as of its
            #     OWN buffer position applies — writes completed before
            #     the record are completed before the op
            #   invoke AFTER    -> malformed interleaving; no constraint
            #     (under-flag instead of false-flag)
            floor = np.where(
                inv_idx <= own, floor, np.where(inv_idx == h_dim, excl, _MIN)
            )
            viol |= (resp & (arg < floor)).any(axis=1)
    return ~viol


def monotonic_reads(h: BatchHistory, read_op: int = OP_READ) -> np.ndarray:
    """Per (client, key): a successful read returns no older a version
    than the newest read **by the same client completed before this read
    was invoked** — the monotonic-reads session guarantee, invoke-
    interval aware. Pipelined reads (several open at once on one
    session) may legally complete out of order and are NOT flagged;
    instantaneous read events (no invoke record) are ordered by their
    buffer position. This is the floor construction of
    :func:`stale_reads` with completed same-client reads as the floor
    source, so it inherits the FIFO invoke/response pairing contract.
    The old response-order pass survives as
    :func:`monotonic_reads_strict` (opt-in; unsound for pipelined
    reads)."""
    return _read_floor_violations(h, read_op, read_op, own_writes_only=True)


def read_your_writes(
    h: BatchHistory, read_op: int = OP_READ, write_op: int = OP_WRITE
) -> np.ndarray:
    """A client's successful read returns no older a version than its
    own newest write completed before the read was invoked."""
    return _read_floor_violations(h, read_op, write_op, own_writes_only=True)


def stale_reads(
    h: BatchHistory, read_op: int = OP_READ, write_op: int = OP_WRITE
) -> np.ndarray:
    """Linearizable-read form: a successful read returns no older a
    version than the newest write completed (by *any* client) before
    the read was invoked. On a system that routes reads through the
    authority for the key, a flagged seed means a committed write's
    effect vanished — the lost-write detector."""
    return _read_floor_violations(h, read_op, write_op, own_writes_only=False)


def recovery_safety(
    h: BatchHistory, sync_op: int, recover_op: int
) -> np.ndarray:
    """Crash-recovery safety: a restarted node never regresses durably
    synced state.

    The workload records a successful ``sync_op`` event whenever a sync
    COMMITS a state change (arg = the new durable value, e.g. a log
    length — raftlog's ``OP_SYNCED``) and a ``recover_op`` event when a
    restarted node comes back up (arg = the value it recovered —
    ``OP_RECOVER``). A seed is flagged when any recover's arg is below
    the arg of the SAME client's (node's) latest earlier sync record.

    The floor is the LAST sync, not the running max: a newer-term
    leader may legitimately truncate a follower's log, and the
    truncated-then-synced length is exactly what a crash must recover
    to. Under correct fsync placement this holds even through torn-
    write faults (a tear only loses *uncommitted* bytes); a lying disk
    (chaos ``SYNC_LOSS`` windows) violates it by design — the detector
    doubles as the positive control that the fault injection works.
    Buffer order is dispatch order (the engine appends at dispatch), so
    "earlier" needs no timestamps.
    """
    valid, op, key, arg, client, ok = _cols(h)
    s_dim, h_dim = valid.shape
    if h_dim == 0:
        return np.ones(s_dim, bool)
    sync_m = valid & (op == sync_op) & (ok == OK_OK)
    rec_m = valid & (op == recover_op) & (ok == OK_OK)
    viol = np.zeros(s_dim, bool)
    if not rec_m.any() or not sync_m.any():
        return ~viol
    idx_row = np.broadcast_to(np.arange(h_dim)[None, :], valid.shape)
    for c in np.unique(client[rec_m]):
        sm = sync_m & (client == c)
        # index of the latest sync at-or-before each buffer slot
        # (running max over marked indices; -1 = no sync yet)
        last = np.maximum.accumulate(np.where(sm, idx_row, -1), axis=1)
        floor = np.take_along_axis(
            np.where(sm, arg, 0), np.maximum(last, 0), axis=1
        )
        rm = rec_m & (client == c)
        viol |= (rm & (last >= 0) & (arg < floor)).any(axis=1)
    return ~viol


def lease_safety(h: BatchHistory, serve_op: int, lease_op: int) -> np.ndarray:
    """Lease-service safety (models/leasekv.py): no operation is served
    through an expired lease, and expiry respects the skew-adjusted TTL
    contract.

    The workload records the lease LIFECYCLE on ``lease_op`` — a grant
    or renewal as ``OK_OK`` with arg = the granted deadline (the
    server's own clock, ms), an expiry as ``OK_FAIL`` with arg = the
    server's local clock at expiry — and every served operation on
    ``serve_op``/``OK_OK``, all keyed by lease id. A seed is flagged
    when:

    1. a serve's latest earlier lifecycle record (same lease) is an
       expiry — the lease was dead and no re-grant intervened, or
    2. an expiry's clock arg is below the latest earlier grant's
       deadline arg — the lease died before its own server's clock
       reached the deadline it was granted (the TTL contract is stated
       on the server's LOCAL clock, so honest skew never flags; only a
       server expiring early against itself does).

    A serve with no earlier lifecycle record constrains nothing
    (under-flag, not false-flag). Buffer order is dispatch order and
    all three record kinds come from the single lease server, so
    "earlier" is the server's own event order — no timestamps needed.
    """
    valid, op, key, arg, client, ok = _cols(h)
    s_dim, h_dim = valid.shape
    if h_dim == 0:
        return np.ones(s_dim, bool)
    life = valid & (op == lease_op)
    grant = life & (ok == OK_OK)
    expire = life & (ok == OK_FAIL)
    serve = valid & (op == serve_op) & (ok == OK_OK)
    viol = np.zeros(s_dim, bool)
    if not life.any():
        return ~viol
    idx_row = np.broadcast_to(np.arange(h_dim)[None, :], valid.shape)
    for k in np.unique(key[life | serve]):
        lm = life & (key == k)
        em = expire & (key == k)
        # clause 1: index of the latest lifecycle record at-or-before
        # each slot (inclusive accumulate — a serve row is never itself
        # a lifecycle row, so inclusive == strictly earlier)
        last_l = np.maximum.accumulate(np.where(lm, idx_row, -1), axis=1)
        last_is_exp = np.take_along_axis(
            em.astype(np.int64), np.maximum(last_l, 0), axis=1
        ) > 0
        sm = serve & (key == k)
        viol |= (sm & (last_l >= 0) & last_is_exp).any(axis=1)
        # clause 2: expiry clock vs the latest earlier grant's deadline
        gm = grant & (key == k)
        last_g = np.maximum.accumulate(np.where(gm, idx_row, -1), axis=1)
        gfloor = np.take_along_axis(
            np.where(gm, arg, 0), np.maximum(last_g, 0), axis=1
        )
        viol |= (em & (last_g >= 0) & (arg < gfloor)).any(axis=1)
    return ~viol


def shard_coverage(h: BatchHistory, own_op: int, write_op: int) -> np.ndarray:
    """Shard-migration safety (models/shardkv.py): every shard is owned
    by at most one group per config epoch, and no committed write is
    lost across a migration.

    The workload records every install on ``own_op``/``OK_OK`` (key =
    shard, arg = the packed (epoch, group, adopted-version) word —
    ``history.pack_shard_own``) and every committed write on
    ``write_op``/``OK_OK`` (key = shard, arg = the version; versions
    must fit ``SHARD_VER_MASK``). A seed is flagged when:

    1. two install records share (shard, epoch) with different groups —
       a double-served range, or
    2. an install's adopted version is below some committed write
       earlier in the history for that shard — a lost range: the
       handoff shipped state that predates a committed write.

    Buffer order is dispatch order (deterministic across the fleet), so
    "earlier" is well-defined without timestamps; a write committed
    *while* a handoff is legally in flight cannot exist in the clean
    protocol (the source freezes before handing off), which is exactly
    why clause 2 is stated over plain buffer order.
    """
    valid, op, key, arg, client, ok = _cols(h)
    s_dim, h_dim = valid.shape
    if h_dim == 0:
        return np.ones(s_dim, bool)
    own = valid & (op == own_op) & (ok == OK_OK)
    write = valid & (op == write_op) & (ok == OK_OK)
    epoch = arg >> SHARD_EPOCH_SHIFT
    group = (arg >> SHARD_GROUP_SHIFT) & SHARD_GROUP_MASK
    ver = arg & SHARD_VER_MASK
    # clause 1: pairwise (shard, epoch) with different groups
    pair = own[:, :, None] & own[:, None, :]
    same_key = key[:, :, None] == key[:, None, :]
    same_ep = epoch[:, :, None] == epoch[:, None, :]
    diff_g = group[:, :, None] != group[:, None, :]
    viol = (pair & same_key & same_ep & diff_g).any(axis=(1, 2))
    # clause 2: per shard, installs vs the running max committed
    # version (inclusive accumulate — an install row is never itself a
    # write row, so inclusive == strictly earlier)
    if own.any() and write.any():
        for k in np.unique(key[own | write]):
            wm = write & (key == k)
            wmax = np.maximum.accumulate(np.where(wm, arg, _MIN), axis=1)
            om = own & (key == k)
            viol |= (om & (wmax > _MIN) & (ver < wmax)).any(axis=1)
    return ~viol


def exactly_once(h: BatchHistory, apply_op: int) -> np.ndarray:
    """At-most-once application (the client-retry safety property,
    models/shardkv.py army puts): no operation is applied twice by the
    state machine.

    The workload records every APPLY — the moment a delivery actually
    mutates state, not the delivery itself — on ``apply_op``/``OK_OK``
    with key = the op id (retry attempt bits stripped; the arg may
    carry the attempt for forensics, it is not judged). A seed is
    flagged when two apply records share (client, key): the same
    logical op took effect more than once, which is exactly what a
    modeled retry (chaos.RetryPolicy) turns from impossible into
    routine the moment an apply path is not idempotent. A correctly
    deduplicating state machine produces zero duplicates by
    construction no matter how aggressively the policy re-sends.

    Pairwise over the history buffer (the election_safety cost shape) —
    sized for op streams of hundreds of records, not millions.
    """
    valid, op, key, arg, client, ok = _cols(h)
    m = valid & (op == apply_op) & (ok == OK_OK)
    s_dim, h_dim = m.shape
    if h_dim == 0:
        return np.ones(s_dim, bool)
    pair = m[:, :, None] & m[:, None, :]
    same_key = key[:, :, None] == key[:, None, :]
    same_client = client[:, :, None] == client[:, None, :]
    off_diag = ~np.eye(h_dim, dtype=bool)[None, :, :]
    return ~(pair & same_key & same_client & off_diag).any(axis=(1, 2))


def collapse_retries(h: BatchHistory) -> BatchHistory:
    """Collapse retried invokes into one invocation interval per op.

    A model that records an invoke per DELIVERY (one per retry attempt)
    gives the FIFO invoke/response pairing several pending invokes for
    one logical op: the response then pairs the oldest attempt — which
    is the correct interval (latency clocks span first attempt ->
    final response) — but every later attempt's invoke lingers as a
    spurious pending op, and the floor detectors
    (:func:`read_your_writes` / :func:`stale_reads` /
    :func:`monotonic_reads`) would rank-match some FUTURE response to
    it, skewing intervals. This pass rewrites the history so each
    (client, op, key) carries at most one open invoke at a time: an
    invoke arriving while an earlier invoke of the same (client, op,
    key) is still unresponded is a retry re-send, and its record's op
    code is cleared to 0 (matching no detector mask — the row count
    and buffer order are untouched, so downstream index math is
    unchanged).

    The rule is stated over buffer (= dispatch) order: row j's invoke
    collapses iff an earlier invoke of the same (client, op, key)
    exists with no response of that (client, op, key) between them.
    O(S·H²) pairwise, like the pairwise detectors; the device twin is
    ``check.device.collapse_retries_cols`` (bit-identical by
    construction — same masks, same formula).
    """
    valid, op, key, arg, client, ok = _cols(h)
    s_dim, h_dim = valid.shape
    if h_dim == 0:
        return h
    inv = valid & (ok == OK_PENDING)
    resp = valid & (ok != OK_PENDING)
    same = (
        (key[:, :, None] == key[:, None, :])
        & (client[:, :, None] == client[:, None, :])
        & (op[:, :, None] == op[:, None, :])
    )
    lower = np.tril(np.ones((h_dim, h_dim), bool), k=-1)[None, :, :]
    # per-row count of same-group responses strictly before it: two
    # rows of one group share a "segment" iff these counts are equal,
    # i.e. no group response lies between them
    rcnt = (same & lower & resp[:, None, :]).sum(axis=2)
    collapsed = (
        inv
        & (
            same & lower & inv[:, None, :]
            & (rcnt[:, :, None] == rcnt[:, None, :])
        ).any(axis=2)
    )
    word = np.array(h.word, copy=True)
    word[..., COL_OP] = np.where(collapsed, 0, word[..., COL_OP])
    return BatchHistory(
        word=word, t=h.t, count=h.count, drop=h.drop
    )


def election_safety(h: BatchHistory, elect_op: int) -> np.ndarray:
    """At most one winner per term: no two successful ``elect_op``
    records share a key (term) with different args (winners). Pairwise
    over the history buffer — sized for election histories (capacity
    ~tens), not for long op streams."""
    valid, op, key, arg, client, ok = _cols(h)
    m = valid & (op == elect_op) & (ok == OK_OK)
    if m.shape[1] == 0:
        return np.ones(m.shape[0], bool)
    pair = m[:, :, None] & m[:, None, :]
    same_key = key[:, :, None] == key[:, None, :]
    diff_win = arg[:, :, None] != arg[:, None, :]
    return ~(pair & same_key & diff_win).any(axis=(1, 2))
