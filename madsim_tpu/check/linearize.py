"""Wing–Gong linearizability checker for register and KV histories.

The exact, per-seed checker: given one seed's paired operations
(:meth:`check.history.BatchHistory.ops`), decide whether there exists a
linearization — a total order of the operations that (a) respects
real-time precedence (op A completed before op B was invoked ⇒ A
before B) and (b) is a legal sequential execution of the model
(int-valued registers; KV = one register per key).

The algorithm is the Wing–Gong recursion with porcupine's memoization:
repeatedly pick a *minimal* operation (one invoked before every
still-unlinearized definite operation's response), apply it to the
model state, recurse; prune on (remaining-set, state) pairs already
proven dead. Real-time precedence is judged by the operations' record
*indices* (``Op.idx_inv``/``Op.idx_res``), not raw timestamps: the
engine appends history records in dispatch order, so indices are a
strict refinement of sim-time that resolves same-timestamp ties (a
write response and a read invoke recorded by one handler) exactly. Worst case exponential like every linearizability check
(the problem is NP-complete); the histories the batched models record
are small (tens of ops, few clients) and check in microseconds. For
whole-batch sweeps use the cheap vectorized detectors first
(check/vectorized.py) and reserve this checker for flagged seeds — or
run it everywhere when the op counts are small (tools/check_soak.py
does).

Uncertain operations:

* pending ops (invoked, never responded) **may or may not** have taken
  effect — the search may linearize them anywhere after their invoke
  or drop them entirely (the FoundationDB "maybe committed" case);
* explicitly failed writes (``ok == OK_FAIL``) are treated the same
  way (a failed response proves nothing about the effect);
* failed/pending reads constrain nothing (their output was never
  observed) and are discarded.
"""

from __future__ import annotations

import dataclasses

from .history import OK_FAIL, OK_OK, OK_PENDING, OP_READ, OP_WRITE, Op

__all__ = ["LinResult", "check_register", "check_kv"]

_T_INF = 2**63  # "never responded" for real-time ordering purposes


@dataclasses.dataclass(frozen=True)
class LinResult:
    """Verdict of one linearizability check."""

    ok: bool
    n_ops: int  # ops the search actually had to order (definite+optional)
    reason: str | None = None

    def __bool__(self) -> bool:
        return self.ok


def check_register(ops: list[Op], init: int = 0) -> LinResult:
    """Linearizability of a single int register (ignores ``Op.key``).

    write(v): always legal, sets the register. read()->v: legal iff the
    register holds v. ``init`` is the register's initial value.
    """
    definite: list[Op] = []
    optional: list[Op] = []
    for o in ops:
        if o.op not in (OP_READ, OP_WRITE):
            raise ValueError(
                f"check_register only models OP_READ/OP_WRITE histories, "
                f"got op kind {o.op} — filter workload-specific events "
                f"out (or check them with check.vectorized)"
            )
        if o.ok == OK_OK:
            definite.append(o)
        elif o.op == OP_WRITE and o.ok in (OK_PENDING, OK_FAIL):
            optional.append(o)
        # pending/failed reads: no observed output, no constraint
    items = definite + optional
    n = len(items)
    if n > 63:
        raise ValueError(
            f"{n} ops exceed the 63-op bitmask bound of this checker; "
            f"shard the history (e.g. per key via check_kv) first"
        )
    nd = len(definite)
    t_inv = [o.idx_inv for o in items]
    # optional ops get an infinite response for ordering: their effect
    # window is open-ended, so they never constrain the frontier (the
    # conservative — more permissive, no-false-violation — choice)
    t_res = [
        (o.idx_res if i < nd and o.idx_res is not None else _T_INF)
        for i, o in enumerate(items)
    ]
    definite_mask = (1 << nd) - 1
    full_mask = (1 << n) - 1
    seen: set = set()

    def dfs(rem: int, state: int) -> bool:
        rem_def = rem & definite_mask
        if rem_def == 0:
            return True  # leftover optional ops simply never took effect
        if (rem, state) in seen:
            return False
        seen.add((rem, state))
        # frontier: an op is minimal iff invoked no later than every
        # remaining definite op's response
        bound = min(t_res[j] for j in _bits(rem_def))
        for i in _bits(rem):
            if t_inv[i] > bound:
                continue
            o = items[i]
            if o.op == OP_WRITE:
                if dfs(rem & ~(1 << i), o.arg_inv):
                    return True
            elif o.arg_res == state:
                if dfs(rem & ~(1 << i), state):
                    return True
        return False

    if dfs(full_mask, init):
        return LinResult(True, n)
    return LinResult(
        False,
        n,
        f"no linearization of {nd} completed ops "
        f"(+{n - nd} maybe-applied) exists for register init={init}",
    )


def check_kv(ops: list[Op], init: int = 0) -> LinResult:
    """Linearizability of a KV store: one independent register per key.

    Keys never interact in the sequential model, so the history
    partitions exactly and each key checks separately (this is also
    what keeps the exponential worst case at bay).
    """
    by_key: dict[int, list[Op]] = {}
    for o in ops:
        by_key.setdefault(o.key, []).append(o)
    total = 0
    for key, kops in sorted(by_key.items()):
        r = check_register(kops, init=init)
        total += r.n_ops
        if not r.ok:
            return LinResult(False, total, f"key {key}: {r.reason}")
    return LinResult(True, total)


def _bits(mask: int):
    while mask:
        low = mask & -mask
        yield low.bit_length() - 1
        mask ^= low
