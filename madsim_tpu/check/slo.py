"""SLO-violation detection over the engine's latency sketches.

The tail-latency analog of the vectorized history detectors: a
violation is not a lost write but a *latency objective breach* — the
p99 (or any quantile) of the client-observed response time exceeding a
bound. The check is evaluated **per measurement window** (the
``LatencySpec.phases`` cut), which is what makes it gray-failure-aware:
a 150 ms fault window that blows the tail 10x is invisible in a
whole-run percentile (diluted by the healthy windows) but is exactly
one window's histogram here.

``slo_bounded`` returns a predicate with the ``search_seeds``
final-state ``invariant`` contract (view dict -> (S,) bool, True =
clean), so SLO breaches join the detector family: they count as
violations in searches, guide the explore hunt, shrink under ddmin
(``shrink_plan(latency=...)``) and replay exactly like any safety
violation.

Resolution contract (documented, not silent): quantiles live on the
fixed ladder (``engine.LAT_EDGES_NS``), so the bound is judged at
bucket resolution — a seed is flagged only when the quantile bucket's
LOWER edge exceeds the bound, i.e. when the true quantile *provably*
exceeds it. Breaches inside the same bucket as the bound are not
flagged (under-flag, never false-flag — the vectorized-detector rule).

Under a client-retry policy (``chaos.RetryPolicy``) the judged latency
is attempt-collapsed by construction: the engine's per-op clocks span
the FIRST attempt's invoke to the final response (lat_start is
first-start-wins, core.py), so a breach here is the latency the end
user saw across every re-send — retries can only widen it, never reset
the clock. Give-ups leave the op uncompleted (it never folds into the
sketch), the same undercount rule as a lost op without retries.
"""

from __future__ import annotations

import numpy as np

from ..engine.core import N_LAT_BUCKETS, lat_bucket_lo

__all__ = ["slo_bounded", "slo_breaches"]


def slo_breaches(
    lat_hist: np.ndarray,
    bound_ns: int,
    q: float = 0.99,
    min_ops: int = 16,
) -> np.ndarray:
    """(S, P, B) sketches -> (S,) True where some window breaches.

    A window is judged only when it completed at least ``min_ops`` ops
    (a one-op window has no p99; requiring a floor keeps a single slow
    straggler from flagging a seed). The quantile-rank convention is
    shared with ``obs.hist_quantile_bucket``.
    """
    from ..obs.latency import hist_quantile_bucket

    h = np.asarray(lat_hist, np.int64)
    if h.ndim != 3 or h.shape[2] != N_LAT_BUCKETS:
        raise ValueError(
            f"lat_hist must be (S, P, {N_LAT_BUCKETS}), got shape {h.shape}"
        )
    if not 0.0 < q < 1.0:
        raise ValueError(f"q must be in (0, 1), got {q}")
    if min_ops < 1:
        raise ValueError(f"min_ops must be >= 1, got {min_ops}")
    total = h.sum(axis=-1)  # (S, P)
    bucket = hist_quantile_bucket(h, q)  # (S, P), -1 where empty
    # provable breach: the whole quantile bucket sits above the bound
    lo = lat_bucket_lo(np.clip(bucket, 0, None))
    breach = (total >= min_ops) & (bucket >= 0) & (lo > int(bound_ns))
    return breach.any(axis=-1)


def slo_bounded(
    bound_ns: int,
    q: float = 0.99,
    min_ops: int = 16,
):
    """Build a ``search_seeds`` invariant: every measurement window's
    ``q``-quantile latency stays at-or-under ``bound_ns``.

    Requires the sweep to run with ``latency=LatencySpec(...)`` (and a
    ``chaos.ClientArmy`` — or hand-rolled ``lat_start/lat_end`` markers
    — actually producing ops); a sweep without the tap raises rather
    than silently passing every seed.
    """

    def invariant(view) -> np.ndarray:
        h = np.asarray(view["lat_hist"])
        if h.ndim != 3 or h.shape[1] == 0 or h.shape[2] == 0:
            raise ValueError(
                "slo_bounded needs latency sketches: run the sweep with "
                "latency=LatencySpec(...) (engine latency tap) and a "
                "client army producing ops"
            )
        return ~slo_breaches(h, bound_ns, q=q, min_ops=min_ops)

    invariant.__name__ = f"slo_p{int(q * 1000)}_le_{int(bound_ns)}ns"
    return invariant
