"""Static non-interference proof for the engine's derived state.

The engine's observability columns (coverage, metrics, timeline,
history — and the disk columns when the sync discipline is off) carry a
contract: *derived state only* — the step may append to them but no
value computed from them may reach a core ``SimState`` column, an RNG
draw, or the trace fold. PRs 1-5 verified this dynamically (off = zero
size + bit-identical traces, sampled per layout). This module proves
it statically, per (workload, config, build flags): trace the step (or
run) function to a jaxpr, taint the derived input leaves with their
``engine.derived_fields`` names, propagate (lint.taint), and require
every CORE output leaf to come back label-free.

The report is machine-readable and cites SimState **field names** — the
same column vocabulary ``obs.explain`` narrates with — so a leak reads
like "``met`` reaches ``step`` via eqns[412]:add", not like an XLA dump.

The dynamic identity tests and this proof are complementary: the tests
catch semantic drift the type system can't see; the proof catches
value-identical-but-data-dependent edges (e.g. ``step + met*0``) that
bit-identity can never witness. :func:`plant_met_leak` builds exactly
that mutant, and the test suite asserts it is caught.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

import jax

from ..engine.core import (
    EngineConfig,
    LatencySpec,
    Workload,
    derived_fields,
    make_init,
    make_run,
    make_step,
)
from .taint import analyze_jaxpr

__all__ = [
    "NonInterferenceReport",
    "check_matrix",
    "check_noninterference",
    "model_matrix",
    "plant_met_leak",
    "BUILD_AXES",
    "CAMPAIGN_AXES",
    "CHECK_AXES",
    "FLIGHT_AXES",
    "LAYOUT_AXES",
]

# Host round-trip primitives: none may appear in a traced sim program.
# The flight recorder / profiler (obs.prof, obs.flight) is host-side
# bookkeeping by design — the matrix proves it stays that way by
# tracing WITH a profiler active and scanning for these. The real rule
# is the substring match (io_callback/pure_callback/debug_callback/...
# all contain it); the set holds only the names that don't.
_CALLBACK_PRIMS = frozenset({"outside_call"})


def _callback_prims(jaxpr, found=None) -> list:
    """Names of host-callback primitives anywhere in a jaxpr tree."""
    if found is None:
        found = set()
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if "callback" in name or name in _CALLBACK_PRIMS:
            found.add(name)
        for key, val in eqn.params.items():
            vals = val if isinstance(val, (tuple, list)) else (val,)
            for item in vals:
                inner = getattr(item, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    _callback_prims(inner, found)
                elif hasattr(item, "eqns"):
                    _callback_prims(item, found)
    return sorted(found)


def _leaf_names(tree) -> list:
    """SimState leaf names in flatten order (``.field`` -> ``field``)."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(path).lstrip(".") for path, _leaf in leaves]


@dataclasses.dataclass
class NonInterferenceReport:
    """Verdict + isolation frontier of one traced (wl, cfg, flags)."""

    workload: str
    config_hash: str
    entry: str  # "step" or "run"
    flags: dict  # the build flags that shaped the traced program
    derived: tuple  # taint-source field names (engine.derived_fields)
    # field -> sorted source labels, for EVERY tainted output field.
    # Derived fields legitimately appear here (they are read-modify-
    # write); a CORE field appearing is the leak.
    out_taint: dict
    # field -> {labels, chain} for core outputs only — the violations
    leaks: dict
    # tainted equations: [{path, prim, sources, mixes_clean}]
    frontier: list
    n_eqns: int
    # host-callback primitives found anywhere in the traced program —
    # always scanned (cheap); must be empty for sim code. With
    # flags["flight"] the trace itself ran under an active
    # ProgramProfiler, so a nonempty list would mean the flight taps
    # leaked INTO the traced program.
    callback_prims: list = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.leaks and not self.callback_prims

    def to_dict(self) -> dict:
        return {
            "workload": self.workload,
            "config_hash": self.config_hash,
            "entry": self.entry,
            "flags": self.flags,
            "derived": list(self.derived),
            "out_taint": self.out_taint,
            "leaks": self.leaks,
            "frontier": self.frontier,
            "n_eqns": self.n_eqns,
            "callback_prims": self.callback_prims,
            "ok": self.ok,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    def summary(self) -> str:
        what = (
            f"{self.workload} [{self.entry}] flags="
            f"{{{', '.join(f'{k}={v}' for k, v in sorted(self.flags.items()) if v)}}}"
        )
        if self.ok:
            return (
                f"OK   {what}: {len(self.derived)} tainted columns stay "
                f"isolated over {self.n_eqns} equations "
                f"({len(self.frontier)} on the frontier)"
            )
        if self.callback_prims and not self.leaks:
            return (
                f"LEAK {what}: host-callback primitive(s) "
                f"{self.callback_prims} inside the traced program"
            )
        lines = [f"LEAK {what}:"]
        for field, info in self.leaks.items():
            lines.append(
                f"  derived {sorted(info['labels'])} reaches core "
                f"column {field!r}"
            )
            for hop in info["chain"]:
                lines.append(
                    f"    via {hop['path']}:{hop['prim']} "
                    f"(sources {hop['sources']})"
                )
        return "\n".join(lines)


def check_noninterference(
    wl: Workload,
    cfg: EngineConfig,
    *,
    entry: str = "step",
    layout: str = "scatter",
    time32: bool = False,
    placement: str | None = None,
    dup_rows: bool = False,
    cov_words: int = 0,
    metrics: bool = False,
    timeline_cap: int = 0,
    cov_hitcount: bool = False,
    latency: LatencySpec | None = None,
    pool_index: bool | None = None,
    causal: bool = False,
    n_steps: int = 4,
    n_seeds: int = 2,
    mutate=None,
    flight: bool = False,
    check: bool = False,
) -> NonInterferenceReport:
    """Prove (or refute) derived-state non-interference for one build.

    ``entry="step"`` traces the single-seed step — the per-equation
    frontier is then readable. ``entry="run"`` traces
    ``make_run(n_steps)`` over a batched state, which routes the whole
    proof through a vmapped ``lax.scan`` body (the loop-carry fixpoint
    path). ``entry="sharded_run"`` traces the same batched run under
    ``shard_map`` across every available device — the multi-chip
    campaign program (explore.run_device's simulate stage), proved
    through the shard_map call boundary (the batch is rounded up to
    the device count). ``mutate`` optionally wraps the traced function (the planted
    leak mutants use it); it receives and returns a
    ``SimState -> SimState`` callable.

    ``flight=True`` performs the whole trace under an ACTIVE
    ``obs.prof.ProgramProfiler`` — the flight-recorder boundary proof:
    the profiler is host-side bookkeeping, so the traced program must
    be unchanged (same equations, no host-callback primitives, taint
    still isolated). Every report also carries ``callback_prims``: any
    host round-trip primitive found in the traced program fails the
    proof regardless of taint.

    ``check=True`` appends the device history detectors
    (``check.device.default_screens`` over the final state's history
    columns) to the traced program — the device-verification boundary
    proof: the detector kernels are traced WITH the sim (through the
    ``shard_map`` boundary under ``entry="sharded_run"``, the
    ``explore.run_device`` history-hunt program shape), and the proof
    obligations are that the taint set is UNCHANGED (the detectors
    read derived history columns and write only the new ``check_ok``
    output — never a core column) and that no host-callback primitive
    appears. Needs a run-shaped entry (the detectors judge batched
    final states).
    """
    flags = dict(
        layout=layout, time32=time32, placement=placement, dup_rows=dup_rows,
        cov_words=cov_words, metrics=metrics, timeline_cap=timeline_cap,
        cov_hitcount=cov_hitcount, pool_index=pool_index, causal=causal,
        # JSON-able form (reports serialize): the spec's defining triple
        latency=(
            (latency.ops, latency.phases, latency.phase_ns)
            if latency is not None else None
        ),
    )
    obs_kw = dict(
        dup_rows=dup_rows, cov_words=cov_words, metrics=metrics,
        timeline_cap=timeline_cap, cov_hitcount=cov_hitcount,
        latency=latency, causal=causal,
    )
    if check:
        if entry == "step":
            raise ValueError(
                "check=True traces the batch detectors over a RUN's "
                "final states; use entry='run' or 'sharded_run'"
            )
        from ..check.device import default_screens
        from ..check.device import screen_ok as _screen_ok

        flags["check"] = True
        _screens = default_screens()

        def _with_check(base):
            def checked(st):
                out = base(st)
                return out, _screen_ok(
                    _screens, out.hist_word, out.hist_t, out.hist_count,
                    out.hist_drop,
                )
            return checked
    else:
        def _with_check(base):
            return base
    init = make_init(
        wl, cfg, time32=time32, cov_words=cov_words, metrics=metrics,
        timeline_cap=timeline_cap, cov_hitcount=cov_hitcount,
        latency=latency, pool_index=pool_index, causal=causal,
    )
    state = init(np.zeros(max(n_seeds, 1), np.uint64))
    if entry == "step":
        fn = make_step(
            wl, cfg, layout=layout, time32=time32, placement=placement,
            pool_index=pool_index, **obs_kw,
        )
        template = jax.tree.map(lambda a: a[0], state)
    elif entry == "run":
        fn = _with_check(make_run(
            wl, cfg, n_steps, layout=layout, time32=time32,
            placement=placement, pool_index=pool_index, **obs_kw,
        ))
        template = state
    elif entry == "sharded_run":
        # the multi-chip campaign program (explore.run_device's simulate
        # stage): the batched run under shard_map across every available
        # device — the proof walks THROUGH the shard_map call boundary
        # (lint.taint) instead of stopping at it. The per-shard body is
        # the same make_run scan, so a leak inside a shard is reported
        # with its nested eqns[..].shard_map.body path.
        from jax.sharding import PartitionSpec as _P

        from .. import parallel as _par

        mesh = _par.make_mesh()
        n_dev = int(mesh.devices.size)
        flags["mesh_devices"] = n_dev
        rows = max(n_seeds, n_dev)
        if rows % n_dev:
            rows += n_dev - rows % n_dev
        state = init(np.zeros(rows, np.uint64))
        run_fn = _with_check(make_run(
            wl, cfg, n_steps, layout=layout, time32=time32,
            placement=placement, pool_index=pool_index, **obs_kw,
        ))
        spec = _P(mesh.axis_names)
        # the detector (when check=True) is INSIDE the shard_map body:
        # the per-shard program is sim + screen, exactly how
        # explore.run_device composes them
        fn = _par.shard_map_nocheck(
            run_fn, mesh, in_specs=spec, out_specs=spec
        )
        template = state
    else:
        raise ValueError(
            f"unknown entry {entry!r} (step, run, or sharded_run)"
        )
    if mutate is not None:
        fn = mutate(fn)

    if flight:
        # trace with the flight recorder's profiler ACTIVE: the traced
        # program must come out identical to the profiler-off trace
        # (the analysis below proves taint + callback-freedom; the test
        # suite additionally pins equation-count equality)
        from ..obs import prof as _prof

        flags["flight"] = True
        with _prof.profiled():
            closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(
                template
            )
    else:
        closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(template)
    in_names = _leaf_names(template)
    out_names = _leaf_names(out_shape)
    if check:
        # the checked entry returns (state, verdict): strip the tuple
        # prefix from the state leaves and name the verdict leaf — it
        # is ALLOWED to carry history taint (that is what a verdict
        # is); a core column newly tainted is still the leak
        out_names = [
            "check_ok" if n.startswith("[1]")
            else n.removeprefix("[0]").lstrip(".")
            for n in out_names
        ]
    derived = derived_fields(wl)
    dset = set(derived)
    if check:
        dset.add("check_ok")
    in_taints = [
        frozenset({name}) if name in dset else frozenset()
        for name in in_names
    ]
    result = analyze_jaxpr(closed, in_taints)

    out_taint = {}
    leaks = {}
    for i, (name, labels) in enumerate(zip(out_names, result.out_taint)):
        if not labels:
            continue
        out_taint[name] = sorted(labels)
        if name not in dset:
            leaks[name] = {
                "labels": sorted(labels),
                "chain": result.leak_chain(i),
            }
    return NonInterferenceReport(
        workload=wl.name,
        config_hash=cfg.hash(),
        entry=entry,
        flags=flags,
        derived=derived,
        out_taint=out_taint,
        leaks=leaks,
        frontier=[r.to_dict() for r in result.frontier],
        n_eqns=_count_eqns(closed.jaxpr),
        callback_prims=_callback_prims(closed.jaxpr),
    )


def _count_eqns(jaxpr) -> int:
    n = len(jaxpr.eqns)
    for eqn in jaxpr.eqns:
        for key, val in eqn.params.items():
            vals = val if isinstance(val, (tuple, list)) else (val,)
            for item in vals:
                inner = getattr(item, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    n += _count_eqns(inner)
                elif hasattr(item, "eqns"):
                    n += _count_eqns(item)
    return n


# ---------------------------------------------------------------------------
# Planted leak mutants — the positive controls.
# ---------------------------------------------------------------------------


def plant_met_leak(step_fn):
    """Wrap a step so one op reads ``met`` into the RNG cursor.

    ``step + met[MET_SENT] * 0`` is **value-identical** to the clean
    step on every input — no runtime identity test, oracle compare or
    trace hash can ever distinguish them — yet it is a real data edge
    from a derived column into the RNG coordinate, exactly the class of
    bug the static proof exists to catch. Requires ``metrics=True``
    (otherwise ``met`` is zero-size and there is nothing to read).
    """
    import jax.numpy as jnp

    from ..engine.core import MET_SENT

    def mutant(st):
        out = step_fn(st)
        if out.met.ndim != 1:
            raise ValueError(
                "plant_met_leak is a step-entry mutant: it indexes the "
                "(N_METRICS,) met vector of ONE seed; with entry='run' "
                "the batched (S, N_METRICS) axis would be poisoned "
                "along the wrong dimension"
            )
        poison = (out.met[MET_SENT] * jnp.int32(0)).astype(jnp.uint32)
        return dataclasses.replace(out, step=out.step + poison)

    return mutant


# ---------------------------------------------------------------------------
# The certified matrix: four recorded models x build-flag axes.
# ---------------------------------------------------------------------------

# build-flag axes: each turns one derived-column family (or all of
# them) on. History on/off and disk-discipline on/off are MODEL
# variants (record= / durable=), so they live in model_matrix below.
BUILD_AXES = {
    "base": {},
    "metrics": dict(metrics=True),
    "timeline": dict(timeline_cap=8),
    "coverage": dict(cov_words=8),
    "hitcount": dict(cov_words=8, cov_hitcount=True),
    "latency": dict(latency=LatencySpec(ops=8, phases=2)),
    # the causal-provenance columns (ISSUE 19): the per-node Lamport
    # clock, the pool's parent/lam provenance columns and the ring's
    # seq/parent/lam banks. The clock FOLDS across dispatches
    # (lam[dst] = max(lam[dst], lam_at_emit) + 1) — a read-modify-write
    # cycle entirely inside the derived set, which is exactly the shape
    # a leak would take if the fold ever touched the RNG cursor or the
    # pool times, so the row is swept with the timeline on (the ring
    # banks only exist with a ring to write into).
    "causal": dict(causal=True, timeline_cap=8),
    "all": dict(
        metrics=True, timeline_cap=8, cov_words=8, cov_hitcount=True,
        latency=LatencySpec(ops=8, phases=2), causal=True,
    ),
}

# lowering/representation axes: (layout, time32, placement) triples.
# The scatter int64 build was the historical matrix; dense and time32
# produce the same jaxpr SHAPES (masked selects vs gathers, int32 vs
# int64 pool times) but different equation graphs — the proof must
# hold over all of them, and the COMBINED (dense, time32) pair is the
# exact program an accelerator runs (layout and representation both
# auto-resolve that way off-CPU), so it is swept too, not merely each
# axis alone. The placement member sweeps the scatter layout's two
# pool-write lowerings (PR 8): "rank" is the select-chain program a
# small-pool CPU run compiles (cold-bank appends — history rank-append,
# timeline/latency rows — ride this path), "scatter" the historical
# .at[].set stores a client-army-scale pool still uses; both must keep
# the derived columns isolated, not just the default one. Dense
# ignores placement (its one-hot writes are already rank-matched).
LAYOUT_AXES = (
    ("scatter", False, "rank"),
    ("scatter", False, "scatter"),
    ("dense", False, None),
    ("scatter", True, "rank"),
    ("dense", True, None),
    # the readiness-partitioned pool (ISSUE 13): the indexed program an
    # army-scale CPU pool compiles — tile-summary pop, per-tile free
    # search, element-store placement. The tile summary columns are
    # derived BY CONSTRUCTION (rebuilt on restore, excluded from the
    # checkpoint format) but trajectory-coupled, so they sit on the
    # CORE side of this proof: the obligation here is that no obs
    # column ever reaches them (or anything else core) through the new
    # index arithmetic; their own value-correctness certificate is the
    # index on/off bit-identity pin (tests/test_pool_index.py,
    # tools/lint_soak.py cert 1c). The time32 pair covers the rebased
    # tile minima.
    ("scatter", False, None, True),
    ("scatter", True, None, True),
)

# The sharded-campaign matrix entry (ROADMAP lint follow-on; required
# before pod-scale campaigns are load-bearing): the device campaign's
# tap set — coverage guidance + fleet metrics + latency sketches, the
# derived columns explore.run_device folds while the simulate stage
# runs under shard_map — proved through the shard_map call boundary
# with entry="sharded_run". Sweep it as
# ``check_matrix(models, CAMPAIGN_AXES, entry="sharded_run")``.
CAMPAIGN_AXES = {
    "sharded-campaign": dict(
        cov_words=8, metrics=True, latency=LatencySpec(ops=8, phases=2),
    ),
    # the causal campaign (ISSUE 19): run_device with causal=True — the
    # Lamport fold + provenance ring traced THROUGH the shard_map call
    # boundary, proving the clock columns stay label-free per shard
    # exactly as they do in the single-chip program.
    "sharded-causal": dict(cov_words=8, causal=True, timeline_cap=8),
}

# The flight-recorder boundary entry (PR 12): the campaign tap set
# traced with an obs.prof.ProgramProfiler ACTIVE — proving the flight
# taps (profiler, heartbeats, device-memory accounting) stay host-side:
# the traced program carries no host-callback primitive and the taint
# proof is unchanged. Sweep as
# ``check_matrix(models, FLIGHT_AXES, entry="sharded_run")`` (the soak)
# or ``entry="run"`` (the tier-1 smoke).
FLIGHT_AXES = {
    "flight-campaign": dict(
        cov_words=8, metrics=True, latency=LatencySpec(ops=8, phases=2),
        flight=True,
    ),
}

# The device-verification entry (ISSUE 14): the history-hunt program
# shape — sim + the check.device detector kernels in ONE traced
# program, proved through the shard_map boundary
# (``check_matrix(models, CHECK_AXES, entry="sharded_run")``; the
# tier-1 smoke uses entry="run"). Obligations: the detectors read the
# derived history columns and write ONLY the new ``check_ok`` verdict
# output (taint set unchanged — no derived value reaches a core
# column through the detector arithmetic), and no host-callback
# primitive joins the program (the detectors are a lowering of the
# numpy checkers, not a host bridge).
CHECK_AXES = {
    "device-check": dict(cov_words=8, metrics=True, check=True),
}

def model_matrix() -> list:
    """(name, workload, config) triples for the six recorded models.

    Each model module owns its tracing entry points
    (``models/<name>.py lint_entries()``): every model appears with
    history recording on AND off, and raftlog additionally with the
    disk discipline on — the {metrics, timeline, coverage, history,
    disk-discipline} axes the acceptance matrix sweeps (build flags
    come from BUILD_AXES).
    """
    from ..models import kvchaos, leasekv, paxos, raft, raftlog, shardkv

    entries = []
    for mod in (raft, kvchaos, paxos, raftlog, leasekv, shardkv):
        for tag, wl, cfg_kw in mod.lint_entries():
            entries.append((tag, wl, EngineConfig(**cfg_kw)))
    return entries


def check_matrix(
    models=None,
    axes=None,
    *,
    entry: str = "step",
    layout: str = "scatter",
    layouts: tuple | None = None,
    log=None,
) -> list:
    """Run the proof over a model x build-flag matrix; returns reports.

    Defaults to the full certified matrix (tools/lint_soak.py scale);
    tests pass a slice for the tier-1 smoke. ``layouts`` sweeps
    (layout, time32[, placement]) lowering tuples per cell
    (``LAYOUT_AXES`` is the full set; two-tuples mean the auto
    placement, four-tuples add the pool_index axis); the single
    ``layout`` argument remains the one-lowering form. A model whose
    (workload, config) is not time32-eligible is skipped for time32
    pairs, and one whose pool has no tile divisor is skipped for
    pool-index rows, rather than failing the matrix.
    """
    from ..engine.core import pool_index_eligible, time32_eligible

    if models is not None and not models:
        # an explicitly empty slice is a caller bug (e.g. a tag filter
        # that matched nothing) — falling back to the full matrix here
        # would silently multiply the gate's cost instead
        raise ValueError("check_matrix: models is empty")
    if layouts is None:
        layouts = ((layout, False),)
    reports = []
    for name, wl, cfg in (models if models is not None else model_matrix()):
        for lay, t32, *rest in layouts:
            place = rest[0] if rest else None
            pidx = rest[1] if len(rest) > 1 else None
            if t32 and not time32_eligible(wl, cfg):
                continue
            if pidx and not pool_index_eligible(cfg):
                continue
            for axis, flags in (axes or BUILD_AXES).items():
                rep = check_noninterference(
                    wl, cfg, entry=entry, layout=lay, time32=t32,
                    placement=place, pool_index=pidx, **flags,
                )
                rep.flags["axis"] = axis
                if log is not None:
                    log(rep.summary())
                reports.append(rep)
    return reports
