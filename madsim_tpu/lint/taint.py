"""Jaxpr taint propagation — the non-interference core.

A jaxpr is a first-order dataflow program, which makes information-flow
analysis on it almost embarrassingly direct: label some inputs with
taint sources, and for every equation the outputs inherit the union of
the input labels. The only real work is the higher-order primitives:

* ``pjit`` / call-like primitives — recurse into the sub-jaxpr with the
  call-site labels mapped onto its invars.
* ``shard_map`` — the multi-chip call boundary (madsim_tpu.parallel):
  per-shard invars map 1:1 onto the call-site operands (the mesh and
  sharding specs are metadata, not data), so labels cross the boundary
  positionally; the collectives a mapped body may run (``psum`` & co)
  are first-order equations inside and propagate like any other. This
  is what lets the proof walk the sharded-campaign programs
  (explore.run_device) instead of conservatively smearing every label
  across the whole generation.
* ``cond`` (which ``lax.switch`` lowers to) — outputs join over every
  branch, PLUS the predicate's labels: a tainted branch index is an
  implicit flow (which value you got depends on tainted data), and a
  sound checker must treat it as a leak.
* ``scan`` / ``while`` — the loop carry is a cycle, so labels iterate
  to a fixpoint (monotone unions over a finite label set: terminates).
  A tainted ``while`` condition taints every carry for the same
  implicit-flow reason (the iteration count observes tainted data).

Everything here is *conservative over data+control flow*: no false
negatives by construction (an unknown primitive with a sub-jaxpr it
cannot map falls back to all-inputs-taint-all-outputs). False positives
are possible in principle — e.g. ``x * 0`` keeps ``x``'s labels — and
that is exactly the property the engine's discipline needs: a
value-identical-but-data-dependent edge from derived state into the
trajectory is a latent leak the runtime bit-identity tests can NEVER
see, and this analysis is the only line of defense that flags it.
"""

from __future__ import annotations

import dataclasses

from jax import core as jax_core

__all__ = ["TaintEqn", "TaintResult", "analyze_jaxpr"]


@dataclasses.dataclass(frozen=True)
class TaintEqn:
    """One tainted equation — a row of the isolation frontier.

    ``path`` locates the equation (``eqns[12]``, or nested:
    ``eqns[7].cond.branch0.eqns[3]``); ``sources`` are the taint labels
    flowing in; ``mixes_clean`` marks equations that also consume at
    least one untainted, non-literal value — the places where derived
    state meets core data, i.e. exactly where a leak would originate if
    the equation's results ever reached a core output.
    """

    path: str
    prim: str
    sources: tuple
    mixes_clean: bool

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "prim": self.prim,
            "sources": list(self.sources),
            "mixes_clean": self.mixes_clean,
        }


@dataclasses.dataclass
class TaintResult:
    """Outcome of one :func:`analyze_jaxpr` pass."""

    out_taint: list  # per-outvar frozenset of source labels
    frontier: list  # list[TaintEqn], program order (tainted eqns only)
    # top-level var -> taint labels and var -> defining eqn index, kept
    # for leak-chain extraction (sub-jaxpr internals are summarized by
    # their enclosing equation)
    _env: dict
    _defs: dict
    _jaxpr: object  # the analyzed (closed) jaxpr

    def leak_chain(self, out_index: int, max_len: int = 32) -> list:
        """Backward slice from output ``out_index`` to a tainted input.

        Returns equation descriptors (dicts) from the source end to the
        output end — the "offending equation" trail a leak report
        prints. Chains through sub-jaxprs stop at the enclosing
        equation (its ``path`` names the nested location).
        """
        jaxpr = _unclose(self._jaxpr)
        v = jaxpr.outvars[out_index]
        chain = []
        seen = set()
        while (
            isinstance(v, jax_core.Var)
            and v in self._defs
            and v not in seen
            and len(chain) < max_len
        ):
            seen.add(v)
            idx = self._defs[v]
            eqn = jaxpr.eqns[idx]
            in_ts = [_read(self._env, u) for u in eqn.invars]
            chain.append(
                {
                    "path": f"eqns[{idx}]",
                    "prim": eqn.primitive.name,
                    "sources": sorted(frozenset().union(*in_ts) if in_ts else ()),
                }
            )
            nxt = None
            for u, t in zip(eqn.invars, in_ts):
                if t and isinstance(u, jax_core.Var):
                    nxt = u
                    break
            if nxt is None:
                break
            v = nxt
        chain.reverse()
        return chain


def _unclose(j):
    return j.jaxpr if isinstance(j, jax_core.ClosedJaxpr) else j


def _read(env, v):
    if isinstance(v, jax_core.Literal):
        return frozenset()
    return env.get(v, frozenset())


def _sub_jaxprs(params):
    """Every (key, ClosedJaxpr/Jaxpr) pair hiding in an eqn's params."""
    out = []
    for key, val in params.items():
        if isinstance(val, (jax_core.Jaxpr, jax_core.ClosedJaxpr)):
            out.append((key, val))
        elif isinstance(val, (tuple, list)):
            for i, item in enumerate(val):
                if isinstance(item, (jax_core.Jaxpr, jax_core.ClosedJaxpr)):
                    out.append((f"{key}[{i}]", item))
    return out


def _propagate(jaxpr, in_taints, path, rows, defs=None, env_out=None):
    """Forward-propagate taint through one (open) jaxpr.

    ``rows`` collects TaintEqn frontier entries (pass a throwaway list
    to analyze silently — the fixpoint loops do, then re-run once
    converged so each equation reports exactly once). ``defs``/
    ``env_out``: optional dicts filled with var->eqn-index and
    var->labels for the chain extractor (top level only).
    """
    env = {}
    for v, t in zip(jaxpr.invars, in_taints):
        env[v] = frozenset(t)
    for v in jaxpr.constvars:
        env[v] = frozenset()

    for idx, eqn in enumerate(jaxpr.eqns):
        in_ts = [_read(env, v) for v in eqn.invars]
        union = frozenset().union(*in_ts) if in_ts else frozenset()
        name = eqn.primitive.name
        epath = f"{path}eqns[{idx}]"
        n_out = len(eqn.outvars)
        out_ts = None

        if name == "cond":
            # lax.cond/switch: invars[0] is the branch index. Implicit
            # flow: a tainted index taints every output.
            branches = eqn.params["branches"]
            pred_t = in_ts[0]
            op_ts = in_ts[1:]
            per_branch = []
            for bi, br in enumerate(branches):
                per_branch.append(
                    _call_sub(br, op_ts, f"{epath}.branch{bi}.", rows)
                )
            out_ts = [
                frozenset(pred_t).union(*[b[i] for b in per_branch])
                for i in range(n_out)
            ]
        elif name == "while":
            cn = eqn.params["cond_nconsts"]
            bn = eqn.params["body_nconsts"]
            cond_j = eqn.params["cond_jaxpr"]
            body_j = eqn.params["body_jaxpr"]
            cconst = in_ts[:cn]
            bconst = in_ts[cn : cn + bn]
            carry = list(in_ts[cn + bn :])
            scratch = []
            while True:
                pred_t = _call_sub(
                    cond_j, cconst + carry, f"{epath}.cond.", scratch
                )[0]
                body_out = _call_sub(
                    body_j, bconst + carry, f"{epath}.body.", scratch
                )
                # implicit flow: the iteration count observes the
                # condition, so its labels reach every carried value
                new_carry = [
                    c | o | pred_t for c, o in zip(carry, body_out)
                ]
                if new_carry == carry:
                    break
                carry = new_carry
            # converged: re-run once so the frontier reports each body
            # equation exactly once, at the fixpoint labels
            _call_sub(cond_j, cconst + carry, f"{epath}.cond.", rows)
            _call_sub(body_j, bconst + carry, f"{epath}.body.", rows)
            out_ts = carry
        elif name == "scan":
            nc = eqn.params["num_consts"]
            ncar = eqn.params["num_carry"]
            body = eqn.params["jaxpr"]
            consts = in_ts[:nc]
            carry = list(in_ts[nc : nc + ncar])
            xs = in_ts[nc + ncar :]
            ys = [frozenset() for _ in range(n_out - ncar)]
            scratch = []
            while True:
                outs = _call_sub(
                    body, consts + carry + xs, f"{epath}.body.", scratch
                )
                new_carry = [c | o for c, o in zip(carry, outs[:ncar])]
                ys = [y | o for y, o in zip(ys, outs[ncar:])]
                if new_carry == carry:
                    break
                carry = new_carry
            final = _call_sub(
                body, consts + carry + xs, f"{epath}.body.", rows
            )
            ys = [y | o for y, o in zip(ys, final[ncar:])]
            out_ts = carry + ys
        elif name == "shard_map":
            # the multi-chip call boundary: params["jaxpr"] is the
            # per-shard body whose invars line up 1:1 with the eqn's
            # operands (mesh/in_names/out_names are metadata). Explicit
            # rather than via the generic single-sub-jaxpr path so a
            # future param-shape change (a renamed param, a changed
            # arity) degrades to the conservative fallback instead of
            # silently mis-mapping labels.
            sub = eqn.params.get("jaxpr")
            if sub is not None:
                sub = _unclose(sub)
                if len(sub.invars) == len(in_ts):
                    out_ts = _call_sub(
                        sub, in_ts, f"{epath}.shard_map.", rows
                    )
                    if len(out_ts) != n_out:
                        out_ts = None
            if out_ts is None:
                out_ts = [union] * n_out
        else:
            subs = _sub_jaxprs(eqn.params)
            if len(subs) == 1:
                # pjit / closed_call / remat / custom_* — a plain call
                # whose sub-jaxpr invars line up with the eqn invars
                key, sub = subs[0]
                n_sub_in = len(_unclose(sub).invars)
                if n_sub_in == len(in_ts):
                    out_ts = _call_sub(sub, in_ts, f"{epath}.{name}.", rows)
                    if len(out_ts) > n_out:
                        # custom_vjp-style extras: keep the leading ones
                        out_ts = out_ts[:n_out]
                    elif len(out_ts) < n_out:
                        out_ts = None  # shape surprise: fall through
            if out_ts is None:
                # first-order primitive — or a higher-order shape this
                # walker doesn't know: all inputs taint all outputs
                # (conservative, never unsound)
                out_ts = [union] * n_out

        if union:
            rows.append(
                TaintEqn(
                    path=epath,
                    prim=name,
                    sources=tuple(sorted(union)),
                    mixes_clean=any(
                        (not t) and isinstance(v, jax_core.Var)
                        for v, t in zip(eqn.invars, in_ts)
                    ),
                )
            )
        for v, t in zip(eqn.outvars, out_ts):
            env[v] = t
            if defs is not None and isinstance(v, jax_core.Var):
                defs[v] = idx

    if env_out is not None:
        env_out.update(env)
    return [_read(env, v) for v in jaxpr.outvars]


def _call_sub(sub, in_ts, path, rows):
    jaxpr = _unclose(sub)
    return _propagate(jaxpr, in_ts, path, rows)


def analyze_jaxpr(closed, in_taints) -> TaintResult:
    """Propagate ``in_taints`` (one label set per invar) through a
    (closed) jaxpr and return per-outvar label sets plus the tainted-
    equation frontier."""
    jaxpr = _unclose(closed)
    if len(in_taints) != len(jaxpr.invars):
        raise ValueError(
            f"{len(in_taints)} taint sets for {len(jaxpr.invars)} invars"
        )
    rows: list = []
    defs: dict = {}
    env: dict = {}
    out = _propagate(
        jaxpr,
        [frozenset(t) for t in in_taints],
        "",
        rows,
        defs=defs,
        env_out=env,
    )
    return TaintResult(
        out_taint=out, frontier=rows, _env=env, _defs=defs, _jaxpr=closed
    )
