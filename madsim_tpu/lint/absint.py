"""Interval abstract interpretation over jaxprs — range proofs.

Two of this repo's confirmed bug classes are invisible to BOTH the
bit-identity tests and the taint walker (lint.taint): silent integer
wraparound on the time32 layout (a decayed +inf sentinel, an overflowed
reduction) and threefry purpose-lane collisions (two draw sites sharing
a ``(purpose, counter)`` lane and silently correlating "independent"
streams). Both are *value-range* properties — exactly what a forward
interval domain proves. This module walks the same first-order dataflow
programs the taint proof walks (scan/while fixpoint with widening, cond
join, pjit recursion, conservative top for unknown primitives), but
carries per-var integer ranges instead of labels, seeded from the
SimState column contracts declared in ``engine.column_contracts``.

Two provers ride the walk:

* **Overflow certification** (:func:`check_ranges`) — every ``add``/
  ``sub``/``mul`` (and shift-left/scatter-add/cumsum, the same
  operation in other clothes) whose operands carry a *time* or
  *counter* tag must produce a mathematical result interval that fits
  the result dtype. The signed/unsigned rule mirrors C's: unsigned
  arithmetic is modular by definition (the threefry rounds, the trace
  hash, the coverage folds, packed meta words — all deliberately
  uint32/uint64), so only signed results are overflow surfaces.
  Findings cite the offending equation chain in SimState field
  vocabulary (``time:ev_time``, ``counter:hist_count``), the way
  ``noninterference`` leak reports do.

* **Lane disjointness** — under :func:`engine.rng.lane_site_tracing`
  every threefry application appears as one named call-site equation;
  the walker records each site's resolved ``(x0, x1)`` operand ranges
  (the counter and purpose words — exact vectors when the purposes are
  the engine's static lane stack) and requires (a) every purpose to
  lie inside a registered :data:`engine.rng.PURPOSE_LANES` block,
  (b) no site to draw one purpose twice, and (c) every pair of
  non-branch-exclusive sites with overlapping counters to have
  pairwise-disjoint purposes. Sites in sibling ``cond`` branches are
  mutually exclusive by construction and exempt from (c).

Soundness posture (stated, not hidden):

* **Contracts are assumptions.** The column contracts are the declared
  runtime invariants (eligibility bounds, insertion clamps, capacity
  saturation, the halt discipline); loop carries that map to contract
  columns are narrowed back into their contract at each fixpoint
  iteration — the assume half of an assume-guarantee proof. The
  certified statement is therefore: *within the declared horizon, and
  for states satisfying the column contracts, no tracked arithmetic
  can wrap and no two live lanes can alias.* The guarantee half is the
  engine's runtime backstops plus the bit-identity pins.
* **The masked-sum pick idiom is trusted.** ``sum(where(m, x, 0))`` is
  this engine's "pick one element" (one-hot match matrices, rank
  placement); a non-relational domain cannot prove the one-hot-ness,
  so with ``onehot_sums=True`` (default) the sum is modeled as the
  hull of {0, x} instead of ``n*x``. Every such site in the engine is
  one-hot by cumsum-rank construction.
* **Relational facts need pragmas.** A handful of sites wrap by
  design (the time32 stale-slot rebases); they carry per-site
  ``# lint: allow(absint-overflow)`` pragmas, and the allowlist is
  checked — a pragma no traced program exercises is reported stale
  (:func:`stale_absint_pragmas`), the ``unused-allow`` rule extended
  to this analysis.
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path

import numpy as np

import jax
from jax import core as jax_core

from ..engine.core import (
    ABSINT_HORIZON_NS,
    EngineConfig,
    LatencySpec,
    Workload,
    column_contracts,
    make_init,
    make_run,
    make_step,
    pool_index_eligible,
    time32_eligible,
)
from ..engine import rng as _rng
from .rules import DEFAULT_PATHS, _pragma_entries

__all__ = [
    "AbsintReport",
    "LaneSite",
    "OVERFLOW_RULE",
    "LANE_RULE",
    "ABSINT_AXES",
    "absint_matrix",
    "absint_model_matrix",
    "absint_pragma_inventory",
    "analyze_intervals",
    "check_lane_sites",
    "check_ranges",
    "plant_lane_collision",
    "plant_time32_sentinel_decay",
    "run_mutant_controls",
    "stale_absint_pragmas",
]

OVERFLOW_RULE = "absint-overflow"
LANE_RULE = "absint-lane"
_TRACKED = ("time:", "counter:")
_REPO_ROOT = str(Path(__file__).resolve().parents[2])
_CONST_MAX = 4096  # largest array kept as an exact constant
_WIDEN_AFTER = 2  # fixpoint iterations before widening unstable bounds
_MAX_ITERS = 8


# ---------------------------------------------------------------------------
# The abstract domain: integer intervals + contract-family tags.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AVal:
    """One var's abstract value: ``[lo, hi]`` (None = unbounded, the
    float case), the contract-family tags that flowed into it, an
    optional exactly-known constant, and the narrowing contract a
    loop carry re-assumes at each iteration."""

    lo: object = None
    hi: object = None
    tags: frozenset = frozenset()
    const: object = None
    contract: tuple = None

    def key(self):
        return (self.lo, self.hi, self.tags)


def _dtype_range(dt):
    dt = np.dtype(dt)
    if dt == np.bool_:
        return 0, 1
    if np.issubdtype(dt, np.integer):
        info = np.iinfo(dt)
        return int(info.min), int(info.max)
    return None, None


def _top_for(var, tags=frozenset()):
    lo, hi = _dtype_range(var.aval.dtype)
    return AVal(lo, hi, tags)


def _from_concrete(val):
    arr = np.asarray(val)
    if arr.dtype == np.bool_:
        lo, hi = (int(arr.min()), int(arr.max())) if arr.size else (0, 0)
        return AVal(lo, hi, frozenset(), arr if arr.size <= _CONST_MAX else None)
    if np.issubdtype(arr.dtype, np.integer):
        if arr.size == 0:
            return AVal(0, 0)
        return AVal(
            int(arr.min()), int(arr.max()), frozenset(),
            arr if arr.size <= _CONST_MAX else None,
        )
    return AVal(None, None)


def _join(a: AVal, b: AVal) -> AVal:
    lo = None if a.lo is None or b.lo is None else min(a.lo, b.lo)
    hi = None if a.hi is None or b.hi is None else max(a.hi, b.hi)
    const = a.const if (
        a.const is not None and b.const is not None
        and np.array_equal(a.const, b.const)
    ) else None
    return AVal(lo, hi, a.tags | b.tags, const, a.contract)


def _narrow(a: AVal, contract) -> AVal:
    """Assume-narrow a loop carry back into its declared contract."""
    if contract is None:
        return a
    clo, chi = contract
    lo = clo if a.lo is None else max(a.lo, clo)
    hi = chi if a.hi is None else min(a.hi, chi)
    if hi < lo:  # contradiction: keep the contract (the assumption)
        lo, hi = clo, chi
    return dataclasses.replace(a, lo=lo, hi=hi)


def _tracked(tags) -> bool:
    return any(t.startswith(_TRACKED) for t in tags)


def _corners(a: AVal, b: AVal, op):
    if None in (a.lo, a.hi, b.lo, b.hi):
        return None, None
    cs = [op(a.lo, b.lo), op(a.lo, b.hi), op(a.hi, b.lo), op(a.hi, b.hi)]
    return min(cs), max(cs)


# ---------------------------------------------------------------------------
# Lane sites.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LaneSite:
    """One threefry application in a traced program."""

    path: str
    src: tuple  # (repo-relative file, line) or (None, 0)
    purposes: object  # exact np.ndarray of purpose words, or None
    p_lo: int
    p_hi: int
    x0_lo: int
    x0_hi: int
    x0_tags: tuple

    def describe(self) -> str:
        if self.purposes is not None:
            vals = sorted(int(v) for v in np.unique(self.purposes))
            shown = ", ".join(f"{v:#x}" for v in vals[:8])
            if len(vals) > 8:
                shown += f", ... ({len(vals)} lanes)"
            p = f"purposes {{{shown}}}"
        else:
            p = f"purposes [{self.p_lo:#x}, {self.p_hi:#x}]"
        where = f"{self.src[0]}:{self.src[1]}" if self.src[0] else self.path
        return f"{where} {p}"

    def purpose_set(self):
        if self.purposes is None:
            return None
        return {int(v) for v in np.unique(self.purposes)}


def _branch_exclusive(pa: str, pb: str) -> bool:
    """True when the two equation paths live in SIBLING branches of one
    cond/switch — at most one executes per dispatch, so their draws
    can never coexist at the same counter."""
    for x, y in zip(pa.split("."), pb.split(".")):
        if x != y:
            return x.startswith("branch") and y.startswith("branch")
    return False


# ---------------------------------------------------------------------------
# The walker.
# ---------------------------------------------------------------------------


def _unclose(j):
    return j.jaxpr if isinstance(j, jax_core.ClosedJaxpr) else j


def _sub_jaxprs(params):
    out = []
    for key, val in params.items():
        if isinstance(val, (jax_core.Jaxpr, jax_core.ClosedJaxpr)):
            out.append((key, val))
        elif isinstance(val, (tuple, list)):
            for i, item in enumerate(val):
                if isinstance(item, (jax_core.Jaxpr, jax_core.ClosedJaxpr)):
                    out.append((f"{key}[{i}]", item))
    return out


def _is_drop(v) -> bool:
    return type(v).__name__ == "DropVar"


class _Walker:
    """One forward interval pass over a (closed) jaxpr."""

    def __init__(self, closed, in_vals, *, onehot_sums=True,
                 root=_REPO_ROOT):
        self.onehot_sums = onehot_sums
        self.root = root
        self.findings: list = []
        self.sites: list = []
        self.checked_ops = 0
        self.n_eqns = 0
        self.out = self._walk(closed, list(in_vals), "", report=True)

    # -- source attribution ---------------------------------------------
    def _src(self, eqn, skip_rng=False):
        tb = getattr(eqn.source_info, "traceback", None)
        frames = getattr(tb, "frames", None) if tb is not None else None
        rng_file = os.path.join("engine", "rng.py")
        for fr in frames or ():
            fn = getattr(fr, "file_name", "")
            if fn.startswith(self.root):
                if skip_rng and fn.endswith(rng_file):
                    # lane sites cite the DRAW SITE (Draw's caller),
                    # not the cipher plumbing inside rng.py
                    continue
                return os.path.relpath(fn, self.root), int(fr.line_num)
        return None, 0

    # -- the walk -------------------------------------------------------
    def _walk(self, closed, in_vals, path, report):
        jaxpr = _unclose(closed)
        if len(in_vals) != len(jaxpr.invars):
            raise ValueError(
                f"{len(in_vals)} abstract values for "
                f"{len(jaxpr.invars)} invars at {path or '<top>'}"
            )
        env, defs = {}, {}
        for v, a in zip(jaxpr.invars, in_vals):
            env[v] = a
        consts = getattr(closed, "consts", None) or []
        for v, c in zip(jaxpr.constvars, consts):
            env[v] = _from_concrete(c)
        for v in jaxpr.constvars[len(consts):]:
            env[v] = _top_for(v)
        level = (jaxpr, env, defs, path)

        for idx, eqn in enumerate(jaxpr.eqns):
            if report:
                self.n_eqns += 1
            ivals = [self._read(env, v) for v in eqn.invars]
            name = eqn.primitive.name
            epath = f"{path}eqns[{idx}]"
            outs = None

            if name == "cond":
                branches = eqn.params["branches"]
                op_vals = ivals[1:]
                per = [
                    self._walk(
                        br, list(op_vals), f"{epath}.branch{bi}.", report
                    )
                    for bi, br in enumerate(branches)
                ]
                outs = [
                    _join_many([b[i] for b in per])
                    for i in range(len(eqn.outvars))
                ]
            elif name == "scan":
                outs = self._scan(eqn, ivals, epath, report)
            elif name == "while":
                outs = self._while(eqn, ivals, epath, report)
            elif name == "pjit" and eqn.params.get("name") == _rng.LANE_SITE_NAME:
                outs = self._lane_site(eqn, ivals, epath, report)
            elif name == "shard_map":
                sub = eqn.params.get("jaxpr")
                if sub is not None:
                    sub_o = _unclose(sub)
                    if len(sub_o.invars) == len(ivals):
                        outs = self._walk(
                            sub, ivals, f"{epath}.shard_map.", report
                        )
                        if len(outs) != len(eqn.outvars):
                            outs = None
                if outs is None:
                    outs = [
                        _top_for(v, _union_tags(ivals)) for v in eqn.outvars
                    ]
            else:
                subs = _sub_jaxprs(eqn.params)
                if name in _CALL_PRIMS and len(subs) == 1:
                    key, sub = subs[0]
                    if len(_unclose(sub).invars) == len(ivals):
                        outs = self._walk(
                            sub, ivals, f"{epath}.{name}.", report
                        )
                        if len(outs) > len(eqn.outvars):
                            outs = outs[: len(eqn.outvars)]
                        elif len(outs) < len(eqn.outvars):
                            outs = None
                if outs is None:
                    outs = self._transfer(
                        eqn, ivals, epath, report, level
                    )

            for v, a in zip(eqn.outvars, outs):
                if _is_drop(v):
                    continue
                env[v] = a
                defs[v] = idx

        return [self._read(env, v) for v in jaxpr.outvars]

    def _read(self, env, v):
        if isinstance(v, jax_core.Literal):
            return _from_concrete(v.val)
        return env.get(v, AVal(None, None))

    # -- loops ----------------------------------------------------------
    def _scan(self, eqn, ivals, epath, report):
        nc = eqn.params["num_consts"]
        ncar = eqn.params["num_carry"]
        body = eqn.params["jaxpr"]
        consts = ivals[:nc]
        carry0 = ivals[nc : nc + ncar]
        # xs enter the body one leading-axis element at a time: the
        # interval is unchanged, the exact constant is not (shape)
        xs = [dataclasses.replace(a, const=None) for a in ivals[nc + ncar :]]
        carry = self._fixpoint(
            body, consts, carry0, xs, f"{epath}.body."
        )
        outs = self._walk(
            body, consts + carry + xs, f"{epath}.body.", report
        )
        final_carry = [_join(c0, o) for c0, o in zip(carry0, outs[:ncar])]
        ys = [dataclasses.replace(a, const=None) for a in outs[ncar:]]
        return final_carry + ys

    def _while(self, eqn, ivals, epath, report):
        cn = eqn.params["cond_nconsts"]
        bn = eqn.params["body_nconsts"]
        cond_j = eqn.params["cond_jaxpr"]
        body_j = eqn.params["body_jaxpr"]
        cconst = ivals[:cn]
        bconst = ivals[cn : cn + bn]
        carry0 = ivals[cn + bn :]
        carry = self._fixpoint(
            body_j, bconst, carry0, [], f"{epath}.body."
        )
        self._walk(cond_j, cconst + carry, f"{epath}.cond.", report)
        outs = self._walk(
            body_j, bconst + carry, f"{epath}.body.", report
        )
        return [_join(c0, o) for c0, o in zip(carry0, outs)]

    def _fixpoint(self, body, consts, carry0, xs, path):
        body_o = _unclose(body)
        n = len(carry0)
        drs = [
            _dtype_range(v.aval.dtype)
            for v in body_o.invars[len(consts) : len(consts) + n]
        ]
        carry = list(carry0)
        for it in range(_MAX_ITERS):
            outs = self._walk(body, consts + carry + xs, path, report=False)
            stable = True
            new = []
            for c, o, dr in zip(carry, outs[:n], drs):
                j = _narrow(_join(c, o), c.contract)
                if it >= _WIDEN_AFTER:
                    j = _widen(j, c, dr)
                if j.key() != c.key():
                    stable = False
                new.append(j)
            carry = new
            if stable:
                break
        return carry

    # -- lane sites ------------------------------------------------------
    def _lane_site(self, eqn, ivals, epath, report):
        x0, x1 = ivals[2], ivals[3]
        if report:
            purposes = None
            if x1.const is not None:
                purposes = np.asarray(x1.const).astype(np.uint64)
            self.sites.append(
                LaneSite(
                    path=epath,
                    src=self._src(eqn, skip_rng=True),
                    purposes=purposes,
                    p_lo=0 if x1.lo is None else int(x1.lo),
                    p_hi=(1 << 32) - 1 if x1.hi is None else int(x1.hi),
                    x0_lo=0 if x0.lo is None else int(x0.lo),
                    x0_hi=(1 << 32) - 1 if x0.hi is None else int(x0.hi),
                    x0_tags=tuple(sorted(x0.tags)),
                )
            )
        # the cipher is modular by definition: outputs are uniform
        # uint32 words carrying no range information and no tags
        return [AVal(0, (1 << 32) - 1) for _ in eqn.outvars]

    # -- findings --------------------------------------------------------
    def _flag(self, eqn, epath, level, math_lo, math_hi, ivals, report):
        """Record a potential-wrap finding for a tracked signed op."""
        if not report:
            return
        dt = np.dtype(eqn.outvars[0].aval.dtype)
        tags = frozenset().union(*[a.tags for a in ivals]) if ivals else frozenset()
        if not (np.issubdtype(dt, np.signedinteger) and _tracked(tags)):
            return
        lo, hi = _dtype_range(dt)
        src = self._src(eqn)
        self.findings.append(
            {
                "rule": OVERFLOW_RULE,
                "path": epath,
                "prim": eqn.primitive.name,
                "dtype": dt.name,
                "math": [math_lo, math_hi],
                "dtype_range": [lo, hi],
                "sources": sorted(t for t in tags if t.startswith(_TRACKED)),
                "file": src[0],
                "line": src[1],
                "chain": self._chain(level, eqn),
            }
        )

    def _chain(self, level, eqn, max_len=12):
        jaxpr, env, defs, path = level
        chain = []
        cur = eqn
        seen = set()
        for _ in range(max_len):
            ivals = [self._read(env, v) for v in cur.invars]
            tags = frozenset().union(*[a.tags for a in ivals]) if ivals else frozenset()
            chain.append(
                {
                    "path": f"{path}eqns[{jaxpr.eqns.index(cur)}]"
                    if cur in jaxpr.eqns else path,
                    "prim": cur.primitive.name,
                    "sources": sorted(t for t in tags if t.startswith(_TRACKED)),
                }
            )
            nxt = None
            for v, a in zip(cur.invars, ivals):
                if (
                    isinstance(v, jax_core.Var)
                    and _tracked(a.tags)
                    and v not in seen
                ):
                    nxt = v
                    break
            if nxt is None or nxt not in defs:
                break
            seen.add(nxt)
            cur = jaxpr.eqns[defs[nxt]]
        chain.reverse()
        return chain

    # -- first-order transfer functions ---------------------------------
    def _transfer(self, eqn, ivals, epath, report, level):
        name = eqn.primitive.name
        outv = eqn.outvars
        tags = _union_tags(ivals)

        def top_all():
            return [_top_for(v, tags) for v in outv]

        def one(aval: AVal):
            return [dataclasses.replace(aval, tags=aval.tags | tags)]

        def checked(mlo, mhi, const=None):
            """An arithmetic result: exact when it fits the dtype,
            wrapped (and flagged when tracked+signed) when it can't."""
            dr = _dtype_range(outv[0].aval.dtype)
            if dr[0] is None:
                return [AVal(None, None, tags)]
            if mlo is None or mhi is None:
                self._flag(eqn, epath, level, mlo, mhi, ivals, report)
                return [AVal(dr[0], dr[1], tags)]
            self.checked_ops += 1 if report and _tracked(tags) else 0
            if dr[0] <= mlo and mhi <= dr[1]:
                return [AVal(int(mlo), int(mhi), tags, const)]
            self._flag(eqn, epath, level, int(mlo), int(mhi), ivals, report)
            return [AVal(dr[0], dr[1], tags)]

        if name in ("add", "sub", "mul"):
            a, b = ivals
            op = {
                "add": lambda x, y: x + y,
                "sub": lambda x, y: x - y,
                "mul": lambda x, y: x * y,
            }[name]
            mlo, mhi = _corners(a, b, op)
            const = None
            if (
                a.const is not None and b.const is not None
                and np.asarray(a.const).size == 1
                and np.asarray(b.const).size == 1
            ):
                const = op(int(np.asarray(a.const).ravel()[0]),
                           int(np.asarray(b.const).ravel()[0]))
            return checked(mlo, mhi, const)
        if name == "neg":
            a = ivals[0]
            if a.lo is None or a.hi is None:
                return top_all()
            return checked(-a.hi, -a.lo)
        if name == "integer_pow":
            a = ivals[0]
            y = int(eqn.params["y"])
            if a.lo is None or a.hi is None or y < 0 or y > 8:
                return top_all()
            cs = [a.lo ** y, a.hi ** y] + ([0] if a.lo < 0 < a.hi else [])
            return checked(min(cs), max(cs))
        if name == "shift_left":
            a, s = ivals
            if None in (a.lo, a.hi, s.lo, s.hi) or s.lo < 0 or s.hi > 64:
                return top_all()
            m = AVal(1 << s.lo, 1 << s.hi)
            mlo, mhi = _corners(a, m, lambda x, y: x * y)
            return checked(mlo, mhi)
        if name == "cumsum":
            a = ivals[0]
            n = int(np.prod(outv[0].aval.shape)) or 1
            if a.lo is None or a.hi is None:
                return top_all()
            return checked(min(a.lo, a.lo * n), max(a.hi, a.hi * n))
        if name == "reduce_sum":
            return self._reduce_sum(eqn, ivals, epath, report, level, checked)
        if name == "scatter-add":
            op, _idx, upd = ivals
            n = int(np.prod(eqn.invars[2].aval.shape)) or 1
            if None in (op.lo, op.hi, upd.lo, upd.hi):
                return top_all()
            return checked(
                op.lo + min(0, upd.lo) * n, op.hi + max(0, upd.hi) * n
            )
        if name in ("scatter", "scatter-min", "scatter-max",
                    "dynamic_update_slice"):
            # index operands pick WHERE the update lands, not its
            # magnitude: value range = hull(operand, updates) only
            op = ivals[0]
            upd = ivals[2] if name.startswith("scatter") else ivals[1]
            return [
                AVal(
                    *_hull2(op, upd),
                    op.tags | upd.tags,
                )
            ]
        if name in ("max", "min"):
            a, b = ivals
            f = max if name == "max" else min
            if None in (a.lo, a.hi, b.lo, b.hi):
                return top_all()
            return one(AVal(f(a.lo, b.lo), f(a.hi, b.hi), tags))
        if name == "clamp":
            # clamp(a, x, c) = min(max(x, a), c), monotone in every
            # operand: the sound hull takes max-then-min per corner.
            # (A variable LOWER bound can RAISE x — ignoring a.hi here
            # would under-approximate and silently certify a wrap.)
            a, x, c = ivals

            def _mx(p, q):
                return None if p is None or q is None else max(p, q)

            def _mn(p, q):
                return None if p is None or q is None else min(p, q)

            return one(
                AVal(_mn(_mx(x.lo, a.lo), c.lo), _mn(_mx(x.hi, a.hi), c.hi),
                     tags)
            )
        if name == "select_n":
            cases = ivals[1:]
            out = cases[0]
            for c in cases[1:]:
                out = _join(out, c)
            # the predicate steers which value, not its range: implicit
            # flows are the taint walker's concern, not the interval's
            return [dataclasses.replace(out, contract=None)]
        if name == "convert_element_type":
            a = ivals[0]
            dr = _dtype_range(outv[0].aval.dtype)
            if dr[0] is None:
                return [AVal(None, None, tags)]
            if a.lo is not None and a.hi is not None and (
                dr[0] <= a.lo and a.hi <= dr[1]
            ):
                const = a.const
                return [AVal(a.lo, a.hi, tags, const)]
            return [AVal(dr[0], dr[1], tags)]
        if name in ("broadcast_in_dim", "reshape", "squeeze", "transpose",
                    "rev", "copy", "expand_dims", "stop_gradient",
                    "reduce_precision", "device_put",
                    "sharding_constraint"):
            a = ivals[0]
            const = _reshape_const(name, eqn, a.const)
            return [AVal(a.lo, a.hi, a.tags | tags, const)]
        if name == "slice":
            a = ivals[0]
            const = None
            if a.const is not None:
                try:
                    sl = tuple(
                        slice(b, e, s)
                        for b, e, s in zip(
                            eqn.params["start_indices"],
                            eqn.params["limit_indices"],
                            eqn.params["strides"]
                            or (1,) * len(eqn.params["start_indices"]),
                        )
                    )
                    const = np.asarray(a.const)[sl]
                except Exception:
                    const = None
            return [AVal(a.lo, a.hi, a.tags | tags, const)]
        if name == "concatenate":
            out = ivals[0]
            for a in ivals[1:]:
                out = _join(out, a)
            const = None
            if all(a.const is not None for a in ivals):
                try:
                    const = np.concatenate(
                        [np.asarray(a.const) for a in ivals],
                        axis=eqn.params["dimension"],
                    )
                except Exception:
                    const = None
            return [dataclasses.replace(out, tags=tags, const=const,
                                        contract=None)]
        if name == "pad":
            return one(_join(ivals[0], ivals[1]))
        if name in ("gather", "dynamic_slice"):
            # the indices pick WHICH element, not its range: only the
            # operand's magnitude (and tags) flow — implicit index
            # flows are the taint walker's jurisdiction, and tagging
            # them here would smear `time:` onto every popped value
            a = ivals[0]
            return [AVal(a.lo, a.hi, a.tags)]
        if name == "iota":
            n = int(eqn.params["shape"][eqn.params["dimension"]])
            return [AVal(0, max(0, n - 1))]
        if name in ("eq", "ne", "lt", "le", "gt", "ge", "lt_to", "le_to",
                    "is_finite", "reduce_and", "reduce_or"):
            return [AVal(0, 1, tags)]
        if name == "not":
            if np.dtype(outv[0].aval.dtype) == np.bool_:
                return [AVal(0, 1, tags)]
            return top_all()
        if name in ("and", "or", "xor"):
            a, b = ivals
            if np.dtype(outv[0].aval.dtype) == np.bool_:
                return [AVal(0, 1, tags)]
            if (
                a.lo is not None and b.lo is not None
                and a.lo >= 0 and b.lo >= 0
                and a.hi is not None and b.hi is not None
            ):
                if name == "and":
                    return [AVal(0, min(a.hi, b.hi), tags)]
                bits = max(int(a.hi).bit_length(), int(b.hi).bit_length())
                return [AVal(0, (1 << bits) - 1, tags)]
            return top_all()
        if name == "shift_right_logical":
            a, s = ivals
            if None in (a.lo, a.hi, s.lo, s.hi) or a.lo < 0:
                return top_all()
            return [AVal(a.lo >> min(s.hi, 64), a.hi >> max(s.lo, 0), tags)]
        if name == "shift_right_arithmetic":
            a, s = ivals
            if None in (a.lo, a.hi, s.lo, s.hi) or s.lo < 0:
                return top_all()
            cs = [a.lo >> s.lo, a.lo >> min(s.hi, 64),
                  a.hi >> s.lo, a.hi >> min(s.hi, 64)]
            return [AVal(min(cs), max(cs), tags)]
        if name == "div":
            a, b = ivals
            if None in (a.lo, a.hi, b.lo, b.hi) or (b.lo <= 0 <= b.hi):
                return top_all()
            cs = [_trunc_div(x, y) for x in (a.lo, a.hi)
                  for y in (b.lo, b.hi)]
            return one(AVal(min(cs), max(cs), tags))
        if name == "rem":
            a, b = ivals
            if None in (b.lo, b.hi) or (b.lo <= 0 <= b.hi):
                return top_all()
            m = max(abs(b.lo), abs(b.hi)) - 1
            if a.lo is not None and a.lo >= 0:
                hi = m if a.hi is None else min(a.hi, m)
                return [AVal(0, hi, tags)]
            return [AVal(-m, m, tags)]
        if name in ("reduce_min", "reduce_max", "cummax", "cummin", "sort"):
            return [
                AVal(a.lo, a.hi, a.tags | tags)
                for a in (ivals if name == "sort" else [ivals[0]])
            ][: len(outv)] or top_all()
        if name in ("argmin", "argmax"):
            # the result is a POSITION in [0, n): its magnitude carries
            # nothing of the operand's value range (the operand's
            # influence is an implicit flow, the taint walker's beat)
            axes = eqn.params.get("axes", ())
            shape = eqn.invars[0].aval.shape
            n = max((int(shape[ax]) for ax in axes), default=1)
            return [AVal(0, max(0, n - 1))]
        if name == "abs":
            a = ivals[0]
            if a.lo is None or a.hi is None:
                return top_all()
            lo = 0 if a.lo < 0 else a.lo
            return one(AVal(lo, max(abs(a.lo), abs(a.hi)), tags))
        if name == "sign":
            return [AVal(-1, 1, tags)]
        if name == "population_count":
            return [AVal(0, 64, tags)]
        if name == "clz":
            return [AVal(0, 64, tags)]
        if name == "optimization_barrier":
            return [dataclasses.replace(a, contract=None) for a in ivals]
        # unknown primitive: conservative top (full dtype range for
        # integers, unbounded for floats), tags flow through
        return top_all()

    def _reduce_sum(self, eqn, ivals, epath, report, level, checked):
        a = ivals[0]
        axes = eqn.params.get("axes", ())
        shape = eqn.invars[0].aval.shape
        n = 1
        for ax in axes:
            n *= int(shape[ax])
        n = max(n, 1)
        if a.lo is None or a.hi is None:
            return [_top_for(eqn.outvars[0], a.tags)]
        if self.onehot_sums:
            # the masked-sum pick idiom: sum(where(m, x, 0)) with the
            # mask one-hot by cumsum-rank construction — modeled as a
            # pick (hull with 0) instead of n*x. See the module
            # docstring's trust statement.
            picked = self._onehot_operand(eqn, level)
            if picked is not None:
                lo = min(0, picked.lo if picked.lo is not None else 0)
                hi = max(0, picked.hi if picked.hi is not None else 0)
                if picked.lo is None or picked.hi is None:
                    return [_top_for(eqn.outvars[0], a.tags | picked.tags)]
                return [AVal(lo, hi, a.tags | picked.tags)]
        return checked(min(a.lo * n, a.lo), max(a.hi * n, a.hi))

    def _onehot_operand(self, eqn, level):
        """If the summed operand is ``where(m, x, 0)`` (a pjit-wrapped
        select_n with a zero case), return x's abstract value."""
        jaxpr, env, defs, _path = level
        v = eqn.invars[0]
        if not isinstance(v, jax_core.Var) or v not in defs:
            return None
        d = jaxpr.eqns[defs[v]]
        if d.primitive.name == "pjit" and d.params.get("name") == "_where":
            cases = d.invars[1:]
        elif d.primitive.name == "select_n":
            cases = d.invars[1:]
        else:
            return None
        vals = [self._read(env, c) for c in cases]
        zero = [
            i for i, (c, a) in enumerate(zip(cases, vals))
            if _is_zero(c, a)
        ]
        if len(zero) != 1 or len(vals) != 2:
            return None
        return vals[1 - zero[0]]


_CALL_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "remat", "remat2", "checkpoint",
    "custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
})


def _hull2(a: AVal, b: AVal):
    lo = None if a.lo is None or b.lo is None else min(a.lo, b.lo)
    hi = None if a.hi is None or b.hi is None else max(a.hi, b.hi)
    return lo, hi


def _union_tags(ivals):
    return frozenset().union(*[a.tags for a in ivals]) if ivals else frozenset()


def _join_many(vals):
    out = vals[0]
    for v in vals[1:]:
        out = _join(out, v)
    return dataclasses.replace(out, contract=None)


def _widen(j: AVal, prev: AVal, dr) -> AVal:
    """Threshold widening: a contract acts as the first threshold (the
    narrowing step applies it before this runs); a bound still
    unstable here jumps straight to the dtype bound — monotone over a
    finite chain, so fixpoints always terminate."""
    lo, hi = j.lo, j.hi
    if prev.lo is not None and (lo is None or lo < prev.lo):
        lo = dr[0]
    if prev.hi is not None and (hi is None or hi > prev.hi):
        hi = dr[1]
    return dataclasses.replace(j, lo=lo, hi=hi, const=None)


def _reshape_const(name, eqn, const):
    if const is None:
        return None
    try:
        arr = np.asarray(const)
        if name == "broadcast_in_dim":
            shape = eqn.params["shape"]
            bdims = tuple(eqn.params["broadcast_dimensions"])
            tmp = [1] * len(shape)
            for i, d in enumerate(bdims):
                tmp[d] = arr.shape[i]
            out = np.broadcast_to(np.reshape(arr, tmp), shape)
            return out if out.size <= _CONST_MAX else None
        if name == "reshape":
            return np.reshape(arr, eqn.params["new_sizes"])
        if name == "squeeze":
            return np.squeeze(arr, axis=tuple(eqn.params["dimensions"]))
        if name == "transpose":
            return np.transpose(arr, eqn.params["permutation"])
        if name == "rev":
            return np.flip(arr, axis=tuple(eqn.params["dimensions"]))
        if name in ("copy", "stop_gradient", "reduce_precision",
                    "expand_dims", "device_put", "sharding_constraint"):
            return arr
    except Exception:
        return None
    return None


def _is_zero(var, aval: AVal) -> bool:
    if isinstance(var, jax_core.Literal):
        try:
            return float(np.asarray(var.val).ravel()[0]) == 0.0
        except Exception:
            return False
    return aval.lo == 0 and aval.hi == 0


def _trunc_div(a, b):
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


# ---------------------------------------------------------------------------
# Entry points.
# ---------------------------------------------------------------------------


def analyze_intervals(closed, in_vals, *, onehot_sums=True) -> _Walker:
    """Run one interval pass over a (closed) jaxpr.

    ``in_vals`` is one :class:`AVal` per invar. Returns the walker,
    whose ``out`` holds per-outvar abstract values and whose
    ``findings``/``sites`` hold the raw overflow findings and threefry
    lane sites (pragma filtering is the caller's job — tests use this
    raw form directly)."""
    return _Walker(closed, in_vals, onehot_sums=onehot_sums)


def check_lane_sites(sites) -> list:
    """The lane-disjointness obligations over recorded threefry sites."""
    findings = []

    def _f(msg, involved):
        findings.append(
            {
                "rule": LANE_RULE,
                "message": msg,
                "sites": [s.describe() for s in involved],
                "file": involved[0].src[0],
                "line": involved[0].src[1],
                "paths": [s.path for s in involved],
            }
        )

    resolved = []
    for s in sites:
        pset = s.purpose_set()
        if pset is not None:
            if len(pset) != np.asarray(s.purposes).size:
                _f(
                    "one site draws the same purpose twice in one block "
                    "— identical cipher values, correlated lanes",
                    [s],
                )
            lanes = {}
            for p in pset:
                ln = _rng.lane_of(p)
                if ln is None:
                    _f(
                        f"purpose {p:#x} lies in unassigned space — "
                        f"register a PURPOSE_LANES block (engine/rng.py)",
                        [s],
                    )
                else:
                    lanes.setdefault(ln.name, set()).add(p)
            resolved.append((s, pset, lanes))
        else:
            ln_lo = _rng.lane_of(s.p_lo)
            ln_hi = _rng.lane_of(s.p_hi)
            if ln_lo is None or ln_lo is not ln_hi:
                _f(
                    f"dynamic purpose interval [{s.p_lo:#x}, {s.p_hi:#x}] "
                    f"is not contained in one registered lane — the draw "
                    f"cannot be proven disjoint",
                    [s],
                )
            resolved.append((s, None, {ln_lo.name: set()} if ln_lo else {}))

    for i in range(len(resolved)):
        for j in range(i + 1, len(resolved)):
            a, pa, _la = resolved[i]
            b, pb, _lb = resolved[j]
            if _branch_exclusive(a.path, b.path):
                continue
            if a.x0_hi < b.x0_lo or b.x0_hi < a.x0_lo:
                continue  # counters can never coincide
            shared = _shared_purposes(a, pa, b, pb)
            if shared:
                shown = ", ".join(
                    f"{p:#x}" for p in sorted(shared)[:6]
                ) if isinstance(shared, set) else shared
                _f(
                    f"two live draw sites share purpose lane(s) {shown} "
                    f"at overlapping counters — the streams are "
                    f"IDENTICAL, not independent",
                    [a, b],
                )
    return findings


def _shared_purposes(a, pa, b, pb):
    if pa is not None and pb is not None:
        return pa & pb
    ia = (a.p_lo, a.p_hi)
    ib = (b.p_lo, b.p_hi)
    if pa is not None:
        hit = {p for p in pa if ib[0] <= p <= ib[1]}
        return hit
    if pb is not None:
        return {p for p in pb if ia[0] <= p <= ia[1]}
    lo = max(ia[0], ib[0])
    hi = min(ia[1], ib[1])
    return f"[{lo:#x}, {hi:#x}]" if lo <= hi else None


# ---------------------------------------------------------------------------
# Pragma plumbing (the checked allowlist, extended to jaxpr findings).
# ---------------------------------------------------------------------------


_PRAGMA_CACHE: dict = {}


def _file_pragmas(rel_path, root=_REPO_ROOT):
    key = (root, rel_path)
    if key not in _PRAGMA_CACHE:
        entries = []
        full = Path(root) / rel_path
        try:
            entries = _pragma_entries(full.read_text(encoding="utf-8"))
        except OSError:
            pass
        _PRAGMA_CACHE[key] = entries
    return _PRAGMA_CACHE[key]


def _apply_pragmas(findings, root=_REPO_ROOT):
    """Split raw findings into (kept, allowed, used-pragma keys)."""
    kept, allowed, used = [], [], set()
    for f in findings:
        rel, line = f.get("file"), f.get("line", 0)
        suppressed = False
        if rel:
            for p in _file_pragmas(rel, root):
                if line in p["covers"] and f["rule"] in p["rules"]:
                    used.add((rel, p["anchor"], f["rule"]))
                    suppressed = True
        (allowed if suppressed else kept).append(f)
    return kept, allowed, used


def absint_pragma_inventory(paths=None, root=None) -> list:
    """Every ``absint-*`` pragma on the lint surface, as
    ``(repo-relative path, anchor line, rule)`` tuples."""
    root = Path(root) if root else Path(_REPO_ROOT)
    out = []
    targets = paths if paths is not None else [
        root / p for p in DEFAULT_PATHS if (root / p).exists()
    ]
    files = []
    for p in targets:
        p = Path(p)
        files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
    for f in files:
        if "__pycache__" in f.parts:
            continue
        try:
            entries = _pragma_entries(f.read_text(encoding="utf-8"))
        except OSError:
            continue
        try:
            rel = str(f.relative_to(root))
        except ValueError:
            rel = str(f)
        for p in entries:
            for rule in sorted(p["rules"]):
                if rule.startswith("absint-"):
                    out.append((rel, p["anchor"], rule))
    return out


def stale_absint_pragmas(used, paths=None, root=None) -> list:
    """Inventory minus exercised: each stale entry is a finding, the
    ``unused-allow`` rule applied to this analysis. Judged against the
    set of proofs the CALLER ran — the repo gates run the full lowering
    sweep for at least one model, which exercises every in-engine
    pragma site."""
    used = set(used)
    stale = []
    for rel, line, rule in absint_pragma_inventory(paths, root):
        if (rel, line, rule) not in used:
            stale.append(
                {
                    "rule": "unused-allow",
                    "file": rel,
                    "line": line,
                    "message": (
                        f"pragma allows [{rule!r}] but no traced program "
                        f"exercised it — stale allowlist entry"
                    ),
                }
            )
    return stale


# ---------------------------------------------------------------------------
# The provers over real engine programs.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AbsintReport:
    """Verdict of one range proof over a traced (wl, cfg, flags)."""

    workload: str
    config_hash: str
    entry: str
    flags: dict
    horizon_ns: int
    findings: list  # unsuppressed finding dicts (overflow + lane)
    allowed: list  # pragma-suppressed findings (the allowlist in use)
    used_pragmas: list  # sorted (file, line, rule) keys
    lane_sites: list  # site descriptions
    lanes: list  # sorted names of registry lanes with live draws
    n_eqns: int
    checked_ops: int

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    def summary(self) -> str:
        what = (
            f"{self.workload} [{self.entry}] flags="
            f"{{{', '.join(f'{k}={v}' for k, v in sorted(self.flags.items()) if v)}}}"
        )
        if self.ok:
            return (
                f"OK   {what}: {self.n_eqns} eqns, {self.checked_ops} "
                f"tracked ops in range, {len(self.lane_sites)} threefry "
                f"site(s) over lanes {{{', '.join(self.lanes)}}} disjoint"
                + (f", {len(self.allowed)} allowlisted" if self.allowed else "")
            )
        lines = [f"FAIL {what}: {len(self.findings)} finding(s)"]
        for f in self.findings:
            if f["rule"] == OVERFLOW_RULE:
                lines.append(
                    f"  {OVERFLOW_RULE} {f['file']}:{f['line']} "
                    f"{f['prim']}:{f['dtype']} math={f['math']} "
                    f"exceeds {f['dtype_range']} (sources {f['sources']})"
                )
                for hop in f["chain"]:
                    lines.append(
                        f"    via {hop['path']}:{hop['prim']} "
                        f"(sources {hop['sources']})"
                    )
            else:
                lines.append(f"  {f['rule']}: {f['message']}")
                for s in f.get("sites", []):
                    lines.append(f"    site {s}")
        return "\n".join(lines)


def check_ranges(
    wl: Workload,
    cfg: EngineConfig,
    *,
    entry: str = "step",
    layout: str = "scatter",
    time32: bool = False,
    placement: str | None = None,
    pool_index: bool | None = None,
    dup_rows: bool = False,
    cov_words: int = 0,
    metrics: bool = False,
    timeline_cap: int = 0,
    cov_hitcount: bool = False,
    latency: LatencySpec | None = None,
    causal: bool = False,
    horizon_ns: int | None = None,
    n_steps: int = 4,
    n_seeds: int = 2,
    mutate=None,
    onehot_sums: bool = True,
) -> AbsintReport:
    """Prove (or refute) overflow-freedom + lane disjointness for one
    build. ``entry="step"`` walks the single-seed step with inputs
    seeded at the column contracts; ``entry="run"`` walks the vmapped
    ``make_run`` scan (the loop-carry fixpoint path, carries narrowed
    to their contracts — the assume-guarantee boundary). ``mutate``
    wraps the traced function, the planted-mutant hook shared with the
    taint proof."""
    flags = dict(
        layout=layout, time32=time32, placement=placement,
        pool_index=pool_index, dup_rows=dup_rows, cov_words=cov_words,
        metrics=metrics, timeline_cap=timeline_cap,
        cov_hitcount=cov_hitcount, causal=causal,
        latency=(
            (latency.ops, latency.phases, latency.phase_ns)
            if latency is not None else None
        ),
    )
    obs_kw = dict(
        dup_rows=dup_rows, cov_words=cov_words, metrics=metrics,
        timeline_cap=timeline_cap, cov_hitcount=cov_hitcount,
        latency=latency, causal=causal,
    )
    init = make_init(
        wl, cfg, time32=time32, cov_words=cov_words, metrics=metrics,
        timeline_cap=timeline_cap, cov_hitcount=cov_hitcount,
        latency=latency, pool_index=pool_index, causal=causal,
    )
    state = init(np.zeros(max(n_seeds, 1), np.uint64))
    if entry == "step":
        fn = make_step(
            wl, cfg, layout=layout, time32=time32, placement=placement,
            pool_index=pool_index, **obs_kw,
        )
        template = jax.tree.map(lambda a: a[0], state)
    elif entry == "run":
        fn = make_run(
            wl, cfg, n_steps, layout=layout, time32=time32,
            placement=placement, pool_index=pool_index, **obs_kw,
        )
        template = state
    else:
        raise ValueError(f"unknown entry {entry!r} (step or run)")
    if mutate is not None:
        fn = mutate(fn)

    with _rng.lane_site_tracing():
        closed = jax.make_jaxpr(fn)(template)

    from .noninterference import _leaf_names

    names = _leaf_names(template)
    contracts = column_contracts(
        wl, cfg, time32=bool(time32), horizon_ns=horizon_ns
    )
    in_vals = []
    for name, var in zip(names, closed.jaxpr.invars):
        dr = _dtype_range(var.aval.dtype)
        cc = contracts.get(name)
        if cc is None or dr[0] is None:
            in_vals.append(AVal(dr[0], dr[1]))
            continue
        lo, hi = max(cc.lo, dr[0]), min(cc.hi, dr[1])
        tags = frozenset({f"{cc.family}:{name}"}) if cc.family else frozenset()
        in_vals.append(AVal(lo, hi, tags, None, (lo, hi)))

    walker = analyze_intervals(closed, in_vals, onehot_sums=onehot_sums)
    raw = walker.findings + check_lane_sites(walker.sites)
    kept, allowed, used = _apply_pragmas(raw)
    live = set()
    for s in walker.sites:
        pset = s.purpose_set()
        if pset is None:
            ln = _rng.lane_of(s.p_lo)
            if ln is not None:
                live.add(ln.name)
        else:
            for p in pset:
                ln = _rng.lane_of(p)
                if ln is not None:
                    live.add(ln.name)
    h = horizon_ns if horizon_ns is not None else (
        cfg.time_limit_ns or ABSINT_HORIZON_NS
    )
    return AbsintReport(
        workload=wl.name,
        config_hash=cfg.hash(),
        entry=entry,
        flags=flags,
        horizon_ns=int(h),
        findings=kept,
        allowed=allowed,
        used_pragmas=sorted(used),
        lane_sites=[s.describe() for s in walker.sites],
        lanes=sorted(live),
        n_eqns=walker.n_eqns,
        checked_ops=walker.checked_ops,
    )


# ---------------------------------------------------------------------------
# Planted positive controls.
# ---------------------------------------------------------------------------


def plant_time32_sentinel_decay(step_fn):
    """Re-create the PR-13 time32 sentinel-decay bug class as a mutant.

    The carried ``tile_min`` of an EMPTY tile holds the +inf sentinel;
    the clean step re-masks empty tiles to a FRESH sentinel before any
    arithmetic touches them. This mutant applies the per-step rebase to
    the carried column directly — the decayed sentinel keeps shrinking
    and, once the accumulated advance exceeds the int32 range
    (~2.1 sim-seconds), the subtraction wraps: exactly the silent
    divergence the PR-13 review caught. Value-plausible (each single
    step is in range), invisible to one-shot runtime checks — and a
    certain catch for the interval prover, whose finding cites the
    ``time:tile_min`` chain at THIS (un-pragma'd) site."""
    import jax.numpy as jnp

    def mutant(st):
        out = step_fn(st)
        if out.tile_min.ndim != 1 or out.tile_min.shape[0] == 0:
            raise ValueError(
                "plant_time32_sentinel_decay needs a step built with "
                "pool_index=True (the tile summary columns)"
            )
        if out.tile_min.dtype != jnp.int32:
            raise ValueError(
                "plant_time32_sentinel_decay is a time32 mutant: the "
                "decay wrap exists only in the int32 offset form"
            )
        adv = (out.now - st.now).astype(jnp.int32)
        return dataclasses.replace(out, tile_min=st.tile_min - adv)

    return mutant


def plant_lane_collision(step_fn):
    """Plant a threefry draw that re-uses the engine's first per-emit
    latency lane (``PURPOSE_LATENCY + 0``) at the same ``(seed, step)``
    counter. The value is folded into the trace hash xor-masked to
    zero, so the mutant is value-identical on every input — no runtime
    test can see it — yet the two draw sites now share a live
    ``(purpose, counter)`` lane: the stream the handler thinks is
    independent is bit-for-bit the engine's latency draw."""
    import jax.numpy as jnp

    from ..engine.rng import PURPOSE_LATENCY, Draw

    def mutant(st):
        out = step_fn(st)
        d = Draw(st.seed, st.step)
        x = d.bits(PURPOSE_LATENCY + 0)
        poison = x.astype(jnp.uint64) & jnp.uint64(0)
        return dataclasses.replace(out, trace=out.trace ^ poison)

    return mutant


def run_mutant_controls() -> list:
    """Run both planted positive controls against the canonical small
    raft/record build and judge them: returns
    ``[(name, report, caught), ...]`` — THE one declaration of the
    control recipe, shared by tools/lint_soak.py cert 5,
    tools/absint_soak.py cert 2 and the test suite, so the catch
    criteria cannot drift between gates."""
    from ..models import make_raft

    wl = make_raft(record=True)
    cfg = EngineConfig(
        pool_size=40, loss_p=0.02, clog_backoff_max_ns=2_000_000_000
    )
    rep_sd = check_ranges(
        wl, cfg, entry="step", layout="scatter", time32=True,
        pool_index=True, mutate=plant_time32_sentinel_decay,
    )
    caught_sd = not rep_sd.ok and any(
        f["rule"] == OVERFLOW_RULE
        and any(t.endswith("tile_min") for t in f["sources"])
        and f["chain"]
        for f in rep_sd.findings
    )
    rep_lc = check_ranges(
        wl, cfg, entry="step", layout="scatter",
        mutate=plant_lane_collision,
    )
    caught_lc = not rep_lc.ok and any(
        f["rule"] == LANE_RULE and len(f.get("sites", [])) == 2
        for f in rep_lc.findings
    )
    return [
        ("time32-sentinel-decay", rep_sd, caught_sd),
        ("lane-collision", rep_lc, caught_lc),
    ]


# ---------------------------------------------------------------------------
# The certified matrix.
# ---------------------------------------------------------------------------

# absint build axes: "base" is the lean program, "dup" compiles the
# duplication shadow lanes (the dup purpose block goes live), "all"
# turns every observability tap on (the widest arithmetic surface —
# timeline/latency/metrics each add tracked adds).
ABSINT_AXES = {
    "base": {},
    "dup": dict(dup_rows=True),
    # the causal-provenance counters (ISSUE 19): the Lamport fold
    # (max + 1 per dispatch) and the int32 dispatch-sequence stamp both
    # grow with the step count, so their overflow-freedom rests on the
    # step-budget contract (column_contracts bounds lam and seq by
    # ABSINT_STEP_MAX) — this row makes the prover actually walk that
    # arithmetic rather than trusting the bound.
    "causal": dict(causal=True, timeline_cap=8),
    "all": dict(
        metrics=True, timeline_cap=8, cov_words=8, cov_hitcount=True,
        latency=LatencySpec(ops=8, phases=2), causal=True,
    ),
}


def absint_model_matrix() -> list:
    """(tag, workload, config, horizon_ns) rows from each recorded
    model's own ``absint_entries()`` declaration (models/*.py — the
    range-entry analog of ``lint_entries``)."""
    from ..models import kvchaos, leasekv, paxos, raft, raftlog, shardkv

    entries = []
    for mod in (raft, kvchaos, paxos, raftlog, leasekv, shardkv):
        for tag, wl, cfg_kw, horizon in mod.absint_entries():
            entries.append((tag, wl, EngineConfig(**cfg_kw), horizon))
    return entries


def absint_matrix(
    models=None,
    axes=None,
    layouts=None,
    *,
    entry: str = "step",
    log=None,
    onehot_sums: bool = True,
) -> list:
    """Run the range proof over a model x build-flag x lowering matrix.

    ``layouts`` takes the same (layout, time32[, placement[,
    pool_index]]) tuples as ``noninterference.check_matrix``
    (``LAYOUT_AXES`` is the full set); ineligible (model, lowering)
    pairs are skipped, not failed."""
    from .noninterference import LAYOUT_AXES

    if models is not None and not models:
        raise ValueError("absint_matrix: models is empty")
    if layouts is None:
        layouts = LAYOUT_AXES
    reports = []
    for tag, wl, cfg, horizon in (
        models if models is not None else absint_model_matrix()
    ):
        for lay, t32, *rest in layouts:
            place = rest[0] if rest else None
            pidx = rest[1] if len(rest) > 1 else None
            if t32 and not time32_eligible(wl, cfg):
                continue
            if pidx and not pool_index_eligible(cfg):
                continue
            for axis, fl in (axes or ABSINT_AXES).items():
                rep = check_ranges(
                    wl, cfg, entry=entry, layout=lay, time32=t32,
                    placement=place, pool_index=pidx,
                    horizon_ns=horizon, onehot_sums=onehot_sums, **fl,
                )
                rep.flags["axis"] = axis
                rep.workload = tag
                if log is not None:
                    log(rep.summary())
                reports.append(rep)
    return reports
