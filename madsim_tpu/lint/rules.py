"""Nondeterminism-leak linter: AST rules over sim code.

The runtime interposition layer (runtime/intercept.py) makes *patched*
stdlib entry points deterministic inside a simulation — but it openly
admits bypasses (``datetime.datetime.now`` reads the clock in C), and
it can do nothing about code that runs OUTSIDE a sim context yet feeds
deterministic artifacts: a soak tool seeding from the wall clock, a
plan compiler iterating a ``set``, a handler calling ``id()`` in a
branch. This module turns the convention into a checked invariant: a
small, alias-aware AST pass with one rule per leak class.

Rules (each Finding carries the rule name):

* ``wall-clock``      — wall/monotonic clock reads (``time.time``,
  ``time.time_ns``, ``time.monotonic*``, ``time.perf_counter*``,
  ``datetime.datetime.now/utcnow/today``, ``datetime.date.today``).
  Telemetry wall timers are legitimate — annotate them.
* ``ambient-entropy`` — ``os.urandom``, ``os.getrandom``,
  ``secrets.*``, ``random.SystemRandom`` (entropy the threefry
  discipline never sees).
* ``uuid-entropy``    — ``uuid.uuid1``/``uuid.uuid4`` (MAC/clock and
  ambient entropy respectively; uuid3/5 are pure functions).
* ``np-random``       — the un-threefry'd numpy RNG: any
  ``numpy.random.*`` call (``default_rng``/``RandomState``/
  ``SeedSequence`` with an explicit seed argument are allowed — those
  are deterministic constructions).
* ``unordered-iter``  — a set-typed expression in an ordering-
  sensitive position: iterated by ``for``/comprehensions, or
  materialized via ``list``/``tuple``/``enumerate``/``iter``/
  ``.join`` without ``sorted``. Set iteration order is salted per
  process; feeding it into emits or plan compilation is a schedule
  leak. (dict preserves insertion order in py>=3.7 and is not
  flagged.)
* ``id-hash-branch``  — ``id()`` / object-``hash()`` inside a branch
  condition (``if``/``while``/ternary/``assert``): memory addresses
  and salted hashes must never steer control flow in sim code.
* ``host-callback``   — ``io_callback`` / ``pure_callback`` /
  ``jax.debug.callback`` / ``jax.debug.print`` in sim code: a host
  round-trip inside a jitted step breaks both determinism (host
  effects are unordered across devices) and the never-move-state-
  to-host discipline.
* ``fixed-key``       — ``jax.random.PRNGKey``/``jax.random.key`` with
  a literal constant seed in library (sim) code: the repo's RNG
  discipline is counter-based threefry keyed by the INSTANCE seed
  (engine/rng.py); a hard-coded ``PRNGKey(0)`` silently correlates
  "independent" draws across every seed in a batch and across every
  call site sharing the constant. Derive keys from the instance seed
  (or annotate a deliberately-fixed key).

Pragmas: append ``# lint: allow(rule)`` (comma-separate several rules)
to the offending line — or put it on a comment line directly above —
to allowlist an intentional site. The allowlist is CHECKED: a pragma
that suppressed nothing becomes an ``unused-allow`` finding, so stale
annotations cannot accumulate.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path

__all__ = [
    "DEFAULT_PATHS",
    "Finding",
    "LintResult",
    "RULES",
    "lint_paths",
    "lint_repo",
    "lint_source",
]

RULES = (
    "wall-clock",
    "ambient-entropy",
    "uuid-entropy",
    "np-random",
    "unordered-iter",
    "id-hash-branch",
    "host-callback",
    "fixed-key",
    "unused-allow",
    "parse-error",
)

# the default lint surface: the package itself plus everything that
# produces deterministic artifacts or exercises the sim
DEFAULT_PATHS = ("madsim_tpu", "examples", "tools", "bench.py")

_PRAGMA_RE = re.compile(r"#\s*lint:\s*allow\(([^)]*)\)")

_WALL_CLOCK = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.clock_gettime",
    "time.clock_gettime_ns",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "datetime.date.today",
}

_ENTROPY = {
    "os.urandom",
    "os.getrandom",
    "random.SystemRandom",
}

_UUID = {"uuid.uuid1", "uuid.uuid4"}

_SEEDED_NP = {"default_rng", "RandomState", "SeedSequence", "Generator"}

_HOST_CB = {
    "jax.experimental.io_callback",
    "jax.pure_callback",
    "jax.debug.callback",
    "jax.debug.print",
    "jax.experimental.host_callback.call",
}

# key constructors whose literal-constant seeds the fixed-key rule flags
_JAX_KEY = {"jax.random.PRNGKey", "jax.random.key"}
# bare suffixes that identify the same callables when imported directly
# (``from jax.experimental import io_callback``)
_HOST_CB_SUFFIX = {"io_callback", "pure_callback"}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LintResult:
    findings: list  # list[Finding] — violations (incl. unused-allow)
    allowed: list  # list[Finding] — suppressed by a pragma (the
    #                checked allowlist inventory)
    n_files: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings

    def merge(self, other: "LintResult") -> None:
        self.findings.extend(other.findings)
        self.allowed.extend(other.allowed)
        self.n_files += other.n_files


class _Aliases:
    """Import-alias resolution: dotted names back to canonical roots."""

    def __init__(self):
        self.map: dict = {}

    def visit_imports(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.map[a.asname] = a.name
                    else:
                        # ``import os.path`` binds the local name
                        # ``os`` to the ROOT module — mapping it to
                        # the dotted name would mis-resolve a later
                        # ``os.urandom`` to ``os.path.urandom`` and
                        # silently disable every call rule on that root
                        root = a.name.split(".")[0]
                        self.map[root] = root
            elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                for a in node.names:
                    self.map[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, node: ast.AST) -> str | None:
        """Dotted canonical name of a Name/Attribute chain, or None."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.map.get(node.id, node.id)
        parts.append(root)
        name = ".".join(reversed(parts))
        # normalize the common numpy alias once resolved
        if name == "np" or name.startswith("np."):
            name = "numpy" + name[2:]
        return name


def _is_set_expr(node: ast.AST) -> bool:
    """Syntactically set-typed: a set display/comprehension or a
    ``set(...)``/``frozenset(...)`` call (including methods returning
    sets: ``.union``/``.intersection``/``.difference`` on one)."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id in ("set", "frozenset"):
            return True
        if isinstance(f, ast.Attribute) and f.attr in (
            "union", "intersection", "difference", "symmetric_difference",
        ):
            return _is_set_expr(f.value)
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
    ):
        return _is_set_expr(node.left) or _is_set_expr(node.right)
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, aliases: _Aliases, sim_code: bool):
        self.path = path
        self.aliases = aliases
        self.sim_code = sim_code  # host-callback rule scope
        self.found: list = []
        self._branch_depth = 0

    def _emit(self, rule: str, node: ast.AST, msg: str) -> None:
        self.found.append(
            Finding(
                rule=rule,
                path=self.path,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                message=msg,
            )
        )

    # -- calls ----------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        name = self.aliases.resolve(node.func)
        if name:
            self._check_call(name, node)
        # ordering-sensitive materialization of a set
        if isinstance(node.func, ast.Name) and node.func.id in (
            "list", "tuple", "enumerate", "iter",
        ):
            if node.args and _is_set_expr(node.args[0]):
                self._emit(
                    "unordered-iter",
                    node,
                    f"{node.func.id}() over a set materializes the "
                    f"process-salted iteration order; wrap in sorted()",
                )
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
            and node.args
            and _is_set_expr(node.args[0])
        ):
            self._emit(
                "unordered-iter",
                node,
                "str.join over a set depends on the salted iteration "
                "order; wrap in sorted()",
            )
        self.generic_visit(node)

    def _check_call(self, name: str, node: ast.Call) -> None:
        if name in _WALL_CLOCK:
            self._emit(
                "wall-clock",
                node,
                f"{name}() bypasses the determinism substrate outside a "
                f"sim context (intercept.py patches it only in-sim); "
                f"annotate telemetry walls with a pragma",
            )
        elif name in _ENTROPY or name.startswith("secrets."):
            self._emit(
                "ambient-entropy",
                node,
                f"{name}() draws ambient entropy the threefry discipline "
                f"never sees",
            )
        elif name in _UUID:
            self._emit(
                "uuid-entropy",
                node,
                f"{name}() is clock/entropy-derived; use uuid3/uuid5 "
                f"over deterministic inputs or a seeded stream",
            )
        elif name.startswith("numpy.random."):
            leaf = name.rsplit(".", 1)[1]
            if not (leaf in _SEEDED_NP and (node.args or node.keywords)):
                self._emit(
                    "np-random",
                    node,
                    f"{name}() is the un-threefry'd numpy RNG; draw "
                    f"through engine.rng / np_threefry2x32 or seed an "
                    f"explicit Generator",
                )
        elif self.sim_code and (
            name in _HOST_CB or name.rsplit(".", 1)[-1] in _HOST_CB_SUFFIX
        ):
            self._emit(
                "host-callback",
                node,
                f"{name}() is a host round-trip inside sim code: host "
                f"effects are unordered across devices and break the "
                f"device-resident discipline",
            )
        elif (
            self.sim_code
            and name in _JAX_KEY
            and node.args
            and isinstance(node.args[0], ast.Constant)
        ):
            self._emit(
                "fixed-key",
                node,
                f"{name}({node.args[0].value!r}) hard-codes an RNG key "
                f"in library code: every batch row (and every call "
                f"site sharing the constant) draws the SAME stream — "
                f"derive the key from the instance seed "
                f"(engine/rng.py), or annotate a deliberately-fixed "
                f"key",
            )

    # -- unordered iteration -------------------------------------------
    def visit_For(self, node: ast.For) -> None:
        if _is_set_expr(node.iter):
            self._emit(
                "unordered-iter",
                node.iter,
                "iterating a set: order is process-salted; wrap in "
                "sorted()",
            )
        self.generic_visit(node)

    def visit_comprehension(self, node: ast.comprehension) -> None:
        if _is_set_expr(node.iter):
            self._emit(
                "unordered-iter",
                node.iter,
                "comprehension over a set: order is process-salted; "
                "wrap in sorted()",
            )
        self.generic_visit(node)

    # -- id()/hash() in branch conditions -------------------------------
    def _scan_branch(self, test: ast.AST) -> None:
        for sub in ast.walk(test):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id in ("id", "hash")
            ):
                self._emit(
                    "id-hash-branch",
                    sub,
                    f"{sub.func.id}() in a branch condition: memory "
                    f"addresses / salted hashes must not steer sim "
                    f"control flow",
                )

    def visit_If(self, node: ast.If) -> None:
        self._scan_branch(node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._scan_branch(node.test)
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._scan_branch(node.test)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._scan_branch(node.test)
        self.generic_visit(node)


def _pragma_entries(source: str) -> list:
    """One entry per ``# lint: allow(...)`` comment:
    ``{"anchor": line, "rules": set, "covers": set}``.

    A trailing pragma covers exactly its own line; a pragma on a
    comment-only line covers exactly the next line (annotation-above
    style). Each pragma's usage is tracked INDIVIDUALLY so a dead
    pragma next to a live same-rule one is still reported stale.
    """
    entries: list = []
    lines = source.splitlines()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            rules = {
                r.strip() for r in m.group(1).split(",") if r.strip()
            }
            line = tok.start[0]
            # comment-only line: the token starts at the line's first
            # non-whitespace column
            src_line = lines[line - 1] if line <= len(lines) else ""
            covers = (
                {line + 1} if src_line.lstrip().startswith("#") else {line}
            )
            entries.append(
                {"anchor": line, "rules": rules, "covers": covers}
            )
    except tokenize.TokenError:
        pass
    return entries


def lint_source(
    source: str, path: str = "<string>", sim_code: bool = True
) -> LintResult:
    """Lint one module's source text."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return LintResult(
            findings=[
                Finding(
                    rule="parse-error",
                    path=path,
                    line=exc.lineno or 0,
                    col=exc.offset or 0,
                    message=f"unparseable: {exc.msg}",
                )
            ],
            allowed=[],
            n_files=1,
        )
    aliases = _Aliases()
    aliases.visit_imports(tree)
    visitor = _Visitor(path, aliases, sim_code)
    visitor.visit(tree)

    pragmas = _pragma_entries(source)
    lines = source.splitlines()
    findings, allowed = [], []
    for f in visitor.found:
        snippet = lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ""
        f = dataclasses.replace(f, snippet=snippet)
        suppressed = False
        for p in pragmas:
            if f.line in p["covers"] and f.rule in p["rules"]:
                p.setdefault("used", set()).add(f.rule)
                suppressed = True
        if suppressed:
            allowed.append(f)
        else:
            findings.append(f)
    # the checked allowlist: every pragma must suppress something —
    # per pragma, not per line, so a dead pragma adjacent to a live
    # same-rule one is still reported. ``absint-*`` rules belong to the
    # jaxpr interval prover (lint.absint): their staleness is judged
    # against traced programs, not this AST pass — see
    # ``absint.stale_absint_pragmas``, run by the same repo gates.
    for p in pragmas:
        stale = {
            r for r in p["rules"] - p.get("used", set())
            if not r.startswith("absint-")
        }
        if not stale:
            continue
        findings.append(
            Finding(
                rule="unused-allow",
                path=path,
                line=p["anchor"],
                col=0,
                message=(
                    f"pragma allows {sorted(stale)} but suppresses no "
                    f"such finding — stale allowlist entry"
                ),
                snippet=(
                    lines[p["anchor"] - 1].strip()
                    if p["anchor"] <= len(lines)
                    else ""
                ),
            )
        )
    return LintResult(findings=findings, allowed=allowed, n_files=1)


def _iter_py_files(paths) -> list:
    out = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py" and p.exists():
            out.append(p)
    return [p for p in out if "__pycache__" not in p.parts]


def lint_paths(paths, root: str | None = None) -> LintResult:
    """Lint every ``*.py`` under the given files/directories.

    The ``host-callback`` rule applies only to sim code — files under a
    ``madsim_tpu`` package directory; examples and tools run host-side
    by definition.
    """
    result = LintResult(findings=[], allowed=[], n_files=0)
    rootp = Path(root) if root else None
    for file in _iter_py_files(paths):
        rel = str(file.relative_to(rootp)) if rootp else str(file)
        sim_code = "madsim_tpu" in Path(rel).parts
        result.merge(
            lint_source(
                file.read_text(encoding="utf-8"), rel, sim_code=sim_code
            )
        )
    return result


def lint_repo(root: str | None = None) -> LintResult:
    """Lint the default surface (DEFAULT_PATHS) relative to ``root``
    (default: the repository containing this package)."""
    base = Path(root) if root else Path(__file__).resolve().parents[2]
    return lint_paths(
        [base / p for p in DEFAULT_PATHS if (base / p).exists()], root=base
    )
