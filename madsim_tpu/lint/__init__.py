"""madsim_tpu.lint — static determinism analysis.

The engine's whole value proposition is that every source of
nondeterminism is intercepted and every observability column is
write-only with respect to the trajectory. Both conventions were
previously enforced only dynamically — runtime stdlib interposition
(runtime/intercept.py) plus sampled bit-identity tests. This package
turns them into *checked invariants* at analysis time:

* :func:`check_noninterference` — traces the compiled step/run function
  of a (workload, config, build-flags) triple to a jaxpr, taints the
  derived-state inputs named by ``engine.derived_fields`` (``cov``,
  ``met``, the ``tl_*`` ring, history columns, the disk columns when
  the sync discipline is off) and propagates the taint through every
  equation — including ``scan``/``cond``/``while`` bodies and ``pjit``
  sub-jaxprs — to prove no data path reaches a core ``SimState`` column
  or the trace fold. The report is machine-readable: the isolation
  frontier per equation, and for any leak the offending equation chain
  plus the source/destination column names (the same names
  ``obs.explain`` prints).
* :func:`check_ranges` (lint.absint) — a forward interval abstract
  interpreter over the same jaxprs: per-var integer ranges seeded from
  the SimState column contracts (``engine.column_contracts``), walked
  through scan/while fixpoints with widening. Two provers ride the
  walk: overflow certification (no signed add/sub/mul on a time- or
  counter-tainted value may exceed its dtype within the declared
  horizon — the time32 wraparound bug class) and threefry lane
  disjointness (every draw site's (purpose, counter) operands resolved
  against the structured ``engine.rng.PURPOSE_LANES`` registry, all
  live lanes pairwise disjoint — the correlated-streams bug class).
  Findings honor the same checked ``# lint: allow(absint-*)`` pragma
  allowlist; ``absint_matrix`` sweeps the recorded-model x lowering
  matrix.
* :func:`lint_paths` / :func:`lint_repo` — an AST linter over sim code
  flagging intercept-bypassing calls (wall clocks, ambient entropy,
  ``uuid``, un-threefry'd ``np.random``), unordered-set iteration in
  ordering-sensitive positions, ``id()``/``hash()`` in branch
  conditions, and host callbacks inside sim code. Intentional
  real-mode sites carry a ``# lint: allow(<rule>)`` pragma; the
  allowlist is *checked* — a pragma that suppresses nothing is itself
  a finding (``unused-allow``).

``make lint`` (or ``python -m madsim_tpu.lint``) runs both and fails
on any new finding; ``tools/lint_soak.py`` runs the full model×config
jaxpr matrix.
"""

from .taint import TaintEqn, TaintResult, analyze_jaxpr  # noqa: F401
from .absint import (  # noqa: F401
    ABSINT_AXES,
    AbsintReport,
    absint_matrix,
    absint_model_matrix,
    absint_pragma_inventory,
    analyze_intervals,
    check_lane_sites,
    check_ranges,
    plant_lane_collision,
    plant_time32_sentinel_decay,
    run_mutant_controls,
    stale_absint_pragmas,
)
from .noninterference import (  # noqa: F401
    CAMPAIGN_AXES,
    CHECK_AXES,
    FLIGHT_AXES,
    NonInterferenceReport,
    check_matrix,
    check_noninterference,
    model_matrix,
    plant_met_leak,
)
from .rules import (  # noqa: F401
    DEFAULT_PATHS,
    Finding,
    LintResult,
    RULES,
    lint_paths,
    lint_repo,
    lint_source,
)

__all__ = [
    "TaintEqn",
    "TaintResult",
    "analyze_jaxpr",
    "ABSINT_AXES",
    "AbsintReport",
    "absint_matrix",
    "absint_model_matrix",
    "absint_pragma_inventory",
    "analyze_intervals",
    "check_lane_sites",
    "check_ranges",
    "plant_lane_collision",
    "plant_time32_sentinel_decay",
    "run_mutant_controls",
    "stale_absint_pragmas",
    "CAMPAIGN_AXES",
    "CHECK_AXES",
    "FLIGHT_AXES",
    "NonInterferenceReport",
    "check_matrix",
    "check_noninterference",
    "model_matrix",
    "plant_met_leak",
    "DEFAULT_PATHS",
    "Finding",
    "LintResult",
    "RULES",
    "lint_paths",
    "lint_repo",
    "lint_source",
]
