"""``python -m madsim_tpu.lint`` — the ``make lint`` entry point.

Runs the repo-wide nondeterminism-leak linter (fails on any finding)
and, with ``--jaxpr``, a non-interference smoke over a small slice of
the model matrix (the full matrix lives in tools/lint_soak.py). Exit
status 0 = clean, 1 = findings, the usual linter contract.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m madsim_tpu.lint",
        description="static determinism analysis (madsim_tpu.lint)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/directories to lint (default: the repo surface)",
    )
    ap.add_argument(
        "--jaxpr",
        action="store_true",
        help="also run the non-interference smoke (raft + raftlog/durable)",
    )
    ap.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    ap.add_argument(
        "--show-allowed",
        action="store_true",
        help="print the checked allowlist (pragma inventory)",
    )
    args = ap.parse_args(argv)

    from .rules import lint_paths, lint_repo

    result = lint_paths(args.paths) if args.paths else lint_repo()

    reports = []
    if args.jaxpr:
        from .noninterference import (
            BUILD_AXES,
            CHECK_AXES,
            LAYOUT_AXES,
            check_matrix,
            model_matrix,
        )

        want = ("raft/record", "raftlog/durable", "kvchaos/army")
        models = [m for m in model_matrix() if m[0] in want]
        if len(models) != len(want):
            # fail LOUDLY on tag drift: a silent miss would either
            # shrink the smoke or (via the empty-filter fallback) trace
            # the full model matrix inside the tier-1 budget
            raise SystemExit(
                f"lint --jaxpr: expected tags {want} in model_matrix(), "
                f"found {[m[0] for m in models]} — update the smoke "
                f"filter to match models/*.py lint_entries()"
            )
        # the same 'all' axis the soak matrix certifies — a new build
        # flag added there is automatically smoked here too — over
        # every lowering pair (scatter/int64, dense, time32): the TPU
        # runs exactly the dense/time32 programs the historical smoke
        # never traced
        reports = check_matrix(
            models, {"all": BUILD_AXES["all"]}, layouts=LAYOUT_AXES
        )
        # the device-verification boundary smoke (ISSUE 14): the
        # history-recording models with the check.device detector
        # kernels traced WITH the sim through the shard_map boundary —
        # taint set unchanged, verdict output only, no callback prims
        # (the full matrix row runs in tools/lint_soak.py)
        check_models = [m for m in models if m[0] in ("raft/record",)]
        reports += check_matrix(
            check_models, CHECK_AXES, entry="sharded_run"
        )

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in result.findings],
                    "allowed": [f.to_dict() for f in result.allowed],
                    "n_files": result.n_files,
                    "noninterference": [r.to_dict() for r in reports],
                },
                sort_keys=True,
            )
        )
    else:
        for f in result.findings:
            print(str(f))
            if f.snippet:
                print(f"    {f.snippet}")
        if args.show_allowed:
            for f in result.allowed:
                print(f"ALLOWED {f}")
        for r in reports:
            print(r.summary())
        print(
            f"lint: {result.n_files} files, {len(result.findings)} "
            f"finding(s), {len(result.allowed)} allowlisted site(s)"
            + (f", {len(reports)} non-interference proofs" if reports else "")
        )

    bad = bool(result.findings) or any(not r.ok for r in reports)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
