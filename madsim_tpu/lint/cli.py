"""``python -m madsim_tpu.lint`` — the ``make lint`` entry point.

Runs the repo-wide nondeterminism-leak linter (fails on any finding)
and, with ``--jaxpr``, a non-interference smoke over a small slice of
the model matrix; with ``--absint``, the interval-prover smoke
(overflow + lane disjointness on one model across the lowering sweep,
plus the absint pragma staleness check). The full matrices live in
tools/lint_soak.py and tools/absint_soak.py. Exit status 0 = clean,
1 = findings, the usual linter contract.

``--format json`` emits one machine-readable object (findings, the
allowlist inventory, every proof report) for CI gating; ``--json`` is
the legacy spelling of the same thing.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m madsim_tpu.lint",
        description="static determinism analysis (madsim_tpu.lint)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/directories to lint (default: the repo surface)",
    )
    ap.add_argument(
        "--jaxpr",
        action="store_true",
        help="also run the non-interference smoke (raft + raftlog/durable)",
    )
    ap.add_argument(
        "--absint",
        action="store_true",
        help=(
            "also run the interval-prover smoke: overflow + threefry-lane "
            "proofs on raft/record across the lowering sweep, plus the "
            "absint pragma staleness check"
        ),
    )
    ap.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format (json = one machine-readable object for CI)",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="legacy alias for --format json",
    )
    ap.add_argument(
        "--show-allowed",
        action="store_true",
        help="print the checked allowlist (pragma inventory)",
    )
    args = ap.parse_args(argv)
    as_json = args.json or args.format == "json"

    from .rules import lint_paths, lint_repo

    result = lint_paths(args.paths) if args.paths else lint_repo()

    reports = []
    if args.jaxpr:
        from .noninterference import (
            BUILD_AXES,
            CHECK_AXES,
            LAYOUT_AXES,
            check_matrix,
            model_matrix,
        )

        want = ("raft/record", "raftlog/durable", "kvchaos/army")
        models = [m for m in model_matrix() if m[0] in want]
        if len(models) != len(want):
            # fail LOUDLY on tag drift: a silent miss would either
            # shrink the smoke or (via the empty-filter fallback) trace
            # the full model matrix inside the tier-1 budget
            raise SystemExit(
                f"lint --jaxpr: expected tags {want} in model_matrix(), "
                f"found {[m[0] for m in models]} — update the smoke "
                f"filter to match models/*.py lint_entries()"
            )
        # the same 'all' axis the soak matrix certifies — a new build
        # flag added there is automatically smoked here too — over
        # every lowering pair (scatter/int64, dense, time32): the TPU
        # runs exactly the dense/time32 programs the historical smoke
        # never traced
        reports = check_matrix(
            models, {"all": BUILD_AXES["all"]}, layouts=LAYOUT_AXES
        )
        # the device-verification boundary smoke (ISSUE 14): the
        # history-recording models with the check.device detector
        # kernels traced WITH the sim through the shard_map boundary —
        # taint set unchanged, verdict output only, no callback prims
        # (the full matrix row runs in tools/lint_soak.py)
        check_models = [m for m in models if m[0] in ("raft/record",)]
        reports += check_matrix(
            check_models, CHECK_AXES, entry="sharded_run"
        )

    absint_reports = []
    absint_stale = []
    if args.absint:
        from .absint import (
            ABSINT_AXES,
            absint_matrix,
            absint_model_matrix,
            stale_absint_pragmas,
        )
        from .noninterference import LAYOUT_AXES as _LAX

        models = [m for m in absint_model_matrix() if m[0] == "raft/record"]
        if not models:
            raise SystemExit(
                "lint --absint: tag raft/record missing from "
                "absint_model_matrix() — update the smoke filter to "
                "match models/*.py absint_entries()"
            )
        # one model, the FULL lowering sweep: the time32 and
        # readiness-indexed rows are what exercise the stale-slot
        # rebase pragmas, so the staleness check below stays honest
        absint_reports = absint_matrix(
            models, {"all": ABSINT_AXES["all"], "dup": ABSINT_AXES["dup"]},
            layouts=_LAX,
        )
        used = set()
        for r in absint_reports:
            used.update(tuple(u) for u in r.used_pragmas)
        # staleness at smoke scale is judged over the files the smoke
        # provably traced: engine/core.py (every step build walks it)
        # plus any file a used pragma named. A legitimate pragma at a
        # site only the full matrix exercises (another model's path)
        # must not fail every `make lint` — tools/absint_soak.py
        # judges the whole surface against the whole matrix. core.py
        # stays in the set even with ZERO used pragmas, so an
        # allowlist that has gone entirely stale still fails here.
        from pathlib import Path as _Path

        from .absint import _REPO_ROOT as _AROOT

        smoke_files = sorted(
            {u[0] for u in used} | {"madsim_tpu/engine/core.py"}
        )
        absint_stale = stale_absint_pragmas(
            used, paths=[_Path(_AROOT) / f for f in smoke_files],
            root=_AROOT,
        )

    if as_json:
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in result.findings],
                    "allowed": [f.to_dict() for f in result.allowed],
                    "n_files": result.n_files,
                    "noninterference": [r.to_dict() for r in reports],
                    "absint": [r.to_dict() for r in absint_reports],
                    "absint_stale_pragmas": absint_stale,
                },
                sort_keys=True,
            )
        )
    else:
        for f in result.findings:
            print(str(f))
            if f.snippet:
                print(f"    {f.snippet}")
        if args.show_allowed:
            for f in result.allowed:
                print(f"ALLOWED {f}")
        for r in reports:
            print(r.summary())
        for r in absint_reports:
            print(r.summary())
        for s in absint_stale:
            print(
                f"{s['file']}:{s['line']}: [unused-allow] {s['message']}"
            )
        print(
            f"lint: {result.n_files} files, {len(result.findings)} "
            f"finding(s), {len(result.allowed)} allowlisted site(s)"
            + (f", {len(reports)} non-interference proofs" if reports else "")
            + (
                f", {len(absint_reports)} range proofs"
                if absint_reports else ""
            )
        )

    bad = (
        bool(result.findings)
        or any(not r.ok for r in reports)
        or any(not r.ok for r in absint_reports)
        or bool(absint_stale)
    )
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
