"""madsim_tpu — a TPU-native deterministic-simulation-testing framework.

A brand-new framework with the capabilities of the reference
(skyzh/madsim, mounted at /root/reference): a deterministic async runtime
for distributed systems that mocks scheduling, time, randomness, network
and filesystem behind one seeded RNG, amplifies chaos (random
interleavings, latency, loss, partitions, node kill/restart), and
reproduces any failure exactly from its seed — plus simulated gRPC-, etcd-
and Kafka-style services and a real backend for production.

Unlike the reference (one OS thread per seeded run), the TPU-first core in
:mod:`madsim_tpu.engine` advances thousands of seeded simulation instances
in lockstep as one XLA-compiled step function — ``vmap`` over a seed axis,
``shard_map`` over TPU meshes — with counter-based RNG draws replacing the
serial RNG stream and a C++ oracle guaranteeing bit-identical traces.

Layout (mirrors SURVEY.md §7):
  * ``runtime/`` — single-seed deterministic async runtime (madsim core
    parity: executor, virtual time, seeded RNG, chaos, test harness).
  * ``net/`` — simulated network: NetSim, Endpoint, RPC, TCP/UDP.
  * ``fs.py`` — simulated per-node filesystem.
  * ``sync.py`` — deterministic async sync primitives.
  * ``services/`` — gRPC-like / etcd-like / kafka-like simulators.
  * ``engine/`` — batched JAX discrete-event core (the TPU path).
  * ``models/`` — batched workloads (ping-pong, broadcast, raft election).
  * ``parallel/`` — seed-axis sharding over device meshes.
  * ``std/`` — real-world backends (production path).
"""

from .runtime import (  # noqa: F401
    Builder,
    Config,
    DeadlockError,
    DeterminismError,
    Elapsed,
    FallibleTask,
    Handle,
    Instant,
    Interval,
    JoinError,
    JoinHandle,
    NetConfig,
    NodeBuilder,
    NodeHandle,
    Runtime,
    SimContextFilter,
    SimFormatter,
    SimFuture,
    Simulator,
    SystemTime,
    TimeLimitError,
    available_parallelism,
    init_logger,
    interval,
    join_all,
    main,
    node,
    now,
    now_ns,
    random,
    select,
    simulator,
    sleep,
    sleep_until,
    span,
    spawn,
    spawn_blocking,
    spawn_local,
    test,
    thread_rng,
    timeout,
    yield_now,
)

# Importing the device-simulator packages registers them as default
# simulators on every Runtime (reference runtime/mod.rs:62-64).
from . import fs  # noqa: E402,F401
from . import net  # noqa: E402,F401
from . import sync  # noqa: E402,F401
from .fs import FsSim  # noqa: F401
from .net import Endpoint, NetSim, TcpListener, TcpStream, UdpSocket  # noqa: F401

__version__ = "0.1.0"
