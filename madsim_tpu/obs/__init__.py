"""madsim_tpu.obs — observability for the batched engine.

The reference threads ``tracing`` spans through every node, task and
network op (SURVEY.md §5); a 65k-seed batched sweep compresses all of
that into a trace *hash* and a violation count. This package is the
flight recorder that closes the gap, built on the engine's
derived-state-only tap discipline (coverage proved the pattern: off =
zero-size arrays and bit-identical values):

* **fleet metrics** (obs/metrics.py) — per-seed MET_* counters folded
  in the step (``metrics=True``), reduced ON DEVICE to fleet totals,
  log2 histograms and the halt-reason distribution; a sweep's shape
  without per-seed transfer.
* **timeline capture** (obs/timeline.py) — an opt-in per-seed event
  ring (``timeline_cap=T``) recording the dispatched-event stream
  (payload words included), decoded host-side against the workload's
  kind table; the decoded timeline refolds to the certified trace hash.
* **Perfetto export** (obs/perfetto.py) — ``to_perfetto`` renders a
  captured timeline as trace-event JSON: per-node tracks, message flow
  arrows, chaos-plan spans — a shrunk violation opens as a readable
  timeline in ui.perfetto.dev.
* **campaign telemetry** (obs/telemetry.py) — ``JsonlSink`` structured
  progress for exploration campaigns and soaks, and ``explain``: the
  per-violation narrative interleaving timeline, history ops and the
  checker verdict.
* **causal provenance** (obs/causal.py) — under the engine's
  ``causal=True`` axis every ring row carries exact lineage (dispatch
  seq, emitting-dispatch parent, per-node Lamport clock);
  ``causal_slice`` computes the backward happens-before **cone** of a
  violating record (everything outside it is provably concurrent),
  ``explain(causal=True)`` narrates the cone instead of the whole
  stream, ``explain_diff(causal=True)`` names the first divergent
  causal edge, Perfetto arrows become exact, and ``fleet_reduce``
  folds per-seed depth/width stats on device.
* **tail latency** (obs/latency.py) — device-side reduction of the
  engine's per-seed log-linear latency sketches (``LatencySpec`` +
  ``chaos.ClientArmy`` open-loop load): per-window p50/p90/p99/p999 +
  max for the whole fleet with only (P, B)-shaped transfer, exactly
  mergeable across shards (``parallel.merge_latency``).
* **program profiling** (obs/prof.py) — trace/lower/compile/execute
  wall attribution, retrace counting per cache key, HLO cost analysis
  and device-memory accounting for every compiled program the search
  stack dispatches (``ProgramProfiler`` + the ``AotProgram`` wrapper
  the engine/explore program caches build through).
* **campaign flight recorder** (obs/flight.py) — ``FlightRecorder``
  wraps any telemetry sink with heartbeats (gens/s, ETA, HBM),
  compile events and a closing program-table summary;
  ``campaign_perfetto`` renders a campaign's JSONL as a Perfetto
  timeline (generation spans + counter tracks), the campaign-scale
  complement of the per-seed ``to_perfetto``.

Evidence artifacts: ``tools/obs_soak.py`` (OBS_r09.txt),
``tools/latency_soak.py`` (LATENCY_r12.txt),
``tools/flight_soak.py`` (FLIGHT_r08.txt).
"""

from ..engine.core import (  # noqa: F401 — the slot layout obs consumes
    HALT_DONE,
    HALT_IDLE,
    HALT_RUNNING,
    HALT_TIME_LIMIT,
    MET_HALT_CODE,
    METRIC_NAMES,
    N_METRICS,
)
from ..engine.core import (  # noqa: F401 — the ladder obs consumes
    LAT_EDGES_NS,
    N_LAT_BUCKETS,
    LatencySpec,
)
from .latency import (  # noqa: F401
    FleetLatency,
    fleet_latency,
    hist_quantile_bucket,
    latency_reduce,
)
from .flight import (  # noqa: F401
    FlightRecorder,
    campaign_perfetto,
    write_campaign_perfetto,
)
from .causal import (  # noqa: F401
    CausalCone,
    causal_slice,
    derive_parents,
    format_cone,
    parent_class,
    rederive,
)
from .metrics import FleetMetrics, fleet_metrics, fleet_reduce  # noqa: F401
from .perfetto import to_perfetto, write_perfetto  # noqa: F401
from .prof import (  # noqa: F401
    AotProgram,
    ProgramProfiler,
    device_memory,
)
from .telemetry import JsonlSink, explain, explain_diff  # noqa: F401
from .timeline import (  # noqa: F401
    decode_timeline,
    refold_timeline,
    timeline_counts,
)

__all__ = [
    "AotProgram",
    "CausalCone",
    "FleetLatency",
    "FleetMetrics",
    "FlightRecorder",
    "JsonlSink",
    "LAT_EDGES_NS",
    "LatencySpec",
    "METRIC_NAMES",
    "N_LAT_BUCKETS",
    "N_METRICS",
    "ProgramProfiler",
    "campaign_perfetto",
    "causal_slice",
    "decode_timeline",
    "derive_parents",
    "format_cone",
    "parent_class",
    "rederive",
    "device_memory",
    "explain",
    "explain_diff",
    "fleet_latency",
    "fleet_metrics",
    "fleet_reduce",
    "hist_quantile_bucket",
    "latency_reduce",
    "refold_timeline",
    "timeline_counts",
    "to_perfetto",
    "write_campaign_perfetto",
    "write_perfetto",
]
