"""Per-seed timeline decode: the captured event ring as readable events.

The engine's timeline ring (engine/core.py, ``timeline_cap=T``) records
the dispatched-event stream — exactly the (time, kind, node, src, args)
tuples the trace hash folds — as fixed-size per-seed columns. This
module decodes one seed's ring host-side against the workload's kind
table into the same :class:`~madsim_tpu.engine.replay.ReplayEvent` rows
the C++-oracle replay produces, so everything downstream (text
timelines via ``engine.replay.format_timeline``, Perfetto export via
``obs.to_perfetto``, the ``obs.explain`` narrative) is shared between
the two capture paths.

``refold`` recomputes the certified trace hash from a decoded timeline
(payload words are captured too): the test gate proving the captured
story and the bit-identical evidence are the same events — the
engine.replay refold contract, now available without the oracle.
"""

from __future__ import annotations

import numpy as np

from ..engine.core import Workload
from ..engine.replay import ReplayEvent
from ..engine.replay import refold as _replay_refold

__all__ = ["decode_timeline", "refold_timeline", "timeline_counts"]


def _get(view, name: str):
    """Field access across the shapes a timeline travels in: a
    search_seeds view dict, a SearchReport.timeline namespace, or a raw
    batched SimState."""
    if isinstance(view, dict):
        return view[name]
    return getattr(view, name)


def timeline_counts(view) -> tuple:
    """(tl_count, tl_drop) numpy arrays over the seed axis."""
    return (
        np.asarray(_get(view, "tl_count")),
        np.asarray(_get(view, "tl_drop")),
    )


def decode_timeline(view, wl: Workload | None = None, seed: int = 0) -> list:
    """Decode seed-row ``seed``'s captured ring into ReplayEvent rows.

    ``view`` is anything carrying the ``tl_*`` columns with a leading
    seed axis: the final batched ``SimState``, a ``search_seeds`` state
    view, or ``SearchReport.timeline``. ``wl`` is only consulted for
    arg width (rows keep the captured width without it).
    """
    count = int(np.asarray(_get(view, "tl_count"))[seed])
    t = np.asarray(_get(view, "tl_t"))[seed]
    meta = np.asarray(_get(view, "tl_meta"))[seed].astype(np.uint32)
    args = np.asarray(_get(view, "tl_args"))[seed]
    pay = np.asarray(_get(view, "tl_pay"))[seed]
    if t.shape[0] == 0:
        raise ValueError(
            "state carries no timeline columns — run with timeline_cap > 0"
        )
    # emit-time sidecar: rings captured before the sidecar existed (or
    # views that dropped the column) decode with emit_ns = -1
    try:
        emit = np.asarray(_get(view, "tl_emit"))[seed]
        if emit.shape[0] == 0:
            emit = None
    except (KeyError, AttributeError):
        emit = None
    # causal-provenance columns (causal=True rings): same fallback rule
    # — pre-causal captures decode with the "not captured" defaults, so
    # every consumer (Perfetto arrows, obs.causal) must handle seq=-1
    try:
        seq = np.asarray(_get(view, "tl_seq"))[seed]
        parent = np.asarray(_get(view, "tl_parent"))[seed]
        lam = np.asarray(_get(view, "tl_lam"))[seed]
        if seq.shape[0] == 0:
            seq = parent = lam = None
    except (KeyError, AttributeError):
        seq = parent = lam = None
    events = []
    for i in range(count):
        m = int(meta[i])
        events.append(
            ReplayEvent(
                time_ns=int(t[i]),
                kind=m & 0xFF,
                node=((m >> 8) & 0xFF) - 1,
                src=((m >> 16) & 0xFF) - 1,
                args=tuple(int(x) for x in args[i]),
                pay=tuple(int(x) for x in pay[i]),
                emit_ns=int(emit[i]) if emit is not None else -1,
                seq=int(seq[i]) if seq is not None else -1,
                parent=int(parent[i]) if parent is not None else -1,
                lam=int(lam[i]) if lam is not None else 0,
            )
        )
    return events


def refold_timeline(events, wl: Workload) -> int:
    """Recompute the trace hash from a decoded timeline.

    Must equal the run's ``SimState.trace`` for the same seed whenever
    the ring did not overflow (``tl_drop == 0`` — a truncated stream
    can only refold a prefix). The ring captures payload words, so the
    certificate covers payload workloads (kvchaos, raftlog) too.
    """
    # the replay refold reads four arg words; pad captured rows (the
    # engine folds only args_words, missing high words are zero)
    padded = [
        ReplayEvent(
            time_ns=e.time_ns, kind=e.kind, node=e.node, src=e.src,
            args=tuple(e.args) + (0,) * (4 - len(e.args)), pay=e.pay,
        )
        for e in events
    ]
    return _replay_refold(padded, wl)
