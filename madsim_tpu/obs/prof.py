"""Program profiler: per-program trace/lower/compile/execute attribution.

A campaign's wall clock hides four very different costs inside every
"dispatch": Python tracing, StableHLO lowering, XLA compilation, and
the actual device execution. jit reports none of them — worse, a fresh
closure per call silently re-pays the first three (the ``run_device``
re-trace cost ROADMAP item 1 flagged). This module makes the split a
measured quantity:

* :class:`AotProgram` — a jit-compatible callable built through the
  explicit ``jax.stages`` pipeline (``jit(fn).trace -> .lower ->
  .compile``), executing through the compiled artifact. Every build is
  timed per phase and counted, so *retraces per cache key* is a
  counter, not a guess; the most recent call's build share is exposed
  as :attr:`AotProgram.last_build_s` so drivers can split
  ``compile_wall_s`` out of their dispatch telemetry. The compiled
  program's HLO cost analysis (flops, bytes accessed) and memory
  footprint (argument/output/temp bytes) are recorded at build time.
  Values are bit-identical to ``jax.jit(fn)(*args)`` — the same XLA
  program runs either way; only the host-side bookkeeping differs.
* :class:`ProgramProfiler` — the session registry: enable one
  (:func:`enable` / :func:`profiled`) and every ``AotProgram`` build
  and execution in the process reports into it, giving the
  campaign-wide program table (``report()``) and the retrace
  certificate (``retraces()``). With no profiler active the only
  overhead is a None check per call.
* :func:`device_memory` — the live-buffer footprint: every live jax
  array summed (plus the backend allocator's ``memory_stats`` where
  the platform provides one — TPU/GPU HBM; CPU returns only the
  live-array view).

Everything here is host-side bookkeeping over wall clocks and compiled
artifacts; nothing enters traced code (the lint matrix pins this — see
``lint.noninterference.FLIGHT_AXES``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from contextlib import contextmanager

import jax

__all__ = [
    "AotProgram",
    "ProgramProfiler",
    "ProgramRecord",
    "current",
    "device_memory",
    "disable",
    "enable",
    "profiled",
    "program_cost",
]


def digest(key) -> str:
    """Short stable digest of a cache key (any repr-able object)."""
    return hashlib.sha1(repr(key).encode()).hexdigest()[:12]


def _signature(args) -> tuple:
    """Structure + aval signature of a call's arguments: the identity a
    compiled executable is pinned to (jit's retrace key, minus
    shardings — a sharding drift surfaces as an executable rejection
    and is handled by a counted rebuild)."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (
        treedef,
        tuple(
            (getattr(a, "shape", None), str(getattr(a, "dtype", type(a))))
            for a in leaves
        ),
    )


def program_cost(compiled) -> dict:
    """HLO cost analysis + memory footprint of a compiled program.

    Returns whatever the backend exposes: ``flops`` and
    ``bytes_accessed`` from XLA's cost analysis, and the
    argument/output/temp/code byte sizes from the compiled memory
    stats (the per-program device-memory budget — on TPU this is the
    HBM the program itself pins, distinct from the live-buffer pool
    :func:`device_memory` reports)."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            out["flops"] = float(ca.get("flops", 0.0))
            out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    except Exception:
        pass
    try:
        ms = compiled.memory_analysis()
        if ms is not None:
            out["arg_bytes"] = int(ms.argument_size_in_bytes)
            out["out_bytes"] = int(ms.output_size_in_bytes)
            out["temp_bytes"] = int(ms.temp_size_in_bytes)
            out["code_bytes"] = int(ms.generated_code_size_in_bytes)
    except Exception:
        pass
    return out


def device_memory() -> dict:
    """Live device-memory accounting: every live jax array summed.

    ``live_buffer_bytes`` is the logical byte count of all live arrays
    (a replicated array counts once); ``allocator_bytes_in_use`` joins
    when the backend exposes per-device ``memory_stats`` (TPU/GPU HBM
    allocators do; CPU does not)."""
    arrs = jax.live_arrays()
    total = 0
    for a in arrs:
        try:
            total += a.nbytes
        except Exception:
            pass
    out = {"live_buffers": len(arrs), "live_buffer_bytes": int(total)}
    in_use = 0
    have = False
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_in_use" in stats:
            in_use += int(stats["bytes_in_use"])
            have = True
    if have:
        out["allocator_bytes_in_use"] = in_use
    return out


@dataclasses.dataclass
class ProgramRecord:
    """One program's accumulated profile (per (name, key))."""

    name: str
    key: str  # cache-key digest — same key twice means a RETRACE
    traces: int = 0  # trace+lower+compile events (the retrace counter)
    calls: int = 0
    trace_wall_s: float = 0.0
    lower_wall_s: float = 0.0
    compile_wall_s: float = 0.0
    execute_wall_s: float = 0.0
    # last build's HLO cost analysis + memory footprint (program_cost)
    flops: float = 0.0
    bytes_accessed: float = 0.0
    arg_bytes: int = 0
    out_bytes: int = 0
    temp_bytes: int = 0
    code_bytes: int = 0

    @property
    def build_wall_s(self) -> float:
        return self.trace_wall_s + self.lower_wall_s + self.compile_wall_s

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class ProgramProfiler:
    """Session-wide program registry: builds and executions of every
    :class:`AotProgram` report here while the profiler is active
    (:func:`enable` / :func:`profiled`).

    ``programs`` maps (name, key-digest) to :class:`ProgramRecord`;
    ``pop_events()`` drains the build-event stream (one dict per
    trace/lower/compile, in build order) — the flight recorder turns
    these into ``compile`` telemetry records and Perfetto instants.
    """

    def __init__(self):
        self.programs: dict = {}
        self.events: list = []

    def record(self, name: str, key: str) -> ProgramRecord:
        rec = self.programs.get((name, key))
        if rec is None:
            rec = self.programs[(name, key)] = ProgramRecord(name, key)
        return rec

    def note_build(self, name, key, trace_s, lower_s, compile_s, cost):
        rec = self.record(name, key)
        rec.traces += 1
        rec.trace_wall_s += trace_s
        rec.lower_wall_s += lower_s
        rec.compile_wall_s += compile_s
        for f in ("flops", "bytes_accessed", "arg_bytes", "out_bytes",
                  "temp_bytes", "code_bytes"):
            if f in cost:
                setattr(rec, f, cost[f])
        self.events.append({
            "program": name, "key": key, "retrace": rec.traces,
            "trace_s": round(trace_s, 4), "lower_s": round(lower_s, 4),
            "compile_s": round(compile_s, 4), **cost,
        })

    def note_execute(self, name, key, seconds):
        rec = self.record(name, key)
        rec.calls += 1
        rec.execute_wall_s += seconds

    def pop_events(self) -> list:
        ev, self.events = self.events, []
        return ev

    def retraces(self, prefix: str = "") -> dict:
        """(name, key) -> trace count, optionally filtered by a name
        prefix — the retrace certificate reads this (== 1 per key)."""
        return {
            nk: rec.traces
            for nk, rec in sorted(self.programs.items())
            if nk[0].startswith(prefix)
        }

    def to_dicts(self) -> list:
        return [rec.to_dict() for _, rec in sorted(self.programs.items())]

    def report(self) -> str:
        """Text table of every profiled program (the artifact form)."""
        lines = [
            f"{'program':<28} {'key':<13} {'tr':>3} {'calls':>5} "
            f"{'trace_s':>8} {'lower_s':>8} {'compile_s':>9} {'exec_s':>8} "
            f"{'GFLOP':>8} {'MB_acc':>8} {'MB_tmp':>7}"
        ]
        for _, r in sorted(self.programs.items()):
            lines.append(
                f"{r.name:<28} {r.key:<13} {r.traces:>3} {r.calls:>5} "
                f"{r.trace_wall_s:>8.3f} {r.lower_wall_s:>8.3f} "
                f"{r.compile_wall_s:>9.3f} {r.execute_wall_s:>8.3f} "
                f"{r.flops / 1e9:>8.3f} {r.bytes_accessed / 1e6:>8.1f} "
                f"{r.temp_bytes / 1e6:>7.1f}"
            )
        return "\n".join(lines)


_ACTIVE: ProgramProfiler | None = None


def enable(profiler: ProgramProfiler | None = None) -> ProgramProfiler:
    """Install ``profiler`` (or a fresh one) as the session profiler."""
    global _ACTIVE
    _ACTIVE = profiler if profiler is not None else ProgramProfiler()
    return _ACTIVE


def disable() -> None:
    global _ACTIVE
    _ACTIVE = None


def current() -> ProgramProfiler | None:
    return _ACTIVE


@contextmanager
def profiled(profiler: ProgramProfiler | None = None):
    """Scope a profiler: ``with profiled() as p: ...; p.report()`` —
    restores whatever was active before on exit."""
    global _ACTIVE
    prev = _ACTIVE
    p = enable(profiler)
    try:
        yield p
    finally:
        _ACTIVE = prev


class AotProgram:
    """A to-be-jitted function, built through the explicit AOT pipeline.

    Call it exactly like ``jax.jit(fn)``. The first call per argument
    signature pays trace → lower → compile with each phase timed
    (:attr:`last_build_s` carries the most recent call's build share —
    0.0 on warm calls, so ``dispatch_wall - last_build_s`` is pure
    execution); later calls run the compiled executable directly.
    ``builds`` counts compilations over the program's lifetime — the
    retrace counter the generation-program caches are certified by.

    A sharding or aval drift on the inputs (the executable is pinned
    to what it compiled under; jit would silently recompile) triggers
    ONE counted rebuild and retries — visible in the profile instead
    of hidden in dispatch wall.
    """

    def __init__(self, name: str, key, fn):
        self.name = name
        self.key = digest(key)
        self._jit = jax.jit(fn)
        self._exes: dict = {}
        self.builds = 0
        self.trace_wall_s = 0.0
        self.lower_wall_s = 0.0
        self.compile_wall_s = 0.0
        self.last_build_s = 0.0
        self.cost: dict = {}

    def _build(self, sig, args):
        t0 = time.monotonic()  # lint: allow(wall-clock)
        traced = self._jit.trace(*args)
        t1 = time.monotonic()  # lint: allow(wall-clock)
        lowered = traced.lower()
        t2 = time.monotonic()  # lint: allow(wall-clock)
        exe = lowered.compile()
        t3 = time.monotonic()  # lint: allow(wall-clock)
        self._exes[sig] = exe
        self.builds += 1
        self.trace_wall_s += t1 - t0
        self.lower_wall_s += t2 - t1
        self.compile_wall_s += t3 - t2
        self.last_build_s += t3 - t0
        self.cost = program_cost(exe)
        if _ACTIVE is not None:
            _ACTIVE.note_build(
                self.name, self.key, t1 - t0, t2 - t1, t3 - t2, self.cost
            )
        return exe

    def __call__(self, *args):
        self.last_build_s = 0.0
        sig = _signature(args)
        exe = self._exes.get(sig)
        if exe is None:
            exe = self._build(sig, args)
        p = _ACTIVE
        if p is None:
            try:
                return exe(*args)
            except (TypeError, ValueError):
                exe = self._build(sig, args)
                return exe(*args)
        t0 = time.monotonic()  # lint: allow(wall-clock)
        try:
            out = exe(*args)
        except (TypeError, ValueError):
            exe = self._build(sig, args)
            t0 = time.monotonic()  # lint: allow(wall-clock)
            out = exe(*args)
        jax.block_until_ready(out)
        p.note_execute(
            self.name, self.key, time.monotonic() - t0  # lint: allow(wall-clock)
        )
        return out

    def call_async(self, *args):
        """``__call__`` without the profiler's completion barrier.

        The profiled ``__call__`` blocks on the outputs so
        ``execute_wall_s`` measures device time — which would serialize
        a pipelined schedule right back into the blocking one. This
        path ENQUEUES only (jax async dispatch; the caller owns the
        ``block_until_ready`` at its consume point): builds are still
        timed and retrace-counted identically, calls are still counted,
        but the profiler's per-call execute wall is recorded as the
        enqueue cost (~0), with the real device wall visible in the
        caller's queue/idle split instead.
        """
        self.last_build_s = 0.0
        sig = _signature(args)
        exe = self._exes.get(sig)
        if exe is None:
            exe = self._build(sig, args)
        t0 = time.monotonic()  # lint: allow(wall-clock)
        try:
            out = exe(*args)
        except (TypeError, ValueError):
            exe = self._build(sig, args)
            t0 = time.monotonic()  # lint: allow(wall-clock)
            out = exe(*args)
        if _ACTIVE is not None:
            _ACTIVE.note_execute(
                self.name, self.key,
                time.monotonic() - t0,  # lint: allow(wall-clock)
            )
        return out
