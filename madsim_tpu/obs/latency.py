"""Fleet tail-latency: device-side reduction of the engine's sketches.

The engine folds every completed client-army op into a per-seed
log-linear histogram (``SimState.lat_hist``, engine/core.py
``latency=LatencySpec(...)`` — the ladder lives in ``LAT_EDGES_NS``).
This module reduces the (S, P, B) sketch batch **on device** into the
fleet tail shape — per-window p50/p90/p99/p999 + max — so a 65k-seed
sweep reports its latency distribution without moving any per-seed
column to the host; only the (P, B)-shaped totals cross the transfer
boundary. The sketch is *exactly mergeable*: the fleet histogram equals
the histogram of the concatenated per-op latencies (the property that
matters from t-digest, bought here with a fixed ladder instead of
adaptive centroids so merging is integer addition and bit-exact).

Quantiles read off the ladder are exact to one bucket of rank error:
``quantile(q)`` returns the upper edge of the bucket the q-th completed
op falls in (~19% relative width). That is the resolution an SLO
statement needs; per-op ``lat_inv``/``lat_resp`` columns remain on the
state for forensics when exactness matters.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from ..engine.core import (
    LAT_EDGES_NS,
    N_LAT_BUCKETS,
    LatencySpec,
    lat_bucket_hi,
)

__all__ = [
    "FleetLatency",
    "fleet_latency",
    "latency_reduce",
    "hist_quantile_bucket",
]

_QUANTILES = (0.50, 0.90, 0.99, 0.999)


def hist_quantile_bucket(hist: np.ndarray, q: float) -> np.ndarray:
    """Bucket index holding the ``q``-quantile of a ladder histogram.

    ``hist`` is (..., N_LAT_BUCKETS); returns int64 bucket indices of
    the same leading shape (-1 where the histogram is empty). The rank
    convention is ``ceil(q * total)`` — the smallest bucket whose
    cumulative count reaches it — which is the one place the sketch,
    the SLO detector (check.slo_bounded) and the accuracy tests must
    agree, so they all call this function.
    """
    h = np.asarray(hist, np.int64)
    total = h.sum(axis=-1)
    rank = np.ceil(q * total).astype(np.int64).clip(min=1)
    cum = np.cumsum(h, axis=-1)
    idx = np.argmax(cum >= rank[..., None], axis=-1)
    return np.where(total > 0, idx, -1)


@dataclasses.dataclass(frozen=True)
class FleetLatency:
    """Fleet-level reduction of per-seed latency sketches.

    ``hist`` is the merged (P, B) ladder histogram over every seed's
    completed ops; ``completed`` the total op count folded into it.
    Quantile values are bucket **upper edges** (conservative for an SLO:
    the true quantile is at most the reported value's bucket width
    below it, never above).
    """

    n_seeds: int
    hist: np.ndarray  # (P, B) int64 merged ladder histogram
    completed: int  # total ops folded in
    dropped: int  # markers with out-of-range op ids (fleet sum; loud)
    phase_ns: int  # window width the sketches were cut with

    @property
    def phases(self) -> int:
        return int(self.hist.shape[0])

    def quantile(self, q: float, phase: int | None = None) -> int:
        """q-quantile latency in ns (bucket upper edge); ``phase=None``
        pools every window. -1 when no ops completed there."""
        h = self.hist.sum(axis=0) if phase is None else self.hist[phase]
        b = int(hist_quantile_bucket(h, q))
        return -1 if b < 0 else int(lat_bucket_hi(b))

    def max_ns(self, phase: int | None = None) -> int:
        """Upper edge of the highest occupied bucket (-1 when empty)."""
        h = self.hist.sum(axis=0) if phase is None else self.hist[phase]
        nz = np.nonzero(h)[0]
        return -1 if nz.size == 0 else int(lat_bucket_hi(int(nz[-1])))

    def format(self) -> str:
        """Text table of the fleet tail (the soak-artifact rendering)."""
        lines = [
            f"fleet latency over {self.n_seeds} seeds: "
            f"{self.completed} completed ops"
            + (f", {self.dropped} DROPPED marker(s)" if self.dropped else ""),
            f"  {'window':<10} {'ops':>9} {'p50':>9} {'p90':>9} "
            f"{'p99':>9} {'p999':>9} {'max':>9}",
        ]

        def row(label, h):
            n = int(h.sum())
            cells = []
            for q in _QUANTILES:
                b = int(hist_quantile_bucket(h, q))
                cells.append(
                    "-" if b < 0 else f"{int(lat_bucket_hi(b)) / 1e6:.2f}ms"
                )
            nz = np.nonzero(h)[0]
            mx = "-" if nz.size == 0 else f"{int(lat_bucket_hi(int(nz[-1]))) / 1e6:.2f}ms"
            lines.append(
                f"  {label:<10} {n:>9} " + " ".join(f"{c:>9}" for c in cells)
                + f" {mx:>9}"
            )

        for p in range(self.phases):
            t0 = p * self.phase_ns / 1e6
            row(f"[{t0:.0f}ms..]", self.hist[p])
        if self.phases > 1:
            row("all", self.hist.sum(axis=0))
        return "\n".join(lines)


@jax.jit
def _reduce(lat_hist, lat_count, lat_drop):
    """(S, P, B) int32 -> merged totals, entirely on device."""
    return (
        jnp.sum(lat_hist.astype(jnp.int64), axis=0),
        jnp.sum(lat_count.astype(jnp.int64)),
        jnp.sum(lat_drop.astype(jnp.int64)),
    )


def latency_reduce(
    lat_hist, lat_count=None, lat_drop=None, *, phase_ns: int
) -> FleetLatency:
    """Reduce an (S, P, B) per-seed sketch batch to the fleet tail.

    ``lat_hist`` may be the device-resident ``SimState.lat_hist`` batch
    (the reduction runs jitted on device and only the (P, B) totals
    transfer) or a host copy (``SearchReport.lat_hist``) — same values
    either way, because the sketch merge is integer addition.

    ``phase_ns`` is REQUIRED and must be the ``LatencySpec.phase_ns``
    the sweep ran with: the sketches were cut into windows of that
    width, and a defaulted value would silently mislabel every window
    in the report (pass ``spec.phase_ns``).
    """
    hh = jnp.asarray(lat_hist)
    if hh.ndim != 3 or hh.shape[2] != N_LAT_BUCKETS:
        raise ValueError(
            f"lat_hist must be (S, P, {N_LAT_BUCKETS}) sketch columns, "
            f"got shape {hh.shape}"
        )
    s = hh.shape[0]
    cnt = jnp.zeros((s,), jnp.int32) if lat_count is None else jnp.asarray(lat_count)
    drp = jnp.zeros((s,), jnp.int32) if lat_drop is None else jnp.asarray(lat_drop)
    hist, completed, dropped = _reduce(hh, cnt, drp)
    hist = np.asarray(hist)
    return FleetLatency(
        n_seeds=int(s),
        hist=hist,
        completed=(
            int(completed) if lat_count is not None else int(hist.sum())
        ),
        dropped=int(dropped),
        phase_ns=int(phase_ns),
    )


# compiled-run cache, the engine.search discipline: repeated tail sweeps
# over one (workload, config, budget, spec) reuse the XLA program
_RUN_CACHE: dict = {}


def fleet_latency(
    wl,
    cfg,
    spec: LatencySpec,
    n_seeds: int = 4096,
    max_steps: int = 1000,
    seed_base: int = 0,
    seeds=None,
    plan=None,
    layout: str | None = None,
) -> FleetLatency:
    """The tail-only sweep: run ``n_seeds`` schedules and return the
    fleet latency reduction — nothing per-seed ever reaches the host.

    The latency analog of ``obs.fleet_metrics``: the final batched
    state stays on device, ``latency_reduce`` consumes its sketch
    columns jitted, and only the (P, B) totals transfer. ``plan``
    follows the ``search_seeds`` contract — for a tail profile it
    normally composes a ``chaos.ClientArmy`` (the load) with fault
    specs (the chaos the tail is measured under).
    """
    from ..engine.core import make_init, make_run_while

    if seeds is None:
        seeds = np.arange(seed_base, seed_base + n_seeds, dtype=np.uint64)
    else:
        seeds = np.asarray(seeds, np.uint64)
    plan_slots = int(plan.slots) if plan is not None else 0
    dup = bool(plan.uses_dup()) if plan is not None else False
    key = (id(wl), cfg.hash(), max_steps, layout, plan_slots, dup, spec)
    if key not in _RUN_CACHE:
        _RUN_CACHE[key] = (
            make_init(wl, cfg, plan_slots=plan_slots, latency=spec),
            jax.jit(make_run_while(
                wl, cfg, max_steps, layout=layout, dup_rows=dup,
                latency=spec,
            )),
            wl,  # keep alive so id() stays unique
        )
    init, run, _ = _RUN_CACHE[key]
    if plan is not None:
        state = init(seeds, plan.compile_batch(seeds, wl=wl))
    else:
        state = init(seeds)
    out = run(state)
    return latency_reduce(
        out.lat_hist, out.lat_count, out.lat_drop, phase_ns=spec.phase_ns
    )
