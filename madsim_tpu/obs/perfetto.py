"""Perfetto / Chrome trace-event export of a captured timeline.

Renders a decoded per-seed timeline (obs.decode_timeline — or any list
of ``engine.replay.ReplayEvent``) into the Trace Event JSON format that
``ui.perfetto.dev`` and ``chrome://tracing`` open directly:

* one **process track per node** — every dispatched event at that node
  is a slice, named by the workload's handler table;
* **message flow arrows** — each delivered message draws a flow from
  the sending node's track to the delivery slice. Causal captures
  (``ReplayEvent.parent``, engine ``ev_parent``/``tl_parent`` under
  ``causal=True``) attribute the arrow EXACTLY: it leaves the dispatch
  that emitted the message, by sequence number — no approximation at
  all. Rings captured with only the emit-time sidecar
  (``ReplayEvent.emit_ns``, engine ``ev_emit``/``tl_emit``) anchor the
  arrow at the true send time but attribute by node; older captures
  (``emit_ns < 0`` too) fall back to the historical approximation:
  the sender's last dispatch at-or-before the delivery — which two
  same-timestamp sends can mis-attribute (the tested reason the
  causal path exists). Client-army deliveries under a retry policy
  (``chaos.RetryPolicy``) name the arrow by **(op, attempt)** decoded
  from the packed op token, so a re-send of op 7 reads
  ``msg n1->n0 op7 try2`` — the same ambiguity class as the Duplicate
  mis-anchors banked in CAUSAL_r13.txt, disambiguated in the label
  whenever the send-time anchor (sidecar or causal) is present.
  Attempt-0 tokens are plain op ids, so off-policy traces are
  byte-identical to pre-retry exports;
* **chaos spans** — kill/restart, pause/resume, clog/unclog (node,
  link, and one-way forms), slow/unslow, dup on/off, and disk-fault
  (lying-fsync / torn-write) window pairs from the dispatched stream
  become duration slices on a dedicated "chaos" process, so a shrunk
  fault plan reads as shaded bands over the protocol's tracks.

The export is a pure function of the decoded events: the count of
``cat == "dispatch"`` slices always equals the timeline length (the
validity check the soak and tests pin).
"""

from __future__ import annotations

import json

from ..engine.core import (
    FIRST_EXT_KIND,
    FIRST_USER_KIND,
    KIND_CLOG,
    KIND_CLOG_1W,
    KIND_CLOG_NODE,
    KIND_DUP_OFF,
    KIND_DUP_ON,
    KIND_KILL,
    KIND_PAUSE,
    KIND_RESTART,
    KIND_RESUME,
    KIND_SKEW,
    KIND_SLOW_LINK,
    KIND_SYNC_LOSS,
    KIND_SYNC_OK,
    KIND_TORN_OFF,
    KIND_TORN_ON,
    KIND_UNCLOG,
    KIND_UNCLOG_1W,
    KIND_UNCLOG_NODE,
    KIND_UNSLOW,
    Workload,
    retry_token_attempt,
    retry_token_op,
    unpack_slow_arg,
)

__all__ = ["to_perfetto", "write_perfetto"]

# chaos spans ride one synthetic process so they band across the node
# tracks without colliding with node pids (nodes are 0..253)
_CHAOS_PID = 1000

# span-opening kind -> (closing kind, key function, label function).
# key identifies the pair (node id, link tuple, ...), so interleaved
# spans of different targets close independently.
_SPAN_PAIRS = {
    KIND_KILL: (KIND_RESTART, lambda a: ("node", a[0]),
                lambda a: f"killed n{a[0]}"),
    KIND_PAUSE: (KIND_RESUME, lambda a: ("node", a[0]),
                 lambda a: f"paused n{a[0]}"),
    KIND_CLOG: (KIND_UNCLOG, lambda a: ("link", *sorted(a[:2])),
                lambda a: f"partition n{a[0]}<->n{a[1]}"),
    KIND_CLOG_NODE: (KIND_UNCLOG_NODE, lambda a: ("nodeclog", a[0]),
                     lambda a: f"partition n{a[0]}"),
    KIND_CLOG_1W: (KIND_UNCLOG_1W, lambda a: ("link1w", a[0], a[1]),
                   lambda a: f"partition n{a[0]}->n{a[1]}"),
    KIND_SLOW_LINK: (
        KIND_UNSLOW,
        lambda a: ("slow", a[0], unpack_slow_arg(a[1])[0]),
        lambda a: (
            f"slow n{a[0]}<->"
            f"{'*' if unpack_slow_arg(a[1])[0] < 0 else 'n%d' % unpack_slow_arg(a[1])[0]}"
            f" x{unpack_slow_arg(a[1])[1]}"
        ),
    ),
    KIND_DUP_ON: (KIND_DUP_OFF, lambda a: ("dup",), lambda a: "duplication"),
    # disk-fault windows (chaos.DiskFault): a0 = node, -1 = every node
    KIND_SYNC_LOSS: (
        KIND_SYNC_OK, lambda a: ("syncloss", a[0]),
        lambda a: f"lying fsync {'n%d' % a[0] if a[0] >= 0 else '*'}",
    ),
    KIND_TORN_ON: (
        KIND_TORN_OFF, lambda a: ("torn", a[0]),
        lambda a: f"torn writes {'n%d' % a[0] if a[0] >= 0 else '*'}",
    ),
}
_SPAN_CLOSERS = {v[0]: k for k, v in _SPAN_PAIRS.items()}


def _us(t_ns: int) -> float:
    """Trace-event timestamps are microseconds (fractions allowed)."""
    return t_ns / 1e3


def _flow_name(e) -> str:
    """Arrow label for a delivery — attempt-aware for retried ops.

    User-kind deliveries carry a packed op token in ``args[0]``
    (engine.retry_token); a nonzero attempt id marks a RetryPolicy
    re-send, which is the same arrow-anchoring ambiguity as a
    Duplicate re-delivery (CAUSAL_r13.txt) — so the label names the
    (op, attempt) pair and the anchor (sidecar emit time or causal
    parent) disambiguates which send the arrow leaves. Attempt-0
    tokens are plain op ids: off-policy labels are unchanged.
    """
    base = f"msg n{e.src}->n{e.node}"
    if FIRST_USER_KIND <= e.kind < FIRST_EXT_KIND and len(e.args) > 0:
        att = retry_token_attempt(int(e.args[0]))
        if att > 0:
            return f"{base} op{retry_token_op(int(e.args[0]))} try{att}"
    return base


def to_perfetto(
    events,
    wl: Workload | None = None,
    name: str = "madsim",
    seed: int | None = None,
) -> dict:
    """Render decoded timeline events as a trace-event JSON dict.

    ``events`` is the ``obs.decode_timeline`` output (ReplayEvent rows,
    dispatch order). Serialize with ``json.dump`` or
    :func:`write_perfetto`; the result opens in ui.perfetto.dev as-is.
    """
    events = list(events)
    out = []
    is_engine = lambda k: k < FIRST_USER_KIND or k >= FIRST_EXT_KIND  # noqa: E731
    # engine/chaos events ride the chaos process: their pool rows target
    # node 0 by convention (chaos plan layout), which is not where the
    # fault acts — the span pairing below shows the real targets
    nodes = sorted({
        e.node for e in events if e.node >= 0 and not is_engine(e.kind)
    })
    wl_name = getattr(wl, "name", None) or name

    for n in nodes:
        out.append({
            "ph": "M", "name": "process_name", "pid": n, "tid": 0,
            "args": {"name": f"node {n} ({wl_name})"},
        })
        out.append({
            "ph": "M", "name": "process_sort_index", "pid": n, "tid": 0,
            "args": {"sort_index": n},
        })
    out.append({
        "ph": "M", "name": "process_name", "pid": _CHAOS_PID, "tid": 0,
        "args": {"name": "chaos"},
    })
    out.append({
        "ph": "M", "name": "process_sort_index", "pid": _CHAOS_PID,
        "tid": 0, "args": {"sort_index": -1},
    })

    # per-node next-event gap bounds each slice's duration so adjacent
    # dispatches never overlap; 200 us default keeps slices visible at
    # the 1-10 ms latency scale
    next_at: dict = {}
    by_node_rev: dict = {}
    for i in reversed(range(len(events))):
        e = events[i]
        next_at[i] = by_node_rev.get(e.node)
        by_node_rev[e.node] = e.time_ns
    end_ns = events[-1].time_ns if events else 0

    # dispatch slices: one per timeline event — the count invariant
    # seq -> ring index for exact parent attribution (causal captures)
    by_seq = {
        e.seq: i for i, e in enumerate(events) if getattr(e, "seq", -1) >= 0
    }
    last_idx_at_node: dict = {}
    flow_id = 0
    for i, e in enumerate(events):
        eng = is_engine(e.kind)
        pid = e.node if (e.node >= 0 and not eng) else _CHAOS_PID
        dur_ns = 200_000
        nxt = next_at.get(i)
        if nxt is not None and nxt > e.time_ns:
            dur_ns = min(dur_ns, nxt - e.time_ns)
        dur_ns = max(dur_ns, 1_000)
        row = {
            "ph": "X", "cat": "dispatch",
            "name": e.kind_name(wl),
            "pid": pid, "tid": 0,
            "ts": _us(e.time_ns), "dur": _us(dur_ns),
            "args": {
                "t_ms": e.time_ns / 1e6,
                "kind": e.kind,
                "src": e.src,
                "ev_args": list(e.args),
            },
        }
        if getattr(e, "seq", -1) >= 0:
            row["args"].update(seq=e.seq, parent=e.parent, lam=e.lam)
        out.append(row)
        # message flow arrow, best provenance first: exact emitting
        # dispatch (causal parent seq) > true send time (emit sidecar)
        # > the sender's last dispatch at-or-before this delivery (see
        # the module docstring)
        emit_ns = getattr(e, "emit_ns", -1)
        parent_i = (
            by_seq.get(e.parent)
            if getattr(e, "parent", -1) >= 0 else None
        )
        if e.src >= 0 and parent_i is not None:
            p = events[parent_i]
            out.append({
                "ph": "s", "cat": "flow", "id": flow_id,
                "name": _flow_name(e),
                "pid": p.node, "tid": 0,
                # the emitting dispatch's own timestamp IS the send
                # time (emission happens during its handler), so the
                # exact arrow needs no sidecar — but keep the finer
                # emit_ns anchor when both were captured
                "ts": _us(emit_ns if emit_ns >= 0 else p.time_ns),
            })
            out.append({
                "ph": "f", "cat": "flow", "id": flow_id, "bp": "e",
                "name": _flow_name(e),
                "pid": pid, "tid": 0, "ts": _us(e.time_ns),
            })
            flow_id += 1
        elif e.src >= 0 and emit_ns >= 0:
            out.append({
                "ph": "s", "cat": "flow", "id": flow_id,
                "name": _flow_name(e),
                "pid": e.src, "tid": 0, "ts": _us(emit_ns),
            })
            out.append({
                "ph": "f", "cat": "flow", "id": flow_id, "bp": "e",
                "name": _flow_name(e),
                "pid": pid, "tid": 0, "ts": _us(e.time_ns),
            })
            flow_id += 1
        elif e.src >= 0 and e.src in last_idx_at_node:
            s = events[last_idx_at_node[e.src]]
            out.append({
                "ph": "s", "cat": "flow", "id": flow_id,
                "name": _flow_name(e),
                "pid": s.node, "tid": 0, "ts": _us(s.time_ns),
            })
            out.append({
                "ph": "f", "cat": "flow", "id": flow_id, "bp": "e",
                "name": _flow_name(e),
                "pid": pid, "tid": 0, "ts": _us(e.time_ns),
            })
            flow_id += 1
        if e.node >= 0 and not eng:
            last_idx_at_node[e.node] = i

    # chaos spans: pair engine fault kinds from the same stream
    open_spans: dict = {}
    chaos_tids: dict = {}

    def _tid(key) -> int:
        if key not in chaos_tids:
            chaos_tids[key] = len(chaos_tids) + 1
        return chaos_tids[key]

    for e in events:
        if not is_engine(e.kind):
            continue
        if e.kind in _SPAN_PAIRS:
            _closer, keyf, labelf = _SPAN_PAIRS[e.kind]
            open_spans[keyf(e.args)] = (e.time_ns, labelf(e.args))
        elif e.kind in _SPAN_CLOSERS:
            opener = _SPAN_CLOSERS[e.kind]
            key = _SPAN_PAIRS[opener][1](e.args)
            started = open_spans.pop(key, None)
            if started is not None:
                t0, label = started
                out.append({
                    "ph": "X", "cat": "chaos", "name": label,
                    "pid": _CHAOS_PID, "tid": _tid(key),
                    "ts": _us(t0), "dur": _us(max(e.time_ns - t0, 1_000)),
                })
        elif e.kind == KIND_SKEW:
            out.append({
                "ph": "i", "cat": "chaos", "s": "g",
                "name": f"skew n{e.args[0]} {e.args[1]}ns",
                "pid": _CHAOS_PID, "tid": _tid(("skew",)),
                "ts": _us(e.time_ns),
            })
    # unclosed spans run to the end of the capture
    for key, (t0, label) in open_spans.items():
        out.append({
            "ph": "X", "cat": "chaos", "name": label,
            "pid": _CHAOS_PID, "tid": _tid(key),
            "ts": _us(t0), "dur": _us(max(end_ns - t0, 1_000)),
        })
    for key, tid in chaos_tids.items():
        out.append({
            "ph": "M", "name": "thread_name", "pid": _CHAOS_PID,
            "tid": tid, "args": {"name": "/".join(str(k) for k in key)},
        })

    meta = {"workload": wl_name, "events": len(events)}
    if seed is not None:
        meta["seed"] = int(seed)
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": meta,
    }


def write_perfetto(path: str, events, wl: Workload | None = None, **kw) -> dict:
    """``to_perfetto`` + serialize to ``path``; returns the dict."""
    doc = to_perfetto(events, wl, **kw)
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return doc
