"""Campaign telemetry + the per-violation ``explain`` narrative.

Two consumers of the observability columns live here:

* :class:`JsonlSink` — the structured-progress writer the exploration
  driver (``explore.run(telemetry=...)``) and the soak tools emit
  through: one JSON object per line (coverage bits, violations, corpus
  size, dispatch wall per generation), machine-greppable where the old
  ``log=print`` lines were prose.
* :func:`explain` — the story the search banner only gestures at: for
  one ``(seed, plan)`` repro key it re-runs the schedule with the
  timeline ring, fleet metrics and history recording on, then
  interleaves the dispatched-event stream, the injected fault plan, the
  recorded operation history and the checker verdict into a readable
  account of what the seed actually did.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

import jax

from ..engine.core import (
    HALT_DONE,
    HALT_IDLE,
    HALT_RUNNING,
    HALT_TIME_LIMIT,
    MET_HALT_CODE,
    METRIC_NAMES,
    make_init,
    make_run_while,
)
from .timeline import decode_timeline

__all__ = ["JsonlSink", "explain", "explain_diff"]


class JsonlSink:
    """Append-mode JSONL writer usable as an ``explore.run`` telemetry
    callable: ``sink(record_dict)`` writes one line and flushes PER
    RECORD, so a crashed or killed campaign still leaves every
    completed generation's record readable — a flight recorder that
    loses its tail on crash is not one. ``fsync=True`` additionally
    forces each record to stable storage (``os.fsync``): survives the
    whole BOX dying, at a per-record syscall cost — opt in for
    multi-hour hunts whose telemetry is the only evidence.
    """

    def __init__(self, path_or_file, fsync: bool = False):
        if hasattr(path_or_file, "write"):
            self._fh = path_or_file
            self._own = False
        else:
            self._fh = open(path_or_file, "a")
            self._own = True
        self._fsync = fsync

    def __call__(self, record: dict) -> None:
        self._fh.write(json.dumps(record, sort_keys=True) + "\n")
        self._fh.flush()
        if self._fsync:
            os.fsync(self._fh.fileno())

    def close(self) -> None:
        if self._own:
            self._fh.close()

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_HALT_STORY = {
    HALT_RUNNING: "still running when the step budget ended",
    HALT_DONE: "halted: the workload completed its scenario",
    HALT_TIME_LIMIT: "halted: the configured time limit tripped",
    HALT_IDLE: "deadlocked: the event pool ran empty with the seed "
               "unhalted (nothing pending, nothing ever will be)",
}

# history `ok` convention (check.history): -1 invoke, 1 ok, 0 failed
_OK_STORY = {-1: "invoke", 1: "ok", 0: "failed"}


def _plan_rows_for(plan, seed):
    """Compile whatever plan form the caller holds into one-seed rows."""
    from ..chaos.plan import LiteralPlan, stack_plan_rows

    if isinstance(plan, LiteralPlan):
        return stack_plan_rows([plan]), plan.slots, plan.uses_dup(), plan
    # a FaultPlan space: literalize for the exact trajectory + pretty
    # printing, then compile the literal (identical rows by contract)
    lit = plan.literalize(int(seed))
    return stack_plan_rows([lit]), lit.slots, lit.uses_dup(), lit


# compiled-run cache: explain/explain_diff re-runs over the same
# (workload, config, caps) — a diff is two captures, a forensics
# session many — reuse the XLA program instead of re-tracing per call
# (the engine.search._RUN_CACHE pattern: jit keys on function identity,
# so a fresh make_run_while closure per capture would defeat it).
# Keyed on id(wl) like that cache (workload closures aren't hashable),
# so hold ONE workload object across captures to hit it; bounded FIFO
# so a sweep over many (wl, cfg) pairs cannot grow memory unboundedly.
_CAPTURE_CACHE: dict = {}
_CAPTURE_CACHE_MAX = 8


def _capture(wl, cfg, seed, plan, max_steps, timeline_cap, layout,
             latency=None, causal=False):
    """Re-run one (seed, plan) with the forensics taps on: a field-name
    view dict of the final state plus the literalized plan (or None)."""
    seeds = np.asarray([seed], np.uint64)
    if plan is not None:
        rows, slots, dup, lit = _plan_rows_for(plan, seed)
    else:
        rows, slots, dup, lit = None, 0, False, None
    key = (id(wl), cfg.hash(), max_steps, timeline_cap, layout, slots, dup,
           latency, causal)
    if key not in _CAPTURE_CACHE:
        while len(_CAPTURE_CACHE) >= _CAPTURE_CACHE_MAX:
            _CAPTURE_CACHE.pop(next(iter(_CAPTURE_CACHE)))
        _CAPTURE_CACHE[key] = (
            make_init(
                wl, cfg, plan_slots=slots, metrics=True,
                timeline_cap=timeline_cap, latency=latency, causal=causal,
            ),
            jax.jit(make_run_while(
                wl, cfg, max_steps, layout=layout, dup_rows=dup,
                metrics=True, timeline_cap=timeline_cap, latency=latency,
                causal=causal,
            )),
            wl,  # keep the workload alive so id() stays unique
        )
    init, run, _wl = _CAPTURE_CACHE[key]
    state = init(seeds, rows) if rows is not None else init(seeds)
    out = jax.block_until_ready(run(state))
    view = {
        f.name: np.asarray(getattr(out, f.name))
        for f in dataclasses.fields(out)
    }
    return view, lit


def explain(
    wl,
    cfg,
    seed: int,
    plan=None,
    invariant=None,
    history_invariant=None,
    max_steps: int = 1000,
    timeline_cap: int = 1024,
    layout: str | None = None,
    max_events: int = 200,
    latency=None,
    causal: bool = False,
) -> str:
    """Narrate one ``(seed, plan)`` run: timeline + history + verdict.

    ``plan`` is a chaos ``LiteralPlan`` (a corpus entry's exact form) or
    ``FaultPlan`` (literalized for this seed), or None for a plain
    seeded run. ``invariant`` / ``history_invariant`` follow the
    ``search_seeds`` contract and become the verdict lines; without
    either the narrative reports the run without judging it.
    ``max_events`` bounds the printed timeline (the middle is elided;
    the head establishes context, the tail holds the crash site).
    ``latency`` (an ``engine.LatencySpec``) re-runs with the
    tail-latency tap on and adds the latency section: per-window
    percentiles off the seed's own sketch plus the slowest completed
    ops — the narrative an SLO breach needs.
    ``causal=True`` re-runs with the provenance columns on and narrates
    the backward happens-before **cone** of the violation instead of
    the whole stream (``obs.causal.causal_slice`` anchored at the last
    failed history record, else the last record, else the final
    dispatch): only the events that can have influenced the anchor,
    each with its seq/Lamport-clock/parent lineage, plus the injected
    fault windows inside the cone.
    """
    view, lit = _capture(
        wl, cfg, seed, plan, max_steps, timeline_cap, layout, latency,
        causal,
    )

    lines = [
        f"=== explain: {wl.name!r} seed {int(seed)} "
        f"config_hash={cfg.hash()}"
        + (f" plan_hash={lit.hash()}" if lit is not None else ""),
    ]
    if lit is not None:
        lines.append("--- injected fault plan:")
        mask = lit._mask()
        for e, on in zip(lit.events, mask):
            if on:
                lines.append(f"    {e}")

    # merge the dispatched-event stream with the history records by
    # time; records carry an indented `*` marker under their dispatch
    events = decode_timeline(view, wl, 0)
    hist_n = int(view["hist_count"][0]) if view["hist_word"].shape[1] else 0
    hist = [
        (
            int(view["hist_t"][0][i]),
            tuple(int(x) for x in view["hist_word"][0][i]),
        )
        for i in range(hist_n)
    ]
    if causal:
        # the cone narration replaces the whole-stream section: only
        # the events that happens-before-precede the violation anchor
        lines.extend(_cone_section(events, hist, view, wl, max_events))
    else:
        merged = []
        hi = 0
        for e in events:
            merged.append(("ev", e))
            while hi < len(hist) and hist[hi][0] <= e.time_ns:
                merged.append(("rec", hist[hi]))
                hi += 1
        merged.extend(("rec", h) for h in hist[hi:])

        lines.append(
            f"--- timeline ({len(events)} dispatched events, "
            f"{hist_n} history records"
            + (f", {int(view['tl_drop'][0])} DROPPED at ring capacity"
               if int(view["tl_drop"][0]) else "")
            + "):"
        )
        shown = merged
        if len(merged) > max_events:
            head = max_events // 3
            tail = max_events - head
            shown = (
                merged[:head]
                + [("gap", len(merged) - max_events)]
                + merged[-tail:]
            )
        for tag, item in shown:
            if tag == "gap":
                lines.append(f"    ... {item} rows elided ...")
            elif tag == "ev":
                lines.append(f"  {_fmt_event(item, wl)}")
            else:
                t, (op, key, arg, client, ok) = item
                lines.append(
                    f"  [{t / 1e6:>10.3f}ms]   * history: op{op} key={key} "
                    f"arg={arg} client=n{client} "
                    f"{_OK_STORY.get(ok, f'ok={ok}')}"
                )

    met = view["met"][0]
    code = int(met[MET_HALT_CODE])
    lines.append(f"--- outcome: {_HALT_STORY.get(code, f'halt code {code}')}")
    lines.append(
        "    "
        + ", ".join(
            f"{name}={int(met[m])}"
            for m, name in enumerate(METRIC_NAMES)
            if name != "halt_code" and int(met[m])
        )
    )
    if int(view["overflow"][0]):
        lines.append(
            f"    WARNING: {int(view['overflow'][0])} event(s) dropped to "
            f"pool overflow — this run's evidence is unreliable"
        )
    if view["hist_word"].shape[1] and int(view["hist_drop"][0]):
        lines.append(
            f"    WARNING: {int(view['hist_drop'][0])} history record(s) "
            f"dropped — checker verdicts are void for this seed"
        )

    if latency is not None and view["lat_hist"].shape[2]:
        lines.extend(_latency_section(view, latency))

    verdicts = []
    if invariant is not None:
        ok = bool(np.asarray(invariant(view))[0])
        verdicts.append(("final-state invariant", ok))
    if history_invariant is not None:
        from ..check.history import BatchHistory

        hok = bool(np.asarray(history_invariant(BatchHistory.from_view(view)))[0])
        verdicts.append(("history invariant", hok))
    for what, ok in verdicts:
        verdict = "HOLDS" if ok else "VIOLATED"
        lines.append(f"--- verdict: {what} {verdict}")
    if not verdicts:
        lines.append("--- verdict: no invariant supplied (narrative only)")
    lines.append(
        f"--- repro: seed={int(seed)} config_hash={cfg.hash()}"
        + (f" plan_hash={lit.hash()}" if lit is not None else "")
        + f" trace={int(view['trace'][0]):#018x}"
    )
    return "\n".join(lines)


def _latency_section(view, latency) -> list:
    """The tail-percentile narrative of one seed's sketch columns."""
    from ..engine.core import lat_bucket_hi
    from .latency import hist_quantile_bucket

    inv = view["lat_inv"][0]
    resp = view["lat_resp"][0]
    hist = view["lat_hist"][0]  # (P, B)
    invoked = int((inv >= 0).sum())
    completed = int(view["lat_count"][0])
    lines = [
        f"--- latency: {invoked} op(s) invoked, {completed} completed, "
        f"{invoked - completed} never answered"
        + (f", {int(view['lat_drop'][0])} marker(s) DROPPED "
           f"(op id out of range)" if int(view["lat_drop"][0]) else "")
    ]
    for p in range(hist.shape[0]):
        h = hist[p]
        n = int(h.sum())
        if not n:
            continue
        qs = []
        for q in (0.50, 0.90, 0.99):
            b = int(hist_quantile_bucket(h, q))
            qs.append(f"p{int(q * 100)}<={int(lat_bucket_hi(b)) / 1e6:.2f}ms")
        t0 = p * latency.phase_ns / 1e6
        lines.append(
            f"    window [{t0:.0f}ms..): {n} ops, " + ", ".join(qs)
        )
    done = np.flatnonzero((inv >= 0) & (resp >= 0))
    if done.size:
        d = (resp[done] - inv[done]).astype(np.int64)
        worst = done[np.argsort(d)[::-1][:5]]
        tops = ", ".join(
            f"op{int(i)}={int(resp[i] - inv[i]) / 1e6:.2f}ms" for i in worst
        )
        lines.append(f"    slowest completed: {tops}")
    return lines


def _cone_section(events, hist, view, wl, max_events) -> list:
    """The ``explain(causal=True)`` timeline section: anchor selection
    plus the happens-before cone narration (obs/causal.py)."""
    from .causal import causal_slice, format_cone

    failed = [h for h in hist if h[1][4] == 0]
    if failed:
        t, (op, key, arg, client, _ok) = failed[-1]
        anchor, what = (t, client), (
            f"last FAILED history record (op{op} key={key} client=n{client} "
            f"at {t / 1e6:.3f}ms)"
        )
    elif hist:
        t, (op, key, arg, client, _ok) = hist[-1]
        anchor, what = (t, client), (
            f"last history record (op{op} client=n{client} "
            f"at {t / 1e6:.3f}ms)"
        )
    else:
        anchor, what = None, "final dispatch (no history records)"
    lines = [f"--- causal anchor: {what}"]
    if int(view["tl_drop"][0]):
        lines.append(
            f"    WARNING: {int(view['tl_drop'][0])} event(s) dropped at "
            f"ring capacity — the cone's ancestry is prefix-only"
        )
    cone = causal_slice(events, seed=0, anchor=anchor)
    lines.append(format_cone(cone, wl, max_events=max_events))
    return lines


def _fmt_event(e, wl) -> str:
    origin = "timer" if e.src < 0 else f"node{e.src}"
    argstr = ",".join(str(a) for a in e.args)
    return (
        f"[{e.time_ns / 1e6:>10.3f}ms] node{e.node} <- "
        f"{e.kind_name(wl)}({argstr}) from {origin}"
    )


def _row_key(e) -> tuple:
    return (e.time_ns, e.kind, e.node, e.src, tuple(e.args), tuple(e.pay))


def _edge_divergence(ev_a, ev_b, wl) -> list:
    """Name the first causal edge the two runs attribute differently.

    Over the common prefix the per-seed dispatch seqs coincide row for
    row, so comparing raw ``parent`` values IS comparing edges in the
    two derivation DAGs — the first mismatch is the fork, and it can
    sit at a row whose (time, kind, node, args) tuple is still
    identical on both sides (same event, different emitter)."""
    from .causal import derive_parents, parent_class

    pa, pb = derive_parents(ev_a), derive_parents(ev_b)

    def _edge(evs, parents, i):
        e = evs[i]
        if e.parent < 0:
            return f"seq {e.seq} <- {parent_class(e.parent)} row"
        j = parents[i]
        via = (
            _fmt_event(evs[j], wl) if j is not None
            else "(emitter outside the captured ring)"
        )
        return f"seq {e.seq} <- seq {e.parent}  {via}"

    for i in range(min(len(ev_a), len(ev_b))):
        if ev_a[i].parent != ev_b[i].parent:
            return [
                f"--- first divergent causal edge: row {i}",
                f"    clean:     {_edge(ev_a, pa, i)}",
                f"    violating: {_edge(ev_b, pb, i)}",
            ]
    return [
        "--- causal edges identical over the common "
        f"{min(len(ev_a), len(ev_b))}-row prefix"
    ]


def explain_diff(
    wl,
    cfg,
    clean,
    violating,
    invariant=None,
    history_invariant=None,
    max_steps: int = 1000,
    timeline_cap: int = 1024,
    layout: str | None = None,
    context: int = 6,
    causal: bool = False,
) -> str:
    """Localize where a violating run departs from a clean sibling.

    ``clean`` / ``violating`` are ``(seed, plan)`` pairs (plan None for
    a bare seeded run) — typically two children of the same corpus
    parent, one admitted clean and one violating (``explore``'s
    frontier breeding makes such siblings abundant). Both are re-run
    with the timeline ring on; the narrative prints the **first
    divergent timeline row** (compared over the captured ``tl_t`` /
    ``tl_meta`` / ``tl_args`` / ``tl_pay`` columns — the exact tuples
    the trace hash folds, so "row k diverges" is a certified
    statement, not a heuristic), a window of common context before it,
    and each side's continuation plus verdict. Identical streams are
    reported as such — then the divergence is in final state only.

    ``causal=True`` captures both runs with the provenance columns on
    and names the first divergent causal **edge** as well: the first
    row whose parent attribution differs between the runs — which can
    precede the first divergent row tuple (two schedules can dispatch
    the same (time, kind, node, args) event from *different* emitting
    dispatches), and is the actual fork in the derivation DAG.
    """
    (seed_a, plan_a), (seed_b, plan_b) = clean, violating
    view_a, lit_a = _capture(
        wl, cfg, seed_a, plan_a, max_steps, timeline_cap, layout,
        causal=causal,
    )
    view_b, lit_b = _capture(
        wl, cfg, seed_b, plan_b, max_steps, timeline_cap, layout,
        causal=causal,
    )
    ev_a = decode_timeline(view_a, wl, 0)
    ev_b = decode_timeline(view_b, wl, 0)

    def _key(side, seed, lit):
        return (
            f"seed={int(seed)}"
            + (f" plan={lit.hash()}" if lit is not None else "")
            + f" trace={int(side['trace'][0]):#018x}"
        )

    lines = [
        f"=== explain-diff: {wl.name!r} config_hash={cfg.hash()}",
        f"    clean:     {_key(view_a, seed_a, lit_a)}",
        f"    violating: {_key(view_b, seed_b, lit_b)}",
    ]
    for tag, lit in (("clean", lit_a), ("violating", lit_b)):
        if lit is not None:
            on = [e for e, m in zip(lit.events, lit._mask()) if m]
            lines.append(f"--- {tag} plan ({len(on)} events):")
            lines.extend(f"    {e}" for e in on)

    div = None
    for i in range(min(len(ev_a), len(ev_b))):
        if _row_key(ev_a[i]) != _row_key(ev_b[i]):
            div = i
            break
    if div is None and len(ev_a) != len(ev_b):
        div = min(len(ev_a), len(ev_b))

    for side in (view_a, view_b):
        if int(side["tl_drop"][0]):
            lines.append(
                f"    WARNING: {int(side['tl_drop'][0])} event(s) dropped "
                f"at ring capacity — divergence index is prefix-only"
            )

    if div is None:
        lines.append(
            f"--- timelines IDENTICAL over {len(ev_a)} dispatched events "
            f"(divergence, if any, is outside the captured stream)"
        )
    else:
        lines.append(
            f"--- first divergent timeline row: {div} "
            f"(of {len(ev_a)} clean / {len(ev_b)} violating events)"
        )
        lo = max(div - context, 0)
        if lo > 0:
            lines.append(f"    ... {lo} identical rows elided ...")
        for i in range(lo, div):
            lines.append(f"    ={i:>5}  {_fmt_event(ev_a[i], wl)}")
        for tag, evs in (("clean", ev_a), ("violating", ev_b)):
            lines.append(f"  {tag} continues:")
            if div >= len(evs):
                lines.append("        (stream ends)")
            for i in range(div, min(div + context, len(evs))):
                lines.append(f"    {tag[0]}{i:>5}  {_fmt_event(evs[i], wl)}")

    if causal:
        lines.extend(_edge_divergence(ev_a, ev_b, wl))

    for tag, side in (("clean", view_a), ("violating", view_b)):
        met = side["met"][0]
        code = int(met[MET_HALT_CODE])
        lines.append(
            f"--- {tag} outcome: "
            f"{_HALT_STORY.get(code, f'halt code {code}')}"
        )
        verdicts = []
        if invariant is not None:
            verdicts.append(
                ("final-state invariant", bool(np.asarray(invariant(side))[0]))
            )
        if history_invariant is not None:
            from ..check.history import BatchHistory

            verdicts.append((
                "history invariant",
                bool(np.asarray(
                    history_invariant(BatchHistory.from_view(side))
                )[0]),
            ))
        for what, ok in verdicts:
            lines.append(
                f"    {what}: {'HOLDS' if ok else 'VIOLATED'}"
            )
    return "\n".join(lines)
