"""Campaign telemetry + the per-violation ``explain`` narrative.

Two consumers of the observability columns live here:

* :class:`JsonlSink` — the structured-progress writer the exploration
  driver (``explore.run(telemetry=...)``) and the soak tools emit
  through: one JSON object per line (coverage bits, violations, corpus
  size, dispatch wall per generation), machine-greppable where the old
  ``log=print`` lines were prose.
* :func:`explain` — the story the search banner only gestures at: for
  one ``(seed, plan)`` repro key it re-runs the schedule with the
  timeline ring, fleet metrics and history recording on, then
  interleaves the dispatched-event stream, the injected fault plan, the
  recorded operation history and the checker verdict into a readable
  account of what the seed actually did.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

import jax

from ..engine.core import (
    HALT_DONE,
    HALT_IDLE,
    HALT_RUNNING,
    HALT_TIME_LIMIT,
    MET_HALT_CODE,
    METRIC_NAMES,
    make_init,
    make_run_while,
)
from .timeline import decode_timeline

__all__ = ["JsonlSink", "explain"]


class JsonlSink:
    """Append-mode JSONL writer usable as an ``explore.run`` telemetry
    callable: ``sink(record_dict)`` writes one line and flushes (a
    killed campaign keeps every completed generation's record).
    """

    def __init__(self, path_or_file):
        if hasattr(path_or_file, "write"):
            self._fh = path_or_file
            self._own = False
        else:
            self._fh = open(path_or_file, "a")
            self._own = True

    def __call__(self, record: dict) -> None:
        self._fh.write(json.dumps(record, sort_keys=True) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._own:
            self._fh.close()

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_HALT_STORY = {
    HALT_RUNNING: "still running when the step budget ended",
    HALT_DONE: "halted: the workload completed its scenario",
    HALT_TIME_LIMIT: "halted: the configured time limit tripped",
    HALT_IDLE: "deadlocked: the event pool ran empty with the seed "
               "unhalted (nothing pending, nothing ever will be)",
}

# history `ok` convention (check.history): -1 invoke, 1 ok, 0 failed
_OK_STORY = {-1: "invoke", 1: "ok", 0: "failed"}


def _plan_rows_for(plan, seed):
    """Compile whatever plan form the caller holds into one-seed rows."""
    from ..chaos.plan import LiteralPlan, stack_plan_rows

    if isinstance(plan, LiteralPlan):
        return stack_plan_rows([plan]), plan.slots, plan.uses_dup(), plan
    # a FaultPlan space: literalize for the exact trajectory + pretty
    # printing, then compile the literal (identical rows by contract)
    lit = plan.literalize(int(seed))
    return stack_plan_rows([lit]), lit.slots, lit.uses_dup(), lit


def explain(
    wl,
    cfg,
    seed: int,
    plan=None,
    invariant=None,
    history_invariant=None,
    max_steps: int = 1000,
    timeline_cap: int = 1024,
    layout: str | None = None,
    max_events: int = 200,
) -> str:
    """Narrate one ``(seed, plan)`` run: timeline + history + verdict.

    ``plan`` is a chaos ``LiteralPlan`` (a corpus entry's exact form) or
    ``FaultPlan`` (literalized for this seed), or None for a plain
    seeded run. ``invariant`` / ``history_invariant`` follow the
    ``search_seeds`` contract and become the verdict lines; without
    either the narrative reports the run without judging it.
    ``max_events`` bounds the printed timeline (the middle is elided;
    the head establishes context, the tail holds the crash site).
    """
    seeds = np.asarray([seed], np.uint64)
    if plan is not None:
        rows, slots, dup, lit = _plan_rows_for(plan, seed)
    else:
        rows, slots, dup, lit = None, 0, False, None
    init = make_init(
        wl, cfg, plan_slots=slots, metrics=True, timeline_cap=timeline_cap
    )
    run = jax.jit(make_run_while(
        wl, cfg, max_steps, layout=layout, dup_rows=dup,
        metrics=True, timeline_cap=timeline_cap,
    ))
    state = init(seeds, rows) if rows is not None else init(seeds)
    out = jax.block_until_ready(run(state))
    view = {
        f.name: np.asarray(getattr(out, f.name))
        for f in dataclasses.fields(out)
    }

    lines = [
        f"=== explain: {wl.name!r} seed {int(seed)} "
        f"config_hash={cfg.hash()}"
        + (f" plan_hash={lit.hash()}" if lit is not None else ""),
    ]
    if lit is not None:
        lines.append("--- injected fault plan:")
        mask = lit._mask()
        for e, on in zip(lit.events, mask):
            if on:
                lines.append(f"    {e}")

    # merge the dispatched-event stream with the history records by
    # time; records carry an indented `*` marker under their dispatch
    events = decode_timeline(view, wl, 0)
    hist_n = int(view["hist_count"][0]) if view["hist_word"].shape[1] else 0
    hist = [
        (
            int(view["hist_t"][0][i]),
            tuple(int(x) for x in view["hist_word"][0][i]),
        )
        for i in range(hist_n)
    ]
    merged = []
    hi = 0
    for e in events:
        merged.append(("ev", e))
        while hi < len(hist) and hist[hi][0] <= e.time_ns:
            merged.append(("rec", hist[hi]))
            hi += 1
    merged.extend(("rec", h) for h in hist[hi:])

    lines.append(
        f"--- timeline ({len(events)} dispatched events, "
        f"{hist_n} history records"
        + (f", {int(view['tl_drop'][0])} DROPPED at ring capacity"
           if int(view["tl_drop"][0]) else "")
        + "):"
    )
    shown = merged
    if len(merged) > max_events:
        head = max_events // 3
        tail = max_events - head
        shown = (
            merged[:head]
            + [("gap", len(merged) - max_events)]
            + merged[-tail:]
        )
    for tag, item in shown:
        if tag == "gap":
            lines.append(f"    ... {item} rows elided ...")
        elif tag == "ev":
            e = item
            origin = "timer" if e.src < 0 else f"node{e.src}"
            argstr = ",".join(str(a) for a in e.args)
            lines.append(
                f"  [{e.time_ns / 1e6:>10.3f}ms] node{e.node} <- "
                f"{e.kind_name(wl)}({argstr}) from {origin}"
            )
        else:
            t, (op, key, arg, client, ok) = item
            lines.append(
                f"  [{t / 1e6:>10.3f}ms]   * history: op{op} key={key} "
                f"arg={arg} client=n{client} "
                f"{_OK_STORY.get(ok, f'ok={ok}')}"
            )

    met = view["met"][0]
    code = int(met[MET_HALT_CODE])
    lines.append(f"--- outcome: {_HALT_STORY.get(code, f'halt code {code}')}")
    lines.append(
        "    "
        + ", ".join(
            f"{name}={int(met[m])}"
            for m, name in enumerate(METRIC_NAMES)
            if name != "halt_code" and int(met[m])
        )
    )
    if int(view["overflow"][0]):
        lines.append(
            f"    WARNING: {int(view['overflow'][0])} event(s) dropped to "
            f"pool overflow — this run's evidence is unreliable"
        )
    if view["hist_word"].shape[1] and int(view["hist_drop"][0]):
        lines.append(
            f"    WARNING: {int(view['hist_drop'][0])} history record(s) "
            f"dropped — checker verdicts are void for this seed"
        )

    verdicts = []
    if invariant is not None:
        ok = bool(np.asarray(invariant(view))[0])
        verdicts.append(("final-state invariant", ok))
    if history_invariant is not None:
        from ..check.history import BatchHistory

        hok = bool(np.asarray(history_invariant(BatchHistory.from_view(view)))[0])
        verdicts.append(("history invariant", hok))
    for what, ok in verdicts:
        verdict = "HOLDS" if ok else "VIOLATED"
        lines.append(f"--- verdict: {what} {verdict}")
    if not verdicts:
        lines.append("--- verdict: no invariant supplied (narrative only)")
    lines.append(
        f"--- repro: seed={int(seed)} config_hash={cfg.hash()}"
        + (f" plan_hash={lit.hash()}" if lit is not None else "")
        + f" trace={int(view['trace'][0]):#018x}"
    )
    return "\n".join(lines)
