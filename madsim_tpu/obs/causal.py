"""Causal provenance: exact event lineage from a ``causal=True`` ring.

Reproducing a violation is not *explaining* it: the flight recorder
(obs/timeline.py) hands back the full dispatched-event stream, and a
human still has to guess which of those hundreds of rows actually led
to the bad state. Under the engine's ``causal=True`` build axis every
captured ring row carries exact lineage (engine/core.py make_step):

* ``seq``    — the dispatch's per-seed sequence number,
* ``parent`` — the seq of the dispatch that EMITTED this event (or a
  ``PARENT_*`` sentinel: init row, chaos/engine plan row, client-army
  row), folded on device the way ``ev_emit`` already was,
* ``lam``    — the destination node's Lamport clock after the
  happens-before fold ``lam[dst] = max(lam[dst], lam_at_emit) + 1``.

This module turns those columns into forensics. The happens-before
relation is the standard one — per-node program order (each node
dispatches serially) plus emit->deliver edges (the ``parent`` column)
— and :func:`causal_slice` computes the backward closure from a
violating record: the **cone** of events that can have influenced it.
Everything outside the cone is provably concurrent with the anchor and
can be ignored, which is the whole point — on real found violations
the cone is a small fraction of the captured ring (tools/causal_soak.py
banks the measured reduction).

``rederive`` recomputes seq/parent/lam host-side from nothing but the
event stream and checks them against the device fold — the refold
discipline (obs/timeline.py) applied to the causal columns, and the
test gate proving the device DAG and the replay derivation agree.
"""

from __future__ import annotations

import dataclasses

from ..engine.core import (
    PARENT_ARMY,
    PARENT_NONE,
    PARENT_PLAN,
    Workload,
)
from .timeline import decode_timeline

__all__ = [
    "CausalCone",
    "causal_slice",
    "derive_parents",
    "format_cone",
    "parent_class",
    "rederive",
]

# sentinel -> provenance class (engine/core.py PARENT_* numbering)
_PARENT_CLASS = {
    PARENT_NONE: "init",
    PARENT_PLAN: "plan",
    PARENT_ARMY: "army",
}


def parent_class(parent: int) -> str:
    """Provenance class of a ``ReplayEvent.parent`` value: ``"event"``
    for a real dispatch seq, else the sentinel's class (``"init"`` /
    ``"plan"`` / ``"army"``)."""
    if parent >= 0:
        return "event"
    return _PARENT_CLASS.get(parent, f"sentinel[{parent}]")


def _require_causal(events) -> None:
    if not events or events[0].seq < 0:
        raise ValueError(
            "timeline carries no causal columns — capture with causal=True "
            "(decoded rows have seq=-1, the pre-causal fallback)"
        )


def derive_parents(events) -> list:
    """Resolve each event's ``parent`` seq to a ring index (or None).

    None means either a sentinel class (init/plan/army — no emitting
    dispatch exists) or a parent dispatch the ring no longer holds
    (overflow dropped it, or capture started late): callers that need
    the distinction check ``parent_class(e.parent)``.
    """
    by_seq = {e.seq: i for i, e in enumerate(events)}
    return [
        by_seq.get(e.parent) if e.parent >= 0 else None for e in events
    ]


def rederive(events) -> list:
    """Host-side re-derivation of the Lamport column from the stream.

    Replays the device fold — per-node clock, ``max(clock, parent's
    post-fold clock) + 1`` — over the decoded events in ring order and
    returns the expected ``lam`` per row. Equality with the captured
    ``tl_lam`` is the DAG==derivation certificate (tests/test_causal.py
    pins it); a mismatch means the ring's edges don't describe the
    fold that actually ran. Only exact on un-truncated rings (a parent
    outside the ring re-derives from clock 0).
    """
    _require_causal(events)
    parents = derive_parents(events)
    clock: dict = {}
    lam = []
    for i, e in enumerate(events):
        p = parents[i]
        at_emit = lam[p] if p is not None else 0
        v = max(clock.get(e.node, 0), at_emit) + 1
        lam.append(v)
        clock[e.node] = v
    return lam


@dataclasses.dataclass(frozen=True)
class CausalCone:
    """The backward happens-before cone of one anchor event.

    ``indices`` are ring positions (sorted ascending — ring order is
    dispatch order, so iterating them narrates the cone in causal
    time); ``events`` is the full decoded ring the indices point into.
    ``missing_parents`` counts cone rows whose emitting dispatch the
    ring no longer holds — nonzero means the cone is a *prefix-sound*
    underapproximation (everything listed does precede the anchor, but
    dropped ancestors are absent), the tl_drop caveat in cone form.
    """

    seed: int
    events: list
    indices: tuple
    anchor: int
    missing_parents: int = 0

    @property
    def fraction(self) -> float:
        """Cone size over captured-ring size — the forensic reduction."""
        return len(self.indices) / max(len(self.events), 1)

    @property
    def chaos_indices(self) -> tuple:
        """Cone members that are injected chaos/plan dispatches — the
        fault windows that causally precede the anchor."""
        return tuple(
            i for i in self.indices
            if parent_class(self.events[i].parent) == "plan"
        )

    @property
    def depth(self) -> int:
        """Anchor's Lamport depth (longest causal chain ending there)."""
        return self.events[self.anchor].lam


def _resolve_anchor(events, anchor) -> int:
    if anchor is None:
        return len(events) - 1
    if isinstance(anchor, tuple):
        t_ns, node = anchor
        # a history record anchors at the dispatch that wrote it: the
        # last dispatch at its client node at-or-before the record time
        for i in range(len(events) - 1, -1, -1):
            if events[i].node == node and events[i].time_ns <= t_ns:
                return i
        raise ValueError(
            f"no dispatch at node {node} at-or-before t={t_ns} in the "
            f"captured ring — the anchor predates the capture"
        )
    i = int(anchor)
    if not 0 <= i < len(events):
        raise ValueError(
            f"anchor index {i} outside the captured ring "
            f"(0..{len(events) - 1})"
        )
    return i


def causal_slice(view, seed: int = 0, anchor=None, wl=None) -> CausalCone:
    """Backward happens-before cone from one event of a causal capture.

    ``view`` is anything :func:`~madsim_tpu.obs.decode_timeline`
    accepts (a ``search_seeds`` view, ``SearchReport.timeline``, a raw
    batched ``SimState``) captured under ``causal=True``. ``anchor``
    selects the apex: ``None`` = the last captured event, an ``int`` =
    a ring index, or ``(time_ns, node)`` = the last dispatch at that
    node at-or-before the time — the form a violating history record's
    ``(hist_t, client)`` pair plugs into directly.

    The cone is the transitive closure over both happens-before edge
    classes: emit->deliver (the ``parent`` column) and per-node program
    order (the dispatch immediately before each cone member at the
    same node). By construction it is closed — every listed event's
    causes are listed too (modulo ``missing_parents``) — so replaying
    the cone alone re-derives the anchor's Lamport clock, and every
    event OUTSIDE it is concurrent with the anchor: no schedule
    reordering of those rows can change what the anchor saw.
    """
    events = (
        view if isinstance(view, list)
        else decode_timeline(view, wl, seed)
    )
    _require_causal(events)
    apex = _resolve_anchor(events, anchor)
    parents = derive_parents(events)
    # per-node program-order predecessor, one linear scan
    pred = [None] * len(events)
    last: dict = {}
    for i, e in enumerate(events):
        pred[i] = last.get(e.node)
        last[e.node] = i
    member = set()
    missing = 0
    work = [apex]
    while work:
        i = work.pop()
        if i in member:
            continue
        member.add(i)
        for j in (parents[i], pred[i]):
            if j is not None and j not in member:
                work.append(j)
        if events[i].parent >= 0 and parents[i] is None:
            missing += 1  # the emitting dispatch left the ring
    return CausalCone(
        seed=seed,
        events=events,
        indices=tuple(sorted(member)),
        anchor=apex,
        missing_parents=missing,
    )


def format_cone(
    cone: CausalCone, wl: Workload | None = None, max_events: int = 200
) -> str:
    """Narrate a cone: the lineage story ``obs.explain(causal=True)``
    prints instead of the whole stream."""
    from .telemetry import _fmt_event  # avoid a cycle at import time

    n, total = len(cone.indices), len(cone.events)
    lines = [
        f"--- causal cone: {n} of {total} captured events "
        f"({100.0 * cone.fraction:.0f}%) precede the anchor; "
        f"depth {cone.depth} (longest happens-before chain)"
    ]
    if cone.missing_parents:
        lines.append(
            f"    WARNING: {cone.missing_parents} cone row(s) cite an "
            f"emitting dispatch outside the ring — ancestry is "
            f"prefix-only (ring overflow or late capture)"
        )
    chaos = cone.chaos_indices
    if chaos:
        lines.append(
            f"    {len(chaos)} injected fault dispatch(es) inside the "
            f"cone — the chaos that causally precedes the violation:"
        )
        for i in chaos:
            lines.append(f"      {_fmt_event(cone.events[i], wl)}")
    shown = list(cone.indices)
    elided = 0
    if len(shown) > max_events:
        head = max_events // 3
        elided = len(shown) - max_events
        shown = shown[:head] + [None] + shown[-(max_events - head):]
    for i in shown:
        if i is None:
            lines.append(f"    ... {elided} cone rows elided ...")
            continue
        e = cone.events[i]
        cls = parent_class(e.parent)
        via = (f"<- seq {e.parent}" if cls == "event" else f"<- {cls}")
        mark = " ** ANCHOR" if i == cone.anchor else ""
        lines.append(
            f"  [seq {e.seq:>5} lam {e.lam:>5} {via:>11}] "
            f"{_fmt_event(e, wl)}{mark}"
        )
    return "\n".join(lines)
