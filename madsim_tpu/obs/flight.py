"""Campaign flight recorder: live telemetry, heartbeats, and the
campaign-level Perfetto export.

PR 4's observability answers "what happened inside one simulation";
this module answers the operator questions about the CAMPAIGN wrapped
around 65k of them: where does wall time go between tracing, XLA
compilation, dispatch and host sync; what device memory does the
corpus + seed batch occupy; and is the multi-hour hunt still making
progress *right now*.

* :class:`FlightRecorder` — wraps any telemetry sink (an
  ``obs.JsonlSink``, a path, or a bare callable) for
  ``explore.run(telemetry=...)`` / ``run_device(telemetry=...)``. It
  stamps every record with a sequence number and a campaign-relative
  wall clock, interleaves **heartbeat** records (gens/s, coverage
  growth, ETA, live device-memory footprint) at a configurable cadence,
  drains the active :class:`obs.prof.ProgramProfiler`'s build events
  into **compile** records, and closes the log with a
  ``flight_summary`` (the full program table + memory accounting).
  ``profile=True`` (default) enables a session profiler if none is
  active, so a bare ``FlightRecorder(path)`` is the whole
  instrumentation story.
* :func:`campaign_perfetto` — renders a campaign's telemetry records
  (a list, or a JSONL path — including the half-written log of a
  crashed or still-running campaign) as trace-event JSON:
  one span per generation with dispatch / compile / mutate / admit /
  sync sub-slices from the drivers' wall split, counter tracks for
  coverage bits, corpus size, violations and device memory, and
  compile events as instants. Complements PR 4's per-seed
  ``to_perfetto``: that one shows one schedule's microseconds, this one
  shows the hunt's hours.

Every tap is host-side and derived-only: recorder on vs off leaves
corpus, coverage, violations and traces bit-identical (test-pinned
across both drivers), because the drivers only ever *hand records to*
the recorder — nothing flows back into the campaign.
"""

from __future__ import annotations

import json
import sys
import time

from . import prof as _prof
from .telemetry import JsonlSink

__all__ = ["FlightRecorder", "campaign_perfetto", "write_campaign_perfetto"]


class FlightRecorder:
    """Telemetry sink wrapper: heartbeats + compile events + summary.

    ``sink`` is a path (opened as a :class:`JsonlSink`, honoring
    ``fsync=``), an open file object, or any callable taking one record
    dict. Pass the recorder itself as the driver's ``telemetry=``.

    ``heartbeat_s`` is the minimum wall gap between heartbeat records
    (0.0 = one after every generation); heartbeats are emitted from
    within the record stream, so they interleave with generation
    records in sequence order and their ``generations_done`` /
    ``t_s`` / ``seq`` fields are monotone by construction.

    ``profile=True`` enables a session :class:`~.prof.ProgramProfiler`
    if none is active (and releases it on :meth:`close`); an already
    active profiler is used as-is and left alone. ``memory=True`` adds
    the live device-memory footprint (:func:`~.prof.device_memory`) to
    heartbeats and the summary.
    """

    def __init__(self, sink, *, heartbeat_s: float = 10.0,
                 profile: bool = True, memory: bool = True,
                 fsync: bool = False):
        if callable(sink) and not hasattr(sink, "write"):
            self._sink = sink
            self._own_sink = False
        else:
            self._sink = JsonlSink(sink, fsync=fsync)
            self._own_sink = True
        self.heartbeat_s = heartbeat_s
        self._memory = memory
        self._seq = 0
        self._t0 = None
        self._last_hb = -float("inf")
        self._gens_target = 0
        self._gens_done = 0
        self._campaign_t0 = 0.0
        self._last_gen: dict = {}
        self._own_profiler = False
        if profile and _prof.current() is None:
            _prof.enable()
            self._own_profiler = True

    # -- the sink protocol ------------------------------------------------
    def __call__(self, record: dict) -> None:
        now = time.monotonic()  # lint: allow(wall-clock)
        if self._t0 is None:
            self._t0 = now
        ev = record.get("event")
        if ev == "campaign_start":
            self._gens_target = int(record.get("generations", 0))
            self._gens_done = 0
            self._campaign_t0 = now
            self._last_hb = now  # first heartbeat after the first gen
        # compile events that happened during the dispatch PRECEDING
        # this record land before it in the log
        p = _prof.current()
        if p is not None:
            for e in p.pop_events():
                self._write({"event": "compile", **e}, now)
        self._write(record, now)
        if ev == "generation":
            self._gens_done += 1
            self._last_gen = record
            if now - self._last_hb >= self.heartbeat_s:
                self._write(self._heartbeat(now), now)
                self._last_hb = now
        elif ev == "campaign_end":
            self._write(self._summary(), now)

    def tagged(self, tenant: str):
        """A per-tenant view of this recorder for farm scheduling.

        The returned callable stamps every record with ``"tenant"``
        before feeding it to the shared recorder, so N scheduled
        campaigns interleave into ONE flight log with one monotone
        ``seq``/``t_s`` spine — heartbeats and the flight summary stay
        farm-wide, and ``tools/campaign_top.py`` splits the stream back
        into per-tenant tables by the tag. Existing ``"tenant"`` keys
        are preserved (re-tagging a tagged stream is a no-op)."""
        def _sink(record: dict, _t=str(tenant)) -> None:
            if "tenant" not in record:
                record = {**record, "tenant": _t}
            self(record)
        return _sink

    def _write(self, record: dict, now: float) -> None:
        rec = dict(record)
        rec["seq"] = self._seq
        rec["t_s"] = round(now - self._t0, 3)
        self._seq += 1
        self._sink(rec)

    def _heartbeat(self, now: float) -> dict:
        wall = max(now - self._campaign_t0, 1e-9)
        rate = self._gens_done / wall
        remaining = max(self._gens_target - self._gens_done, 0)
        hb = {
            "event": "heartbeat",
            "generations_done": self._gens_done,
            "generations": self._gens_target,
            "gens_per_s": round(rate, 4),
            "eta_s": round(remaining / rate, 1) if rate > 0 else None,
            "cov_bits": self._last_gen.get("cov_bits"),
            "corpus_size": self._last_gen.get("corpus_size"),
            "violations": self._last_gen.get("violations"),
        }
        if "tenant" in self._last_gen:
            hb["tenant"] = self._last_gen["tenant"]
        if self._memory:
            hb.update(_prof.device_memory())
        return hb

    def _summary(self) -> dict:
        out: dict = {"event": "flight_summary"}
        p = _prof.current()
        if p is not None:
            out["programs"] = p.to_dicts()
        if self._memory:
            out["memory"] = _prof.device_memory()
        # generation-program cache accounting (LRU size + evictions) —
        # checked via sys.modules so recording a host-only campaign
        # never drags the device driver in
        dev = sys.modules.get("madsim_tpu.explore.device")
        if dev is not None:
            out["gen_cache"] = dev.gen_cache_stats()
        return out

    # -- lifecycle --------------------------------------------------------
    def close(self) -> None:
        if self._own_sink:
            self._sink.close()
        if self._own_profiler:
            _prof.disable()
            self._own_profiler = False

    def __enter__(self) -> "FlightRecorder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# campaign-level Perfetto
# ---------------------------------------------------------------------------

_CAMPAIGN_PID = 0
_COUNTERS = ("cov_bits", "corpus_size", "violations")


def _records(source) -> list:
    if isinstance(source, (list, tuple)):
        return list(source)
    out = []
    with open(source) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                # the torn last line of a crashed campaign: everything
                # before it is still a readable flight log
                break
    return out


def _us(t_s: float) -> float:
    return t_s * 1e6


def campaign_perfetto(source, name: str = "campaign") -> dict:
    """Render campaign telemetry as trace-event JSON (ui.perfetto.dev).

    ``source`` is a record list (e.g. captured via
    ``telemetry=records.append``) or a path to a telemetry JSONL — the
    flight recorder's stamped log or a bare ``JsonlSink`` one; a torn
    final line (crashed campaign) is tolerated. The export carries:

    * one ``cat="generation"`` slice per ``generation`` record (span
      count == generation count — the validity pin), with
      mutate/compile/dispatch/admit/sync child slices from whichever
      wall-split keys the driver emitted, in driver order;
    * counter tracks for coverage bits, corpus size, violations (all
      monotone for a healthy campaign) and — when heartbeats carry the
      memory tap — live device-memory bytes and gens/s;
    * ``compile`` records (profiler build events) as instants, and
      heartbeats as counter samples.

    Timestamps come from the flight recorder's ``t_s`` stamps when
    present; records from a bare sink fall back to a cursor summed
    from the wall splits, so the picture is identical up to idle gaps.
    """
    recs = _records(source)
    events: list = []
    wl_name = name
    n_gens = 0
    cursor = 0.0
    for rec in recs:
        ev = rec.get("event")
        if ev == "campaign_start":
            wl_name = rec.get("workload", name)
            driver = rec.get("driver", "host")
            events.append({
                "ph": "i", "cat": "campaign", "s": "g",
                "name": f"campaign_start [{driver}]",
                "pid": _CAMPAIGN_PID, "tid": 0,
                "ts": _us(rec.get("t_s", cursor)),
                "args": {
                    k: v for k, v in rec.items()
                    if isinstance(v, (int, float, str, bool))
                },
            })
            if "t_s" in rec:
                cursor = rec["t_s"]
        elif ev == "generation":
            n_gens += 1
            # sub-span walls in driver order: (host) mutate -> compile
            # -> dispatch -> admit | (device) compile -> dispatch -> sync
            parts = [
                (k.replace("_wall_s", ""), float(rec.get(k, 0.0)))
                for k in ("mutate_wall_s", "compile_wall_s",
                          "dispatch_wall_s", "admit_wall_s", "sync_wall_s")
                if rec.get(k)
            ]
            span = sum(w for _, w in parts)
            # host_wall_s covers mutate+admit plus unmeasured residue;
            # bill the residue so the generation span matches the
            # driver's own accounting
            residue = max(
                float(rec.get("host_wall_s", 0.0))
                - float(rec.get("mutate_wall_s", 0.0))
                - float(rec.get("admit_wall_s", 0.0)),
                0.0,
            )
            span += residue
            end = rec.get("t_s", cursor + span)
            start = max(end - span, 0.0)
            g = rec.get("generation", n_gens - 1)
            events.append({
                "ph": "X", "cat": "generation", "name": f"generation {g}",
                "pid": _CAMPAIGN_PID, "tid": 0,
                "ts": _us(start), "dur": _us(max(span, 1e-6)),
                "args": {
                    k: v for k, v in rec.items()
                    if isinstance(v, (int, float)) and k != "t_s"
                },
            })
            t = start
            for label, w in parts:
                if w <= 0:
                    continue
                events.append({
                    "ph": "X", "cat": "phase", "name": label,
                    "pid": _CAMPAIGN_PID, "tid": 0,
                    "ts": _us(t), "dur": _us(w),
                })
                t += w
            for c in _COUNTERS:
                if c in rec:
                    events.append({
                        "ph": "C", "name": c, "pid": _CAMPAIGN_PID,
                        "tid": 0, "ts": _us(end), "args": {c: rec[c]},
                    })
            cursor = end
        elif ev == "compile":
            events.append({
                "ph": "i", "cat": "compile", "s": "p",
                "name": f"compile {rec.get('program', '?')}",
                "pid": _CAMPAIGN_PID, "tid": 0,
                "ts": _us(rec.get("t_s", cursor)),
                "args": {
                    k: rec[k]
                    for k in ("program", "key", "retrace", "trace_s",
                              "lower_s", "compile_s", "flops",
                              "bytes_accessed")
                    if k in rec
                },
            })
        elif ev == "heartbeat":
            ts = _us(rec.get("t_s", cursor))
            if rec.get("live_buffer_bytes") is not None:
                events.append({
                    "ph": "C", "name": "live_buffer_bytes",
                    "pid": _CAMPAIGN_PID, "tid": 0, "ts": ts,
                    "args": {"live_buffer_bytes": rec["live_buffer_bytes"]},
                })
            if rec.get("gens_per_s") is not None:
                events.append({
                    "ph": "C", "name": "gens_per_s",
                    "pid": _CAMPAIGN_PID, "tid": 0, "ts": ts,
                    "args": {"gens_per_s": rec["gens_per_s"]},
                })
    events.insert(0, {
        "ph": "M", "name": "process_name", "pid": _CAMPAIGN_PID, "tid": 0,
        "args": {"name": f"campaign ({wl_name})"},
    })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"workload": wl_name, "generations": n_gens},
    }


def write_campaign_perfetto(path: str, source, **kw) -> dict:
    """``campaign_perfetto`` + serialize to ``path``; returns the dict."""
    doc = campaign_perfetto(source, **kw)
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return doc
