"""Fleet metrics: device-side reduction of the engine's MET_* columns.

The reference surfaces per-run stats through ``tracing`` spans and the
``Stat`` counters (reference madsim/src/sim/net/network.rs:106-111 —
``msg_count``); at engine scale the same information is a column: every
seed folds the MET_* counters into ``SimState.met`` (engine/core.py,
``metrics=True``) and this module reduces the (S, M) batch **on
device** — totals, min/max, log2 histograms, the halt-code
distribution — so a 65k-seed sweep reports fleet-level shape without
ever moving per-seed history or timeline columns to the host. Only the
(M,)- and (M, B)-shaped reductions cross the transfer boundary.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from ..engine.core import (
    HALT_DONE,
    HALT_IDLE,
    HALT_RUNNING,
    HALT_TIME_LIMIT,
    MET_HALT_CODE,
    METRIC_NAMES,
    N_METRICS,
)

__all__ = ["FleetMetrics", "fleet_reduce", "fleet_metrics"]

# log2 histogram buckets: bucket 0 = count 0, bucket b in 1..16 = value
# in [2^(b-1), 2^b), bucket 17 = >= 2^16. 18 buckets cover any int32
# counter a realistic step budget can reach while staying readable.
N_BUCKETS = 18

_HALT_LABELS = {
    HALT_RUNNING: "running",
    HALT_DONE: "workload-halt",
    HALT_TIME_LIMIT: "time-limit",
    HALT_IDLE: "idle",
}


@dataclasses.dataclass(frozen=True)
class FleetMetrics:
    """Fleet-level reduction of per-seed MET_* counters.

    Every array is indexed by metric slot (``METRIC_NAMES`` order). The
    MET_HALT_CODE slot is categorical, not a counter — its total/mean
    are meaningless and the ``halt_codes`` distribution is the real
    signal there.
    """

    n_seeds: int
    totals: np.ndarray  # (M,) int64 fleet sums
    mins: np.ndarray  # (M,) int32 per-seed minima
    maxs: np.ndarray  # (M,) int32 per-seed maxima
    hist: np.ndarray  # (M, N_BUCKETS) int64 log2 histograms
    halt_codes: np.ndarray  # (4,) int64 seeds per HALT_* code
    # seeds whose event pool dropped events (the engine overflow
    # contract): their counters undercount, so a nonzero value means
    # the fleet shape includes unreliable rows — loud in format().
    # 0 when the reducer was handed a bare met batch with no overflow
    # column (fleet_metrics always supplies one).
    overflowed: int = 0
    # causal-provenance stats (``fleet_reduce(lam=...)``, causal=True
    # runs): per-seed max Lamport depth — the longest happens-before
    # chain any node folded — reduced to min/max + log2 histogram, and
    # the fleet-mean concurrency width sum(lam)/max(lam) (~how many
    # causal chains advanced in parallel; 1.0 = fully sequential,
    # n_nodes = perfectly concurrent). None without causal columns.
    depth_min: int | None = None
    depth_max: int | None = None
    depth_hist: np.ndarray | None = None  # (N_BUCKETS,) int64
    width_mean: float | None = None

    @property
    def names(self) -> tuple:
        return METRIC_NAMES

    def mean(self, name: str) -> float:
        return float(self.totals[METRIC_NAMES.index(name)]) / self.n_seeds

    def total(self, name: str) -> int:
        return int(self.totals[METRIC_NAMES.index(name)])

    def format(self, histograms: bool = False) -> str:
        """Text table of the fleet shape (the soak-artifact rendering)."""
        lines = [
            f"fleet metrics over {self.n_seeds} seeds:",
            f"  {'metric':<12} {'total':>12} {'mean':>10} "
            f"{'min':>7} {'max':>7}",
        ]
        for m, name in enumerate(METRIC_NAMES):
            if m == MET_HALT_CODE:
                continue
            lines.append(
                f"  {name:<12} {int(self.totals[m]):>12} "
                f"{self.totals[m] / self.n_seeds:>10.1f} "
                f"{int(self.mins[m]):>7} {int(self.maxs[m]):>7}"
            )
            if histograms:
                nz = np.nonzero(self.hist[m])[0]
                if nz.size:
                    buckets = ", ".join(
                        f"{_bucket_label(b)}: {int(self.hist[m, b])}"
                        for b in nz
                    )
                    lines.append(f"      hist {buckets}")
        halt = ", ".join(
            f"{_HALT_LABELS[c]} {int(self.halt_codes[c])}"
            for c in sorted(_HALT_LABELS)
            if self.halt_codes[c]
        )
        lines.append(f"  halt codes: {halt or 'none'}")
        if self.depth_hist is not None:
            lines.append(
                f"  causal: depth min {self.depth_min} max "
                f"{self.depth_max}, mean concurrency width "
                f"{self.width_mean:.2f}"
            )
            if histograms:
                nz = np.nonzero(self.depth_hist)[0]
                if nz.size:
                    buckets = ", ".join(
                        f"{_bucket_label(b)}: {int(self.depth_hist[b])}"
                        for b in nz
                    )
                    lines.append(f"      depth hist {buckets}")
        if self.overflowed:
            lines.append(
                f"  WARNING: {self.overflowed} seed(s) overflowed the "
                f"event pool — their counters undercount (raise "
                f"pool_size and re-sweep)"
            )
        return "\n".join(lines)


def _bucket_label(b: int) -> str:
    if b == 0:
        return "0"
    if b == N_BUCKETS - 1:
        return f">={1 << (b - 1)}"
    lo, hi = 1 << (b - 1), (1 << b) - 1
    return str(lo) if lo == hi else f"{lo}-{hi}"


@jax.jit
def _reduce(met):
    """(S, M) int32 -> all fleet reductions, entirely on device."""
    m64 = met.astype(jnp.int64)
    totals = jnp.sum(m64, axis=0)
    mins = jnp.min(met, axis=0)
    maxs = jnp.max(met, axis=0)
    thresholds = jnp.asarray(
        [1 << b for b in range(N_BUCKETS - 1)], jnp.int64
    )
    bucket = jnp.sum(
        m64[:, :, None] >= thresholds[None, None, :], axis=-1
    )  # (S, M) in 0..N_BUCKETS-1
    hist = jnp.sum(
        (bucket[:, :, None] == jnp.arange(N_BUCKETS)[None, None, :]).astype(
            jnp.int64
        ),
        axis=0,
    )
    codes = met[:, MET_HALT_CODE]
    halt = jnp.sum(
        (codes[:, None] == jnp.arange(4)[None, :]).astype(jnp.int64), axis=0
    )
    return totals, mins, maxs, hist, halt


@jax.jit
def _reduce_lam(lam):
    """(S, N) uint32 Lamport clocks -> fleet causal stats, on device.

    Per-seed depth = max over nodes (the longest happens-before chain
    folded anywhere); per-seed width = sum/max (total causal work over
    the critical path — the classic parallelism ratio). Only the
    scalar/histogram reductions leave the device.
    """
    depth = jnp.max(lam, axis=1).astype(jnp.int64)  # (S,)
    total = jnp.sum(lam.astype(jnp.int64), axis=1)
    width = jnp.where(depth > 0, total / jnp.maximum(depth, 1), 1.0)
    thresholds = jnp.asarray(
        [1 << b for b in range(N_BUCKETS - 1)], jnp.int64
    )
    bucket = jnp.sum(depth[:, None] >= thresholds[None, :], axis=-1)
    hist = jnp.sum(
        (bucket[:, None] == jnp.arange(N_BUCKETS)[None, :]).astype(
            jnp.int64
        ),
        axis=0,
    )
    return jnp.min(depth), jnp.max(depth), hist, jnp.mean(width)


def fleet_reduce(met, overflow=None, lam=None) -> FleetMetrics:
    """Reduce an (S, N_METRICS) per-seed metric batch to fleet shape.

    ``met`` may be the device-resident ``SimState.met`` batch (the
    metrics-only path: the reduction runs jitted on device and only the
    reduced arrays transfer) or a host copy (``SearchReport.met``) —
    same values either way. Pass the run's ``overflow`` column too when
    available: overflowed seeds' counters undercount (dropped events
    never dispatched), and the reduction surfaces their count loudly.
    ``lam`` is a causal run's (S, N) Lamport-clock batch
    (``SimState.lam`` / ``SearchReport.lam``): the causal depth/width
    stats fold on device the same way.
    """
    mm = jnp.asarray(met)
    if mm.ndim != 2 or mm.shape[1] != N_METRICS:
        raise ValueError(
            f"met must be (S, {N_METRICS}) MET_*-slot columns, got shape "
            f"{mm.shape}"
        )
    totals, mins, maxs, hist, halt = _reduce(mm)
    n_over = 0
    if overflow is not None:
        n_over = int(jax.jit(lambda o: jnp.sum(o > 0))(jnp.asarray(overflow)))
    causal: dict = {}
    if lam is not None and np.prod(np.shape(lam)):
        dmin, dmax, dhist, wmean = _reduce_lam(jnp.asarray(lam))
        causal = dict(
            depth_min=int(dmin),
            depth_max=int(dmax),
            depth_hist=np.asarray(dhist),
            width_mean=float(wmean),
        )
    return FleetMetrics(
        n_seeds=int(mm.shape[0]),
        totals=np.asarray(totals),
        mins=np.asarray(mins),
        maxs=np.asarray(maxs),
        hist=np.asarray(hist),
        halt_codes=np.asarray(halt),
        overflowed=n_over,
        **causal,
    )


# compiled-run cache, the engine.search discipline: repeated fleet
# sweeps over one (workload, config, budget) reuse the XLA program
_RUN_CACHE: dict = {}


def fleet_metrics(
    wl,
    cfg,
    n_seeds: int = 4096,
    max_steps: int = 1000,
    seed_base: int = 0,
    seeds=None,
    plan=None,
    layout: str | None = None,
) -> FleetMetrics:
    """The metrics-only sweep: run ``n_seeds`` schedules and return the
    fleet reduction — nothing per-seed ever reaches the host.

    This is the flight-recorder overview of a seed space: the final
    batched state stays on device, ``fleet_reduce`` consumes its
    ``met`` column jitted, and only the (M,)-/(M, B)-shaped results
    transfer. History and timeline columns are not even allocated
    (their taps stay off), satisfying the metrics-only-path contract.
    ``plan`` follows the ``search_seeds`` contract (a chaos FaultPlan
    compiled per seed).
    """
    from ..engine.core import make_init, make_run_while

    if seeds is None:
        seeds = np.arange(seed_base, seed_base + n_seeds, dtype=np.uint64)
    else:
        seeds = np.asarray(seeds, np.uint64)
    plan_slots = int(plan.slots) if plan is not None else 0
    dup = bool(plan.uses_dup()) if plan is not None else False
    key = (id(wl), cfg.hash(), max_steps, layout, plan_slots, dup)
    if key not in _RUN_CACHE:
        _RUN_CACHE[key] = (
            make_init(wl, cfg, plan_slots=plan_slots, metrics=True),
            jax.jit(make_run_while(
                wl, cfg, max_steps, layout=layout, dup_rows=dup,
                metrics=True,
            )),
            wl,  # keep alive so id() stays unique
        )
    init, run, _ = _RUN_CACHE[key]
    if plan is not None:
        state = init(seeds, plan.compile_batch(seeds, wl=wl))
    else:
        state = init(seeds)
    out = run(state)
    return fleet_reduce(out.met, overflow=out.overflow)
