"""Lease/watch KV service under chaos (the etcd-shaped batched model).

The batched analog of the reference ecosystem's ``madsim-etcd-client``
surface (services/etcd.py is the single-seed shim): one lease server,
``n_clients`` lease-holding clients and one watcher. Each client grants
itself a TTL lease at the server, keeps it alive with periodic
heartbeats, and serves puts through it; the server's scan loop expires
any lease whose deadline passed on the SERVER'S OWN CLOCK and publishes
the resulting delete events to the watcher as a sequenced stream. Under
``chaos.ClockSkew`` the server's local expiry clock drifts from true
time — the classic spurious-expiry bug class — and under loss or
``Partition`` the watch stream must stay gap-free or explicitly resync
(the watcher detects a sequence gap and re-syncs against the server's
stream head, recording the resync marker).

Safety contract (check.lease_safety over ``record=True`` histories):

1. no put is served through a lease whose latest recorded lifecycle
   event is an expiry (serve-after-expire needs a re-grant first), and
2. a lease expires only at or after its last granted deadline *on the
   server's own clock* — the skew-adjusted TTL contract.

Internal chaos kills a random client mid-run (the lease then expires
server-side and the reborn client must re-grant — the clean
grant-after-expiry path); composed plans add skew, partitions and
crash storms on top.

``bug=True`` plants the grant-after-expiry mutant: a keepalive landing
on an EXPIRED lease silently resurrects it instead of being rejected,
so later puts are served through a lease the history says is dead —
visible only to the history checkers (final states look healthy).

Node layout: [server 0, clients 1..C (lease id = node id), watcher C+1]
Server state:  [deadline_ms(lease 1) .. deadline_ms(lease C),
                wseq, fin_mask, expire_count]   (0 deadline = no lease)
Client state:  [granted, acked, fin, 0...]
Watcher state: [last_wseq, events, resyncs, 0...]

Deadlines are stored in int32 MILLISECONDS of the node's observed
clock, clamped to the declared certification horizon — the
``state_contracts`` declaration below is what lets the interval prover
(lint.absint) check the deadline arithmetic for overflow instead of
waving node_state through as full-range.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..check.history import OK_FAIL, OK_OK, OP_USER
from ..engine import (
    KIND_KILL,
    KIND_RESTART,
    HistorySpec,
    StateContract,
    Workload,
    user_kind,
)

# history op codes (check.lease_safety reads these)
OP_PUT = OP_USER  # serve: key = lease id, arg = put seq
OP_EXPIRE = OP_USER + 1  # lifecycle: OK_OK grant (arg = deadline_ms),
#                          OK_FAIL expiry (arg = server local ms)
OP_WATCH_EVT = OP_USER + 2  # stream: OK_OK in-order event (arg = wseq),
#                             OK_FAIL explicit resync (arg = new head)

_H_INIT = 0
_H_GRANT = 1  # at server: args = (lid,)
_H_GRANTED = 2  # at client
_H_KA_T = 3  # at client: keepalive timer
_H_KEEPALIVE = 4  # at server: args = (lid,)
_H_KA_REJ = 5  # at client: keepalive hit an expired lease
_H_SCAN = 6  # at server: expiry scan timer
_H_PUT_T = 7  # at client: put/progress timer
_H_PUT = 8  # at server: args = (lid, seq)
_H_PUT_OK = 9  # at client: args = (seq,)
_H_PUT_REJ = 10  # at client: put hit an expired lease
_H_FIN = 11  # at server: args = (lid,)
_H_WEVT = 12  # at watcher: args = (lid, wseq)
_H_RESYNC = 13  # at server: watcher stream-head request
_H_RESYNC_OK = 14  # at watcher: args = (wseq,)
_H_AREQ = 15  # at watcher: army op arrival — army mode
_H_APROBE = 16  # at server: army probe
_H_ARESP = 17  # at watcher: army response

SERVER = 0

_P_KILL_AT = 0
_P_KILL_WHO = 1
_P_REVIVE = 2

# Certification horizon in MILLISECONDS for the stored deadline columns:
# observed clocks are clamped here before any deadline arithmetic, so
# the declared state contract is owed by construction. 300 sim-seconds
# matches the model's declared ABSINT_HORIZON_NS.
HORIZON_MS = 300_000
# watch-stream sequence cap (the server stops numbering past it; a run
# certifying more watch events than this is out of contract)
WSEQ_CAP = (1 << 16) - 1
# cap on the monotone event/resync/expiry counters
EVT_CAP = (1 << 16) - 1


def _local_ms(now):
    """The handling node's observed clock in clamped int32 ms.

    ``ctx.now`` is the skew-adjusted view (chaos.ClockSkew lands in it),
    so expiry deadlines computed from this ARE the node's drifting local
    clock — exactly the spurious-expiry surface. The clamp keeps every
    stored deadline inside the declared state contract.
    """
    ms = jnp.clip(now // 1_000_000, 0, HORIZON_MS)
    return ms.astype(jnp.int32)


def make_leasekv(
    n_clients: int = 3,
    puts: int = 6,
    ttl_ms: int = 120,
    ka_ms: int = 40,
    scan_ms: int = 20,
    put_ms: int = 30,
    ka_stop_ms: int | None = None,
    chaos: bool = True,
    record: bool = False,
    hist_capacity: int | None = None,
    bug: bool = False,
    army: bool = False,
    army_probes: int = 1,
) -> Workload:
    """``record=True`` turns on the lease lifecycle history: the server
    records every grant (OP_EXPIRE/OK_OK, arg = the granted deadline in
    its own ms clock), every expiry (OP_EXPIRE/OK_FAIL, arg = its local
    ms at expiry) and every served put (OP_PUT/OK_OK); the watcher
    records in-order stream events and explicit resyncs (OP_WATCH_EVT).
    Keepalive renewals extend the deadline silently — sound for the
    detector, because a renewal can only move the deadline LATER than
    the last recorded grant, and a renewal never follows an expiry on
    the clean paths (that is precisely what ``bug=True`` breaks).

    ``ka_stop_ms`` makes client 1 stop sending keepalives once its
    local clock passes that mark (a stalled client) — the knob the
    dual-mode convergence test drives both arms with.

    ``bug=True`` plants grant-after-expiry: a keepalive on an expired
    lease resurrects it with no grant record, so subsequent puts are
    served through a dead lease. Requires ``record=True``.

    ``army=True`` opens the watcher node as an open-loop client
    surface (``client_army`` builds the spec): ops probe the server's
    stream head, a read-only path that perturbs scheduling but never
    protocol state.
    """
    n = n_clients + 2
    watcher = n_clients + 1
    width = max(n_clients + 3, 4)
    c_wseq, c_fin_mask, c_exp_cnt = n_clients, n_clients + 1, n_clients + 2
    full_mask = (1 << n_clients) - 1
    if bug and not record:
        raise ValueError(
            "bug=True plants a fault only histories can see; it requires "
            "record=True (otherwise nothing would ever detect it)"
        )
    if army_probes < 1:
        raise ValueError(f"army_probes must be >= 1, got {army_probes}")
    ttl = jnp.int32(ttl_ms)

    def _lid(ctx):
        return jnp.clip(ctx.args[0], 1, n_clients)

    def on_init(ctx):
        eb = ctx.emits()
        is_client = (ctx.node >= 1) & (ctx.node <= jnp.int32(n_clients))
        is_watcher = ctx.node == jnp.int32(watcher)
        is_server = ctx.node == jnp.int32(SERVER)
        # a client (re)grants its lease and starts its timers — at t=0
        # and again after a restart, the natural rejoin path
        eb.send(SERVER, user_kind(_H_GRANT), (ctx.node,), when=is_client)
        eb.after(ka_ms * 1_000_000, user_kind(_H_KA_T), ctx.node,
                 when=is_client)
        eb.after(put_ms * 1_000_000, user_kind(_H_PUT_T), ctx.node,
                 when=is_client)
        eb.after(scan_ms * 1_000_000, user_kind(_H_SCAN), SERVER,
                 when=is_server)
        if chaos:
            who = ctx.draw.user_int(
                1, 1 + n_clients, _P_KILL_WHO
            ).astype(jnp.int32)
            at = ctx.draw.user_int(20_000_000, 300_000_000, _P_KILL_AT)
            revive = ctx.draw.user_int(100_000_000, 600_000_000, _P_REVIVE)
            eb.after(at, KIND_KILL, 0, (who,), when=is_watcher)
            eb.after(at + revive, KIND_RESTART, 0, (who,), when=is_watcher)
        return ctx.state, eb.build()

    def on_grant(ctx):
        # grants and re-grants land here; granting a live lease is a
        # renewal that records (harmless — it only raises the floor)
        lid = _lid(ctx)
        st = ctx.state
        deadline = _local_ms(ctx.now) + ttl
        new = st.at[lid - 1].set(deadline)
        eb = ctx.emits()
        if record:
            eb.record(OP_EXPIRE, lid, deadline, ok=OK_OK)
        eb.send(lid, user_kind(_H_GRANTED), ())
        return new, eb.build()

    def on_granted(ctx):
        return ctx.state.at[0].set(1), ctx.emits().build()

    def on_ka_t(ctx):
        st = ctx.state
        send = st[0] > 0
        if ka_stop_ms is not None:
            # client 1 stalls: its keepalives stop once its own clock
            # passes the mark (the dual-mode scenario knob)
            stalled = (ctx.node == jnp.int32(1)) & (
                _local_ms(ctx.now) >= jnp.int32(ka_stop_ms)
            )
            send = send & ~stalled
        eb = ctx.emits()
        eb.send(SERVER, user_kind(_H_KEEPALIVE), (ctx.node,), when=send)
        eb.after(ka_ms * 1_000_000, user_kind(_H_KA_T), ctx.node)
        return ctx.state, eb.build()

    def on_keepalive(ctx):
        lid = _lid(ctx)
        st = ctx.state
        live = st[lid - 1] > 0
        deadline = _local_ms(ctx.now) + ttl
        if bug:
            # planted grant-after-expiry: the keepalive resurrects an
            # expired lease with no grant record — puts served through
            # it look fine in every final state, and only the history
            # checkers (serve after expiry, no re-grant between) can
            # see the dead lease serving
            renew = jnp.bool_(True)
        else:
            renew = live
        new = jnp.where(renew, st.at[lid - 1].set(deadline), st)
        eb = ctx.emits()
        eb.send(lid, user_kind(_H_KA_REJ), (), when=~renew)
        return new, eb.build()

    def on_ka_rej(ctx):
        # lease expired server-side: drop to ungranted; the put timer
        # re-grants
        return ctx.state.at[0].set(0), ctx.emits().build()

    def on_scan(ctx):
        # the expiry scan: every lease whose deadline passed the
        # server's OWN clock expires now; each expiry publishes one
        # sequenced delete event to the watcher
        st = ctx.state
        now_ms = _local_ms(ctx.now)
        wseq = st[c_wseq]
        eb = ctx.emits()
        new = st
        fired = jnp.int32(0)
        for lid in range(1, n_clients + 1):
            d = st[lid - 1]
            exp = (d > 0) & (now_ms >= d)
            new = jnp.where(exp, new.at[lid - 1].set(0), new)
            seq_i = jnp.minimum(wseq + fired + 1, jnp.int32(WSEQ_CAP))
            eb.send(watcher, user_kind(_H_WEVT), (jnp.int32(lid), seq_i),
                    when=exp)
            if record:
                eb.record(OP_EXPIRE, jnp.int32(lid), now_ms, ok=OK_FAIL,
                          when=exp)
            fired = fired + exp.astype(jnp.int32)
        new = new.at[c_wseq].set(
            jnp.minimum(wseq + fired, jnp.int32(WSEQ_CAP))
        )
        new = new.at[c_exp_cnt].set(
            jnp.minimum(st[c_exp_cnt] + fired, jnp.int32(EVT_CAP))
        )
        eb.after(scan_ms * 1_000_000, user_kind(_H_SCAN), SERVER)
        return new, eb.build()

    def on_put_t(ctx):
        # the client progress loop: re-grant if ungranted, else push
        # the next unacked put, else keep offering FIN (all three are
        # lossy, so all three retry until acknowledged)
        st = ctx.state
        granted, acked = st[0] > 0, st[1]
        done = acked >= jnp.int32(puts)
        eb = ctx.emits()
        eb.send(SERVER, user_kind(_H_GRANT), (ctx.node,),
                when=~granted & ~done)
        eb.send(SERVER, user_kind(_H_PUT), (ctx.node, acked + 1),
                when=granted & ~done)
        eb.send(SERVER, user_kind(_H_FIN), (ctx.node,), when=done)
        eb.after(put_ms * 1_000_000, user_kind(_H_PUT_T), ctx.node)
        return ctx.state, eb.build()

    def on_put(ctx):
        # serve iff the lease is live on the server — the record IS the
        # serve event check.lease_safety audits
        lid = _lid(ctx)
        seq = jnp.clip(ctx.args[1], 0, puts)
        st = ctx.state
        live = st[lid - 1] > 0
        eb = ctx.emits()
        if record:
            eb.record(OP_PUT, lid, seq, ok=OK_OK, when=live)
        eb.send(lid, user_kind(_H_PUT_OK), (seq,), when=live)
        eb.send(lid, user_kind(_H_PUT_REJ), (), when=~live)
        return ctx.state, eb.build()

    def on_put_ok(ctx):
        seq = jnp.clip(ctx.args[0], 0, puts)
        st = ctx.state
        return st.at[1].set(jnp.maximum(st[1], seq)), ctx.emits().build()

    def on_put_rej(ctx):
        return ctx.state.at[0].set(0), ctx.emits().build()

    def on_fin(ctx):
        lid = _lid(ctx)
        st = ctx.state
        mask = st[c_fin_mask] | (jnp.int32(1) << (lid - 1))
        new = st.at[c_fin_mask].set(mask)
        eb = ctx.emits()
        eb.halt(when=mask == jnp.int32(full_mask))
        return new, eb.build()

    def on_wevt(ctx):
        # the watch stream: in-order events append; a sequence gap
        # (lost event) triggers an explicit resync against the server's
        # stream head — gap-free or resync, never silently skipped
        lid = jnp.clip(ctx.args[0], 0, n_clients)
        seq = jnp.clip(ctx.args[1], 0, WSEQ_CAP)
        st = ctx.state
        in_order = seq == st[0] + 1
        gap = seq > st[0] + 1
        new = jnp.where(in_order, st.at[0].set(seq), st)
        new = jnp.where(
            in_order,
            new.at[1].set(jnp.minimum(new[1] + 1, jnp.int32(EVT_CAP))),
            new,
        )
        new = jnp.where(
            gap,
            new.at[2].set(jnp.minimum(new[2] + 1, jnp.int32(EVT_CAP))),
            new,
        )
        eb = ctx.emits()
        if record:
            eb.record(OP_WATCH_EVT, lid, seq, ok=OK_OK, when=in_order)
        eb.send(SERVER, user_kind(_H_RESYNC), (st[0],), when=gap)
        return new, eb.build()

    def on_resync(ctx):
        eb = ctx.emits()
        eb.send(watcher, user_kind(_H_RESYNC_OK), (ctx.state[c_wseq],))
        return ctx.state, eb.build()

    def on_resync_ok(ctx):
        # adopt the stream head and record the explicit resync marker
        # (OK_FAIL on the stream op = "gap resolved by resync")
        w = jnp.clip(ctx.args[0], 0, WSEQ_CAP)
        st = ctx.state
        adv = w > st[0]
        new = jnp.where(adv, st.at[0].set(w), st)
        eb = ctx.emits()
        if record:
            eb.record(OP_WATCH_EVT, 0, w, ok=OK_FAIL, when=adv)
        return new, eb.build()

    def on_areq(ctx):
        # army op arrival at the watcher (a ClientArmy pool row): mark
        # the invoke and open a k-round probe session against the
        # server's stream head — read-only, open-loop, no retries
        op_id = ctx.args[0]
        eb = ctx.emits()
        eb.lat_start(op_id)
        eb.send(SERVER, user_kind(_H_APROBE),
                (op_id, jnp.int32(army_probes - 1)))
        return ctx.state, eb.build()

    def on_aprobe(ctx):
        eb = ctx.emits()
        eb.send(watcher, user_kind(_H_ARESP), (ctx.args[0], ctx.args[1]))
        return ctx.state, eb.build()

    def on_aresp(ctx):
        op_id, k = ctx.args[0], ctx.args[1]
        eb = ctx.emits()
        eb.send(SERVER, user_kind(_H_APROBE), (op_id, k - 1), when=k > 0)
        eb.lat_end(op_id, when=k == 0)
        return ctx.state, eb.build()

    def _cov(ns, now):
        # protocol coverage: the lease liveness configuration (which
        # leases are live RIGHT NOW) and the watcher's stream lag —
        # lease state transitions and stream health are the behaviors
        # a guided hunt should treat as new, not just event kinds.
        # uint32 words only (coverage is derived state)
        live_bits = jnp.uint32(0)
        for lid in range(1, n_clients + 1):
            live_bits = live_bits | (
                (ns[SERVER, lid - 1] > 0).astype(jnp.uint32)
                << jnp.uint32(lid)
            )
        exp = jnp.minimum(ns[SERVER, c_exp_cnt], 15).astype(jnp.uint32)
        lag = jnp.clip(
            ns[SERVER, c_wseq] - ns[watcher, 0], 0, 15
        ).astype(jnp.uint32)
        f1 = live_bits | (exp << jnp.uint32(8)) | jnp.uint32(1 << 16)
        f2 = lag | jnp.uint32(1 << 17)
        return ((f1, jnp.bool_(True)), (f2, jnp.bool_(True)))

    # per-column range contracts (lint.absint): the hull each column is
    # owed at step boundaries, across every role that uses it. Deadline
    # columns carry the "time" family so the prover tracks the ms
    # deadline arithmetic; everything else is a bounded counter.
    def _sc(col):
        lo, hi, fam = 0, 1, "counter"
        ranges = []
        if col < n_clients:  # server deadline_ms for lease col+1
            ranges.append((0, HORIZON_MS + ttl_ms, "time"))
        if col == c_wseq:
            ranges.append((0, WSEQ_CAP, "counter"))
        if col == c_fin_mask:
            ranges.append((0, full_mask, "counter"))
        if col == c_exp_cnt:
            ranges.append((0, EVT_CAP, "counter"))
        if col == 0:  # client granted; watcher last_wseq
            ranges.append((0, max(1, WSEQ_CAP), "counter"))
        if col == 1:  # client acked; watcher events
            ranges.append((0, max(puts, EVT_CAP), "counter"))
        if col == 2:  # client fin; watcher resyncs
            ranges.append((0, EVT_CAP, "counter"))
        for rlo, rhi, rfam in ranges:
            lo, hi = min(lo, rlo), max(hi, rhi)
            fam = "time" if rfam == "time" else fam
        return StateContract(col, lo, hi, fam)

    hist = None
    if record:
        cap = (
            6 * n_clients * max(puts, 2) + 32
            if hist_capacity is None else hist_capacity
        )
        # widest recording dispatch: the scan records one expiry per
        # lease
        hist = HistorySpec(capacity=cap, max_records=max(n_clients, 1))

    name = "leasekv"
    if record:
        name += "-bug" if bug else "-record"
    if army:
        name += "-army"
    handler_names = (
        "init", "grant", "granted", "ka_t", "keepalive", "ka_rej",
        "scan", "put_t", "put", "put_ok", "put_rej", "fin", "wevt",
        "resync", "resync_ok",
    )
    handlers = (
        on_init, on_grant, on_granted, on_ka_t, on_keepalive, on_ka_rej,
        on_scan, on_put_t, on_put, on_put_ok, on_put_rej, on_fin,
        on_wevt, on_resync, on_resync_ok,
    )
    if army:
        handler_names += ("areq", "aprobe", "aresp")
        handlers += (on_areq, on_aprobe, on_aresp)
    return Workload(
        name=name,
        handler_names=handler_names,
        n_nodes=n,
        state_width=width,
        handlers=handlers,
        # widest: the scan sends one watch event per lease + its timer;
        # on_init builds 3 client rows (grant + 2 timers)
        max_emits=max(n_clients + 1, 6),
        # largest timer: the chaos restart at 'at + revive' <= 900 ms
        delay_bound_ns=max(
            ka_ms * 1_000_000, scan_ms * 1_000_000, put_ms * 1_000_000,
            900_000_000,
        ),
        args_words=2,
        history=hist,
        lat_markers=1 if army else 0,
        cov_features=_cov,
        state_contracts=tuple(_sc(c) for c in range(width)),
        draw_purposes=(
            (_P_KILL_AT, _P_KILL_WHO, _P_REVIVE) if chaos else ()
        ),
    )


def client_army(
    n_ops: int = 256,
    t_min_ns: int = 20_000_000,
    t_max_ns: int = 400_000_000,
    n_clients: int = 3,
    op_base: int = 0,
):
    """A :class:`chaos.ClientArmy` bound to leasekv's watcher surface
    (``make_leasekv(army=True)`` with the same ``n_clients``): ops
    arrive at the watcher and probe the server's stream head."""
    from ..chaos.plan import ClientArmy

    return ClientArmy(
        node=n_clients + 1,  # [server, clients 1..C, watcher C+1]
        kind=user_kind(_H_AREQ),
        n_ops=n_ops,
        t_min_ns=t_min_ns,
        t_max_ns=t_max_ns,
        op_base=op_base,
    )


def lint_entries():
    """Tracing entry points for the static non-interference matrix
    (madsim_tpu.lint): base + record (the new history/coverage columns
    must prove derived-only) + army (the latency-marker path)."""
    kw = dict(pool_size=48, loss_p=0.02, clog_backoff_max_ns=2_000_000_000)
    return [
        ("leasekv/plain", make_leasekv(), kw),
        ("leasekv/record", make_leasekv(record=True), kw),
        ("leasekv/army", make_leasekv(army=True), kw),
    ]


# Declared interval-certification horizon (lint.absint): lease TTLs and
# scan periods are sim-milliseconds; 300 sim-seconds of scan/renewal
# cycles is generous slack over every recorded leasekv hunt shape, and
# matches the HORIZON_MS clamp the deadline arithmetic is owed under.
ABSINT_HORIZON_NS = 300 * 1_000_000_000


def absint_entries():
    """Range-contract entry points for the interval prover
    (lint.absint): lint_entries rows plus the declared horizon."""
    return [
        (tag, wl, kw, ABSINT_HORIZON_NS)
        for tag, wl, kw in lint_entries()
    ]
