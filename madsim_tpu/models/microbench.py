"""Single-node timer + RNG microbenchmark (BASELINE.md config 2).

The pure time/rand core with no network: a node repeatedly sleeps a
random interval and folds a random draw into an accumulator — the
batched analog of a madsim test that only uses ``time::sleep`` and
``rand`` (reference sim/time/mod.rs + sim/rand.rs). Measures raw engine
event throughput.

State row: [tick_count, accumulator, 0, 0]
"""

from __future__ import annotations

import jax.numpy as jnp

from ..engine import Workload, user_kind

_H_INIT = 0
_H_TICK = 1

# user draw purposes
_P_DELAY = 0
_P_VALUE = 1


def make_microbench(
    rounds: int = 1000,
    delay_min_ns: int = 1_000,
    delay_max_ns: int = 1_000_000,
) -> Workload:
    def on_init(ctx):
        eb = ctx.emits()
        d = ctx.draw.user_int(delay_min_ns, delay_max_ns, _P_DELAY)
        eb.after(d, user_kind(_H_TICK), ctx.node)
        return ctx.state, eb.build()

    def on_tick(ctx):
        st = ctx.state
        count = st[0] + jnp.int32(1)
        bits = ctx.draw.user(_P_VALUE).astype(jnp.int32)
        new = st.at[0].set(count).at[1].set(st[1] ^ bits)
        done = count >= jnp.int32(rounds)
        eb = ctx.emits()
        d = ctx.draw.user_int(delay_min_ns, delay_max_ns, _P_DELAY)
        eb.after(d, user_kind(_H_TICK), ctx.node, when=~done)
        eb.halt(when=done)
        return new, eb.build()

    return Workload(
        name="microbench",
        handler_names=("init", "tick"),
        n_nodes=1,
        state_width=4,
        handlers=(on_init, on_tick),
        max_emits=2,
        # largest timer: the tick delay draw (time32 eligibility)
        delay_bound_ns=delay_max_ns,
        # no handler reads past args[1]
        args_words=2,
        # prefetch the tick draws into the step's batched RNG block
        # (engine BatchRNG — see models/raftlog.py for the rule)
        draw_purposes=(_P_DELAY, _P_VALUE),
    )
