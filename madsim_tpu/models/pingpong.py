"""3-node ping-pong RPC (BASELINE.md config 1 — the tonic-example shape).

One server (node 0) and two clients (nodes 1, 2): each client sends
``rounds`` pings, the server answers each with a pong carrying the same
sequence number (the unary-RPC pattern of the reference's
tonic-example/src/server.rs), and the run halts when both clients have
finished. Exercises the full send -> latency -> deliver -> reply path.

Server state: [completed_clients, pings_served, 0, 0]
Client state: [next_seq, 0, 0, 0]
"""

from __future__ import annotations

import jax.numpy as jnp

from ..engine import Workload, user_kind

_H_INIT = 0
_H_PING = 1  # at server: args = (seq, client)
_H_PONG = 2  # at client: args = (seq,)
_H_DONE = 3  # at server: client finished

SERVER = 0


def make_pingpong(rounds: int = 10, n_clients: int = 2) -> Workload:
    n = 1 + n_clients

    def on_init(ctx):
        eb = ctx.emits()
        is_client = ctx.node != jnp.int32(SERVER)
        eb.send(SERVER, user_kind(_H_PING), (jnp.int32(0), ctx.node), when=is_client)
        return ctx.state, eb.build()

    def on_ping(ctx):
        seq, client = ctx.args[0], ctx.args[1]
        new = ctx.state.at[1].set(ctx.state[1] + 1)
        eb = ctx.emits()
        eb.send(client, user_kind(_H_PONG), (seq,))
        return new, eb.build()

    def on_pong(ctx):
        seq = ctx.args[0] + jnp.int32(1)
        new = ctx.state.at[0].set(seq)
        done = seq >= jnp.int32(rounds)
        eb = ctx.emits()
        eb.send(SERVER, user_kind(_H_PING), (seq, ctx.node), when=~done)
        eb.send(SERVER, user_kind(_H_DONE), (), when=done)
        return new, eb.build()

    def on_done(ctx):
        finished = ctx.state[0] + jnp.int32(1)
        new = ctx.state.at[0].set(finished)
        eb = ctx.emits()
        eb.halt(when=finished >= jnp.int32(n_clients))
        return new, eb.build()

    return Workload(
        name="pingpong",
        handler_names=("init", "ping", "pong", "done"),
        n_nodes=n,
        state_width=4,
        handlers=(on_init, on_ping, on_pong, on_done),
        max_emits=2,
        # no user timers at all; sends ride latency draws only
        delay_bound_ns=0,
        # handlers read args[0:2] (round, client)
        args_words=2,
    )
