"""Lai-Yang distributed snapshot over a money-transfer workload — the
ninth oracle-verified family, covering a mechanism class none of the
others do: **global consistent cuts under message reordering**.

The classic snapshot setting (Chandy-Lamport needs FIFO channels; the
engine's per-message random latency deliberately reorders, so this
family implements Lai-Yang coloring, which is correct on non-FIFO
channels): every node starts with ``balance`` units and makes
``n_sends`` random transfers to random peers on random timers. At a
drawn time the initiator (node 0) goes **red** and records its
balance; every message carries its sender's color, and

* a white node receiving a RED message records its balance FIRST
  (turning red), then applies the amount — the amount is post-cut;
* a red node receiving a WHITE message applies the amount AND records
  it as channel state (sent pre-cut, received post-cut);
* on turning red a node broadcasts a zero-amount red "paint" transfer
  so color reaches nodes nobody happens to pay (loss-free family —
  lost money would break the very invariant under test).

The snapshot invariant — **conservation over the cut**:
``sum(recorded balances) + sum(recorded channel state) == n_nodes *
balance`` exactly, even though no two nodes record at the same virtual
instant and transfers are in flight across the cut. Termination rides
a witness count: every transfer (real or paint) sends a delivery
notice to node 0, which halts the instance when all
``n_nodes*n_sends + n_nodes*(n_nodes-1)`` messages have landed —
reachable only after every node turned red.

This is the distributed analog of the aux checkpoint story (SURVEY §5
checkpoint/resume): a *consistent* state capture taken while the
system keeps running, with the cut's correctness machine-checked per
seed. Reference anchor: the fault-model machinery it runs on is the
same NetSim semantics as every family (mod.rs:265-302 send path).

State row: [color, bal, rec_bal, chan_in, sent, rcnt]
"""

from __future__ import annotations

import jax.numpy as jnp

from ..engine import Workload, user_kind

_H_INIT = 0
_H_SEND = 1      # per-node transfer timer
_H_TRANSFER = 2  # args = (amount, sender_color); paints are amount 0
_H_SNAP = 3      # snapshot start (initiator only)
_H_RECVD = 4     # delivery notice, counted by the witness (node 0)

COLOR, BAL, RECBAL, CHANIN, SENT, RCNT = range(6)

_P_SEND = 0
_P_DST = 1
_P_AMT = 2
_P_SNAP = 3


def make_snapshot(
    n_nodes: int = 5,
    n_sends: int = 6,
    balance: int = 1000,
    amount_max: int = 100,
    send_min_ns: int = 5_000_000,
    send_max_ns: int = 25_000_000,
    snap_min_ns: int = 20_000_000,
    snap_max_ns: int = 80_000_000,
) -> Workload:
    n = n_nodes
    total_msgs = n * n_sends + n * (n - 1)
    peers = list(range(n))

    def _arm_send(ctx, eb, when):
        d = ctx.draw.user_int(send_min_ns, send_max_ns, _P_SEND)
        eb.after(d, user_kind(_H_SEND), ctx.node, (), when=when)

    def _paints(ctx, eb, when):
        # zero-amount red transfers to every peer: color propagation
        for p in peers:
            eb.send(
                p,
                user_kind(_H_TRANSFER),
                (jnp.int32(0), jnp.int32(1)),
                when=when & (jnp.int32(p) != ctx.node),
            )

    def on_init(ctx):
        eb = ctx.emits()
        _arm_send(ctx, eb, True)
        snap_d = ctx.draw.user_int(snap_min_ns, snap_max_ns, _P_SNAP)
        eb.after(
            snap_d, user_kind(_H_SNAP), ctx.node, (),
            when=ctx.node == jnp.int32(0),
        )
        new = ctx.state.at[BAL].set(jnp.int32(balance))
        return new, eb.build()

    def on_send(ctx):
        st = ctx.state
        fire = st[SENT] < jnp.int32(n_sends)
        r = ctx.draw.user_int(0, n - 1, _P_DST)          # [0, n-1)
        dst = (ctx.node + jnp.int32(1) + jnp.asarray(r, jnp.int32)) \
            % jnp.int32(n)                               # never self
        amt = jnp.asarray(
            ctx.draw.user_int(1, amount_max + 1, _P_AMT), jnp.int32
        )
        new = jnp.where(
            fire, st.at[BAL].add(-amt).at[SENT].add(1), st
        )
        eb = ctx.emits()
        eb.send(dst, user_kind(_H_TRANSFER), (amt, st[COLOR]), when=fire)
        _arm_send(ctx, eb, fire & (st[SENT] + 1 < jnp.int32(n_sends)))
        return new, eb.build()

    def on_transfer(ctx):
        st = ctx.state
        amt, mcolor = ctx.args[0], ctx.args[1]
        was_white = st[COLOR] == jnp.int32(0)
        msg_red = mcolor == jnp.int32(1)
        turn = was_white & msg_red
        # Lai-Yang receive rules, in order: record BEFORE applying a
        # first red message; count a white arrival at a red node as
        # channel state; always apply the amount
        st1 = jnp.where(
            turn, st.at[COLOR].set(1).at[RECBAL].set(st[BAL]), st
        )
        chan = (~was_white) & (~msg_red)
        st2 = jnp.where(chan, st1.at[CHANIN].add(amt), st1)
        new = st2.at[BAL].add(amt)
        eb = ctx.emits()
        _paints(ctx, eb, turn)
        eb.send(jnp.int32(0), user_kind(_H_RECVD), ())
        return new, eb.build()

    def on_snap(ctx):
        st = ctx.state
        turn = st[COLOR] == jnp.int32(0)
        new = jnp.where(
            turn, st.at[COLOR].set(1).at[RECBAL].set(st[BAL]), st
        )
        eb = ctx.emits()
        _paints(ctx, eb, turn)
        return new, eb.build()

    def on_recvd(ctx):
        st = ctx.state
        cnt = st[RCNT] + jnp.int32(1)
        new = st.at[RCNT].set(cnt)
        eb = ctx.emits()
        eb.halt(when=cnt == jnp.int32(total_msgs))
        return new, eb.build()

    return Workload(
        name="snapshot",
        handler_names=("init", "send", "transfer", "snap", "recvd"),
        n_nodes=n,
        state_width=6,
        handlers=(on_init, on_send, on_transfer, on_snap, on_recvd),
        # transfer: n paint slots (self slot statically present, when
        # =False) + 1 notice
        max_emits=max(n + 1, 2),
        delay_bound_ns=max(send_max_ns, snap_max_ns),
        args_words=2,
    )
