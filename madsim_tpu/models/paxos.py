"""Single-decree Paxos with dueling proposers and proposer-crash chaos.

The eighth oracle-verified protocol family: ``n_acceptors`` acceptors
(nodes ``0..A-1``) and ``n_proposers`` proposers (nodes ``A..A+P-1``)
run classic synod consensus. Every proposer wants its own value chosen
(value ``pidx+1``), ballots are globally unique by construction
(``ballot = round*P + pidx + 1``), and random per-round timeouts break
the dueling-proposers livelock. Chaos kills one random PROPOSER
mid-protocol and restarts it later; a reborn proposer re-runs on_init
with wiped RAM and simply starts proposing again from round 0, getting
NACK-fast-forwarded to a live ballot. Acceptors are never killed:
their (promised, accepted) state is the protocol's stable storage, and
single-decree safety genuinely requires it — killing an acceptor
models losing its disk, which real Paxos does not survive either.

Message flow (standard synod, with NACKs for liveness):

* PREPARE(b) -> acceptor: grant iff ``b > promised``; reply
  PROMISE(b, accepted_bal, accepted_val) or NACK(promised).
* majority of PROMISEs -> proposer adopts the highest-ballot accepted
  value it heard (or its own if none) and broadcasts ACCEPT(b, v).
* ACCEPT(b, v) -> acceptor: ok iff ``b >= promised``; accept + reply
  ACCEPTED(b), else NACK(promised).
* majority of ACCEPTEDs -> chosen: the proposer records the decision
  and broadcasts DECIDED(v) to every proposer plus acceptor 0, whose
  receipt halts the instance.
* NACK(b') with ``b' > ballot`` abandons the round and fast-forwards
  the round counter so the next ballot exceeds ``b'``.

Safety invariants checked at halt (tests/test_engine.py and the chaos
search): **agreement** — every nonzero decided value is the same;
**validity** — the decision is some proposer's value (1..P); and the
acceptor-majority witness — at least a majority of acceptors hold
``accepted_val == decision`` (the choosing majority can only move to
higher ballots carrying the chosen value).

Acceptor state row: [promised, accepted_bal, accepted_val, 0, ...]
Proposer state row: [phase(0=idle 1=prepare 2=accept 3=done), ballot,
                     value, promise_count, best_bal, best_val,
                     accept_count, decided, round, timer_seq]
"""

from __future__ import annotations

import jax.numpy as jnp

from ..check.history import OP_USER
from ..engine import KIND_KILL, KIND_RESTART, HistorySpec, Workload, user_kind

# history op kind (record=True): a decide event, recorded when a
# proposer first reaches a choosing majority AND when any proposer
# first adopts a decision it hears — key 0 (single decree), arg = the
# decided value. check.election_safety(h, elect_op=OP_DECIDE) is then
# paxos agreement over every decision *observed* along the run, not
# just the survivors' final state.
OP_DECIDE = OP_USER

_H_INIT = 0
_H_PROPOSE = 1  # at proposer (timer): args = (tseq,)
_H_PREPARE = 2  # at acceptor: args = (ballot,)
_H_PROMISE = 3  # at proposer: args = (ballot, acc_bal, acc_val)
_H_ACCEPT = 4  # at acceptor: args = (ballot, value)
_H_ACCEPTED = 5  # at proposer: args = (ballot,)
_H_DECIDED = 6  # anywhere: args = (value,)
_H_NACK = 7  # at proposer: args = (promised,)

# acceptor columns
A_PROM, A_BAL, A_VAL = 0, 1, 2
# proposer columns
P_PHASE, P_BAL, P_VAL, P_PCNT, P_BESTB, P_BESTV, P_ACNT, P_DEC, P_ROUND, P_TSEQ = (
    range(10)
)
IDLE, PREPARING, ACCEPTING, DONE = 0, 1, 2, 3

# user draw purposes
_P_START = 0
_P_TIMEOUT = 1
_P_KILL_AT = 2
_P_KILL_WHO = 3
_P_REVIVE = 4


def make_paxos(
    n_acceptors: int = 5,
    n_proposers: int = 3,
    start_min_ns: int = 5_000_000,
    start_max_ns: int = 30_000_000,
    timeout_min_ns: int = 60_000_000,
    timeout_max_ns: int = 120_000_000,
    chaos: bool = True,
    kill_min_ns: int = 30_000_000,
    kill_max_ns: int = 150_000_000,
    revive_min_ns: int = 80_000_000,
    revive_max_ns: int = 300_000_000,
    durable_acceptors: bool = False,
    record: bool = False,
) -> Workload:
    """``record=True`` turns on operation-history recording
    (madsim_tpu.check): every decision — a proposer reaching a choosing
    majority, and every first adoption of a DECIDED message — records an
    instantaneous ``OP_DECIDE`` event (key 0, arg = value), so
    ``check.election_safety`` asserts agreement over every decision
    observed along the way (a reborn proposer adopting a *different*
    value would be invisible to the final state once overwritten).

    ``durable_acceptors=True`` gives every node durable columns 0-2
    (``Workload.durable_cols`` — the FsSim power-fail analog) and aims
    the chaos kill at an ACCEPTOR (from ``1..A-1``; acceptor 0 is the
    halt witness) instead of a proposer: classic paxos with real
    stable storage, where an acceptor crash loses its RAM and in-flight
    messages but its (promised, accepted) disk survives — the exact
    condition single-decree safety requires."""
    a, p = n_acceptors, n_proposers
    if durable_acceptors and a < 2:
        raise ValueError(
            "durable_acceptors needs n_acceptors >= 2: the kill target is "
            "drawn from acceptors 1..A-1 (acceptor 0 is the halt witness)"
        )
    n = a + p
    majority = a // 2 + 1
    acceptors = list(range(a))
    proposers = list(range(a, n))

    def _is_prop(node):
        return node >= jnp.int32(a)

    def _pidx(node):
        return node - jnp.int32(a)

    def _arm(ctx, eb, tseq, when, lo, hi, purpose):
        d = ctx.draw.user_int(lo, hi, purpose)
        eb.after(d, user_kind(_H_PROPOSE), ctx.node, (tseq,), when=when)

    def on_init(ctx):
        st = ctx.state
        is_prop = _is_prop(ctx.node)
        eb = ctx.emits()
        _arm(ctx, eb, jnp.int32(1), is_prop, start_min_ns, start_max_ns, _P_START)
        if chaos:
            # acceptor 0's t=0 init schedules the seed's chaos plan: one
            # PROPOSER killed and later restarted — or, with durable
            # acceptor storage, one ACCEPTOR (see factory docstring)
            first = (ctx.node == jnp.int32(0)) & (ctx.now == 0)
            if durable_acceptors:
                who = jnp.int32(1) + ctx.draw.user_int(
                    0, a - 1, _P_KILL_WHO
                ).astype(jnp.int32)
            else:
                who = jnp.int32(a) + ctx.draw.user_int(
                    0, p, _P_KILL_WHO
                ).astype(jnp.int32)
            at = ctx.draw.user_int(kill_min_ns, kill_max_ns, _P_KILL_AT)
            revive = ctx.draw.user_int(revive_min_ns, revive_max_ns, _P_REVIVE)
            eb.after(at, KIND_KILL, 0, (who,), when=first)
            eb.after(at + revive, KIND_RESTART, 0, (who,), when=first)
        new = jnp.where(is_prop, st.at[P_TSEQ].set(1), st)
        return new, eb.build()

    def on_propose(ctx):
        st = ctx.state
        live = (ctx.args[0] == st[P_TSEQ]) & _is_prop(ctx.node)
        fire = live & (st[P_DEC] == jnp.int32(0))
        # decided proposers keep the timer chain alive to re-deliver
        # DECIDED to the halt witness (acceptor 0) — the one message
        # with no other retry path; a lost copy would otherwise strand
        # a fully-decided system un-halted
        redeliver = live & (st[P_DEC] != jnp.int32(0))
        ballot = st[P_ROUND] * jnp.int32(p) + _pidx(ctx.node) + jnp.int32(1)
        new = jnp.where(
            fire,
            st.at[P_PHASE].set(PREPARING)
            .at[P_BAL].set(ballot)
            .at[P_PCNT].set(0)
            .at[P_BESTB].set(0)
            .at[P_BESTV].set(0)
            .at[P_ACNT].set(0)
            .at[P_ROUND].set(st[P_ROUND] + 1)
            .at[P_TSEQ].set(st[P_TSEQ] + 1),
            jnp.where(redeliver, st.at[P_TSEQ].set(st[P_TSEQ] + 1), st),
        )
        eb = ctx.emits()
        eb.send(0, user_kind(_H_DECIDED), (st[P_DEC],), when=redeliver)
        for acc in acceptors:
            eb.send(acc, user_kind(_H_PREPARE), (ballot,), when=fire)
        # the retry chain: a fresh timer per attempt, tseq-guarded so
        # only the latest fires (stale timers are no-ops)
        _arm(
            ctx, eb, st[P_TSEQ] + 1, fire | redeliver,
            timeout_min_ns, timeout_max_ns, _P_TIMEOUT,
        )
        return new, eb.build()

    def on_prepare(ctx):
        st = ctx.state
        b = ctx.args[0]
        grant = b > st[A_PROM]
        new = jnp.where(grant, st.at[A_PROM].set(b), st)
        eb = ctx.emits()
        eb.send(
            ctx.src, user_kind(_H_PROMISE), (b, st[A_BAL], st[A_VAL]), when=grant
        )
        eb.send(ctx.src, user_kind(_H_NACK), (st[A_PROM],), when=~grant)
        return new, eb.build()

    def on_promise(ctx):
        st = ctx.state
        b, abal, aval = ctx.args[0], ctx.args[1], ctx.args[2]
        relevant = (st[P_PHASE] == jnp.int32(PREPARING)) & (b == st[P_BAL])
        pcnt = jnp.where(relevant, st[P_PCNT] + 1, st[P_PCNT])
        better = relevant & (abal > st[P_BESTB])
        bestb = jnp.where(better, abal, st[P_BESTB])
        bestv = jnp.where(better, aval, st[P_BESTV])
        won = relevant & (pcnt >= jnp.int32(majority))
        # paxos's value rule: adopt the highest-ballot accepted value
        # heard in the promise quorum, else propose our own
        own = _pidx(ctx.node) + jnp.int32(1)
        value = jnp.where(bestb > 0, bestv, own)
        new = (
            st.at[P_PCNT].set(pcnt)
            .at[P_BESTB].set(bestb)
            .at[P_BESTV].set(bestv)
            .at[P_PHASE].set(jnp.where(won, jnp.int32(ACCEPTING), st[P_PHASE]))
            .at[P_VAL].set(jnp.where(won, value, st[P_VAL]))
            .at[P_ACNT].set(jnp.where(won, 0, st[P_ACNT]))
        )
        eb = ctx.emits()
        for acc in acceptors:
            eb.send(acc, user_kind(_H_ACCEPT), (b, value), when=won)
        return new, eb.build()

    def on_accept(ctx):
        st = ctx.state
        b, v = ctx.args[0], ctx.args[1]
        ok = b >= st[A_PROM]
        new = jnp.where(
            ok, st.at[A_PROM].set(b).at[A_BAL].set(b).at[A_VAL].set(v), st
        )
        eb = ctx.emits()
        eb.send(ctx.src, user_kind(_H_ACCEPTED), (b,), when=ok)
        eb.send(ctx.src, user_kind(_H_NACK), (st[A_PROM],), when=~ok)
        return new, eb.build()

    def on_accepted(ctx):
        st = ctx.state
        b = ctx.args[0]
        relevant = (st[P_PHASE] == jnp.int32(ACCEPTING)) & (b == st[P_BAL])
        acnt = jnp.where(relevant, st[P_ACNT] + 1, st[P_ACNT])
        chosen = relevant & (acnt >= jnp.int32(majority))
        new = (
            st.at[P_ACNT].set(acnt)
            .at[P_PHASE].set(jnp.where(chosen, jnp.int32(DONE), st[P_PHASE]))
            .at[P_DEC].set(jnp.where(chosen, st[P_VAL], st[P_DEC]))
        )
        eb = ctx.emits()
        for prop in proposers:
            eb.send(
                prop, user_kind(_H_DECIDED), (st[P_VAL],),
                when=chosen & (jnp.int32(prop) != ctx.node),
            )
        # acceptor 0 is the halt witness: its DECIDED receipt freezes
        # the instance
        eb.send(0, user_kind(_H_DECIDED), (st[P_VAL],), when=chosen)
        if record:
            eb.record(OP_DECIDE, key=0, arg=st[P_VAL], when=chosen)
        return new, eb.build()

    def on_decided(ctx):
        st = ctx.state
        v = ctx.args[0]
        is_prop = _is_prop(ctx.node)
        new = jnp.where(
            is_prop,
            st.at[P_DEC].set(jnp.where(st[P_DEC] == 0, v, st[P_DEC]))
            .at[P_PHASE].set(DONE),
            st,
        )
        eb = ctx.emits()
        eb.halt(when=ctx.node == jnp.int32(0))
        if record:
            # first adoption only (P_DEC was 0): what this proposer now
            # believes was decided — disagreement here is the violation
            eb.record(
                OP_DECIDE, key=0, arg=v,
                when=is_prop & (st[P_DEC] == jnp.int32(0)),
            )
        return new, eb.build()

    def on_nack(ctx):
        st = ctx.state
        b = ctx.args[0]
        # a NACK naming a higher ballot kills this round: abandon it and
        # fast-forward so the next attempt's ballot exceeds what we saw
        act = (
            _is_prop(ctx.node)
            & (b > st[P_BAL])
            & (st[P_DEC] == jnp.int32(0))
        )
        ffwd = b // jnp.int32(p) + jnp.int32(1)
        new = jnp.where(
            act,
            st.at[P_PHASE].set(IDLE)
            .at[P_ROUND].set(jnp.maximum(st[P_ROUND], ffwd)),
            st,
        )
        return new, ctx.emits().build()

    return Workload(
        name="paxos-record" if record else "paxos",
        handler_names=(
            "init", "propose", "prepare", "promise", "accept", "accepted",
            "decided", "nack",
        ),
        n_nodes=n,
        state_width=10,
        handlers=(
            on_init, on_propose, on_prepare, on_promise, on_accept,
            on_accepted, on_decided, on_nack,
        ),
        # widest: on_propose (1 DECIDED redelivery + A prepares + 1
        # timer); on_accepted sends P-1 + 1 DECIDEDs; on_init arms 1
        # timer + 2 chaos events
        max_emits=max(a + 2, p + 1, 3),
        # largest timer: the chaos restart at 'at + revive'
        delay_bound_ns=max(timeout_max_ns, kill_max_ns + revive_max_ns),
        args_words=3,
        # acceptor stable storage (promised, accepted_bal, accepted_val)
        durable_cols=(A_PROM, A_BAL, A_VAL) if durable_acceptors else None,
        # decide records: <= 1 per chosen round + 1 first-adoption per
        # proposer incarnation; 32 covers deep re-proposal chains, and
        # overflow is loud (hist_drop) + quarantined by search_seeds
        history=HistorySpec(capacity=32, max_records=1) if record else None,
        # prefetch handler draws into the step's batched RNG block
        # (engine BatchRNG — see models/raftlog.py for the rule)
        draw_purposes=(_P_START, _P_TIMEOUT)
        + ((_P_KILL_AT, _P_KILL_WHO, _P_REVIVE) if chaos else ()),
    )


def lint_entries():
    """Tracing entry points for the static non-interference matrix
    (madsim_tpu.lint)."""
    kw = dict(pool_size=48, loss_p=0.02, clog_backoff_max_ns=2_000_000_000)
    return [
        ("paxos/plain", make_paxos(), kw),
        ("paxos/record", make_paxos(record=True), kw),
    ]


# Declared interval-certification horizon (lint.absint): a ballot
# settles within sim-seconds; 60 sim-seconds covers every recorded
# paxos run shape with an order of magnitude of slack.
ABSINT_HORIZON_NS = 60 * 1_000_000_000


def absint_entries():
    """Range-contract entry points for the interval prover
    (lint.absint): lint_entries rows plus the declared horizon."""
    return [
        (tag, wl, kw, ABSINT_HORIZON_NS)
        for tag, wl, kw in lint_entries()
    ]
