"""Batched simulation workloads ("models") for the engine.

Each module builds a :class:`madsim_tpu.engine.Workload`: per-node int32
state plus pure event handlers, the state-machine form in which user
programs enter the XLA-compiled step function. Every one has a
bit-identical C++ oracle implementation (native/oracle.cpp). The first
five cover the benchmark configs in BASELINE.md:

  1. pingpong    — 3-node ping-pong RPC (tonic-example shape)
  2. microbench  — single-node timer+rand loop (no network)
  3. broadcast   — 5-node broadcast under latency/loss/partition chaos
  4. raft        — 5-node leader election (the north-star workload)
  5. kvchaos     — replicated KV cluster with kill/restart chaos and a
                   majority-durability invariant
  6. twophase    — two-phase commit with stored votes, phase-aware
                   retransmits and participant crash/recovery
  7. raftlog     — raft log replication (single-inflight AppendEntries
                   with full-prefix install, lexicographic vote checks,
                   win-time re-stamp) under leader-crash chaos
  8. paxos       — single-decree Paxos (dueling proposers, NACK
                   fast-forward, acceptor stable storage) under
                   proposer-crash chaos
  9. snapshot    — Lai-Yang distributed snapshot (consistent cut under
                   message reordering) over a money-transfer workload,
                   with an exact conservation invariant

Service-scale models (ISSUE 18 — the batched analogs of the reference
ecosystem's service simulators, no C++ oracle, verified by the
check-package detectors instead):

  10. leasekv    — etcd-style lease/watch KV: TTL leases under
                   keepalives, server-clock expiry scans (ClockSkew's
                   spurious-expiry surface) and a sequenced watch
                   stream with explicit resync
  11. shardkv    — sharded KV with key-range migration: config epochs,
                   freeze/handoff/install/release rebalancing, 14
                   nodes by default (the first N=12+ model)
"""

from .microbench import make_microbench  # noqa: F401
from .pingpong import make_pingpong  # noqa: F401
from .broadcast import make_broadcast  # noqa: F401
from .raft import make_raft  # noqa: F401
from .raftlog import make_raftlog  # noqa: F401
from .kvchaos import make_kvchaos  # noqa: F401
from .twophase import make_twophase  # noqa: F401
from .paxos import make_paxos  # noqa: F401
from .snapshot import make_snapshot  # noqa: F401
from .leasekv import make_leasekv  # noqa: F401
from .shardkv import make_shardkv  # noqa: F401

# The BASELINE.md benchmark configurations, shared by bench.py and
# examples/cross_backend_check.py so the cross-backend determinism
# artifact certifies exactly the configuration the benchmark reports:
#   name -> (factory, engine-config kwargs, bench seed count, step cap)
# clog_backoff_max_ns is capped at 2 s (default: the reference's 10 s
# pump cap, net/mod.rs:341-355) so every config passes time32_eligible
# and accelerators run int32 event times; a 2 s retry ceiling is far
# beyond any of these scenarios' clog windows (<= 0.5 s), so the cap
# itself never binds
_B2 = {"clog_backoff_max_ns": 2_000_000_000}
BENCH_SPECS = {
    # pool sizes: the (S, E) pool is the step's memory-traffic term, so
    # each config runs the smallest pool verified overflow-free over
    # every seed range the bench AND sweep actually run (raft:
    # 0..524287; broadcast/kvchaos: 0..131071) — overflow is loud,
    # bench.py refuses any run that drops events. raftlog needs 64
    # (56 drops events: measured 36 over 32k seeds)
    "raft": (make_raft, dict(pool_size=40, loss_p=0.02, **_B2), 65536, 600),
    "microbench": (make_microbench, dict(pool_size=32, **_B2), 1024, 1100),
    "pingpong": (make_pingpong, dict(pool_size=32, **_B2), 1, 300),
    "broadcast": (make_broadcast, dict(pool_size=40, loss_p=0.05, **_B2), 16384, 500),
    "kvchaos": (make_kvchaos, dict(pool_size=40, loss_p=0.02, **_B2), 4096, 900),
    # beyond the 5 BASELINE configs: the raft log-replication family
    # (protocol depth on the north-star workload; reported, non-headline)
    "raftlog": (make_raftlog, dict(pool_size=64, loss_p=0.02, **_B2), 16384, 4000),
}
