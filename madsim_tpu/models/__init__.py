"""Batched simulation workloads ("models") for the engine.

Each module builds a :class:`madsim_tpu.engine.Workload`: per-node int32
state plus pure event handlers, the state-machine form in which user
programs enter the XLA-compiled step function. These four cover the
benchmark configs in BASELINE.md:

  1. pingpong    — 3-node ping-pong RPC (tonic-example shape)
  2. microbench  — single-node timer+rand loop (no network)
  3. broadcast   — 5-node broadcast under latency/loss/partition chaos
  4. raft        — 5-node leader election (the north-star workload)
  5. kvchaos     — replicated KV cluster with kill/restart chaos and a
                   majority-durability invariant
"""

from .microbench import make_microbench  # noqa: F401
from .pingpong import make_pingpong  # noqa: F401
from .broadcast import make_broadcast  # noqa: F401
from .raft import make_raft  # noqa: F401
from .kvchaos import make_kvchaos  # noqa: F401
