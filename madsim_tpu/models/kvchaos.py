"""Replicated KV cluster under chaos (BASELINE.md config 5 shape).

The batched analog of the reference ecosystem's service-simulator chaos
tests (etcd/kafka clusters driven by seeded chaos schedules): a
primary-backup KV store — one primary, ``n_replicas`` backups, one
client — where every write must be acknowledged by a majority before the
client sees a commit. The seed schedules replica kills and restarts
mid-stream; retransmits and re-acks must preserve the invariant the test
checks: **every committed write is durable on a majority of replicas**.

The run halts when ``writes`` commits have been acknowledged.

Node layout: [primary, replicas 1..R, client R+1]
Primary state:  [committed_seq, inflight_seq, ack_mask, 0]
Replica state:  [last_applied_seq, applies, 0, 0]
Client state:   [commits_seen, 0, 0, 0]
"""

from __future__ import annotations

import jax.numpy as jnp

from ..engine import KIND_KILL, KIND_RESTART, Workload, user_kind

_H_INIT = 0
_H_WRITE = 1  # at primary: args = (seq,)
_H_REPL = 2  # at replica: args = (seq,)
_H_ACK = 3  # at primary: args = (seq, replica)
_H_COMMIT = 4  # at client: args = (seq,)
_H_RETX = 5  # at primary: args = (seq,)

PRIMARY = 0

_P_KILL_AT = 0
_P_KILL_WHO = 1
_P_REVIVE = 2


def make_kvchaos(
    writes: int = 20,
    n_replicas: int = 4,
    retx_ns: int = 40_000_000,
    chaos: bool = True,
) -> Workload:
    n = 1 + n_replicas + 1
    client = n - 1
    replicas = list(range(1, 1 + n_replicas))
    majority = n_replicas // 2 + 1

    def _replicate(eb, seq, when, mask=None):
        for i, r in enumerate(replicas):
            w = when if mask is None else (when & (((mask >> i) & 1) == 0))
            eb.send(r, user_kind(_H_REPL), (seq,), when=w)

    def on_init(ctx):
        eb = ctx.emits()
        is_client = ctx.node == jnp.int32(client)
        # client issues the first write
        eb.send(PRIMARY, user_kind(_H_WRITE), (jnp.int32(1),), when=is_client)
        if chaos:
            # the client doubles as the chaos scheduler: kill a random
            # replica partway through, restart it later
            who = ctx.draw.user_int(1, 1 + n_replicas, _P_KILL_WHO).astype(jnp.int32)
            at = ctx.draw.user_int(20_000_000, 300_000_000, _P_KILL_AT)
            revive = ctx.draw.user_int(100_000_000, 600_000_000, _P_REVIVE)
            eb.after(at, KIND_KILL, 0, (who,), when=is_client)
            eb.after(at + revive, KIND_RESTART, 0, (who,), when=is_client)
        return ctx.state, eb.build()

    def on_write(ctx):
        seq = ctx.args[0]
        st = ctx.state
        fresh = seq > st[0]
        new = jnp.where(
            fresh, st.at[1].set(seq).at[2].set(0), st
        )
        eb = ctx.emits()
        _replicate(eb, seq, fresh)
        eb.after(retx_ns, user_kind(_H_RETX), PRIMARY, (seq,), when=fresh)
        return new, eb.build()

    def on_repl(ctx):
        seq = ctx.args[0]
        st = ctx.state
        new = st.at[0].set(jnp.maximum(st[0], seq)).at[1].set(st[1] + 1)
        eb = ctx.emits()
        eb.send(PRIMARY, user_kind(_H_ACK), (seq, ctx.node))
        return new, eb.build()

    def on_ack(ctx):
        seq, who = ctx.args[0], ctx.args[1]
        st = ctx.state
        bit = jnp.int32(1) << (who - 1)
        current = (seq == st[1]) & (seq > st[0])
        mask = jnp.where(current, st[2] | bit, st[2])
        acks = jnp.zeros((), jnp.int32)
        for i in range(n_replicas):
            acks = acks + ((mask >> i) & 1)
        committed = current & (acks >= jnp.int32(majority))
        new = st.at[2].set(mask)
        new = jnp.where(committed, new.at[0].set(seq), new)
        eb = ctx.emits()
        eb.send(client, user_kind(_H_COMMIT), (seq,), when=committed)
        return new, eb.build()

    def on_commit(ctx):
        seq = ctx.args[0]
        st = ctx.state
        fresh = seq > st[0]
        new = jnp.where(fresh, ctx.state.at[0].set(seq), ctx.state)
        done = seq >= jnp.int32(writes)
        eb = ctx.emits()
        eb.send(
            PRIMARY, user_kind(_H_WRITE), (seq + 1,), when=fresh & ~done
        )
        eb.halt(when=fresh & done)
        return new, eb.build()

    def on_retx(ctx):
        seq = ctx.args[0]
        st = ctx.state
        pending = (seq == st[1]) & (seq > st[0])
        eb = ctx.emits()
        _replicate(eb, seq, pending, mask=st[2])
        eb.after(retx_ns, user_kind(_H_RETX), PRIMARY, (seq,), when=pending)
        return ctx.state, eb.build()

    return Workload(
        name="kvchaos",
        n_nodes=n,
        state_width=4,
        handlers=(on_init, on_write, on_repl, on_ack, on_commit, on_retx),
        max_emits=n_replicas + 2,
    )
