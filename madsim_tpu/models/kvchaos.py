"""Replicated KV cluster under chaos (BASELINE.md config 5 shape).

The batched analog of the reference ecosystem's service-simulator chaos
tests (etcd/kafka clusters driven by seeded chaos schedules): a
primary-backup KV store — one primary, ``n_replicas`` backups, one
client — where a write commits only after a majority of replicas ack.
The seed schedules a replica kill and restart mid-stream; every message
kind has a retry path (client re-sends writes, the primary re-replicates
and re-acks, restarted replicas rejoin and re-sync), so the protocol
makes progress through loss, partition-grade delays and the crash.

Halt condition (checked by the test): the client saw all ``writes``
commits (it sends FIN), **and** the primary's ack mask for the final
write is full. Replicas are RAM-only (restart wipes state, the power-
failure semantics of node reset), so the guarantee provable at halt is:
the final write was acked by every replica, and is still present on
every replica **except possibly one crashed after acking within the
final commit window** — i.e. durable on >= n_replicas-1 always, and on
all replicas whenever the crash/rejoin resolved before the last write
(the overwhelmingly common schedule; a restarted replica rejoins with
periodic JOINs and is re-synced by the retx loop before halt).

Node layout: [primary, replicas 1..R, client R+1]
Primary state:  [committed_seq, inflight_seq, ack_mask, fin_seen]
Replica state:  [last_applied_seq, applies, 0, 0]
Client state:   [commits_seen, last_read_rseq, 0, 0]
"""

from __future__ import annotations

import jax.numpy as jnp

from ..check.history import OK_OK, OK_PENDING, OP_READ, OP_WRITE
from ..engine import (
    KIND_KILL,
    KIND_RESTART,
    HistorySpec,
    Workload,
    retry_token_op,
    user_kind,
)

_H_INIT = 0
_H_WRITE = 1  # at primary: args = (seq,)
_H_REPL = 2  # at replica: args = (seq,)
_H_ACK = 3  # at primary: args = (seq, replica)
_H_COMMIT = 4  # at client: args = (seq,)
_H_RETX = 5  # at primary: args = (seq,)
_H_CRETX = 6  # at client: periodic progress retry
_H_FIN = 7  # at primary: client done
_H_JOIN = 8  # at primary: args = (replica,) — replica (re)joined
_H_JRETX = 9  # at replica: retry JOIN until synced
_H_READ = 10  # at primary: args = (rseq,) — record mode only
_H_READRESP = 11  # at client: args = (rseq, committed) — record mode only
_H_AREQ = 12  # at client: army op arrival, args = (op_id, word) — army mode
_H_APROBE = 13  # at primary: army probe, args = (op_id,)
_H_ARESP = 14  # at client: army response, args = (op_id, committed)

PRIMARY = 0

_P_KILL_AT = 0
_P_KILL_WHO = 1
_P_REVIVE = 2
_P_VAL0 = 8
_P_VAL1 = 9


def make_kvchaos(
    writes: int = 20,
    n_replicas: int = 4,
    retx_ns: int = 40_000_000,
    client_retx_ns: int = 100_000_000,
    chaos: bool = True,
    payload: bool = False,
    record: bool = False,
    hist_capacity: int | None = None,
    bug: bool = False,
    army: bool = False,
    army_probes: int = 1,
) -> Workload:
    """``payload=True`` turns on the engine payload arena: each WRITE
    carries two random int32 value words (drawn by the client, unknowable
    to replicas except via the message), the primary stores and
    re-replicates them, replicas store what they receive — real data
    transported through the batched network, oracle-verified since the
    payload words feed the trace hash.

    Payload state layout (state_width 6):
      Primary: [committed, inflight, mask, fin, v0, v1]
      Replica: [applied_seq, applies, v0, v1, 0, 0]
      Client:  [commits_seen, last_read_rseq, 0, 0, 0, 0]

    ``record=True`` turns on operation-history recording (the
    madsim_tpu.check workload check): the client records every write as
    an invoke/response pair (version = seq), and after each commit
    issues a best-effort READ through the primary, recording the
    committed version it returns. A stale-rseq gate (client slot 1)
    keeps reordered read responses out of the history. Capacity is
    sized at 4 records/write unless ``hist_capacity`` overrides it.

    ``bug=True`` plants a lost-write fault: when a replica (re)joins,
    the primary also forgets its commit point (committed_seq := 0).
    The protocol recovers — later acks re-commit everything, so final
    states (and the final-state durability invariant) look perfectly
    healthy — but a read landing in the regression window observes a
    committed write vanish, which only the history checkers can see.

    ``army=True`` opens the model's **client surface** for open-loop
    load (madsim_tpu.obs latency): a ``chaos.ClientArmy`` row arriving
    at the client node (``client_army`` builds the spec) marks the op's
    invoke, probes the primary, and the final response marks
    completion — client-observed latency through the authority, the
    quantity a tail SLO is stated over. ``army_probes=k`` makes each
    op a k-round SESSION (the probes chain sequentially; the op
    completes on the k-th response), the multi-round-operation shape
    real client calls have — under gray failure a session is slowed
    end to end only by SUSTAINED slowness, which is exactly what
    separates a windowed SLO breach from a blip. The probe path reads
    protocol state but never writes it, so army load measures (and
    perturbs the schedule of) the protocol without changing what it
    decides.
    """
    n = 1 + n_replicas + 1
    client = n - 1
    replicas = list(range(1, 1 + n_replicas))
    majority = n_replicas // 2 + 1
    full_mask = (1 << n_replicas) - 1
    width = 6 if payload else 4
    if bug and not record:
        raise ValueError(
            "bug=True plants a fault only histories can see; it requires "
            "record=True (otherwise nothing would ever detect it)"
        )

    def _client_value(ctx):
        """Two fresh random words for an outgoing WRITE (payload mode)."""
        v0 = ctx.draw.user(_P_VAL0).astype(jnp.int32)
        v1 = ctx.draw.user(_P_VAL1).astype(jnp.int32)
        return (v0, v1)

    def _replicate(eb, seq, when, mask, pay=()):
        for i, r in enumerate(replicas):
            eb.send(
                r, user_kind(_H_REPL), (seq,),
                when=when & (((mask >> i) & 1) == 0),
                pay=pay,
            )

    def on_init(ctx):
        eb = ctx.emits()
        is_client = ctx.node == jnp.int32(client)
        is_replica = (ctx.node >= 1) & (ctx.node <= jnp.int32(n_replicas))
        # client kicks off write 1 and its progress-retry timer
        eb.send(
            PRIMARY, user_kind(_H_WRITE), (jnp.int32(1),),
            when=is_client, pay=_client_value(ctx) if payload else (),
        )
        if record:  # write 1 is invoked here (retries are the same op)
            eb.record(OP_WRITE, 0, 1, ok=OK_PENDING, when=is_client)
        eb.after(client_retx_ns, user_kind(_H_CRETX), client, when=is_client)
        # replicas announce themselves — at t=0 and again after restart,
        # which is how the primary learns to re-sync a reborn replica;
        # retried by a timer until the first write applies (JOINs are
        # lossy like everything else)
        eb.send(PRIMARY, user_kind(_H_JOIN), (ctx.node,), when=is_replica)
        eb.after(retx_ns, user_kind(_H_JRETX), ctx.node, when=is_replica)
        if chaos:
            who = ctx.draw.user_int(1, 1 + n_replicas, _P_KILL_WHO).astype(jnp.int32)
            at = ctx.draw.user_int(20_000_000, 300_000_000, _P_KILL_AT)
            revive = ctx.draw.user_int(100_000_000, 600_000_000, _P_REVIVE)
            eb.after(at, KIND_KILL, 0, (who,), when=is_client)
            eb.after(at + revive, KIND_RESTART, 0, (who,), when=is_client)
        return ctx.state, eb.build()

    def on_write(ctx):
        seq = ctx.args[0]
        st = ctx.state
        fresh = (seq > st[0]) & (seq > st[1])
        new = jnp.where(fresh, st.at[1].set(seq).at[2].set(0), st)
        if payload:
            # the first WRITE to arrive for a seq fixes its value; the
            # primary stores it so retx re-sends the accepted value
            new = jnp.where(
                fresh,
                new.at[4].set(ctx.payload[0]).at[5].set(ctx.payload[1]),
                new,
            )
        eb = ctx.emits()
        pay = (new[4], new[5]) if payload else ()
        _replicate(eb, seq, fresh, jnp.int32(0), pay)
        eb.after(retx_ns, user_kind(_H_RETX), PRIMARY, (seq,), when=fresh)
        return new, eb.build()

    def on_repl(ctx):
        seq = ctx.args[0]
        st = ctx.state
        fresh = seq > st[0]
        new = st.at[0].set(jnp.maximum(st[0], seq)).at[1].set(st[1] + 1)
        if payload:
            new = jnp.where(
                fresh,
                new.at[2].set(ctx.payload[0]).at[3].set(ctx.payload[1]),
                new,
            )
        eb = ctx.emits()
        eb.send(PRIMARY, user_kind(_H_ACK), (seq, ctx.node))
        return new, eb.build()

    def _maybe_halt(eb, committed, mask, fin):
        eb.halt(
            when=(committed >= jnp.int32(writes))
            & (mask == jnp.int32(full_mask))
            & (fin > 0)
        )

    def on_ack(ctx):
        seq, who = ctx.args[0], ctx.args[1]
        st = ctx.state
        bit = jnp.int32(1) << (who - 1)
        current = seq == st[1]
        mask = jnp.where(current, st[2] | bit, st[2])
        acks = jnp.zeros((), jnp.int32)
        for i in range(n_replicas):
            acks = acks + ((mask >> i) & 1)
        committed_now = current & (seq > st[0]) & (acks >= jnp.int32(majority))
        committed = jnp.where(committed_now, seq, st[0])
        new = st.at[0].set(committed).at[2].set(mask)
        eb = ctx.emits()
        eb.send(
            client, user_kind(_H_COMMIT), (committed,),
            when=current & (committed >= seq),
        )
        _maybe_halt(eb, committed, mask, st[3])
        return new, eb.build()

    def on_commit(ctx):
        seq = ctx.args[0]
        st = ctx.state
        fresh = seq > st[0]
        new = jnp.where(fresh, st.at[0].set(seq), st)
        done = seq >= jnp.int32(writes)
        eb = ctx.emits()
        eb.send(
            PRIMARY, user_kind(_H_WRITE), (seq + 1,),
            when=fresh & ~done, pay=_client_value(ctx) if payload else (),
        )
        eb.send(PRIMARY, user_kind(_H_FIN), (), when=fresh & done)
        if record:
            # close the pending write op with its committed version,
            # then probe it: a best-effort READ through the primary,
            # rseq = seq so the client can order the responses. The
            # record order matters — the write response must precede the
            # read invoke so the read's version floor includes it.
            eb.record(OP_WRITE, 0, seq, ok=OK_OK, when=fresh)
            eb.record(OP_READ, 0, 0, ok=OK_PENDING, when=fresh)
            eb.send(PRIMARY, user_kind(_H_READ), (seq,), when=fresh)
            eb.record(OP_WRITE, 0, seq + 1, ok=OK_PENDING, when=fresh & ~done)
        return new, eb.build()

    def on_retx(ctx):
        seq = ctx.args[0]
        st = ctx.state
        current = seq == st[1]
        pending_repl = current & (st[2] != jnp.int32(full_mask))
        # committed but the client may not know (lost COMMIT): re-ack
        pending_commit = current & (st[0] >= seq)
        eb = ctx.emits()
        _replicate(
            eb, seq, pending_repl, st[2],
            (st[4], st[5]) if payload else (),
        )
        eb.send(client, user_kind(_H_COMMIT), (st[0],), when=pending_commit)
        eb.after(
            retx_ns, user_kind(_H_RETX), PRIMARY, (seq,),
            when=pending_repl | pending_commit,
        )
        return ctx.state, eb.build()

    def on_cretx(ctx):
        # client progress guard: re-send the write (or FIN) it is waiting
        # on — covers lost WRITEs/FINs outright
        st = ctx.state
        waiting = st[0] < jnp.int32(writes)
        eb = ctx.emits()
        eb.send(
            PRIMARY, user_kind(_H_WRITE), (st[0] + 1,), when=waiting,
            pay=_client_value(ctx) if payload else (),
        )
        eb.send(PRIMARY, user_kind(_H_FIN), (), when=~waiting)
        eb.after(client_retx_ns, user_kind(_H_CRETX), client)
        return ctx.state, eb.build()

    def on_fin(ctx):
        st = ctx.state
        new = st.at[3].set(1)
        eb = ctx.emits()
        _maybe_halt(eb, st[0], st[2], jnp.int32(1))
        return new, eb.build()

    def on_jretx(ctx):
        st = ctx.state
        behind = st[0] == 0
        eb = ctx.emits()
        eb.send(PRIMARY, user_kind(_H_JOIN), (ctx.node,), when=behind)
        eb.after(retx_ns, user_kind(_H_JRETX), ctx.node, when=behind)
        return ctx.state, eb.build()

    def on_read(ctx):
        # record mode: answer a client history probe with the current
        # commit point. Reads route through the authority for the key,
        # so a version below the client's floor means a committed
        # write's effect vanished (check.vectorized.stale_reads).
        rseq = ctx.args[0]
        st = ctx.state
        eb = ctx.emits()
        eb.send(client, user_kind(_H_READRESP), (rseq, st[0]))
        return ctx.state, eb.build()

    def on_readresp(ctx):
        rseq, committed = ctx.args[0], ctx.args[1]
        st = ctx.state
        # stale-rseq gate: only in-invoke-order responses enter the
        # history — a reordered older response would close the wrong
        # pending invoke under FIFO pairing and could false-flag. The
        # gated-out read simply stays pending, which constrains nothing.
        fresh_r = rseq > st[1]
        new = jnp.where(fresh_r, st.at[1].set(rseq), st)
        eb = ctx.emits()
        if record:
            eb.record(OP_READ, 0, committed, ok=OK_OK, when=fresh_r)
        return new, eb.build()

    def on_join(ctx):
        # a replica (re)joined with empty state: clear its ack bit so the
        # retx loop re-replicates the current write to it
        who = ctx.args[0]
        st = ctx.state
        bit = jnp.int32(1) << (who - 1)
        mask = st[2] & ~bit
        new = st.at[2].set(mask)
        if bug:
            # planted lost-write fault: re-admitting a replica also
            # forgets the commit point. The protocol recovers — later
            # acks re-commit everything and every final state looks
            # healthy — but a READ landing in the regression window
            # observes a committed write vanish, which only the
            # operation-history checkers can see.
            new = new.at[0].set(0)
        eb = ctx.emits()
        # the retx timer may have died while the mask was full: re-arm
        eb.after(
            retx_ns, user_kind(_H_RETX), PRIMARY, (st[1],), when=st[1] > 0
        )
        return new, eb.build()

    if army_probes < 1:
        raise ValueError(f"army_probes must be >= 1, got {army_probes}")

    def on_areq(ctx):
        # army op arrival at the client (a ClientArmy pool row): mark
        # the invoke and open the session — args[1] carries the number
        # of probe rounds still owed after this one. The client itself
        # never re-offers — an open-loop army does not slow down (or
        # retry on its own) because the system is struggling; a modeled
        # RetryPolicy re-delivers THIS handler with the attempt id in
        # the token's high bits, so the op id is stripped (identity for
        # plain attempt-0 tokens) and first-start-wins keeps the
        # latency clock spanning first invoke -> final response.
        op_id = retry_token_op(ctx.args[0])
        eb = ctx.emits()
        eb.lat_start(op_id)
        eb.send(
            PRIMARY, user_kind(_H_APROBE),
            (op_id, jnp.int32(army_probes - 1)),
        )
        return ctx.state, eb.build()

    def on_aprobe(ctx):
        # the authority echoes the session's remaining-round count: a
        # read-only probe — protocol state is never written here
        eb = ctx.emits()
        eb.send(client, user_kind(_H_ARESP), (ctx.args[0], ctx.args[1]))
        return ctx.state, eb.build()

    def on_aresp(ctx):
        op_id, k = ctx.args[0], ctx.args[1]
        eb = ctx.emits()
        # rounds remaining: chain the next probe; 0 = session complete
        eb.send(
            PRIMARY, user_kind(_H_APROBE), (op_id, k - 1), when=k > 0
        )
        eb.lat_end(op_id, when=k == 0)
        return ctx.state, eb.build()

    # capacity sizing (see HistorySpec docstring): per write exactly one
    # invoke + one response + one read invoke + at most one read
    # response = 4 records; nothing else records
    hist = None
    if record:
        # every write contributes one write op + at most one read op,
        # all on key 0 — a single register whose exact-checker history
        # (check_register, reached via check_kv) is bounded at 63 ops
        if 2 * writes > 63:
            raise ValueError(
                f"record=True supports at most 31 writes: {writes} "
                f"writes record up to {2 * writes} ops on the single "
                f"key, past the 63-op bound of the exact checker "
                f"(check/linearize.py); lower writes or record "
                f"without the exact sweep"
            )
        cap = 4 * writes if hist_capacity is None else hist_capacity
        hist = HistorySpec(capacity=cap, max_records=3)

    name = "kvchaos-payload" if payload else "kvchaos"
    if record:
        name += "-bug" if bug else "-record"
    if army:
        name += "-army"
    handler_names = (
        "init", "write", "repl", "ack", "commit", "retx", "cretx",
        "fin", "join", "jretx", "read", "readresp",
    )
    handlers = (
        on_init, on_write, on_repl, on_ack, on_commit, on_retx,
        on_cretx, on_fin, on_join, on_jretx, on_read, on_readresp,
    )
    if army:
        handler_names += ("areq", "aprobe", "aresp")
        handlers += (on_areq, on_aprobe, on_aresp)
    return Workload(
        name=name,
        handler_names=handler_names,
        n_nodes=n,
        state_width=width,
        handlers=handlers,
        # on_init builds up to 5 rows (write/cretx + join/jretx + 2 chaos);
        # on_retx builds n_replicas+2
        max_emits=max(n_replicas + 2, 6),
        # largest timer: chaos restart at 'at + revive' <= 300 ms + 600 ms
        delay_bound_ns=max(retx_ns, client_retx_ns, 900_000_000),
        # handlers read args[0:2] (seq/who, rseq/committed)
        args_words=2,
        payload_words=2 if payload else 0,
        history=hist,
        # army mode: at most one lat_start OR lat_end per invocation
        lat_markers=1 if army else 0,
        # prefetch handler draws into the step's batched RNG block
        # (engine BatchRNG — see models/raftlog.py for the rule)
        draw_purposes=((_P_KILL_AT, _P_KILL_WHO, _P_REVIVE) if chaos else ())
        + ((_P_VAL0, _P_VAL1) if payload else ()),
    )


def client_army(
    n_ops: int = 256,
    t_min_ns: int = 20_000_000,
    t_max_ns: int = 400_000_000,
    n_replicas: int = 4,
    op_base: int = 0,
    retry=None,
):
    """A :class:`chaos.ClientArmy` bound to kvchaos's client surface
    (``make_kvchaos(army=True)`` with the same ``n_replicas``): ops
    arrive at the client node and probe the primary. Compose it into a
    ``FaultPlan`` next to the chaos specs and run the sweep with
    ``latency=LatencySpec(ops >= op_base + n_ops)``. ``retry`` attaches
    a :class:`chaos.RetryPolicy` (build the engine with
    ``retry=plan.retry_spec()``)."""
    from ..chaos.plan import ClientArmy

    return ClientArmy(
        node=1 + n_replicas,  # [primary, replicas 1..R, client R+1]
        kind=user_kind(_H_AREQ),
        n_ops=n_ops,
        t_min_ns=t_min_ns,
        t_max_ns=t_max_ns,
        op_base=op_base,
        retry=retry,
    )


def lint_entries():
    """Tracing entry points for the static non-interference matrix
    (madsim_tpu.lint); the payload variant rides along so the proof
    covers the payload-arena trace fold too, and the army variant so
    the latency-marker path (lat_start/lat_end writes) proves isolated
    under the latency build axis."""
    kw = dict(pool_size=40, loss_p=0.02, clog_backoff_max_ns=2_000_000_000)
    return [
        ("kvchaos/plain", make_kvchaos(), kw),
        ("kvchaos/record", make_kvchaos(record=True, payload=True), kw),
        ("kvchaos/army", make_kvchaos(army=True), kw),
    ]


# Declared interval-certification horizon (lint.absint): client-army
# load windows span sim-seconds; 300 sim-seconds is generous slack
# over every recorded kvchaos hunt shape.
ABSINT_HORIZON_NS = 300 * 1_000_000_000


def absint_entries():
    """Range-contract entry points for the interval prover
    (lint.absint): lint_entries rows plus the declared horizon."""
    return [
        (tag, wl, kw, ABSINT_HORIZON_NS)
        for tag, wl, kw in lint_entries()
    ]
