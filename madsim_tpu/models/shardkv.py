"""Sharded KV with key-range migration under chaos (the first N=12+ model).

A configuration epoch maps ``n_shards`` key ranges onto ``n_groups``
replica groups (one primary + backups per group); a controller
rebalances by migrating one shard at a time: freeze the shard at its
source primary, hand the shard's version state to the destination
primary, and commit the new epoch only after the destination confirms
the install — the source keeps (frozen) data until the controller's
RELEASE, so a kill or lost message mid-migration can stall but never
lose or double-serve the range. This is the classic lost-shard bug
class, and with ``n_groups=4, group_size=3`` the fleet is 14 nodes —
the first model that actually stresses the per-node (N, N) slow and
partition state the 5-node protocol cores never scale.

Safety contract (check.shard_coverage over ``record=True`` histories):

1. per config epoch, every shard is owned by at most one group (no
   double-serve): no two install records share (shard, epoch) with
   different groups, and
2. no committed write is lost across a migration: every install's
   adopted version covers every write committed to that shard earlier
   in the history.

``bug=True`` plants the lost-shard mutant — the migration is "acked"
before the install is confirmed: the source releases the shard the
moment it sends the handoff, so a retried handoff (first one lost, or
the destination killed mid-install) re-sends from the already-wiped
state and the destination installs version 0, silently dropping every
committed write — exactly what clause 2 exists to catch.

Node layout: [controller 0, client 1, then group g's replicas at
2+g*R .. 2+g*R+R-1 (primary first)]
Primary/backup state: [ver(shard 0..S-1), epoch(shard 0..S-1), frozen]
Controller state:     [epoch, phase, mig_shard, mig_dst, assign0,
                       assign1, migs_done, fin_seen] (low columns)
Client state:         [epoch, acked, fin, -, assign0, assign1]

Shard assignment is packed 4 bits per shard into two 16-bit words
(``assign0`` shards 0..3, ``assign1`` shards 4..7-style split), so
S <= 8 groups-of-16 stay inside positive int32. All state columns are
durable (the nodes model disk-backed servers: a crash is an
availability + in-flight-message loss, not a RAM wipe), which is what
makes mid-migration kills recoverable by retry instead of fatal.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..check.history import OK_OK, OP_USER, pack_shard_own
from ..engine import (
    KIND_KILL,
    KIND_RESTART,
    HistorySpec,
    StateContract,
    Workload,
    retry_token_attempt,
    retry_token_op,
    user_kind,
)

# history op codes (check.shard_coverage reads these)
OP_SHARD_WRITE = OP_USER  # commit: key = shard, arg = version
OP_SHARD_OWN = OP_USER + 1  # install: key = shard, arg = packed
#                             (epoch, group, adopted version)
OP_ARMY_PUT = OP_USER + 2  # army apply: key = op id, arg = attempt —
#                            the stream check.exactly_once audits

_H_INIT = 0
_H_PUT_T = 1  # at client: write/progress timer
_H_WRITE = 2  # at primary: args = (shard, seq)
_H_REPL = 3  # at backup: args = (shard, ver)
_H_WRITE_OK = 4  # at client: args = (shard, seq)
_H_WRONG = 5  # at client: routed to a non-owner — refetch config
_H_CFG_REQ = 6  # at controller
_H_CFG = 7  # at client: args = (epoch, assign0, assign1)
_H_MIG_T = 8  # at controller: rebalance timer
_H_MIG_RETX = 9  # at controller: re-drive the open migration
_H_MIG_START = 10  # at src primary: args = (shard, new_epoch, dst)
_H_HANDOFF = 11  # at dst primary: args = (shard, new_epoch, ver)
_H_INSTALL_ACK = 12  # at controller: args = (shard, new_epoch)
_H_RELEASE = 13  # at src primary: args = (shard, new_epoch)
_H_FIN = 14  # at controller: client done
_H_AREQ = 15  # at client: army op arrival — army mode
_H_APROBE = 16  # at controller: army probe
_H_ARESP = 17  # at client: army response

CONTROLLER = 0
CLIENT = 1

# controller columns (low state words; groups use the same columns as
# shard versions — different nodes, the contracts below take the hull)
_C_EPOCH, _C_PHASE, _C_MIG_S, _C_MIG_D = 0, 1, 2, 3
_C_A0, _C_A1, _C_DONE, _C_FIN = 4, 5, 6, 7
# client columns (col 3 = last army op APPLIED — the dedup floor the
# exactly-once discipline lives in; shared with _C_MIG_D on the
# controller, the contracts below take the hull)
_K_EPOCH, _K_ACKED, _K_FIN, _K_APPLIED = 0, 1, 2, 3

_P_KILL_AT = 0
_P_KILL_WHO = 1
_P_REVIVE = 2

# contract caps: versions are clamped here on every message arrival,
# epochs at every bump — the declared state contracts are owed by
# construction
VER_CAP = (1 << 16) - 1
EPOCH_CAP = 255
_A_MASK = 0xFFFF  # packed-assignment word bound (4 shards x 4 bits)


def _initial_assign(n_shards: int, n_groups: int) -> tuple[int, int]:
    """Initial shard -> group map, packed: shard s starts at s % G."""
    a0 = a1 = 0
    for s in range(n_shards):
        g = s % n_groups
        if s < 4:
            a0 |= g << (4 * s)
        else:
            a1 |= g << (4 * (s - 4))
    return a0, a1


def make_shardkv(
    n_groups: int = 4,
    group_size: int = 3,
    n_shards: int = 8,
    writes: int = 16,
    n_migs: int = 4,
    put_ms: int = 25,
    mig_ms: int = 70,
    retx_ms: int = 40,
    chaos: bool = True,
    record: bool = False,
    hist_capacity: int | None = None,
    bug: "bool | str" = False,
    army: bool = False,
    army_probes: int = 1,
) -> Workload:
    """``record=True`` records every committed write (OP_SHARD_WRITE,
    key = shard, arg = version) at the serving primary and every shard
    install (OP_SHARD_OWN, key = shard, arg = the packed
    epoch/group/version word) at the installing primary — the two
    streams check.shard_coverage audits. With ``army=True`` it also
    records every army op APPLY (OP_ARMY_PUT, key = op id, arg =
    attempt) at the client — the stream check.exactly_once audits.

    ``bug=True`` plants the lost-shard mutant (release-before-ack, see
    module docstring). ``bug="noidem"`` plants the non-idempotent
    retried-put mutant instead: the army apply skips its last-applied
    guard and applies (and records) on EVERY delivery, so a modeled
    retry whose first attempt did land applies the same op twice —
    invisible to every final-state invariant (the guard column feeds
    nothing else), caught only by check.exactly_once. Both require
    ``record=True``; ``"noidem"`` additionally requires ``army=True``.

    ``army=True`` opens the client node as an open-loop surface
    (``client_army``): ops probe the controller's config head and apply
    an exactly-once put at the client.
    """
    G, R, S = n_groups, group_size, n_shards
    n = 2 + G * R
    if not 1 <= S <= 8:
        raise ValueError(f"n_shards must be in [1, 8] (packed 4-bit "
                         f"assignment words), got {S}")
    if not 1 <= G <= 15:
        raise ValueError(f"n_groups must be in [1, 15] (4-bit group "
                         f"ids), got {G}")
    width = 2 * S + 1
    c_frozen = 2 * S
    if width < 8:
        width = 8  # controller scalars need cols 0..7
        c_frozen = 2 * S
    if bug not in (False, True, "noidem"):
        raise ValueError(
            f"bug must be False, True (lost-shard) or 'noidem' "
            f"(non-idempotent retried put), got {bug!r}"
        )
    if bug and not record:
        raise ValueError(
            "bug plants a fault only histories can see; it requires "
            "record=True (otherwise nothing would ever detect it)"
        )
    if bug == "noidem" and not army:
        raise ValueError(
            "bug='noidem' lives in the army apply path; it requires "
            "army=True"
        )
    if army_probes < 1:
        raise ValueError(f"army_probes must be >= 1, got {army_probes}")
    a0_init, a1_init = _initial_assign(S, G)

    def _group_of(a0, a1, s):
        """Shard -> group from the packed words (traced or host).

        The nibble index is ``s & 3`` (== s-4 for shards in the high
        word), which the interval prover can bound non-negative — a
        ``where(s < 4, s, s - 4)`` hull would admit a negative shift
        count and decay the whole read to full range.
        """
        w = jnp.where(s < 4, a0, a1)
        sh = (s & 3) * 4
        return (w >> sh) & 0xF

    def _primary_of(g):
        return jnp.int32(2) + g.astype(jnp.int32) * jnp.int32(R)

    def _shard(ctx):
        return jnp.clip(ctx.args[0], 0, S - 1)

    def on_init(ctx):
        eb = ctx.emits()
        is_ctl = ctx.node == jnp.int32(CONTROLLER)
        is_client = ctx.node == jnp.int32(CLIENT)
        eb.after(mig_ms * 1_000_000, user_kind(_H_MIG_T), CONTROLLER,
                 when=is_ctl)
        eb.after(put_ms * 1_000_000, user_kind(_H_PUT_T), CLIENT,
                 when=is_client)
        if chaos:
            # kill a random PRIMARY mid-run — mid-migration kills are
            # the schedules the lost-shard class lives in
            p = ctx.draw.user_int(0, G, _P_KILL_WHO).astype(jnp.int32)
            who = jnp.int32(2) + p * jnp.int32(R)
            at = ctx.draw.user_int(20_000_000, 300_000_000, _P_KILL_AT)
            revive = ctx.draw.user_int(100_000_000, 600_000_000, _P_REVIVE)
            eb.after(at, KIND_KILL, 0, (who,), when=is_client)
            eb.after(at + revive, KIND_RESTART, 0, (who,), when=is_client)
        return ctx.state, eb.build()

    def on_put_t(ctx):
        # stop-and-wait client: one outstanding write, retried until
        # acked; writes round-robin the shards (seq k targets shard
        # k % S, so per-shard versions are strictly increasing)
        st = ctx.state
        acked = st[_K_ACKED]
        done = acked >= jnp.int32(writes)
        seq = jnp.minimum(acked + 1, jnp.int32(VER_CAP))
        s = seq % jnp.int32(S)
        g = _group_of(st[_C_A0], st[_C_A1], s)
        eb = ctx.emits()
        eb.send(_primary_of(g), user_kind(_H_WRITE), (s, seq), when=~done)
        eb.send(CONTROLLER, user_kind(_H_FIN), (), when=done)
        eb.after(put_ms * 1_000_000, user_kind(_H_PUT_T), CLIENT)
        return ctx.state, eb.build()

    def on_write(ctx):
        # serve iff this group owns the shard AND it is not frozen for
        # an open migration; anything unservable redirects the client
        # to refetch config. The frozen case MUST redirect too: the
        # commit-time CFG and RELEASE messages are sent once and lossy,
        # so a client whose refetch races a migration may retry into a
        # forever-frozen source — silence there wedges the run
        s = _shard(ctx)
        seq = jnp.clip(ctx.args[1], 0, VER_CAP)
        st = ctx.state
        owned = st[S + s] > 0
        frozen = ((st[c_frozen] >> s) & 1) > 0
        serving = owned & ~frozen
        fresh = serving & (seq > st[s])
        new = jnp.where(fresh, st.at[s].set(seq), st)
        eb = ctx.emits()
        if record:
            eb.record(OP_SHARD_WRITE, s, seq, ok=OK_OK, when=fresh)
        eb.send(CLIENT, user_kind(_H_WRITE_OK), (s, seq), when=serving)
        eb.send(CLIENT, user_kind(_H_WRONG), (s,), when=~serving)
        # replicate the committed version inside the group
        base = jnp.int32(2) + ((ctx.node - 2) // jnp.int32(R)) * jnp.int32(R)
        for i in range(1, R):
            eb.send(base + i, user_kind(_H_REPL), (s, seq), when=fresh)
        return new, eb.build()

    def on_repl(ctx):
        s = _shard(ctx)
        v = jnp.clip(ctx.args[1], 0, VER_CAP)
        st = ctx.state
        return st.at[s].set(jnp.maximum(st[s], v)), ctx.emits().build()

    def on_write_ok(ctx):
        seq = jnp.clip(ctx.args[1], 0, VER_CAP)
        st = ctx.state
        new = st.at[_K_ACKED].set(jnp.maximum(st[_K_ACKED], seq))
        return new, ctx.emits().build()

    def on_wrong(ctx):
        eb = ctx.emits()
        eb.send(CONTROLLER, user_kind(_H_CFG_REQ), ())
        return ctx.state, eb.build()

    def on_cfg_req(ctx):
        st = ctx.state
        eb = ctx.emits()
        eb.send(CLIENT, user_kind(_H_CFG),
                (st[_C_EPOCH], st[_C_A0], st[_C_A1]))
        return ctx.state, eb.build()

    def on_cfg(ctx):
        e = jnp.clip(ctx.args[0], 0, EPOCH_CAP)
        a0 = jnp.clip(ctx.args[1], 0, _A_MASK)
        a1 = jnp.clip(ctx.args[2], 0, _A_MASK)
        st = ctx.state
        adopt = e > st[_K_EPOCH]
        new = jnp.where(
            adopt,
            st.at[_K_EPOCH].set(e).at[_C_A0].set(a0).at[_C_A1].set(a1),
            st,
        )
        return new, ctx.emits().build()

    def _mig_start_row(eb, st, when):
        """(Re)drive the open migration: idempotent MIG_START to the
        shard's CURRENT owner (assignment changes only at commit)."""
        s = st[_C_MIG_S]
        src = _group_of(st[_C_A0], st[_C_A1], s)
        new_ep = jnp.minimum(st[_C_EPOCH] + 1, jnp.int32(EPOCH_CAP))
        eb.send(_primary_of(src), user_kind(_H_MIG_START),
                (s, new_ep, st[_C_MIG_D]), when=when)

    def on_mig_t(ctx):
        st = ctx.state
        idle = st[_C_PHASE] == 0
        more = st[_C_DONE] < jnp.int32(n_migs)
        start = idle & more
        s = st[_C_DONE] % jnp.int32(S)
        dst = (_group_of(st[_C_A0], st[_C_A1], s) + 1) % jnp.int32(G)
        new = jnp.where(
            start,
            st.at[_C_PHASE].set(1).at[_C_MIG_S].set(s).at[_C_MIG_D].set(dst),
            st,
        )
        eb = ctx.emits()
        _mig_start_row(eb, new, start)
        eb.after(retx_ms * 1_000_000, user_kind(_H_MIG_RETX), CONTROLLER,
                 when=start)
        eb.after(mig_ms * 1_000_000, user_kind(_H_MIG_T), CONTROLLER,
                 when=more)
        return new, eb.build()

    def on_mig_retx(ctx):
        # the migration makes progress through loss and kills because
        # the controller re-drives it until the install is confirmed
        st = ctx.state
        open_ = st[_C_PHASE] == 1
        eb = ctx.emits()
        _mig_start_row(eb, st, open_)
        eb.after(retx_ms * 1_000_000, user_kind(_H_MIG_RETX), CONTROLLER,
                 when=open_)
        return ctx.state, eb.build()

    def on_mig_start(ctx):
        s = _shard(ctx)
        new_ep = jnp.clip(ctx.args[1], 0, EPOCH_CAP)
        dst = jnp.clip(ctx.args[2], 0, G - 1)
        st = ctx.state
        owned = st[S + s] > 0
        eb = ctx.emits()
        if bug is True:
            # planted lost-shard mutant: the source treats "handoff
            # sent" as "migration done" — it releases the shard
            # immediately instead of waiting for the controller's
            # RELEASE, and answers retried MIG_STARTs from the wiped
            # state. A lost first handoff (or a dst killed
            # mid-install) then re-hands version 0: the destination's
            # install adopts a version below the committed writes,
            # which only check.shard_coverage clause 2 can see.
            eb.send(_primary_of(dst), user_kind(_H_HANDOFF),
                    (s, new_ep, st[s]))
            new = jnp.where(
                owned,
                st.at[s].set(0).at[S + s].set(0),
                st,
            )
        else:
            # freeze and hand off; KEEP the shard until RELEASE — the
            # retx loop can always re-send the real state
            eb.send(_primary_of(dst), user_kind(_H_HANDOFF),
                    (s, new_ep, st[s]), when=owned)
            new = jnp.where(
                owned,
                st.at[c_frozen].set(st[c_frozen] | (jnp.int32(1) << s)),
                st,
            )
        return new, eb.build()

    def on_handoff(ctx):
        s = _shard(ctx)
        new_ep = jnp.clip(ctx.args[1], 0, EPOCH_CAP)
        v = jnp.clip(ctx.args[2], 0, VER_CAP)
        st = ctx.state
        fresh = st[S + s] < new_ep
        ver_new = jnp.maximum(st[s], v)
        # installing also clears any stale frozen bit for the shard: if
        # this group's OWN outbound migration of s lost its RELEASE, the
        # shard coming back supersedes that freeze — keeping it would
        # leave the new owner permanently unservable
        new = jnp.where(
            fresh,
            st.at[s].set(ver_new).at[S + s].set(new_ep)
            .at[c_frozen].set(
                st[c_frozen] & (jnp.int32(_A_MASK) ^ (jnp.int32(1) << s))
            ),
            st,
        )
        my_group = (ctx.node - 2) // jnp.int32(R)
        eb = ctx.emits()
        if record:
            eb.record(
                OP_SHARD_OWN, s,
                pack_shard_own(new_ep, my_group,
                               jnp.minimum(ver_new, jnp.int32(VER_CAP))),
                ok=OK_OK, when=fresh,
            )
        # always ack (idempotent): a lost ack must not wedge the
        # migration
        eb.send(CONTROLLER, user_kind(_H_INSTALL_ACK), (s, new_ep))
        return new, eb.build()

    def _set_assign(st, s, g):
        # nibble index via s & 3 (see _group_of): keeps the shift count
        # provably non-negative for the interval prover. g is clamped to
        # the nibble it is packed into — a wider value would corrupt the
        # neighboring shards' assignments
        sh = (s & 3) * 4
        g = jnp.clip(g, 0, G - 1)
        keep = jnp.int32(_A_MASK) ^ (jnp.int32(0xF) << sh)
        a0 = jnp.where(s < 4, (st[_C_A0] & keep) | (g << sh), st[_C_A0])
        a1 = jnp.where(s < 4, st[_C_A1], (st[_C_A1] & keep) | (g << sh))
        return st.at[_C_A0].set(a0).at[_C_A1].set(a1)

    def on_install_ack(ctx):
        s = _shard(ctx)
        e = jnp.clip(ctx.args[1], 0, EPOCH_CAP)
        st = ctx.state
        match = (
            (st[_C_PHASE] == 1)
            & (s == st[_C_MIG_S])
            & (e == jnp.minimum(st[_C_EPOCH] + 1, jnp.int32(EPOCH_CAP)))
        )
        src = _group_of(st[_C_A0], st[_C_A1], s)
        new = jnp.where(
            match,
            _set_assign(st, s, st[_C_MIG_D])
            .at[_C_EPOCH].set(e)
            .at[_C_PHASE].set(0)
            .at[_C_DONE].set(jnp.minimum(st[_C_DONE] + 1,
                                         jnp.int32(EPOCH_CAP))),
            st,
        )
        eb = ctx.emits()
        eb.send(_primary_of(src), user_kind(_H_RELEASE), (s, e), when=match)
        eb.send(CLIENT, user_kind(_H_CFG),
                (new[_C_EPOCH], new[_C_A0], new[_C_A1]), when=match)
        eb.halt(
            when=(new[_C_FIN] > 0) & (new[_C_DONE] >= jnp.int32(n_migs))
        )
        return new, eb.build()

    def on_release(ctx):
        # the committed migration's epilogue: drop the frozen source
        # copy — the ONLY place a clean source ever forgets a shard
        s = _shard(ctx)
        st = ctx.state
        frozen = ((st[c_frozen] >> s) & 1) > 0
        new = jnp.where(
            frozen,
            st.at[s].set(0).at[S + s].set(0)
            .at[c_frozen].set(
                st[c_frozen] & (jnp.int32(_A_MASK) ^ (jnp.int32(1) << s))
            ),
            st,
        )
        return new, ctx.emits().build()

    def on_fin(ctx):
        st = ctx.state
        new = st.at[_C_FIN].set(1)
        eb = ctx.emits()
        eb.halt(when=st[_C_DONE] >= jnp.int32(n_migs))
        return new, eb.build()

    def on_areq(ctx):
        # army op arrival at the client: an exactly-once PUT. The token
        # may carry a retry attempt id in its high bits (chaos
        # RetryPolicy re-deliveries), so the op id is stripped first
        # (identity for plain attempt-0 tokens). The clean client
        # dedups on a floor (col _K_APPLIED = last applied op id + 1,
        # so op 0 passes the zero-initialised floor): ops are offered
        # in increasing id order, so ``op >= floor`` admits each op
        # once and swallows both retried and reordered older
        # deliveries — structurally zero exactly-once violations. The
        # floor column feeds nothing else (no send, no coverage, no
        # invariant), which is exactly why a double-apply is invisible
        # to final-state checking and needs the history detector.
        op_id = retry_token_op(ctx.args[0])
        att = retry_token_attempt(ctx.args[0])
        st = ctx.state
        if bug == "noidem":
            # planted non-idempotent mutant: "the handler is the apply"
            # — every delivery applies and records, so a retry whose
            # first attempt DID land (response slow, not lost) applies
            # the same op twice. Only check.exactly_once sees it.
            applied = jnp.bool_(True)
        else:
            applied = op_id >= st[_K_APPLIED]
        new = jnp.where(
            applied,
            st.at[_K_APPLIED].set(jnp.clip(op_id + 1, 0, VER_CAP)),
            st,
        )
        eb = ctx.emits()
        if record:
            eb.record(OP_ARMY_PUT, op_id, att, ok=OK_OK, when=applied)
        eb.lat_start(op_id)
        eb.send(CONTROLLER, user_kind(_H_APROBE),
                (op_id, jnp.int32(army_probes - 1)))
        return new, eb.build()

    def on_aprobe(ctx):
        eb = ctx.emits()
        eb.send(CLIENT, user_kind(_H_ARESP), (ctx.args[0], ctx.args[1]))
        return ctx.state, eb.build()

    def on_aresp(ctx):
        op_id, k = ctx.args[0], ctx.args[1]
        eb = ctx.emits()
        eb.send(CONTROLLER, user_kind(_H_APROBE), (op_id, k - 1),
                when=k > 0)
        eb.lat_end(op_id, when=k == 0)
        return ctx.state, eb.build()

    def _cov(ns, now):
        # protocol coverage: the migration epoch edge the controller is
        # on (epoch, phase, which shard) and the fleet-wide ownership
        # count — a shard transiently owned by 0 or 2 groups is exactly
        # the behavior a guided lost-shard hunt should chase. uint32
        # words only (coverage is derived state)
        ep = jnp.minimum(ns[CONTROLLER, _C_EPOCH], 255).astype(jnp.uint32)
        ph = jnp.clip(ns[CONTROLLER, _C_PHASE], 0, 1).astype(jnp.uint32)
        ms = jnp.clip(ns[CONTROLLER, _C_MIG_S], 0, 7).astype(jnp.uint32)
        f1 = ep | (ph << jnp.uint32(8)) | (ms << jnp.uint32(9)) \
            | jnp.uint32(1 << 20)
        owned = jnp.uint32(0)
        for g in range(G):
            p = 2 + g * R
            for s in range(S):
                owned = owned + (ns[p, S + s] > 0).astype(jnp.uint32)
        f2 = jnp.minimum(owned, jnp.uint32(63)) | jnp.uint32(1 << 21)
        return ((f1, jnp.bool_(True)), (f2, jnp.bool_(True)))

    # per-column contracts (lint.absint): versions and controller
    # scalars share the low columns across roles, so each column
    # declares the hull; everything here is a bounded counter
    def _sc(col):
        if col < S:  # shard versions
            hi = VER_CAP
        elif col < 2 * S:  # per-shard ownership epochs
            hi = EPOCH_CAP
        elif col == c_frozen:
            hi = (1 << S) - 1
        else:
            hi = 1
        if col <= _C_FIN:
            # controller/client scalars share the low columns with the
            # group versions; everything they store is <= VER_CAP
            hi = max(hi, VER_CAP)
        return StateContract(col, 0, hi, "counter")

    init = np.zeros((n, width), np.int32)
    init[CONTROLLER, _C_EPOCH] = 1
    init[CONTROLLER, _C_A0] = a0_init
    init[CONTROLLER, _C_A1] = a1_init
    init[CLIENT, _K_EPOCH] = 1
    init[CLIENT, _C_A0] = a0_init
    init[CLIENT, _C_A1] = a1_init
    for s in range(S):
        init[2 + (s % G) * R, S + s] = 1  # initial owners at epoch 1

    hist = None
    if record:
        # the army term covers the default client_army (256 ops) at 4
        # deliveries each — retried armies larger than that should pass
        # hist_capacity explicitly
        cap = (
            2 * writes + 4 * n_migs + 16 + (1024 if army else 0)
            if hist_capacity is None else hist_capacity
        )
        hist = HistorySpec(capacity=cap, max_records=1)

    name = "shardkv"
    if record:
        if bug == "noidem":
            name += "-noidem"
        else:
            name += "-bug" if bug else "-record"
    if army:
        name += "-army"
    handler_names = (
        "init", "put_t", "write", "repl", "write_ok", "wrong",
        "cfg_req", "cfg", "mig_t", "mig_retx", "mig_start", "handoff",
        "install_ack", "release", "fin",
    )
    handlers = (
        on_init, on_put_t, on_write, on_repl, on_write_ok, on_wrong,
        on_cfg_req, on_cfg, on_mig_t, on_mig_retx, on_mig_start,
        on_handoff, on_install_ack, on_release, on_fin,
    )
    if army:
        handler_names += ("areq", "aprobe", "aresp")
        handlers += (on_areq, on_aprobe, on_aresp)
    return Workload(
        name=name,
        handler_names=handler_names,
        n_nodes=n,
        state_width=width,
        handlers=handlers,
        # widest: on_write = ok + wrong + (R-1) replications; on_init =
        # client put timer + 2 chaos rows + controller mig timer
        max_emits=max(R + 1, 6),
        init_state=init,
        # largest timer: the chaos restart at 'at + revive' <= 900 ms
        delay_bound_ns=max(
            put_ms * 1_000_000, mig_ms * 1_000_000, retx_ms * 1_000_000,
            900_000_000,
        ),
        args_words=3,
        # disk-backed servers: every column survives a kill (a crash
        # is an availability window + message loss, not a RAM wipe) —
        # which is what makes mid-migration kills retryable
        durable_cols=tuple(range(width)),
        history=hist,
        lat_markers=1 if army else 0,
        cov_features=_cov,
        state_contracts=tuple(_sc(c) for c in range(width)),
        draw_purposes=(
            (_P_KILL_AT, _P_KILL_WHO, _P_REVIVE) if chaos else ()
        ),
    )


def client_army(
    n_ops: int = 256,
    t_min_ns: int = 20_000_000,
    t_max_ns: int = 400_000_000,
    op_base: int = 0,
    retry=None,
):
    """A :class:`chaos.ClientArmy` bound to shardkv's client surface
    (``make_shardkv(army=True)``): ops arrive at the client node, apply
    an exactly-once put, and probe the controller's config head.
    ``retry`` attaches a :class:`chaos.RetryPolicy` (build the engine
    with ``retry=plan.retry_spec()``)."""
    from ..chaos.plan import ClientArmy

    return ClientArmy(
        node=CLIENT,
        kind=user_kind(_H_AREQ),
        n_ops=n_ops,
        t_min_ns=t_min_ns,
        t_max_ns=t_max_ns,
        op_base=op_base,
        retry=retry,
    )


def lint_entries():
    """Tracing entry points for the static non-interference matrix
    (madsim_tpu.lint): base + record (the new history/coverage columns
    must prove derived-only) + army (the latency-marker path). The
    default 14-node shape rides every row — this model exists to
    stress N=12+."""
    kw = dict(pool_size=64, loss_p=0.02, clog_backoff_max_ns=2_000_000_000)
    return [
        ("shardkv/plain", make_shardkv(), kw),
        ("shardkv/record", make_shardkv(record=True), kw),
        ("shardkv/army", make_shardkv(army=True), kw),
    ]


# Declared interval-certification horizon (lint.absint): migrations and
# write windows are sim-milliseconds; 300 sim-seconds is generous slack
# over every recorded shardkv hunt shape.
ABSINT_HORIZON_NS = 300 * 1_000_000_000


def absint_entries():
    """Range-contract entry points for the interval prover
    (lint.absint): lint_entries rows plus the declared horizon."""
    return [
        (tag, wl, kw, ABSINT_HORIZON_NS)
        for tag, wl, kw in lint_entries()
    ]
