"""Raft-style 5-node leader election — the north-star workload
(BASELINE.md config 4: 65,536 seeds, >= 200k simulated-seconds/sec).

The MadRaft-shaped scenario the reference ecosystem uses for DST: five
nodes with randomized election timeouts (150-300 ms) race to win a
majority under 1-10 ms message latency, packet loss, and (optionally) a
leader kill + restart. The seed decides every timeout and latency draw,
so each seed explores a different interleaving; the instance halts when
a leader first wins an election (halt_time = election latency).

State row: [role, term, voted_term, votes, timeout_seq, 0]
  role: 0 follower, 1 candidate, 2 leader
"""

from __future__ import annotations

import jax.numpy as jnp

from ..check.history import OP_USER
from ..engine import HistorySpec, Workload, user_kind

_H_INIT = 0
_H_TIMEOUT = 1  # args = (timeout_seq,)
_H_REQVOTE = 2  # args = (term, candidate)
_H_GRANT = 3  # args = (term,)
_H_HEARTBEAT = 4  # args = (term,)

# history op kind (record=True): an election win, recorded as an
# instantaneous event — key = term, arg = winner.
# check.election_safety(h, elect_op=OP_ELECT) is the history analog of
# the final-state single-leader invariant, but over every win along the
# way, not just the roles at halt
OP_ELECT = OP_USER

ROLE, TERM, VOTED, VOTES, TSEQ = 0, 1, 2, 3, 4
FOLLOWER, CANDIDATE, LEADER = 0, 1, 2

_P_TIMEOUT = 0


def make_raft(
    n_nodes: int = 5,
    timeout_min_ns: int = 150_000_000,
    timeout_max_ns: int = 300_000_000,
    record: bool = False,
) -> Workload:
    """``record=True`` turns on operation-history recording
    (madsim_tpu.check): every election win is recorded as an
    instantaneous ``OP_ELECT`` event (key = term, arg = winner node),
    so ``check.election_safety`` can assert at-most-one-winner-per-term
    over the whole seed batch — including wins that a later term
    overwrites in the final node state."""
    majority = n_nodes // 2 + 1
    nodes = list(range(n_nodes))

    def _arm_timer(ctx, eb, new_seq, when):
        d = ctx.draw.user_int(timeout_min_ns, timeout_max_ns, _P_TIMEOUT)
        eb.after(d, user_kind(_H_TIMEOUT), ctx.node, (new_seq,), when=when)

    def on_init(ctx):
        eb = ctx.emits()
        _arm_timer(ctx, eb, jnp.int32(1), True)
        new = ctx.state.at[TSEQ].set(1)
        return new, eb.build()

    def on_timeout(ctx):
        st = ctx.state
        fire = (ctx.args[0] == st[TSEQ]) & (st[ROLE] != jnp.int32(LEADER))
        term = st[TERM] + 1
        new = jnp.where(
            fire,
            st.at[ROLE]
            .set(CANDIDATE)
            .at[TERM]
            .set(term)
            .at[VOTED]
            .set(term)
            .at[VOTES]
            .set(1)
            .at[TSEQ]
            .set(st[TSEQ] + 1),
            st,
        )
        eb = ctx.emits()
        for p in nodes:
            eb.send(
                p,
                user_kind(_H_REQVOTE),
                (term, ctx.node),
                when=fire & (jnp.int32(p) != ctx.node),
            )
        _arm_timer(ctx, eb, st[TSEQ] + 1, fire)
        return new, eb.build()

    def on_reqvote(ctx):
        st = ctx.state
        term, cand = ctx.args[0], ctx.args[1]
        # step down on a newer term
        newer = term > st[TERM]
        st1 = jnp.where(
            newer,
            st.at[TERM].set(term).at[ROLE].set(FOLLOWER).at[VOTES].set(0),
            st,
        )
        grant = (term == st1[TERM]) & (st1[VOTED] < term)
        new = jnp.where(grant, st1.at[VOTED].set(term).at[TSEQ].set(st1[TSEQ] + 1), st1)
        eb = ctx.emits()
        eb.send(cand, user_kind(_H_GRANT), (term,), when=grant)
        # granting resets the election timer (vote then wait)
        _arm_timer(ctx, eb, st1[TSEQ] + 1, grant)
        return new, eb.build()

    def on_grant(ctx):
        st = ctx.state
        term = ctx.args[0]
        counts = (st[ROLE] == jnp.int32(CANDIDATE)) & (term == st[TERM])
        votes = jnp.where(counts, st[VOTES] + 1, st[VOTES])
        wins = counts & (votes >= jnp.int32(majority))
        new = st.at[VOTES].set(votes)
        new = jnp.where(wins, new.at[ROLE].set(LEADER), new)
        eb = ctx.emits()
        for p in nodes:
            eb.send(
                p,
                user_kind(_H_HEARTBEAT),
                (term,),
                when=wins & (jnp.int32(p) != ctx.node),
            )
        if record:
            eb.record(OP_ELECT, key=term, arg=ctx.node, when=wins)
        # leader elected: scenario complete (halt_time = election latency)
        eb.halt(when=wins)
        return new, eb.build()

    def on_heartbeat(ctx):
        st = ctx.state
        term = ctx.args[0]
        accept = term >= st[TERM]
        new = jnp.where(
            accept,
            st.at[TERM]
            .set(term)
            .at[ROLE]
            .set(FOLLOWER)
            .at[TSEQ]
            .set(st[TSEQ] + 1),
            st,
        )
        eb = ctx.emits()
        _arm_timer(ctx, eb, st[TSEQ] + 1, accept)
        return new, eb.build()

    return Workload(
        name="raft-election-record" if record else "raft-election",
        handler_names=("init", "timeout", "reqvote", "grant", "heartbeat"),
        n_nodes=n_nodes,
        state_width=6,
        handlers=(on_init, on_timeout, on_reqvote, on_grant, on_heartbeat),
        max_emits=n_nodes + 1,
        # largest timer: the election timeout draw (time32 eligibility)
        delay_bound_ns=timeout_max_ns,
        # handlers read args[0:2] (term/candidate/seq)
        args_words=2,
        # the run halts at the first win, so concurrent in-flight wins
        # bound recorded events at a handful; 8 slots is generous
        history=HistorySpec(capacity=8, max_records=1) if record else None,
        # prefetch the timeout draw into the step's batched RNG block
        # (engine BatchRNG — see models/raftlog.py for the rule)
        draw_purposes=(_P_TIMEOUT,),
    )


def lint_entries():
    """Tracing entry points for the static non-interference matrix
    (madsim_tpu.lint): (tag, workload, engine-config kwargs) — the
    history on/off axis of the proof lives here, in the model's own
    recorded/plain variants."""
    kw = dict(pool_size=40, loss_p=0.02, clog_backoff_max_ns=2_000_000_000)
    return [
        ("raft/plain", make_raft(), kw),
        ("raft/record", make_raft(record=True), kw),
    ]


# Declared interval-certification horizon (lint.absint): elections
# resolve within sim-seconds; 60 sim-seconds is an order of magnitude
# of slack over every recorded raft run shape.
ABSINT_HORIZON_NS = 60 * 1_000_000_000


def absint_entries():
    """Range-contract entry points for the interval prover
    (lint.absint): ``(tag, workload, engine-config kwargs,
    certification horizon ns)`` — lint_entries plus the model's
    declared horizon."""
    return [
        (tag, wl, kw, ABSINT_HORIZON_NS)
        for tag, wl, kw in lint_entries()
    ]
