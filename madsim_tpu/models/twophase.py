"""Two-phase commit under chaos: atomicity with votes, retries, crashes.

A coordinator (node 0) drives ``txns`` transactions over ``n_parts``
participants: PREPARE -> votes (each participant decides once per
transaction, seeded, and re-sends its STORED vote on retransmit) ->
COMMIT when every vote is yes / ABORT on the first no -> acks. Packet
loss and a scheduled participant kill/restart (the engine KILL/RESTART
chaos events) exercise every retry path; the retransmit loop re-sends
whichever phase's messages are missing.

Recovery: a reborn participant (on_init runs again after RESTART)
announces itself with HELLO, retried until it has seen any traffic;
the coordinator clears the reborn node's vote/ack bit for the current
transaction so the retransmit loop re-covers it — without this, a
participant that acked the final decision and then crashed+restarted
before completion would never be re-sent the decision (its ack bit is
already set) and would halt ignorant of it. HELLO alone still races
(it is lossy and takes a latency to arrive; completion can land inside
that window), so the coordinator — which schedules the kill/restart
chaos itself — also arms a loss-free local RESYNC timer at the revive
time that clears the same bit deterministically.

The RESYNC guarantee is config-conditional, not structural: it requires
every pre-crash message to have drained before the resync fires, i.e.
``revive_min_ns > cfg.lat_max_ns`` (a stale in-flight ack arriving
after the RESYNC would re-set the cleared bit). The default
``revive_min_ns`` of 80 ms comfortably exceeds the engine's default
10 ms latency cap; raise it in step with any larger ``lat_max_ns``.
Beyond that, an unrelated ack carrying the exact revive timestamp can
dispatch before the RESYNC under the engine's deterministic same-time
ordering; the 4,096-schedule chaos-search soak is the standing evidence
neither residue occurs for the shipped parameters.

Halt condition: every transaction decided AND the final decision acked
by every participant. Invariants the tests / chaos search check at
halt: the coordinator's commit+abort tally equals ``txns``, every
participant applied the final transaction's decision, and every
participant's stored decision VALUE agrees with the coordinator's
(atomicity: nobody committed what another aborted).

Coordinator state: [cur_txn, phase(0=prepare 1=commit 2=abort),
                    votes_mask, ack_mask, n_commit, n_abort]
Participant state: [last_prepared, my_vote, last_decided, n_applied,
                    last_decision_value]
"""

from __future__ import annotations

import jax.numpy as jnp

from ..check.history import OP_USER
from ..engine import KIND_KILL, KIND_RESTART, HistorySpec, Workload, user_kind

# history op kind (record=True): a decide event per transaction — the
# coordinator records its decision when votes resolve, and every
# participant records the decision value it adopts (first adoption per
# incarnation). check.election_safety(h, elect_op=OP_DECIDE) over
# key=txn is then 2PC atomicity as a HISTORY property: no transaction
# is ever decided/applied with two different outcomes anywhere in the
# cluster, including decisions later overwritten by recovery traffic.
OP_DECIDE = OP_USER

COORD = 0

_H_INIT = 0
_H_PREPARE = 1  # at participant: args = (txn,)
_H_VOTE = 2  # at coordinator: args = (txn, part, yes)
_H_DECISION = 3  # at participant: args = (txn, commit)
_H_ACK = 4  # at coordinator: args = (txn, part)
_H_RETX = 5  # at coordinator: args = (txn,)
_H_HELLO = 6  # at coordinator: args = (part,) — a (re)born participant
_H_HRETX = 7  # at participant: retry HELLO until any traffic seen
_H_RESYNC = 8  # at coordinator: args = (part,) — scheduled at revive time

# user draw purposes
_P_VOTE = 0
_P_KILL_AT = 1
_P_KILL_WHO = 2
_P_REVIVE = 3


def make_twophase(
    txns: int = 5,
    n_parts: int = 4,
    no_pct: int = 10,
    retx_ns: int = 40_000_000,
    chaos: bool = True,
    revive_min_ns: int = 80_000_000,
    revive_max_ns: int = 400_000_000,
    record: bool = False,
) -> Workload:
    """``no_pct``: percent chance a participant votes NO per transaction.
    ``revive_min_ns`` must exceed the engine config's ``lat_max_ns`` for
    the crash-recovery guarantee (module docstring).

    ``record=True`` turns on operation-history recording
    (madsim_tpu.check): the coordinator records one ``OP_DECIDE`` event
    (key = txn, arg = commit/abort) when votes resolve, and every
    participant records the decision it adopts, so
    ``check.election_safety(h, elect_op=OP_DECIDE)`` asserts atomicity
    over the whole run — the nemesis-soak oracle for this family."""
    n = 1 + n_parts
    parts = list(range(1, n))
    full_mask = (1 << n_parts) - 1

    def _bcast_prepare(eb, txn, when, skip_mask):
        # slots 0..P-1 (parity-critical ordering, like the other models)
        for i, p in enumerate(parts):
            eb.send(
                p, user_kind(_H_PREPARE), (txn,),
                when=when & (((skip_mask >> i) & 1) == 0),
            )

    def _bcast_decision(eb, txn, commit, when, skip_mask):
        for i, p in enumerate(parts):
            eb.send(
                p, user_kind(_H_DECISION), (txn, commit),
                when=when & (((skip_mask >> i) & 1) == 0),
            )

    def on_init(ctx):
        is_coord = ctx.node == jnp.int32(COORD)
        is_part = ~is_coord
        eb = ctx.emits()
        _bcast_prepare(eb, jnp.int32(1), is_coord, jnp.int32(0))
        eb.after(retx_ns, user_kind(_H_RETX), COORD, (1,), when=is_coord)
        # announce this (re)born participant; lossy, so retried by a
        # timer until any traffic has been seen
        eb.send(COORD, user_kind(_H_HELLO), (ctx.node,), when=is_part)
        eb.after(retx_ns, user_kind(_H_HRETX), ctx.node, when=is_part)
        if chaos:
            who = ctx.draw.user_int(1, n, _P_KILL_WHO).astype(jnp.int32)
            at = ctx.draw.user_int(20_000_000, 250_000_000, _P_KILL_AT)
            revive = ctx.draw.user_int(revive_min_ns, revive_max_ns, _P_REVIVE)
            eb.after(at, KIND_KILL, 0, (who,), when=is_coord)
            eb.after(at + revive, KIND_RESTART, 0, (who,), when=is_coord)
            # loss-free local resync at the revive time: the reliable
            # half of the crash-after-ack recovery (see docstring)
            eb.after(
                at + revive, user_kind(_H_RESYNC), COORD, (who,),
                when=is_coord,
            )
        new = jnp.where(is_coord, ctx.state.at[0].set(1), ctx.state)
        return new, eb.build()

    def on_prepare(ctx):
        txn = ctx.args[0]
        st = ctx.state
        fresh = txn > st[0]
        # the vote is drawn ONCE (at first receipt) and stored, so a
        # retransmitted PREPARE re-sends the same vote — a participant
        # cannot change its mind (2PC's vote durability, modulo the
        # RAM-wipe crash the invariant documents)
        roll = ctx.draw.user_int(0, 100, _P_VOTE)
        new_vote = jnp.where(roll >= jnp.int64(no_pct), 1, 0).astype(jnp.int32)
        vote = jnp.where(fresh, new_vote, st[1])
        new = st.at[0].set(jnp.maximum(st[0], txn)).at[1].set(vote)
        eb = ctx.emits()
        eb.send(COORD, user_kind(_H_VOTE), (txn, ctx.node, vote))
        return new, eb.build()

    def on_vote(ctx):
        txn, who, yes = ctx.args[0], ctx.args[1], ctx.args[2]
        st = ctx.state
        relevant = (txn == st[0]) & (st[1] == jnp.int32(0))
        bit = jnp.int32(1) << (who - 1)
        votes = jnp.where(relevant, st[2] | bit, st[2])
        abort_now = relevant & (yes == jnp.int32(0))
        commit_now = relevant & (yes != 0) & (votes == jnp.int32(full_mask))
        decide = abort_now | commit_now
        phase = jnp.where(
            decide, jnp.where(abort_now, jnp.int32(2), jnp.int32(1)), st[1]
        )
        new = st.at[1].set(phase).at[2].set(votes).at[3].set(
            jnp.where(decide, jnp.int32(0), st[3])
        )
        eb = ctx.emits()
        _bcast_decision(
            eb, txn, (phase == 1).astype(jnp.int32), decide, jnp.int32(0)
        )
        if record:
            eb.record(
                OP_DECIDE, key=txn, arg=(phase == 1).astype(jnp.int32),
                when=decide,
            )
        # no retx arm here: the per-transaction chain armed at prepare
        # time keeps firing while this txn is current and re-sends
        # whichever phase's messages are missing
        return new, eb.build()

    def on_decision(ctx):
        txn, commit = ctx.args[0], ctx.args[1]
        st = ctx.state
        fresh = txn > st[2]
        new = (
            st.at[2].set(jnp.maximum(st[2], txn))
            .at[3].set(st[3] + fresh.astype(jnp.int32))
            # store the decision VALUE so agreement with the coordinator
            # is checkable at halt (atomicity, not just delivery)
            .at[4].set(jnp.where(fresh, commit, st[4]))
        )
        eb = ctx.emits()
        eb.send(COORD, user_kind(_H_ACK), (txn, ctx.node))
        if record:
            eb.record(OP_DECIDE, key=txn, arg=commit, when=fresh)
        return new, eb.build()

    def on_ack(ctx):
        txn, who = ctx.args[0], ctx.args[1]
        st = ctx.state
        relevant = (txn == st[0]) & (st[1] >= jnp.int32(1))
        bit = jnp.int32(1) << (who - 1)
        acks = jnp.where(relevant, st[3] | bit, st[3])
        complete = relevant & (acks == jnp.int32(full_mask))
        committed = st[1] == jnp.int32(1)
        n_commit = st[4] + (complete & committed).astype(jnp.int32)
        n_abort = st[5] + (complete & ~committed).astype(jnp.int32)
        last = st[0] >= jnp.int32(txns)
        advance = complete & ~last
        nxt = jnp.where(advance, st[0] + 1, st[0])
        new = (
            st.at[0].set(nxt)
            .at[1].set(jnp.where(advance, jnp.int32(0), st[1]))
            .at[2].set(jnp.where(advance, jnp.int32(0), st[2]))
            .at[3].set(acks)
            .at[4].set(n_commit)
            .at[5].set(n_abort)
        )
        eb = ctx.emits()
        _bcast_prepare(eb, nxt, advance, jnp.int32(0))
        eb.after(retx_ns, user_kind(_H_RETX), COORD, (nxt,), when=advance)
        eb.halt(when=complete & last)
        return new, eb.build()

    def on_retx(ctx):
        txn = ctx.args[0]
        st = ctx.state
        current = txn == st[0]
        preparing = current & (st[1] == jnp.int32(0))
        deciding = current & (st[1] >= jnp.int32(1))
        eb = ctx.emits()
        # missing votes -> re-PREPARE; missing acks -> re-DECISION. The
        # two broadcasts share the per-participant slots 0..P-1 via the
        # phase-dependent kind/args (one slot set per phase).
        for i, p in enumerate(parts):
            unheard_vote = preparing & (((st[2] >> i) & 1) == 0)
            eb.send(p, user_kind(_H_PREPARE), (txn,), when=unheard_vote)
        for i, p in enumerate(parts):
            unacked = deciding & (((st[3] >> i) & 1) == 0)
            eb.send(
                p, user_kind(_H_DECISION),
                (txn, (st[1] == 1).astype(jnp.int32)),
                when=unacked,
            )
        eb.after(retx_ns, user_kind(_H_RETX), COORD, (txn,), when=current)
        return ctx.state, eb.build()

    def _clear_bit(ctx):
        # a (re)born participant lost its RAM: clear its bit for the
        # current transaction so the retransmit loop re-covers it — the
        # recovery path for crash-after-ack (see module docstring).
        # Shared by on_hello (lossy, covers externally injected kills)
        # and on_resync (loss-free, covers the scheduled chaos).
        who = ctx.args[0]
        st = ctx.state
        bit = jnp.int32(1) << (who - 1)
        preparing = st[1] == jnp.int32(0)
        votes = jnp.where(preparing, st[2] & ~bit, st[2])
        acks = jnp.where(~preparing, st[3] & ~bit, st[3])
        return st.at[2].set(votes).at[3].set(acks)

    def on_hello(ctx):
        return _clear_bit(ctx), ctx.emits().build()

    def on_resync(ctx):
        return _clear_bit(ctx), ctx.emits().build()

    def on_hretx(ctx):
        st = ctx.state
        # retry until ANY traffic seen (a prepare or a decision)
        unseen = (st[0] == jnp.int32(0)) & (st[2] == jnp.int32(0))
        eb = ctx.emits()
        eb.send(COORD, user_kind(_H_HELLO), (ctx.node,), when=unseen)
        eb.after(retx_ns, user_kind(_H_HRETX), ctx.node, when=unseen)
        return ctx.state, eb.build()

    return Workload(
        name="twophase-record" if record else "twophase",
        handler_names=("init", "prepare", "vote", "decision", "ack", "retx", "hello", "hretx", "resync"),
        n_nodes=n,
        state_width=6,
        handlers=(
            on_init, on_prepare, on_vote, on_decision, on_ack, on_retx,
            on_hello, on_hretx, on_resync,
        ),
        # widest handlers: on_retx (2*P sends + 1 timer) and on_init
        # (P prepares + retx + hello + hretx + 3 chaos)
        max_emits=max(2 * n_parts + 1, n_parts + 6, 6),
        # largest timer: chaos restart/resync at 'at + revive'
        delay_bound_ns=max(retx_ns, 250_000_000 + revive_max_ns),
        # on_decision reads args[2]
        args_words=3,
        # capacity: one coordinator decide + one adoption per
        # participant per txn, plus re-adoptions after crash-restarts
        # (a reborn participant's wiped state re-records the current
        # txn once per retransmitted decision heard first). Overflow is
        # loud (hist_drop) and search_seeds quarantines it.
        history=(
            HistorySpec(capacity=txns * (1 + n_parts) + 16, max_records=1)
            if record
            else None
        ),
    )
