"""5-node reliable broadcast under chaos (BASELINE.md config 3).

Node 0 broadcasts ``rounds`` sequenced messages to 4 peers, collecting
acks and retransmitting on timeout — so the protocol makes progress
through packet loss and the random link partition the origin schedules
at init (engine CLOG/UNCLOG events, the clog_link chaos of reference
net/mod.rs:157-216). The run halts when every round is fully acked.

Origin state:   [current_seq, ack_mask, 0, 0]
Receiver state: [last_seen_seq, acks_sent, 0, 0]
"""

from __future__ import annotations

import jax.numpy as jnp

from ..engine import Workload, user_kind

_H_INIT = 0
_H_MSG = 1  # at receiver: args = (seq,)
_H_ACK = 2  # at origin:   args = (seq, peer)
_H_RETX = 3  # at origin:   args = (seq,)

ORIGIN = 0

# user draw purposes
_P_RETX = 0
_P_CHAOS_LINK = 1
_P_CHAOS_AT = 2
_P_CHAOS_LEN = 3


def make_broadcast(
    rounds: int = 5,
    n_nodes: int = 5,
    retx_ns: int = 50_000_000,
    partition: bool = True,
) -> Workload:
    peers = list(range(1, n_nodes))
    full_mask = (1 << len(peers)) - 1

    def _bcast(eb, seq, when):
        for p in peers:
            eb.send(p, user_kind(_H_MSG), (seq,), when=when)

    def on_init(ctx):
        is_origin = ctx.node == jnp.int32(ORIGIN)
        eb = ctx.emits()
        seq = jnp.int32(1)
        _bcast(eb, seq, is_origin)
        eb.after(retx_ns, user_kind(_H_RETX), ORIGIN, (seq,), when=is_origin)
        if partition:
            # partition a random non-origin link for a random window —
            # chaos the retransmit path must survive
            a = ctx.draw.user_int(1, n_nodes, _P_CHAOS_LINK)
            b_raw = ctx.draw.user_int(1, n_nodes - 1, _P_CHAOS_LINK + 16)
            b = jnp.where(b_raw >= a, b_raw + 1, b_raw).astype(jnp.int32)
            at = ctx.draw.user_int(0, 100_000_000, _P_CHAOS_AT)
            length = ctx.draw.user_int(50_000_000, 400_000_000, _P_CHAOS_LEN)
            from ..engine import KIND_CLOG, KIND_UNCLOG

            eb.after(at, KIND_CLOG, 0, (a.astype(jnp.int32), b), when=is_origin)
            eb.after(
                at + length,
                KIND_UNCLOG,
                0,
                (a.astype(jnp.int32), b),
                when=is_origin,
            )
        new = jnp.where(
            is_origin, ctx.state.at[0].set(1), ctx.state
        )
        return new, eb.build()

    def on_msg(ctx):
        seq = ctx.args[0]
        last = ctx.state[0]
        new = ctx.state.at[0].set(jnp.maximum(last, seq)).at[1].set(ctx.state[1] + 1)
        eb = ctx.emits()
        # always ack (idempotent) so lost acks are re-covered by retx
        eb.send(ORIGIN, user_kind(_H_ACK), (seq, ctx.node))
        return new, eb.build()

    def on_ack(ctx):
        seq, peer = ctx.args[0], ctx.args[1]
        cur = ctx.state[0]
        mask = ctx.state[1]
        bit = jnp.int32(1) << (peer - 1)
        mask = jnp.where(seq == cur, mask | bit, mask)
        complete = mask == jnp.int32(full_mask)
        last_round = cur >= jnp.int32(rounds)
        nxt = jnp.where(complete & ~last_round, cur + 1, cur)
        new_mask = jnp.where(complete & ~last_round, jnp.int32(0), mask)
        eb = ctx.emits()
        _bcast(eb, nxt, complete & ~last_round)
        eb.after(
            retx_ns, user_kind(_H_RETX), ORIGIN, (nxt,), when=complete & ~last_round
        )
        eb.halt(when=complete & last_round)
        new = ctx.state.at[0].set(nxt).at[1].set(new_mask)
        return new, eb.build()

    def on_retx(ctx):
        seq = ctx.args[0]
        cur = ctx.state[0]
        mask = ctx.state[1]
        pending = (seq == cur) & (mask != jnp.int32(full_mask))
        eb = ctx.emits()
        for i, p in enumerate(peers):
            unacked = ((mask >> i) & 1) == 0
            eb.send(p, user_kind(_H_MSG), (cur,), when=pending & unacked)
        eb.after(retx_ns, user_kind(_H_RETX), ORIGIN, (cur,), when=pending)
        return ctx.state, eb.build()

    return Workload(
        name="broadcast",
        handler_names=("init", "msg", "ack", "retx"),
        n_nodes=n_nodes,
        state_width=4,
        handlers=(on_init, on_msg, on_ack, on_retx),
        max_emits=max(len(peers) + 3, 6),
        # largest timer: chaos unclog at 'at + length' <= 100 ms + 400 ms
        delay_bound_ns=max(retx_ns, 500_000_000),
        # handlers read args[0:2] (seq / clog pair)
        args_words=2,
        # prefetch the chaos draws into the step's batched RNG block
        # (engine BatchRNG — see models/raftlog.py for the rule)
        draw_purposes=(
            (_P_CHAOS_LINK, _P_CHAOS_LINK + 16, _P_CHAOS_AT, _P_CHAOS_LEN)
            if partition
            else ()
        ),
    )
