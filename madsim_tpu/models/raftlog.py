"""Raft log replication under leader-crash chaos — the MadRaft shape.

Extends the election-only north-star workload (models/raft.py) to the
full replication loop the reference ecosystem's flagship DST target
(MadRaft) exercises: an elected leader proposes ``n_writes`` entries
one at a time, replicates them with AppendEntries, commits each on a
majority of acks, and (under ``chaos=True``) every seed schedules one
node kill at a uniformly drawn node — ``user_int(0, n_nodes)`` is
half-open, so some valid node is always hit — plus a later restart
mid-stream. The instance halts when
the final entry commits; the test-checkable safety invariant is the
raft one: **every committed entry is present, in order and with equal
values, on a majority of nodes at halt** — across elections, crashes,
packet loss and partition-grade delays.

Protocol simplifications, chosen to keep the state machine dense while
preserving the real safety argument:

* **Single inflight entry** — entry ``i+1`` is proposed only after
  ``i`` commits, so AppendEntries can carry the sender's *entire* log
  prefix in the event payload arena and followers adopt it wholesale
  (no nextIndex backtracking; a restarted node is caught up by the
  first retransmission it hears).
* **Vote check** is the real lexicographic up-to-date rule: grant only
  if the candidate's (last-log term, log length) >= the voter's.
* **Win-time re-stamp** — a new leader re-stamps its uncommitted
  suffix with its current term before re-replicating. Acks therefore
  always cover a log whose last term is the leader's own, which closes
  raft's "figure 8" hazard (committing an old-term entry by counting
  current-term acks) without no-op filler entries: any later winner
  must out-vote a majority holding the committed (term, length), and
  only extensions of the committing leader's log can do that.

Log entries pack as value | term << 8 in one int32 state word.

State row: [role, term, voted_term, votes, timer_seq, log_len,
            commit, ack_mask, log_0 .. log_{W-1}]
"""

from __future__ import annotations

import jax.numpy as jnp

from ..check.history import OP_USER
from ..engine import KIND_KILL, KIND_RESTART, HistorySpec, Workload, user_kind

# history op kinds (record=True): an election win (key = term, arg =
# winner) and a leader commit decision (key = log index, arg = the
# committed entry VALUE). check.election_safety over OP_ELECT is
# at-most-one-winner-per-term; over OP_COMMIT it is raft's log
# agreement — no index ever committed with two different values,
# across every leader along the way (not just the logs at halt).
# The value byte, not the full value|term<<8 entry word: the model's
# win-time re-stamp (see module docstring) deliberately rewrites the
# term byte of the suffix above the VOLATILE commit index, so after a
# leader restart the same committed value is legitimately re-committed
# under a higher term — the state machine's history is the value
# sequence, and that is what must agree.
OP_ELECT = OP_USER
OP_COMMIT = OP_USER + 1
# storage-discipline events (durable=True + record=True): a node
# records OP_SYNCED (arg = its new log length) whenever a sync commits
# a log-length change, and OP_RECOVER (arg = the log length it came
# back with) when its post-restart on_init runs. check.recovery_safety
# over (OP_SYNCED, OP_RECOVER) asserts a restarted node never regresses
# durably synced state — the crash-recovery-safety detector.
OP_SYNCED = OP_USER + 2
OP_RECOVER = OP_USER + 3

_H_INIT = 0
_H_TIMEOUT = 1  # args = (timer_seq,)
_H_REQVOTE = 2  # args = (term, candidate, cand_loglen, cand_lastterm)
_H_GRANT = 3  # args = (term,)
_H_APPEND = 4  # args = (term, idx, leader_commit, leader); pay = full log
_H_ACKAPP = 5  # args = (term, idx, follower)
_H_PROPOSE = 6  # leader propose timer; args = (term,)
_H_RETX = 7  # leader retransmit timer; args = (term,)
_H_AREQ = 8  # at client: army op arrival, args = (op_id, word) — army mode
_H_APROBE = 9  # at server: army probe, args = (op_id,)
_H_ARESP = 10  # at client: army response, args = (op_id, commit)

ROLE, TERM, VOTED, VOTES, TSEQ, LOGLEN, COMMIT, ACKS = range(8)
LOG0 = 8
FOLLOWER, CANDIDATE, LEADER = 0, 1, 2

_P_TIMEOUT = 0
_P_VALUE = 1
_P_KILL_AT = 2
_P_KILL_WHO = 3
_P_REVIVE = 4


def _entry_term(e):
    # value = low 8 bits, term = the remaining 23 — terms are unbounded
    # in long chaos runs (an 0xFF mask here would silently wrap term 256
    # to 0 and corrupt the up-to-date vote rule)
    return e >> jnp.int32(8)


def make_raftlog(
    n_nodes: int = 5,
    n_writes: int = 4,
    timeout_min_ns: int = 150_000_000,
    timeout_max_ns: int = 300_000_000,
    propose_ns: int = 20_000_000,
    retx_ns: int = 60_000_000,
    chaos: bool = True,
    durable: bool = False,
    record: bool = False,
    bug: str | None = None,
    army: bool = False,
    cov_spread: bool = False,
) -> Workload:
    """``record=True`` turns on operation-history recording
    (madsim_tpu.check): every election win records an ``OP_ELECT`` event
    (key = term, arg = winner) and every leader commit records one
    ``OP_COMMIT`` event per newly committed index (key = index, arg =
    the entry word), so ``check.election_safety`` asserts both
    at-most-one-winner-per-term and log agreement over the whole seed
    batch — including decisions a later term's traffic overwrites in
    the final state.

    ``durable=True`` persists exactly the columns the raft paper's
    Figure 2 marks persistent — currentTerm (TERM), votedFor (VOTED,
    here the voted-in term), and the log (LOGLEN + LOG0..) — across
    kill/restart via ``Workload.durable_cols`` (the FsSim power-fail
    analog, fs.rs:51). Role, votes, timer seq, ack mask and COMMIT stay
    volatile, as specified: a restarted node comes back a follower and
    re-learns commitIndex from its leader's next AppendEntries. The
    default ``durable=False`` keeps the historical diskless behavior
    (restart restores the initial row), which leans on the first
    retransmission to reinstall the whole log.

    ``durable=True`` now also adopts the engine's **two-phase sync
    discipline** (``Workload.durable_sync``): the persistent columns
    survive a kill only up to the node's last ``EmitBuilder.sync``.
    Every handler that dirties a Figure-2 column syncs in the same
    dispatch, before its messages go out — the fsync-before-reply
    placement the paper requires — so with no injected disk faults the
    trajectory is bit-identical to the pre-discipline durable mode (the
    revert is a no-op) and the oracle compare stays exact. Chaos
    ``DiskFault`` plans then exercise torn writes and lying syncs
    against exactly this surface.

    ``bug="nosync"`` plants the missing-sync mutant: the handlers never
    call ``sync``, so every "persistent" write really sits in the
    volatile write buffer and a kill wipes it back to the last synced
    image (the initial state) — acked votes and committed entries
    escape before durability, the bug class the FoundationDB/sled DST
    lineage exists to catch. The committed-value-loss hunt
    (``tools/store_soak.py``) must find it; correct placement must hold
    clean under the same fault space.

    With ``record=True`` and ``durable=True`` the model additionally
    records ``OP_SYNCED`` (a committed log-length change) and
    ``OP_RECOVER`` (the length a restarted node came back with) events
    for ``check.recovery_safety``.

    ``durable=True`` handlers are also **EIO-aware**: inside an
    injected observable fsync-failure window (``chaos.DiskFault``
    ``n_eio``, surfaced as ``ctx.sync_err`` — the batched
    ``FsSim.set_fail_writes``) a node withholds every externally
    visible durability promise — candidacy, vote grants, append acks,
    proposals — and retries after the window, so correctness holds
    under EIO storms by design. All the gates read a flag that is
    constant False on fault-free runs, keeping those trajectories (and
    the oracle compare) bit-identical.

    ``army=True`` appends one CLIENT node (index ``n_nodes`` — the
    servers stay 0..n_nodes-1 and the raft protocol never addresses
    it) and opens the client surface for open-loop load
    (``client_army`` builds the matching ``chaos.ClientArmy``): each
    arriving op probes server ``op_id % n_nodes``, which answers with
    its commit index — a dirty read, deliberately: it always completes
    while the probed server is up, so the measured RTT isolates the
    *transport and scheduling* tail (gray-failure slow links, pause
    storms) from leader-election availability. Probes to a dead server
    never complete — incomplete ops ARE the unavailability signal.

    ``cov_spread=True`` contributes protocol-specific coverage
    features (``Workload.cov_features``): the fleet's commit-index
    spread (max - min over the servers) and the (floor, spread) pair —
    the guidance signal for history hunts, where the interesting
    schedules are the ones that drag replicas' commit points apart (a
    wide spread is exactly the window a lost write or a recovery
    regression hides in). Coverage-only: traces and verdicts are
    bit-identical with it on or off."""
    if bug not in (None, "nosync"):
        raise ValueError(f"unknown raftlog bug {bug!r} (only 'nosync')")
    if bug and not durable:
        raise ValueError(
            "bug='nosync' plants a missing-sync mutant: it needs "
            "durable=True (diskless mode has no syncs to miss)"
        )
    majority = n_nodes // 2 + 1
    nodes = list(range(n_nodes))
    # army mode appends the client node AFTER the servers: the raft
    # loops above iterate `nodes` (servers only), so protocol traffic,
    # elections and the chaos kill draw never touch it
    n_total = n_nodes + (1 if army else 0)
    client = n_nodes
    w = n_writes
    width = LOG0 + w
    # the correct placement syncs every durable write in the dispatch
    # that made it; the planted mutant never syncs (see the docstring)
    sync_en = durable and bug != "nosync"
    # storage-event recording rides the existing record flag, but only
    # durable mode has syncs/recoveries to record — diskless histories
    # stay byte-identical to the pre-storage model
    rec_store = record and durable

    jv = jnp.arange(w, dtype=jnp.int32)  # log column index vector

    def _lastterm(st):
        """Term of the last log entry (0 for an empty log).

        One vectorized select over the log slice — bit-identical to the
        per-column where-chain it replaces (ll matches at most one
        column; ll == 0 sums nothing), at 1/w the op count: the
        lax.switch runs EVERY branch per dispatch, so per-branch op
        count is a first-order step cost (PROFILE_CPU_r06)."""
        ll = st[LOGLEN]
        terms = _entry_term(st[LOG0 : LOG0 + w])
        return jnp.sum(jnp.where(jv + 1 == ll, terms, 0)).astype(jnp.int32)

    def _arm_election(ctx, eb, new_seq, when):
        d = ctx.draw.user_int(timeout_min_ns, timeout_max_ns, _P_TIMEOUT)
        eb.after(d, user_kind(_H_TIMEOUT), ctx.node, (new_seq,), when=when)

    def _log_payload(st):
        return tuple(st[LOG0 + j] for j in range(w))

    def _send_appends(ctx, eb, st, term, when):
        """Replicate the sender's full log (install-style) to every peer."""
        idx = st[LOGLEN] - 1
        pay = _log_payload(st)
        for p in nodes:
            eb.send(
                p,
                user_kind(_H_APPEND),
                (term, idx, st[COMMIT], ctx.node),
                when=when & (jnp.int32(p) != ctx.node),
                pay=pay,
            )

    def on_init(ctx):
        eb = ctx.emits()
        # the army client (node n_nodes) runs no raft: no election
        # timer, no records. A constant True for army-off builds, so
        # pre-army trajectories are untouched.
        is_server = (
            ctx.node < jnp.int32(n_nodes) if army else jnp.asarray(True)
        )
        _arm_election(ctx, eb, jnp.int32(1), is_server)
        if rec_store:
            # a re-init at now > 0 is a restarted node reading its disk
            # back: record what log length it recovered with (the
            # recovery_safety detector floors this against OP_SYNCED)
            eb.record(
                OP_RECOVER, key=0, arg=ctx.state[LOGLEN],
                when=(ctx.now > 0) & is_server,
            )
        if chaos:
            # node 0's t=0 init schedules the seed's chaos plan (exactly
            # once per run: restarted nodes re-run on_init, but only the
            # epoch-0 instance of node 0 exists at t=0; later re-inits
            # see now > 0)
            first = (ctx.node == jnp.int32(0)) & (ctx.now == 0)
            who = ctx.draw.user_int(0, n_nodes, _P_KILL_WHO).astype(jnp.int32)
            at = ctx.draw.user_int(200_000_000, 500_000_000, _P_KILL_AT)
            revive = ctx.draw.user_int(100_000_000, 600_000_000, _P_REVIVE)
            eb.after(at, KIND_KILL, 0, (who,), when=first)
            eb.after(at + revive, KIND_RESTART, 0, (who,), when=first)
        new = ctx.state.at[TSEQ].set(1)
        return new, eb.build()

    def _eio(ctx):
        """The node's observable fsync-EIO bit (chaos DiskFault n_eio).

        Constant False outside an injected EIO window — and for the
        diskless / nosync variants — so every ``& ~_eio`` gate below is
        value-identical to the ungated model on fault-free runs (the
        oracle compare stays exact). A correct node's rule: never make
        an externally visible durability promise (candidacy, vote
        grant, append ack, proposal) while fsync is failing; retry
        after the window.
        """
        if sync_en and ctx.sync_err is not None:
            return ctx.sync_err
        return jnp.asarray(False)

    def on_timeout(ctx):
        st = ctx.state
        due = (ctx.args[0] == st[TSEQ]) & (st[ROLE] != jnp.int32(LEADER))
        err = _eio(ctx)
        # a node whose disk is failing cannot persist its candidacy
        # (votedFor=self): it skips this election and re-arms the SAME
        # timer seq so the timeout retries after the window
        fire = due & ~err
        term = st[TERM] + 1
        new = jnp.where(
            fire,
            st.at[ROLE].set(CANDIDATE).at[TERM].set(term).at[VOTED].set(term)
            .at[VOTES].set(1).at[TSEQ].set(st[TSEQ] + 1),
            st,
        )
        eb = ctx.emits()
        for p in nodes:
            eb.send(
                p,
                user_kind(_H_REQVOTE),
                (term, ctx.node, st[LOGLEN], _lastterm(st)),
                when=fire & (jnp.int32(p) != ctx.node),
            )
        _arm_election(ctx, eb, st[TSEQ] + 1, fire)
        _arm_election(ctx, eb, st[TSEQ], due & err)
        if sync_en:
            # currentTerm/votedFor changed: fsync before the vote
            # requests leave (Figure 2's persist-before-respond rule)
            eb.sync(when=fire)
        return new, eb.build()

    def on_reqvote(ctx):
        st = ctx.state
        term, cand = ctx.args[0], ctx.args[1]
        c_len, c_lt = ctx.args[2], ctx.args[3]
        newer = term > st[TERM]
        st1 = jnp.where(
            newer,
            st.at[TERM].set(term).at[ROLE].set(FOLLOWER).at[VOTES].set(0),
            st,
        )
        # the up-to-date rule: candidate's (last term, length) >= ours.
        # A failing disk (EIO window) withholds the grant — a vote that
        # cannot be persisted must not be promised; the candidate's
        # retransmitted request after the window can still win it.
        my_lt = _lastterm(st1)
        up_to_date = (c_lt > my_lt) | ((c_lt == my_lt) & (c_len >= st1[LOGLEN]))
        grant = (
            (term == st1[TERM]) & (st1[VOTED] < term) & up_to_date
            & ~_eio(ctx)
        )
        new = jnp.where(
            grant, st1.at[VOTED].set(term).at[TSEQ].set(st1[TSEQ] + 1), st1
        )
        eb = ctx.emits()
        eb.send(cand, user_kind(_H_GRANT), (term,), when=grant)
        _arm_election(ctx, eb, st1[TSEQ] + 1, grant)
        if sync_en:
            # a granted vote (votedFor) — and a bare term bump — must
            # hit the disk before the grant message can leave: a vote
            # that survives only in RAM re-votes after a crash
            eb.sync(when=newer | grant)
        return new, eb.build()

    def on_grant(ctx):
        st = ctx.state
        term = ctx.args[0]
        counts = (st[ROLE] == jnp.int32(CANDIDATE)) & (term == st[TERM])
        votes = jnp.where(counts, st[VOTES] + 1, st[VOTES])
        # a candidate whose disk is failing defers leadership: the
        # win-time re-stamp must be persisted before re-replication
        wins = counts & (votes >= jnp.int32(majority)) & ~_eio(ctx)
        new = st.at[VOTES].set(votes)
        new = jnp.where(wins, new.at[ROLE].set(LEADER), new)
        # win-time re-stamp: uncommitted suffix takes the new term (the
        # figure-8 guard, see module docstring) — one select over the
        # log slice (the _lastterm vectorization rule)
        log = new[LOG0 : LOG0 + w]
        stamped = (log & jnp.int32(0xFF)) | (term << jnp.int32(8))
        restamp = wins & (jv >= new[COMMIT]) & (jv < new[LOGLEN])
        new = new.at[LOG0 : LOG0 + w].set(jnp.where(restamp, stamped, log))
        has_inflight = new[LOGLEN] > new[COMMIT]
        new = jnp.where(
            wins,
            new.at[ACKS].set(
                jnp.where(has_inflight, jnp.int32(1) << ctx.node, 0)
            ),
            new,
        )
        eb = ctx.emits()
        _send_appends(ctx, eb, new, term, wins)
        eb.after(propose_ns, user_kind(_H_PROPOSE), ctx.node, (term,), when=wins)
        eb.after(retx_ns, user_kind(_H_RETX), ctx.node, (term,), when=wins)
        if record:
            eb.record(OP_ELECT, key=term, arg=ctx.node, when=wins)
        if sync_en:
            # the win-time re-stamp rewrote log entry terms: persist
            # before re-replicating the suffix
            eb.sync(when=wins)
        return new, eb.build()

    def on_append(ctx):
        st = ctx.state
        term, idx, l_commit = ctx.args[0], ctx.args[1], ctx.args[2]
        leader = ctx.args[3]
        ok = term >= st[TERM]
        newer_term = term > st[TERM]
        new = jnp.where(
            ok,
            st.at[TERM].set(term).at[ROLE].set(FOLLOWER)
            .at[TSEQ].set(st[TSEQ] + 1),
            st,
        )
        # adopt the leader's full log prefix (single-inflight install).
        # Within a term there is one leader and its log only grows, so a
        # same-term append may only EXTEND — a stale retransmission with
        # a lower idx must not regress a log we already acked at a
        # higher idx. A higher term overwrites unconditionally (the new
        # leader's log is authoritative).
        adopt = ok & (idx >= 0) & (newer_term | (idx + 1 >= st[LOGLEN]))
        take = adopt & (jv <= idx)
        new = new.at[LOG0 : LOG0 + w].set(
            jnp.where(take, ctx.payload[:w], new[LOG0 : LOG0 + w])
        )
        new = jnp.where(adopt, new.at[LOGLEN].set(idx + 1), new)
        new = jnp.where(
            ok, new.at[COMMIT].set(jnp.maximum(new[COMMIT], l_commit)), new
        )
        eb = ctx.emits()
        # EIO window: the entries were adopted in RAM but the fsync
        # will fail — withhold the ack (acking would be exactly the
        # acked-before-durable bug); the leader's retransmission after
        # the window re-adopts at the same idx and acks then
        err = _eio(ctx)
        eb.send(
            leader, user_kind(_H_ACKAPP), (term, idx, ctx.node),
            when=adopt & ~err,
        )
        # a heartbeat resets the election timer
        _arm_election(ctx, eb, st[TSEQ] + 1, ok)
        if sync_en:
            # adopted entries (and the term bump) fsync before the ack
            # leaves — THE sync whose absence is the classic
            # acked-but-not-durable bug (the bug="nosync" mutant)
            eb.sync(when=ok)
        if rec_store and sync_en:
            # a committed log-length change (adoptions that merely
            # re-install the same length are not length events). Under
            # an EIO window the sync did NOT commit — recording it
            # would teach recovery_safety a floor the disk never held.
            # The converse case is accepted conservatism: entries first
            # adopted INSIDE a window get their committing sync on a
            # same-length re-adopt after it, which this gate skips, so
            # the detector's floor can sit below the true synced state
            # (it misses nothing falsely, it just under-floors; a
            # per-node "unsynced adopt" flag would fix it but would
            # widen the state row the C++ oracle pins bit-for-bit)
            eb.record(
                OP_SYNCED, key=0, arg=idx + 1,
                when=adopt & ~err & (idx + jnp.int32(1) != st[LOGLEN]),
            )
        return new, eb.build()

    def on_ackapp(ctx):
        st = ctx.state
        term, idx, frm = ctx.args[0], ctx.args[1], ctx.args[2]
        counts = (
            (st[ROLE] == jnp.int32(LEADER))
            & (term == st[TERM])
            & (idx == st[LOGLEN] - 1)
            & (st[COMMIT] < st[LOGLEN])
        )
        acks = jnp.where(counts, st[ACKS] | (jnp.int32(1) << frm), st[ACKS])
        n_acks = jnp.sum(
            (acks >> jnp.arange(n_nodes, dtype=jnp.int32)) & jnp.int32(1)
        ).astype(jnp.int32)
        commit_now = counts & (n_acks >= jnp.int32(majority))
        new = st.at[ACKS].set(acks)
        new = jnp.where(commit_now, new.at[COMMIT].set(idx + 1), new)
        eb = ctx.emits()
        # propagate the commit index immediately
        _send_appends(ctx, eb, new, term, commit_now)
        if record:
            # one decision event per newly committed index (a leader
            # with a caught-up log may commit several at once): the
            # decided VALUE (low byte; the term byte is mutable by the
            # re-stamp, see OP_COMMIT note) — log agreement means no
            # index is ever recorded with two different values
            for j in range(w):
                eb.record(
                    OP_COMMIT, key=j, arg=new[LOG0 + j] & jnp.int32(0xFF),
                    when=commit_now
                    & (jnp.int32(j) >= st[COMMIT])
                    & (jnp.int32(j) <= idx),
                )
        eb.halt(when=commit_now & (new[COMMIT] == jnp.int32(w)))
        return new, eb.build()

    def on_propose(ctx):
        st = ctx.state
        term = ctx.args[0]
        alive_leader = (st[ROLE] == jnp.int32(LEADER)) & (term == st[TERM])
        # a leader with a failing disk does not propose (it pre-counts
        # its own ack below, which is a durability promise); the
        # propose timer re-arms via alive_leader, so it retries
        can = alive_leader & (st[COMMIT] == st[LOGLEN]) & (
            st[LOGLEN] < jnp.int32(w)
        ) & ~_eio(ctx)
        value = (ctx.draw.user(_P_VALUE) & jnp.uint32(0xFF)).astype(jnp.int32)
        entry = value | (st[TERM] << jnp.int32(8))
        ins = can & (jv == st[LOGLEN])
        new = st.at[LOG0 : LOG0 + w].set(
            jnp.where(ins, entry, st[LOG0 : LOG0 + w])
        )
        new = jnp.where(
            can,
            new.at[LOGLEN].set(st[LOGLEN] + 1)
            .at[ACKS].set(jnp.int32(1) << ctx.node),
            new,
        )
        eb = ctx.emits()
        _send_appends(ctx, eb, new, term, can)
        eb.after(
            propose_ns, user_kind(_H_PROPOSE), ctx.node, (term,),
            when=alive_leader,
        )
        if sync_en:
            # the leader's own append fsyncs before it counts its own
            # ack (it pre-set its ACKS bit above) or replicates
            eb.sync(when=can)
        if rec_store and sync_en:
            eb.record(OP_SYNCED, key=0, arg=st[LOGLEN] + 1, when=can)
        return new, eb.build()

    def on_retx(ctx):
        st = ctx.state
        term = ctx.args[0]
        alive_leader = (st[ROLE] == jnp.int32(LEADER)) & (term == st[TERM])
        # re-replicate whatever is outstanding; doubles as the heartbeat
        send = alive_leader & (st[LOGLEN] > 0)
        eb = ctx.emits()
        _send_appends(ctx, eb, st, term, send)
        eb.after(
            retx_ns, user_kind(_H_RETX), ctx.node, (term,), when=alive_leader
        )
        return ctx.state, eb.build()

    def on_areq(ctx):
        # army op arrival at the client (a ClientArmy pool row): mark
        # the invoke and probe one server, round-robin by op id. No
        # retries — open-loop clients never slow their offered load to
        # match a struggling system (that feedback is exactly what
        # hides the tail).
        op_id = ctx.args[0]
        eb = ctx.emits()
        eb.lat_start(op_id)
        eb.send(op_id % jnp.int32(n_nodes), user_kind(_H_APROBE), (op_id,))
        return ctx.state, eb.build()

    def on_aprobe(ctx):
        # a dirty read: any live server answers with its commit index
        # (read-only — raft state is never written on this path)
        op_id = ctx.args[0]
        eb = ctx.emits()
        eb.send(client, user_kind(_H_ARESP), (op_id, ctx.state[COMMIT]))
        return ctx.state, eb.build()

    def on_aresp(ctx):
        op_id = ctx.args[0]
        eb = ctx.emits()
        eb.lat_end(op_id)
        return ctx.state, eb.build()

    handler_names = (
        "init", "timeout", "reqvote", "grant", "append", "ackapp",
        "propose", "retx",
    )
    handlers = (
        on_init, on_timeout, on_reqvote, on_grant, on_append,
        on_ackapp, on_propose, on_retx,
    )
    if army:
        handler_names += ("areq", "aprobe", "aresp")
        handlers += (on_areq, on_aprobe, on_aresp)

    def _commit_spread(ns, now):
        # servers only (the army client's row never holds a commit
        # index); spread as its own feature word, plus the (floor,
        # spread) pair so the SAME spread at a new commit floor still
        # reads as fresh behavior. Both fields masked to their 8-bit
        # lanes: commit indices are bounded by n_writes but n_writes is
        # caller-chosen, and an overflowing floor must alias other
        # (floor, spread) pairs — never the discriminator bit or the
        # bare-spread word
        c = ns[:n_nodes, COMMIT]
        lo = jnp.min(c).astype(jnp.uint32)
        spread = (jnp.max(c).astype(jnp.uint32)) - lo
        m8 = jnp.uint32(0xFF)
        return (
            (spread, jnp.bool_(True)),
            ((lo & m8) | ((spread & m8) << jnp.uint32(8))
             | jnp.uint32(1 << 16),
             jnp.bool_(True)),
        )

    return Workload(
        name="raftlog"
        + ("-nosync" if bug == "nosync" else "")
        + ("-record" if record else "")
        + ("-army" if army else ""),
        handler_names=handler_names,
        n_nodes=n_total,
        state_width=width,
        handlers=handlers,
        # widest: on_grant = N gated append rows + propose + retx timers
        max_emits=n_nodes + 2,
        payload_words=w,
        args_words=4,
        # largest timer: election timeout, leader timers, or the chaos
        # restart at 'at + revive' <= 500 + 600 ms
        delay_bound_ns=max(
            timeout_max_ns, propose_ns, retx_ns, 1_100_000_000
        ),
        durable_cols=(
            (TERM, VOTED, LOGLEN) + tuple(LOG0 + j for j in range(w))
            if durable
            else None
        ),
        # two-phase sync discipline over exactly those columns: a kill
        # keeps them only up to the node's last EmitBuilder.sync
        durable_sync=durable,
        cov_features=_commit_spread if cov_spread else None,
        # capacity sizing: elections are a handful per run even under
        # chaos; commit records total w plus re-commits after leader
        # changes (a new leader re-records the indices it re-confirms).
        # Durable mode adds OP_SYNCED length events (per node, per
        # length change, bounded by w plus truncation churn) and one
        # OP_RECOVER per restart. Overflow is loud (hist_drop), and
        # search_seeds quarantines it.
        history=(
            HistorySpec(
                capacity=6 * w + 24 + (n_nodes * (w + 6) if durable else 0),
                max_records=max(w, 1),
            )
            if record
            else None
        ),
        # army mode: at most one lat_start OR lat_end per invocation
        lat_markers=1 if army else 0,
        # prefetch every handler draw into the step's batched RNG block
        # (engine BatchRNG): the switch runs all branches per dispatch,
        # so each of these would otherwise be its own per-step cipher
        draw_purposes=(_P_TIMEOUT, _P_VALUE)
        + ((_P_KILL_AT, _P_KILL_WHO, _P_REVIVE) if chaos else ()),
    )


def client_army(
    n_ops: int = 256,
    t_min_ns: int = 20_000_000,
    t_max_ns: int = 400_000_000,
    n_nodes: int = 5,
    op_base: int = 0,
):
    """A :class:`chaos.ClientArmy` bound to raftlog's client surface
    (``make_raftlog(army=True)`` with the same ``n_nodes``): ops arrive
    at the appended client node and probe server ``op_id % n_nodes``.
    Compose it into a ``FaultPlan`` next to the chaos specs and run
    with ``latency=LatencySpec(ops >= op_base + n_ops)``."""
    from ..chaos.plan import ClientArmy

    return ClientArmy(
        node=n_nodes,  # the appended client node
        kind=user_kind(_H_AREQ),
        n_ops=n_ops,
        t_min_ns=t_min_ns,
        t_max_ns=t_max_ns,
        op_base=op_base,
    )


def lint_entries():
    """Tracing entry points for the static non-interference matrix
    (madsim_tpu.lint). The durable variant is the disk-discipline-ON
    axis: the storage columns become core there (a crash reads the
    disk image back into node_state) and ``engine.derived_fields``
    reclassifies them — the proof then covers the remaining derived
    set. The army variant is the client-load axis: its pre-seeded pool
    rows and lat_* marker writes ride the rank-placement select chains
    and the cold-bank appends (PR 8), so the proof covers the engine's
    heaviest placement surface, not just protocol traffic."""
    kw = dict(pool_size=64, loss_p=0.02, clog_backoff_max_ns=2_000_000_000)
    return [
        ("raftlog/plain", make_raftlog(), kw),
        ("raftlog/record", make_raftlog(record=True), kw),
        ("raftlog/durable", make_raftlog(durable=True, record=True), kw),
        ("raftlog/army", make_raftlog(army=True), kw),
    ]


# Declared interval-certification horizon (lint.absint): chaos soaks
# replicate for sim-minutes; 300 sim-seconds covers every recorded
# raftlog campaign shape with room.
ABSINT_HORIZON_NS = 300 * 1_000_000_000


def absint_entries():
    """Range-contract entry points for the interval prover
    (lint.absint): lint_entries rows plus the declared horizon."""
    return [
        (tag, wl, kw, ABSINT_HORIZON_NS)
        for tag, wl, kw in lint_entries()
    ]
