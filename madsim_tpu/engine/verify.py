"""Engine determinism verification helpers.

The batched analog of the reference's replay determinism checker
(``check_determinism``, runtime/mod.rs:165-190): run the same seeds
twice (or on two backends) and compare the uint64 trace hashes — any
divergence names the first differing seed. The strongest form is the
C++ oracle compare in engine/oracle.py; this module is the quick
self-check usable on any workload without an oracle implementation.
"""

from __future__ import annotations

import numpy as np

import jax

from ..runtime.rand import DeterminismError
from .core import EngineConfig, Workload, make_init, make_run, time32_eligible

__all__ = [
    "HISTORY_FIELDS",
    "check_determinism",
    "check_layouts",
    "compare_traces",
]


# operation-history buffers (engine/core.py SimState): deliberately NOT
# folded into the trace hash (the C++ oracle mirrors the hash and knows
# nothing of histories), so determinism checks compare them directly
HISTORY_FIELDS = ("hist_count", "hist_drop", "hist_word", "hist_t")


def compare_traces(a, b, what: str = "run", history: bool = True) -> None:
    """Raise DeterminismError naming the first seed whose traces differ.

    With ``history=True`` (default) the operation-history buffers are
    compared too, when both states carry them — history columns are
    outside the trace hash, so a divergence there would otherwise be
    invisible to this check.
    """
    ta, tb = np.asarray(a.trace), np.asarray(b.trace)
    if ta.shape != tb.shape:
        raise DeterminismError(
            f"{what}: batch shapes differ ({ta.shape} vs {tb.shape})"
        )
    diff = np.nonzero(ta != tb)[0]
    if diff.size:
        s = int(diff[0])
        raise DeterminismError(
            f"non-determinism detected in {what}: seed index {s} "
            f"(seed {int(np.asarray(a.seed)[s])}) produced trace "
            f"{int(ta[s]):#x} vs {int(tb[s]):#x}"
        )
    if not history:
        return
    for field in HISTORY_FIELDS:
        da, db = getattr(a, field, None), getattr(b, field, None)
        if da is None or db is None:
            continue  # compacted results without banked history columns
        da, db = np.asarray(da), np.asarray(db)
        if da.shape != db.shape:
            raise DeterminismError(
                f"{what}: history field {field!r} shapes differ "
                f"({da.shape} vs {db.shape}) — runs used different "
                f"HistorySpec capacities"
            )
        if not np.array_equal(da, db):
            s = int(
                np.nonzero((da != db).reshape(da.shape[0], -1).any(axis=1))[0][0]
            )
            raise DeterminismError(
                f"non-determinism detected in {what}: history field "
                f"{field!r} diverged at seed index {s} "
                f"(seed {int(np.asarray(a.seed)[s])})"
            )


def check_determinism(
    wl: Workload, cfg: EngineConfig, seeds, n_steps: int
) -> None:
    """Run the workload twice over ``seeds``; raise on any divergence.

    Catches hidden nondeterminism in handlers (e.g. float ops that
    compile differently between runs) the way the reference's two-run
    RNG-log compare catches nondeterministic user code.
    """
    seeds = np.asarray(seeds, np.uint64)
    init = make_init(wl, cfg)
    run = jax.jit(make_run(wl, cfg, n_steps))
    a = run(init(seeds))
    b = run(init(seeds))
    compare_traces(a, b, what=f"{wl.name} x2")


def check_layouts(
    wl: Workload, cfg: EngineConfig, seeds, n_steps: int
) -> None:
    """Run the workload through BOTH step lowerings (dense and scatter,
    see make_step's ``layout``) and raise on any trace divergence.

    The library form of the cross-backend determinism check
    (examples/cross_backend_check.py runs it across real silicon): the
    two lowerings are the same program in different clothes, so any
    difference is an engine bug, typically an out-of-range index whose
    gather/scatter semantics diverge from the dense masks.
    """
    seeds = np.asarray(seeds, np.uint64)
    variants = [("dense", False), ("scatter", False)]
    if time32_eligible(wl, cfg):
        # the int32 offset representation is a third value-identical
        # lowering (make_step's ``time32``); cross it with both layouts
        variants += [("dense", True), ("scatter", True)]
    runs = {}
    for layout, t32 in variants:
        # pool_index pinned OFF: the dense layout has no tile index,
        # and this check's subject is the dense/scatter duality — the
        # indexed lowering has its own on/off identity pins
        # (tests/test_pool_index.py, lint-soak cert 1c)
        init = make_init(wl, cfg, time32=t32, pool_index=False)
        runs[(layout, t32)] = jax.jit(
            make_run(wl, cfg, n_steps, layout=layout, time32=t32,
                     pool_index=False)
        )(init(seeds))
    base_key = ("dense", False)
    base = runs[base_key]
    for key, other in runs.items():
        if key == base_key:
            continue
        what = f"{wl.name} {base_key}-vs-{key}"
        compare_traces(base, other, what=what)
        # the trace doesn't see everything (dropped-on-overflow events,
        # a mis-masked state write after the last fold): compare the
        # same field set the cross-backend artifact checks, plus the
        # node state. ev_time is excluded: representations differ by
        # design (absolute int64 vs rebased int32 offsets)
        for field in ("now", "halted", "halt_time", "msg_count", "overflow",
                      "node_state", "ev_valid", "hist_count", "hist_drop",
                      "hist_word", "hist_t"):
            da = np.asarray(getattr(base, field))
            sa = np.asarray(getattr(other, field))
            if not np.array_equal(da, sa):
                seed_idx = np.nonzero(
                    (da != sa).reshape(da.shape[0], -1).any(axis=1)
                )[0][0]
                raise DeterminismError(
                    f"{what}: field {field!r} diverged "
                    f"at seed index {int(seed_idx)} "
                    f"(seed {int(seeds[seed_idx])})"
                )
