"""Single-seed replay: a failing seed becomes a readable event timeline.

The batched engine's per-seed evidence is a uint64 trace *hash* — great
for equality checking, useless for a human chasing a bug. The reference
gives users `tracing` spans per node/task (SURVEY.md §5); the batched
analog is this module: re-run ONE seed through the C++ oracle with its
per-dispatch event log attached and print what actually happened, in
order, with virtual timestamps, node ids and decoded handler names.

The log rows are exactly the tuples the trace hash folds, so
:func:`refold` recomputes the certified hash from the timeline — the
test gate proving the human-readable story and the bit-identical
evidence are the same events (any divergence is an oracle/logging bug).

Typical flow with the chaos search::

    report = search_seeds(wl, cfg, invariant, n_seeds=65536, ...)
    for seed in report.failing_seeds[:3]:
        print(format_timeline(*replay(wl, cfg, int(seed), 600, txns=4)))
"""

from __future__ import annotations

import ctypes
from dataclasses import dataclass

import numpy as np

from . import core as _core
from . import oracle as _oracle
from .core import (
    FIRST_EXT_KIND,
    FIRST_USER_KIND,
    _TRACE_MIX,
    _TRACE_PRIME,
    EngineConfig,
    Workload,
)

__all__ = ["ReplayEvent", "replay", "refold", "format_timeline"]

# derived from the KIND_* constants so the timeline can't drift from
# engine/core.py's numbering (the single source of truth the C++ oracle
# mirrors too)
_ENGINE_KIND_NAMES = {
    v: k[len("KIND_"):]
    for k, v in vars(_core).items()
    if k.startswith("KIND_")
}



@dataclass(frozen=True)
class ReplayEvent:
    """One dispatched event: the tuple the trace hash folds.

    ``emit_ns`` is the clock at which the event was INSERTED into the
    pool — for a delivered message, the sender's dispatch that emitted
    it (the true send time Perfetto flow arrows anchor at). -1 = not
    captured (oracle replays and pre-emit rings); it never participates
    in the trace fold.

    ``seq``/``parent``/``lam`` are the causal-provenance columns
    (``causal=True`` captures only; engine/core.py make_step): ``seq``
    is this dispatch's per-seed sequence number, ``parent`` the seq of
    the dispatch that emitted this event (or a ``PARENT_*`` sentinel:
    -1 init, -2 chaos/engine plan, -3 client-army row), ``lam`` the
    destination node's Lamport clock AFTER the happens-before fold.
    Defaults mean "not captured"; none participate in the trace fold.
    """

    time_ns: int
    kind: int
    node: int
    src: int  # -1 = timer/engine event, else sending node
    args: tuple
    pay: tuple
    emit_ns: int = -1
    seq: int = -1
    parent: int = -1
    lam: int = 0

    def kind_name(self, wl: Workload | None = None) -> str:
        # extended chaos kinds (>= FIRST_EXT_KIND) are engine kinds too
        # — without this clause a plan-driven timeline would label a
        # SLOW_LINK as user[234]
        if self.kind < FIRST_USER_KIND or self.kind >= FIRST_EXT_KIND:
            return _ENGINE_KIND_NAMES.get(self.kind, f"engine[{self.kind}]")
        u = self.kind - FIRST_USER_KIND
        names = getattr(wl, "handler_names", None) if wl is not None else None
        if names and u < len(names):
            return str(names[u])
        return f"user[{u}]"


def replay(
    wl: Workload,
    cfg: EngineConfig,
    seed: int,
    n_steps: int,
    cap: int = 4096,
    **model_kwargs,
):
    """Re-run one seed through the oracle with event logging.

    Returns ``(events, result)`` — the dispatched-event list and the
    oracle's :class:`OracleResult`. The log buffer auto-grows until the
    full run fits, so the timeline is never silently truncated.
    ``model_kwargs`` are the workload factory parameters, exactly as
    for :func:`engine.oracle.run_oracle`.
    """
    lib = _oracle.load()
    lib.oracle_log_count.restype = ctypes.c_int64
    lib.oracle_set_log.restype = None
    # declared argtypes so the detach call's plain ints marshal as full
    # 64-bit values (an unmarked int marshals as 4-byte c_int, which for
    # the stack-passed 7th arg could leave garbage high bits in cap)
    _p64 = ctypes.POINTER(ctypes.c_int64)
    _p32 = ctypes.POINTER(ctypes.c_int32)
    lib.oracle_set_log.argtypes = [_p64, _p32, _p32, _p32, _p32, _p32,
                                   ctypes.c_int64]
    while True:
        t = np.zeros(cap, np.int64)
        kind = np.zeros(cap, np.int32)
        node = np.zeros(cap, np.int32)
        src = np.zeros(cap, np.int32)
        args = np.zeros((cap, 4), np.int32)
        pay = np.zeros((cap, 4), np.int32)
        # the log buffers are process-global (oracle.cpp g_log_*): hold
        # the reentrant oracle lock across the whole attach->run->detach
        # window so no other oracle_run (with or without logging) can
        # write through the attached pointers. run_oracle re-enters the
        # same lock on this thread; other threads block. The attach is
        # INSIDE the with/try so any failure still detaches + releases.
        with _oracle.ORACLE_LOCK:
            try:
                lib.oracle_set_log(
                    t.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    kind.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    node.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    args.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    pay.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    ctypes.c_int64(cap),
                )
                res = _oracle.run_oracle(wl, cfg, seed, n_steps, **model_kwargs)
                count = int(lib.oracle_log_count())
            finally:
                # detach: the buffers die with this frame, a later
                # un-logged oracle_run must not write through dangling
                # pointers
                lib.oracle_set_log(None, None, None, None, None, None, 0)
        if count <= cap:
            break
        cap = max(cap * 2, count)
    events = [
        ReplayEvent(
            time_ns=int(t[i]),
            kind=int(kind[i]),
            node=int(node[i]),
            src=int(src[i]),
            args=tuple(int(x) for x in args[i]),
            pay=tuple(int(x) for x in pay[i]),
        )
        for i in range(count)
    ]
    return events, res


def refold(events, wl: Workload) -> int:
    """Recompute the trace hash from a replay's events (engine
    ``_trace_fold`` semantics). Must equal both the oracle's and the
    batched engine's trace for the same (seed, config, steps)."""
    mix = int(_TRACE_MIX)
    prime = int(_TRACE_PRIME)
    mask = (1 << 64) - 1
    trace = 0
    for e in events:
        h = (e.time_ns * mix) & mask
        h ^= (e.kind & 0xFFFFFFFF) << 32
        h ^= (e.node & 0xFFFFFFFF) << 40
        h &= mask
        for j in range(4):  # words past args_words are zero: identical
            h ^= (e.args[j] & 0xFFFFFFFF) << (8 * j)
        h &= mask
        if wl.payload_words > 0:
            acc = 0
            for w in range(wl.payload_words):
                acc += (e.pay[w] & 0xFFFFFFFF) * (mix ^ w)
            h ^= acc & mask
        trace = (trace * prime + h) & mask
    return trace


def format_timeline(events, res=None, wl: Workload | None = None) -> str:
    """Render a replay as text, one dispatched event per line."""
    lines = []
    n_args = getattr(wl, "args_words", 4) if wl is not None else 4
    for e in events:
        origin = "timer" if e.src < 0 else f"node{e.src}"
        # positions matter (args[1] == 0 is information): print the
        # declared width verbatim, never skip zero words
        argstr = ",".join(str(a) for a in e.args[:n_args])
        lines.append(
            f"[{e.time_ns / 1e6:>12.3f}ms] node{e.node} <- "
            f"{e.kind_name(wl)}({argstr}) from {origin}"
        )
    if res is not None:
        lines.append(
            f"-- halted={res.halted} at {res.halt_time / 1e6:.3f}ms, "
            f"{res.msg_count} msgs, trace {res.trace:#018x}"
        )
    return "\n".join(lines)
