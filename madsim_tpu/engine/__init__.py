"""madsim_tpu.engine — the batched, XLA-compiled simulation core.

This is the TPU-native inversion of the reference's architecture
(SURVEY.md §7): instead of one OS thread per seeded run, simulation state
lives in dense arrays with a leading seed axis and one compiled step
function advances every seed in lockstep. See engine/core.py for the
full design narrative and engine/rng.py for the counter-based RNG
contract.

Importing this package enables 64-bit mode in JAX: virtual time is exact
int64 nanoseconds and trace hashes are uint64 — the integer disciplines
that make cross-backend traces bit-identical. The heavy per-seed state
(node state, event kinds/args, RNG) stays 32-bit.
"""

import jax

jax.config.update("jax_enable_x64", True)

from .core import (  # noqa: E402,F401
    CAUSAL_STATE_FIELDS,
    DERIVED_STATE_FIELDS,
    POOL_INDEX_STATE_FIELDS,
    POOL_TILE_CANDIDATES,
    STORAGE_STATE_FIELDS,
    FIRST_EXT_KIND,
    FIRST_USER_KIND,
    KIND_CLOG,
    KIND_CLOG_1W,
    KIND_CLOG_NODE,
    KIND_DUP_OFF,
    KIND_DUP_ON,
    KIND_HALT,
    KIND_KILL,
    KIND_NOP,
    KIND_PAUSE,
    KIND_RESTART,
    KIND_RESUME,
    KIND_SKEW,
    KIND_SLOW_LINK,
    KIND_SYNC_LOSS,
    KIND_SYNC_OK,
    KIND_TORN_OFF,
    KIND_TORN_ON,
    KIND_UNCLOG,
    KIND_UNCLOG_1W,
    KIND_UNCLOG_NODE,
    KIND_UNSLOW,
    HALT_DONE,
    HALT_IDLE,
    HALT_RUNNING,
    HALT_TIME_LIMIT,
    MET_HALT_CODE,
    METRIC_NAMES,
    N_METRICS,
    EmitBuilder,
    Emits,
    EngineConfig,
    HandlerCtx,
    HistorySpec,
    LAT_EDGES_NS,
    LatencySpec,
    N_LAT_BUCKETS,
    PlanRows,
    RETRY_ATTEMPT_MAX,
    RETRY_ATTEMPT_SHIFT,
    RETRY_OP_MASK,
    RETRY_STATE_FIELDS,
    RetrySpec,
    MET_RETRY,
    MET_RETRY_GIVEUP,
    SimState,
    Workload,
    lat_bucket,
    lat_bucket_hi,
    lat_bucket_lo,
    ABSINT_COUNTER_MAX,
    ABSINT_HORIZON_NS,
    ABSINT_STEP_MAX,
    ColumnContract,
    StateContract,
    SLOW_MULT_MAX,
    build_pool_index,
    column_contracts,
    core_fields,
    derived_fields,
    pool_index_eligible,
    pool_tile,
    resolve_rank_place_max_pool,
    make_init,
    make_run,
    make_run_while,
    make_step,
    pack_slow_arg,
    retry_token,
    retry_token_attempt,
    retry_token_op,
    time32_eligible,
    user_kind,
)
from .compact import make_run_compacted  # noqa: E402,F401
from .verify import check_determinism, check_layouts, compare_traces  # noqa: E402,F401
from .checkpoint import load as load_checkpoint  # noqa: E402,F401
from .checkpoint import save as save_checkpoint  # noqa: E402,F401
from .search import SearchReport, make_sweep, search_seeds  # noqa: E402,F401
from .replay import ReplayEvent, format_timeline, refold, replay  # noqa: E402,F401
from .rng import (  # noqa: E402,F401
    DRAW_SPAN_MAX,
    PURPOSE_LANES,
    Draw,
    PurposeLane,
    chance_threshold,
    lane_of,
    np_threefry2x32,
    threefry2x32,
    validate_user_purposes,
)
