"""Checkpoint / resume for batched simulation state.

The reference has no checkpointing — reproducibility comes from
replaying the seed (SURVEY.md §5 "Checkpoint/resume: none"). For the
batched engine a checkpoint is just the state arrays, so saving and
resuming a 65k-seed run is cheap and worth having: long chaos searches
can snapshot progress, and a snapshot plus the (workload, config) pair
deterministically resumes to the same trajectory as the uninterrupted
run (the test asserts that).

Format: a single .npz with one entry per SimState field plus a manifest
entry recording the config hash, so resuming under a different config —
which would silently change the trajectory — is rejected.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

import jax.numpy as jnp

from .core import (
    POOL_INDEX_STATE_FIELDS,
    EngineConfig,
    SimState,
    _resolve_pool_index,
    build_pool_index,
    pool_tile,
)

__all__ = ["save", "load"]

_MANIFEST_KEY = "__madsim_manifest__"
# format 2: ev_kind/ev_node/ev_src/ev_retry merged into packed ev_meta
# (core.py byte-layout note); format 3: operation-history columns
# (hist_word/hist_t/hist_count/hist_drop, madsim_tpu.check); format 4:
# extended chaos state (slow/dup/skew, madsim_tpu.chaos); format 5:
# coverage fingerprint (cov/cov_last, madsim_tpu.explore); format 6:
# observability columns (cov_hits/met/tl_*, madsim_tpu.obs); format 7:
# storage sync-discipline columns (disk/wmask/sync_loss/torn,
# madsim_tpu.chaos disk faults); format 8: the observable fsync-EIO
# window column (sync_eio, ctx.sync_err); format 9: the tail-latency
# columns (lat_inv/lat_resp/lat_hist/lat_count/lat_drop) and the
# emit-time sidecar (ev_emit/tl_emit, madsim_tpu.obs latency);
# format 10: the causal-provenance columns (lam/ev_parent/ev_lam and
# the ring's tl_seq/tl_parent/tl_lam, causal=True) — unlike the pool
# index these ACCUMULATE (a Lamport clock is history, not a pure
# function of the pool), so they are part of the format, not rebuilt
# on load. Older checkpoints are rejected with the designed mismatch
# error rather than a KeyError mid-load; format 11: the client-retry
# columns (rt_done/rt_attempt/rt_deadline, retry=RetrySpec) — CORE
# state like the causal clocks (rt_done feeds the deliver gate and the
# armed deadlines are history), zero-size off-policy so off-policy
# checkpoints stay byte-comparable modulo the empty entries.
#
# The readiness-index tile summaries (POOL_INDEX_STATE_FIELDS, ISSUE
# 13) are NOT part of the format: they are derived by construction
# (a pure function of ev_time/ev_valid — engine.build_pool_index is
# the definition), so save() skips them and load() rebuilds them for
# whatever pool_index resolution the resumed run uses.
_FORMAT = 11


def save(path: str, state: SimState, cfg: EngineConfig) -> None:
    """Write a batched SimState to ``path`` (.npz)."""
    arrays = {
        f.name: np.asarray(getattr(state, f.name))
        for f in dataclasses.fields(state)
        if f.name not in POOL_INDEX_STATE_FIELDS  # derived: rebuilt on load
    }
    # ev_time dtype records the time representation (int32 = time32
    # offset form, int64 = absolute): time32 auto-resolution depends on
    # the config *and* the builder arguments, so the config hash alone
    # can't catch a checkpoint resumed under the other representation
    manifest = json.dumps(
        {
            "format": _FORMAT,
            "config_hash": cfg.hash(),
            "ev_time_dtype": str(np.asarray(state.ev_time).dtype),
        }
    )
    arrays[_MANIFEST_KEY] = np.frombuffer(manifest.encode(), dtype=np.uint8)
    # write through a file handle so the given path is used verbatim
    # (np.savez(path_str) would append .npz and break load symmetry)
    with open(path, "wb") as fh:
        np.savez(fh, **arrays)


def load(
    path: str,
    cfg: EngineConfig,
    time32: bool | None = None,
    pool_index: bool | None = None,
    retry=None,
) -> SimState:
    """Load a SimState; refuses a checkpoint taken under another config.

    ``time32``: the representation the resumed run will use (what you
    will pass to make_run/make_run_while/make_run_compacted). time32
    auto-resolution is platform-dependent (int32 on accelerators when
    eligible, int64 on CPU), so a checkpoint saved on one platform can
    silently mismatch the builder on another; passing it here turns the
    later step-time dtype TypeError into an immediate, explained error.
    None skips the check (the manifest still records the saved dtype).

    ``pool_index``: whether the resumed run carries the readiness-index
    tile summaries (pass the same value you pass the run builders;
    None = the same auto rule). The summaries are never read from the
    file — they are REBUILT here from the loaded pool columns
    (``engine.build_pool_index``), which is what makes them derived
    state: the checkpoint format carries only ground truth.

    ``retry``: the RetrySpec the resumed run will use (what you will
    pass to the run builders), or None for an off-policy resume. The
    retry columns are CORE state (armed deadlines are history), so a
    checkpoint taken under one policy shape cannot resume under
    another: a mismatch between the saved ``rt_done`` width and the
    declared ``retry.n_ops`` is refused here with the shape named,
    rather than surfacing as a jit shape error mid-resume.
    """
    with np.load(path) as data:
        manifest = json.loads(bytes(data[_MANIFEST_KEY]).decode())
        if manifest.get("format") != _FORMAT:
            raise ValueError(f"unknown checkpoint format {manifest.get('format')}")
        if manifest["config_hash"] != cfg.hash():
            raise ValueError(
                "checkpoint was taken under a different EngineConfig "
                f"({manifest['config_hash']} != {cfg.hash()}); resuming would "
                "silently change the simulation trajectory"
            )
        fields = {
            f.name: jnp.asarray(data[f.name])
            for f in dataclasses.fields(SimState)
            if f.name not in POOL_INDEX_STATE_FIELDS
        }
    if _resolve_pool_index(cfg, pool_index):
        fields["tile_min"], fields["tile_cnt"] = build_pool_index(
            fields["ev_time"], fields["ev_valid"], pool_tile(cfg.pool_size)
        )
    else:
        s = fields["ev_valid"].shape[:-1] + (0,)
        fields["tile_min"] = jnp.zeros(s, fields["ev_time"].dtype)
        fields["tile_cnt"] = jnp.zeros(s, jnp.int32)
    state = SimState(**fields)
    saved_dt = manifest.get("ev_time_dtype", str(np.asarray(state.ev_time).dtype))
    if time32 is not None:
        want_dt = "int32" if time32 else "int64"
        if saved_dt != want_dt:
            raise ValueError(
                f"checkpoint ev_time dtype is {saved_dt} but the resumed run "
                f"was declared time32={time32} ({want_dt}); pass the matching "
                "explicit time32= to make_run/make_run_while/"
                "make_run_compacted (auto-resolution is platform-dependent, "
                "so a checkpoint saved on another platform will not resume "
                "under the default)"
            )
    saved_ops = int(np.asarray(state.rt_done).shape[-1])
    want_ops = 0 if retry is None else int(retry.n_ops)
    if saved_ops != want_ops:
        raise ValueError(
            f"checkpoint carries retry columns for {saved_ops} ops but the "
            f"resumed run declared "
            f"{'no retry policy' if retry is None else f'retry.n_ops={want_ops}'}"
            "; armed retry deadlines are core state, so resume with the "
            "checkpoint's own RetrySpec (or an off-policy checkpoint "
            "off-policy) — pass the matching retry= here and to "
            "make_run/make_run_while/make_run_compacted"
        )
    return state
