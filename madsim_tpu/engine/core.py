"""Batched discrete-event simulation core — the TPU path.

The reference advances one seeded simulation per OS thread: a
single-threaded executor pops ready tasks in random order, polls
arbitrary futures, and jumps a virtual clock between timer events
(reference madsim/src/sim/task.rs:142-216, time/mod.rs:45-60). This
module inverts that architecture for TPUs: **simulation state is a pytree
of dense arrays with a leading seed axis**, and one XLA-compiled step
function advances *every* seed by one event in lockstep —
``vmap`` over seeds, ``lax.scan`` over steps, ``shard_map``/``jit`` with
``NamedSharding`` over device meshes (see madsim_tpu.parallel).

Mapping from the reference's moving parts to array form:

  reference (per run)                      engine (per seed row)
  ---------------------------------------  --------------------------------
  ready queue + timer wheel                one event pool (E slots): time,
    (task.rs:176-216, time/mod.rs:45-60)   kind, dst, src, epoch, args
  random ready-task pick (mpsc.rs:73-83)   per-event latency/cost draws
                                           randomize order; argmin pops the
                                           earliest event deterministically
  50-100 ns poll cost (task.rs:213)        poll-cost draw added to the
                                           clock after each dispatch
  serial SmallRng (rand.rs:30-61)          counter-based threefry draws
                                           keyed (seed, step, purpose)
  NodeInfo epoch swap on kill              alive/epoch arrays; events carry
    (task.rs:255-276)                      their target's epoch and are
                                           dropped on mismatch
  NetSim clog/loss/latency                 clog matrix (N,N); per-send loss
    (network.rs:75-95, 268-276)            and latency draws; clogged
                                           deliveries self-reschedule with
                                           exponential backoff
                                           (net/mod.rs:341-355 semantics)
  user futures polled by the executor      user code is a **state
                                           machine**: per-node int32 state
                                           rows + pure handler functions
                                           dispatched by ``lax.switch``

The last row is the central design decision (SURVEY.md §7 hard part 1):
XLA cannot trace arbitrary coroutines, so batched workloads are written
as event handlers over integer node state. The asyncio-style frontend in
madsim_tpu.runtime remains the ergonomic single-seed API; this engine is
the scaling path, and workloads written for it get 10^4-10^5 seeds per
chip.

Everything in the hot path is integer arithmetic (int32/int64/uint32) —
bit-identical across CPU and TPU backends, which makes the trace hash an
exact cross-backend determinism check (the analog of the reference's
replay checker, runtime/mod.rs:165-190).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .rng import (
    DRAW_SPAN_MAX,
    PURPOSE_DUP,
    PURPOSE_LATENCY,
    PURPOSE_LOSS,
    PURPOSE_POLL_COST,
    PURPOSE_RETRY,
    PURPOSE_TORN,
    PURPOSE_USER,
    Draw,
    chance_threshold,
    validate_user_purposes,
)

__all__ = [
    "EngineConfig",
    "HistorySpec",
    "LatencySpec",
    "N_LAT_BUCKETS",
    "LAT_EDGES_NS",
    "lat_bucket",
    "lat_bucket_lo",
    "lat_bucket_hi",
    "Workload",
    "SimState",
    "RetrySpec",
    "RETRY_ATTEMPT_SHIFT",
    "RETRY_ATTEMPT_MAX",
    "RETRY_OP_MASK",
    "RETRY_STATE_FIELDS",
    "retry_token",
    "retry_token_op",
    "retry_token_attempt",
    "MET_RETRY",
    "MET_RETRY_GIVEUP",
    "Emits",
    "EmitBuilder",
    "HandlerCtx",
    "PlanRows",
    "METRIC_NAMES",
    "N_METRICS",
    "MET_HALT_CODE",
    "HALT_RUNNING",
    "HALT_DONE",
    "HALT_TIME_LIMIT",
    "HALT_IDLE",
    "KIND_KILL",
    "KIND_RESTART",
    "KIND_CLOG",
    "KIND_UNCLOG",
    "KIND_CLOG_NODE",
    "KIND_UNCLOG_NODE",
    "KIND_HALT",
    "KIND_NOP",
    "KIND_PAUSE",
    "KIND_RESUME",
    "FIRST_USER_KIND",
    "FIRST_EXT_KIND",
    "KIND_SLOW_LINK",
    "KIND_UNSLOW",
    "KIND_DUP_ON",
    "KIND_DUP_OFF",
    "KIND_SKEW",
    "KIND_CLOG_1W",
    "KIND_UNCLOG_1W",
    "KIND_SYNC_LOSS",
    "KIND_SYNC_OK",
    "KIND_TORN_ON",
    "KIND_TORN_OFF",
    "pack_slow_arg",
    "unpack_slow_arg",
    "user_kind",
    "make_init",
    "make_step",
    "make_run",
    "time32_eligible",
    "DERIVED_STATE_FIELDS",
    "STORAGE_STATE_FIELDS",
    "POOL_INDEX_STATE_FIELDS",
    "CAUSAL_STATE_FIELDS",
    "derived_fields",
    "core_fields",
    "ColumnContract",
    "column_contracts",
    "ABSINT_HORIZON_NS",
    "ABSINT_COUNTER_MAX",
    "ABSINT_STEP_MAX",
    "SLOW_MULT_MAX",
    "pool_tile",
    "pool_index_eligible",
    "resolve_layout",
    "build_pool_index",
    "resolve_rank_place_max_pool",
]

_INF_NS = np.int64(2**62)
_INF_32 = np.int32(2**31 - 1)
_T32_LIMIT = 2**31 - 1  # max future-event offset representable in int32

# Pool-size crossover for the scatter layout's two placement lowerings
# (see make_step's ``placement``). Rank-matched placement costs fused
# vector passes over the whole pool (O(E) elements, ~60 ns per pass per
# seed per 64 slots on CPU); scatter-store placement costs one serial
# row-update per emit slot (~110 ns per row on XLA CPU, independent of
# E). Measured crossover sits near E ≈ 1k; 512 keeps headroom for wider
# emit rows (tools/profile_step.py re-measures it per config). This is
# the DEFAULT of the documented ``make_step(rank_place_max_pool=)``
# knob; the env var below overrides the default without touching call
# sites (a deployment knob — program-shaping, so callers that CACHE
# compiled runs must key on the resolved value: engine.search's
# _RUN_CACHE folds resolve_rank_place_max_pool() into its key).
_RANK_PLACE_MAX_POOL = 512
_RANK_PLACE_ENV = "MADSIM_RANK_PLACE_MAX_POOL"


def _env_int(name: str, default: int) -> int:
    """A validated non-negative int env override (a deployment typo
    must name the variable, not crash as a bare int() error or pass
    through as silent nonsense)."""
    env = os.environ.get(name)
    if not env:
        return default
    try:
        val = int(env)
    except ValueError:
        raise ValueError(f"{name}={env!r} is not an integer") from None
    if val < 0:
        raise ValueError(f"{name}={env!r} must be >= 0")
    return val


def resolve_rank_place_max_pool(override: int | None = None) -> int:
    """Resolve the rank-placement pool-size crossover (the ``placement``
    default in :func:`make_step`): explicit ``override`` beats the
    ``MADSIM_RANK_PLACE_MAX_POOL`` env var beats the measured module
    default (512). Pinned by tests/test_pool_index.py."""
    if override is not None:
        if override < 0:
            raise ValueError(
                f"rank_place_max_pool must be >= 0, got {override}"
            )
        return int(override)
    return _env_int(_RANK_PLACE_ENV, _RANK_PLACE_MAX_POOL)


# ---------------------------------------------------------------------------
# Readiness-partitioned pool index (make_step's ``pool_index``). The E
# pool slots split into fixed tiles of T rows; SimState carries per-tile
# summary columns (tile_min = earliest VALID time in the tile, tile_cnt
# = number of valid slots), maintained incrementally by the step. The
# pop becomes argmin over E/T tile minima + argmin inside the ONE
# winning tile, and free-slot search for placement becomes a cumsum
# over per-tile free counts + a rank match inside the target tiles —
# O(E/T + T + emits) per event instead of the flat layout's O(E)
# masked-min + flatnonzero passes, which dominate the step at
# client-army pool sizes (thousands of slots, see ISSUE 13 /
# PROFILE_CPU_r07). Values are identical by construction: argmin over
# tile minima followed by argmin inside the winning tile picks exactly
# the global first-minimum slot, and the rank-matched free search
# reproduces flatnonzero's slot order bit-for-bit.
#
# The summaries are DERIVED BY CONSTRUCTION — a pure function of
# (ev_time, ev_valid), rebuilt on checkpoint restore (engine/checkpoint
# excludes them from the format; _FORMAT is unchanged) — but they are
# trajectory-COUPLED: the pop reads them. So they live in core_fields
# for the static taint proof (derived obs columns must stay out of
# them, which lint.check_matrix proves over the indexed program), and
# their own correctness certificate is the index on/off bit-identity
# pin (tests/test_pool_index.py, tests/test_stepident.py goldens).
# ---------------------------------------------------------------------------
# candidate tile widths, preferred first. T ~ sqrt(E) is the pop
# optimum; 64 serves every army-scale pool (2048 -> 32 tiles, 8192 ->
# 128), the smaller widths let small test pools (40/48/64/72/96) run
# the indexed program for identity pins.
POOL_TILE_CANDIDATES = (64, 32, 16, 8)
# auto-resolution threshold: pool_index=None turns the index on (CPU
# backend, scatter layout) for pools STRICTLY larger than this. 1024
# keeps every measured small-pool config (<= 512, BENCH_SPECS) on
# today's lowering — the interleaved A/B (BENCH_AB_r07.txt) measured
# the crossover between 1024 and 2048 on CPU.
_POOL_INDEX_MIN_POOL = 1024
_POOL_INDEX_ENV = "MADSIM_POOL_INDEX_MIN_POOL"


def _pool_index_min_pool() -> int:
    return _env_int(_POOL_INDEX_ENV, _POOL_INDEX_MIN_POOL)


def pool_tile(pool_size: int) -> int:
    """Tile width the readiness index would use for this pool size
    (the largest :data:`POOL_TILE_CANDIDATES` divisor leaving >= 2
    tiles), or 0 when no candidate divides it — the pool is then not
    index-eligible and ``pool_index=True`` is rejected."""
    for t in POOL_TILE_CANDIDATES:
        if pool_size % t == 0 and pool_size // t >= 2:
            return t
    return 0


def pool_index_eligible(cfg: "EngineConfig") -> bool:
    """Whether this config's pool can carry the readiness index."""
    return pool_tile(cfg.pool_size) > 0


def resolve_layout(layout: str | None) -> str:
    """THE layout default (make_step's ``layout=None`` rule): scatter
    on the CPU backend, dense elsewhere. Shared with every caller that
    must pre-resolve a build flag against the layout a run will
    actually compile (engine.search resolves ``pool_index`` through
    it), so the rule cannot silently fork."""
    if layout is None:
        return "scatter" if jax.default_backend() == "cpu" else "dense"
    if layout not in ("dense", "scatter"):
        raise ValueError(f"unknown layout {layout!r}")
    return layout


def _resolve_pool_index(
    cfg: "EngineConfig", pool_index: bool | None, dense: bool | None = None
) -> bool:
    """Shared by make_init (dense=None: the backend rule, mirroring the
    layout default) and make_step (dense = the resolved layout). Auto
    (None) turns the index on only where it wins: the scatter layout's
    large pools ON THE CPU BACKEND — the backend conjunct keeps a
    forced ``layout="scatter"`` on an accelerator consistent with
    make_init's layout-blind resolution (a mismatch the other way —
    CPU init auto-on + a forced dense step — is absorbed by the
    off-step's index-preserving rebuild, see make_step). Explicit True
    on an ineligible pool or under the dense layout is an error, never
    a silent fallback."""
    tile = pool_tile(cfg.pool_size)
    if pool_index is None:
        if dense is None:
            dense = jax.default_backend() != "cpu"
        return (
            bool(tile)
            and not dense
            and jax.default_backend() == "cpu"
            and cfg.pool_size > _pool_index_min_pool()
        )
    if pool_index:
        if not tile:
            raise ValueError(
                f"pool_index requested but pool_size={cfg.pool_size} has "
                f"no tile divisor in {POOL_TILE_CANDIDATES} with >= 2 "
                f"tiles; round the pool up (chaos.FaultPlan.min_pool_size "
                f"sizes army pools tile-aligned)"
            )
        if dense:
            raise ValueError(
                "pool_index is a scatter-layout lowering; the dense "
                "layout's one-hot pop has no tile index — pass "
                "pool_index=False (or leave it None) with layout='dense'"
            )
    return bool(pool_index)


def build_pool_index(ev_time, ev_valid, tile: int):
    """Compute ``(tile_min, tile_cnt)`` summaries from pool columns.

    Pure function of the pool — THE definition the step maintains
    incrementally and checkpoint restore / tests rebuild from scratch.
    Works on one seed's ``(E,)`` columns or a batch's ``(S, E)``
    (any leading axes; the pool axis is last). ``tile_min`` of an empty
    tile is the +inf sentinel of the time dtype; every consumer masks
    by ``tile_cnt > 0`` (stale minima of empty tiles are meaningless,
    exactly like stale times of invalid pool slots)."""
    v = jnp.asarray(ev_valid)
    t = jnp.asarray(ev_time)
    e = v.shape[-1]
    if tile <= 0 or e % tile:
        raise ValueError(f"tile={tile} does not partition pool_size={e}")
    shape = v.shape[:-1] + (e // tile, tile)
    inf = jnp.asarray(_INF_32 if t.dtype == jnp.int32 else _INF_NS, t.dtype)
    tile_min = jnp.min(jnp.where(v.reshape(shape), t.reshape(shape), inf), axis=-1)
    tile_cnt = jnp.sum(v.reshape(shape).astype(jnp.int32), axis=-1)
    return tile_min, tile_cnt

# ---------------------------------------------------------------------------
# ev_meta byte layout. The four small per-event fields travel as one
# uint32 word — every per-slot pick and placement touches one array
# instead of four, the dominant per-step cost on TPU (the placement
# selects scale with the number of placed words, SCALING.md §3).
#   byte 0: kind                  (engine kinds + user handlers <= 255)
#   byte 1: target node + 1       (-1..n clipped; 0 = no node, n+1 = OOB)
#   byte 2: source node + 1       (0 = timer/engine event)
#   byte 3: clog-retry count, saturating at 255 (the backoff shift caps
#           at 34, so saturation is behaviorally invisible)
# Out-of-range kinds/nodes are clipped at pack time; every consumer
# treats a clipped value exactly like the out-of-range original (a
# no-match in the one-hots / in_range masks), so observable semantics
# are unchanged.
# ---------------------------------------------------------------------------


def _meta_pack(kind, node1, src1, retry):
    return (
        kind.astype(jnp.uint32)
        | (node1.astype(jnp.uint32) << jnp.uint32(8))
        | (src1.astype(jnp.uint32) << jnp.uint32(16))
        | (retry.astype(jnp.uint32) << jnp.uint32(24))
    )


def _meta_kind(meta):
    return (meta & jnp.uint32(0xFF)).astype(jnp.int32)


def _meta_node(meta):
    return ((meta >> jnp.uint32(8)) & jnp.uint32(0xFF)).astype(jnp.int32) - 1


def _meta_src(meta):
    return ((meta >> jnp.uint32(16)) & jnp.uint32(0xFF)).astype(jnp.int32) - 1


def _meta_retry(meta):
    return ((meta >> jnp.uint32(24)) & jnp.uint32(0xFF)).astype(jnp.int32)


def _check_meta_ranges(wl: "Workload") -> None:
    """ev_meta byte-range requirements — enforced wherever packing
    happens (make_init and make_step), so no corrupt state can be built."""
    if wl.n_nodes > 254:
        raise ValueError(
            f"n_nodes={wl.n_nodes} exceeds the meta byte range (254)"
        )
    if FIRST_USER_KIND + len(wl.handlers) > FIRST_EXT_KIND:
        raise ValueError(
            f"{len(wl.handlers)} handlers exceed the user kind range "
            f"[{FIRST_USER_KIND}, {FIRST_EXT_KIND}) (extended chaos "
            f"kinds occupy {FIRST_EXT_KIND}..255)"
        )
_TRACE_PRIME = np.uint64(0x100000001B3)
_TRACE_MIX = np.uint64(0x9E3779B97F4A7C15)

# ---------------------------------------------------------------------------
# Event kinds. Engine kinds come first so user handler k has kind
# FIRST_USER_KIND + k regardless of workload; handler 0 is by convention
# on_init (run for every node at t=0 and again after RESTART).
# ---------------------------------------------------------------------------
KIND_KILL = 0  # args[0]=node          Handle::kill        (runtime/mod.rs:246)
KIND_RESTART = 1  # args[0]=node       Handle::restart     (runtime/mod.rs:251)
KIND_CLOG = 2  # args[0]=a args[1]=b   NetSim::clog_link   (net/mod.rs:157-216)
KIND_UNCLOG = 3  # args[0]=a args[1]=b
KIND_CLOG_NODE = 4  # args[0]=node     NetSim::clog_node
KIND_UNCLOG_NODE = 5  # args[0]=node
KIND_HALT = 6  # scenario complete: freeze this seed's instance
KIND_NOP = 7
KIND_PAUSE = 8  # args[0]=node      Handle::pause       (runtime/mod.rs:256)
KIND_RESUME = 9  # args[0]=node     Handle::resume
FIRST_USER_KIND = 10

# Extended chaos kinds (madsim_tpu.chaos): allocated at the TOP of the
# kind byte so every existing kind id — and therefore every existing
# trace hash and the C++ oracle — is untouched. User handler kinds live
# in [FIRST_USER_KIND, FIRST_EXT_KIND); anything >= FIRST_EXT_KIND is an
# engine kind again (dispatched inline, exempt from the epoch/pause
# gates exactly like kinds < FIRST_USER_KIND). The oracle does not
# implement these kinds, so plan-driven runs are verified by the
# two-run/two-layout checks, not the oracle compare.
FIRST_EXT_KIND = 244
KIND_SLOW_LINK = 244  # args[0]=a args[1]=pack_slow_arg(b, mult): gray
#                       failure — multiply a<->b latency (b=-1: node a)
KIND_UNSLOW = 245  # args[0]=a args[1]=pack_slow_arg(b, 1): restore x1
KIND_DUP_ON = 246  # message duplication: every send also delivers a copy
KIND_DUP_OFF = 247
KIND_SKEW = 248  # args[0]=node args[1]=skew_ns: the node's clock reads
#                  now+skew (what its handlers observe as ctx.now)
KIND_CLOG_1W = 249  # args[0]=src args[1]=dst — asymmetric partition edge
KIND_UNCLOG_1W = 250
# disk-fault kinds (madsim_tpu.chaos DiskFault; only meaningful for
# Workload.durable_sync workloads — a no-op otherwise, like DUP_ON
# without dup_rows). args[0] = target node, -1 = every node.
KIND_SYNC_LOSS = 251  # args[1]=0 (default): the node's disk starts
#                       LYING — sync commits are silently dropped (the
#                       committed bit never sets). args[1]=1: the disk
#                       starts FAILING — syncs still don't commit, but
#                       the fault is OBSERVABLE: handlers see
#                       ctx.sync_err while the window is open, the
#                       batched analog of FsSim.set_fail_writes raising
#                       OSError(EIO)
KIND_SYNC_OK = 252  # end of the sync-lie/EIO window: syncs commit again
KIND_TORN_ON = 253  # arm torn-write mode: the next KILL persists only a
#                     threefry-drawn PREFIX of the last uncommitted
#                     durable write (PURPOSE_TORN) on top of the synced
#                     image — the FDB/sled power-failure fault
KIND_TORN_OFF = 254


# ---------------------------------------------------------------------------
# Fleet-metric slot layout (madsim_tpu.obs). SimState.met is an
# (N_METRICS,) int32 vector per seed when the step is built with
# ``metrics=True`` (else zero-size). Every slot except MET_HALT_CODE is
# a monotone counter folded at dispatch time; all of them are derived
# from values the step already computes — no RNG draws, no feedback into
# the trajectory, so metrics-off runs are bit-identical (the cov_words
# discipline applied again). The obs package reduces these columns on
# device (obs.fleet_reduce) so a 65k-seed sweep reports fleet histograms
# without moving per-seed state to the host.
# ---------------------------------------------------------------------------
MET_SENT = 0  # messages sent (valid send emits at dispatch, lost or not)
MET_DELIVERED = 1  # message deliveries dispatched (src >= 0)
MET_LOST = 2  # sends dropped by the loss draw
MET_DEAD_DROP = 3  # sends dropped because the dst was dead at send time
MET_DUP = 4  # duplicated deliveries inserted (chaos KIND_DUP_ON)
MET_CRASH = 5  # KIND_KILL dispatches
MET_RESTART = 6  # KIND_RESTART dispatches
MET_PAUSE = 7  # KIND_PAUSE dispatches
MET_CLOG_BLOCK = 8  # delivery attempts held by a clogged link (each
#                     backoff retry counts again — it is an attempt)
MET_TIMER = 9  # user timer fires (non-message user dispatches)
MET_RECORD = 10  # history records appended
MET_RNG = 11  # threefry blocks drawn while the seed was active
MET_HALT_CODE = 12  # not a counter: HALT_* code of how the seed stopped
# storage-fault counters (Workload.durable_sync; always 0 otherwise).
# Appended after MET_HALT_CODE so every pre-existing slot id is stable.
MET_SYNC = 13  # sync commits honored (EmitBuilder.sync, disk committed)
MET_SYNC_LOST = 14  # syncs that failed to commit inside a
#                     KIND_SYNC_LOSS window — silently (lie mode) or
#                     observably (EIO mode, ctx.sync_err)
MET_TORN = 15  # kills that landed inside an armed torn-write window
#                (whether bytes actually tore depends on an uncommitted
#                write being outstanding — on a correct fsync-everywhere
#                model nothing ever is, which is the theorem, so this
#                counts the exercised windows, not the data damage)
# client-retry counters (RetrySpec; always 0 without a policy). Appended
# after MET_TORN so every pre-existing slot id is stable.
MET_RETRY = 16  # army re-deliveries dispatched (attempt > 0 that ran)
MET_RETRY_GIVEUP = 17  # ops abandoned: the max_attempts-th timer fired
#                        with no response recorded — at-least-once gave up
N_METRICS = 18

METRIC_NAMES = (
    "sent", "delivered", "lost", "dead_drop", "dup", "crash", "restart",
    "pause", "clog_block", "timer", "record", "rng_blocks", "halt_code",
    "sync", "sync_lost", "torn", "retry", "retry_giveup",
)

# ---------------------------------------------------------------------------
# Tail-latency sketch ladder (madsim_tpu.obs latency). Per-op latencies
# fold ON DEVICE into a per-seed log-linear histogram — the property
# that matters from t-digest is *exact mergeability* (sketch of a union
# = sum of sketches), which a FIXED bucket ladder gives for free while
# staying pure integer arithmetic (bit-identical across backends, like
# every other column). Ladder: bucket 0 holds [0, 64 µs); buckets 1..62
# are quarter-octaves (edge ratio 2^(1/4) ≈ 1.19x) from 64 µs up to
# ~3.0 s; bucket 63 saturates above that. Quantiles read off the ladder
# are exact to one bucket of rank error — ~19% relative, far inside
# what any p99 SLO statement needs — and the ladder is a static module
# constant, so merged sketches from any run ever taken remain
# comparable.
# ---------------------------------------------------------------------------
N_LAT_BUCKETS = 64
_LAT_EDGE0_NS = 1 << 16  # 65.536 µs, the bottom of the interesting range
# 63 edges; bucket(v) = #edges <= v, in 0..63. Rounded to exact int64
# once, host-side: the table itself is the spec.
LAT_EDGES_NS = np.asarray(
    [int(round(_LAT_EDGE0_NS * 2.0 ** (b / 4.0))) for b in range(N_LAT_BUCKETS - 1)],
    np.int64,
)


def lat_bucket(v_ns) -> np.ndarray:
    """Host-side ladder lookup: bucket index of a latency (vectorized)."""
    return np.searchsorted(LAT_EDGES_NS, np.asarray(v_ns, np.int64), side="right")


def lat_bucket_lo(b) -> np.ndarray:
    """Inclusive lower edge of bucket ``b`` (0 for bucket 0)."""
    b = np.asarray(b, np.int64)
    return np.where(b <= 0, 0, LAT_EDGES_NS[np.clip(b - 1, 0, N_LAT_BUCKETS - 2)])


def lat_bucket_hi(b) -> np.ndarray:
    """Exclusive upper edge of bucket ``b`` (the top bucket saturates at
    the last edge — values above it are reported AS that edge, loudly
    documented rather than silently exact)."""
    b = np.asarray(b, np.int64)
    return LAT_EDGES_NS[np.clip(b, 0, N_LAT_BUCKETS - 2)]


@dataclasses.dataclass(frozen=True)
class LatencySpec:
    """Build parameters of the engine's latency observability tap.

    ``ops`` sizes the per-seed op-slot columns: every client-army op id
    must lie in [0, ops). ``phases``/``phase_ns`` cut the run into
    fixed measurement windows (an op belongs to the window its INVOKE
    fell in; the last window is open-ended): per-window sketches are
    what makes an SLO check gray-failure-aware — a p99 blowup during a
    120 ms fault window is invisible in a whole-run percentile but is
    exactly window k's histogram. Hashable (frozen), so it keys the
    compiled-run caches like every other build flag.
    """

    ops: int
    phases: int = 1
    phase_ns: int = 1 << 27  # ~134 ms, the coverage time-phase width

    def __post_init__(self):
        if self.ops < 1:
            raise ValueError(f"LatencySpec.ops must be >= 1, got {self.ops}")
        if self.phases < 1:
            raise ValueError(
                f"LatencySpec.phases must be >= 1, got {self.phases}"
            )
        if self.phase_ns < 1:
            raise ValueError(
                f"LatencySpec.phase_ns must be >= 1, got {self.phase_ns}"
            )


# ---------------------------------------------------------------------------
# Client-retry token packing (madsim_tpu.chaos RetryPolicy). A retried
# op rides the SAME user kind as the original offer; the attempt id is
# packed into the high bits of the op token (args[0]) so handlers,
# history records and the Perfetto sidecar can tell re-sends apart while
# attempt-0 tokens stay PLAIN op ids — the bit-identity-off-policy
# invariant costs nothing to state: with no policy, no attempt is ever
# nonzero, so every token is the pre-retry value.
# ---------------------------------------------------------------------------
RETRY_ATTEMPT_SHIFT = 26
RETRY_ATTEMPT_MAX = 15  # attempt ids 0..15 fit bits 26..29 (sign bit free)
RETRY_OP_MASK = (1 << RETRY_ATTEMPT_SHIFT) - 1


def retry_token(op, attempt):
    """Pack (op id, attempt id) into an op token. Host or traced."""
    return op | (attempt << RETRY_ATTEMPT_SHIFT)


def retry_token_op(token):
    """The plain op id of a token (identity for attempt-0 tokens)."""
    return token & RETRY_OP_MASK


def retry_token_attempt(token):
    """The attempt id of a token (0 for plain pre-retry tokens)."""
    return (token >> RETRY_ATTEMPT_SHIFT) & RETRY_ATTEMPT_MAX


# backoff entries are clipped host-side so the traced jitter product
# (entry * uint32 draw) stays inside int64: cap * 2^32 < 2^63
_RETRY_BACKOFF_CAP = 2**31 - 1


@dataclasses.dataclass(frozen=True)
class RetrySpec:
    """Build parameters of the engine's client-retry timer mechanism.

    The compiled form of ``chaos.RetryPolicy`` attached to a
    ``ClientArmy``: ``kind``/``node``/``op_base``/``n_ops`` identify
    the army's offered ops (one retry-state slot per op), the policy
    fields drive the timers. Each delivered army attempt arms ONE
    follow-up pool row at ``now + timeout_ns + backoff + jitter`` with
    the attempt id incremented; when it pops, the op is re-delivered
    unless a response was recorded meanwhile (the op's ``lat_end``
    marker — the same first-response-wins discipline the latency tap
    uses, which is why a retry build requires ``Workload.lat_markers``).
    ``max_attempts`` counts total deliveries: the row carrying attempt
    id ``max_attempts`` is the give-up sentinel — it never delivers,
    only closes the books (MET_RETRY_GIVEUP). Backoff before attempt
    ``a >= 1`` is ``backoff_base_ns * backoff_mult**(a-1)``, jittered
    by a fresh PURPOSE_RETRY threefry draw scaled to ``[0, jitter]`` of
    the backoff — per (seed, step), so the schedule is seed-pure.
    Hashable (frozen), so it keys the compiled-run caches like every
    other build flag.
    """

    kind: int
    node: int
    op_base: int
    n_ops: int
    timeout_ns: int
    max_attempts: int = 3
    backoff_base_ns: int = 0
    backoff_mult: float = 2.0
    jitter: float = 0.0

    def __post_init__(self):
        if self.n_ops < 1:
            raise ValueError(f"RetrySpec.n_ops must be >= 1, got {self.n_ops}")
        if self.timeout_ns < 1:
            raise ValueError(
                f"RetrySpec.timeout_ns must be >= 1, got {self.timeout_ns}"
            )
        if not (1 <= self.max_attempts <= RETRY_ATTEMPT_MAX):
            raise ValueError(
                f"RetrySpec.max_attempts must be in 1..{RETRY_ATTEMPT_MAX} "
                f"(the token packs attempts into 4 bits), got "
                f"{self.max_attempts}"
            )
        if self.op_base < 0:
            raise ValueError(
                f"RetrySpec.op_base must be >= 0, got {self.op_base}"
            )
        if self.op_base + self.n_ops - 1 > RETRY_OP_MASK:
            raise ValueError(
                f"RetrySpec op ids reach {self.op_base + self.n_ops - 1}, "
                f"past the {RETRY_ATTEMPT_SHIFT}-bit token op field "
                f"(max {RETRY_OP_MASK})"
            )
        if not (FIRST_USER_KIND <= self.kind < FIRST_EXT_KIND):
            raise ValueError(
                f"RetrySpec.kind={self.kind} must be a user kind "
                f"(in [{FIRST_USER_KIND}, {FIRST_EXT_KIND}))"
            )
        if self.backoff_base_ns < 0:
            raise ValueError(
                f"RetrySpec.backoff_base_ns must be >= 0, got "
                f"{self.backoff_base_ns}"
            )
        if self.backoff_mult < 1.0:
            raise ValueError(
                f"RetrySpec.backoff_mult must be >= 1, got "
                f"{self.backoff_mult}"
            )
        if not (0.0 <= self.jitter <= 1.0):
            raise ValueError(
                f"RetrySpec.jitter must be in [0, 1], got {self.jitter}"
            )


def _retry_backoff_tables(rt: RetrySpec):
    """Host-side backoff tables, indexed by the NEXT attempt id.

    Entry ``a`` is the deterministic backoff before delivering attempt
    ``a`` (0 for a=0/1 when base is 0), clipped to the int64-safe cap;
    the jit table is the maximum jitter addend (``backoff * jitter``,
    same cap) that the uint32 draw scales down. Both are plain Python
    int tuples — compiled into the step as constants.
    """
    boff = [0]
    for a in range(1, rt.max_attempts + 1):
        b = rt.backoff_base_ns * rt.backoff_mult ** (a - 1)
        boff.append(min(int(b), _RETRY_BACKOFF_CAP))
    bjit = [min(int(b * rt.jitter), _RETRY_BACKOFF_CAP) for b in boff]
    return tuple(boff), tuple(bjit)


def _check_retry(wl: "Workload", retry: "RetrySpec | None") -> int:
    """Validate a retry build parameter; returns n_ops (0 = off).

    Shared by make_init and make_step so no mismatched pair of builders
    can be constructed (the _check_obs discipline).
    """
    if retry is None:
        return 0
    if not isinstance(retry, RetrySpec):
        raise TypeError(
            f"retry must be a RetrySpec or None, got {type(retry).__name__}"
        )
    if wl.lat_markers == 0:
        raise ValueError(
            "retry needs a workload with latency markers "
            "(Workload.lat_markers > 0): the response-deadline timer is "
            "disarmed by the op's lat_end marker, so a model that never "
            "marks responses would retry forever"
        )
    return retry.n_ops


# MET_HALT_CODE values
HALT_RUNNING = 0  # still live (or stopped only by the step cap)
HALT_DONE = 1  # workload emitted KIND_HALT: scenario complete
HALT_TIME_LIMIT = 2  # cfg.time_limit_ns tripped
HALT_IDLE = 3  # event pool ran empty while unhalted (a deadlocked seed:
#                nothing pending, nothing will ever be)


# ---------------------------------------------------------------------------
# Derived-state manifest (madsim_tpu.lint). The engine's observability
# discipline — "off = zero-size arrays + bit-identical traces" — rests
# on these SimState fields being WRITE-ONLY with respect to the
# trajectory: the step may read them to append to them, but no value
# derived from them may ever reach a core column, an RNG draw, or the
# trace fold. The names below are the stable taint-source vocabulary
# the static non-interference proof (lint.check_noninterference) tags
# and the isolation-frontier report cites; obs.explain's views use the
# same field names, so a reported leak names exactly the columns a
# forensics reader already knows.
# ---------------------------------------------------------------------------

# always derived, whatever the build flags: history columns
# (madsim_tpu.check), the coverage fingerprint (explore), fleet metrics
# and the timeline ring (obs). With the matching tap off they are
# zero-size arrays — trivially non-interfering — and with it on the
# proof obligation is exactly the bit-identity the runtime tests sample.
DERIVED_STATE_FIELDS = (
    "hist_count", "hist_drop", "hist_word", "hist_t",
    "cov", "cov_last", "cov_hits",
    "met",
    "tl_count", "tl_drop", "tl_t", "tl_meta", "tl_args", "tl_pay",
    # emit-time sidecar (timeline_cap > 0): the pool row's insertion
    # clock, read only into tl_emit — flow-arrow anchoring, never the
    # trajectory
    "ev_emit", "tl_emit",
    # causal provenance (causal=True): per-node Lamport clocks, the
    # pool rows' emitting-dispatch seq + emit-time clock, and the ring
    # columns they bank into — read exclusively to fold more causal
    # state / the ring, never the trajectory
    "lam", "ev_parent", "ev_lam", "tl_seq", "tl_parent", "tl_lam",
    # tail-latency columns (LatencySpec): per-op invoke/response clocks
    # and the per-seed log-linear sketch
    "lat_inv", "lat_resp", "lat_hist", "lat_count", "lat_drop",
)

# ev_parent sentinel classes (causal=True): a pool row whose value is
# >= 0 was emitted by the dispatch with that event-sequence number
# (SimState.step at emit time); negative values classify rows with no
# emitting dispatch. obs.causal treats sentinel-parented events as DAG
# roots and labels them by class.
PARENT_NONE = -1  # on_init rows and never-written slots
PARENT_PLAN = -2  # compiled fault-plan rows (engine/extended-chaos kinds)
PARENT_ARMY = -3  # client-army plan rows (open-loop USER-kind arrivals)

# the two-phase sync-discipline columns: derived (zero-size) when
# Workload.durable_sync is off, CORE when it is on — a crash then reads
# the disk image back into node_state, a legitimate feedback path.
STORAGE_STATE_FIELDS = ("disk", "wmask", "sync_loss", "sync_eio", "torn")

# the readiness-index tile summaries (see the pool-index note above):
# derived BY CONSTRUCTION — a pure function of (ev_time, ev_valid),
# rebuilt on checkpoint restore, excluded from the checkpoint format —
# but trajectory-coupled (the pop reads them), so they are NOT in the
# taint-source set: the static proof treats them as core columns (obs
# state must never reach them) and their value-correctness certificate
# is the index on/off bit-identity pin. Zero-size when the index is
# off, the usual discipline.
POOL_INDEX_STATE_FIELDS = ("tile_min", "tile_cnt")

# the causal-provenance columns (causal=True, ISSUE 19): inside the
# derived set above, zero-size when the axis is off. Named separately
# so schema-sensitive consumers (tools/step_goldens.py digests every
# SimState field name+shape) can keep pre-causal golden digests valid
# for causal=False builds — the off-state value identity is pinned by
# tests/test_causal.py, the on-state fold by its rederive pins.
CAUSAL_STATE_FIELDS = (
    "lam", "ev_parent", "ev_lam", "tl_seq", "tl_parent", "tl_lam",
)

# the client-retry columns (RetrySpec, ISSUE 20): CORE state, not
# derived — rt_done feeds the deliver/suppress gate, so retried
# trajectories legitimately depend on it — but zero-size when no policy
# is attached (the usual off-axis discipline: retry-off runs are
# bit-identical to pre-retry builds). Named separately for the same
# schema-sensitive consumers CAUSAL_STATE_FIELDS serves: excluding the
# field NAMES keeps pre-retry golden digests valid for retry-off
# builds; the off-state value identity is pinned by tests/test_retry.py.
RETRY_STATE_FIELDS = ("rt_done", "rt_attempt", "rt_deadline")


def derived_fields(wl: "Workload") -> tuple:
    """SimState field names that are derived-only for this workload.

    The manifest the static non-interference proof (madsim_tpu.lint)
    taints: no data path from any of these fields may reach a field
    outside the set (nor the trace fold, which lives in the core
    ``trace`` field). Build flags (metrics/cov_words/timeline_cap)
    don't change membership — an off tap is a zero-size array whose
    non-interference is vacuous — but the sync discipline does: its
    columns feed ``node_state`` on a crash when ``durable_sync`` is on.
    """
    out = DERIVED_STATE_FIELDS
    if not wl.durable_sync:
        out = out + STORAGE_STATE_FIELDS
    return out


def core_fields(wl: "Workload") -> tuple:
    """Complement of :func:`derived_fields` over the SimState fields."""
    derived = set(derived_fields(wl))
    return tuple(
        f.name for f in dataclasses.fields(SimState)
        if f.name not in derived
    )


# ---------------------------------------------------------------------------
# Column range contracts (lint.absint). Each SimState column declares
# the integer range its values occupy at step boundaries, under the
# certification horizon — the assumptions the interval abstract
# interpreter seeds its walk with, and the vocabulary its findings
# cite. Two tracked families:
#   "time"    — virtual-clock values (absolute int64 ns, or int32
#               offsets under time32). Bounded by the horizon plus the
#               largest insertion offset; under time32, pool columns
#               span the full int32 range because STALE slot offsets
#               keep rebasing after their slot is consumed and may wrap
#               (masked at every use — the per-site pragma'd
#               subtractions in make_step are exactly these).
#   "counter" — monotone/capacity-bounded counts (event sequence
#               number, overflow/drop tallies, history/timeline fills,
#               metrics). Bounded by capacity where one exists, else by
#               ABSINT_COUNTER_MAX (the certified run-length budget).
# Untracked columns (hashes, RNG seeds, workload state words, packed
# meta) get their full dtype range and no family: arithmetic on them is
# either intentionally modular (unsigned hashes/ciphers) or
# workload-defined (node_state), neither a time32/counter wraparound
# surface.
# ---------------------------------------------------------------------------

# Default certification horizon: the largest virtual clock the prover
# certifies arithmetic under when the config declares no time_limit_ns.
# 2^42 ns ~ 73 sim-minutes — an order of magnitude past every recorded
# run shape (bench runs sim seconds to minutes); models declare their
# own (smaller) horizons via absint_entries().
ABSINT_HORIZON_NS = 1 << 42
# Certified bound on unbounded counters (cumulative drops, msg counts,
# metrics): a run is certified for at most this many counted events.
ABSINT_COUNTER_MAX = 1 << 30
# Certified bound on the event sequence number (the RNG step
# coordinate, uint32): one instance is certified for this many steps.
ABSINT_STEP_MAX = 1 << 31


@dataclasses.dataclass(frozen=True)
class ColumnContract:
    """Declared value range of one SimState column at step boundaries."""

    field: str
    lo: int
    hi: int
    family: str | None = None  # "time" | "counter" | None (untracked)
    note: str = ""


@dataclasses.dataclass(frozen=True)
class StateContract:
    """Declared range of ONE workload state column at step boundaries.

    A workload that declares ``Workload.state_contracts`` (one entry
    per state column, total over ``state_width``) narrows the
    ``node_state`` ColumnContract from the full-int32 default to the
    hull of its declared columns, and TAGS it — so the interval prover
    (lint.absint) tracks overflow through the workload's own deadline
    and epoch arithmetic instead of waving it through as
    "workload-defined words". The contract is assume-guarantee like
    every loop-carried contract: the prover ASSUMES it at each step
    entry and the model author owes its truth (clamp what you store).
    """

    col: int
    lo: int
    hi: int
    family: str | None = None  # "time" | "counter" | None (untracked)
    note: str = ""


def _dtype_full(dt) -> tuple:
    info = np.iinfo(dt)
    return int(info.min), int(info.max)


def _node_state_contract(wl: "Workload", i32: tuple) -> "ColumnContract":
    """The node_state ColumnContract for one workload: full int32 and
    untracked by default; the hull of the declared per-column ranges
    (tagged "time" if any declared column is) when the workload ships
    ``state_contracts``. The hull is the honest join — node_state is
    one (N, U) array to the prover, so the contract of the array is
    the union of the contracts of its columns."""
    if not wl.state_contracts:
        return ColumnContract(
            "node_state", *i32, None, "workload-defined words"
        )
    lo = min(sc.lo for sc in wl.state_contracts)
    hi = max(sc.hi for sc in wl.state_contracts)
    families = {sc.family for sc in wl.state_contracts if sc.family}
    family = "time" if "time" in families else (
        "counter" if families else None
    )
    return ColumnContract(
        "node_state", lo, hi, family,
        f"hull of {len(wl.state_contracts)} declared state columns",
    )


def column_contracts(
    wl: "Workload",
    cfg: "EngineConfig",
    *,
    time32: bool = False,
    horizon_ns: int | None = None,
) -> dict:
    """The per-column range contracts for one (workload, config) build.

    ``horizon_ns`` is the certification horizon (default: the config's
    ``time_limit_ns`` when set, else :data:`ABSINT_HORIZON_NS`). The
    returned dict maps field name -> :class:`ColumnContract` and is
    TOTAL over SimState: every field must be declared here (an
    untracked column declares its full dtype range with no family) —
    a new column missing from the list raises, because silently
    defaulting would weaken the proof without anyone deciding so.
    """
    if horizon_ns is None:
        horizon_ns = cfg.time_limit_ns or ABSINT_HORIZON_NS
    h = int(horizon_ns)
    cnt = ABSINT_COUNTER_MAX
    i32 = _dtype_full(np.int32)
    i64 = _dtype_full(np.int64)
    u32 = _dtype_full(np.uint32)
    u64 = _dtype_full(np.uint64)
    # the largest offset one insertion can put on the pool clock: a
    # handler timer (declared bound, else the horizon itself), a
    # slow-scaled latency draw, or a clog-backoff reschedule (+ <1 us
    # jitter) — the same terms time32_eligible bounds
    delay_hi = wl.delay_bound_ns if wl.delay_bound_ns is not None else h
    offset_hi = max(
        int(delay_hi),
        int(cfg.lat_max_ns) * SLOW_MULT_MAX,
        int(cfg.clog_backoff_max_ns) + 1_000,
    )
    hcap = wl.history.capacity if wl.history is not None else 0

    def c(field, lo, hi, family=None, note=""):
        return ColumnContract(field, int(lo), int(hi), family, note)

    if time32:
        # offsets from `now`; valid slots are bounded by the insertion
        # clamp (lim32), but stale slots rebase forever and may wrap —
        # the honest contract is the full dtype range (family still
        # "time": any NEW arithmetic on these columns is a wrap surface
        # unless its site is individually annotated)
        ev_time = c("ev_time", *i32, "time", "int32 offsets; stale may wrap")
        tile_min = c("tile_min", *i32, "time", "empty tiles = +inf sentinel")
    else:
        ev_time = c("ev_time", 0, h + offset_hi, "time", "absolute ns")
        tile_min = c(
            "tile_min", 0, int(_INF_NS), "time", "empty tiles = +inf sentinel"
        )
    out = [
        c("seed", *u64),
        c("now", 0, h, "time"),
        c("step", 0, ABSINT_STEP_MAX, "counter", "RNG step coordinate"),
        c("halted", 0, 1),
        c("halt_time", 0, h, "time"),
        c("trace", *u64, None, "rolling hash, modular by design"),
        c("overflow", 0, cnt, "counter"),
        c("msg_count", 0, cnt, "counter"),
        ev_time,
        c("ev_valid", 0, 1),
        c("ev_meta", *u32, None, "packed kind/node/src/retry bytes"),
        c("ev_epoch", -1, cnt, "counter", "-1 = ANY-epoch sentinel"),
        c("ev_args", *i32),
        c("ev_pay", *i32),
        c("alive", 0, 1),
        c("paused", 0, 1),
        c("epoch", 0, cnt, "counter"),
        _node_state_contract(wl, i32),
        c("clog", 0, 1),
        c("slow", 0, SLOW_MULT_MAX, None, "link latency multiplier"),
        c("dup", 0, 1),
        c("skew", *i32, None, "per-node clock skew ns"),
        c("disk", *i32),
        c("wmask", 0, 1),
        c("sync_loss", 0, 1),
        c("sync_eio", 0, 1),
        c("torn", 0, 1),
        c("hist_count", 0, max(hcap, 0), "counter"),
        c("hist_drop", 0, cnt, "counter"),
        c("hist_word", *i32),
        c("hist_t", 0, h, "time"),
        c("cov", *u32, None, "bitmap words, modular folds"),
        c("cov_last", -1, 255),
        c("cov_hits", 0, 255),
        c("met", 0, cnt, "counter"),
        c("tl_count", 0, cnt, "counter"),
        c("tl_drop", 0, cnt, "counter"),
        c("tl_t", 0, h, "time"),
        c("tl_meta", *u32),
        c("tl_args", *i32),
        c("tl_pay", *i32),
        c("ev_emit", 0, h, "time"),
        c("tl_emit", 0, h, "time"),
        # causal columns (causal=True): the Lamport clocks grow by at
        # most one per dispatch, so the step-count budget bounds them;
        # parent seqs are clamped copies of `step` with the sentinel
        # classes below zero (PARENT_ARMY = -3 is the floor)
        c("lam", 0, ABSINT_STEP_MAX, "counter", "per-node Lamport clock"),
        c("ev_parent", PARENT_ARMY, ABSINT_STEP_MAX, "counter",
          "emitting dispatch seq; -1/-2/-3 sentinel classes"),
        c("ev_lam", 0, ABSINT_STEP_MAX, "counter",
          "emitting dispatch's Lamport clock"),
        c("tl_seq", 0, ABSINT_STEP_MAX, "counter", "dispatch seq per row"),
        c("tl_parent", PARENT_ARMY, ABSINT_STEP_MAX, "counter",
          "parent seq per row; sentinel classes below zero"),
        c("tl_lam", 0, ABSINT_STEP_MAX, "counter"),
        c("lat_inv", -1, h, "time", "-1 = never invoked"),
        c("lat_resp", -1, h, "time", "-1 = incomplete"),
        c("lat_hist", 0, cnt, "counter"),
        c("lat_count", 0, cnt, "counter"),
        c("lat_drop", 0, cnt, "counter"),
        # client-retry columns (RetrySpec): attempt ids are token-packed
        # 4-bit values; the deadline clock is ALWAYS absolute int64
        # (observability-friendly even under time32 — it never feeds the
        # pool), bounded by the horizon plus one timer arm's offset and
        # the int64-capped backoff+jitter
        c("rt_done", 0, 1),
        c("rt_attempt", 0, RETRY_ATTEMPT_MAX, "counter",
          "delivered attempt id per op"),
        c("rt_deadline", 0,
          h + offset_hi + 2 * _RETRY_BACKOFF_CAP, "time",
          "absolute ns; armed response deadline per op"),
        tile_min,
        c("tile_cnt", 0, max(pool_tile(cfg.pool_size), 64), "counter"),
    ]
    contracts = {cc.field: cc for cc in out}
    missing = [
        f.name for f in dataclasses.fields(SimState)
        if f.name not in contracts
    ]
    if missing:
        # a new SimState column without a declared contract would
        # silently weaken the proof (full-range, untracked)
        raise AssertionError(
            f"column_contracts is missing SimState fields: {missing}"
        )
    return contracts


# Largest slow-link latency multiplier the packed args word can carry:
# pack_slow_arg stores the multiplier in bits 8..30 of an int32. The
# chaos plan validator (chaos/plan.py GrayFailure) enforces it at spec
# build time and the absint range contracts (column_contracts) assume
# it — one declaration, so the validator and the prover cannot drift.
SLOW_MULT_MAX = (1 << 23) - 1


def pack_slow_arg(b, mult):
    """Pack a slow-link peer + multiplier into one int32 args word:
    low byte = peer node + 1 (0 = node-wide), bits 8.. = multiplier.
    This function OWNS the layout (the chaos plan compiler and the
    in-step decode both route through it / unpack_slow_arg). Works on
    Python ints, numpy arrays (plan compilation) and traced values
    (EmitBuilder helpers)."""
    if isinstance(b, (int, np.integer)) and isinstance(mult, (int, np.integer)):
        return ((int(b) + 1) & 0xFF) | (int(mult) << 8)
    if isinstance(b, np.ndarray) or isinstance(mult, np.ndarray):
        return ((np.asarray(b, np.int64) + 1) & 0xFF) | (
            np.asarray(mult, np.int64) << 8
        )
    return (
        (jnp.asarray(b, jnp.int32) + 1) & jnp.int32(0xFF)
    ) | (jnp.asarray(mult, jnp.int32) << jnp.int32(8))


def unpack_slow_arg(word: int) -> tuple:
    """Inverse of :func:`pack_slow_arg` for host ints: (peer, mult) —
    peer -1 means node-wide."""
    return (int(word) & 0xFF) - 1, int(word) >> 8


def user_kind(i: int) -> int:
    """Kind id of user handler ``i`` (handler 0 = on_init)."""
    return FIRST_USER_KIND + i


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static simulation parameters (the analog of sim Config, config.rs:15).

    All values participate in the config hash printed on failure so a
    repro needs (seed, config) exactly like the reference
    (runtime/mod.rs:193-200).
    """

    pool_size: int = 256  # E: max in-flight events per seed
    lat_min_ns: int = 1_000_000  # network latency range, default 1-10 ms
    lat_max_ns: int = 10_000_000  # (reference network.rs:84-90)
    loss_p: float = 0.0  # packet loss rate (network.rs:75-95)
    proc_min_ns: int = 50  # per-event processing cost
    proc_max_ns: int = 100  # (task.rs:213)
    clog_backoff_min_ns: int = 1_000_000  # clogged-delivery recheck backoff
    clog_backoff_max_ns: int = 10_000_000_000  # 1 ms -> 10 s (net/mod.rs:341-355)
    time_limit_ns: int = 0  # 0 = unlimited (set_time_limit, runtime/mod.rs:143)

    def __post_init__(self):
        # draws are 32-bit; a span that doesn't fit uint32 would silently
        # wrap in the modulo reduction and skew the distribution
        for lo, hi, what in (
            (self.lat_min_ns, self.lat_max_ns, "latency"),
            (self.proc_min_ns, self.proc_max_ns, "processing-cost"),
        ):
            if hi < lo:
                raise ValueError(f"{what} range [{lo}, {hi}) is empty")
            if hi - lo > DRAW_SPAN_MAX:
                raise ValueError(
                    f"{what} span {hi - lo} ns does not fit uint32 "
                    f"(max {DRAW_SPAN_MAX} ns, ~4.29 s)"
                )

    @property
    def loss_u32(self) -> int:
        return chance_threshold(self.loss_p)

    def hash(self) -> str:
        """Stable hex hash of the config (config.rs:27-31 analog)."""
        import hashlib

        s = repr(dataclasses.astuple(self)).encode()
        return hashlib.sha256(s).hexdigest()[:16]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Emits:
    """Fixed-capacity batch of events a handler emits (K slots).

    ``send`` slots are translated by the engine into future deliveries
    (latency + loss + clog, the NetSim path in SURVEY §3.3); timer slots
    become plain future events (add_timer, time/mod.rs:138-149).
    """

    valid: jnp.ndarray  # (K,)  bool
    send: jnp.ndarray  # (K,)  bool: network message vs local timer
    kind: jnp.ndarray  # (K,)  int32
    dst: jnp.ndarray  # (K,)  int32
    delay: jnp.ndarray  # (K,)  int64 ns (timer) / ignored for sends
    args: jnp.ndarray  # (K,4) int32
    pay: jnp.ndarray  # (K,W) int32 payload words (W = Workload.payload_words)
    # operation-history records (R = HistorySpec.max_records, 0 = off):
    # each row is (op, key, arg, ok); the engine stamps the client node
    # and the dispatch time when appending to the history columns
    rec_valid: jnp.ndarray = None  # (R,) bool
    rec: jnp.ndarray = None  # (R,4) int32
    # sync flag (Workload.durable_sync): True = the handler called
    # fsync before returning — the engine commits the node's durable
    # columns to its disk image at THIS dispatch (unless a SYNC_LOSS
    # window makes the disk lie). A scalar, not per-slot: one dispatch
    # is one fsync decision. Ignored when the discipline is off.
    sync: jnp.ndarray = None  # () bool
    # latency markers (L = Workload.lat_markers, 0 = off): each row
    # marks one client-army op — lat[j] = (op_id, phase) with phase 0 =
    # invoke (EmitBuilder.lat_start) and 1 = response (lat_end). The
    # engine stamps the dispatch clock into the latency columns; with
    # the latency tap off the markers are dead values XLA removes.
    lat_valid: jnp.ndarray = None  # (L,) bool
    lat: jnp.ndarray = None  # (L, 2) int32

    @staticmethod
    def none(k: int, w: int = 0, a: int = 4, r: int = 0, l: int = 0) -> "Emits":
        return Emits(
            valid=jnp.zeros((k,), jnp.bool_),
            send=jnp.zeros((k,), jnp.bool_),
            kind=jnp.zeros((k,), jnp.int32),
            dst=jnp.zeros((k,), jnp.int32),
            delay=jnp.zeros((k,), jnp.int64),
            args=jnp.zeros((k, a), jnp.int32),
            pay=jnp.zeros((k, w), jnp.int32),
            rec_valid=jnp.zeros((r,), jnp.bool_),
            rec=jnp.zeros((r, 4), jnp.int32),
            sync=jnp.asarray(False),
            lat_valid=jnp.zeros((l,), jnp.bool_),
            lat=jnp.zeros((l, 2), jnp.int32),
        )


class EmitBuilder:
    """Trace-time helper for constructing :class:`Emits` inside handlers.

    Slot assignment happens at Python trace time (static); the ``when``
    flag is the traced per-seed condition making an emit conditional.
    """

    def __init__(self, k: int, w: int = 0, a: int = 4, r: int = 0, l: int = 0):
        self._k = k
        self._w = w
        self._a = a
        self._r = r
        self._l = l
        self._recs: list[tuple] = []
        self._rows: list[tuple] = []
        self._syncs: list = []
        self._lats: list[tuple] = []

    def _push(self, send, kind, dst, delay, args, when, pay=()):
        if len(self._rows) >= self._k:
            raise ValueError(
                f"handler emits more than max_emits={self._k} events; "
                f"raise Workload.max_emits"
            )
        if len(args) > self._a:
            raise ValueError(
                f"{len(args)} event args exceed Workload.args_words={self._a}"
            )
        a = list(args) + [0] * (self._a - len(args))
        p = list(pay)
        if len(p) > self._w:
            raise ValueError(
                f"payload of {len(p)} words exceeds "
                f"Workload.payload_words={self._w}"
            )
        self._rows.append((when, send, kind, dst, delay, a, p))

    def send(self, dst, kind, args=(), when=True, pay=()):
        """Send a network message: delivery after latency unless lost/clogged.
        ``pay`` is an optional payload of up to ``Workload.payload_words``
        int32 words, carried with the event (the batched analog of the
        reference's ``Payload = Box<dyn Any>``, sim/net/endpoint.rs:13-23)."""
        self._push(True, kind, dst, 0, args, when, pay)

    def after(self, delay_ns, kind, dst, args=(), when=True, pay=()):
        """Schedule a local event ``delay_ns`` in the future (a timer)."""
        self._push(False, kind, dst, delay_ns, args, when, pay)

    def kill(self, node, when=True):
        self.after(0, KIND_KILL, 0, (node,), when)

    def restart(self, node, when=True):
        self.after(0, KIND_RESTART, 0, (node,), when)

    def restart_after(self, delay_ns, node, when=True):
        self.after(delay_ns, KIND_RESTART, 0, (node,), when)

    def pause(self, node, when=True):
        self.after(0, KIND_PAUSE, 0, (node,), when)

    def resume(self, node, when=True):
        self.after(0, KIND_RESUME, 0, (node,), when)

    def clog_link(self, a, b, when=True):
        self.after(0, KIND_CLOG, 0, (a, b), when)

    def unclog_link(self, a, b, when=True):
        self.after(0, KIND_UNCLOG, 0, (a, b), when)

    def clog_link_one_way(self, src, dst, when=True):
        """Asymmetric partition edge: block src -> dst only."""
        self.after(0, KIND_CLOG_1W, 0, (src, dst), when)

    def unclog_link_one_way(self, src, dst, when=True):
        self.after(0, KIND_UNCLOG_1W, 0, (src, dst), when)

    def slow_link(self, a, b, mult, when=True):
        """Gray failure: multiply a<->b latency by ``mult`` (b=-1 slows
        every link in or out of a)."""
        self.after(0, KIND_SLOW_LINK, 0, (a, pack_slow_arg(b, mult)), when)

    def unslow_link(self, a, b, when=True):
        self.after(0, KIND_UNSLOW, 0, (a, pack_slow_arg(b, 1)), when)

    def dup_on(self, when=True):
        """Start duplicating messages (needs make_step(dup_rows=True))."""
        self.after(0, KIND_DUP_ON, 0, (), when)

    def dup_off(self, when=True):
        self.after(0, KIND_DUP_OFF, 0, (), when)

    def set_skew(self, node, skew_ns, when=True):
        """Set the node's clock skew: its handlers observe now+skew_ns."""
        self.after(0, KIND_SKEW, 0, (node, skew_ns), when)

    def sync(self, when=True):
        """fsync the handling node's durable columns (Workload.durable_sync).

        Under the two-phase sync discipline a durable write lands in a
        volatile buffer and survives KIND_KILL only once a sync has
        committed it; calling this inside the dispatch that wrote models
        a blocking fsync before the handler's messages go out. A no-op
        when the workload does not opt into the discipline, and a LIE
        inside a chaos ``KIND_SYNC_LOSS`` window (the commit silently
        does not happen — what the hunt for missing-sync bugs injects).
        """
        self._syncs.append(when)

    def sync_loss(self, node, when=True):
        """Chaos: the node's disk starts lying — syncs stop committing
        (node=-1: every node). See ``chaos.DiskFault`` for the plan form."""
        self.after(0, KIND_SYNC_LOSS, 0, (node,), when)

    def sync_eio(self, node, when=True):
        """Chaos: the node's disk starts FAILING observably — syncs stop
        committing and the node's handlers see ``ctx.sync_err`` until a
        ``sync_ok`` (the batched ``FsSim.set_fail_writes``)."""
        self.after(0, KIND_SYNC_LOSS, 0, (node, 1), when)

    def sync_ok(self, node, when=True):
        """Chaos: end the node's sync-lie AND fsync-EIO windows."""
        self.after(0, KIND_SYNC_OK, 0, (node,), when)

    def torn_on(self, node, when=True):
        """Chaos: arm torn-write mode — the node's next KILL persists
        only a drawn prefix of its last uncommitted durable write."""
        self.after(0, KIND_TORN_ON, 0, (node,), when)

    def torn_off(self, node, when=True):
        self.after(0, KIND_TORN_OFF, 0, (node,), when)

    def halt(self, when=True):
        self.after(0, KIND_HALT, 0, (), when)

    def record(self, op, key=0, arg=0, ok=1, when=True):
        """Append one operation-history record (madsim_tpu.check).

        ``op``/``key``/``arg`` are workload-defined int32 words; ``ok``
        follows the check.history convention (-1 = invoke of a pending
        operation, 1 = successful response, 0 = failed response). The
        engine stamps the record with the handling node (the client
        column) and the dispatch sim-time. Requires ``Workload.history``.
        """
        if self._r == 0:
            raise ValueError(
                "record() needs history slots; set Workload.history to a "
                "HistorySpec (and size its max_records)"
            )
        if len(self._recs) >= self._r:
            raise ValueError(
                f"handler records more than max_records={self._r} history "
                f"entries; raise HistorySpec.max_records"
            )
        self._recs.append((when, op, key, arg, ok))

    def _lat_mark(self, op_id, phase: int, when) -> None:
        if self._l == 0:
            raise ValueError(
                "lat_start/lat_end need latency marker slots; set "
                "Workload.lat_markers (the per-invocation marker count)"
            )
        if len(self._lats) >= self._l:
            raise ValueError(
                f"handler marks more than lat_markers={self._l} latency "
                f"ops; raise Workload.lat_markers"
            )
        self._lats.append((when, op_id, phase))

    def lat_start(self, op_id, when=True):
        """Mark the INVOKE of client-army op ``op_id`` (madsim_tpu.obs
        latency): the engine stamps this dispatch's clock into
        ``lat_inv[op_id]``. The first start wins; repeats are ignored —
        an open-loop army invokes each op id exactly once, so repeats
        only arise from hand-built workloads. Derived state only: with
        the latency tap off (``latency=None``) the marker costs nothing
        and traces are bit-identical."""
        self._lat_mark(op_id, 0, when)

    def lat_end(self, op_id, when=True):
        """Mark the RESPONSE of client-army op ``op_id``: the engine
        stamps ``lat_resp[op_id]`` and folds the op's latency into the
        per-seed log-linear sketch (``lat_hist``). First response wins
        (a duplicated delivery does not count twice); an end without a
        prior start is ignored (the invoke never happened)."""
        self._lat_mark(op_id, 1, when)

    def _build_recs(self):
        r = self._r
        if not self._recs:
            return (
                jnp.zeros((r,), jnp.bool_),
                jnp.zeros((r, 4), jnp.int32),
            )
        pad = r - len(self._recs)
        valid = [jnp.asarray(wh, jnp.bool_) for (wh, *_x) in self._recs]
        words: list = []
        for (_wh, *rest) in self._recs:
            words.extend(rest)
        words += [0] * (pad * 4)
        return (
            jnp.stack(valid + [False] * pad),
            jnp.stack([jnp.asarray(x, jnp.int32) for x in words]).reshape(r, 4),
        )

    def _build_sync(self):
        sync = jnp.asarray(False)
        for wh in self._syncs:
            sync = sync | jnp.asarray(wh, jnp.bool_)
        return sync

    def _build_lats(self):
        l = self._l
        if not self._lats:
            return (
                jnp.zeros((l,), jnp.bool_),
                jnp.zeros((l, 2), jnp.int32),
            )
        pad = l - len(self._lats)
        valid = [jnp.asarray(wh, jnp.bool_) for (wh, *_x) in self._lats]
        words: list = []
        for (_wh, oid, ph) in self._lats:
            words.extend((oid, ph))
        words += [0] * (pad * 2)
        return (
            jnp.stack(valid + [False] * pad),
            jnp.stack([jnp.asarray(x, jnp.int32) for x in words]).reshape(l, 2),
        )

    def build(self) -> Emits:
        k, w = self._k, self._w
        rec_valid, rec = self._build_recs()
        sync = self._build_sync()
        lat_valid, lat = self._build_lats()
        if not self._rows:
            em = Emits.none(k, w, self._a)
            return dataclasses.replace(
                em, rec_valid=rec_valid, rec=rec, sync=sync,
                lat_valid=lat_valid, lat=lat,
            )
        pad = k - len(self._rows)
        valid = [jnp.asarray(wh, jnp.bool_) for (wh, *_r) in self._rows]
        send = [jnp.asarray(s, jnp.bool_) for (_w, s, *_r) in self._rows]
        kind = [jnp.asarray(kd, jnp.int32) for (_w, _s, kd, *_r) in self._rows]
        dst = [jnp.asarray(d, jnp.int32) for (*_h, d, _dl, _a, _p) in self._rows]
        delay = [jnp.asarray(dl, jnp.int64) for (*_h, dl, _a, _p) in self._rows]
        args = [
            jnp.stack([jnp.asarray(x, jnp.int32) for x in a])
            for (*_h, a, _p) in self._rows
        ]

        def pay_row(p: list) -> jnp.ndarray:
            if not p:
                return jnp.zeros((w,), jnp.int32)
            row = jnp.stack([jnp.asarray(x, jnp.int32) for x in p])
            return jnp.concatenate([row, jnp.zeros((w - len(p),), jnp.int32)])

        pay = [pay_row(p) for (*_h, p) in self._rows]
        z32 = jnp.int32(0)
        return Emits(
            valid=jnp.stack(valid + [jnp.asarray(False)] * pad),
            send=jnp.stack(send + [jnp.asarray(False)] * pad),
            kind=jnp.stack(kind + [z32] * pad),
            dst=jnp.stack(dst + [z32] * pad),
            delay=jnp.stack(delay + [jnp.int64(0)] * pad),
            args=jnp.stack(args + [jnp.zeros((self._a,), jnp.int32)] * pad),
            pay=jnp.stack(pay + [jnp.zeros((w,), jnp.int32)] * pad),
            rec_valid=rec_valid,
            rec=rec,
            sync=sync,
            lat_valid=lat_valid,
            lat=lat,
        )


@dataclasses.dataclass(frozen=True)
class HistorySpec:
    """Per-seed operation-history recording (madsim_tpu.check).

    Histories are fixed-size on-device columns, the same discipline as
    the trace hash: ``capacity`` slots per seed, each slot one record of
    (op, key, arg, client, ok) int32 words plus an int64 sim-time.
    Handlers append records through :meth:`EmitBuilder.record`; a full
    buffer never drops silently — overflow is counted in
    ``SimState.hist_drop`` and the checkers refuse such seeds.

    Sizing: one *operation* costs two records (an invoke and a
    response); instantaneous events (e.g. an election win) cost one.
    ``max_records`` is the per-handler-invocation slot count (the
    history analog of ``max_emits``).
    """

    capacity: int
    max_records: int = 2

    def __post_init__(self):
        if self.capacity < 1:
            raise ValueError(f"history capacity must be >= 1, got {self.capacity}")
        if self.max_records < 1:
            raise ValueError(
                f"max_records must be >= 1, got {self.max_records}"
            )


@dataclasses.dataclass
class HandlerCtx:
    """Everything a handler sees about the event it is processing."""

    now: jnp.ndarray  # int64 ns — virtual clock
    node: jnp.ndarray  # int32 — the node this event targets
    state: jnp.ndarray  # (U,) int32 — the node's state row
    args: jnp.ndarray  # (4,) int32 — event arguments
    src: jnp.ndarray  # int32 — sender node for messages, -1 for timers
    draw: Draw  # counter-based RNG for this event
    max_emits: int
    payload: jnp.ndarray = None  # (W,) int32 — the event's payload words
    payload_words: int = 0
    args_words: int = 4
    max_records: int = 0  # history record slots (Workload.history)
    # () bool — the handling node is inside an injected fsync-EIO
    # window (KIND_SYNC_LOSS mode 1, chaos.DiskFault n_eio): its syncs
    # are failing OBSERVABLY, the batched analog of FsSim's
    # set_fail_writes OSError(EIO). Always False when the workload has
    # no sync discipline or no EIO window is open, so handlers that
    # gate on it (e.g. withhold an ack they cannot persist) are
    # value-identical to ungated ones on every fault-free trajectory.
    sync_err: jnp.ndarray = None
    max_lat: int = 0  # latency marker slots (Workload.lat_markers)

    def emits(self) -> EmitBuilder:
        return EmitBuilder(
            self.max_emits, self.payload_words, self.args_words,
            self.max_records, self.max_lat,
        )


Handler = Callable[[HandlerCtx], tuple]


@dataclasses.dataclass(frozen=True)
class Workload:
    """A batched simulation program: per-node int32 state + event handlers.

    This is how "user code" enters the traced step function. Handlers are
    pure: ``handler(ctx) -> (new_state_row, Emits)``. Handler 0 is
    ``on_init`` — invoked for every node at t=0 and again when a node is
    restarted (the stored-init-task semantics of task.rs:279-291).
    """

    name: str
    n_nodes: int
    state_width: int
    handlers: tuple  # tuple[Handler, ...]
    max_emits: int = 8
    init_state: np.ndarray | None = None  # (N,U) int32; zeros if None
    # payload arena width: int32 words carried by every event (0 = off).
    # The batched analog of Payload = Box<dyn Any> (endpoint.rs:13-23):
    # payload lifetime equals event lifetime, so the arena IS the event
    # pool — no separate allocator, no leaks
    payload_words: int = 0
    # width of the per-event args vector (int32 words). Engine kinds use
    # args[0:2] (kill/clog targets), so 2 is the floor; shrink from the
    # default 4 when no handler reads args[2:] — the args arena is a
    # per-step placement cost like every pool field
    args_words: int = 4
    # largest timer delay (ns) any handler can pass to EmitBuilder.after.
    # Declaring it (together with config bounds, see time32_eligible)
    # unlocks the int32 event-time representation on accelerators; None
    # = unknown, keep int64 times. The engine still guards the claim at
    # runtime: a timer emit beyond the int32 horizon is counted into
    # `overflow`, which the bench refuses (bench.py pool_overflow path)
    delay_bound_ns: int | None = None
    # optional human names for the user handlers (len == len(handlers)),
    # used only by engine.replay timelines — no effect on execution
    handler_names: tuple | None = None
    # state-row columns that survive kill/restart — the batched analog
    # of FsSim's power-fail semantics (fs.rs:51: disk contents survive
    # a crash, RAM doesn't). RESTART restores the workload's initial
    # rows for every column NOT listed here; listed columns keep their
    # pre-kill values. None = everything volatile (pure-RAM nodes, the
    # default and the previous behavior). Applies to every node — pick
    # column meanings so "disk" columns line up across roles.
    durable_cols: tuple | None = None
    # operation-history recording (madsim_tpu.check): None = off (no
    # history columns, zero step cost). With a HistorySpec, handlers may
    # call EmitBuilder.record and the engine appends fixed-size history
    # rows per seed, checked host-side by the check package.
    history: HistorySpec | None = None
    # two-phase sync discipline over durable_cols (the storage-chaos
    # analog of a real write buffer, fs.rs:51 taken seriously): a
    # durable write lands in the node's volatile buffer and survives
    # KIND_KILL only once an EmitBuilder.sync commits it to the node's
    # disk image; chaos SYNC_LOSS windows make syncs lie and TORN_ON
    # makes a kill persist a drawn prefix of the last uncommitted
    # write. False (default) keeps the historical all-or-nothing
    # semantics: durable columns survive kill verbatim. NOTE: a
    # workload that syncs every durable write in the same dispatch is
    # trajectory-identical either way when no disk faults are injected
    # (the revert is a no-op), which keeps oracle compares exact.
    durable_sync: bool = False
    # latency marker slots per handler invocation (madsim_tpu.obs
    # latency): how many EmitBuilder.lat_start/lat_end calls one
    # dispatch may make. 0 (default) keeps the Emits pytree free of the
    # marker rows — every pre-latency workload is byte-identical.
    # Marker semantics are derived-state-only: the markers do nothing
    # at all unless the step is built with a LatencySpec.
    lat_markers: int = 0
    # protocol-specific coverage features (madsim_tpu.explore): a
    # traceable hook ``cov_features(node_state, now) -> iterable of
    # (feature, on)`` pairs — feature a uint32 word (the engine
    # namespaces it under its own tag before hashing into the bitmap),
    # ``on`` a () bool gate (ANDed with the user-dispatch gate). Runs
    # once per dispatched event over the POST-dispatch fleet state, so
    # a workload can contribute guidance signals the generic taps
    # cannot see — e.g. the fleet's commit-index spread (raftlog's
    # ``cov_spread=True``): a schedule that drags replicas apart is
    # new behavior even when no individual event is. Coverage is
    # derived state, so the hook CHANGES BITMAPS ONLY: traces,
    # trajectories and verdicts are bit-identical with it on or off
    # (and campaigns must not mix hooks, the cov_hitcount rule). None
    # (default) = no extra features, bitmaps unchanged.
    cov_features: Callable | None = None
    # user purposes to PREFETCH into the per-dispatch batched RNG block
    # (the BatchRNG shape, PAPERS.md): handler draws at these purposes
    # (the ints passed to ctx.draw.user/user_int) are served from lanes
    # of the ONE cipher pass the step already runs, instead of each
    # branch issuing its own scalar threefry — under vmap the
    # lax.switch evaluates EVERY branch per dispatch, so each distinct
    # in-branch cipher is a per-step cost whether or not its branch is
    # selected. Draw VALUES are bit-identical either way (same
    # (seed, step, purpose) counter per lane), so this is a pure
    # declaration of which lanes to batch; None/() changes nothing.
    draw_purposes: tuple | None = None
    # per-column range declarations (lint.absint): a tuple of
    # StateContract, TOTAL over state_width when present. Narrows the
    # node_state contract in column_contracts() from full int32 to the
    # hull of the declared columns and tags it, which makes the
    # interval prover check the workload's own deadline/epoch
    # arithmetic for overflow. Assume-guarantee: the model owes the
    # declared bounds (clamp before storing). None (default) keeps the
    # untracked full-range contract — existing proofs are unchanged.
    state_contracts: tuple | None = None

    def __post_init__(self):
        # emit slot s draws both its latency and loss words from the
        # PURPOSE_LATENCY(8)+s block (Draw.bits2); the slot range must
        # stay below the reserved PURPOSE_LOSS(64) space so it can never
        # bleed toward PURPOSE_USER and correlate "independent" draws.
        # -1: the engine appends one internal row (the restart re-init
        # event) after the user slots
        if not (2 <= self.args_words <= 4):
            raise ValueError(
                f"args_words={self.args_words} must be in [2, 4] "
                f"(engine kinds read args[0:2])"
            )
        limit = PURPOSE_LOSS - PURPOSE_LATENCY - 1
        if self.max_emits > limit:
            raise ValueError(
                f"max_emits={self.max_emits} exceeds the purpose-namespace "
                f"limit of {limit} (engine/rng.py purpose layout)"
            )
        if self.durable_cols is not None:
            bad = [c for c in self.durable_cols if not 0 <= c < self.state_width]
            if bad:
                raise ValueError(
                    f"durable_cols {bad} out of range for "
                    f"state_width={self.state_width}"
                )
        if self.durable_sync and not self.durable_cols:
            raise ValueError(
                "durable_sync needs durable_cols: the sync discipline "
                "governs exactly the columns that survive a kill"
            )
        if self.lat_markers < 0:
            raise ValueError(
                f"lat_markers must be >= 0, got {self.lat_markers}"
            )
        if self.draw_purposes is not None:
            # validated against the structured lane registry: before
            # PURPOSE_LANES, any purpose below 2^32 - PURPOSE_USER was
            # accepted and an out-of-range user lane silently aliased
            # the plan/explore/client high blocks. The error now names
            # the lane the purpose would collide with.
            validate_user_purposes(
                self.draw_purposes, what="Workload.draw_purposes"
            )
        if self.state_contracts is not None:
            cols = sorted(sc.col for sc in self.state_contracts)
            if cols != list(range(self.state_width)):
                raise ValueError(
                    f"state_contracts must declare every state column "
                    f"exactly once (expected cols 0..{self.state_width - 1}, "
                    f"got {cols}) — a partial declaration would silently "
                    f"weaken the node_state hull"
                )
            bad = [
                sc.col for sc in self.state_contracts
                if not (-(2 ** 31) <= sc.lo <= sc.hi <= 2 ** 31 - 1)
            ]
            if bad:
                raise ValueError(
                    f"state_contracts columns {bad} declare ranges that "
                    f"are empty or exceed int32"
                )
        if self.handler_names is not None and len(self.handler_names) != len(
            self.handlers
        ):
            raise ValueError(
                f"handler_names has {len(self.handler_names)} entries for "
                f"{len(self.handlers)} handlers — replay timelines would "
                f"label the wrong handlers"
            )

    def initial_state(self) -> np.ndarray:
        if self.init_state is not None:
            return np.asarray(self.init_state, np.int32)
        return np.zeros((self.n_nodes, self.state_width), np.int32)

    def volatile_mask(self) -> np.ndarray:
        """(U,) bool — True where RESTART resets to the initial row."""
        mask = np.ones((self.state_width,), bool)
        if self.durable_cols:
            mask[list(self.durable_cols)] = False
        return mask


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SimState:
    """One seed's full simulation state. ``vmap`` adds the (S,) axis."""

    seed: jnp.ndarray  # ()  uint64 instance seed
    now: jnp.ndarray  # ()  int64 virtual clock, ns
    step: jnp.ndarray  # ()  uint32 event sequence number (RNG coordinate)
    halted: jnp.ndarray  # () bool
    halt_time: jnp.ndarray  # () int64: clock when halted (else 0)
    trace: jnp.ndarray  # () uint64 rolling hash of dispatched events
    overflow: jnp.ndarray  # () int32 events dropped to pool overflow
    msg_count: jnp.ndarray  # () int64 — Stat{msg_count} (network.rs:106-111)
    # event pool, E slots
    ev_time: jnp.ndarray  # (E,) int64 absolute ns — or, under time32
    #                          (make_step), int32 offset from `now`
    ev_valid: jnp.ndarray  # (E,) bool
    ev_meta: jnp.ndarray  # (E,) uint32 packed kind/node/src/retry (see
    #                          the ev_meta byte-layout note above)
    ev_epoch: jnp.ndarray  # (E,) int32 target-node epoch at emit time
    ev_args: jnp.ndarray  # (E,4) int32
    ev_pay: jnp.ndarray  # (E,W) int32 payload words (W=0 when disabled)
    # nodes
    alive: jnp.ndarray  # (N,) bool
    paused: jnp.ndarray  # (N,) bool — events held while paused (pause/resume)
    epoch: jnp.ndarray  # (N,) int32
    node_state: jnp.ndarray  # (N,U) int32
    # network
    clog: jnp.ndarray  # (N,N) bool — link-clog matrix (net/mod.rs:157-216)
    # extended chaos state (madsim_tpu.chaos; defaults are the identity,
    # so workloads that never emit the extended kinds are bit-identical
    # to the pre-chaos engine)
    slow: jnp.ndarray  # (N,N) int32 — per-link latency multiplier (1 = normal)
    dup: jnp.ndarray  # () bool — message duplication on
    skew: jnp.ndarray  # (N,) int32 — per-node clock skew, ns (ctx.now offset)
    # two-phase sync discipline (Workload.durable_sync; D = n_nodes when
    # on, else 0 — zero-size arrays, zero step cost, bit-identical
    # values, the cov_words discipline). ``disk`` is the last-SYNCED
    # image of each node's durable columns (volatile columns unused);
    # a KILL reverts the node's durable state to it. ``wmask`` marks
    # the columns of the node's most recent uncommitted durable write —
    # the write a TORN_ON kill tears (a drawn prefix persists).
    disk: jnp.ndarray  # (D,U) int32 synced durable image
    wmask: jnp.ndarray  # (D,U) bool last uncommitted durable write's columns
    sync_loss: jnp.ndarray  # (D,) bool — sync-lie window active (chaos)
    sync_eio: jnp.ndarray  # (D,) bool — observable fsync-EIO window
    #   active (chaos): syncs fail AND handlers see ctx.sync_err, the
    #   batched FsSim.set_fail_writes. A lie window hides the failure;
    #   an EIO window reports it — the two bug surfaces differ exactly
    #   in whether correct code can react.
    torn: jnp.ndarray  # (D,) bool — torn-write mode armed (chaos)
    # operation history (madsim_tpu.check), H = HistorySpec.capacity
    # (0 when Workload.history is None). Rows are append-ordered by
    # dispatch time; hist_drop counts records lost to a full buffer —
    # a nonzero value means the seed's history verdict is unreliable
    # (the checkers refuse it, the pool-overflow rule applied again).
    hist_count: jnp.ndarray  # () int32 records stored
    hist_drop: jnp.ndarray  # () int32 records dropped at capacity
    hist_word: jnp.ndarray  # (H,5) int32 [op, key, arg, client, ok]
    hist_t: jnp.ndarray  # (H,) int64 record sim-time ns (absolute)
    # coverage fingerprint (madsim_tpu.explore), CW = the cov_words
    # build parameter (0 = off, zero-size arrays, zero step cost). Each
    # dispatched event folds features — per-node event-kind transition
    # pairs, engine/chaos kind x time-phase markers, history-record
    # words — into a CW*32-bit AFL-style bitmap; a set bit is a
    # behavior the seed exhibited. Purely derived from dispatched
    # values, so it never feeds back into the trajectory or the trace.
    cov: jnp.ndarray  # (CW,) uint32 coverage bitmap words
    cov_last: jnp.ndarray  # (N,) int32 last user kind per node (CW>0), else (0,)
    # AFL-style hit-count counters (cov_hitcount=True, madsim_tpu.obs):
    # one saturating uint8 per bitmap bit position; a feature's bit is
    # then keyed by (feature, hit-count bucket class) so a behavior
    # happening an order of magnitude more often is NEW coverage. Empty
    # (0,) when the bucketing flag is off — set-only bitmaps unchanged.
    cov_hits: jnp.ndarray  # (CW*32,) uint8 when hit-counting, else (0,)
    # fleet metrics (madsim_tpu.obs, metrics=True): see the MET_* slot
    # layout above. (0,) when off — derived state only, like cov.
    met: jnp.ndarray  # (N_METRICS,) int32 counters + the halt code
    # per-seed timeline ring (madsim_tpu.obs, timeline_cap=T): the
    # dispatched-event stream, exactly the tuples the trace hash folds
    # (time, packed meta, args, payload). A full
    # ring drops LOUDLY via tl_drop, the hist_drop rule again: the
    # timeline is forensics, not evidence, so a drop never quarantines
    # the seed's verdict — but the search banner surfaces it.
    tl_count: jnp.ndarray  # () int32 events recorded
    tl_drop: jnp.ndarray  # () int32 events dropped at capacity
    tl_t: jnp.ndarray  # (T,) int64 dispatch clock ns (unskewed)
    tl_meta: jnp.ndarray  # (T,) uint32 packed kind/node/src (ev_meta layout)
    tl_args: jnp.ndarray  # (T, A) int32 event args
    tl_pay: jnp.ndarray  # (T, W) int32 payload words — so the decoded
    # stream refolds to the certified trace for payload workloads too
    # emit-time sidecar (timeline_cap > 0, else both zero-size):
    # ev_emit[e] is the clock at which pool row e was INSERTED (the
    # emitting dispatch's time; 0 for init/plan rows), carried into
    # tl_emit so Perfetto flow arrows anchor at the true send time.
    # Derived state only — read exclusively into the ring.
    ev_emit: jnp.ndarray  # (E,) int64 when the ring is on, else (0,)
    tl_emit: jnp.ndarray  # (T,) int64 emit clock per captured dispatch
    # causal provenance (causal=True, else all zero-size — the same
    # derived-state-only discipline). ``lam`` is the node's Lamport
    # clock, folded at dispatch: lam[dst] = max(lam[dst], lam-at-emit)
    # + 1. ``ev_parent`` carries each pool row's emitting dispatch's
    # event-sequence number (SimState.step at emit; sentinel classes
    # PARENT_NONE/PLAN/ARMY for rows with no emitting dispatch) and
    # ``ev_lam`` that dispatch's folded clock — both read at pop
    # exclusively into the ring / the next fold, exactly the ev_emit
    # pattern. The ring banks the dispatch's own seq (``tl_seq``), its
    # parent's seq (``tl_parent``) and the folded clock (``tl_lam``),
    # which is the exact event-derivation DAG obs.causal reconstructs.
    lam: jnp.ndarray  # (N,) uint32 per-node Lamport clock, else (0,)
    ev_parent: jnp.ndarray  # (E,) int32 emitting dispatch seq, else (0,)
    ev_lam: jnp.ndarray  # (E,) uint32 clock at emit, else (0,)
    tl_seq: jnp.ndarray  # (T,) int32 dispatch seq per captured row
    tl_parent: jnp.ndarray  # (T,) int32 parent seq per captured row
    tl_lam: jnp.ndarray  # (T,) uint32 folded clock per captured row
    # tail-latency columns (madsim_tpu.obs latency; C = LatencySpec.ops,
    # 0 when the tap is off — zero-size, zero cost, bit-identical, the
    # cov_words discipline once more). lat_inv/lat_resp are the per-op
    # invoke/response clocks (-1 = not yet); lat_hist is the per-seed
    # log-linear sketch, (P, B) over (LatencySpec.phases, N_LAT_BUCKETS)
    # — exactly mergeable across seeds/shards by summation.
    lat_inv: jnp.ndarray  # (C,) int64 invoke clock per op id, -1 = never
    lat_resp: jnp.ndarray  # (C,) int64 response clock, -1 = incomplete
    lat_hist: jnp.ndarray  # (P, B) int32 latency sketch
    lat_count: jnp.ndarray  # () int32 completed ops folded into the sketch
    lat_drop: jnp.ndarray  # () int32 markers with out-of-range op ids (loud)
    # client-retry columns (make_step's ``retry``; CR = RetrySpec.n_ops,
    # 0 with no policy — zero-size, zero cost, bit-identical). CORE
    # state, not derived: rt_done gates re-delivery, so retried
    # trajectories depend on it (see RETRY_STATE_FIELDS). rt_deadline is
    # ALWAYS absolute int64 — it never feeds the pool clock, so the
    # time32 representation does not apply (forensics read it directly).
    rt_done: jnp.ndarray  # (CR,) bool: response recorded for op
    rt_attempt: jnp.ndarray  # (CR,) int32: last DELIVERED attempt id
    rt_deadline: jnp.ndarray  # (CR,) int64: armed deadline, absolute ns
    # readiness-partitioned pool index (make_step's ``pool_index``; NT =
    # pool_size/tile when on, else 0 — zero-size, zero cost, the usual
    # off discipline). Derived by construction from (ev_time, ev_valid)
    # — build_pool_index is the definition, checkpoint restore rebuilds
    # them, the format is unchanged — but trajectory-coupled: the pop
    # reads them, so they sit in core_fields for the taint proof (see
    # POOL_INDEX_STATE_FIELDS). tile_min of an empty tile is stale
    # (masked by tile_cnt > 0 at every use, the invalid-slot rule).
    tile_min: jnp.ndarray  # (NT,) pool-time dtype: earliest valid time/tile
    tile_cnt: jnp.ndarray  # (NT,) int32: valid slots per tile

    @property
    def sim_seconds(self):
        """Virtual seconds this instance has advanced (bench metric)."""
        return self.now.astype(jnp.float64) / 1e9


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def time32_eligible(wl: Workload, cfg: EngineConfig) -> bool:
    """Whether this (workload, config) pair can use int32 event times.

    Pool times under ``time32`` are offsets from the current clock; they
    only shrink as the clock advances, so the static bound is just the
    largest offset any insertion can create: a handler timer
    (``delay_bound_ns``), a network latency draw, or a clog-backoff
    reschedule (cap + the <1 µs jitter draw). The headroom subtracts
    ``proc_max_ns + 1`` so (a) a maximal valid offset stays strictly
    below the ``_INF_32`` invalid-slot sentinel in the pop, and (b) the
    per-step clock advance (offset + poll cost) can never overflow the
    int32 rebase.
    """
    lim = _T32_LIMIT - cfg.proc_max_ns - 1
    return (
        wl.delay_bound_ns is not None
        and wl.delay_bound_ns <= lim
        and cfg.lat_max_ns <= lim
        and cfg.clog_backoff_max_ns + 1_000 <= lim
    )


def _resolve_time32(wl: Workload, cfg: EngineConfig, time32: bool | None) -> bool:
    if time32 is None:
        # int64 is native on CPU; accelerators (v5e has no 64-bit lanes)
        # get the narrow representation whenever the bounds allow it
        return time32_eligible(wl, cfg) and jax.default_backend() != "cpu"
    if time32 and not time32_eligible(wl, cfg):
        raise ValueError(
            f"time32 requested but {wl.name} / config are not eligible: "
            f"need delay_bound_ns ({wl.delay_bound_ns}), lat_max_ns "
            f"({cfg.lat_max_ns}) and clog_backoff_max_ns+1000 "
            f"({cfg.clog_backoff_max_ns + 1000}) all <= "
            f"{_T32_LIMIT - cfg.proc_max_ns - 1}"
        )
    return bool(time32)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PlanRows:
    """Per-seed fault-plan events, compiled to pre-seeded event-pool rows.

    Produced host-side by ``madsim_tpu.chaos`` (FaultPlan.compile_batch)
    and consumed by the ``init`` built with ``make_init(plan_slots=P)``:
    slot ``j`` of seed ``s`` becomes pool row ``n_nodes + j`` — an engine
    (or extended-chaos) event at the given absolute time. Invalid rows
    are skipped, so the per-seed event *count* may vary under one static
    ``P``. Times must respect the time32 horizon when that representation
    is active (the chaos compiler validates this).
    """

    time: jnp.ndarray  # (S, P) int64 absolute ns
    kind: jnp.ndarray  # (S, P) int32 engine/extended kind ids
    args: jnp.ndarray  # (S, P, 2) int32 — engine kinds read args[0:2]
    valid: jnp.ndarray  # (S, P) bool
    # target node per row (chaos ClientArmy: USER-kind rows address a
    # client node). None (the pre-army form) = every row targets node 0,
    # which engine kinds ignore — old plans are bit-identical.
    node: jnp.ndarray = None  # (S, P) int32, or None


def _check_cov_words(cov_words: int) -> None:
    if cov_words and (cov_words < 1 or cov_words & (cov_words - 1)):
        raise ValueError(
            f"cov_words={cov_words} must be 0 (off) or a power of two "
            f"(the feature hash reduces by bitmask)"
        )


def _check_obs(
    cov_words: int,
    cov_hitcount: bool,
    timeline_cap: int,
    latency: "LatencySpec | None" = None,
) -> None:
    """Observability build-parameter validation — shared by make_init and
    make_step so no mismatched pair of builders can be constructed."""
    if cov_hitcount and not cov_words:
        raise ValueError(
            "cov_hitcount=True needs coverage enabled (cov_words > 0): "
            "hit-count buckets refine the coverage bitmap"
        )
    if timeline_cap < 0:
        raise ValueError(f"timeline_cap={timeline_cap} must be >= 0")
    if latency is not None and not isinstance(latency, LatencySpec):
        raise TypeError(
            f"latency must be a LatencySpec or None, got "
            f"{type(latency).__name__}"
        )


def make_init(
    wl: Workload,
    cfg: EngineConfig,
    time32: bool | None = None,
    plan_slots: int = 0,
    cov_words: int = 0,
    metrics: bool = False,
    timeline_cap: int = 0,
    cov_hitcount: bool = False,
    latency: LatencySpec | None = None,
    pool_index: bool | None = None,
    causal: bool = False,
    retry: "RetrySpec | None" = None,
):
    """Build ``init(seeds) -> SimState`` (batched over the seeds array).

    Seeds every node with an on_init event at t=0, mirroring the builder
    running each node's init task at simulation start. ``time32`` must
    match the value resolved by :func:`make_step` (both default to the
    same automatic rule, so callers normally pass neither).

    ``plan_slots=P`` reserves P pool rows per seed for a compiled fault
    plan (madsim_tpu.chaos): the returned ``init(seeds, plan)`` then
    requires a :class:`PlanRows` whose arrays carry the (S, P) events.

    ``cov_words=CW`` sizes the per-seed coverage bitmap (CW*32 bits,
    madsim_tpu.explore); must match the step builder's value. 0 (the
    default) compiles recording away entirely.

    ``metrics``/``timeline_cap``/``cov_hitcount``/``latency`` size the
    observability columns (madsim_tpu.obs; see the make_step docstring);
    each must match the step builder's value, and each defaults to off
    (zero-size arrays, zero cost, bit-identical values).

    ``pool_index`` sizes the readiness-index tile summaries (see the
    make_step docstring) and must match the step builder's value; both
    default to the same automatic rule (on for CPU scatter pools larger
    than the crossover threshold), so callers normally pass neither —
    but a caller forcing a non-default ``layout`` on an accelerator
    should pass it explicitly to both, exactly like ``time32``.

    ``causal=True`` sizes the causal-provenance columns (``lam``,
    ``ev_parent``/``ev_lam`` and — with the ring on — the
    ``tl_seq``/``tl_parent``/``tl_lam`` ring columns); must match the
    step builder's value. Plan rows are classed by sentinel at init:
    engine/chaos rows get :data:`PARENT_PLAN`, client-army USER rows
    :data:`PARENT_ARMY`, on_init rows :data:`PARENT_NONE`.
    """
    n, u, e, k = wl.n_nodes, wl.state_width, cfg.pool_size, wl.max_emits
    p = plan_slots
    if e < n + p:
        raise ValueError(
            f"pool_size={e} must hold one on_init event per node ({n}) "
            f"plus the {p} fault-plan rows"
        )
    _check_meta_ranges(wl)
    _check_cov_words(cov_words)
    _check_obs(cov_words, cov_hitcount, timeline_cap, latency)
    rt_c = _check_retry(wl, retry)
    del k
    w = wl.payload_words
    h = wl.history.capacity if wl.history is not None else 0
    lat_c = latency.ops if latency is not None else 0
    lat_p = latency.phases if latency is not None else 0
    tdtype = jnp.int32 if _resolve_time32(wl, cfg, time32) else jnp.int64
    base_state = jnp.asarray(wl.initial_state())
    # sync discipline: a fresh node's disk holds the initial image (the
    # durable columns of init_state are what a cold start reads back)
    d = n if wl.durable_sync else 0
    # readiness index: tile width + count (0 tiles = index off)
    p_tile = pool_tile(e) if _resolve_pool_index(cfg, pool_index) else 0
    n_tiles = e // p_tile if p_tile else 0

    def init_one(seed, pt=None, pk=None, pa=None, pv=None, pn=None) -> SimState:
        seed = jnp.asarray(seed, jnp.uint64)
        ev_valid = jnp.zeros((e,), jnp.bool_).at[:n].set(True)
        ev_kind = jnp.full((e,), KIND_NOP, jnp.int32)
        ev_kind = ev_kind.at[:n].set(FIRST_USER_KIND)
        ev_node = jnp.zeros((e,), jnp.int32).at[:n].set(jnp.arange(n, dtype=jnp.int32))
        ev_time = jnp.zeros((e,), tdtype)
        ev_args = jnp.zeros((e, wl.args_words), jnp.int32)
        ev_epoch = jnp.zeros((e,), jnp.int32)
        if p:
            # plan rows ride slots [n, n+p): engine kinds target node 0
            # from a timer source, epoch 0 (engine kinds bypass the
            # epoch gate); client-army rows (USER kinds, chaos
            # ClientArmy) carry their target in the plan's node column
            # and ride the ANY-epoch sentinel (-1): open-loop load is
            # addressed to whatever incarnation of the client is up at
            # arrival time, so a kill+restart of the client drops only
            # the ops that arrive while it is DOWN — not every op for
            # the rest of the run (arrivals are wall-scheduled, not
            # incarnation-scoped). The liveness gate still applies.
            # At t=0 the time32 offset form equals the absolute form,
            # so the cast below is exact for validated plans (times
            # within the int32 horizon).
            ev_valid = ev_valid.at[n : n + p].set(pv)
            ev_kind = ev_kind.at[n : n + p].set(pk)
            ev_time = ev_time.at[n : n + p].set(pt.astype(tdtype))
            ev_args = ev_args.at[n : n + p, 0:2].set(pa)
            is_user_row = (pk >= FIRST_USER_KIND) & (pk < FIRST_EXT_KIND)
            ev_epoch = ev_epoch.at[n : n + p].set(
                jnp.where(is_user_row, jnp.int32(-1), jnp.int32(0))
            )
            if pn is not None:
                # clip to the meta byte range like every emit pack: an
                # out-of-range target matches nothing downstream
                ev_node = ev_node.at[n : n + p].set(
                    jnp.clip(pn.astype(jnp.int32), -1, n)
                )
        # src = -1 (timer), retry = 0 for every initial on_init event
        ev_meta = _meta_pack(
            ev_kind,
            ev_node + 1,
            jnp.zeros((e,), jnp.int32),
            jnp.zeros((e,), jnp.int32),
        )
        if causal:
            # no pre-seeded row has an emitting dispatch: on_init rows
            # (and never-written slots) are PARENT_NONE roots; plan rows
            # are classed engine/chaos vs client-army by the same USER-
            # kind predicate the epoch sentinel uses above
            ev_parent = jnp.full((e,), PARENT_NONE, jnp.int32)
            if p:
                ev_parent = ev_parent.at[n : n + p].set(
                    jnp.where(
                        is_user_row,
                        jnp.int32(PARENT_ARMY),
                        jnp.int32(PARENT_PLAN),
                    )
                )
        else:
            ev_parent = jnp.zeros((0,), jnp.int32)
        tc_c = timeline_cap if causal else 0
        if n_tiles:
            tile_min, tile_cnt = build_pool_index(ev_time, ev_valid, p_tile)
        else:
            tile_min = jnp.zeros((0,), tdtype)
            tile_cnt = jnp.zeros((0,), jnp.int32)
        return SimState(
            seed=seed,
            now=jnp.int64(0),
            step=jnp.uint32(0),
            halted=jnp.asarray(False),
            halt_time=jnp.int64(0),
            trace=jnp.uint64(0),
            overflow=jnp.int32(0),
            msg_count=jnp.int64(0),
            ev_time=ev_time,
            ev_valid=ev_valid,
            ev_meta=ev_meta,
            ev_epoch=ev_epoch,
            ev_args=ev_args,
            ev_pay=jnp.zeros((e, w), jnp.int32),
            alive=jnp.ones((n,), jnp.bool_),
            paused=jnp.zeros((n,), jnp.bool_),
            epoch=jnp.zeros((n,), jnp.int32),
            node_state=base_state,
            clog=jnp.zeros((n, n), jnp.bool_),
            slow=jnp.ones((n, n), jnp.int32),
            dup=jnp.asarray(False),
            skew=jnp.zeros((n,), jnp.int32),
            disk=(base_state if d else jnp.zeros((0, u), jnp.int32)),
            wmask=jnp.zeros((d, u), jnp.bool_),
            sync_loss=jnp.zeros((d,), jnp.bool_),
            sync_eio=jnp.zeros((d,), jnp.bool_),
            torn=jnp.zeros((d,), jnp.bool_),
            hist_count=jnp.int32(0),
            hist_drop=jnp.int32(0),
            hist_word=jnp.zeros((h, 5), jnp.int32),
            hist_t=jnp.zeros((h,), jnp.int64),
            cov=jnp.zeros((cov_words,), jnp.uint32),
            cov_last=jnp.zeros((n if cov_words else 0,), jnp.int32),
            cov_hits=jnp.zeros(
                (cov_words * 32 if cov_hitcount else 0,), jnp.uint8
            ),
            met=jnp.zeros((N_METRICS if metrics else 0,), jnp.int32),
            tl_count=jnp.int32(0),
            tl_drop=jnp.int32(0),
            tl_t=jnp.zeros((timeline_cap,), jnp.int64),
            tl_meta=jnp.zeros((timeline_cap,), jnp.uint32),
            tl_args=jnp.zeros((timeline_cap, wl.args_words), jnp.int32),
            tl_pay=jnp.zeros((timeline_cap, w), jnp.int32),
            ev_emit=jnp.zeros((e if timeline_cap else 0,), jnp.int64),
            tl_emit=jnp.zeros((timeline_cap,), jnp.int64),
            lam=jnp.zeros((n if causal else 0,), jnp.uint32),
            ev_parent=ev_parent,
            ev_lam=jnp.zeros((e if causal else 0,), jnp.uint32),
            tl_seq=jnp.zeros((tc_c,), jnp.int32),
            tl_parent=jnp.zeros((tc_c,), jnp.int32),
            tl_lam=jnp.zeros((tc_c,), jnp.uint32),
            lat_inv=jnp.full((lat_c,), -1, jnp.int64),
            lat_resp=jnp.full((lat_c,), -1, jnp.int64),
            lat_hist=jnp.zeros((lat_p, N_LAT_BUCKETS if lat_c else 0), jnp.int32),
            lat_count=jnp.int32(0),
            lat_drop=jnp.int32(0),
            rt_done=jnp.zeros((rt_c,), jnp.bool_),
            rt_attempt=jnp.zeros((rt_c,), jnp.int32),
            rt_deadline=jnp.zeros((rt_c,), jnp.int64),
            tile_min=tile_min,
            tile_cnt=tile_cnt,
        )

    def init(seeds, plan: PlanRows | None = None) -> SimState:
        seeds = jnp.asarray(seeds, jnp.uint64)
        if p:
            if plan is None:
                raise ValueError(
                    f"init was built with plan_slots={p}; pass the "
                    f"compiled PlanRows"
                )
            pn = getattr(plan, "node", None)
            if pn is None:
                # pre-army PlanRows: every row targets node 0 (engine
                # kinds ignore the target, the historical layout)
                pn = jnp.zeros_like(jnp.asarray(plan.kind, jnp.int32))
            return jax.vmap(init_one)(
                seeds,
                jnp.asarray(plan.time, jnp.int64),
                jnp.asarray(plan.kind, jnp.int32),
                jnp.asarray(plan.args, jnp.int32),
                jnp.asarray(plan.valid, jnp.bool_),
                jnp.asarray(pn, jnp.int32),
            )
        return jax.vmap(init_one)(seeds)

    return init


# ---------------------------------------------------------------------------
# step
# ---------------------------------------------------------------------------


@jax.custom_batching.custom_vmap
def _materialize(xs):
    """Identity barrier: force XLA to materialize ``xs`` here.

    Blocks producer fusion across the boundary —
    ``lax.optimization_barrier`` with the vmap rule the primitive
    itself lacks (the engine is always used under one ``jax.vmap``
    over seeds). The rank-placement path uses it to materialize the
    branch-selected emit rows ONCE: without it XLA fuses the
    lax.switch select chain into every per-slot placement pass and
    recomputes it per pool slot (measured 3.2 µs/seed-step in one
    fusion — a third of the raftlog step, PROFILE_CPU_r06)."""
    return lax.optimization_barrier(xs)


@_materialize.def_vmap
def _materialize_vmap(axis_size, in_batched, xs):
    del axis_size
    return lax.optimization_barrier(xs), in_batched[0]


def _trace_fold(trace, now, kind, node, args, pay=None):
    """Fold one dispatched event into the rolling trace hash (uint64)."""
    h = now.astype(jnp.uint64) * _TRACE_MIX
    h = h ^ (kind.astype(jnp.uint64) << jnp.uint64(32))
    h = h ^ (node.astype(jnp.uint64) << jnp.uint64(40))
    a = args.astype(jnp.uint32).astype(jnp.uint64)
    # missing high words were always emitted as zeros, so folding only
    # the declared args_words is value-identical to the 4-wide fold
    for j in range(args.shape[0]):
        h = h ^ (a[j] << jnp.uint64(8 * j))
    if pay is not None and pay.shape[0] > 0:
        # payload words participate in the trace so a payload divergence
        # between backends is caught; W=0 keeps pre-payload traces intact
        p = pay.astype(jnp.uint32).astype(jnp.uint64)
        idx = jnp.arange(p.shape[0], dtype=jnp.uint64)
        h = h ^ jnp.sum(p * (_TRACE_MIX ^ idx))
    return trace * _TRACE_PRIME + h


def make_step(
    wl: Workload,
    cfg: EngineConfig,
    layout: str | None = None,
    time32: bool | None = None,
    dup_rows: bool = False,
    cov_words: int = 0,
    metrics: bool = False,
    timeline_cap: int = 0,
    cov_hitcount: bool = False,
    latency: LatencySpec | None = None,
    placement: str | None = None,
    pool_index: bool | None = None,
    rank_place_max_pool: int | None = None,
    causal: bool = False,
    retry: "RetrySpec | None" = None,
    _lat_export: bool = False,
):
    """Build the single-seed ``step(SimState) -> SimState`` function.

    Pops the earliest pending event, dispatches it through
    ``lax.switch`` (engine kinds + user handlers), applies chaos effects,
    and inserts emitted events. ``jax.vmap`` over the seed axis and
    ``lax.scan`` over steps give the batched run loop.

    ``layout`` picks the *lowering* of the per-event reads/writes — the
    VALUES are bit-identical either way (the oracle suite asserts it):

    * ``"dense"`` — one-hot masked reductions and rank-match placement,
      no gather/scatter ops. TPU lowers batched scatter/gather to
      serial loops (measured 96% of step wall time,
      examples/profile_step.py), so dense is ~70x faster there.
    * ``"scatter"`` — row-indexed: gathers for the per-event reads plus
      ``placement``-selected pool writes, the fast CPU lowering.
    * ``None`` (default) — scatter on the CPU backend, dense elsewhere.

    ``placement`` picks the scatter layout's pool-WRITE lowering (a
    third value-identical choice; dense ignores it — its one-hot
    placement is already rank-matched):

    * ``"rank"`` — rank-matched vector placement: the j-th ready emit
      pairs with the j-th free slot by cumsum rank, pool columns update
      through fused gather+select passes and the popped slot is
      consumed by a masked select. No scatter ops anywhere in the hot
      path — XLA CPU lowers a batched scatter to a SERIAL per-row
      update loop (~110 ns/row, measured: ~123 such rows/step were 90%
      of the pre-PR-8 step wall), while the select forms stay fused
      vector code.
    * ``"scatter"`` — the historical ``.at[].set`` stores. Each store
      costs O(emit rows), independent of pool size, so it WINS once
      the pool is large (client-army pools, thousands of slots) and
      the O(E) vector passes dominate instead.
    * ``None`` (default) — ``"rank"`` when ``cfg.pool_size`` <=
      :func:`resolve_rank_place_max_pool` (the documented crossover knob:
      ``rank_place_max_pool=`` here, the ``MADSIM_RANK_PLACE_MAX_POOL``
      env var, or the measured module default 512) and the readiness
      index is off, else ``"scatter"``. Under ``pool_index`` the
      default is ``"scatter"``: placement writes are then O(emits)
      element stores whatever the pool size, and the measured CPU
      crossover favors them over the within-tile select chains
      (``"rank"`` under the index) — see SCALING.md round 9.

    ``pool_index`` adds the two-level readiness index to the pool (the
    ISSUE-13 tentpole): per-tile ``tile_min``/``tile_cnt`` summary
    columns ride SimState (derived by construction, rebuilt on
    checkpoint restore, format unchanged), the pop runs argmin over
    E/T tile minima then argmin inside the ONE winning tile, and
    placement's free-slot search runs over per-tile free counts plus a
    rank match inside the target tiles — O(E/T + T + emits) per event
    instead of O(E), value-identical by construction (the goldens pin
    it). ``None`` (default) resolves on for CPU scatter pools larger
    than 1024 slots (``MADSIM_POOL_INDEX_MIN_POOL`` overrides the
    threshold), off otherwise — small-pool programs are exactly
    today's lowering. Requires a tile-divisible pool
    (:func:`pool_tile`; ``chaos.FaultPlan.min_pool_size`` sizes army
    pools aligned) and the scatter layout. Must match the ``init``
    builder's value, like ``time32``.

    ``time32`` picks the *representation* of pool event times — again
    value-identical (tests/test_engine.py asserts it):

    * ``True`` — ``ev_time`` holds int32 offsets from ``now``, rebased
      by the clock advance each step. Every per-slot time op (the
      argmin, the placement selects) becomes native-width on TPU (v5e
      emulates 64-bit lanes at ~2x cost). Requires
      :func:`time32_eligible` bounds.
    * ``False`` — absolute int64 nanoseconds, the natural CPU form.
    * ``None`` (default) — int32 on accelerators when eligible.

    ``dup_rows=True`` compiles the message-duplication path (KIND_DUP_ON
    chaos): each user emit row gets a shadow row that, while the seed's
    ``dup`` flag is set, inserts a second delivery of every send with an
    independent latency/loss draw (purpose PURPOSE_DUP+slot). The shadow
    rows cost pool-placement work every step, so they are compiled only
    when a fault plan actually uses duplication; with the flag off (or
    ``dup`` never set) values are bit-identical to the plain step.

    ``cov_words=CW`` compiles the coverage taps (madsim_tpu.explore):
    each dispatched event folds behavior features into the seed's
    CW*32-bit bitmap — (node, previous kind, kind) transition pairs for
    user events, (kind, coarse time phase) markers for engine/chaos
    events (so injected crash/partition phases are coverage), and the
    (op, key, arg, ok) words of appended history records (term bumps
    and leader changes become bits). Coverage is derived state only:
    with CW=0 (default) the block compiles away and values are
    bit-identical to the pre-coverage step.

    The three observability taps (madsim_tpu.obs) follow the exact same
    derived-state-only discipline — off (the defaults) means zero-size
    arrays, zero ops, bit-identical values; on means extra columns that
    never feed back into the trajectory, the RNG, or the trace:

    * ``metrics=True`` folds the MET_* fleet counters (messages sent/
      delivered/lost, crashes, pauses, clog-blocked attempts, timer
      fires, history records, RNG blocks, halt reason) into
      ``SimState.met`` per seed, sized for on-device fleet reduction.
    * ``timeline_cap=T`` records the dispatched-event stream — the
      tuples the trace hash folds — into a T-slot ring per seed
      (``tl_t``/``tl_meta``/``tl_args``), overflow counted loudly in
      ``tl_drop``. Decoded host-side by ``obs.decode_timeline``.
    * ``cov_hitcount=True`` upgrades the coverage taps from set-only to
      AFL-style hit-count bucketing: each feature keeps a saturating
      per-seed counter (``cov_hits``) and its bitmap bit is keyed by
      (feature, bucket class 1/2/3/4-7/8-15/16-31/32-127/128+), so a
      behavior recurring an order of magnitude more often is new
      coverage. Changes which bits mean what — campaigns must not mix
      flag states — but never the trajectory.
    * ``latency=LatencySpec(ops=C, ...)`` compiles the tail-latency
      tap: handlers mark client-army op invokes/responses
      (``EmitBuilder.lat_start/lat_end``), the engine stamps dispatch
      clocks into the per-op ``lat_inv``/``lat_resp`` columns and folds
      each completed op's latency into the per-seed log-linear sketch
      ``lat_hist`` (one histogram per measurement window — the
      invoke-time phase). When coverage is also on, each completion
      folds a (window, latency-bucket) feature, so "the tail moved"
      is new coverage the guided hunt can chase. Out-of-range op ids
      count loudly in ``lat_drop``.
    * ``causal=True`` folds exact causal provenance: each dispatch's
      event-sequence number (``SimState.step`` at dispatch) becomes the
      ``ev_parent`` of every event it emits, the destination node's
      Lamport clock folds ``lam[dst] = max(lam[dst], lam_at_emit) + 1``,
      and — with the ring on — each captured row banks its own seq
      (``tl_seq``), its parent's seq (``tl_parent``) and the folded
      clock (``tl_lam``): the exact event-derivation DAG, decoded by
      ``obs.causal``. When coverage is also on, each dispatch folds a
      (Lamport-depth bucket, cross-node-jump bucket) feature, so deeper
      or wider causality is new coverage.
    """
    n = wl.n_nodes
    k = wl.max_emits
    w = wl.payload_words
    aw = wl.args_words
    # history columns: capacity H and per-invocation record slots R
    # (both 0 when recording is off — the history block compiles away)
    hcap = wl.history.capacity if wl.history is not None else 0
    rr = wl.history.max_records if wl.history is not None else 0
    # latency tap: C op slots / P windows (both 0 when off) and the
    # per-invocation marker slots L (an Emits-shape constant like rr)
    ll = wl.lat_markers
    lat_c = latency.ops if latency is not None else 0
    lat_p = latency.phases if latency is not None else 0
    lat_phase_ns = latency.phase_ns if latency is not None else 1
    # numpy (not jnp) so they embed as literals: a jnp closure constant
    # would block wrapping the step in a pallas kernel (pallas requires
    # traced constants to be declared inputs)
    init_rows = wl.initial_state()
    # durable columns survive kill/restart (FsSim power-fail analog);
    # static per workload, so the select compiles to a constant mask
    volatile = wl.volatile_mask()
    # two-phase sync discipline (durable_sync): durable columns survive
    # a KILL only up to the node's last committed sync — static per
    # workload, so the whole block compiles away when off
    sync_on = wl.durable_sync
    n_user = len(wl.handlers)
    # user purposes prefetched into the per-dispatch RNG block (static)
    user_purposes = tuple(int(p) for p in (wl.draw_purposes or ()))
    _check_meta_ranges(wl)
    _check_cov_words(cov_words)
    _check_obs(cov_words, cov_hitcount, timeline_cap, latency)
    rt_c = _check_retry(wl, retry)
    if rt_c:
        rt_boff, rt_bjit = _retry_backoff_tables(retry)
        rt_kind = np.int32(retry.kind)
        rt_node = np.int32(retry.node)
        rt_base = np.int32(retry.op_base)
        rt_max = np.int32(retry.max_attempts)
        rt_timeout = np.int64(retry.timeout_ns)
    layout = resolve_layout(layout)
    dense = layout == "dense"
    pool_index = _resolve_pool_index(cfg, pool_index, dense=dense)
    p_tile = pool_tile(cfg.pool_size) if pool_index else 0
    n_tiles = cfg.pool_size // p_tile if p_tile else 0
    if placement is None:
        placement = (
            "rank"
            if (not pool_index)
            and cfg.pool_size <= resolve_rank_place_max_pool(rank_place_max_pool)
            else "scatter"
        )
    if placement not in ("rank", "scatter"):
        raise ValueError(f"unknown placement {placement!r}")
    # rank-matched pool writes (scatter layout only; dense has its own
    # one-hot placement). Single-row appends (timeline ring, latency
    # clocks) stay .at[] stores either way — one serial row per step is
    # exactly the O(1) write a cold-bank append wants. Under the
    # readiness index, "rank" means the within-tile select-chain
    # variant: the full-pool passes never run.
    rank_place = (not dense) and placement == "rank"
    if _lat_export:
        # cold/hot split (make_run's cold_split): the step EXPORTS the
        # raw latency markers instead of folding them — the (C,)-wide
        # per-op columns pass through untouched and the run builder
        # applies them batch-level under a lax.cond, so the cold bank
        # is read/written only on steps where some seed marked an op.
        if latency is None or ll == 0:
            raise ValueError(
                "_lat_export needs the latency tap (a LatencySpec and "
                "Workload.lat_markers > 0) — there is nothing to export"
            )
        if cov_words:
            raise ValueError(
                "cold_split folds latency markers outside the step, so "
                "the (window, latency-bucket) coverage features cannot "
                "be computed in-step: use cov_words=0 (bench/obs runs) "
                "or the in-step latency tap (hunt runs)"
            )
    time32 = _resolve_time32(wl, cfg, time32)
    if rt_c and time32:
        # a retry timer's delay must fit the int32 offset form like any
        # declared timer bound (the delay_bound_ns eligibility rule)
        rt_worst = int(rt_timeout) + rt_boff[-1] + rt_bjit[-1]
        lim32 = _T32_LIMIT - cfg.proc_max_ns - 1
        if rt_worst > lim32:
            raise ValueError(
                f"retry timeout + max backoff + max jitter = {rt_worst} ns "
                f"exceeds the int32 offset horizon ({lim32} ns); shrink "
                f"the policy or build with time32=False"
            )
    t_inf = _INF_32 if time32 else _INF_NS

    # -- user branch table -------------------------------------------------
    # Only USER handlers go through lax.switch; engine kinds (kill, clog,
    # halt, ...) are trivial functions of (kind, args) and are computed
    # inline below as masked selects — under vmap a switch evaluates
    # every branch and selects each output leaf, so ten extra engine
    # branches cost real per-step op count for no information.
    # lax.switch operands must be pytrees, so the context travels as a
    # tuple of arrays and each branch rebuilds the HandlerCtx view.
    def _unpack(op) -> HandlerCtx:
        now, node, state, args, src, k0, k1, stp, pay, eio, ul0, ul1 = op
        # prefetched user lanes -> the Draw cache (trace-time dict,
        # static purposes): a declared purpose's draw reads its lane of
        # the per-dispatch block instead of running a scalar cipher in
        # this branch — identical (seed, step, purpose) values
        cache = {
            PURPOSE_USER + p: (ul0[j], ul1[j])
            for j, p in enumerate(user_purposes)
        } or None
        return HandlerCtx(
            now=now,
            node=node,
            state=state,
            args=args,
            src=src,
            draw=Draw.from_parts(k0, k1, stp, cache),
            max_emits=k,
            payload=pay,
            payload_words=w,
            args_words=aw,
            max_records=rr,
            sync_err=eio,
            max_lat=ll,
        )

    def _user_branch(handler):
        def branch(op):
            ctx = _unpack(op)
            new_state, emits = handler(ctx)
            rv = emits.rec_valid
            if rv is None or (rr > 0 and rv.shape[0] == 0):
                # hand-built Emits (not via ctx.emits()): no history
                # records — normalize to the branch pytree shape so the
                # switch doesn't fail on a None/empty leaf
                emits = dataclasses.replace(
                    emits,
                    rec_valid=jnp.zeros((rr,), jnp.bool_),
                    rec=jnp.zeros((rr, 4), jnp.int32),
                )
            elif rv.shape[0] != rr:
                raise ValueError(
                    f"handler returned Emits with {rv.shape[0]} history-"
                    f"record rows but the workload's HistorySpec allows "
                    f"{rr}; build emits via ctx.emits() (EmitBuilder) to "
                    f"get the right row count"
                )
            if emits.sync is None:
                # hand-built Emits: no sync flag — normalize so the
                # switch branches share one pytree shape
                emits = dataclasses.replace(emits, sync=jnp.asarray(False))
            lv = emits.lat_valid
            if lv is None or (ll > 0 and lv.shape[0] == 0):
                # hand-built Emits: no latency markers — normalize to
                # the branch pytree shape (the rec rule again)
                emits = dataclasses.replace(
                    emits,
                    lat_valid=jnp.zeros((ll,), jnp.bool_),
                    lat=jnp.zeros((ll, 2), jnp.int32),
                )
            elif lv.shape[0] != ll:
                raise ValueError(
                    f"handler returned Emits with {lv.shape[0]} latency-"
                    f"marker rows but Workload.lat_markers={ll}; build "
                    f"emits via ctx.emits() (EmitBuilder) to get the "
                    f"right row count"
                )
            return jnp.asarray(new_state, jnp.int32), emits

        return branch

    user_branches = [_user_branch(h) for h in wl.handlers]

    loss_u32 = cfg.loss_u32
    time_limit = np.int64(cfg.time_limit_ns) if cfg.time_limit_ns else _INF_NS

    def step(st: SimState, _tables=None) -> SimState:
        # ``_tables``: optional (init_rows, volatile) arrays overriding
        # the embedded literals — the pallas seam: a kernel cannot
        # capture non-scalar jaxpr constants, so kernel wrappers thread
        # the two tables through as kernel inputs (engine/vmem.py).
        # Values are identical either way.
        ir, vo = (init_rows, volatile) if _tables is None else _tables
        # representation guard (trace-time): a state built or restored
        # under the other time representation would be silently
        # misread — e.g. a checkpoint saved where time32 auto-resolved
        # differently (engine/checkpoint.py). Dtypes are static, so
        # this costs nothing in the compiled program.
        expected_t = jnp.int32 if time32 else jnp.int64
        if st.ev_time.dtype != expected_t:
            raise TypeError(
                f"SimState.ev_time has dtype {st.ev_time.dtype} but this "
                f"step was built with time32={time32} (expects "
                f"{jnp.dtype(expected_t).name}); build init/step with "
                f"matching explicit time32= values"
            )
        # pool-index shape guard (the same trace-time rule): an INDEXED
        # step popping a state without matching summaries would be
        # silently wrong, so it demands the exact tile count. The
        # off-step accepts anything — it rebuilds whatever summaries
        # the state carries (index-preserving, below), so a flat build
        # can always consume an indexed state.
        if pool_index and st.tile_cnt.shape[0] != n_tiles:
            raise TypeError(
                f"SimState carries {st.tile_cnt.shape[0]} pool-index "
                f"tiles but this step was built with {n_tiles}; build "
                f"init/step with matching explicit pool_index= values "
                f"(auto-resolution is backend-dependent, the time32 "
                f"rule)"
            )
        # causal shape guard (the same trace-time rule): a causal step
        # folding zero-size clock columns would be silently wrong
        if causal and st.lam.shape[0] != n:
            raise TypeError(
                f"SimState.lam has shape {st.lam.shape} but this step "
                f"was built with causal=True (expects ({n},)); build "
                f"init/step with matching causal= values"
            )
        # ---- pop the earliest pending event (the timer-jump of
        # time/mod.rs:45-60 merged with the ready-queue drain) ----
        # Two value-identical lowerings of every per-event read/write
        # (see the ``layout`` docstring): dense = one-hot masked
        # reductions over the small E or N axis (per-seed dynamic
        # indexing lowers to batched gathers under vmap, ~1 ms/step on
        # TPU, examples/profile_step.py); scatter = plain indexing with
        # in_range masks so OOB handling matches dense and the oracle.
        e_slots = st.ev_valid.shape[0]
        if pool_index:
            # two-level pop: argmin over the E/T carried tile minima
            # (empty tiles masked to +inf — their stale minima never
            # compete), then argmin inside the ONE winning tile (a
            # single T-wide row gather from the reshaped pool). The
            # first tile achieving the global minimum contains the
            # globally first minimal slot, and argmin's first-match
            # tie-break inside it picks exactly that slot — identical
            # to the flat argmin over all E, at O(E/T + T).
            tmin = jnp.where(st.tile_cnt > 0, st.tile_min, t_inf)
            wtile = jnp.argmin(tmin).astype(jnp.int32)
            tv_row = st.ev_valid.reshape(n_tiles, p_tile)[wtile]
            tt_row = st.ev_time.reshape(n_tiles, p_tile)[wtile]
            li = jnp.argmin(
                jnp.where(tv_row, tt_row, t_inf)
            ).astype(jnp.int32)
            i = wtile * p_tile + li
        else:
            tmask = jnp.where(st.ev_valid, st.ev_time, t_inf)
            i = jnp.argmin(tmask)
        slot_ids = jnp.arange(e_slots, dtype=jnp.int32)
        is_popped = slot_ids == i.astype(jnp.int32)

        if dense:

            def pick_slot(arr):
                """arr (E, ...) -> arr[i] via the one-hot mask (exact)."""
                extra = arr.ndim - 1
                m = is_popped.reshape((-1,) + (1,) * extra)
                return jnp.sum(jnp.where(m, arr, 0), axis=0).astype(arr.dtype)

        else:

            def pick_slot(arr):
                return arr[i]

        if pool_index:
            # == ev_valid[i], read from the already-gathered tile row
            # instead of an O(E) masked any
            has_event = tv_row[li]
        else:
            has_event = jnp.any(st.ev_valid & is_popped)
        ev_time_i = pick_slot(st.ev_time)
        if time32:
            # offsets are relative to st.now; a (slightly) negative
            # offset is an event whose time the clock already passed by
            # a poll cost — identical to the absolute-form maximum
            ev_t = st.now + jnp.maximum(ev_time_i, 0).astype(jnp.int64)
        else:
            ev_t = jnp.maximum(st.now, ev_time_i)
        over_limit = ev_t > time_limit
        active = has_event & ~st.halted & ~over_limit

        meta_i = pick_slot(st.ev_meta)
        kind = _meta_kind(meta_i)
        dst = _meta_node(meta_i)
        src = _meta_src(meta_i)
        args = pick_slot(st.ev_args)
        ev_epoch_i = pick_slot(st.ev_epoch)
        pay_i = pick_slot(st.ev_pay)
        # emit-time sidecar (ring on): when THIS event entered the pool
        # — read before placement can reuse the freed slot
        emit_i = pick_slot(st.ev_emit) if timeline_cap else jnp.int64(0)
        if causal:
            # causal sidecar: the popped row's emitting-dispatch seq and
            # the clock that dispatch folded — the same read-before-
            # placement rule as emit_i
            parent_i = pick_slot(st.ev_parent)
            evlam_i = pick_slot(st.ev_lam)
        else:
            parent_i = jnp.int32(PARENT_NONE)
            evlam_i = jnp.uint32(0)
        # extended chaos kinds (>= FIRST_EXT_KIND) are engine kinds too:
        # dispatched inline, exempt from the epoch/pause gates
        is_engine = (kind < FIRST_USER_KIND) | (kind >= FIRST_EXT_KIND)
        is_msg = src >= 0

        node_ids = jnp.arange(n, dtype=jnp.int32)
        dst_oh = node_ids == dst  # (N,) one-hot; all-False for OOB dst
        if dense:
            state_row = jnp.sum(
                jnp.where(dst_oh[:, None], st.node_state, 0), axis=0
            ).astype(jnp.int32)
            alive_dst = jnp.any(st.alive & dst_oh)
            paused_dst = jnp.any(st.paused & dst_oh)
            epoch_dst = jnp.sum(jnp.where(dst_oh, st.epoch, 0)).astype(jnp.int32)
            skew_dst = jnp.sum(jnp.where(dst_oh, st.skew, 0)).astype(jnp.int32)
        else:
            # gather lowering. Gathers clamp out-of-range indices, which
            # would silently diverge from the dense form's no-match (and
            # the oracle); mask with in_range so an OOB dst reads as a
            # dead node with zero state in BOTH layouts
            in_range = (dst >= 0) & (dst < n)
            dst_c = jnp.clip(dst, 0, n - 1)
            state_row = jnp.where(in_range, st.node_state[dst_c], 0)
            alive_dst = st.alive[dst_c] & in_range
            paused_dst = st.paused[dst_c] & in_range
            epoch_dst = jnp.where(in_range, st.epoch[dst_c], 0)
            skew_dst = jnp.where(in_range, st.skew[dst_c], 0)
        # the handling node's observable fsync-EIO bit (ctx.sync_err):
        # pre-dispatch state, like every other ctx view. Constant False
        # without the sync discipline — the gate compiles away.
        if sync_on:
            if dense:
                eio_dst = jnp.any(st.sync_eio & dst_oh)
            else:
                eio_dst = st.sync_eio[dst_c] & in_range
        else:
            eio_dst = jnp.asarray(False)

        # liveness/epoch gate: user events to a dead or reincarnated node
        # are dropped — the kill-drops-futures semantics of task.rs:255-276.
        # Epoch -1 is the ANY-epoch sentinel client-army plan rows carry
        # (make_init): open-loop arrivals address whatever incarnation is
        # up, so only the liveness half gates them. No emitted event ever
        # carries -1 (emit epochs copy node epochs, which only grow), so
        # sentinel-free runs take the exact historical gate.
        live = alive_dst & (
            (epoch_dst == ev_epoch_i) | (ev_epoch_i == jnp.int32(-1))
        )
        # clogged links hold messages; re-check with exponential backoff
        # like the connection pump (net/mod.rs:341-355)
        if dense:
            src_oh = node_ids == jnp.maximum(src, 0)
            clogged = is_msg & jnp.any(
                st.clog & src_oh[:, None] & dst_oh[None, :]
            )
        else:
            clogged = is_msg & st.clog[jnp.maximum(src, 0), dst_c] & in_range
        # paused node: user events are stashed and retried, like the
        # executor stashing a paused node's ready tasks (task.rs:294-314)
        held = (~is_engine) & paused_dst
        blocked = clogged | held
        dispatch = active & ~blocked & (is_engine | live)

        # ---- client-retry decode (RetrySpec; rt_c=0 compiles the whole
        # mechanism away). An army row — the original offer or an armed
        # re-send timer — is a USER-kind dispatch of the policy's kind
        # at the army node whose token op id falls in the policy's op
        # range; the attempt id rides the token high bits (attempt-0
        # tokens are plain op ids, the off-policy bit-identity). A row
        # whose op already saw a response (rt_done) or that carries the
        # give-up sentinel attempt (== max_attempts) is SUPPRESSED: it
        # dispatches as a no-op — handler effects, emits and records
        # dropped; only the trace fold and the retry books see it.
        if rt_c:
            rt_tok = args[0]
            rt_idx = (rt_tok & jnp.int32(RETRY_OP_MASK)) - rt_base
            rt_att = (
                rt_tok >> jnp.int32(RETRY_ATTEMPT_SHIFT)
            ) & jnp.int32(RETRY_ATTEMPT_MAX)
            rt_in_r = (rt_idx >= 0) & (rt_idx < rt_c)
            is_army = (
                dispatch & ~is_engine & (kind == rt_kind)
                & (dst == rt_node) & rt_in_r
            )
            rt_ids = jnp.arange(rt_c, dtype=jnp.int32)
            rt_oh = rt_ids == rt_idx  # (CR,); all-False out of range
            rt_done_i = jnp.any(st.rt_done & rt_oh)
            rt_deliver = ~rt_done_i & (rt_att < rt_max)
            rt_suppress = is_army & ~rt_deliver
            rt_arm = is_army & rt_deliver
        else:
            rt_suppress = jnp.asarray(False)

        # ---- causal provenance fold (causal=True; derived state only,
        # the ev_emit discipline: everything below is read exclusively
        # into more causal columns / the ring, never the trajectory) ----
        if causal:
            # the dispatch's event-sequence number, int32 for the
            # sentinel classes: `step` is certified <= ABSINT_STEP_MAX
            # (= 2^31, one past int32), so the clamp makes the narrow
            # cast provably wrap-free — and a clamped seq can only occur
            # past the certified run length anyway
            seq_i32 = jnp.minimum(
                st.step, jnp.uint32(ABSINT_STEP_MAX - 1)
            ).astype(jnp.int32)
            if dense:
                lam_prev = jnp.sum(
                    jnp.where(dst_oh, st.lam, jnp.uint32(0))
                ).astype(jnp.uint32)
            else:
                lam_prev = jnp.where(
                    in_range, st.lam[dst_c], jnp.uint32(0)
                )
            # the Lamport fold: receive = max(own, sender's) + 1. An
            # undelivered step (no dispatch) folds nothing.
            lam_new = jnp.maximum(lam_prev, evlam_i) + jnp.uint32(1)
            if dense or rank_place:
                lam = jnp.where(
                    dst_oh & dispatch, lam_new, st.lam
                ).astype(jnp.uint32)
            else:
                # OOB dst writes nothing, the dropped-scatter rule
                lam = st.lam.at[
                    jnp.where(dispatch & in_range, dst_c, jnp.int32(n))
                ].set(lam_new, mode="drop")
        else:
            seq_i32 = jnp.int32(0)
            lam_prev = lam_new = jnp.uint32(0)
            lam = st.lam

        now = jnp.where(active, ev_t, st.now)
        draw = Draw(st.seed, st.step)
        # ---- per-dispatch batched RNG (the BatchRNG shape, PAPERS.md).
        # Every purpose one event-step can draw is enumerated as a
        # static lane vector and generated by ONE varying-counter
        # threefry pass (Draw.block2): lane 0 is the poll-cost/jitter
        # pair, lanes 1..k+1 the per-emit latency/loss pairs (+ the dup
        # shadow lanes, + the torn-prefix draw under the sync
        # discipline). Each lane keys the same (seed, step, purpose)
        # counter the retired per-use calls did, so every draw VALUE —
        # and therefore every trace and the C++ oracle compare — is
        # bit-identical; what changes is the cipher running as one
        # fused vector pass instead of per-use scalar invocations.
        n_em_lanes = (k + 1) + (k if dup_rows else 0)
        lane_p = [PURPOSE_POLL_COST]
        lane_p += [PURPOSE_LATENCY + s for s in range(k + 1)]
        if dup_rows:
            lane_p += [PURPOSE_DUP + s for s in range(k)]
        i_torn = len(lane_p)
        if sync_on:
            lane_p.append(PURPOSE_TORN)
        # retry backoff jitter: one fresh lane per dispatch (a re-send's
        # jitter is keyed by the ARMING step, seed-pure like everything)
        i_retry = len(lane_p)
        if rt_c:
            lane_p.append(PURPOSE_RETRY)
        # user lanes (Workload.draw_purposes): handler draws at these
        # purposes ride the same block; ctx.draw serves them from a
        # trace-time lane cache (rng.Draw.from_parts) so no branch
        # issues its own scalar cipher for a declared purpose
        i_user = len(lane_p)
        lane_p += [PURPOSE_USER + p for p in user_purposes]
        # stacked scalar literals, NOT a literal array: a pallas kernel
        # (engine/vmem.py) cannot capture non-scalar jaxpr constants,
        # and scalars inline as literals — same values either way
        lane0, lane1 = draw.block2(
            jnp.stack([jnp.uint32(p) for p in lane_p])
        )
        user_l0 = lane0[i_user:]
        user_l1 = lane1[i_user:]
        # per-event processing cost, 50-100 ns (task.rs:213), paired
        # with the clog-recheck jitter in ONE threefry block (lane 0 =
        # cost, lane 1 = jitter) — same bits2 pairing as latency/loss
        cost = draw._reduce(lane0[0], cfg.proc_min_ns, cfg.proc_max_ns)
        clog_jit = draw._reduce(lane1[0], 0, 1000)
        now_after = jnp.where(dispatch, now + cost, now)

        # ---- consume / reschedule the popped slot ----
        # dense: masked selects over the full pool (TPU lowers batched
        # scatter to a serial loop — it measured as 96% of step wall
        # time, examples/profile_step.py); scatter: .at[].set, the
        # faster CPU lowering. Same values either way.
        retries = _meta_retry(meta_i)
        shift = jnp.minimum(retries, jnp.int32(34)).astype(jnp.int64)
        backoff = jnp.minimum(
            jnp.int64(cfg.clog_backoff_min_ns) << shift,
            jnp.int64(cfg.clog_backoff_max_ns),
        )
        backoff = backoff + clog_jit
        resched = active & blocked & (is_engine | live)
        if time32:
            # rebase every offset by this step's clock advance so the
            # pool stays relative to the post-step clock. A reschedule
            # only happens when dispatch is false, so now_after == now
            # and the backoff offset needs no correction. Stale offsets
            # in invalid slots may wrap; they are masked at every use.
            adv32 = (now_after - st.now).astype(jnp.int32)
            # The two rebase subtractions below are the acknowledged
            # stale-slot wrap surface: a consumed slot's offset keeps
            # rebasing and may wrap int32 after ~2.1 sim-seconds —
            # masked at every use (ev_valid), and relationally bounded
            # for VALID slots (a valid offset is >= the popped minimum,
            # so it never drops below -proc_max), which a non-relational
            # interval domain cannot see. Certified instead by the
            # layout bit-identity pins (tests/test_engine.py).
            ev_time_reb = st.ev_time - adv32  # lint: allow(absint-overflow)
            back_t = backoff.astype(jnp.int32)
            old_t = ev_time_i - adv32  # lint: allow(absint-overflow)
        else:
            ev_time_reb = st.ev_time
            back_t = now + backoff
            old_t = ev_time_i
        # retry byte bump, saturating (shift caps at 34 so >=255 retries
        # behave identically); the other three meta bytes are unchanged
        meta_bumped = (meta_i & jnp.uint32(0x00FFFFFF)) | (
            jnp.minimum(retries + 1, 255).astype(jnp.uint32) << jnp.uint32(24)
        )
        if dense or (rank_place and not pool_index):
            # masked selects: the popped slot is consumed (or its
            # backoff rescheduled) by a fused vector pass — identical
            # values to the .at[i] store, no serial scatter
            ev_valid_mid = jnp.where(is_popped, resched, st.ev_valid)
            ev_time_mid = jnp.where(is_popped & resched, back_t, ev_time_reb)
            ev_meta_mid = jnp.where(is_popped & resched, meta_bumped, st.ev_meta)
        else:
            # O(1) element stores (under the readiness index too: one
            # serial row beats a full-pool select pass at army scale)
            ev_valid_mid = st.ev_valid.at[i].set(resched)
            ev_time_mid = ev_time_reb.at[i].set(
                jnp.where(resched, back_t, old_t)
            )
            ev_meta_mid = st.ev_meta.at[i].set(
                jnp.where(resched, meta_bumped, meta_i)
            )
        if pool_index:
            # index maintenance, part 1: rebase the carried minima with
            # the pool (time32 offsets shrink by the clock advance;
            # empty tiles' stale values may wrap — masked at every
            # use), and account the popped slot's consume/reschedule
            # into its tile's count. The popped tile's MIN is
            # recomputed exactly after placement (a consume can RAISE
            # it, which no incremental min update can express).
            # (same stale-wrap surface as the pool rebase above: empty
            # tiles' carried sentinels decay here and are re-masked to
            # +inf before any min-fold reads them — the PR-13 rule)
            tile_min_mid = (st.tile_min - adv32) if time32 else st.tile_min  # lint: allow(absint-overflow)
            tile_cnt_mid = st.tile_cnt.at[wtile].add(
                resched.astype(jnp.int32) - has_event.astype(jnp.int32)
            )

        # ---- dispatch: user handlers via lax.switch; engine kinds are
        # computed inline as masked selects (see the branch-table note) ----
        if n_user:
            user_idx = jnp.clip(kind - FIRST_USER_KIND, 0, n_user - 1)
            # the handler observes its NODE's clock: the true dispatch
            # time plus the node's chaos skew (KIND_SKEW). Zero skew adds
            # nothing, so non-chaos runs see the exact historical ctx.now.
            # The trace fold and history timestamps keep the unskewed
            # time (cross-node orderings stay exact).
            user_now = now + skew_dst.astype(jnp.int64)
            operand = (
                user_now, dst, state_row, args, src,
                draw.k0, draw.k1, draw.step, pay_i, eio_dst,
                user_l0, user_l1,
            )
            user_state, uem = lax.switch(user_idx, user_branches, operand)
        else:
            # chaos-only workload: no user branches to run
            user_state, uem = state_row, Emits.none(k, w, aw, rr, ll)
        user_dispatch = dispatch & ~is_engine
        if rt_c:
            # suppressed army rows are no-ops: the branch ran (a switch
            # always does) but none of its effects apply
            user_dispatch = user_dispatch & ~rt_suppress

        # ---- apply node-state update (an OOB dst matches no row in the
        # dense form, exactly the dropped-scatter semantics) ----
        row = jnp.where(user_dispatch, user_state, state_row)
        if dense or rank_place:
            # (an OOB dst has an all-False one-hot — the dropped-scatter
            # semantics as a select; N*U is small for every model, so
            # the fused pass beats a serial row store)
            node_state = jnp.where(dst_oh[:, None], row[None, :], st.node_state)
        else:
            # negative indices would wrap (numpy semantics); redirect OOB
            # to index n so mode="drop" discards it like dense's no-match
            node_state = st.node_state.at[
                jnp.where(in_range, dst_c, jnp.int32(n))
            ].set(row, mode="drop")

        # ---- engine effects: kill / restart / pause / clog / halt ----
        a0, a1 = args[0], args[1]
        kill_id = jnp.where(dispatch & (kind == KIND_KILL), a0, jnp.int32(-1))
        restart_id = jnp.where(dispatch & (kind == KIND_RESTART), a0, jnp.int32(-1))
        is_killed = node_ids == kill_id
        is_restarted = node_ids == restart_id
        alive = jnp.where(is_killed, False, st.alive)
        alive = jnp.where(is_restarted, True, alive)
        is_pause_kind = (kind == KIND_PAUSE) | (kind == KIND_RESUME)
        pause_id = jnp.where(dispatch & is_pause_kind, a0, jnp.int32(-1))
        paused = jnp.where(node_ids == pause_id, kind == KIND_PAUSE, st.paused)
        # kill/restart clears paused (fresh incarnation runs)
        paused = jnp.where(is_killed | is_restarted, False, paused)
        # epoch bumps invalidate every in-flight event targeting the node
        epoch = st.epoch + is_killed + is_restarted
        node_state = jnp.where(
            is_restarted[:, None] & vo[None, :], ir, node_state
        )

        is_clog_kind = (kind >= KIND_CLOG) & (kind <= KIND_UNCLOG_NODE)
        clog_on = (kind == KIND_CLOG) | (kind == KIND_CLOG_NODE)
        clog_set = jnp.where(
            dispatch & is_clog_kind, clog_on.astype(jnp.int32), jnp.int32(-1)
        )
        is_node_clog = (kind == KIND_CLOG_NODE) | (kind == KIND_UNCLOG_NODE)
        clog_a = a0
        clog_b = jnp.where(is_node_clog, jnp.int32(-1), a1)
        src_ax = node_ids[:, None]
        dst_ax = node_ids[None, :]
        # clog_link(a, b) blocks both directions; clog_b < 0 means
        # clog_node(a): everything in or out of a (net/mod.rs:157-216)
        pair_sel = ((src_ax == clog_a) & (dst_ax == clog_b)) | (
            (src_ax == clog_b) & (dst_ax == clog_a)
        )
        node_sel = (clog_b < 0) & ((src_ax == clog_a) | (dst_ax == clog_a))
        sel = pair_sel | node_sel
        clog = jnp.where(
            sel & (clog_set == 1), True, jnp.where(sel & (clog_set == 0), False, st.clog)
        )
        # asymmetric partition edge (extended kinds): one direction only
        is_c1w = (kind == KIND_CLOG_1W) | (kind == KIND_UNCLOG_1W)
        c1w_set = jnp.where(
            dispatch & is_c1w,
            (kind == KIND_CLOG_1W).astype(jnp.int32),
            jnp.int32(-1),
        )
        sel_1w = (src_ax == a0) & (dst_ax == a1)
        clog = jnp.where(
            sel_1w & (c1w_set == 1),
            True,
            jnp.where(sel_1w & (c1w_set == 0), False, clog),
        )

        # ---- extended chaos effects: gray failure / duplication / skew.
        # Defaults (slow=1, dup off, skew=0) are identities, so these
        # selects change no value for workloads that never emit them.
        is_slow_kind = (kind == KIND_SLOW_LINK) | (kind == KIND_UNSLOW)
        slow_b = (a1 & jnp.int32(0xFF)) - 1  # packed peer; -1 = node-wide
        slow_mult = jnp.maximum(a1 >> jnp.int32(8), 1)
        slow_mult = jnp.where(kind == KIND_UNSLOW, jnp.int32(1), slow_mult)
        slow_set = jnp.where(
            dispatch & is_slow_kind, slow_mult, jnp.int32(-1)
        )
        pair_sl = ((src_ax == a0) & (dst_ax == slow_b)) | (
            (src_ax == slow_b) & (dst_ax == a0)
        )
        node_sl = (slow_b < 0) & ((src_ax == a0) | (dst_ax == a0))
        slow = jnp.where(
            (pair_sl | node_sl) & (slow_set > 0), slow_set, st.slow
        )
        is_dup_kind = (kind == KIND_DUP_ON) | (kind == KIND_DUP_OFF)
        dup = jnp.where(dispatch & is_dup_kind, kind == KIND_DUP_ON, st.dup)
        skew_id = jnp.where(dispatch & (kind == KIND_SKEW), a0, jnp.int32(-1))
        skew = jnp.where(node_ids == skew_id, a1, st.skew)

        # ---- two-phase sync discipline (Workload.durable_sync) ----
        # Durable writes buffer until an explicit sync commits them to
        # the node's disk image; a KILL reverts durable columns to that
        # image (plus, under an armed TORN mode, a threefry-drawn PREFIX
        # of the last uncommitted write — the FDB-style torn write).
        # Everything here is masked selects over (N,)/(N,U) arrays, the
        # same arithmetic in both layouts; with the discipline off the
        # arrays are zero-size and the block compiles away entirely.
        if sync_on:
            dur_m = jnp.asarray(~vo)  # (U,) the durable-column mask
            # chaos windows (engine kinds 251-254): per-node flags,
            # args[0] = node, -1 = every node
            sel_n = (node_ids == a0) | (a0 < jnp.int32(0))
            # args[1] picks the window mode: 0 = silent lie (the
            # historical default, so pre-EIO plans are bit-identical),
            # 1 = observable EIO (ctx.sync_err). SYNC_OK ends both.
            eio_mode = a1 == jnp.int32(1)
            sl_on = dispatch & (kind == KIND_SYNC_LOSS) & ~eio_mode
            ei_on = dispatch & (kind == KIND_SYNC_LOSS) & eio_mode
            sl_off = dispatch & (kind == KIND_SYNC_OK)
            sync_loss = jnp.where(
                sl_on & sel_n, True,
                jnp.where(sl_off & sel_n, False, st.sync_loss),
            )
            sync_eio = jnp.where(
                ei_on & sel_n, True,
                jnp.where(sl_off & sel_n, False, st.sync_eio),
            )
            tn_on = dispatch & (kind == KIND_TORN_ON)
            tn_off = dispatch & (kind == KIND_TORN_OFF)
            torn = jnp.where(
                tn_on & sel_n, True,
                jnp.where(tn_off & sel_n, False, st.torn),
            )
            # the LAST durable write: this dispatch's changed durable
            # columns REPLACE the node's mask (earlier unsynced writes
            # are wholly lost on a crash; only the newest one tears)
            changed = (row != state_row) & dur_m  # (U,)
            wrote = user_dispatch & jnp.any(changed)
            wmask = jnp.where(
                (dst_oh & wrote)[:, None], changed[None, :], st.wmask
            )
            # sync commit: honored unless the node's disk is lying or
            # failing (EIO). Either failure is total — no commit, no
            # wmask clear: the write stays uncommitted and the next
            # kill still loses/tears it. The difference is upstream:
            # an EIO window also showed the handler ctx.sync_err.
            failing = sync_loss | sync_eio
            if dense:
                lying = jnp.any(failing & dst_oh)
            else:
                lying = failing[dst_c] & in_range
            do_sync = user_dispatch & uem.sync & ~lying
            sync_lied = user_dispatch & uem.sync & lying
            commit_sel = (dst_oh & do_sync)[:, None] & dur_m[None, :]
            disk = jnp.where(commit_sel, node_state, st.disk)
            wmask = jnp.where((dst_oh & do_sync)[:, None], False, wmask)
            # crash: durable columns revert to the synced image; an
            # armed torn mode persists rank < keep_cnt columns (column
            # order) of the last uncommitted write on top of it
            torn_bits = lane0[i_torn]  # the PURPOSE_TORN lane of the block
            n_dirty = jnp.sum(wmask.astype(jnp.int32), axis=1)  # (N,)
            rank = jnp.cumsum(wmask.astype(jnp.int32), axis=1) - 1
            keep_cnt = (
                torn_bits % (n_dirty + 1).astype(jnp.uint32)
            ).astype(jnp.int32)
            torn_keep = wmask & torn[:, None] & (rank < keep_cnt[:, None])
            crash_val = jnp.where(torn_keep, node_state, disk)
            crash_sel = is_killed[:, None] & dur_m[None, :]
            tore = jnp.any(is_killed & torn)
            node_state = jnp.where(crash_sel, crash_val, node_state)
            disk = jnp.where(crash_sel, crash_val, disk)
            wmask = jnp.where(is_killed[:, None], False, wmask)
        else:
            disk, wmask = st.disk, st.wmask
            sync_loss, sync_eio, torn = st.sync_loss, st.sync_eio, st.torn
            do_sync = sync_lied = tore = jnp.asarray(False)

        halted = st.halted | (dispatch & (kind == KIND_HALT)) | (has_event & over_limit)
        halt_time = jnp.where(
            (halted & ~st.halted), jnp.minimum(now, time_limit), st.halt_time
        )

        # ---- translate emits into pool insertions ----
        # user emits are suppressed for engine kinds (the clamped switch
        # ran *some* user branch); the reborn node's re-init event
        # (task.rs:279-291) rides an appended timer row — timers never
        # read their slot's latency/loss draws, so the extra slot is
        # trace-neutral
        restart_row = kind == KIND_RESTART
        # user emit rows are also dropped for suppressed army rows (the
        # retry no-op rule); without a policy this is exactly ~is_engine
        user_row_ok = (~is_engine & ~rt_suppress) if rt_c else ~is_engine
        em = Emits(
            valid=jnp.concatenate([uem.valid & user_row_ok, restart_row[None]]),
            send=jnp.concatenate([uem.send, jnp.zeros((1,), jnp.bool_)]),
            kind=jnp.concatenate(
                [uem.kind, jnp.full((1,), FIRST_USER_KIND, jnp.int32)]
            ),
            dst=jnp.concatenate([uem.dst, a0[None]]),
            delay=jnp.concatenate([uem.delay, jnp.zeros((1,), jnp.int64)]),
            args=jnp.concatenate([uem.args, jnp.zeros((1, aw), jnp.int32)]),
            pay=jnp.concatenate([uem.pay, jnp.zeros((1, w), jnp.int32)]),
            rec_valid=uem.rec_valid,  # records never ride the restart row
            rec=uem.rec,
        )
        # one threefry block per emit slot: lane 0 = latency, lane 1 =
        # loss — the emit slices of the per-dispatch batched block
        # (Draw.block2 above), bit-identical to the retired per-slot
        # vmapped cipher. Under dup_rows, K shadow rows follow the
        # restart row: copies of the user send slots, valid only while
        # the seed's dup flag is on, drawing an INDEPENDENT latency/loss
        # pair at the PURPOSE_DUP+slot lane — the duplicated delivery
        # arrives at its own time and is lost on its own coin, exactly
        # like a real duplicate in flight.
        if dup_rows:
            dvalid = uem.valid & user_row_ok & uem.send & st.dup
            em = Emits(
                valid=jnp.concatenate([em.valid, dvalid]),
                send=jnp.concatenate([em.send, uem.send]),
                kind=jnp.concatenate([em.kind, uem.kind]),
                dst=jnp.concatenate([em.dst, uem.dst]),
                delay=jnp.concatenate([em.delay, uem.delay]),
                args=jnp.concatenate([em.args, uem.args]),
                pay=jnp.concatenate([em.pay, uem.pay]),
                rec_valid=em.rec_valid,
                rec=em.rec,
            )
        lat_bits = lane0[1 : 1 + n_em_lanes]
        loss_bits = lane1[1 : 1 + n_em_lanes]
        if rt_c:
            # the armed re-send: ONE timer row per delivered army
            # attempt, appended last — the next attempt's token at
            # now + timeout + backoff + jitter, addressed to the army
            # node on the army kind. A timer (send=False) never reads
            # its latency/loss lane, draws no loss coin and rides the
            # standard epoch copy (a pending retry dies with its client
            # incarnation, exactly like any other timer). Backoff is an
            # unrolled table select — no gathers, layout-identical.
            rt_next = rt_att + jnp.int32(1)
            rt_boff_t = jnp.int64(0)
            rt_bjit_t = jnp.int64(0)
            for a in range(1, int(rt_max) + 1):
                rt_boff_t = jnp.where(
                    rt_next == a, jnp.int64(rt_boff[a]), rt_boff_t
                )
                rt_bjit_t = jnp.where(
                    rt_next == a, jnp.int64(rt_bjit[a]), rt_bjit_t
                )
            # jitter scales the capped max addend by a uint32 draw:
            # (cap * draw) >> 32 is exact integer arithmetic inside
            # int64 (the _RETRY_BACKOFF_CAP bound)
            rt_jit = (
                rt_bjit_t * lane0[i_retry].astype(jnp.int64)
            ) >> jnp.int64(32)
            rt_delay = jnp.int64(rt_timeout) + rt_boff_t + rt_jit
            rt_new_tok = (rt_tok & jnp.int32(RETRY_OP_MASK)) | (
                rt_next << jnp.int32(RETRY_ATTEMPT_SHIFT)
            )
            rt_args_row = jnp.where(
                jnp.arange(aw, dtype=jnp.int32) == 0, rt_new_tok, args
            )
            em = Emits(
                valid=jnp.concatenate([em.valid, rt_arm[None]]),
                send=jnp.concatenate([em.send, jnp.zeros((1,), jnp.bool_)]),
                kind=jnp.concatenate(
                    [em.kind, jnp.full((1,), int(rt_kind), jnp.int32)]
                ),
                dst=jnp.concatenate(
                    [em.dst, jnp.full((1,), int(rt_node), jnp.int32)]
                ),
                delay=jnp.concatenate([em.delay, rt_delay[None]]),
                args=jnp.concatenate([em.args, rt_args_row[None, :]]),
                pay=jnp.concatenate([em.pay, jnp.zeros((1, w), jnp.int32)]),
                rec_valid=em.rec_valid,
                rec=em.rec,
            )
            # keep the row/lane axes aligned: the timer row's lane is
            # never read (send=False), a zero entry suffices
            rt_zlane = jnp.zeros((1,), jnp.uint32)
            lat_bits = jnp.concatenate([lat_bits, rt_zlane])
            loss_bits = jnp.concatenate([loss_bits, rt_zlane])
        span = jnp.uint32(max(cfg.lat_max_ns - cfg.lat_min_ns, 1))
        if time32:  # same value, native width (lat_max fits by eligibility)
            latency = jnp.int32(cfg.lat_min_ns) + (lat_bits % span).astype(jnp.int32)
        else:
            latency = jnp.int64(cfg.lat_min_ns) + (lat_bits % span).astype(jnp.int64)
        # loss_u32 == 2^32 is the static always-drop path (loss_p=1.0);
        # a uint32 compare can't express it (chance_threshold contract)
        if loss_u32 >= (1 << 32):
            lost = em.send
        else:
            lost = em.send & (loss_bits < jnp.uint32(loss_u32))

        e_valid = dispatch & em.valid & ~lost
        if time32:
            # runtime backstop for the declared delay_bound_ns: a timer
            # past the int32 horizon would corrupt the offset form, so
            # it is clamped (to the max offset eligibility allows — the
            # sentinel/rebase headroom) and counted as an overflow
            # (loud — bench refuses any run with a nonzero overflow)
            lim32 = _T32_LIMIT - cfg.proc_max_ns - 1
            delay_over = e_valid & ~em.send & (em.delay > jnp.int64(lim32))
            n_delay_over = jnp.sum(delay_over).astype(jnp.int32)
            delay_t = jnp.minimum(em.delay, jnp.int64(lim32)).astype(
                jnp.int32
            )
        else:
            n_delay_over = jnp.int32(0)
            delay_t = em.delay
        # sends to dead nodes are dropped at send time (socket gone,
        # network.rs:311-313); timers to dead nodes die via the epoch gate
        if dense:
            emit_dst_oh = em.dst[:, None] == node_ids[None, :]  # (K+1, N)
            alive_at_dst = jnp.any(alive[None, :] & emit_dst_oh, axis=1)
            e_epoch = jnp.sum(
                jnp.where(emit_dst_oh, epoch[None, :], 0), axis=1
            ).astype(jnp.int32)
        else:
            em_in_range = (em.dst >= 0) & (em.dst < n)
            em_dst_c = jnp.clip(em.dst, 0, n - 1)
            alive_at_dst = alive[em_dst_c] & em_in_range
            e_epoch = jnp.where(em_in_range, epoch[em_dst_c], 0)
        e_valid = e_valid & jnp.where(em.send, alive_at_dst, True)
        # gray-failure latency multiplier: each send's latency scales by
        # slow[sender, dst] (post-effect, like the alive gate). mult==1
        # takes the untouched draw, so plan-free traces are unchanged.
        if dense:
            sender_slow = jnp.sum(
                jnp.where(dst_oh[:, None], slow, 0), axis=0
            ).astype(jnp.int32)  # (N,) the dispatching node's slow row
            emit_mult = jnp.sum(
                jnp.where(emit_dst_oh, sender_slow[None, :], 0), axis=1
            ).astype(jnp.int32)
        else:
            emit_mult = jnp.where(
                in_range & em_in_range, slow[dst_c, em_dst_c], 1
            )
        emit_mult = jnp.maximum(emit_mult, 1)
        lat_scaled = latency.astype(jnp.int64) * emit_mult.astype(jnp.int64)
        if time32:
            # clamp to the offset horizon (the delay-over rule applied to
            # latency): a pathological multiplier saturates loudly-visibly
            # late rather than corrupting the int32 offset form
            lat_scaled = jnp.minimum(
                lat_scaled, jnp.int64(_T32_LIMIT - cfg.proc_max_ns - 1)
            ).astype(jnp.int32)
        latency = jnp.where(emit_mult > 1, lat_scaled.astype(latency.dtype), latency)
        if time32:
            # offsets are relative to the post-step clock, which is
            # exactly now_after — no addition needed at all
            e_time = jnp.where(em.send, latency, delay_t)
        else:
            e_time = now_after + jnp.where(em.send, latency, delay_t)
        e_src = jnp.where(em.send, dst, jnp.int32(-1))
        # engine-kind events bypass the epoch gate; keep their slot epoch 0
        e_epoch = jnp.where(
            (em.kind < FIRST_USER_KIND) | (em.kind >= FIRST_EXT_KIND),
            0,
            e_epoch,
        )
        # pack the four small fields into the meta word (layout at top of
        # file); kind/dst clip to the byte ranges — out-of-range values
        # already matched nothing downstream, and clipping keeps them
        # matching nothing
        # negative kinds were engine kinds matching no KIND_* constant
        # (a no-op); map them to KIND_NOP, the in-byte value with that
        # exact behavior. Kinds > 255 already dispatched the clamped
        # last user handler and still do at 255 (the handler-count
        # guard keeps 255 above every valid kind). For these two
        # out-of-contract inputs only, the *trace* records the mapped
        # kind rather than the raw one
        e_meta = _meta_pack(
            jnp.where(em.kind < 0, KIND_NOP, jnp.minimum(em.kind, 255)),
            jnp.clip(em.dst, -1, n) + 1,
            jnp.clip(e_src, -1, n) + 1,
            jnp.zeros((em.kind.shape[0],), jnp.int32),
        )

        # compact placement: the j-th *valid* emit takes the j-th free
        # slot (pool order), so sparse emit patterns (gated `when` rows)
        # don't waste slots and only a genuinely full pool drops events.
        pos = jnp.cumsum(e_valid.astype(jnp.int32)) - 1
        msg_count = st.msg_count + jnp.sum(
            dispatch & em.valid & em.send
        ).astype(jnp.int64)
        # user slots + the restart row (+ the dup shadow rows when compiled)
        k1 = int(em.valid.shape[0])

        if dense:
            # slot j's rank among free slots must equal the emit's rank
            # among valid emits — an (E, K+1) match instead of a
            # flatnonzero + scatter (see the scatter note above)
            free_rank = jnp.cumsum(~ev_valid_mid) - 1
            n_free = jnp.sum((~ev_valid_mid).astype(jnp.int32))
            dropped = e_valid & (pos >= n_free)
            overflow = st.overflow + jnp.sum(dropped).astype(jnp.int32) + n_delay_over

            match = (
                (~ev_valid_mid)[:, None]
                & e_valid[None, :]
                & (free_rank[:, None] == pos[None, :])
            )  # (E, K+1); at most one emit matches any slot
            match_any = jnp.any(match, axis=1)

            def place(vals, mid):
                """Write each matched emit's value into its slot."""
                extra = vals.ndim - 1
                m = match.reshape(match.shape + (1,) * extra)
                picked = jnp.sum(
                    jnp.where(m, vals[None], 0), axis=1
                ).astype(vals.dtype)
                keep = match_any.reshape((-1,) + (1,) * extra)
                return jnp.where(keep, picked, mid)

            ev_valid = ev_valid_mid | match_any
            ev_time = place(e_time, ev_time_mid)
            ev_meta = place(e_meta, ev_meta_mid)
            ev_epoch = place(e_epoch, st.ev_epoch)
            ev_args = place(em.args, st.ev_args)
            ev_pay = place(em.pay, st.ev_pay)
            if timeline_cap:
                # every inserted event was emitted at this dispatch's
                # clock; rescheduled (clog-held) rows keep their
                # original emit time — a retry is not a new send
                ev_emit = place(
                    jnp.broadcast_to(now, (k1,)), st.ev_emit
                )
            else:
                ev_emit = st.ev_emit
            if causal:
                # every inserted event's parent is THIS dispatch; a
                # rescheduled row keeps its original parent (a retry is
                # not a new derivation — the emit-time rule again)
                ev_parent = place(
                    jnp.broadcast_to(seq_i32, (k1,)), st.ev_parent
                )
                ev_lam = place(
                    jnp.broadcast_to(lam_new, (k1,)), st.ev_lam
                )
            else:
                ev_parent, ev_lam = st.ev_parent, st.ev_lam
        elif rank_place and not pool_index:
            # rank-matched vector placement: the free slots are the
            # ready-to-receive partition of the pool, ranked in slot
            # order by one cumsum; the j-th valid emit pairs with the
            # j-th free slot exactly like the scatter store and the
            # dense match matrix. Each pool column then updates through
            # a statically-unrolled chain of masked selects — one
            # branchless compare+select per emit row, fused by XLA into
            # a single vector pass per column. No scatters (XLA CPU
            # lowers batched scatter to a serial per-row loop) and no
            # gathers (a batched gather is nearly as serial — the
            # gather-based first cut of this path measured 2.7 µs per
            # seed-step in ONE fusion, half the whole step wall,
            # PROFILE_CPU_r06): not-yet-due rows stream through the
            # selects untouched.
            free_rank = jnp.cumsum((~ev_valid_mid).astype(jnp.int32)) - 1
            n_free = free_rank[-1] + 1
            n_valid_em = jnp.sum(e_valid.astype(jnp.int32))
            dropped = e_valid & (pos >= n_free)
            overflow = st.overflow + jnp.sum(dropped).astype(jnp.int32) + n_delay_over
            place_free = ~ev_valid_mid
            take = place_free & (free_rank < n_valid_em)
            # Materialize the emit rows ONCE before the per-slot select
            # chains (see _materialize: XLA would otherwise recompute
            # the branch select per pool slot). Identity on values.
            e_time, e_meta, e_epoch, em_args_m, em_pay_m = _materialize(
                (e_time, e_meta, e_epoch, em.args, em.pay)
            )
            # slot e takes emit j iff j is valid and e is the free slot
            # whose rank equals j's emit rank — at most one j matches
            sel_rows = [
                place_free & e_valid[j] & (free_rank == pos[j])
                for j in range(k1)
            ]

            def rplace(vals, keep):
                """Each ready slot takes its rank-matched emit's value."""
                extra = vals.ndim - 1
                acc = keep
                for j in range(k1):
                    s = sel_rows[j].reshape((-1,) + (1,) * extra)
                    acc = jnp.where(s, vals[j], acc)
                return acc.astype(keep.dtype)

            ev_valid = ev_valid_mid | take
            ev_time = rplace(e_time, ev_time_mid)
            ev_meta = rplace(e_meta, ev_meta_mid)
            ev_epoch = rplace(e_epoch, st.ev_epoch)
            ev_args = rplace(em_args_m, st.ev_args)
            ev_pay = rplace(em_pay_m, st.ev_pay)
            if timeline_cap:
                # all emit rows share this dispatch's clock (the rule
                # in the dense branch above) — a plain masked select
                ev_emit = jnp.where(take, now, st.ev_emit)
            else:
                ev_emit = st.ev_emit
            if causal:
                # all emit rows share this dispatch's seq + clock (the
                # dense-branch rule) — plain masked selects
                ev_parent = jnp.where(take, seq_i32, st.ev_parent)
                ev_lam = jnp.where(take, lam_new, st.ev_lam)
            else:
                ev_parent, ev_lam = st.ev_parent, st.ev_lam
        else:
            if pool_index:
                # readiness-index free search, O(E/T + T + emits): the
                # j-th valid emit still takes the j-th free slot in
                # pool order (the flatnonzero contract, bit-for-bit) —
                # but the rank is resolved through the carried per-tile
                # counts: a cumsum over E/T free counts locates each
                # emit's target TILE (searchsorted over the exclusive
                # ranks), and one (k1, T) row gather + rank match finds
                # the slot inside it. No O(E) flatnonzero pass.
                free_tiles = jnp.int32(p_tile) - tile_cnt_mid
                cum_incl = jnp.cumsum(free_tiles)
                n_free = cum_incl[n_tiles - 1]
                cum_excl = cum_incl - free_tiles
                dropped = e_valid & (pos >= n_free)
                overflow = (
                    st.overflow + jnp.sum(dropped).astype(jnp.int32)
                    + n_delay_over
                )
                placed = e_valid & ~dropped
                # tile of the pos[j]-th free slot: the last tile whose
                # exclusive cumulative free count is <= pos[j]
                tj = jnp.clip(
                    jnp.searchsorted(cum_excl, pos, side="right").astype(
                        jnp.int32
                    )
                    - 1,
                    0,
                    n_tiles - 1,
                )
                loc_rank = pos - cum_excl[tj]
                fv_rows = (~ev_valid_mid).reshape(n_tiles, p_tile)[tj]
                frank = jnp.cumsum(fv_rows.astype(jnp.int32), axis=1) - 1
                # distinct emits have distinct global ranks, so their
                # (tile, local-rank) pairs are distinct — the match
                # one-hots are disjoint and need no sequential chain
                match = fv_rows & (frank == loc_rank[:, None]) & placed[:, None]
                lj = jnp.sum(
                    jnp.where(
                        match,
                        jnp.arange(p_tile, dtype=jnp.int32)[None, :],
                        0,
                    ),
                    axis=1,
                )
                slot = jnp.where(
                    placed, tj * p_tile + lj, jnp.int32(e_slots)
                )
            else:
                free = jnp.flatnonzero(
                    ~ev_valid_mid, size=k1, fill_value=e_slots
                )
                slot = jnp.where(
                    e_valid, free[jnp.clip(pos, 0, k1 - 1)], jnp.int32(e_slots)
                )
                dropped = e_valid & (slot >= e_slots)
                overflow = (
                    st.overflow + jnp.sum(dropped).astype(jnp.int32)
                    + n_delay_over
                )
            if pool_index and rank_place:
                # the within-tile select-chain write lowering (the
                # PR-8 rank placement confined to each emit's target
                # tile): per emit, gather the T-wide tile row, select
                # the matched slot branchlessly, store the row back.
                # Scatter-free in the ELEMENT sense but still one
                # dynamic row store per emit per column — the
                # interleaved A/B (SCALING.md round 9) measures it
                # against the element stores below; element stores won
                # on CPU, so "scatter" is the default under the index.
                emt, emm, eme, ema, emp = _materialize(
                    (e_time, e_meta, e_epoch, em.args, em.pay)
                )
                v2 = ev_valid_mid.reshape(n_tiles, p_tile)
                t2 = ev_time_mid.reshape(n_tiles, p_tile)
                m2 = ev_meta_mid.reshape(n_tiles, p_tile)
                ep2 = st.ev_epoch.reshape(n_tiles, p_tile)
                a2 = st.ev_args.reshape(n_tiles, p_tile, aw)
                p2 = st.ev_pay.reshape(n_tiles, p_tile, w)
                e2 = (
                    st.ev_emit.reshape(n_tiles, p_tile)
                    if timeline_cap else None
                )
                pa2 = (
                    st.ev_parent.reshape(n_tiles, p_tile)
                    if causal else None
                )
                pl2 = (
                    st.ev_lam.reshape(n_tiles, p_tile)
                    if causal else None
                )
                for j in range(k1):

                    def upd(arr2, val, _s=match[j], _t=tj[j]):
                        row = arr2[_t]
                        m = _s.reshape((p_tile,) + (1,) * (row.ndim - 1))
                        return arr2.at[_t].set(
                            jnp.where(m, val, row).astype(arr2.dtype)
                        )

                    v2 = upd(v2, True)
                    t2 = upd(t2, emt[j])
                    m2 = upd(m2, emm[j])
                    ep2 = upd(ep2, eme[j])
                    a2 = upd(a2, ema[j])
                    p2 = upd(p2, emp[j])
                    if timeline_cap:
                        e2 = upd(e2, now)
                    if causal:
                        pa2 = upd(pa2, seq_i32)
                        pl2 = upd(pl2, lam_new)
                ev_valid = v2.reshape(e_slots)
                ev_time = t2.reshape(e_slots)
                ev_meta = m2.reshape(e_slots)
                ev_epoch = ep2.reshape(e_slots)
                ev_args = a2.reshape(e_slots, aw)
                ev_pay = p2.reshape(e_slots, w)
                ev_emit = (
                    e2.reshape(e_slots) if timeline_cap else st.ev_emit
                )
                if causal:
                    ev_parent = pa2.reshape(e_slots)
                    ev_lam = pl2.reshape(e_slots)
                else:
                    ev_parent, ev_lam = st.ev_parent, st.ev_lam
            else:
                ev_valid = ev_valid_mid.at[slot].set(e_valid, mode="drop")
                ev_time = ev_time_mid.at[slot].set(e_time, mode="drop")
                ev_meta = ev_meta_mid.at[slot].set(e_meta, mode="drop")
                ev_epoch = st.ev_epoch.at[slot].set(e_epoch, mode="drop")
                ev_args = st.ev_args.at[slot].set(em.args, mode="drop")
                ev_pay = st.ev_pay.at[slot].set(em.pay, mode="drop")
                if timeline_cap:
                    ev_emit = st.ev_emit.at[slot].set(
                        jnp.broadcast_to(now, (k1,)), mode="drop"
                    )
                else:
                    ev_emit = st.ev_emit
                if causal:
                    ev_parent = st.ev_parent.at[slot].set(
                        jnp.broadcast_to(seq_i32, (k1,)), mode="drop"
                    )
                    ev_lam = st.ev_lam.at[slot].set(
                        jnp.broadcast_to(lam_new, (k1,)), mode="drop"
                    )
                else:
                    ev_parent, ev_lam = st.ev_parent, st.ev_lam
            if pool_index:
                # index maintenance, part 2: fold the insertions into
                # their tiles' summaries (<= k1 scatter-min/add rows),
                # then recompute the popped tile EXACTLY from the
                # final pool rows: the consume can RAISE its minimum,
                # which no incremental min can express, and the
                # .at[wtile].set override also covers any insertion
                # that landed there (set runs after the fold).
                ins_tile = jnp.where(placed, tj, jnp.int32(n_tiles))
                tile_cnt2 = tile_cnt_mid.at[ins_tile].add(1, mode="drop")
                # mask EMPTY tiles back to the +inf sentinel before
                # folding inserts: under time32 the per-step rebase
                # decays every carried value — including the sentinel
                # of a tile that has sat empty — so after ~2.1 sim
                # seconds an unmasked min() against it would pin a
                # freshly filled tile's minimum below its true value
                # and silently pop the wrong event. The pop masks by
                # tile_cnt at ITS use; this is the other use and needs
                # the same mask (tests/test_pool_index.py
                # test_time32_empty_tile_sentinel_decay is the repro).
                tile_min2 = jnp.where(
                    tile_cnt_mid > 0, tile_min_mid, t_inf
                ).at[ins_tile].min(e_time, mode="drop")
                fin_v = ev_valid.reshape(n_tiles, p_tile)[wtile]
                fin_t = ev_time.reshape(n_tiles, p_tile)[wtile]
                tile_min_out = tile_min2.at[wtile].set(
                    jnp.min(jnp.where(fin_v, fin_t, t_inf))
                )
                tile_cnt_out = tile_cnt2.at[wtile].set(
                    jnp.sum(fin_v.astype(jnp.int32))
                )

        if not pool_index:
            n_tiles_in = st.tile_cnt.shape[0]
            if n_tiles_in:
                # index-preserving off-step: this build does not USE
                # the index, but the state carries summaries (e.g. an
                # auto-indexed CPU init feeding a forced dense run, or
                # an indexed checkpoint resumed flat) — rebuild them
                # exactly from the final pool so they can never go
                # stale and poison a later indexed step. One fused
                # O(E) reduce, the same cost class as the flat pop
                # this build already pays.
                tile_min_out, tile_cnt_out = build_pool_index(
                    ev_time, ev_valid, e_slots // n_tiles_in
                )
            else:
                tile_min_out, tile_cnt_out = st.tile_min, st.tile_cnt

        # ---- operation-history append (madsim_tpu.check) ----
        # the j-th valid record takes slot hist_count+j: same compact
        # cumsum placement as the event pool, same dense/scatter duality
        # (values identical either way), no RNG draws — so traces and
        # every existing workload are byte-identical with recording off.
        # A full buffer drops records LOUDLY: hist_drop is the visible
        # overflow flag the checkers (and search_seeds) refuse.
        if hcap > 0:
            r_valid = user_dispatch & uem.rec_valid
            rpos = st.hist_count + jnp.cumsum(r_valid.astype(jnp.int32)) - 1
            fits = rpos < hcap
            keep = r_valid & fits
            # row layout [op, key, arg, client, ok]: client = the node
            # whose handler recorded it, time = the dispatch clock
            rec_client = jnp.broadcast_to(dst, (rr,)).astype(jnp.int32)
            rec_row = jnp.concatenate(
                [uem.rec[:, :3], rec_client[:, None], uem.rec[:, 3:4]],
                axis=1,
            )
            rec_t = jnp.broadcast_to(now, (rr,))
            if dense:
                hist_ids = jnp.arange(hcap, dtype=jnp.int32)
                hmatch = keep[None, :] & (hist_ids[:, None] == rpos[None, :])
                hany = jnp.any(hmatch, axis=1)
                picked = jnp.sum(
                    jnp.where(hmatch[:, :, None], rec_row[None], 0), axis=1
                ).astype(jnp.int32)
                hist_word = jnp.where(hany[:, None], picked, st.hist_word)
                picked_t = jnp.sum(jnp.where(hmatch, rec_t[None], 0), axis=1)
                hist_t = jnp.where(hany, picked_t, st.hist_t)
            elif rank_place:
                # rank-matched cold-bank append: slot hist_count + r
                # takes the r-th KEPT record (drops are a suffix —
                # rpos is nondecreasing, so `fits` is a prefix
                # property and kept ranks stay contiguous). Same
                # unrolled select-chain form as the pool placement —
                # no scatter, no gather.
                n_keep = jnp.sum(keep).astype(jnp.int32)
                rel = jnp.cumsum(r_valid.astype(jnp.int32)) - 1
                hranks = jnp.arange(hcap, dtype=jnp.int32) - st.hist_count
                hist_word = st.hist_word
                for j in range(rr):
                    sel_h = keep[j] & (hranks == rel[j])
                    hist_word = jnp.where(
                        sel_h[:, None], rec_row[j], hist_word
                    )
                take_h = (hranks >= 0) & (hranks < n_keep)
                hist_t = jnp.where(take_h, now, st.hist_t)
            else:
                hslot = jnp.where(keep, rpos, jnp.int32(hcap))
                hist_word = st.hist_word.at[hslot].set(rec_row, mode="drop")
                hist_t = st.hist_t.at[hslot].set(rec_t, mode="drop")
            hist_count = st.hist_count + jnp.sum(keep).astype(jnp.int32)
            hist_drop = st.hist_drop + jnp.sum(r_valid & ~fits).astype(
                jnp.int32
            )
        else:
            hist_count, hist_drop = st.hist_count, st.hist_drop
            hist_word, hist_t = st.hist_word, st.hist_t

        # ---- tail-latency tap (madsim_tpu.obs latency) ----
        # derived state only, the cov_words discipline: handler markers
        # stamp per-op invoke/response clocks and fold completed ops
        # into the per-seed log-linear sketch. Marker slots are few
        # (L ~= 1-2), so each is handled by its own masked write — a
        # static unroll, the same arithmetic in both layouts. Nothing
        # here is ever read back by the trajectory, the RNG or the
        # trace, so latency=None runs are bit-identical.
        lat_feats = []  # (feature, on) pairs for the coverage fold
        if lat_c and not _lat_export:
            lat_inv, lat_resp = st.lat_inv, st.lat_resp
            lat_hist = st.lat_hist
            lat_count, lat_drop = st.lat_count, st.lat_drop
            lat_edges = jnp.asarray(LAT_EDGES_NS)
            lat_ids = jnp.arange(lat_c, dtype=jnp.int32)
            for j in range(ll):
                mv = user_dispatch & uem.lat_valid[j]
                oid = uem.lat[j, 0]
                is_end = uem.lat[j, 1] == jnp.int32(1)
                lat_in_r = (oid >= 0) & (oid < lat_c)
                lat_drop = lat_drop + (mv & ~lat_in_r).astype(jnp.int32)
                act = mv & lat_in_r
                if dense:
                    oid_oh = lat_ids == oid  # all-False when out of range
                    inv_o = jnp.sum(jnp.where(oid_oh, lat_inv, 0))
                    resp_o = jnp.sum(jnp.where(oid_oh, lat_resp, 0))
                else:
                    oc = jnp.clip(oid, 0, lat_c - 1)
                    inv_o = jnp.where(lat_in_r, lat_inv[oc], jnp.int64(-1))
                    resp_o = jnp.where(lat_in_r, lat_resp[oc], jnp.int64(-1))
                # first start / first response win: an open-loop army
                # invokes each id once, and a duplicated delivery's
                # second lat_end must not double-count
                do_start = act & ~is_end & (inv_o < 0)
                do_end = act & is_end & (inv_o >= 0) & (resp_o < 0)
                d = now - inv_o
                bkt = jnp.sum((d >= lat_edges).astype(jnp.int32))
                ph = jnp.clip(
                    (inv_o // jnp.int64(lat_phase_ns)).astype(jnp.int32),
                    0, lat_p - 1,
                )
                if dense:
                    lat_inv = jnp.where(oid_oh & do_start, now, lat_inv)
                    lat_resp = jnp.where(oid_oh & do_end, now, lat_resp)
                    hsel = (
                        (jnp.arange(lat_p, dtype=jnp.int32)[:, None] == ph)
                        & (jnp.arange(N_LAT_BUCKETS, dtype=jnp.int32)[None, :] == bkt)
                        & do_end
                    )
                    lat_hist = lat_hist + hsel.astype(jnp.int32)
                else:
                    lat_inv = lat_inv.at[
                        jnp.where(do_start, oc, jnp.int32(lat_c))
                    ].set(now, mode="drop")
                    lat_resp = lat_resp.at[
                        jnp.where(do_end, oc, jnp.int32(lat_c))
                    ].set(now, mode="drop")
                    lat_hist = lat_hist.at[
                        jnp.where(do_end, ph, jnp.int32(lat_p)), bkt
                    ].add(jnp.int32(1), mode="drop")
                lat_count = lat_count + do_end.astype(jnp.int32)
                # latency-bucket coverage feature: (window, bucket) —
                # a schedule that pushes ops into a new bucket of a new
                # window is NEW behavior, the guidance signal that lets
                # the hunt chase "blow the tail" (folded in the cov
                # block below, gated on cov_words like every feature)
                lat_feats.append((
                    bkt.astype(jnp.uint32)
                    | (ph.astype(jnp.uint32) << jnp.uint32(8))
                    | jnp.uint32(5 << 24),
                    do_end,
                ))
        else:
            lat_inv, lat_resp = st.lat_inv, st.lat_resp
            lat_hist = st.lat_hist
            lat_count, lat_drop = st.lat_count, st.lat_drop

        # ---- client-retry books (RetrySpec; CORE state — rt_done
        # gates the deliver/suppress decision above) ----
        if rt_c:
            # response bookkeeping: the model's lat_end marker for an op
            # (phase word 1) disarms its timer — first-response-wins,
            # the same discipline the latency tap applies, which is why
            # a retry build requires lat_markers. Markers carry the
            # STRIPPED op id (models strip attempt bits), so the slot
            # index is id - op_base whatever the delivered attempt was.
            rt_done = st.rt_done
            for j in range(ll):
                rt_mv = (
                    user_dispatch
                    & uem.lat_valid[j]
                    & (uem.lat[j, 1] == jnp.int32(1))
                )
                rt_done = rt_done | (
                    (rt_ids == (uem.lat[j, 0] - rt_base)) & rt_mv
                )
            # the delivered-attempt ledger and the armed deadline
            # (absolute ns even under time32 — forensics columns never
            # feed the pool clock)
            rt_attempt = jnp.where(rt_oh & rt_arm, rt_att, st.rt_attempt)
            rt_deadline = jnp.where(
                rt_oh & rt_arm, now_after + rt_delay, st.rt_deadline
            )
        else:
            rt_done = st.rt_done
            rt_attempt, rt_deadline = st.rt_attempt, st.rt_deadline

        # ---- coverage taps (madsim_tpu.explore) ----
        # derived state only: features of the event just dispatched are
        # hashed into an AFL-style bitmap. Nothing here feeds back into
        # the trajectory, the RNG, or the trace, so cov_words=0 (no
        # arrays, no ops) and cov_words>0 produce identical traces.
        if cov_words:
            cb_mask = jnp.uint32(cov_words * 32 - 1)
            cw_ids = jnp.arange(cov_words, dtype=jnp.uint32)

            def _cov_mix(x):
                # 32-bit finalizer (splitmix-style): pure uint32 ALU,
                # bit-identical across backends like everything else
                x = jnp.asarray(x).astype(jnp.uint32)
                x = (x ^ (x >> jnp.uint32(16))) * jnp.uint32(0x7FEB352D)
                x = (x ^ (x >> jnp.uint32(15))) * jnp.uint32(0x846CA68B)
                return x ^ (x >> jnp.uint32(16))

            def _cov_set(cov_acc, feat, on):
                bit = _cov_mix(feat) & cb_mask
                sel = cw_ids == (bit >> jnp.uint32(5))
                m = jnp.uint32(1) << (bit & jnp.uint32(31))
                return cov_acc | jnp.where(sel & on, m, jnp.uint32(0))

            if cov_hitcount:
                # AFL-style bucketing (madsim_tpu.obs): a saturating
                # per-seed counter per bitmap bit position; the bit a
                # feature sets is keyed by (feature, bucket class), so
                # crossing 1 -> 2 -> 4-7 -> ... occurrences keeps
                # setting fresh bits. Same dense/scatter duality and
                # derived-state-only rule as everything here.
                cb_n = cov_words * 32
                cb_ids = jnp.arange(cb_n, dtype=jnp.uint32)
                # AFL's class boundaries: 1,2,3,4-7,8-15,16-31,32-127,128+
                _cls_edges = (1, 2, 3, 4, 8, 16, 32, 128)

                def _tap(cov_acc, hits, feat, on):
                    ci = _cov_mix(feat) & jnp.uint32(cb_n - 1)
                    if dense:
                        cur = jnp.sum(
                            jnp.where(cb_ids == ci, hits, 0)
                        ).astype(jnp.int32)
                    else:
                        cur = hits[ci].astype(jnp.int32)
                    newc = jnp.minimum(cur + 1, 255)
                    cls = (
                        sum(
                            (newc >= t).astype(jnp.uint32)
                            for t in _cls_edges
                        )
                        - jnp.uint32(1)
                    )
                    if dense:
                        hits = jnp.where(
                            (cb_ids == ci) & on,
                            newc.astype(jnp.uint8),
                            hits,
                        )
                    else:
                        hits = hits.at[
                            jnp.where(on, ci, jnp.uint32(cb_n))
                        ].set(newc.astype(jnp.uint8), mode="drop")
                    feat2 = feat ^ (
                        (cls + jnp.uint32(1)) * jnp.uint32(0x9E3779B9)
                    )
                    return _cov_set(cov_acc, feat2, on), hits

            else:

                def _tap(cov_acc, hits, feat, on):
                    return _cov_set(cov_acc, feat, on), hits

            cov_hits = st.cov_hits
            # per-node event-kind transition pair (prev -> kind at dst)
            if dense:
                prev_kind = jnp.sum(
                    jnp.where(dst_oh, st.cov_last, 0)
                ).astype(jnp.int32)
            else:
                prev_kind = jnp.where(in_range, st.cov_last[dst_c], 0)
            f_user = (
                kind.astype(jnp.uint32)
                | (prev_kind.astype(jnp.uint32) << jnp.uint32(8))
                | (jnp.maximum(dst, 0).astype(jnp.uint32) << jnp.uint32(16))
            )
            cov, cov_hits = _tap(st.cov, cov_hits, f_user, user_dispatch)
            # coarse time phase (~134 ms buckets): behaviors that recur
            # in NEW phases are new bits, which keeps long/late
            # trajectories distinguishable from early ones
            phase = jnp.minimum(now >> jnp.int64(27), 31).astype(jnp.uint32)
            # engine/chaos kind x phase: crash/partition/heal phases of
            # an injected plan are coverage features, so a mutated
            # fault time that lands in a new phase is "interesting"
            # even before the protocol reacts
            f_chaos = (
                kind.astype(jnp.uint32)
                | (phase << jnp.uint32(8))
                | jnp.uint32(1 << 24)
            )
            cov, cov_hits = _tap(cov, cov_hits, f_chaos, dispatch & is_engine)
            # message edge (kind, src -> dst): which protocol messages
            # flowed between which nodes — partitions and gray failures
            # reshape exactly this
            f_edge = (
                kind.astype(jnp.uint32)
                | (jnp.maximum(src, 0).astype(jnp.uint32) << jnp.uint32(8))
                | (jnp.maximum(dst, 0).astype(jnp.uint32) << jnp.uint32(16))
                | jnp.uint32(3 << 24)
            )
            cov, cov_hits = _tap(cov, cov_hits, f_edge, user_dispatch & is_msg)
            # user kind x phase: WHEN the protocol did something, not
            # just that it did — a second election at 500 ms is a
            # different behavior than the first at 200 ms
            f_when = (
                kind.astype(jnp.uint32)
                | (phase << jnp.uint32(8))
                | jnp.uint32(4 << 24)
            )
            cov, cov_hits = _tap(cov, cov_hits, f_when, user_dispatch)
            if causal:
                # causal depth/width feature (tag 7): log2 bucket of the
                # folded Lamport clock x log2 bucket of the cross-node
                # causal JUMP (how far the arriving event's clock was
                # ahead of the node's own — a big jump is a long
                # causal chain crossing nodes). A schedule reaching a
                # new depth or jump class is new behavior the guided
                # hunt can chase — "deeper causality" as coverage.
                _pow2 = jnp.asarray(
                    np.power(2, np.arange(1, 32, dtype=np.uint64)).astype(
                        np.uint32
                    )
                )
                depth_b = jnp.sum((lam_new >= _pow2).astype(jnp.uint32))
                # int64 difference, clipped: the uint32 subtraction
                # would be a wrap surface when the node is AHEAD of the
                # arriving event (the common same-node case)
                jump = jnp.clip(
                    evlam_i.astype(jnp.int64) - lam_prev.astype(jnp.int64),
                    0,
                    None,
                ).astype(jnp.uint32)
                jump_b = jnp.sum((jump >= _pow2).astype(jnp.uint32))
                f_causal = (
                    depth_b
                    | (jump_b << jnp.uint32(8))
                    | jnp.uint32(7 << 24)
                )
                cov, cov_hits = _tap(cov, cov_hits, f_causal, dispatch)
            # appended history records: (op, key, arg, ok) words — term
            # bumps, elected leaders, committed (index, value) pairs
            for j in range(rr):
                f_rec = (
                    (uem.rec[j, 0].astype(jnp.uint32) * jnp.uint32(0x9E3779B1))
                    ^ (uem.rec[j, 1].astype(jnp.uint32) * jnp.uint32(0x85EBCA6B))
                    ^ (uem.rec[j, 2].astype(jnp.uint32) * jnp.uint32(0xC2B2AE35))
                    ^ uem.rec[j, 3].astype(jnp.uint32)
                    ^ jnp.uint32(2 << 24)
                )
                cov, cov_hits = _tap(
                    cov, cov_hits, f_rec, user_dispatch & uem.rec_valid[j]
                )
            # completed client-army ops: (measurement window, latency
            # bucket) features computed in the latency block above
            for f_lat, on_lat in lat_feats:
                cov, cov_hits = _tap(cov, cov_hits, f_lat, on_lat)
            # workload-contributed protocol features (Workload.
            # cov_features): post-dispatch fleet state -> (feature, on)
            # pairs, namespaced under tag 6 so they can never collide
            # with the engine's own feature families
            if wl.cov_features is not None:
                for f_wl, on_wl in wl.cov_features(node_state, now):
                    # mask to the 24-bit feature payload BEFORE tagging:
                    # a hook word with high bits set must alias other
                    # tag-6 features, never another family's namespace
                    f_wl = (
                        jnp.asarray(f_wl).astype(jnp.uint32)
                        & jnp.uint32((1 << 24) - 1)
                    ) | jnp.uint32(6 << 24)
                    cov, cov_hits = _tap(
                        cov, cov_hits, f_wl, user_dispatch & on_wl
                    )
            if dense or rank_place:
                cov_last = jnp.where(
                    dst_oh & user_dispatch, kind, st.cov_last
                ).astype(jnp.int32)
            else:
                cov_last = st.cov_last.at[
                    jnp.where(in_range & user_dispatch, dst_c, jnp.int32(n))
                ].set(kind, mode="drop")
        else:
            cov, cov_last, cov_hits = st.cov, st.cov_last, st.cov_hits

        # ---- fleet metrics (madsim_tpu.obs) ----
        # every operand below is a value the step already computed, and
        # nothing written here is ever read by the trajectory — the
        # derived-state-only rule the obs-off identity test pins.
        if metrics:
            i32 = lambda b: jnp.sum(b).astype(jnp.int32)  # noqa: E731
            sent_m = dispatch & em.valid & em.send
            inc = [jnp.int32(0)] * N_METRICS
            inc[MET_SENT] = i32(sent_m)
            inc[MET_DELIVERED] = (dispatch & is_msg).astype(jnp.int32)
            inc[MET_LOST] = i32(sent_m & lost)
            inc[MET_DEAD_DROP] = i32(sent_m & ~lost & ~alive_at_dst)
            if dup_rows:
                # shadow rows are the K emit slots after the restart row
                # (the retry timer row, when compiled, follows them)
                inc[MET_DUP] = i32(e_valid[k + 1 : 2 * k + 1])
            inc[MET_CRASH] = (dispatch & (kind == KIND_KILL)).astype(jnp.int32)
            inc[MET_RESTART] = (
                dispatch & (kind == KIND_RESTART)
            ).astype(jnp.int32)
            inc[MET_PAUSE] = (dispatch & (kind == KIND_PAUSE)).astype(jnp.int32)
            inc[MET_CLOG_BLOCK] = (active & clogged).astype(jnp.int32)
            inc[MET_TIMER] = (user_dispatch & ~is_msg).astype(jnp.int32)
            if hcap > 0:
                inc[MET_RECORD] = i32(keep)
            # threefry blocks per active event step: the poll-cost/jitter
            # pair + one latency/loss block per emit slot (+ the dup
            # shadow slots when compiled, + the torn-prefix draw under
            # the sync discipline) — a static count, so this is
            # bookkeeping, not instrumentation of the RNG itself
            blocks = 1 + (k + 1) + (k if dup_rows else 0) + (
                1 if sync_on else 0
            ) + (1 if rt_c else 0)
            inc[MET_RNG] = jnp.where(active, jnp.int32(blocks), 0)
            if sync_on:
                inc[MET_SYNC] = do_sync.astype(jnp.int32)
                inc[MET_SYNC_LOST] = sync_lied.astype(jnp.int32)
                inc[MET_TORN] = tore.astype(jnp.int32)
            if rt_c:
                # a re-delivery = a DELIVERED army row past attempt 0; a
                # give-up = the max_attempts sentinel popping with the
                # op still unanswered. (A sentinel row that dies with a
                # killed client incarnation is an undercount — the epoch
                # gate drops it before the books see it.)
                inc[MET_RETRY] = (rt_arm & (rt_att > 0)).astype(jnp.int32)
                inc[MET_RETRY_GIVEUP] = (
                    is_army & ~rt_done_i & (rt_att == rt_max)
                ).astype(jnp.int32)
            met = st.met + jnp.stack(inc)
            new_halt = halted & ~st.halted
            code = jnp.where(
                dispatch & (kind == KIND_HALT),
                jnp.int32(HALT_DONE),
                jnp.int32(HALT_TIME_LIMIT),
            )
            cur_code = met[MET_HALT_CODE]
            # an empty pool on an unhalted seed is terminal (nothing
            # pending can create events): record it as a deadlock code
            idle = ~has_event & ~st.halted
            met = met.at[MET_HALT_CODE].set(
                jnp.where(
                    new_halt,
                    code,
                    jnp.where(
                        idle & (cur_code == HALT_RUNNING),
                        jnp.int32(HALT_IDLE),
                        cur_code,
                    ),
                )
            )
        else:
            met = st.met

        # ---- timeline ring (madsim_tpu.obs) ----
        # the dispatched-event stream, one row per dispatch: exactly the
        # (now, kind, node, src, args) tuple the trace hash folds, so a
        # decoded timeline refolds to the certified trace (obs.refold,
        # payload-free workloads). Same compact-append duality as the
        # history columns; a full ring counts drops loudly in tl_drop.
        if timeline_cap:
            tfits = st.tl_count < timeline_cap
            t_do = dispatch & tfits
            if dense:
                tl_ids = jnp.arange(timeline_cap, dtype=jnp.int32)
                t_sel = (tl_ids == st.tl_count) & t_do
                tl_t = jnp.where(t_sel, now, st.tl_t)
                tl_meta = jnp.where(t_sel, meta_i, st.tl_meta)
                tl_args = jnp.where(t_sel[:, None], args[None, :], st.tl_args)
                tl_pay = jnp.where(t_sel[:, None], pay_i[None, :], st.tl_pay)
                tl_emit = jnp.where(t_sel, emit_i, st.tl_emit)
                if causal:
                    tl_seq = jnp.where(t_sel, seq_i32, st.tl_seq)
                    tl_parent = jnp.where(t_sel, parent_i, st.tl_parent)
                    tl_lam = jnp.where(t_sel, lam_new, st.tl_lam)
            else:
                t_slot = jnp.where(t_do, st.tl_count, jnp.int32(timeline_cap))
                tl_t = st.tl_t.at[t_slot].set(now, mode="drop")
                tl_meta = st.tl_meta.at[t_slot].set(meta_i, mode="drop")
                tl_args = st.tl_args.at[t_slot].set(args, mode="drop")
                tl_pay = st.tl_pay.at[t_slot].set(pay_i, mode="drop")
                tl_emit = st.tl_emit.at[t_slot].set(emit_i, mode="drop")
                if causal:
                    tl_seq = st.tl_seq.at[t_slot].set(seq_i32, mode="drop")
                    tl_parent = st.tl_parent.at[t_slot].set(
                        parent_i, mode="drop"
                    )
                    tl_lam = st.tl_lam.at[t_slot].set(lam_new, mode="drop")
            if not causal:
                tl_seq, tl_parent, tl_lam = (
                    st.tl_seq, st.tl_parent, st.tl_lam
                )
            tl_count = st.tl_count + t_do.astype(jnp.int32)
            tl_drop = st.tl_drop + (dispatch & ~tfits).astype(jnp.int32)
        else:
            tl_count, tl_drop = st.tl_count, st.tl_drop
            tl_t, tl_meta, tl_args = st.tl_t, st.tl_meta, st.tl_args
            tl_pay, tl_emit = st.tl_pay, st.tl_emit
            tl_seq, tl_parent, tl_lam = st.tl_seq, st.tl_parent, st.tl_lam

        # ---- trace + clock ----
        trace = jnp.where(
            dispatch,
            _trace_fold(st.trace, now, kind, dst, args, pay_i),
            st.trace,
        )
        out = SimState(
            seed=st.seed,
            now=now_after,
            step=st.step + jnp.uint32(1),
            halted=halted,
            halt_time=halt_time,
            trace=trace,
            overflow=overflow,
            msg_count=msg_count,
            ev_time=ev_time,
            ev_valid=ev_valid,
            ev_meta=ev_meta,
            ev_epoch=ev_epoch,
            ev_args=ev_args,
            ev_pay=ev_pay,
            alive=alive,
            paused=paused,
            epoch=epoch,
            node_state=node_state,
            clog=clog,
            slow=slow,
            dup=dup,
            skew=skew,
            disk=disk,
            wmask=wmask,
            sync_loss=sync_loss,
            sync_eio=sync_eio,
            torn=torn,
            hist_count=hist_count,
            hist_drop=hist_drop,
            hist_word=hist_word,
            hist_t=hist_t,
            cov=cov,
            cov_last=cov_last,
            cov_hits=cov_hits,
            met=met,
            tl_count=tl_count,
            tl_drop=tl_drop,
            tl_t=tl_t,
            tl_meta=tl_meta,
            tl_args=tl_args,
            tl_pay=tl_pay,
            ev_emit=ev_emit,
            tl_emit=tl_emit,
            lam=lam,
            ev_parent=ev_parent,
            ev_lam=ev_lam,
            tl_seq=tl_seq,
            tl_parent=tl_parent,
            tl_lam=tl_lam,
            lat_inv=lat_inv,
            lat_resp=lat_resp,
            lat_hist=lat_hist,
            lat_count=lat_count,
            lat_drop=lat_drop,
            rt_done=rt_done,
            rt_attempt=rt_attempt,
            rt_deadline=rt_deadline,
            tile_min=tile_min_out,
            tile_cnt=tile_cnt_out,
        )
        if _lat_export:
            # cold/hot split: hand the raw markers of this dispatch to
            # the run builder — (valid (L,), (op, phase) rows (L, 2),
            # the dispatch clock). The cold (C,)-wide columns passed
            # through ``out`` untouched; the batch-level fold applies
            # them only on steps where some seed actually marked.
            return out, (user_dispatch & uem.lat_valid, uem.lat, now)
        return out

    return step


def _make_cold_lat_apply(latency: LatencySpec, ll: int):
    """Batch-level fold of exported latency markers onto the cold bank.

    The cold/hot split (``make_run(cold_split=True)``): per-seed steps
    export raw ``(valid, (op, phase), now)`` markers instead of folding
    them, and this function applies the EXACT in-step semantics —
    first start wins, first response wins, window = the invoke-time
    phase, out-of-range ids counted loudly — to the batched
    ``(S, C)``-wide columns at once. The run builder calls it under a
    ``lax.cond`` on "any seed marked this step", so the army's cold
    columns are read and written only on marker steps (they are
    otherwise not an operand of the scan body at all) — on CPU that
    skips the work, on TPU it skips the HBM traffic, and the values
    are bit-identical to the in-step tap by construction
    (tests/test_pool_index.py pins it).
    """
    lat_c = latency.ops
    lat_p = latency.phases
    phase_ns = latency.phase_ns
    edges = jnp.asarray(LAT_EDGES_NS)

    def apply(cold, markers):
        lat_inv, lat_resp, lat_hist, lat_count, lat_drop = cold
        mval, mops, mnow = markers  # (S, L) bool, (S, L, 2) i32, (S,) i64
        rows = jnp.arange(mnow.shape[0])
        for j in range(ll):
            mv = mval[:, j]
            oid = mops[:, j, 0]
            is_end = mops[:, j, 1] == jnp.int32(1)
            in_r = (oid >= 0) & (oid < lat_c)
            lat_drop = lat_drop + (mv & ~in_r).astype(jnp.int32)
            act = mv & in_r
            oc = jnp.clip(oid, 0, lat_c - 1)
            inv_o = jnp.where(in_r, lat_inv[rows, oc], jnp.int64(-1))
            resp_o = jnp.where(in_r, lat_resp[rows, oc], jnp.int64(-1))
            do_start = act & ~is_end & (inv_o < 0)
            do_end = act & is_end & (inv_o >= 0) & (resp_o < 0)
            d = mnow - inv_o
            bkt = jnp.sum(
                (d[:, None] >= edges[None, :]).astype(jnp.int32), axis=1
            )
            ph = jnp.clip(
                (inv_o // jnp.int64(phase_ns)).astype(jnp.int32),
                0, lat_p - 1,
            )
            lat_inv = lat_inv.at[
                rows, jnp.where(do_start, oc, jnp.int32(lat_c))
            ].set(mnow, mode="drop")
            lat_resp = lat_resp.at[
                rows, jnp.where(do_end, oc, jnp.int32(lat_c))
            ].set(mnow, mode="drop")
            lat_hist = lat_hist.at[
                rows, jnp.where(do_end, ph, jnp.int32(lat_p)), bkt
            ].add(jnp.int32(1), mode="drop")
            lat_count = lat_count + do_end.astype(jnp.int32)
        return (lat_inv, lat_resp, lat_hist, lat_count, lat_drop)

    return apply


def _cold_split_body(step, apply):
    """One scan/while iteration of the cold-split run: advance the hot
    state, then fold the exported markers onto the cold bank only when
    some seed marked (the lax.cond is a real device branch — the pred
    is batch-level scalar, not vmapped)."""

    def body(s: SimState) -> SimState:
        s2, markers = step(s)
        cold = (s2.lat_inv, s2.lat_resp, s2.lat_hist, s2.lat_count,
                s2.lat_drop)
        cold = lax.cond(
            jnp.any(markers[0]),
            lambda op: apply(op[0], op[1]),
            lambda op: op[0],
            (cold, markers),
        )
        return dataclasses.replace(
            s2, lat_inv=cold[0], lat_resp=cold[1], lat_hist=cold[2],
            lat_count=cold[3], lat_drop=cold[4],
        )

    return body


def _resolve_cold_split(
    wl: Workload, latency, cov_words: int, cold_split: bool
) -> bool:
    if not cold_split:
        return False
    if latency is None or wl.lat_markers == 0:
        raise ValueError(
            "cold_split needs the latency tap: a LatencySpec and a "
            "workload with lat_markers > 0 (there is no cold bank "
            "otherwise — the split would be a no-op)"
        )
    if cov_words:
        raise ValueError(
            "cold_split is incompatible with coverage (cov_words > 0): "
            "the (window, latency-bucket) coverage features must fold "
            "in-step; run hunts with the in-step tap and benches/obs "
            "sweeps with the split"
        )
    return True


def make_run(
    wl: Workload,
    cfg: EngineConfig,
    n_steps: int,
    layout: str | None = None,
    time32: bool | None = None,
    dup_rows: bool = False,
    cov_words: int = 0,
    metrics: bool = False,
    timeline_cap: int = 0,
    cov_hitcount: bool = False,
    latency: LatencySpec | None = None,
    placement: str | None = None,
    pool_index: bool | None = None,
    rank_place_max_pool: int | None = None,
    cold_split: bool = False,
    causal: bool = False,
    retry: "RetrySpec | None" = None,
):
    """Build ``run(state) -> state``: n_steps of vmapped lockstep advance.

    The returned function is jit-friendly and sharding-friendly: every
    array's leading axis is the seed axis, so a NamedSharding over that
    axis turns this into pure data-parallel work across chips with zero
    collectives in the hot loop (results are combined host-side).

    time32 contract: under the int32 time representation a timer delay
    past the int32 horizon (``cfg.delay_bound_ns`` eligibility) is
    clamped and counted in ``state.overflow`` — the run continues on a
    trajectory that may diverge from the int64 layout. Callers must
    check ``overflow == 0`` before trusting per-seed results (bench.py
    and engine.search do; direct callers are responsible themselves).

    ``cold_split=True`` lands the cold/hot split of the carried scan
    state: the army latency clocks and the (C,)-wide per-op columns
    (``lat_inv``/``lat_resp`` and the sketch) move to a cold bank the
    loop touches only on marker steps — the per-seed step exports raw
    markers and a batch-level ``lax.cond`` folds them (the exact
    in-step semantics, bit-identical values). Requires the latency tap
    and ``cov_words=0``; see :func:`_make_cold_lat_apply`.
    """
    cold = _resolve_cold_split(wl, latency, cov_words, cold_split)
    step = jax.vmap(make_step(
        wl, cfg, layout, time32, dup_rows, cov_words,
        metrics, timeline_cap, cov_hitcount, latency, placement,
        pool_index, rank_place_max_pool, causal, retry=retry,
        _lat_export=cold,
    ))

    if cold:
        cbody = _cold_split_body(step, _make_cold_lat_apply(latency, wl.lat_markers))

        def run(state: SimState) -> SimState:
            def body(s, _):
                return cbody(s), None

            final, _ = lax.scan(body, state, None, length=n_steps)
            return final

        return run

    def run(state: SimState) -> SimState:
        def body(s, _):
            return step(s), None

        final, _ = lax.scan(body, state, None, length=n_steps)
        return final

    return run


def make_run_while(
    wl: Workload,
    cfg: EngineConfig,
    max_steps: int,
    layout: str | None = None,
    time32: bool | None = None,
    dup_rows: bool = False,
    cov_words: int = 0,
    metrics: bool = False,
    timeline_cap: int = 0,
    cov_hitcount: bool = False,
    latency: LatencySpec | None = None,
    placement: str | None = None,
    pool_index: bool | None = None,
    rank_place_max_pool: int | None = None,
    cold_split: bool = False,
    causal: bool = False,
    retry: "RetrySpec | None" = None,
):
    """Like :func:`make_run` but stops as soon as every seed has halted.

    ``lax.while_loop`` on device: no wasted lockstep iterations once the
    slowest seed finishes — the bench path for halting workloads (e.g.
    raft elections, where the tail of seeds needing a second election
    round would otherwise cost every seed the full max_steps). Note the
    all-halted reduction runs per iteration; with a sharded seed axis it
    is XLA's only collective in the loop (a cheap scalar all-reduce).

    The :func:`make_run` time32 contract applies here too: horizon-
    clamped timer delays are counted in ``state.overflow`` and the run
    silently continues — check ``overflow == 0`` before trusting
    per-seed results. ``cold_split`` follows the make_run contract
    (cold latency bank folded batch-level only on marker steps).
    """
    cold = _resolve_cold_split(wl, latency, cov_words, cold_split)
    step = jax.vmap(make_step(
        wl, cfg, layout, time32, dup_rows, cov_words,
        metrics, timeline_cap, cov_hitcount, latency, placement,
        pool_index, rank_place_max_pool, causal, retry=retry,
        _lat_export=cold,
    ))
    advance = (
        _cold_split_body(step, _make_cold_lat_apply(latency, wl.lat_markers))
        if cold else step
    )

    def run(state: SimState) -> SimState:
        def cond(carry):
            s, i = carry
            return (i < max_steps) & ~jnp.all(s.halted)

        def body(carry):
            s, i = carry
            return advance(s), i + 1

        final, _ = lax.while_loop(cond, body, (state, jnp.int64(0)))
        return final

    return run
