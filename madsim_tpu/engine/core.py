"""Batched discrete-event simulation core — the TPU path.

The reference advances one seeded simulation per OS thread: a
single-threaded executor pops ready tasks in random order, polls
arbitrary futures, and jumps a virtual clock between timer events
(reference madsim/src/sim/task.rs:142-216, time/mod.rs:45-60). This
module inverts that architecture for TPUs: **simulation state is a pytree
of dense arrays with a leading seed axis**, and one XLA-compiled step
function advances *every* seed by one event in lockstep —
``vmap`` over seeds, ``lax.scan`` over steps, ``shard_map``/``jit`` with
``NamedSharding`` over device meshes (see madsim_tpu.parallel).

Mapping from the reference's moving parts to array form:

  reference (per run)                      engine (per seed row)
  ---------------------------------------  --------------------------------
  ready queue + timer wheel                one event pool (E slots): time,
    (task.rs:176-216, time/mod.rs:45-60)   kind, dst, src, epoch, args
  random ready-task pick (mpsc.rs:73-83)   per-event latency/cost draws
                                           randomize order; argmin pops the
                                           earliest event deterministically
  50-100 ns poll cost (task.rs:213)        poll-cost draw added to the
                                           clock after each dispatch
  serial SmallRng (rand.rs:30-61)          counter-based threefry draws
                                           keyed (seed, step, purpose)
  NodeInfo epoch swap on kill              alive/epoch arrays; events carry
    (task.rs:255-276)                      their target's epoch and are
                                           dropped on mismatch
  NetSim clog/loss/latency                 clog matrix (N,N); per-send loss
    (network.rs:75-95, 268-276)            and latency draws; clogged
                                           deliveries self-reschedule with
                                           exponential backoff
                                           (net/mod.rs:341-355 semantics)
  user futures polled by the executor      user code is a **state
                                           machine**: per-node int32 state
                                           rows + pure handler functions
                                           dispatched by ``lax.switch``

The last row is the central design decision (SURVEY.md §7 hard part 1):
XLA cannot trace arbitrary coroutines, so batched workloads are written
as event handlers over integer node state. The asyncio-style frontend in
madsim_tpu.runtime remains the ergonomic single-seed API; this engine is
the scaling path, and workloads written for it get 10^4-10^5 seeds per
chip.

Everything in the hot path is integer arithmetic (int32/int64/uint32) —
bit-identical across CPU and TPU backends, which makes the trace hash an
exact cross-backend determinism check (the analog of the reference's
replay checker, runtime/mod.rs:165-190).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .rng import (
    PURPOSE_CLOG_JITTER,
    PURPOSE_LATENCY,
    PURPOSE_LOSS,
    PURPOSE_POLL_COST,
    Draw,
    chance_threshold,
)

__all__ = [
    "EngineConfig",
    "Workload",
    "SimState",
    "Emits",
    "EmitBuilder",
    "HandlerCtx",
    "KIND_KILL",
    "KIND_RESTART",
    "KIND_CLOG",
    "KIND_UNCLOG",
    "KIND_CLOG_NODE",
    "KIND_UNCLOG_NODE",
    "KIND_HALT",
    "KIND_NOP",
    "KIND_PAUSE",
    "KIND_RESUME",
    "FIRST_USER_KIND",
    "user_kind",
    "make_init",
    "make_step",
    "make_run",
]

_INF_NS = np.int64(2**62)
_TRACE_PRIME = np.uint64(0x100000001B3)
_TRACE_MIX = np.uint64(0x9E3779B97F4A7C15)

# ---------------------------------------------------------------------------
# Event kinds. Engine kinds come first so user handler k has kind
# FIRST_USER_KIND + k regardless of workload; handler 0 is by convention
# on_init (run for every node at t=0 and again after RESTART).
# ---------------------------------------------------------------------------
KIND_KILL = 0  # args[0]=node          Handle::kill        (runtime/mod.rs:246)
KIND_RESTART = 1  # args[0]=node       Handle::restart     (runtime/mod.rs:251)
KIND_CLOG = 2  # args[0]=a args[1]=b   NetSim::clog_link   (net/mod.rs:157-216)
KIND_UNCLOG = 3  # args[0]=a args[1]=b
KIND_CLOG_NODE = 4  # args[0]=node     NetSim::clog_node
KIND_UNCLOG_NODE = 5  # args[0]=node
KIND_HALT = 6  # scenario complete: freeze this seed's instance
KIND_NOP = 7
KIND_PAUSE = 8  # args[0]=node      Handle::pause       (runtime/mod.rs:256)
KIND_RESUME = 9  # args[0]=node     Handle::resume
FIRST_USER_KIND = 10


def user_kind(i: int) -> int:
    """Kind id of user handler ``i`` (handler 0 = on_init)."""
    return FIRST_USER_KIND + i


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static simulation parameters (the analog of sim Config, config.rs:15).

    All values participate in the config hash printed on failure so a
    repro needs (seed, config) exactly like the reference
    (runtime/mod.rs:193-200).
    """

    pool_size: int = 256  # E: max in-flight events per seed
    lat_min_ns: int = 1_000_000  # network latency range, default 1-10 ms
    lat_max_ns: int = 10_000_000  # (reference network.rs:84-90)
    loss_p: float = 0.0  # packet loss rate (network.rs:75-95)
    proc_min_ns: int = 50  # per-event processing cost
    proc_max_ns: int = 100  # (task.rs:213)
    clog_backoff_min_ns: int = 1_000_000  # clogged-delivery recheck backoff
    clog_backoff_max_ns: int = 10_000_000_000  # 1 ms -> 10 s (net/mod.rs:341-355)
    time_limit_ns: int = 0  # 0 = unlimited (set_time_limit, runtime/mod.rs:143)

    def __post_init__(self):
        # draws are 32-bit; a span that doesn't fit uint32 would silently
        # wrap in the modulo reduction and skew the distribution
        for lo, hi, what in (
            (self.lat_min_ns, self.lat_max_ns, "latency"),
            (self.proc_min_ns, self.proc_max_ns, "processing-cost"),
        ):
            if hi < lo:
                raise ValueError(f"{what} range [{lo}, {hi}) is empty")
            if hi - lo >= (1 << 32):
                raise ValueError(
                    f"{what} span {hi - lo} ns does not fit uint32 "
                    f"(max {(1 << 32) - 1} ns, ~4.29 s)"
                )

    @property
    def loss_u32(self) -> int:
        return chance_threshold(self.loss_p)

    def hash(self) -> str:
        """Stable hex hash of the config (config.rs:27-31 analog)."""
        import hashlib

        s = repr(dataclasses.astuple(self)).encode()
        return hashlib.sha256(s).hexdigest()[:16]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Emits:
    """Fixed-capacity batch of events a handler emits (K slots).

    ``send`` slots are translated by the engine into future deliveries
    (latency + loss + clog, the NetSim path in SURVEY §3.3); timer slots
    become plain future events (add_timer, time/mod.rs:138-149).
    """

    valid: jnp.ndarray  # (K,)  bool
    send: jnp.ndarray  # (K,)  bool: network message vs local timer
    kind: jnp.ndarray  # (K,)  int32
    dst: jnp.ndarray  # (K,)  int32
    delay: jnp.ndarray  # (K,)  int64 ns (timer) / ignored for sends
    args: jnp.ndarray  # (K,4) int32
    pay: jnp.ndarray  # (K,W) int32 payload words (W = Workload.payload_words)

    @staticmethod
    def none(k: int, w: int = 0) -> "Emits":
        return Emits(
            valid=jnp.zeros((k,), jnp.bool_),
            send=jnp.zeros((k,), jnp.bool_),
            kind=jnp.zeros((k,), jnp.int32),
            dst=jnp.zeros((k,), jnp.int32),
            delay=jnp.zeros((k,), jnp.int64),
            args=jnp.zeros((k, 4), jnp.int32),
            pay=jnp.zeros((k, w), jnp.int32),
        )


class EmitBuilder:
    """Trace-time helper for constructing :class:`Emits` inside handlers.

    Slot assignment happens at Python trace time (static); the ``when``
    flag is the traced per-seed condition making an emit conditional.
    """

    def __init__(self, k: int, w: int = 0):
        self._k = k
        self._w = w
        self._rows: list[tuple] = []

    def _push(self, send, kind, dst, delay, args, when, pay=()):
        if len(self._rows) >= self._k:
            raise ValueError(
                f"handler emits more than max_emits={self._k} events; "
                f"raise Workload.max_emits"
            )
        a = list(args) + [0] * (4 - len(args))
        p = list(pay)
        if len(p) > self._w:
            raise ValueError(
                f"payload of {len(p)} words exceeds "
                f"Workload.payload_words={self._w}"
            )
        self._rows.append((when, send, kind, dst, delay, a, p))

    def send(self, dst, kind, args=(), when=True, pay=()):
        """Send a network message: delivery after latency unless lost/clogged.
        ``pay`` is an optional payload of up to ``Workload.payload_words``
        int32 words, carried with the event (the batched analog of the
        reference's ``Payload = Box<dyn Any>``, sim/net/endpoint.rs:13-23)."""
        self._push(True, kind, dst, 0, args, when, pay)

    def after(self, delay_ns, kind, dst, args=(), when=True, pay=()):
        """Schedule a local event ``delay_ns`` in the future (a timer)."""
        self._push(False, kind, dst, delay_ns, args, when, pay)

    def kill(self, node, when=True):
        self.after(0, KIND_KILL, 0, (node,), when)

    def restart(self, node, when=True):
        self.after(0, KIND_RESTART, 0, (node,), when)

    def restart_after(self, delay_ns, node, when=True):
        self.after(delay_ns, KIND_RESTART, 0, (node,), when)

    def pause(self, node, when=True):
        self.after(0, KIND_PAUSE, 0, (node,), when)

    def resume(self, node, when=True):
        self.after(0, KIND_RESUME, 0, (node,), when)

    def clog_link(self, a, b, when=True):
        self.after(0, KIND_CLOG, 0, (a, b), when)

    def unclog_link(self, a, b, when=True):
        self.after(0, KIND_UNCLOG, 0, (a, b), when)

    def halt(self, when=True):
        self.after(0, KIND_HALT, 0, (), when)

    def build(self) -> Emits:
        k, w = self._k, self._w
        if not self._rows:
            return Emits.none(k, w)
        pad = k - len(self._rows)
        valid = [jnp.asarray(wh, jnp.bool_) for (wh, *_r) in self._rows]
        send = [jnp.asarray(s, jnp.bool_) for (_w, s, *_r) in self._rows]
        kind = [jnp.asarray(kd, jnp.int32) for (_w, _s, kd, *_r) in self._rows]
        dst = [jnp.asarray(d, jnp.int32) for (*_h, d, _dl, _a, _p) in self._rows]
        delay = [jnp.asarray(dl, jnp.int64) for (*_h, dl, _a, _p) in self._rows]
        args = [
            jnp.stack([jnp.asarray(x, jnp.int32) for x in a])
            for (*_h, a, _p) in self._rows
        ]

        def pay_row(p: list) -> jnp.ndarray:
            if not p:
                return jnp.zeros((w,), jnp.int32)
            row = jnp.stack([jnp.asarray(x, jnp.int32) for x in p])
            return jnp.concatenate([row, jnp.zeros((w - len(p),), jnp.int32)])

        pay = [pay_row(p) for (*_h, p) in self._rows]
        z32 = jnp.int32(0)
        return Emits(
            valid=jnp.stack(valid + [jnp.asarray(False)] * pad),
            send=jnp.stack(send + [jnp.asarray(False)] * pad),
            kind=jnp.stack(kind + [z32] * pad),
            dst=jnp.stack(dst + [z32] * pad),
            delay=jnp.stack(delay + [jnp.int64(0)] * pad),
            args=jnp.stack(args + [jnp.zeros((4,), jnp.int32)] * pad),
            pay=jnp.stack(pay + [jnp.zeros((w,), jnp.int32)] * pad),
        )


@dataclasses.dataclass
class HandlerCtx:
    """Everything a handler sees about the event it is processing."""

    now: jnp.ndarray  # int64 ns — virtual clock
    node: jnp.ndarray  # int32 — the node this event targets
    state: jnp.ndarray  # (U,) int32 — the node's state row
    args: jnp.ndarray  # (4,) int32 — event arguments
    src: jnp.ndarray  # int32 — sender node for messages, -1 for timers
    draw: Draw  # counter-based RNG for this event
    max_emits: int
    payload: jnp.ndarray = None  # (W,) int32 — the event's payload words
    payload_words: int = 0

    def emits(self) -> EmitBuilder:
        return EmitBuilder(self.max_emits, self.payload_words)


Handler = Callable[[HandlerCtx], tuple]


@dataclasses.dataclass(frozen=True)
class Workload:
    """A batched simulation program: per-node int32 state + event handlers.

    This is how "user code" enters the traced step function. Handlers are
    pure: ``handler(ctx) -> (new_state_row, Emits)``. Handler 0 is
    ``on_init`` — invoked for every node at t=0 and again when a node is
    restarted (the stored-init-task semantics of task.rs:279-291).
    """

    name: str
    n_nodes: int
    state_width: int
    handlers: tuple  # tuple[Handler, ...]
    max_emits: int = 8
    init_state: np.ndarray | None = None  # (N,U) int32; zeros if None
    # payload arena width: int32 words carried by every event (0 = off).
    # The batched analog of Payload = Box<dyn Any> (endpoint.rs:13-23):
    # payload lifetime equals event lifetime, so the arena IS the event
    # pool — no separate allocator, no leaks
    payload_words: int = 0

    def __post_init__(self):
        # emit slot s draws under PURPOSE_LATENCY(8)+s and
        # PURPOSE_LOSS(64)+s; more than 56 slots would alias the two
        # namespaces (and >64 would bleed into PURPOSE_USER), silently
        # correlating "independent" draws
        limit = PURPOSE_LOSS - PURPOSE_LATENCY
        if self.max_emits > limit:
            raise ValueError(
                f"max_emits={self.max_emits} exceeds the purpose-namespace "
                f"limit of {limit} (engine/rng.py purpose layout)"
            )

    def initial_state(self) -> np.ndarray:
        if self.init_state is not None:
            return np.asarray(self.init_state, np.int32)
        return np.zeros((self.n_nodes, self.state_width), np.int32)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SimState:
    """One seed's full simulation state. ``vmap`` adds the (S,) axis."""

    seed: jnp.ndarray  # ()  uint64 instance seed
    now: jnp.ndarray  # ()  int64 virtual clock, ns
    step: jnp.ndarray  # ()  uint32 event sequence number (RNG coordinate)
    halted: jnp.ndarray  # () bool
    halt_time: jnp.ndarray  # () int64: clock when halted (else 0)
    trace: jnp.ndarray  # () uint64 rolling hash of dispatched events
    overflow: jnp.ndarray  # () int32 events dropped to pool overflow
    msg_count: jnp.ndarray  # () int64 — Stat{msg_count} (network.rs:106-111)
    # event pool, E slots
    ev_time: jnp.ndarray  # (E,) int64
    ev_valid: jnp.ndarray  # (E,) bool
    ev_kind: jnp.ndarray  # (E,) int32
    ev_node: jnp.ndarray  # (E,) int32 target node
    ev_src: jnp.ndarray  # (E,) int32 sender (-1 = timer/engine)
    ev_epoch: jnp.ndarray  # (E,) int32 target-node epoch at emit time
    ev_retry: jnp.ndarray  # (E,) int32 clog-backoff retry count
    ev_args: jnp.ndarray  # (E,4) int32
    ev_pay: jnp.ndarray  # (E,W) int32 payload words (W=0 when disabled)
    # nodes
    alive: jnp.ndarray  # (N,) bool
    paused: jnp.ndarray  # (N,) bool — events held while paused (pause/resume)
    epoch: jnp.ndarray  # (N,) int32
    node_state: jnp.ndarray  # (N,U) int32
    # network
    clog: jnp.ndarray  # (N,N) bool — link-clog matrix (net/mod.rs:157-216)

    @property
    def sim_seconds(self):
        """Virtual seconds this instance has advanced (bench metric)."""
        return self.now.astype(jnp.float64) / 1e9


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class _Effects:
    """Uniform output of every lax.switch branch."""

    node_state: jnp.ndarray  # (U,)
    emits: Emits
    kill: jnp.ndarray  # int32 node or -1
    restart: jnp.ndarray  # int32 node or -1
    pause_node: jnp.ndarray  # int32 node or -1
    pause_set: jnp.ndarray  # int32: 1 pause, 0 resume, -1 none
    clog_a: jnp.ndarray  # int32
    clog_b: jnp.ndarray  # int32 (-1 = whole node)
    clog_set: jnp.ndarray  # int32: -1 none, 0 unclog, 1 clog
    halt: jnp.ndarray  # bool


def _no_effects(state_row: jnp.ndarray, k: int, w: int = 0) -> _Effects:
    m1 = jnp.int32(-1)
    return _Effects(
        node_state=state_row,
        emits=Emits.none(k, w),
        kill=m1,
        restart=m1,
        pause_node=m1,
        pause_set=m1,
        clog_a=m1,
        clog_b=m1,
        clog_set=m1,
        halt=jnp.asarray(False),
    )


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def make_init(wl: Workload, cfg: EngineConfig):
    """Build ``init(seeds) -> SimState`` (batched over the seeds array).

    Seeds every node with an on_init event at t=0, mirroring the builder
    running each node's init task at simulation start.
    """
    n, u, e, k = wl.n_nodes, wl.state_width, cfg.pool_size, wl.max_emits
    if e < n:
        raise ValueError(f"pool_size={e} must hold at least one event per node ({n})")
    del k
    w = wl.payload_words
    base_state = jnp.asarray(wl.initial_state())

    def init_one(seed) -> SimState:
        seed = jnp.asarray(seed, jnp.uint64)
        ev_valid = jnp.zeros((e,), jnp.bool_).at[:n].set(True)
        ev_kind = jnp.full((e,), KIND_NOP, jnp.int32)
        ev_kind = ev_kind.at[:n].set(FIRST_USER_KIND)
        ev_node = jnp.zeros((e,), jnp.int32).at[:n].set(jnp.arange(n, dtype=jnp.int32))
        return SimState(
            seed=seed,
            now=jnp.int64(0),
            step=jnp.uint32(0),
            halted=jnp.asarray(False),
            halt_time=jnp.int64(0),
            trace=jnp.uint64(0),
            overflow=jnp.int32(0),
            msg_count=jnp.int64(0),
            ev_time=jnp.zeros((e,), jnp.int64),
            ev_valid=ev_valid,
            ev_kind=ev_kind,
            ev_node=ev_node,
            ev_src=jnp.full((e,), -1, jnp.int32),
            ev_epoch=jnp.zeros((e,), jnp.int32),
            ev_retry=jnp.zeros((e,), jnp.int32),
            ev_args=jnp.zeros((e, 4), jnp.int32),
            ev_pay=jnp.zeros((e, w), jnp.int32),
            alive=jnp.ones((n,), jnp.bool_),
            paused=jnp.zeros((n,), jnp.bool_),
            epoch=jnp.zeros((n,), jnp.int32),
            node_state=base_state,
            clog=jnp.zeros((n, n), jnp.bool_),
        )

    def init(seeds) -> SimState:
        seeds = jnp.asarray(seeds, jnp.uint64)
        return jax.vmap(init_one)(seeds)

    return init


# ---------------------------------------------------------------------------
# step
# ---------------------------------------------------------------------------


def _trace_fold(trace, now, kind, node, args, pay=None):
    """Fold one dispatched event into the rolling trace hash (uint64)."""
    h = now.astype(jnp.uint64) * _TRACE_MIX
    h = h ^ (kind.astype(jnp.uint64) << jnp.uint64(32))
    h = h ^ (node.astype(jnp.uint64) << jnp.uint64(40))
    a = args.astype(jnp.uint32).astype(jnp.uint64)
    h = h ^ a[0] ^ (a[1] << jnp.uint64(8)) ^ (a[2] << jnp.uint64(16)) ^ (
        a[3] << jnp.uint64(24)
    )
    if pay is not None and pay.shape[0] > 0:
        # payload words participate in the trace so a payload divergence
        # between backends is caught; W=0 keeps pre-payload traces intact
        p = pay.astype(jnp.uint32).astype(jnp.uint64)
        idx = jnp.arange(p.shape[0], dtype=jnp.uint64)
        h = h ^ jnp.sum(p * (_TRACE_MIX ^ idx))
    return trace * _TRACE_PRIME + h


def make_step(wl: Workload, cfg: EngineConfig):
    """Build the single-seed ``step(SimState) -> SimState`` function.

    Pops the earliest pending event, dispatches it through
    ``lax.switch`` (engine kinds + user handlers), applies chaos effects,
    and scatter-inserts emitted events. ``jax.vmap`` over the seed axis
    and ``lax.scan`` over steps give the batched run loop.
    """
    n = wl.n_nodes
    k = wl.max_emits
    w = wl.payload_words
    init_rows = jnp.asarray(wl.initial_state())
    n_branches = FIRST_USER_KIND + len(wl.handlers)

    # -- switch branches ---------------------------------------------------
    # lax.switch operands must be pytrees, so the context travels as a
    # tuple of arrays and each branch rebuilds the HandlerCtx view.
    def _unpack(op) -> HandlerCtx:
        now, node, state, args, src, k0, k1, stp, pay = op
        return HandlerCtx(
            now=now,
            node=node,
            state=state,
            args=args,
            src=src,
            draw=Draw.from_parts(k0, k1, stp),
            max_emits=k,
            payload=pay,
            payload_words=w,
        )

    def _engine_branch(effect_fn):
        def branch(op):
            ctx = _unpack(op)
            eff = _no_effects(ctx.state, k, w)
            return effect_fn(eff, ctx)

        return branch

    def _b_kill(eff, ctx):
        return dataclasses.replace(eff, kill=ctx.args[0])

    def _b_restart(eff, ctx):
        # the reborn node re-runs its init handler — the stored-init-task
        # respawn of task.rs:279-291
        eb = EmitBuilder(k, w)
        eb.after(0, FIRST_USER_KIND, ctx.args[0])
        return dataclasses.replace(eff, restart=ctx.args[0], emits=eb.build())

    def _b_clog(eff, ctx):
        return dataclasses.replace(
            eff, clog_a=ctx.args[0], clog_b=ctx.args[1], clog_set=jnp.int32(1)
        )

    def _b_unclog(eff, ctx):
        return dataclasses.replace(
            eff, clog_a=ctx.args[0], clog_b=ctx.args[1], clog_set=jnp.int32(0)
        )

    def _b_clog_node(eff, ctx):
        return dataclasses.replace(
            eff, clog_a=ctx.args[0], clog_b=jnp.int32(-1), clog_set=jnp.int32(1)
        )

    def _b_unclog_node(eff, ctx):
        return dataclasses.replace(
            eff, clog_a=ctx.args[0], clog_b=jnp.int32(-1), clog_set=jnp.int32(0)
        )

    def _b_halt(eff, ctx):
        return dataclasses.replace(eff, halt=jnp.asarray(True))

    def _b_pause(eff, ctx):
        return dataclasses.replace(
            eff, pause_node=ctx.args[0], pause_set=jnp.int32(1)
        )

    def _b_resume(eff, ctx):
        return dataclasses.replace(
            eff, pause_node=ctx.args[0], pause_set=jnp.int32(0)
        )

    def _b_nop(eff, ctx):
        return eff

    def _user_branch(handler):
        def branch(op):
            ctx = _unpack(op)
            new_state, emits = handler(ctx)
            eff = _no_effects(ctx.state, k, w)
            return dataclasses.replace(
                eff, node_state=jnp.asarray(new_state, jnp.int32), emits=emits
            )

        return branch

    branches = [
        _engine_branch(_b_kill),
        _engine_branch(_b_restart),
        _engine_branch(_b_clog),
        _engine_branch(_b_unclog),
        _engine_branch(_b_clog_node),
        _engine_branch(_b_unclog_node),
        _engine_branch(_b_halt),
        _engine_branch(_b_nop),
        _engine_branch(_b_pause),
        _engine_branch(_b_resume),
    ] + [_user_branch(h) for h in wl.handlers]
    assert len(branches) == n_branches

    loss_u32 = cfg.loss_u32
    time_limit = np.int64(cfg.time_limit_ns) if cfg.time_limit_ns else _INF_NS

    def step(st: SimState) -> SimState:
        # ---- pop the earliest pending event (the timer-jump of
        # time/mod.rs:45-60 merged with the ready-queue drain) ----
        tmask = jnp.where(st.ev_valid, st.ev_time, _INF_NS)
        i = jnp.argmin(tmask)
        has_event = st.ev_valid[i]
        ev_t = jnp.maximum(st.now, st.ev_time[i])
        over_limit = ev_t > time_limit
        active = has_event & ~st.halted & ~over_limit

        kind = st.ev_kind[i]
        dst = st.ev_node[i]
        src = st.ev_src[i]
        args = st.ev_args[i]
        is_engine = kind < FIRST_USER_KIND
        is_msg = src >= 0

        # liveness/epoch gate: user events to a dead or reincarnated node
        # are dropped — the kill-drops-futures semantics of task.rs:255-276
        live = st.alive[dst] & (st.epoch[dst] == st.ev_epoch[i])
        # clogged links hold messages; re-check with exponential backoff
        # like the connection pump (net/mod.rs:341-355)
        clogged = is_msg & st.clog[jnp.maximum(src, 0), dst]
        # paused node: user events are stashed and retried, like the
        # executor stashing a paused node's ready tasks (task.rs:294-314)
        held = (~is_engine) & st.paused[dst]
        blocked = clogged | held
        dispatch = active & ~blocked & (is_engine | live)

        now = jnp.where(active, ev_t, st.now)
        draw = Draw(st.seed, st.step)
        # per-event processing cost, 50-100 ns (task.rs:213)
        cost = draw.uniform_int(cfg.proc_min_ns, cfg.proc_max_ns, PURPOSE_POLL_COST)
        now_after = jnp.where(dispatch, now + cost, now)

        # ---- consume / reschedule the popped slot ----
        retries = st.ev_retry[i]
        shift = jnp.minimum(retries, jnp.int32(34)).astype(jnp.int64)
        backoff = jnp.minimum(
            jnp.int64(cfg.clog_backoff_min_ns) << shift,
            jnp.int64(cfg.clog_backoff_max_ns),
        )
        backoff = backoff + draw.uniform_int(0, 1000, PURPOSE_CLOG_JITTER)
        resched = active & blocked & (is_engine | live)
        ev_valid = st.ev_valid.at[i].set(resched)
        ev_time = st.ev_time.at[i].set(jnp.where(resched, now + backoff, st.ev_time[i]))
        ev_retry = st.ev_retry.at[i].set(jnp.where(resched, retries + 1, retries))

        # ---- dispatch ----
        safe_kind = jnp.clip(kind, 0, n_branches - 1)
        operand = (
            now, dst, st.node_state[dst], args, src,
            draw.k0, draw.k1, draw.step, st.ev_pay[i],
        )
        eff = lax.switch(safe_kind, branches, operand)

        # ---- apply node-state update ----
        row = jnp.where(dispatch, eff.node_state, st.node_state[dst])
        node_state = st.node_state.at[dst].set(row)

        # ---- chaos effects: kill / restart / clog ----
        kill_id = jnp.where(dispatch, eff.kill, jnp.int32(-1))
        restart_id = jnp.where(dispatch, eff.restart, jnp.int32(-1))
        node_ids = jnp.arange(n, dtype=jnp.int32)
        is_killed = node_ids == kill_id
        is_restarted = node_ids == restart_id
        alive = jnp.where(is_killed, False, st.alive)
        alive = jnp.where(is_restarted, True, alive)
        pause_id = jnp.where(dispatch, eff.pause_node, jnp.int32(-1))
        is_pause_target = node_ids == pause_id
        paused = jnp.where(
            is_pause_target, eff.pause_set == 1, st.paused
        )
        # kill/restart clears paused (fresh incarnation runs)
        paused = jnp.where(is_killed | is_restarted, False, paused)
        # epoch bumps invalidate every in-flight event targeting the node
        epoch = st.epoch + is_killed + is_restarted
        node_state = jnp.where(is_restarted[:, None], init_rows, node_state)

        clog_set = jnp.where(dispatch, eff.clog_set, jnp.int32(-1))
        src_ax = node_ids[:, None]
        dst_ax = node_ids[None, :]
        # clog_link(a, b) blocks both directions; clog_b < 0 means
        # clog_node(a): everything in or out of a (net/mod.rs:157-216)
        pair_sel = ((src_ax == eff.clog_a) & (dst_ax == eff.clog_b)) | (
            (src_ax == eff.clog_b) & (dst_ax == eff.clog_a)
        )
        node_sel = (eff.clog_b < 0) & (
            (src_ax == eff.clog_a) | (dst_ax == eff.clog_a)
        )
        sel = pair_sel | node_sel
        clog = jnp.where(
            sel & (clog_set == 1), True, jnp.where(sel & (clog_set == 0), False, st.clog)
        )

        halted = st.halted | (dispatch & eff.halt) | (has_event & over_limit)
        halt_time = jnp.where(
            (halted & ~st.halted), jnp.minimum(now, time_limit), st.halt_time
        )

        # ---- translate emits into pool insertions ----
        em = eff.emits
        slot_ix = jnp.arange(k, dtype=jnp.uint32)
        lat_bits = jax.vmap(lambda s: draw.bits(jnp.uint32(PURPOSE_LATENCY) + s))(
            slot_ix
        )
        loss_bits = jax.vmap(lambda s: draw.bits(jnp.uint32(PURPOSE_LOSS) + s))(slot_ix)
        span = jnp.uint32(max(cfg.lat_max_ns - cfg.lat_min_ns, 1))
        latency = jnp.int64(cfg.lat_min_ns) + (lat_bits % span).astype(jnp.int64)
        # loss_u32 == 2^32 is the static always-drop path (loss_p=1.0);
        # a uint32 compare can't express it (chance_threshold contract)
        if loss_u32 >= (1 << 32):
            lost = em.send
        else:
            lost = em.send & (loss_bits < jnp.uint32(loss_u32))

        e_valid = dispatch & em.valid & ~lost
        # sends to dead nodes are dropped at send time (socket gone,
        # network.rs:311-313); timers to dead nodes die via the epoch gate
        e_valid = e_valid & jnp.where(em.send, alive[em.dst], True)
        e_time = now_after + jnp.where(em.send, latency, em.delay)
        e_src = jnp.where(em.send, dst, jnp.int32(-1))
        e_epoch = epoch[em.dst]
        # engine-kind events bypass the epoch gate; keep their slot epoch 0
        e_epoch = jnp.where(em.kind < FIRST_USER_KIND, 0, e_epoch)

        free = jnp.flatnonzero(~ev_valid, size=k, fill_value=ev_valid.shape[0])
        # compact: the j-th *valid* emit takes the j-th free slot, so
        # sparse emit patterns (gated `when` rows) don't waste slots and
        # only a genuinely full pool drops events
        pos = jnp.cumsum(e_valid.astype(jnp.int32)) - 1
        slot = jnp.where(
            e_valid,
            free[jnp.clip(pos, 0, k - 1)],
            jnp.int32(ev_valid.shape[0]),
        )
        dropped = e_valid & (slot >= ev_valid.shape[0])
        overflow = st.overflow + jnp.sum(dropped).astype(jnp.int32)
        msg_count = st.msg_count + jnp.sum(
            dispatch & em.valid & em.send
        ).astype(jnp.int64)

        ev_valid = ev_valid.at[slot].set(e_valid, mode="drop")
        ev_time = ev_time.at[slot].set(e_time, mode="drop")
        ev_kind = st.ev_kind.at[slot].set(em.kind, mode="drop")
        ev_node = st.ev_node.at[slot].set(em.dst, mode="drop")
        ev_src = st.ev_src.at[slot].set(e_src, mode="drop")
        ev_epoch = st.ev_epoch.at[slot].set(e_epoch, mode="drop")
        ev_retry = ev_retry.at[slot].set(jnp.zeros((k,), jnp.int32), mode="drop")
        ev_args = st.ev_args.at[slot].set(em.args, mode="drop")
        ev_pay = st.ev_pay.at[slot].set(em.pay, mode="drop")

        # ---- trace + clock ----
        trace = jnp.where(
            dispatch,
            _trace_fold(st.trace, now, kind, dst, args, st.ev_pay[i]),
            st.trace,
        )
        return SimState(
            seed=st.seed,
            now=now_after,
            step=st.step + jnp.uint32(1),
            halted=halted,
            halt_time=halt_time,
            trace=trace,
            overflow=overflow,
            msg_count=msg_count,
            ev_time=ev_time,
            ev_valid=ev_valid,
            ev_kind=ev_kind,
            ev_node=ev_node,
            ev_src=ev_src,
            ev_epoch=ev_epoch,
            ev_retry=ev_retry,
            ev_args=ev_args,
            ev_pay=ev_pay,
            alive=alive,
            paused=paused,
            epoch=epoch,
            node_state=node_state,
            clog=clog,
        )

    return step


def make_run(wl: Workload, cfg: EngineConfig, n_steps: int):
    """Build ``run(state) -> state``: n_steps of vmapped lockstep advance.

    The returned function is jit-friendly and sharding-friendly: every
    array's leading axis is the seed axis, so a NamedSharding over that
    axis turns this into pure data-parallel work across chips with zero
    collectives in the hot loop (results are combined host-side).
    """
    step = jax.vmap(make_step(wl, cfg))

    def run(state: SimState) -> SimState:
        def body(s, _):
            return step(s), None

        final, _ = lax.scan(body, state, None, length=n_steps)
        return final

    return run


def make_run_while(wl: Workload, cfg: EngineConfig, max_steps: int):
    """Like :func:`make_run` but stops as soon as every seed has halted.

    ``lax.while_loop`` on device: no wasted lockstep iterations once the
    slowest seed finishes — the bench path for halting workloads (e.g.
    raft elections, where the tail of seeds needing a second election
    round would otherwise cost every seed the full max_steps). Note the
    all-halted reduction runs per iteration; with a sharded seed axis it
    is XLA's only collective in the loop (a cheap scalar all-reduce).
    """
    step = jax.vmap(make_step(wl, cfg))

    def run(state: SimState) -> SimState:
        def cond(carry):
            s, i = carry
            return (i < max_steps) & ~jnp.all(s.halted)

        def body(carry):
            s, i = carry
            return step(s), i + 1

        final, _ = lax.while_loop(cond, body, (state, jnp.int64(0)))
        return final

    return run
