"""Jitter-proof throughput measurement for the batched engine.

The TPU sits behind a remote tunnel whose dispatch path adds
multi-100 ms jitter; a sub-second measured cell (raft @65,536 seeds
runs ~0.2 s) is therefore dominated by transport noise — round 3's
sweep admitted ±2x spread on identical configs. The fix is structural,
not statistical: make each *dispatch* long enough that the jitter is
amortized to nothing, then take the median over a handful of
dispatches.

``make_repeat_program`` builds ONE jitted program that runs ``repeats``
independent seed-batches back-to-back on device (a ``lax.fori_loop``
whose body is the full compacted phase program on a fresh batch of
seeds), returning only scalar reductions (total simulated ns, overflow
count, halted count). One dispatch -> one jitter sample, regardless of
how much simulation rides inside it.

``measure_throughput`` calibrates the single-batch wall, picks
``repeats`` so a dispatch lasts >= ``target_wall_s`` (default 5 s,
vs <= ~0.3 s of observed jitter), and reports the median sim-s/s over
``n_measure`` dispatches with min/max spread.

``null_dispatch_stats`` times a trivial kernel the same way the sweep
times real ones, quantifying the per-dispatch overhead floor once per
artifact instead of letting it silently contaminate every cell.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .compact import make_run_compacted
from .core import EngineConfig, Workload, make_init

__all__ = [
    "make_repeat_program",
    "measure_throughput",
    "measure_latency",
    "null_dispatch_stats",
]


def make_repeat_program(
    wl: Workload,
    cfg: EngineConfig,
    max_steps: int,
    n_seeds: int,
    seed_mod: int,
    layout: str | None = None,
    time32: bool | None = None,
    shrink: int = 4,
    min_size: int = 2048,
):
    """Build ``program(seed_base, repeats) -> (sim_ns, overflow, halted)``.

    Runs ``repeats`` batches of ``n_seeds`` seeds (values
    ``(seed_base + r*n_seeds + i) % seed_mod``) through the compacted
    phase program inside one jitted ``fori_loop`` and reduces each to
    scalars: total simulated nanoseconds, total pool-overflow count,
    total halted-row count (== repeats*n_seeds iff every seed halted).

    ``repeats`` is a *runtime* argument (dynamic trip count), so one
    compile serves both the calibration run and the sized run.
    ``seed_mod`` keeps every seed inside the range the config's pool
    size was verified overflow-free for (models.BENCH_SPECS sizing) —
    without it, long measurement sessions would drift seeds millions
    past the verified range; repeated seed values across repeats are
    identical work, which is exactly what a throughput measure wants.
    """
    if seed_mod < n_seeds:
        raise ValueError(f"seed_mod={seed_mod} must be >= n_seeds={n_seeds}")
    init = make_init(wl, cfg, time32)
    run = make_run_compacted(
        wl, cfg, max_steps, layout, time32,
        shrink=shrink, min_size=min_size, fields=("now", "overflow", "halted"),
    )

    def program(seed_base, repeats):
        seed_base = jnp.asarray(seed_base, jnp.uint64)
        lanes = jnp.arange(n_seeds, dtype=jnp.uint64)

        def body(r, acc):
            sim_ns, ovf, halted = acc
            seeds = (
                seed_base + jnp.uint64(r) * jnp.uint64(n_seeds) + lanes
            ) % jnp.uint64(seed_mod)
            banked = run.phases(init(seeds))
            for b in banked:
                sim_ns = sim_ns + jnp.sum(b["now"]).astype(jnp.int64)
                ovf = ovf + jnp.sum(b["overflow"]).astype(jnp.int64)
                halted = halted + jnp.sum(b["halted"]).astype(jnp.int64)
            return (sim_ns, ovf, halted)

        return lax.fori_loop(
            0, repeats, body,
            (jnp.int64(0), jnp.int64(0), jnp.int64(0)),
        )

    return jax.jit(program)


def _calibrate_and_measure(
    program,
    n_seeds: int,
    target_wall_s: float,
    n_measure: int,
    seed_base: int,
    max_repeats: int,
    cal_repeats: int = 1,
):
    """Shared sizing + timing scaffold for the measure_* entry points.

    Compile, calibrate with one ``cal_repeats``-sized dispatch, pick
    ``repeats`` to reach ``target_wall_s``, then grow it until the
    realized wall does (the calibration dispatch rides the very jitter
    — or cache warm-up — this harness defeats, so a bad sample there
    would mis-size every measured cell; each probe doubles as a warm
    run). Returns ``(repeats, cal_wall, walls, sims, ovf_tot,
    halted_min)`` over ``n_measure`` timed dispatches.
    """
    jax.block_until_ready(program(np.uint64(seed_base), 1))  # compile
    t0 = time.perf_counter()  # lint: allow(wall-clock)
    jax.block_until_ready(program(np.uint64(seed_base), cal_repeats))
    cal_wall = time.perf_counter() - t0  # lint: allow(wall-clock)

    repeats = min(
        max(
            cal_repeats,
            int(np.ceil(target_wall_s / max(cal_wall / cal_repeats, 1e-9))),
        ),
        max_repeats,
    )
    for _ in range(8):
        t0 = time.perf_counter()  # lint: allow(wall-clock)
        jax.block_until_ready(program(np.uint64(seed_base), repeats))
        sized_wall = time.perf_counter() - t0  # lint: allow(wall-clock)
        if sized_wall >= target_wall_s * 0.6 or repeats >= max_repeats:
            break
        per_rep = sized_wall / repeats
        repeats = min(
            max(repeats + 1, int(np.ceil(target_wall_s / max(per_rep, 1e-9)))),
            max_repeats,
        )

    walls, sims, ovf_tot, halted_min = [], [], 0, None
    for m in range(n_measure):
        base = np.uint64(seed_base + (m + 1) * repeats * n_seeds)
        t0 = time.perf_counter()  # lint: allow(wall-clock)
        sim_ns, ovf, halted = jax.block_until_ready(program(base, repeats))
        walls.append(time.perf_counter() - t0)  # lint: allow(wall-clock)
        sims.append(int(sim_ns) / 1e9)
        ovf_tot += int(ovf)
        h = int(halted)
        halted_min = h if halted_min is None else min(halted_min, h)
    return repeats, cal_wall, walls, sims, ovf_tot, halted_min


def measure_throughput(
    wl: Workload,
    cfg: EngineConfig,
    max_steps: int,
    n_seeds: int,
    target_wall_s: float = 5.0,
    n_measure: int = 5,
    seed_base: int = 0,
    seed_mod: int = 131072,
    max_repeats: int = 4096,
    layout: str | None = None,
    time32: bool | None = None,
    shrink: int = 4,
    min_size: int = 2048,
) -> dict:
    """Measure sim-s/s with >= ``target_wall_s``-long dispatches.

    Returns a dict with the median rate over ``n_measure`` timed
    dispatches plus the full per-dispatch walls, the repeat count, and
    correctness counters (overflow must be 0 and halted must equal
    seeds*repeats for the rate to be quotable — callers check).
    ``seed_mod`` must cover only seeds the config's pool size is
    verified overflow-free for (see make_repeat_program, which raises
    if it can't hold one batch).
    """
    program = make_repeat_program(
        wl, cfg, max_steps, n_seeds, seed_mod, layout, time32, shrink, min_size
    )
    repeats, cal_wall, walls, sims, ovf_tot, halted_min = _calibrate_and_measure(
        program, n_seeds, target_wall_s, n_measure, seed_base, max_repeats
    )

    # rate per dispatch = its OWN simulated seconds / its wall (seed
    # blocks differ, so sim time varies slightly across dispatches)
    rates = np.asarray(sims) / np.asarray(walls)
    return {
        "n_seeds": n_seeds,
        "repeats": int(repeats),
        "calibration_wall_s": round(cal_wall, 4),
        "dispatch_walls_s": [round(w, 4) for w in walls],
        "sim_s_per_dispatch": [round(s, 3) for s in sims],
        "sim_s_per_s_median": round(float(np.median(rates)), 1),
        "sim_s_per_s_min": round(float(rates.min()), 1),
        "sim_s_per_s_max": round(float(rates.max()), 1),
        "spread_pct": round(
            100.0 * (rates.max() - rates.min()) / max(float(np.median(rates)), 1e-9),
            1,
        ),
        "overflow": ovf_tot,
        "all_halted": halted_min == repeats * n_seeds,
    }


def measure_latency(
    wl: Workload,
    cfg: EngineConfig,
    max_steps: int,
    target_wall_s: float = 3.5,
    n_measure: int = 3,
    seed_base: int = 0,
    seed_mod: int = 131072,
    max_repeats: int = 131072,
    layout: str | None = None,
    time32: bool | None = None,
) -> dict:
    """Wall microseconds per complete single-seed sim, sized dispatches.

    The latency analog of :func:`measure_throughput` for deliberately
    single-seed configs (BASELINE's pingpong): one seed cannot amortize
    dispatch overhead into a throughput quote, so instead ``repeats``
    independent single-seed sims are packed into one multi-second
    dispatch and the quote is median wall-per-sim. Same correctness
    contract: ``overflow`` must be 0 and ``all_halted`` True for the
    number to be quotable — callers check.
    """
    program = make_repeat_program(
        wl, cfg, max_steps, 1, seed_mod, layout, time32, min_size=1
    )
    # cal_repeats=32: a single 1-seed run is far too short to time
    repeats, cal_wall, walls, sims, ovf_tot, halted_min = _calibrate_and_measure(
        program, 1, target_wall_s, n_measure, seed_base, max_repeats,
        cal_repeats=32,
    )

    lat_us = np.asarray(walls) / repeats * 1e6
    med = float(np.median(lat_us))
    return {
        "n_seeds": 1,
        "repeats": int(repeats),
        "calibration_wall_s": round(cal_wall, 4),
        "dispatch_walls_s": [round(w, 4) for w in walls],
        "wall_us_per_sim_median": round(med, 2),
        "spread_pct": round(
            100.0 * float(lat_us.max() - lat_us.min()) / max(med, 1e-9), 1
        ),
        "sim_s_per_s": round(float(np.sum(sims) / np.sum(walls)), 2),
        "overflow": ovf_tot,
        "all_halted": halted_min == repeats,
    }


def null_dispatch_stats(n: int = 20) -> dict:
    """Per-dispatch overhead floor: time a trivial jitted kernel.

    The result bounds how much of any measured cell is transport, not
    compute — quote it alongside sweep artifacts so a reader can check
    that cells were sized to dominate it.
    """
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((), jnp.int32)
    jax.block_until_ready(f(x))
    walls = []
    for _ in range(n):
        t0 = time.perf_counter()  # lint: allow(wall-clock)
        jax.block_until_ready(f(x))
        walls.append(time.perf_counter() - t0)  # lint: allow(wall-clock)
    w = np.asarray(walls)
    return {
        "n": n,
        "min_ms": round(float(w.min()) * 1e3, 3),
        "median_ms": round(float(np.median(w)) * 1e3, 3),
        "p90_ms": round(float(np.quantile(w, 0.9)) * 1e3, 3),
        "max_ms": round(float(w.max()) * 1e3, 3),
    }
