"""Counter-based RNG for the batched engine.

The reference simulator draws every random decision from one serial
``SmallRng`` stream (reference madsim/src/sim/rand.rs:30-61): draw N
depends on draws 1..N-1 having happened, which serializes the whole
simulation. That is exactly what does not map to a TPU. The batched
engine replaces the serial stream with a **counter-based** generator:
every draw is a pure function of ``(instance_seed, event_step, purpose)``,
so draws are order-independent, trivially vectorizable over the seed
axis, and reproducible from coordinates alone — the property the
determinism checker and the C++ oracle rely on.

The block cipher is an explicit Threefry-2x32-20 implementation (the
Random123 construction, same family JAX uses internally) written here in
plain uint32 ops so that:
  * the spec is owned by this file — the numpy mirror
    (:func:`np_threefry2x32`) and the C++ oracle implement the identical
    function, giving bit-exact cross-backend traces;
  * it runs inside ``vmap``/``jit`` with no host callbacks;
  * TPU executes it as pure 32-bit integer ALU work (no MXU needed, and
    no reliance on JAX PRNG implementation details that could change).

Draw discipline (mirrored by engine/core.py and the oracle):
  key     = (seed & 0xffffffff, seed >> 32)          # per-instance
  counter = (event_step, purpose)                     # per-draw
  value   = threefry2x32(key, counter)[0]             # 32 uniform bits

``purpose`` namespaces the draws made while processing one event. The
namespace is a structured registry — :data:`PURPOSE_LANES` — of named
``(base, width, owner)`` blocks: engine lanes in [0, 128) (poll cost,
per-emit latency/loss, dup shadows, torn prefix), user handler lanes in
[128, plan-base), and the host-side plan/explore/client blocks at
``0x9E37xxxx``+. Two draw sites resolving into the same lane slot at
the same counter read the SAME cipher value, so lane disjointness is a
checked invariant: ``Workload.draw_purposes`` is validated against the
registry at build time (:func:`validate_user_purposes`) and the
interval prover (``lint.absint``) proves every traced program's live
sites pairwise disjoint under :func:`lane_site_tracing`.
"""

from __future__ import annotations

import contextlib
import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "threefry2x32",
    "np_threefry2x32",
    "np_threefry2x32v",
    "Draw",
    "PurposeLane",
    "PURPOSE_LANES",
    "lane",
    "lane_of",
    "validate_user_purposes",
    "lane_site_tracing",
    "LANE_SITE_NAME",
    "DRAW_SPAN_MAX",
    "PURPOSE_POLL_COST",
    "PURPOSE_LATENCY",
    "PURPOSE_LOSS",
    "PURPOSE_DUP",
    "PURPOSE_TORN",
    "PURPOSE_RETRY",
    "PURPOSE_PLAN",
    "PURPOSE_EXPLORE",
    "PURPOSE_CLIENT",
    "PURPOSE_FARM",
    "PURPOSE_USER",
]

# Threefry-2x32 rotation schedule (Random123 / Salmon et al. 2011).
_ROTATIONS = (13, 15, 26, 6, 17, 29, 16, 24)
# Skein key-schedule parity constant for 32-bit words.
_PARITY = np.uint32(0x1BD11BDA)

# The one range contract of the modulo reduction: every bounded draw
# (Draw.uniform_int, the chaos plan streams, EngineConfig's latency and
# processing-cost windows) reduces 32 uniform bits by `bits % span`, so
# a span wider than this silently wraps and skews the distribution.
# EngineConfig, chaos window validation and the absint range contracts
# (lint.absint / engine.column_contracts) all derive from THIS constant
# so the validators and the prover cannot drift.
DRAW_SPAN_MAX = (1 << 32) - 1


@dataclasses.dataclass(frozen=True)
class PurposeLane:
    """One declared block of the threefry purpose namespace.

    The purpose word namespaces every draw made at one ``(seed, step)``
    counter; a lane is a contiguous block of purposes with ONE owner.
    Two draw sites that resolve into the same lane slot at the same
    counter read the SAME cipher value — silently correlated streams —
    so the registry's pairwise disjointness is a checked invariant
    (``lint.absint.check_ranges`` proves it per traced program, and
    :func:`validate_user_purposes` rejects user lanes that would alias
    an engine block at build time).
    """

    name: str
    base: int
    width: int  # number of purpose values in the lane
    owner: str  # "engine" | "user" | "chaos" | "explore"
    note: str = ""

    @property
    def end(self) -> int:
        """Exclusive upper bound of the lane."""
        return self.base + self.width

    def __contains__(self, purpose) -> bool:
        return self.base <= int(purpose) < self.end

    def describe(self) -> str:
        return (
            f"{self.name}[{self.base:#x}..{self.end:#x}) "
            f"owner={self.owner}"
        )


# The structured purpose registry — THE declaration of who owns which
# purposes (previously comment-partitioned constants; MIGRATING.md
# documents the change). One event-step makes at most one draw per
# purpose, so (seed, step, purpose) uniquely keys every draw in a run.
# Gaps between lanes are unassigned: a draw resolving there is a bug
# the lane prover reports. Engine lanes:
#   poll_cost  — ONE block yields both the per-event processing cost
#                (lane 0, 50-100 ns) and the clogged-link recheck
#                jitter (lane 1) via Draw.bits2.
#   clog_jitter — reserved/legacy: the jitter rides poll_cost lane 1
#                now, but the id stays unavailable so old and new
#                layouts never alias.
#   torn       — torn-write prefix draw (chaos disk faults): when a
#                KILL lands on a node whose torn-write mode is armed,
#                ONE block picks how many columns of the last
#                uncommitted durable write survive. Only drawn for
#                Workload.durable_sync workloads; counter-addressed, so
#                enabling the discipline never shifts any other draw.
#   retry      — client-retry backoff jitter (chaos.RetryPolicy): when
#                a dispatched army op arms its response-deadline timer,
#                ONE block draws the jitter fraction of the next
#                attempt's backoff delay. Counter-addressed like torn:
#                attaching a retry policy never shifts any other draw.
#   latency    — per-emit-slot draws at base+slot: latency (lane 0)
#                and loss (lane 1) from one block (Draw.bits2).
#   dup        — duplicated-delivery draws (chaos KIND_DUP_ON): shadow
#                emit slot s draws its independent latency/loss pair at
#                base+s. Re-uses the retired per-slot loss range
#                (PURPOSE_LOSS) — no current layout draws there, and
#                max_emits <= 55 keeps latency slots below this base.
# Host-side lanes (draws keyed by plan/batch slot, not the step
# counter; each sits far above every in-simulation purpose):
#   plan       — fault-plan compilation (chaos.FaultPlan), x0 = draw
#                index, x1 = base + plan slot; slots stay below 64k.
#   explore    — exploration seed/mutation derivation (explore), x1 =
#                base + batch slot; slots below 64k.
#   client     — open-loop client-army arrival generation
#                (chaos.ClientArmy), x1 = base + plan slot: arrivals
#                are pool rows compiled from coordinates, so offered
#                load is a pure function of the seed whatever
#                trajectory the faults push the protocol onto.
#   farm       — fuzzing-farm energy/scheduler draws (madsim_tpu.farm):
#                corpus-entry power schedules key per-child streams at
#                x1 = base, tenant budget draws at x1 = base + 1 —
#                disjoint from the explore lane, so turning energy on
#                or off never shifts a mutation draw.
PURPOSE_LANES = (
    PurposeLane("poll_cost", 0, 1, "engine", "cost lane 0 / jitter lane 1"),
    PurposeLane("clog_jitter", 1, 1, "engine", "reserved/legacy"),
    PurposeLane("torn", 2, 1, "engine", "torn-write prefix draw"),
    PurposeLane("retry", 3, 1, "engine", "retry backoff jitter draw"),
    PurposeLane("latency", 8, 56, "engine", "base+slot, lat/loss pair"),
    PurposeLane("dup", 64, 64, "engine", "base+slot, dup shadow pair"),
    PurposeLane("user", 128, 0x9E370000 - 128, "user", "base+user purpose"),
    PurposeLane("plan", 0x9E370000, 1 << 16, "chaos", "base+plan slot"),
    PurposeLane("explore", 0x9E380000, 1 << 16, "explore", "base+batch slot"),
    PurposeLane("client", 0x9E390000, 1 << 16, "chaos", "base+plan slot"),
    PurposeLane("farm", 0x9E3A0000, 1 << 16, "farm", "base+slot, energy"),
)


def _validate_registry(lanes) -> None:
    prev_end = 0
    for ln in lanes:
        if ln.width < 1 or ln.base < prev_end or ln.end > (1 << 32):
            raise ValueError(
                f"PURPOSE_LANES registry corrupt at {ln.describe()}: lanes "
                f"must be non-empty, sorted and pairwise disjoint in uint32"
            )
        prev_end = ln.end


_validate_registry(PURPOSE_LANES)


def lane(name: str) -> PurposeLane:
    """The registered lane called ``name`` (KeyError if unknown)."""
    for ln in PURPOSE_LANES:
        if ln.name == name:
            return ln
    raise KeyError(f"no purpose lane named {name!r}")


def lane_of(purpose: int) -> PurposeLane | None:
    """The lane containing ``purpose``, or None for unassigned space."""
    for ln in PURPOSE_LANES:
        if purpose in ln:
            return ln
    return None


def validate_user_purposes(purposes, what: str = "draw_purposes") -> None:
    """Reject user purposes that leave the ``user`` lane.

    ``purposes`` are USER-relative (the ints handlers pass to
    ``ctx.draw.user`` / ``Draw.user``, i.e. offsets above
    ``PURPOSE_USER``). Before the registry, any value below
    ``2^32 - PURPOSE_USER`` was accepted — an out-of-range user lane
    silently aliased the plan/explore/client blocks (same cipher
    value, correlated "independent" streams). Now the error names the
    lane the purpose would collide with.
    """
    ulane = lane("user")
    seen = set()
    for p in purposes:
        p = int(p)
        # the raw offset must fit the lane BEFORE any uint32 wrap: a
        # purpose >= 2^32 would wrap back onto a small lane at draw
        # time (Draw.user casts to uint32) and bit-for-bit duplicate
        # its stream — reject on the unwrapped value
        if not 0 <= p < ulane.width:
            absolute = (ulane.base + p) % (1 << 32)
            hit = lane_of(absolute)
            where = hit.describe() if hit is not None else "unassigned space"
            raise ValueError(
                f"{what} purpose {p} is outside the user lane "
                f"[0, {ulane.width:#x}) — at draw time it would resolve "
                f"to absolute purpose {absolute:#x} and alias {where}, "
                f"silently correlating the streams "
                f"(engine/rng.py PURPOSE_LANES)"
            )
        if p in seen:
            raise ValueError(f"{what} has duplicates: purpose {p}")
        seen.add(p)


# Backward-compatible purpose constants, now DERIVED from the registry
# (the bases are the contract; the registry is the declaration).
PURPOSE_POLL_COST = lane("poll_cost").base
PURPOSE_CLOG_JITTER = lane("clog_jitter").base
PURPOSE_TORN = lane("torn").base
PURPOSE_RETRY = lane("retry").base
PURPOSE_LATENCY = lane("latency").base  # + emit slot, both lanes used
PURPOSE_DUP = lane("dup").base  # + shadow emit slot
PURPOSE_LOSS = PURPOSE_DUP  # legacy alias: the retired per-slot loss range
PURPOSE_USER = lane("user").base  # + user purpose
PURPOSE_PLAN = lane("plan").base  # + plan slot (host-side)
PURPOSE_EXPLORE = lane("explore").base  # + batch slot (host-side)
PURPOSE_CLIENT = lane("client").base  # + plan slot (host-side)
PURPOSE_FARM = lane("farm").base  # + slot (host-side energy/scheduler)


def _rotl32(x, r: int):
    """Rotate a uint32 left by the static amount ``r``."""
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


# ---------------------------------------------------------------------------
# Lane-site tracing (lint.absint). A traced simulation program inlines
# every cipher application into ~50 anonymous uint32 rounds, which makes
# the (counter, purpose) operands of each DRAW SITE invisible to jaxpr
# analyses. Under this context manager, threefry2x32 routes through a
# named jit boundary instead: each cipher application then appears in
# the jaxpr as ONE ``pjit[name=threefry2x32_lane_site]`` equation whose
# third/fourth operands are the (x0, x1) counter words — exactly what
# the lane-disjointness prover resolves against PURPOSE_LANES. The jit
# wraps the identical round function, so values are bit-identical;
# production tracing never takes this path (zero cost, zero program
# change outside an analysis trace).
# ---------------------------------------------------------------------------
LANE_SITE_NAME = "threefry2x32_lane_site"
_LANE_SITE_DEPTH = 0
_SITE_JIT = None  # built lazily (jax.jit at import would eager-init jax)


@contextlib.contextmanager
def lane_site_tracing():
    """Trace-time context: make every threefry call a named jaxpr site."""
    global _LANE_SITE_DEPTH
    _LANE_SITE_DEPTH += 1
    try:
        yield
    finally:
        _LANE_SITE_DEPTH -= 1


def threefry2x32_lane_site(k0, k1, x0, x1):
    """The 20 Threefry rounds (uint32 in/out) — the body both the plain
    and the lane-site path run; the name is the jaxpr site marker."""
    ks = (k0, k1, k0 ^ k1 ^ jnp.uint32(_PARITY))
    x0 = x0 + ks[0]
    x1 = x1 + ks[1]
    for chunk in range(5):
        rots = _ROTATIONS[:4] if chunk % 2 == 0 else _ROTATIONS[4:]
        for r in rots:
            x0 = x0 + x1
            x1 = _rotl32(x1, r)
            x1 = x1 ^ x0
        x0 = x0 + ks[(chunk + 1) % 3]
        x1 = x1 + ks[(chunk + 2) % 3] + jnp.uint32(chunk + 1)
    return x0, x1


def threefry2x32(k0, k1, x0, x1):
    """Threefry-2x32, 20 rounds. All inputs/outputs are uint32 arrays.

    Pure jnp integer ops: identical bit patterns on CPU and TPU backends,
    which is what makes batched-vs-oracle traces exactly comparable.
    """
    k0 = jnp.asarray(k0, jnp.uint32)
    k1 = jnp.asarray(k1, jnp.uint32)
    x0 = jnp.asarray(x0, jnp.uint32)
    x1 = jnp.asarray(x1, jnp.uint32)
    if _LANE_SITE_DEPTH:
        global _SITE_JIT
        if _SITE_JIT is None:
            _SITE_JIT = jax.jit(threefry2x32_lane_site)
        return _SITE_JIT(k0, k1, x0, x1)
    return threefry2x32_lane_site(k0, k1, x0, x1)


def np_threefry2x32(k0, k1, x0, x1):
    """Numpy mirror of :func:`threefry2x32` — the oracle's generator.

    Kept textually parallel to the jnp rounds (threefry2x32_lane_site)
    on purpose; any divergence is a bug the trace-compare tests catch.
    """
    k0 = np.uint32(k0)
    k1 = np.uint32(k1)
    x0 = np.uint32(x0)
    x1 = np.uint32(x1)
    with np.errstate(over="ignore"):
        ks = (k0, k1, np.uint32(k0 ^ k1 ^ _PARITY))
        x0 = np.uint32(x0 + ks[0])
        x1 = np.uint32(x1 + ks[1])
        for chunk in range(5):
            rots = _ROTATIONS[:4] if chunk % 2 == 0 else _ROTATIONS[4:]
            for r in rots:
                x0 = np.uint32(x0 + x1)
                x1 = np.uint32((x1 << np.uint32(r)) | (x1 >> np.uint32(32 - r)))
                x1 = np.uint32(x1 ^ x0)
            x0 = np.uint32(x0 + ks[(chunk + 1) % 3])
            x1 = np.uint32(x1 + ks[(chunk + 2) % 3] + np.uint32(chunk + 1))
    return x0, x1


def np_threefry2x32v(k0, k1, x0, x1):
    """Vectorized numpy form of :func:`np_threefry2x32` (same function,
    ufunc ops instead of scalar casts so whole batches go at once) —
    the generator behind host-side plan compilation (madsim_tpu.chaos)
    and exploration seed/mutation derivation (madsim_tpu.explore)."""
    k0 = np.asarray(k0, np.uint32)
    k1 = np.asarray(k1, np.uint32)
    x0 = np.asarray(x0, np.uint32)
    x1 = np.asarray(x1, np.uint32)
    with np.errstate(over="ignore"):
        ks = (k0, k1, (k0 ^ k1 ^ _PARITY).astype(np.uint32))
        x0 = (x0 + ks[0]).astype(np.uint32)
        x1 = (x1 + ks[1]).astype(np.uint32)
        for chunk in range(5):
            rots = _ROTATIONS[:4] if chunk % 2 == 0 else _ROTATIONS[4:]
            for r in rots:
                x0 = (x0 + x1).astype(np.uint32)
                x1 = ((x1 << np.uint32(r)) | (x1 >> np.uint32(32 - r))).astype(
                    np.uint32
                )
                x1 = (x1 ^ x0).astype(np.uint32)
            x0 = (x0 + ks[(chunk + 1) % 3]).astype(np.uint32)
            x1 = (x1 + ks[(chunk + 2) % 3] + np.uint32(chunk + 1)).astype(
                np.uint32
            )
    return x0, x1


class Draw:
    """Per-event draw context handed to handlers (and used by the engine).

    Wraps the ``(seed, step)`` coordinates; each method makes one draw
    under a caller-chosen purpose. All methods are jnp-traceable scalars
    and therefore vmap cleanly over the seed axis.
    """

    __slots__ = ("k0", "k1", "step", "cache")

    def __init__(self, seed_u64, step_u32):
        seed = jnp.asarray(seed_u64, jnp.uint64)
        self.k0 = (seed & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        self.k1 = (seed >> jnp.uint64(32)).astype(jnp.uint32)
        self.step = jnp.asarray(step_u32, jnp.uint32)
        self.cache = None

    @classmethod
    def from_parts(cls, k0, k1, step, cache=None) -> "Draw":
        d = cls.__new__(cls)
        d.k0 = jnp.asarray(k0, jnp.uint32)
        d.k1 = jnp.asarray(k1, jnp.uint32)
        d.step = jnp.asarray(step, jnp.uint32)
        # prefetched lanes of this step's batched block
        # (Workload.draw_purposes; engine/core.py builds the dict):
        # purpose -> (lane0, lane1). A trace-time dict keyed by STATIC
        # purpose ints — a cached lane is the identical
        # (seed, step, purpose) cipher value, just generated inside the
        # per-dispatch block instead of by its own scalar invocation.
        d.cache = cache
        return d

    def bits(self, purpose) -> jnp.ndarray:
        """32 uniform bits for ``purpose`` (uint32)."""
        if (
            self.cache is not None
            and isinstance(purpose, (int, np.integer))
            and int(purpose) in self.cache
        ):
            return self.cache[int(purpose)][0]
        a, _ = threefry2x32(self.k0, self.k1, self.step, jnp.uint32(purpose))
        return a

    def bits2(self, purpose):
        """Both 32-bit lanes of one threefry call — two independent
        uniform words for the price of one block. The engine pairs the
        per-emit latency and loss draws this way (latency = lane 0,
        loss = lane 1 of the PURPOSE_LATENCY+slot counter); the C++
        oracle mirrors the pairing exactly."""
        if (
            self.cache is not None
            and isinstance(purpose, (int, np.integer))
            and int(purpose) in self.cache
        ):
            return self.cache[int(purpose)]
        return threefry2x32(self.k0, self.k1, self.step, jnp.uint32(purpose))

    def block2(self, purposes):
        """Both lanes of MANY purposes in one batched cipher application
        — the per-dispatch BatchRNG form (PAPERS.md): the engine
        enumerates every purpose one event-step can draw (poll cost,
        per-emit latency/loss, dup shadows, torn prefix) as a static
        lane vector and generates the whole block set in one
        varying-counter threefry pass, instead of issuing separate
        cipher calls per use. Each lane is keyed by the identical
        ``(seed, step, purpose)`` counter a scalar :meth:`bits2` call
        would use, so every draw VALUE is bit-identical to the
        per-use form — the property the trace-identity pins and the
        C++ oracle compare rely on. Returns ``(lane0, lane1)`` arrays
        shaped like ``purposes``."""
        p = jnp.asarray(purposes, jnp.uint32)
        return threefry2x32(self.k0, self.k1, self.step, p)

    def uniform_int(self, lo, hi, purpose):
        """Uniform int64 in [lo, hi).

        Uses modulo reduction — a ≤2^-32 bias, identical in the oracle,
        matching the determinism contract (exactness over de-biasing).
        """
        return self._reduce(self.bits(purpose), lo, hi)

    def uniform_int2(self, lo_a, hi_a, lo_b, hi_b, purpose):
        """Two independent uniform int64s from ONE threefry block:
        lane 0 reduced into [lo_a, hi_a), lane 1 into [lo_b, hi_b).
        The engine pairs the per-step poll-cost and clog-jitter draws
        this way; the C++ oracle mirrors the pairing exactly."""
        a, b = self.bits2(purpose)
        return self._reduce(a, lo_a, hi_a), self._reduce(b, lo_b, hi_b)

    @staticmethod
    def _reduce(bits, lo, hi):
        span = (jnp.asarray(hi, jnp.int64) - jnp.asarray(lo, jnp.int64)).astype(
            jnp.uint32
        )
        v = bits % jnp.maximum(span, jnp.uint32(1))
        return jnp.asarray(lo, jnp.int64) + v.astype(jnp.int64)

    def chance(self, threshold_u32, purpose):
        """True with probability threshold/2^32 — integer-exact Bernoulli.

        ``threshold_u32 = int(p * 2**32)`` is computed statically in
        Python so the comparison itself is pure uint32 — no float
        rounding can diverge between backends. A static threshold of
        2^32 (``chance_threshold(1.0)``) is the guaranteed-true path —
        a uint32 compare alone can never return True for the draw
        0xFFFFFFFF.
        """
        if isinstance(threshold_u32, int) and threshold_u32 >= (1 << 32):
            return jnp.bool_(True)
        return self.bits(purpose) < jnp.uint32(threshold_u32)

    def user(self, purpose):
        """32 bits in the user purpose namespace (handlers call this)."""
        if isinstance(purpose, (int, np.integer)):
            # static purpose: routes through the prefetch cache
            # (Workload.draw_purposes) when the lane was batched —
            # identical counter, identical value
            return self.bits(PURPOSE_USER + int(purpose))
        return self.bits(jnp.uint32(PURPOSE_USER) + jnp.uint32(purpose))

    def user_int(self, lo, hi, purpose):
        """Uniform int64 in [lo, hi) in the user purpose namespace."""
        return self.uniform_int(lo, hi, PURPOSE_USER + purpose)


def chance_threshold(p: float) -> int:
    """Static helper: probability -> threshold for :meth:`Draw.chance`.

    Returns a value in [0, 2^32]; 2^32 means "always true" (p=1.0 must
    drop every packet, not 2^32-1 out of 2^32 of them).
    """
    if p <= 0.0:
        return 0
    if p >= 1.0:
        return 1 << 32
    return int(p * (1 << 32))
