"""Counter-based RNG for the batched engine.

The reference simulator draws every random decision from one serial
``SmallRng`` stream (reference madsim/src/sim/rand.rs:30-61): draw N
depends on draws 1..N-1 having happened, which serializes the whole
simulation. That is exactly what does not map to a TPU. The batched
engine replaces the serial stream with a **counter-based** generator:
every draw is a pure function of ``(instance_seed, event_step, purpose)``,
so draws are order-independent, trivially vectorizable over the seed
axis, and reproducible from coordinates alone — the property the
determinism checker and the C++ oracle rely on.

The block cipher is an explicit Threefry-2x32-20 implementation (the
Random123 construction, same family JAX uses internally) written here in
plain uint32 ops so that:
  * the spec is owned by this file — the numpy mirror
    (:func:`np_threefry2x32`) and the C++ oracle implement the identical
    function, giving bit-exact cross-backend traces;
  * it runs inside ``vmap``/``jit`` with no host callbacks;
  * TPU executes it as pure 32-bit integer ALU work (no MXU needed, and
    no reliance on JAX PRNG implementation details that could change).

Draw discipline (mirrored by engine/core.py and the oracle):
  key     = (seed & 0xffffffff, seed >> 32)          # per-instance
  counter = (event_step, purpose)                     # per-draw
  value   = threefry2x32(key, counter)[0]             # 32 uniform bits

``purpose`` namespaces the draws made while processing one event: engine
purposes live in [0, 128) (poll cost, per-emit latency/loss, clog
backoff), user handler purposes in [128, 2^32).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = [
    "threefry2x32",
    "np_threefry2x32",
    "np_threefry2x32v",
    "Draw",
    "PURPOSE_POLL_COST",
    "PURPOSE_LATENCY",
    "PURPOSE_LOSS",
    "PURPOSE_DUP",
    "PURPOSE_TORN",
    "PURPOSE_PLAN",
    "PURPOSE_EXPLORE",
    "PURPOSE_CLIENT",
    "PURPOSE_USER",
]

# Threefry-2x32 rotation schedule (Random123 / Salmon et al. 2011).
_ROTATIONS = (13, 15, 26, 6, 17, 29, 16, 24)
# Skein key-schedule parity constant for 32-bit words.
_PARITY = np.uint32(0x1BD11BDA)

# Engine purpose namespace. One event-step makes at most one draw per
# purpose, so (seed, step, purpose) uniquely keys every draw in a run.
# ONE block at PURPOSE_POLL_COST yields both the per-event processing
# cost (lane 0, 50-100 ns) and the clogged-link recheck jitter (lane 1)
# via Draw.bits2 — the same pairing the per-emit latency/loss draws use.
PURPOSE_POLL_COST = 0
# reserved/legacy: the engine no longer draws a separate block here (the
# jitter rides PURPOSE_POLL_COST lane 1), but the purpose id stays
# unavailable so old and new layouts never alias.
PURPOSE_CLOG_JITTER = 1
# torn-write prefix draw (madsim_tpu.chaos disk faults): when a KILL
# lands on a node whose torn-write mode is armed, ONE block at this
# purpose picks how many columns of the last uncommitted durable write
# survive the crash. Only drawn when the step is built for a
# Workload.durable_sync workload; counter-addressed like every other
# purpose, so enabling the discipline never shifts any other draw.
PURPOSE_TORN = 2
# per-emit-slot draws: ONE block at PURPOSE_LATENCY+s yields both the
# latency (lane 0) and loss (lane 1) words via Draw.bits2. PURPOSE_LOSS
# is reserved/legacy space: the engine no longer draws there, but the
# range stays unavailable to callers so old and new layouts never alias.
PURPOSE_LATENCY = 8  # + emit slot  (8 .. 8+K), both lanes used
PURPOSE_LOSS = 64  # legacy per-slot loss range, re-purposed: see PURPOSE_DUP
# duplicated-delivery draws (chaos KIND_DUP_ON, engine/core.py dup_rows):
# shadow emit slot s draws its independent latency/loss pair at
# PURPOSE_DUP+s. This re-uses the retired per-slot loss range — no
# current layout draws there, and max_emits <= 55 keeps PURPOSE_DUP+s
# below PURPOSE_USER.
PURPOSE_DUP = PURPOSE_LOSS
PURPOSE_USER = 128  # + user purpose

# Fault-plan compilation (madsim_tpu.chaos) also draws from this
# threefry keyed by the instance seed, but host-side with counter
# x0 = draw index, x1 = PURPOSE_PLAN + plan slot. PURPOSE_PLAN sits far
# above any purpose the engine or in-repo handlers use, so plan draws
# can never alias an in-simulation draw at the same (seed, step) — each
# (seed, plan-slot) pair is its own reproducible stream (the BatchRNG
# varying-parameter-stream shape).
PURPOSE_PLAN = 0x9E370000

# Coverage-guided exploration (madsim_tpu.explore) derives fresh child
# seeds and mutation draws from the campaign's ROOT seed with counter
# x1 = PURPOSE_EXPLORE + batch-slot. Plan slots stay below 64k, so
# PURPOSE_PLAN + slot < PURPOSE_EXPLORE — the two host-side streams can
# never alias each other (and both sit far above every in-simulation
# purpose).
PURPOSE_EXPLORE = 0x9E380000

# Open-loop client-army arrival generation (madsim_tpu.chaos
# ClientArmy): arrival times and per-op argument words are threefry
# draws keyed (seed, PURPOSE_CLIENT + plan slot) — one reproducible
# stream per (seed, op), the BatchRNG varying-parameter-stream shape
# again. Because arrivals are pool rows compiled from coordinates (not
# in-simulation draws at a step counter), the offered load is a pure
# function of the seed: the SAME arrival schedule hits the protocol
# whatever trajectory the faults push it onto — the open-loop property
# that makes tail latency measurable. Explore's batch slots stay below
# 64k, so PURPOSE_EXPLORE + slot < PURPOSE_CLIENT keeps the host-side
# streams disjoint.
PURPOSE_CLIENT = 0x9E390000


def _rotl32(x, r: int):
    """Rotate a uint32 left by the static amount ``r``."""
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def threefry2x32(k0, k1, x0, x1):
    """Threefry-2x32, 20 rounds. All inputs/outputs are uint32 arrays.

    Pure jnp integer ops: identical bit patterns on CPU and TPU backends,
    which is what makes batched-vs-oracle traces exactly comparable.
    """
    k0 = jnp.asarray(k0, jnp.uint32)
    k1 = jnp.asarray(k1, jnp.uint32)
    x0 = jnp.asarray(x0, jnp.uint32)
    x1 = jnp.asarray(x1, jnp.uint32)
    ks = (k0, k1, k0 ^ k1 ^ jnp.uint32(_PARITY))
    x0 = x0 + ks[0]
    x1 = x1 + ks[1]
    for chunk in range(5):
        rots = _ROTATIONS[:4] if chunk % 2 == 0 else _ROTATIONS[4:]
        for r in rots:
            x0 = x0 + x1
            x1 = _rotl32(x1, r)
            x1 = x1 ^ x0
        x0 = x0 + ks[(chunk + 1) % 3]
        x1 = x1 + ks[(chunk + 2) % 3] + jnp.uint32(chunk + 1)
    return x0, x1


def np_threefry2x32(k0, k1, x0, x1):
    """Numpy mirror of :func:`threefry2x32` — the oracle's generator.

    Kept textually parallel to the jnp version on purpose; any divergence
    is a bug the trace-compare tests will catch.
    """
    k0 = np.uint32(k0)
    k1 = np.uint32(k1)
    x0 = np.uint32(x0)
    x1 = np.uint32(x1)
    with np.errstate(over="ignore"):
        ks = (k0, k1, np.uint32(k0 ^ k1 ^ _PARITY))
        x0 = np.uint32(x0 + ks[0])
        x1 = np.uint32(x1 + ks[1])
        for chunk in range(5):
            rots = _ROTATIONS[:4] if chunk % 2 == 0 else _ROTATIONS[4:]
            for r in rots:
                x0 = np.uint32(x0 + x1)
                x1 = np.uint32((x1 << np.uint32(r)) | (x1 >> np.uint32(32 - r)))
                x1 = np.uint32(x1 ^ x0)
            x0 = np.uint32(x0 + ks[(chunk + 1) % 3])
            x1 = np.uint32(x1 + ks[(chunk + 2) % 3] + np.uint32(chunk + 1))
    return x0, x1


def np_threefry2x32v(k0, k1, x0, x1):
    """Vectorized numpy form of :func:`np_threefry2x32` (same function,
    ufunc ops instead of scalar casts so whole batches go at once) —
    the generator behind host-side plan compilation (madsim_tpu.chaos)
    and exploration seed/mutation derivation (madsim_tpu.explore)."""
    k0 = np.asarray(k0, np.uint32)
    k1 = np.asarray(k1, np.uint32)
    x0 = np.asarray(x0, np.uint32)
    x1 = np.asarray(x1, np.uint32)
    with np.errstate(over="ignore"):
        ks = (k0, k1, (k0 ^ k1 ^ _PARITY).astype(np.uint32))
        x0 = (x0 + ks[0]).astype(np.uint32)
        x1 = (x1 + ks[1]).astype(np.uint32)
        for chunk in range(5):
            rots = _ROTATIONS[:4] if chunk % 2 == 0 else _ROTATIONS[4:]
            for r in rots:
                x0 = (x0 + x1).astype(np.uint32)
                x1 = ((x1 << np.uint32(r)) | (x1 >> np.uint32(32 - r))).astype(
                    np.uint32
                )
                x1 = (x1 ^ x0).astype(np.uint32)
            x0 = (x0 + ks[(chunk + 1) % 3]).astype(np.uint32)
            x1 = (x1 + ks[(chunk + 2) % 3] + np.uint32(chunk + 1)).astype(
                np.uint32
            )
    return x0, x1


class Draw:
    """Per-event draw context handed to handlers (and used by the engine).

    Wraps the ``(seed, step)`` coordinates; each method makes one draw
    under a caller-chosen purpose. All methods are jnp-traceable scalars
    and therefore vmap cleanly over the seed axis.
    """

    __slots__ = ("k0", "k1", "step", "cache")

    def __init__(self, seed_u64, step_u32):
        seed = jnp.asarray(seed_u64, jnp.uint64)
        self.k0 = (seed & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        self.k1 = (seed >> jnp.uint64(32)).astype(jnp.uint32)
        self.step = jnp.asarray(step_u32, jnp.uint32)
        self.cache = None

    @classmethod
    def from_parts(cls, k0, k1, step, cache=None) -> "Draw":
        d = cls.__new__(cls)
        d.k0 = jnp.asarray(k0, jnp.uint32)
        d.k1 = jnp.asarray(k1, jnp.uint32)
        d.step = jnp.asarray(step, jnp.uint32)
        # prefetched lanes of this step's batched block
        # (Workload.draw_purposes; engine/core.py builds the dict):
        # purpose -> (lane0, lane1). A trace-time dict keyed by STATIC
        # purpose ints — a cached lane is the identical
        # (seed, step, purpose) cipher value, just generated inside the
        # per-dispatch block instead of by its own scalar invocation.
        d.cache = cache
        return d

    def bits(self, purpose) -> jnp.ndarray:
        """32 uniform bits for ``purpose`` (uint32)."""
        if (
            self.cache is not None
            and isinstance(purpose, (int, np.integer))
            and int(purpose) in self.cache
        ):
            return self.cache[int(purpose)][0]
        a, _ = threefry2x32(self.k0, self.k1, self.step, jnp.uint32(purpose))
        return a

    def bits2(self, purpose):
        """Both 32-bit lanes of one threefry call — two independent
        uniform words for the price of one block. The engine pairs the
        per-emit latency and loss draws this way (latency = lane 0,
        loss = lane 1 of the PURPOSE_LATENCY+slot counter); the C++
        oracle mirrors the pairing exactly."""
        if (
            self.cache is not None
            and isinstance(purpose, (int, np.integer))
            and int(purpose) in self.cache
        ):
            return self.cache[int(purpose)]
        return threefry2x32(self.k0, self.k1, self.step, jnp.uint32(purpose))

    def block2(self, purposes):
        """Both lanes of MANY purposes in one batched cipher application
        — the per-dispatch BatchRNG form (PAPERS.md): the engine
        enumerates every purpose one event-step can draw (poll cost,
        per-emit latency/loss, dup shadows, torn prefix) as a static
        lane vector and generates the whole block set in one
        varying-counter threefry pass, instead of issuing separate
        cipher calls per use. Each lane is keyed by the identical
        ``(seed, step, purpose)`` counter a scalar :meth:`bits2` call
        would use, so every draw VALUE is bit-identical to the
        per-use form — the property the trace-identity pins and the
        C++ oracle compare rely on. Returns ``(lane0, lane1)`` arrays
        shaped like ``purposes``."""
        p = jnp.asarray(purposes, jnp.uint32)
        return threefry2x32(self.k0, self.k1, self.step, p)

    def uniform_int(self, lo, hi, purpose):
        """Uniform int64 in [lo, hi).

        Uses modulo reduction — a ≤2^-32 bias, identical in the oracle,
        matching the determinism contract (exactness over de-biasing).
        """
        return self._reduce(self.bits(purpose), lo, hi)

    def uniform_int2(self, lo_a, hi_a, lo_b, hi_b, purpose):
        """Two independent uniform int64s from ONE threefry block:
        lane 0 reduced into [lo_a, hi_a), lane 1 into [lo_b, hi_b).
        The engine pairs the per-step poll-cost and clog-jitter draws
        this way; the C++ oracle mirrors the pairing exactly."""
        a, b = self.bits2(purpose)
        return self._reduce(a, lo_a, hi_a), self._reduce(b, lo_b, hi_b)

    @staticmethod
    def _reduce(bits, lo, hi):
        span = (jnp.asarray(hi, jnp.int64) - jnp.asarray(lo, jnp.int64)).astype(
            jnp.uint32
        )
        v = bits % jnp.maximum(span, jnp.uint32(1))
        return jnp.asarray(lo, jnp.int64) + v.astype(jnp.int64)

    def chance(self, threshold_u32, purpose):
        """True with probability threshold/2^32 — integer-exact Bernoulli.

        ``threshold_u32 = int(p * 2**32)`` is computed statically in
        Python so the comparison itself is pure uint32 — no float
        rounding can diverge between backends. A static threshold of
        2^32 (``chance_threshold(1.0)``) is the guaranteed-true path —
        a uint32 compare alone can never return True for the draw
        0xFFFFFFFF.
        """
        if isinstance(threshold_u32, int) and threshold_u32 >= (1 << 32):
            return jnp.bool_(True)
        return self.bits(purpose) < jnp.uint32(threshold_u32)

    def user(self, purpose):
        """32 bits in the user purpose namespace (handlers call this)."""
        if isinstance(purpose, (int, np.integer)):
            # static purpose: routes through the prefetch cache
            # (Workload.draw_purposes) when the lane was batched —
            # identical counter, identical value
            return self.bits(PURPOSE_USER + int(purpose))
        return self.bits(jnp.uint32(PURPOSE_USER) + jnp.uint32(purpose))

    def user_int(self, lo, hi, purpose):
        """Uniform int64 in [lo, hi) in the user purpose namespace."""
        return self.uniform_int(lo, hi, PURPOSE_USER + purpose)


def chance_threshold(p: float) -> int:
    """Static helper: probability -> threshold for :meth:`Draw.chance`.

    Returns a value in [0, 2^32]; 2^32 means "always true" (p=1.0 must
    drop every packet, not 2^32-1 out of 2^32 of them).
    """
    if p <= 0.0:
        return 0
    if p >= 1.0:
        return 1 << 32
    return int(p * (1 << 32))
