"""VMEM-resident runner: the whole simulation loop inside one kernel.

The plain runners (`make_run`/`make_run_while`) let XLA schedule each
step over HBM-resident state. This module wraps the SAME step function
in a Pallas kernel that grids over seed blocks and runs the full step
loop per block with all state living in VMEM: HBM traffic per block
drops from per-step round trips to one load plus one store, and every
step op reads on-chip memory. Values are bit-identical to the plain
runner — the kernel body IS `make_step` (tests/test_vmem.py asserts
equality per field).

This is the exploratory "fused kernel" lever from the perf plan
(SCALING.md §3): whether it beats the XLA-scheduled loop on real
silicon depends on whether the step is compute- or traffic-bound
there — `examples/vmem_probe.py` measures the head-to-head. On CPU
the kernel runs in interpreter mode (for tests); it is NOT the
default path anywhere.

Constraints: the per-block state must fit VMEM (~16 MB/core —
`block_seeds` trades grid size against residency; raft at time32 is
roughly 0.9 KB/seed, so 2,048-seed blocks use ~2 MB plus
double-buffering headroom), and the loop is lockstep `fori_loop` (no
early exit; halted seeds already freeze inside the step, and the
compacted runner remains the tail-economics answer).
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .core import EngineConfig, SimState, Workload, make_step

__all__ = ["make_run_vmem"]


def make_run_vmem(
    wl: Workload,
    cfg: EngineConfig,
    n_steps: int,
    block_seeds: int = 2048,
    layout: str | None = "dense",
    time32: bool | None = None,
    interpret: bool | None = None,
):
    """Build ``run(state) -> SimState``: ``n_steps`` of the engine step
    with each seed-block's state VMEM-resident for the whole loop.

    ``interpret`` None = interpreter mode on the CPU backend (tests),
    compiled Mosaic elsewhere. The seed count must be a multiple of
    ``block_seeds``.
    """
    step1 = make_step(wl, cfg, layout, time32)
    vstep = jax.vmap(step1, in_axes=(0, None))
    fields = [f.name for f in dataclasses.fields(SimState)]
    # the two tables make_step otherwise embeds as constants — a pallas
    # kernel cannot capture non-scalar jaxpr constants, so they ride as
    # explicit kernel inputs (the `_tables` seam in make_step)
    tables = (
        jnp.asarray(wl.initial_state()),
        jnp.asarray(wl.volatile_mask()),
    )

    def build(state: SimState):
        s0 = int(state.seed.shape[0])
        if s0 % block_seeds:
            raise ValueError(
                f"{s0} seeds do not split into {block_seeds}-seed blocks"
            )
        b = block_seeds
        vals = {f: getattr(state, f) for f in fields}
        # zero-size fields (e.g. ev_pay at payload_words=0) break pallas
        # block padding; they carry no data, so they are rebuilt inside
        # the kernel instead of passed through
        live = [f for f in fields if int(np.prod(vals[f].shape)) > 0]
        zero = {
            f: (vals[f].shape[1:], vals[f].dtype)
            for f in fields
            if f not in live
        }

        def block_spec(arr):
            shape = (b,) + arr.shape[1:]
            ndim = len(shape)
            return pl.BlockSpec(shape, lambda i, _nd=ndim: (i,) + (0,) * (_nd - 1))

        def table_spec(arr):
            shape = arr.shape
            ndim = len(shape)
            return pl.BlockSpec(shape, lambda i, _nd=ndim: (0,) * _nd)

        def kernel(*refs):
            nf = len(live)
            in_refs, t_refs, out_refs = refs[:nf], refs[nf : nf + 2], refs[nf + 2 :]
            d = {f: r[...] for f, r in zip(live, in_refs)}
            for f, (tail, dt) in zero.items():
                d[f] = jnp.zeros((b,) + tail, dt)
            st = SimState(**d)
            tabs = (t_refs[0][...], t_refs[1][...])
            final = lax.fori_loop(0, n_steps, lambda i, s: vstep(s, tabs), st)
            for f, r in zip(live, out_refs):
                r[...] = getattr(final, f)

        call = pl.pallas_call(
            kernel,
            grid=(s0 // b,),
            in_specs=[block_spec(vals[f]) for f in live]
            + [table_spec(t) for t in tables],
            out_specs=[block_spec(vals[f]) for f in live],
            out_shape=[
                jax.ShapeDtypeStruct(vals[f].shape, vals[f].dtype) for f in live
            ],
            interpret=(jax.default_backend() == "cpu")
            if interpret is None
            else interpret,
        )
        return call, live, zero, vals

    # shape-keyed cache: un-jitted callers would otherwise construct a
    # fresh pallas_call (and retrace the kernel) on every invocation
    _built: dict = {}

    def run(state: SimState) -> SimState:
        key = tuple(
            (f.name, getattr(state, f.name).shape,
             str(getattr(state, f.name).dtype))
            for f in dataclasses.fields(SimState)
        )
        if key not in _built:
            call, live, zero, _vals = build(state)
            # cache only the program + field split: holding the first
            # caller's concrete arrays would pin them for the runner's
            # lifetime
            _built[key] = (call, live, zero)
        call, live, zero = _built[key]
        vals = {f: getattr(state, f) for f in live}
        outs = call(*[vals[f] for f in live], *tables)
        d = dict(zip(live, outs))
        for f, (tail, dt) in zero.items():
            d[f] = jnp.zeros((state.seed.shape[0],) + tail, dt)
        return SimState(**d)

    return run
