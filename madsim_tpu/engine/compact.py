"""Seed-compaction runner: stop paying lockstep steps for halted seeds.

The plain batched loop (``make_run_while``) advances every seed until the
*slowest* seed halts. Halting workloads have long tails — measured raft
(8,192 seeds, CPU): median seed halts in 15 steps, p99 in 25, the last
straggler at ~50, so lockstep wastes >3x of the step budget on rows that
are already frozen. The reference never pays this cost because each seed
runs on its own OS thread and simply exits (reference
madsim/src/sim/runtime/builder.rs:110-148, one thread per seed); this
module is the batched analog of "finished seeds stop consuming CPU".

Mechanism: seeds are independent rows under ``vmap`` (no cross-seed ops
anywhere in the engine), so a run can be split into *phases* of static,
shrinking batch sizes inside one jitted program:

    phase 0: while_loop at S rows      until live <= S/shrink
    compact: stable-partition live rows to the front (argsort + gather),
             hand the halted tail back as a banked output
    phase 1: while_loop at S/shrink rows ...
    ...
    final phase: run until every row halts (or the step cap)

Every shape is static (XLA requirement); the *schedule* of sizes is
fixed at trace time and each phase's while_loop exits exactly when the
live count fits the next size. Banked rows leave the hot loop, so the
tail of stragglers runs at 1/shrink^k of the full-batch step cost.

Exactness: a row's trajectory depends only on its own state row (seed,
RNG step coordinate, event pool, node arrays, clog matrix), so
reordering and slicing rows never changes any row's values — the
per-seed (now, trace, node_state, ...) results are bit-identical to the
uncompacted loop, which tests/test_compact.py asserts. The single
intentional divergence is ``SimState.step``: lockstep increments it for
halted rows too, while compaction stops counting once a row is banked.
The counter is the RNG coordinate (engine/rng.py) and halted rows make
no further draws, so nothing downstream can observe the difference.

The total-step cap is shared across phases (one counter threaded through
all while_loops), so ``max_steps`` means the same thing as in
``make_run_while``: rows still live when the cap hits are frozen
mid-flight exactly like the lockstep loop would leave them.
"""

from __future__ import annotations

import dataclasses
from types import SimpleNamespace

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .core import EngineConfig, SimState, Workload, make_step

__all__ = ["make_run_compacted"]

# SimState fields reported per original seed. 'step' is excluded from
# equality guarantees (see module docstring) but still banked so callers
# can inspect it. The history columns ride along so the check package
# works on compacted results; with Workload.history=None they are
# zero-size arrays and cost nothing.
RESULT_FIELDS = (
    "seed",
    "now",
    "step",
    "halted",
    "halt_time",
    "trace",
    "overflow",
    "msg_count",
    "node_state",
    # synced durable image (Workload.durable_sync): zero-size when the
    # sync discipline is off; banked so recovery-state invariants can
    # compare buffered vs committed durable columns on compacted runs
    "disk",
    "hist_count",
    "hist_drop",
    "hist_word",
    "hist_t",
    # coverage bitmap (madsim_tpu.explore): zero-size with cov_words=0
    "cov",
    # observability columns (madsim_tpu.obs): all zero-size when the
    # metrics/timeline taps are off. cov_hits is deliberately NOT banked
    # — guidance consumes only the bitmap, and the counters would add
    # CW*32 bytes/seed of transfer for nothing.
    "met",
    "tl_count",
    "tl_drop",
    "tl_t",
    "tl_meta",
    "tl_args",
    "tl_pay",
    "tl_emit",
    # causal provenance (causal=True): the final Lamport clocks and the
    # ring's DAG columns bank; the pool-side ev_parent/ev_lam sidecars
    # do NOT — they are live-pool forensics only readable against a
    # pool the bank deliberately drops (the lat_inv/lat_resp rule).
    "lam",
    "tl_seq",
    "tl_parent",
    "tl_lam",
    # tail-latency columns (madsim_tpu.obs latency): the sketch and its
    # counters bank (SLO invariants read lat_hist on compacted runs);
    # the per-op lat_inv/lat_resp clocks do NOT — they are the heavy
    # (C,)-wide forensics columns, and banked sweeps consume only the
    # sketch (the cov_hits rule applied again).
    "lat_hist",
    "lat_count",
    "lat_drop",
)

# extra banked outputs of a ``hist_screen`` run (not SimState fields):
# the per-seed device verdict and the prefix-compaction fold counter
SCREEN_FIELDS = ("hist_ok", "hist_fold")


def _phase_sizes(s0: int, shrink: int, min_size: int) -> list[int]:
    sizes = [s0]
    while sizes[-1] // shrink >= min_size:
        sizes.append(sizes[-1] // shrink)
    return sizes


def make_run_compacted(
    wl: Workload,
    cfg: EngineConfig,
    max_steps: int,
    layout: str | None = None,
    time32: bool | None = None,
    shrink: int = 4,
    min_size: int = 2048,
    fields: tuple = RESULT_FIELDS,
    dup_rows: bool = False,
    cov_words: int = 0,
    metrics: bool = False,
    timeline_cap: int = 0,
    cov_hitcount: bool = False,
    latency=None,
    placement: str | None = None,
    pool_index: bool | None = None,
    rank_place_max_pool: int | None = None,
    hist_screen=None,
    causal: bool = False,
    retry=None,
):
    """Build ``run(state) -> SimpleNamespace`` of per-original-seed results.

    The returned callable takes the batched :class:`SimState` from
    ``make_init`` and returns numpy arrays (one per name in ``fields``,
    leading axis = the original seed order) — the same fields bench.py
    and the verify tools read off a ``SimState``, minus the live event
    pool (which only straggler rows still meaningfully own).

    ``shrink``/``min_size`` set the static phase schedule; with
    ``min_size >= n_seeds`` the program degenerates to exactly one
    while_loop — the plain ``make_run_while``.

    ``hist_screen`` (a ``check.device.HistoryScreen`` or tuple of them)
    turns on device-resident verification with history
    **prefix-compaction**: the moment a bank of halted rows leaves the
    hot loop, the screen kernels judge their histories ON DEVICE and —
    for seeds the screen passed — responded (invoke, response) pairs
    fold out of the banked columns (``check.device.fold_verified``),
    so the device→host transfer carries only still-pending invokes
    plus the *flagged* seeds' full histories. Two extra result fields
    appear: ``hist_ok`` (the per-seed verdict, computed BEFORE the
    fold) and ``hist_fold`` (records folded — loud, ``hist_drop``-
    style accounting: original count == hist_count + hist_fold).
    Flagged and overflowed seeds keep every record verbatim, so the
    exact-checker escalation (Wing–Gong over flagged seeds) is
    unaffected by construction. Requires ``wl.history``.
    """
    step = jax.vmap(make_step(
        wl, cfg, layout, time32, dup_rows, cov_words,
        metrics, timeline_cap, cov_hitcount, latency, placement,
        pool_index, rank_place_max_pool, causal, retry=retry,
    ))
    all_names = [f.name for f in dataclasses.fields(SimState)]
    for f in fields:
        if f not in all_names:
            raise ValueError(f"unknown SimState field {f!r}")
    if shrink < 2:
        raise ValueError(f"shrink must be >= 2, got {shrink}")
    if min_size < 1:
        raise ValueError(f"min_size must be >= 1, got {min_size}")
    if hist_screen is not None:
        # imported here: check.device is a consumer of the engine
        from ..check.device import as_screens, fold_verified, screen_ok

        if wl.history is None:
            raise ValueError(
                f"hist_screen judges operation histories, but workload "
                f"{wl.name!r} has Workload.history=None"
            )
        screens = as_screens(hist_screen)
        hist_fields = ("hist_word", "hist_t", "hist_count", "hist_drop")
        missing = [f for f in hist_fields if f not in fields]
        if missing:
            raise ValueError(
                f"hist_screen needs the history columns banked; "
                f"fields is missing {missing}"
            )
    else:
        screens = None

    def _bank(st: SimState, idx: jnp.ndarray) -> dict:
        out = {f: getattr(st, f) for f in fields}
        if screens is not None:
            # bank-time device verification + prefix-compaction: the
            # verdict judges the FULL history (identical to screening
            # the uncompacted run), then clean seeds' responded pairs
            # fold out of what ships to the host
            ok = screen_ok(
                screens, st.hist_word, st.hist_t, st.hist_count,
                st.hist_drop,
            )
            w2, t2, c2, fold = fold_verified(
                st.hist_word, st.hist_t, st.hist_count, st.hist_drop, ok
            )
            out["hist_word"], out["hist_t"], out["hist_count"] = w2, t2, c2
            out["hist_ok"], out["hist_fold"] = ok, fold
        out["_idx"] = idx
        return out

    def compiled(state: SimState, idx_offset=0):
        """The phase program. Shapes are static per input size, so the
        same traced function serves the full batch (make_run_compacted)
        or one device's shard (parallel.shard_run_compacted, which
        passes the shard's global row offset as ``idx_offset``)."""
        s0 = state.seed.shape[0]
        sizes = _phase_sizes(s0, shrink, min_size)
        idx = jnp.arange(s0, dtype=jnp.int32) + jnp.asarray(idx_offset, jnp.int32)
        steps = jnp.int64(0)
        st = state
        banked = []

        for next_size in list(sizes[1:]) + [0]:

            def cond(carry, _n=next_size):
                s, i = carry
                live = jnp.sum(~s.halted)
                return (i < max_steps) & (live > _n)

            def body(carry):
                s, i = carry
                return step(s), i + 1

            st, steps = lax.while_loop(cond, body, (st, steps))
            if next_size == 0:
                banked.append(_bank(st, idx))
                break
            # stable partition: live rows first, halted tail banked.
            # Stability keeps the relative order of live rows, so the
            # kept prefix is a pure row-subset of the lockstep batch.
            order = jnp.argsort(st.halted, stable=True)
            tail = order[next_size:]
            banked.append(_bank(jax.tree.map(lambda a: a[tail], st), idx[tail]))
            head = order[:next_size]
            st = jax.tree.map(lambda a: a[head], st)
            idx = idx[head]

        return banked

    # no donate_argnums: banked phase-0 rows alias the input buffers, so
    # XLA can't actually reuse them (it would only warn); the one extra
    # input-sized allocation is cheap next to the loop carries
    jitted = jax.jit(compiled)

    out_fields = fields if screens is None else fields + SCREEN_FIELDS

    def assemble(banked) -> SimpleNamespace:
        """Device->host transfer + scatter back into original seed order.

        Under a ``hist_screen``, the folded history columns transfer
        only up to the longest surviving record count across the banks
        (fetched first — one tiny counter read): the fold's whole point
        is that the big (rows, H, ...) column transfer shrinks to the
        pending-invoke prefix plus the flagged seeds' full histories.
        """
        s0 = sum(np.asarray(b["_idx"]).shape[0] for b in banked)
        trim = {}
        if screens is not None:
            kept = max(
                (int(np.asarray(b["hist_count"]).max(initial=0))
                 for b in banked),
                default=0,
            )
            trim = {"hist_word": kept, "hist_t": kept}
        out = {}
        for f in out_fields:
            proto = banked[0][f]
            buf = np.zeros((s0,) + tuple(proto.shape[1:]), proto.dtype)
            k = trim.get(f)
            for b in banked:
                if k is None:
                    buf[np.asarray(b["_idx"])] = np.asarray(b[f])
                else:
                    # device-side slice: only the surviving prefix
                    # crosses the boundary (rows past hist_count are
                    # zero by the fold, so the untransferred tail of
                    # the host buffer is value-identical)
                    buf[np.asarray(b["_idx"]), :k] = np.asarray(b[f][:, :k])
            out[f] = buf
        return SimpleNamespace(**out)

    def run(state: SimState) -> SimpleNamespace:
        return assemble(jax.block_until_ready(jitted(state)))

    # benchmark seam: time `compute` (device work only, block on device
    # arrays) and call `assemble` outside the window — keeps the metric
    # methodologically identical to timing the lockstep loop, where the
    # host read also happened after the timed region
    run.compute = jitted
    run.assemble = assemble
    # sharding seam: the raw phase program, for parallel.shard_run_compacted
    run.phases = compiled
    return run
