"""ctypes bridge to the C++ single-seed oracle (native/oracle.cpp).

The oracle independently reimplements the engine's integer semantics and
the benchmark workloads; :func:`run_oracle` runs one seed and returns the
fields the bit-identical trace compare checks (the batched-engine analog
of the reference's replay determinism checker, runtime/mod.rs:165-190).

The shared library is built on demand with ``make -C native`` (g++ is in
the image; pybind11 is not, hence the plain C ABI + ctypes).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from dataclasses import dataclass

import numpy as np

from .core import EngineConfig, Workload

# the oracle's parameter registers and optional event-log buffers are
# process globals (oracle.cpp g_* / g_log_*), so every set_params ->
# oracle_run window must be serialized process-wide. Reentrant so
# replay() can hold it across its attach -> run_oracle -> detach span.
ORACLE_LOCK = threading.RLock()

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE = os.path.join(_REPO, "native")
_LIB = os.path.join(_NATIVE, "lib", "liboracle.so")

WORKLOAD_IDS = {
    "pingpong": 0,
    "microbench": 1,
    "raft-election": 2,
    "broadcast": 3,
    "kvchaos": 4,
    "kvchaos-payload": 4,  # same C++ workload; payload flag via set_params
    "twophase": 5,
    "raftlog": 6,
    "paxos": 7,
    "snapshot": 8,
}

_lib = None


def build() -> str:
    """Build (if stale) and return the shared library path."""
    src = os.path.join(_NATIVE, "oracle.cpp")
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(src):
        subprocess.run(["make", "-C", _NATIVE], check=True, capture_output=True)
    return _LIB


def load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build())
        lib.oracle_run.restype = ctypes.c_int32
        lib.oracle_run.argtypes = [
            ctypes.c_int32, ctypes.c_uint64, ctypes.c_int64,  # wl, seed, steps
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # pool, lat lo/hi
            ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64,  # loss, proc lo/hi
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # backoff lo/hi, limit
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.oracle_threefry2x32.argtypes = [
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
        ]
        _lib = lib
    return _lib


@dataclass
class OracleResult:
    now: int
    trace: int
    msg_count: int
    halted: bool
    halt_time: int
    overflow: int
    node_state: np.ndarray  # (N, U) int32


def set_params(lib: ctypes.CDLL, wl: Workload, **model_kwargs) -> None:
    """Push model factory parameters into the oracle's compiled workload."""
    if wl.name == "pingpong":
        lib.oracle_set_pingpong(
            ctypes.c_int32(model_kwargs["rounds"]),
            ctypes.c_int32(model_kwargs.get("n_clients", 2)),
        )
    elif wl.name == "microbench":
        lib.oracle_set_microbench(
            ctypes.c_int32(model_kwargs["rounds"]),
            ctypes.c_int64(model_kwargs.get("delay_min_ns", 1_000)),
            ctypes.c_int64(model_kwargs.get("delay_max_ns", 1_000_000)),
        )
    elif wl.name == "raft-election":
        lib.oracle_set_raft(
            ctypes.c_int32(model_kwargs.get("n_nodes", 5)),
            ctypes.c_int64(model_kwargs.get("timeout_min_ns", 150_000_000)),
            ctypes.c_int64(model_kwargs.get("timeout_max_ns", 300_000_000)),
        )
    elif wl.name == "broadcast":
        lib.oracle_set_broadcast(
            ctypes.c_int32(model_kwargs.get("rounds", 5)),
            ctypes.c_int32(model_kwargs.get("n_nodes", 5)),
            ctypes.c_int64(model_kwargs.get("retx_ns", 50_000_000)),
            ctypes.c_int32(1 if model_kwargs.get("partition", True) else 0),
        )
    elif wl.name == "twophase":
        lib.oracle_set_twophase(
            ctypes.c_int32(model_kwargs.get("txns", 5)),
            ctypes.c_int32(model_kwargs.get("n_parts", 4)),
            ctypes.c_int32(model_kwargs.get("no_pct", 10)),
            ctypes.c_int64(model_kwargs.get("retx_ns", 40_000_000)),
            ctypes.c_int32(1 if model_kwargs.get("chaos", True) else 0),
            ctypes.c_int64(model_kwargs.get("revive_min_ns", 80_000_000)),
            ctypes.c_int64(model_kwargs.get("revive_max_ns", 400_000_000)),
        )
    elif wl.name in ("kvchaos", "kvchaos-payload"):
        lib.oracle_set_kvchaos(
            ctypes.c_int32(model_kwargs.get("writes", 20)),
            ctypes.c_int32(model_kwargs.get("n_replicas", 4)),
            ctypes.c_int64(model_kwargs.get("retx_ns", 40_000_000)),
            ctypes.c_int64(model_kwargs.get("client_retx_ns", 100_000_000)),
            ctypes.c_int32(1 if model_kwargs.get("chaos", True) else 0),
            ctypes.c_int32(1 if wl.payload_words else 0),
        )
    elif wl.name == "raftlog":
        rc = lib.oracle_set_raftlog(
            ctypes.c_int32(model_kwargs.get("n_nodes", 5)),
            ctypes.c_int32(model_kwargs.get("n_writes", 4)),
            ctypes.c_int64(model_kwargs.get("timeout_min_ns", 150_000_000)),
            ctypes.c_int64(model_kwargs.get("timeout_max_ns", 300_000_000)),
            ctypes.c_int64(model_kwargs.get("propose_ns", 20_000_000)),
            ctypes.c_int64(model_kwargs.get("retx_ns", 60_000_000)),
            ctypes.c_int32(1 if model_kwargs.get("chaos", True) else 0),
        )
        if rc:
            raise ValueError("oracle payload arena caps n_writes at 4")
    elif wl.name == "paxos":
        lib.oracle_set_paxos(
            ctypes.c_int32(model_kwargs.get("n_acceptors", 5)),
            ctypes.c_int32(model_kwargs.get("n_proposers", 3)),
            ctypes.c_int64(model_kwargs.get("start_min_ns", 5_000_000)),
            ctypes.c_int64(model_kwargs.get("start_max_ns", 30_000_000)),
            ctypes.c_int64(model_kwargs.get("timeout_min_ns", 60_000_000)),
            ctypes.c_int64(model_kwargs.get("timeout_max_ns", 120_000_000)),
            ctypes.c_int32(1 if model_kwargs.get("chaos", True) else 0),
            ctypes.c_int64(model_kwargs.get("kill_min_ns", 30_000_000)),
            ctypes.c_int64(model_kwargs.get("kill_max_ns", 150_000_000)),
            ctypes.c_int64(model_kwargs.get("revive_min_ns", 80_000_000)),
            ctypes.c_int64(model_kwargs.get("revive_max_ns", 300_000_000)),
            ctypes.c_int32(
                1 if model_kwargs.get("durable_acceptors", False) else 0
            ),
        )
    elif wl.name == "snapshot":
        lib.oracle_set_snapshot(
            ctypes.c_int32(model_kwargs.get("n_nodes", 5)),
            ctypes.c_int32(model_kwargs.get("n_sends", 6)),
            ctypes.c_int32(model_kwargs.get("balance", 1000)),
            ctypes.c_int32(model_kwargs.get("amount_max", 100)),
            ctypes.c_int64(model_kwargs.get("send_min_ns", 5_000_000)),
            ctypes.c_int64(model_kwargs.get("send_max_ns", 25_000_000)),
            ctypes.c_int64(model_kwargs.get("snap_min_ns", 20_000_000)),
            ctypes.c_int64(model_kwargs.get("snap_max_ns", 80_000_000)),
        )
    else:
        raise ValueError(f"oracle has no implementation of workload {wl.name!r}")


def _plan_kinds(plan) -> set:
    """Static kind set of a chaos plan, whatever form it travels in
    (FaultPlan via its slot templates, LiteralPlan via its events)."""
    if hasattr(plan, "slot_templates"):
        return {int(t.kind) for t in plan.slot_templates()}
    if hasattr(plan, "events"):
        return {int(e.kind) for e in plan.events}
    raise TypeError(f"not a chaos plan: {type(plan).__name__}")


def assert_plan_oracle_free(plan) -> None:
    """Refuse an oracle compare against a plan-driven engine run.

    The oracle has no plan channel at all, and in particular does not
    implement the extended chaos kinds (engine/core.py 244+ — slow
    links, duplication, skew, one-way clogs, and the disk-fault kinds
    SYNC_LOSS/TORN). Before this guard a caller comparing a plan-driven
    engine sweep against ``run_oracle`` would silently diverge on the
    first injected event; now the mismatch is a designed error naming
    the supported verification path.
    """
    from .core import FIRST_EXT_KIND

    kinds = _plan_kinds(plan)
    ext = sorted(k for k in kinds if k >= FIRST_EXT_KIND)
    if ext:
        raise ValueError(
            f"the C++ oracle does not implement extended chaos kinds "
            f"{ext} (engine kinds >= {FIRST_EXT_KIND}: slow-link/dup/"
            f"skew/one-way-clog and the SYNC_LOSS/TORN disk faults); "
            f"plan-driven runs are verified by the two-run/two-layout "
            f"compare instead (engine.verify.check_layouts / "
            f"compare_traces)"
        )
    raise ValueError(
        "the C++ oracle takes no fault plan (plans are pre-seeded "
        "engine pool rows, a channel the oracle does not have); verify "
        "plan-driven runs with the two-run/two-layout compare instead "
        "(engine.verify.check_layouts / compare_traces)"
    )


def run_oracle(
    wl: Workload, cfg: EngineConfig, seed: int, n_steps: int, plan=None,
    **model_kwargs,
) -> OracleResult:
    """Run one seed through the C++ oracle.

    ``plan`` exists only to fail loudly: the oracle cannot execute
    chaos plans (see :func:`assert_plan_oracle_free`), so passing one
    raises the designed "verified by two-run/two-layout compare
    instead" error rather than silently comparing a faulted engine run
    against an unfaulted oracle run. Sync-discipline workloads
    (``Workload.durable_sync``) ARE comparable as long as they sync
    every durable write in the dispatch that made it — the trajectory
    is then identical to the verbatim-durable semantics the oracle
    implements (raftlog ``durable=True`` relies on exactly this).
    """
    if plan is not None:
        assert_plan_oracle_free(plan)
    lib = load()
    with ORACLE_LOCK:
        return _run_locked(lib, wl, cfg, seed, n_steps, **model_kwargs)


def _run_locked(
    lib, wl: Workload, cfg: EngineConfig, seed: int, n_steps: int, **model_kwargs
) -> OracleResult:
    set_params(lib, wl, **model_kwargs)
    # push the workload's initial rows so nonzero init_state (and the
    # restart-restores-initial-rows path) stays bit-identical
    init_rows = np.ascontiguousarray(wl.initial_state(), dtype=np.int32)
    lib.oracle_set_init_state(
        init_rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(init_rows.size),
    )
    # durable (restart-surviving) columns — always pushed, so a prior
    # run's setting can't leak into a workload without any
    dur = np.asarray(sorted(wl.durable_cols or ()), dtype=np.int32)
    lib.oracle_set_durable_cols(
        dur.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)) if dur.size else None,
        ctypes.c_int64(dur.size),
    )
    now = ctypes.c_int64()
    trace = ctypes.c_uint64()
    msg_count = ctypes.c_int64()
    halted = ctypes.c_int32()
    halt_time = ctypes.c_int64()
    overflow = ctypes.c_int32()
    node_state = np.zeros((wl.n_nodes, wl.state_width), np.int32)
    rc = lib.oracle_run(
        ctypes.c_int32(WORKLOAD_IDS[wl.name]),
        ctypes.c_uint64(seed),
        ctypes.c_int64(n_steps),
        ctypes.c_int64(cfg.pool_size),
        ctypes.c_int64(cfg.lat_min_ns),
        ctypes.c_int64(cfg.lat_max_ns),
        ctypes.c_uint64(cfg.loss_u32),
        ctypes.c_int64(cfg.proc_min_ns),
        ctypes.c_int64(cfg.proc_max_ns),
        ctypes.c_int64(cfg.clog_backoff_min_ns),
        ctypes.c_int64(cfg.clog_backoff_max_ns),
        ctypes.c_int64(cfg.time_limit_ns),
        ctypes.byref(now),
        ctypes.byref(trace),
        ctypes.byref(msg_count),
        ctypes.byref(halted),
        ctypes.byref(halt_time),
        ctypes.byref(overflow),
        node_state.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        raise RuntimeError(f"oracle_run failed with rc={rc}")
    return OracleResult(
        now=now.value,
        trace=trace.value,
        msg_count=msg_count.value,
        halted=bool(halted.value),
        halt_time=halt_time.value,
        overflow=overflow.value,
        node_state=node_state,
    )


def oracle_threefry(k0: int, k1: int, x0: int, x1: int) -> tuple[int, int]:
    lib = load()
    o0 = ctypes.c_uint32()
    o1 = ctypes.c_uint32()
    lib.oracle_threefry2x32(k0, k1, x0, x1, ctypes.byref(o0), ctypes.byref(o1))
    return o0.value, o1.value
