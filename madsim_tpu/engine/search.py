"""Batched chaos-schedule search: hunt seeds that violate an invariant.

The reference's multi-seed runner executes ``MADSIM_TEST_NUM`` seeds and
prints a repro banner for the first failure (reference
madsim/src/sim/runtime/builder.rs:110-148, runtime/mod.rs:193-200). At
engine scale the same idea becomes a *search*: sweep tens of thousands
of seeded chaos schedules in one batched run (BASELINE.md config 5 —
"4,096-seed chaos schedule search") and report every seed whose final
state breaks a user invariant, each with the exact repro recipe.

    report = search_seeds(
        wl, cfg,
        invariant=lambda view: view["node_state"][:, 0, 0] >= 1,
        n_seeds=4096, max_steps=900,
    )
    report.failing_seeds  # -> np.ndarray of violating seeds
    report.banner()       # -> repro lines, seed + config hash each

The invariant is a host-side predicate over the final batched state
(numpy views), returning a boolean array over the seed axis — True =
invariant holds. Deterministic by construction: re-running any failing
seed (alone or in any batch) reproduces the identical trace.

Final-state predicates cannot see operations that were lost along the
way; for workloads with ``Workload.history`` the sweep also accepts a
``history_invariant`` over the recorded per-seed operation histories
(madsim_tpu.check) — the FoundationDB-style workload check.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Mapping

import numpy as np

import jax

from .compact import RESULT_FIELDS, SCREEN_FIELDS, make_run_compacted
from .core import (
    EngineConfig,
    Workload,
    _resolve_time32,
    make_init,
    make_run_while,
)

__all__ = ["SearchReport", "make_sweep", "search_seeds"]

# compiled-run cache: repeated searches over the same (workload, config,
# step budget, layout) — the tool's own repro workflow — reuse the XLA
# program instead of re-tracing per call (jit's cache keys on function
# identity, so a fresh closure per call would defeat it). Entries hold
# obs.prof.AotProgram wrappers, so every build is phase-timed and
# retrace-counted (the flight-recorder attribution), and the build
# share of a dispatch is separable from execution
# (SearchReport.build_wall_s).
_RUN_CACHE: dict = {}

# compiled device-verification programs, keyed on the screen tuple (a
# value-hashable invariant identity — check.device.HistoryScreen): the
# screen kernels + the verdict-word pack as ONE jitted program applied
# to the run's device-resident history columns. AotProgram entries, so
# retraces are counted like every other cached program.
_SCREEN_CACHE: dict = {}


def _screen_prog(screens: tuple):
    prog = _SCREEN_CACHE.get(screens)
    if prog is None:
        from ..check.device import pack_verdicts, screen_ok
        from ..obs.prof import AotProgram

        def run_screen(word, t, count, drop):
            ok = screen_ok(screens, word, t, count, drop)
            return pack_verdicts(ok)

        prog = _SCREEN_CACHE[screens] = AotProgram(
            "engine.search.screen", screens, run_screen
        )
    return prog


def _build_init_run(wl: Workload, cfg: EngineConfig, max_steps: int, *,
                    layout=None, plan_slots: int = 0, dup_rows: bool = False,
                    cov_words: int = 0, metrics: bool = False,
                    timeline_cap: int = 0, cov_hitcount: bool = False,
                    latency=None, compact: bool = False,
                    pool_index: bool | None = None, hist_screen=None,
                    causal: bool = False, retry=None):
    # the ONE construction of a batched sweep's (init, run) pair —
    # make_sweep (the device-composable form) and search_seeds' cached
    # runner both build through here, so a flag added to one path cannot
    # silently miss the other and break host/device bit-identity
    if pool_index is None:
        # resolve pool_index HERE, against the layout this sweep will
        # actually run (core.resolve_layout — the ONE default rule),
        # and hand the same concrete bool to init and run: a forced
        # layout= can then never make the two builders'
        # auto-resolutions disagree (make_step's trace-time shape
        # guard would catch it, but loudly failing a sweep over a
        # resolvable default is worse than resolving it)
        from .core import _resolve_pool_index, resolve_layout

        pool_index = _resolve_pool_index(
            cfg, None, dense=resolve_layout(layout) == "dense"
        )
    obs_kw = dict(
        metrics=metrics, timeline_cap=timeline_cap,
        cov_hitcount=cov_hitcount, latency=latency, causal=causal,
        retry=retry,
    )
    init = make_init(wl, cfg, plan_slots=plan_slots, cov_words=cov_words,
                     pool_index=pool_index, **obs_kw)
    if compact:
        run = make_run_compacted(
            wl, cfg, max_steps, layout=layout, dup_rows=dup_rows,
            cov_words=cov_words, pool_index=pool_index,
            hist_screen=hist_screen, **obs_kw,
        )
    else:
        # the lockstep path screens AFTER the run, as a separate cached
        # program over the still-device-resident columns (_screen_prog)
        run = make_run_while(
            wl, cfg, max_steps, layout=layout, dup_rows=dup_rows,
            cov_words=cov_words, pool_index=pool_index, **obs_kw,
        )
    return init, run


def make_sweep(
    wl: Workload,
    cfg: EngineConfig,
    max_steps: int,
    *,
    layout=None,
    plan_slots: int = 0,
    dup_rows: bool = False,
    cov_words: int = 0,
    metrics: bool = False,
    timeline_cap: int = 0,
    cov_hitcount: bool = False,
    latency=None,
    pool_index: bool | None = None,
    causal: bool = False,
    retry=None,
):
    """Build the traceable batched sweep: ``sweep(seeds[, rows]) -> view``.

    The device-resident form of one ``search_seeds`` dispatch: init the
    seed batch (with the compiled ``PlanRows`` when ``plan_slots > 0``),
    run ``make_run_while`` to the step cap, and return the final state
    as a ``{field name: device array}`` view — NO host transfer, no
    invariant evaluation, and the function is jit/shard_map-traceable,
    so callers can fuse it into larger device programs (the explore
    device driver composes it with on-device mutation and admission;
    ``search_seeds`` wraps it with the host-side report instead).
    """
    init, run = _build_init_run(
        wl, cfg, max_steps, layout=layout, plan_slots=plan_slots,
        dup_rows=dup_rows, cov_words=cov_words, metrics=metrics,
        timeline_cap=timeline_cap, cov_hitcount=cov_hitcount,
        latency=latency, pool_index=pool_index, causal=causal,
        retry=retry,
    )

    def sweep(seeds, rows=None):
        out = run(init(seeds, rows) if plan_slots else init(seeds))
        return {
            f.name: getattr(out, f.name) for f in dataclasses.fields(out)
        }

    return sweep


def _compiled_run(wl: Workload, cfg: EngineConfig, max_steps: int, layout,
                  compact: bool, plan_slots: int = 0, dup_rows: bool = False,
                  cov_words: int = 0, metrics: bool = False,
                  timeline_cap: int = 0, cov_hitcount: bool = False,
                  latency=None, pool_index: bool | None = None,
                  hist_screen=None, causal: bool = False, retry=None):
    # plan VALUES are runtime data (PlanRows arrays); only the slot count
    # and the dup-path flag shape the compiled program, so one cache
    # entry serves every plan of the same width. The env-defaulted
    # knobs (pool_index auto threshold, rank-place crossover) are
    # resolved BEFORE keying: a knob change mid-process must build a
    # fresh program, not silently reuse one baked under the old value
    from .core import (
        _resolve_pool_index,
        resolve_layout,
        resolve_rank_place_max_pool,
    )

    if pool_index is None:
        pool_index = _resolve_pool_index(
            cfg, None, dense=resolve_layout(layout) == "dense"
        )
    key = (id(wl), cfg.hash(), max_steps, layout, compact, plan_slots,
           dup_rows, cov_words, metrics, timeline_cap, cov_hitcount,
           latency, pool_index, resolve_rank_place_max_pool(),
           hist_screen, causal, retry)
    if key not in _RUN_CACHE:
        # imported here: obs is a consumer of the engine — a module-level
        # import would run the whole obs package during engine import
        from ..obs.prof import AotProgram

        init, run = _build_init_run(
            wl, cfg, max_steps, layout=layout, plan_slots=plan_slots,
            dup_rows=dup_rows, cov_words=cov_words, metrics=metrics,
            timeline_cap=timeline_cap, cov_hitcount=cov_hitcount,
            latency=latency, compact=compact, pool_index=pool_index,
            hist_screen=hist_screen, causal=causal, retry=retry,
        )
        # make_run_compacted jits internally per growth stage (its
        # build wall stays inside dispatch — documented limitation)
        _RUN_CACHE[key] = (
            init,
            run if compact else AotProgram("engine.search.run", key, run),
            wl,  # keep the workload alive so id() stays unique
        )
    return _RUN_CACHE[key]


@dataclasses.dataclass
class SearchReport:
    """Outcome of one batched invariant sweep."""

    workload: str
    config_hash: str
    seeds: np.ndarray  # every seed searched
    ok: np.ndarray  # (S,) bool — invariant held
    halted: np.ndarray  # (S,) bool
    overflowed: np.ndarray  # (S,) bool — event-pool or history-buffer
    # drops: verdict unreliable
    traces: np.ndarray  # (S,) uint64 — per-seed trace hashes
    # max per-seed step coordinate. Under compact=True the per-row step
    # counters freeze when a row is banked out, so this equals the
    # lockstep loop's iteration count only for the last-halting seed
    # (per-seed values are still bit-identical between the two paths).
    steps: int
    # fault-plan hash when the sweep ran under a chaos plan: the repro
    # key is then (seed, config, plan) — all three printed in the banner
    plan_hash: str = ""
    # wall this call spent building (trace + lower + compile) its run
    # program — nonzero only on a cold compiled-run cache entry or a
    # signature change. Callers timing the dispatch subtract this to
    # get pure execution wall (the explore drivers' compile_wall_s
    # split); 0.0 on the compact path, whose staged internal jits are
    # not separable.
    build_wall_s: float = 0.0
    # per-seed coverage bitmaps, (S, cov_words) uint32 — None unless the
    # sweep ran with cov_words > 0 (madsim_tpu.explore)
    cov: np.ndarray | None = None
    # (S,) int64 per-seed halt clock (0 while running) — the causal
    # horizon explore's mutators use to avoid perturbing post-halt slots
    halt_times: np.ndarray | None = None
    # observability columns (madsim_tpu.obs) — None unless the sweep
    # ran with the corresponding tap enabled:
    # (S, N_METRICS) per-seed fleet counters (metrics=True); reduce
    # fleet-wide with obs.fleet_reduce
    met: np.ndarray | None = None
    # per-seed timeline ring views (timeline_cap > 0): a namespace of
    # tl_count/tl_drop/tl_t/tl_meta/tl_args arrays, each seed-leading;
    # decode one seed's stream with obs.decode_timeline(report.timeline,
    # wl, i)
    timeline: object | None = None
    # overflow breakdown: which channel voided which seeds. overflowed
    # stays the union the quarantine uses; tl_dropped does NOT void a
    # verdict (the timeline is forensics, not evidence) but is loud in
    # the banner
    pool_overflowed: np.ndarray | None = None
    hist_dropped: np.ndarray | None = None
    tl_dropped: np.ndarray | None = None
    # tail-latency columns (latency=LatencySpec(...)): the per-seed
    # log-linear sketches (S, phases, N_LAT_BUCKETS) and completed-op
    # counts — reduce fleet-wide with obs.latency_reduce; SLO verdicts
    # come from check.slo_bounded as the sweep's invariant. lat_dropped
    # flags seeds whose markers named op ids outside LatencySpec.ops —
    # their sketches undercount, so it is loud in the banner (the
    # tl_drop rule: forensic loudness, verdicts judge what WAS folded)
    lat_hist: np.ndarray | None = None
    lat_count: np.ndarray | None = None
    lat_dropped: np.ndarray | None = None
    # device-verification columns (device_check=...): the per-seed
    # screen verdict (True = clean), its packed uint32 transfer form
    # (ceil(S/32) words — what actually crossed the device boundary on
    # the lockstep path), and the escalation input: the FULL histories
    # of exactly the seeds the screen flagged (and that did not
    # overflow), as a check.BatchHistory over flagged_idx rows — feed
    # these to the exact Wing–Gong checker for confirmation (the PR-1
    # cross-check discipline). hist_fold (compact path only) counts
    # records prefix-compaction folded out per seed.
    screen_ok: np.ndarray | None = None
    verdict_words: np.ndarray | None = None
    flagged_idx: np.ndarray | None = None
    flagged_history: object | None = None
    hist_fold: np.ndarray | None = None
    # causal provenance (causal=True): the final per-node Lamport
    # clocks, (S, N) uint32 — per-seed causal depth/width stats reduce
    # with obs.fleet_reduce(lam=...); with timeline_cap the ring's
    # tl_seq/tl_parent/tl_lam columns ride report.timeline and
    # obs.causal.causal_slice computes violation cones from them
    lam: np.ndarray | None = None

    @property
    def failing_seeds(self) -> np.ndarray:
        """Violations on seeds whose simulation was trustworthy (no
        pool or history overflow — see :attr:`overflowed_seeds`)."""
        return self.seeds[~self.ok & ~self.overflowed]

    @property
    def unhalted_seeds(self) -> np.ndarray:
        """Seeds still running at max_steps — schedules the step budget
        could not finish (raise max_steps or treat as liveness bugs)."""
        return self.seeds[~self.halted]

    @property
    def overflowed_seeds(self) -> np.ndarray:
        """Seeds whose event pool dropped events (raise
        ``cfg.pool_size``) or whose history buffer dropped records
        (raise ``HistorySpec.capacity`` / the model's
        ``hist_capacity``): their verdicts are simulator artifacts, not
        evidence — fix the capacity and re-search (the same rule
        bench.py applies to its metric)."""
        return self.seeds[self.overflowed]

    def banner(self, limit: int = 10) -> str:
        """Repro recipe per failing seed (runtime/mod.rs:193-200 shape),
        with the per-seed halt/overflow breakdown when available."""
        bad = self.failing_seeds
        s = len(self.seeds)
        lines = [
            f"chaos search over {s} seeds of "
            f"{self.workload!r}: {len(bad)} violation(s)",
        ]
        n_halt = int(np.asarray(self.halted).sum())
        if self.met is not None:
            # metrics carry the per-seed halt reason (engine HALT_* codes)
            from .core import (
                HALT_DONE,
                HALT_IDLE,
                HALT_TIME_LIMIT,
                MET_HALT_CODE,
            )
            codes = np.asarray(self.met)[:, MET_HALT_CODE]
            done = int((codes == HALT_DONE).sum())
            tlim = int((codes == HALT_TIME_LIMIT).sum())
            idle = int((codes == HALT_IDLE).sum())
            running = s - n_halt - idle
            lines.append(
                f"  halted {n_halt}/{s}: {done} workload-halt, "
                f"{tlim} time-limit; {idle} idle (empty pool), "
                f"{running} still running at the step cap"
            )
        elif n_halt < s:
            lines.append(
                f"  halted {n_halt}/{s}; {s - n_halt} still running at "
                f"the step cap (run with metrics=True for the halt-"
                f"reason breakdown)"
            )
        if self.overflowed.any():
            pool = (
                int(np.asarray(self.pool_overflowed).sum())
                if self.pool_overflowed is not None else 0
            )
            hist = (
                int(np.asarray(self.hist_dropped).sum())
                if self.hist_dropped is not None else 0
            )
            detail = f" (pool {pool}, history {hist})" if pool or hist else ""
            lines.append(
                f"  WARNING: {int(self.overflowed.sum())} seed(s) "
                f"overflowed the event pool or history buffer{detail}; "
                f"excluded (raise pool_size / HistorySpec capacity)"
            )
        if self.tl_dropped is not None and self.tl_dropped.any():
            lines.append(
                f"  WARNING: {int(self.tl_dropped.sum())} seed(s) "
                f"overflowed the timeline ring (raise timeline_cap; "
                f"verdicts unaffected — the timeline is forensics only)"
            )
        if self.lat_dropped is not None and self.lat_dropped.any():
            lines.append(
                f"  WARNING: {int(self.lat_dropped.sum())} seed(s) "
                f"dropped latency markers (op ids outside "
                f"LatencySpec.ops) — their sketches undercount; size "
                f"LatencySpec.ops to cover every army op id"
            )
        if self.screen_ok is not None:
            n_flag = (
                len(self.flagged_idx) if self.flagged_idx is not None
                else int((~self.screen_ok).sum())
            )
            fold = (
                f", {int(self.hist_fold.sum())} records prefix-compacted"
                if self.hist_fold is not None else ""
            )
            lines.append(
                f"  device screen: {n_flag} flagged seed(s) escalated "
                f"with full histories ({len(self.verdict_words)} verdict "
                f"words transferred{fold})"
            )
        plan = f" plan_hash={self.plan_hash}" if self.plan_hash else ""
        for s in bad[:limit]:
            lines.append(
                f"  seed {int(s)}: rerun with seeds=[{int(s)}] "
                f"config_hash={self.config_hash}{plan}"
            )
        if len(bad) > limit:
            lines.append(f"  ... and {len(bad) - limit} more")
        return "\n".join(lines)


def _state_view(out, keep_device: tuple = ()) -> Mapping[str, np.ndarray]:
    """Host-side numpy views of EVERY final-state field, keyed by name
    (the checkpoint.py pattern) — invariants can reach anything,
    including paused/clog chaos state and the raw event pool.
    ``keep_device`` names stay as device arrays (a device-checked sweep
    never materializes the big history columns on the host — that is
    the transfer the verdict words replace)."""
    return {
        f.name: (
            getattr(out, f.name) if f.name in keep_device
            else np.asarray(getattr(out, f.name))
        )
        for f in dataclasses.fields(out)
    }


def search_seeds(
    wl: Workload,
    cfg: EngineConfig,
    invariant: Callable[[Mapping[str, np.ndarray]], np.ndarray] | None,
    n_seeds: int = 4096,
    max_steps: int = 1000,
    seed_base: int = 0,
    require_halt: bool = True,
    layout: str | None = None,
    compact: bool = False,
    history_invariant: Callable | None = None,
    plan=None,
    seeds: np.ndarray | None = None,
    plan_rows=None,
    plan_hash: str | None = None,
    dup_rows: bool | None = None,
    cov_words: int = 0,
    metrics: bool = False,
    timeline_cap: int = 0,
    cov_hitcount: bool = False,
    latency=None,
    pool_index: bool | None = None,
    device_check=None,
    causal: bool = False,
    retry=None,
) -> SearchReport:
    """Run ``n_seeds`` chaos schedules and evaluate ``invariant`` on the
    final states.

    ``require_halt=True`` (default) additionally counts a seed that
    never halts within ``max_steps`` as a violation — an unfinished
    schedule means the scenario's goal condition was never reached,
    which is exactly the liveness bug a chaos search is hunting.

    ``compact=True`` runs the seed-compaction path (engine/compact.py):
    typically 2-3x faster on halting workloads, per-seed values
    identical — but the invariant's view then contains only the banked
    result fields (RESULT_FIELDS: seed/now/step/halted/halt_time/trace/
    overflow/msg_count/node_state plus the history columns), not the
    raw event pool or clog/alive arrays. Invariants over ``node_state``
    (the overwhelmingly common kind) are unaffected.

    ``history_invariant`` makes the sweep a *workload* check instead of
    a final-state check: it receives a ``check.history.BatchHistory``
    over the recorded operation histories of every seed at once and
    returns a ``(n_seeds,)`` boolean array (True = history clean).
    Requires ``wl.history``; composes with ``invariant`` (a seed must
    pass both), and ``invariant=None`` means history-only. Seeds that
    overflowed the history buffer are quarantined exactly like event-
    pool overflows: their verdicts land in ``overflowed_seeds``, never
    in ``failing_seeds`` — the invariant sees them as *empty* histories
    (count 0, drop 0), so strict per-seed checkers
    (``BatchHistory.ops``) can run over every seed without crashing on
    one whose verdict would be discarded anyway.

    ``plan`` injects a declarative fault plan (``madsim_tpu.chaos``):
    each seed's plan compiles to its own deterministic fault trajectory
    (pre-seeded event-pool rows), the nemesis analog of the reference's
    hand-rolled per-model chaos. The plan hash joins the repro banner —
    ``(seed, config, plan)`` is then the complete repro key. Requires
    ``cfg.pool_size >= n_nodes + plan.slots``.

    The coverage-guided exploration loop (``madsim_tpu.explore``) uses
    three extensions: ``seeds`` replaces the contiguous
    ``seed_base..+n_seeds`` range with an explicit seed array (mutated
    corpora draw fresh threefry-derived seeds, not consecutive ints);
    ``plan_rows`` injects PRE-COMPILED per-seed plan rows — every row
    may carry a *different* mutated plan, which no single ``plan``
    object can express (pass ``plan_hash`` to label the banner, and
    ``dup_rows=True`` if any row uses duplication); ``cov_words=CW``
    runs the engine's coverage taps and returns the per-seed bitmaps
    as ``report.cov`` (S, CW).

    The observability taps (madsim_tpu.obs) ride the same way:
    ``metrics=True`` returns per-seed fleet counters as ``report.met``
    (S, N_METRICS) and upgrades the banner with the halt-reason
    breakdown; ``timeline_cap=T`` captures each seed's dispatched-event
    stream (``report.timeline``, decode with ``obs.decode_timeline``);
    ``cov_hitcount=True`` switches the coverage bitmaps to AFL-style
    hit-count bucketing; ``latency=LatencySpec(...)`` runs the
    tail-latency tap (client-army op clocks + per-seed sketches,
    ``report.lat_hist``/``lat_count`` — reduce with
    ``obs.latency_reduce``, judge with ``check.slo_bounded`` as the
    invariant). All of them are derived state only — the traces and
    verdicts are bit-identical with them off or on.

    ``pool_index`` picks the readiness-partitioned pool lowering
    (make_step docstring; value-identical, auto on for CPU scatter
    pools past the crossover) — it keys the compiled-run cache like
    every other build flag.

    ``retry`` arms the client-retry timers (``engine.RetrySpec``, make_
    step docstring). With ``plan`` it defaults to the plan's own policy
    — ``plan.retry_spec()`` when some army carries a
    ``chaos.RetryPolicy`` — so a policied plan sweeps retried without
    further wiring; pass ``retry=`` explicitly on the ``plan_rows``
    path (pre-compiled rows carry no policy object).

    ``causal=True`` folds exact causal provenance (make_step docstring):
    the final per-node Lamport clocks return as ``report.lam`` (S, N)
    and — with ``timeline_cap`` — the ring gains the
    ``tl_seq``/``tl_parent``/``tl_lam`` DAG columns, which
    ``obs.causal.causal_slice`` turns into the backward happens-before
    cone of a violation. Derived state only, like every tap here.

    ``device_check`` (a ``check.device.HistoryScreen`` or tuple of
    them) is the device-resident form of ``history_invariant``
    (mutually exclusive with it): the batch detectors run as jnp
    kernels over the still-device-resident history columns, and the
    host receives **packed verdict words** (``report.verdict_words``,
    one bit per seed) plus the *flagged* seeds' full histories
    (``report.flagged_history`` — the exact-checker escalation input)
    instead of every seed's columns. Verdicts are bit-identical to the
    numpy path (``check.device.screens_invariant(screens)`` is the
    reference arm); overflowed seeds are quarantined identically. With
    ``compact=True`` the screen additionally runs at bank time inside
    the compacted program and **prefix-compacts** the banked columns
    (``report.hist_fold`` counts the folded records; flagged seeds
    keep full histories — see ``make_run_compacted``).
    """
    if history_invariant is not None and wl.history is None:
        raise ValueError(
            f"history_invariant needs operation histories, but workload "
            f"{wl.name!r} has Workload.history=None"
        )
    screens = None
    if device_check is not None:
        from ..check.device import as_screens

        screens = as_screens(device_check)
        if wl.history is None:
            raise ValueError(
                f"device_check judges operation histories, but workload "
                f"{wl.name!r} has Workload.history=None"
            )
        if history_invariant is not None:
            raise ValueError(
                "pass device_check OR history_invariant, not both: they "
                "are the same verdict on two execution paths (compare "
                "them via check.device.screens_invariant in a test, not "
                "in one sweep)"
            )
    if invariant is None and history_invariant is None and screens is None:
        raise ValueError(
            "need an invariant, a history_invariant or a device_check"
        )
    if plan is not None and plan_rows is not None:
        raise ValueError("pass plan OR plan_rows, not both")
    if seeds is None:
        seeds = np.arange(seed_base, seed_base + n_seeds, dtype=np.uint64)
    else:
        seeds = np.asarray(seeds, np.uint64)
        if seeds.ndim != 1:
            raise ValueError(f"seeds must be 1-D, got shape {seeds.shape}")
        n_seeds = len(seeds)
    if plan is not None:
        plan_slots = int(plan.slots)
        if dup_rows is None:
            dup_rows = bool(plan.uses_dup())
        if latency is not None:
            # a client army whose op-id range exceeds the latency
            # columns would silently drop every out-of-range marker
            # (counted in lat_drop, but a whole mis-sized army is a
            # build error, not a runtime anomaly)
            for spec in getattr(plan, "specs", ()):
                ob = getattr(spec, "op_base", None)
                no = getattr(spec, "n_ops", None)
                if ob is not None and no is not None and ob + no > latency.ops:
                    raise ValueError(
                        f"{type(spec).__name__} op ids "
                        f"[{ob}, {ob + no}) exceed LatencySpec.ops="
                        f"{latency.ops}; size the spec to cover every "
                        f"army op id"
                    )
        if cfg.time_limit_ns and hasattr(plan, "validate_windows"):
            # a fault window opening after the clock cap can never fire:
            # the sweep would silently certify the unfaulted protocol
            # (chaos.FaultPlan.validate_windows — warn loudly here,
            # clamp explicitly via plan.clamped(...))
            plan.validate_windows(cfg.time_limit_ns)
        rows = plan.compile_batch(seeds, wl=wl)
        if plan_hash is None:
            plan_hash = plan.hash()
        if retry is None and hasattr(plan, "retry_spec"):
            retry = plan.retry_spec()
    elif plan_rows is not None:
        rows = plan_rows
        plan_slots = int(np.asarray(rows.time).shape[1])
        if np.asarray(rows.time).shape[0] != n_seeds:
            raise ValueError(
                f"plan_rows carries {np.asarray(rows.time).shape[0]} rows "
                f"for {n_seeds} seeds"
            )
        dup_rows = bool(dup_rows)
    else:
        rows = None
        plan_slots = 0
        dup_rows = bool(dup_rows)
    init, run, _ = _compiled_run(
        wl, cfg, max_steps, layout, compact, plan_slots, dup_rows,
        cov_words, metrics, timeline_cap, cov_hitcount, latency,
        pool_index,
        # only the compacted program embeds the screen (bank-time fold);
        # the lockstep path screens via _screen_prog, so its run cache
        # entry must stay shared with unscreened sweeps
        hist_screen=screens if compact else None,
        causal=causal, retry=retry,
    )
    if rows is not None:
        if _resolve_time32(wl, cfg, None):
            # the compiled rows land in the int32 offset representation:
            # a plan event past the horizon would silently wrap
            from .core import _T32_LIMIT

            lim = _T32_LIMIT - cfg.proc_max_ns - 1
            worst = int(np.asarray(rows.time).max(initial=0))
            if worst > lim:
                raise ValueError(
                    f"fault-plan event at t={worst} ns exceeds the int32 "
                    f"time horizon ({lim} ns) active for this (workload, "
                    f"config); shrink the plan windows or disable time32"
                )
        state0 = init(seeds, rows)
    else:
        state0 = init(seeds)
    if compact:
        out = run(state0)
        fields = RESULT_FIELDS if screens is None else (
            RESULT_FIELDS + SCREEN_FIELDS
        )
        view = {f: getattr(out, f) for f in fields}
    else:
        out = jax.block_until_ready(run(state0))
        view = _state_view(
            out,
            keep_device=("hist_word", "hist_t") if screens is not None
            else (),
        )
    if invariant is not None:
        ok = np.asarray(invariant(view), dtype=bool)
        if ok.shape != (n_seeds,):
            raise ValueError(
                f"invariant must return a ({n_seeds},) boolean array, "
                f"got shape {ok.shape}"
            )
    else:
        ok = np.ones((n_seeds,), dtype=bool)
    pool_overflowed = np.asarray(view["overflow"]) > 0
    overflowed = pool_overflowed
    dev_ok = None
    verdict_words = None
    flagged_idx = None
    flagged_history = None
    if screens is not None:
        from ..check.device import pack_verdicts_host, unpack_verdicts
        from ..check.history import BatchHistory

        if compact:
            # bank-time verdicts (computed on device BEFORE the fold)
            dev_ok = np.asarray(view["hist_ok"], bool)
            verdict_words = pack_verdicts_host(dev_ok)
        else:
            # THE history transfer of a device-checked sweep: ceil(S/32)
            # packed words instead of (S, H, 5) + (S, H) columns
            verdict_words = np.asarray(
                _screen_prog(screens)(
                    out.hist_word, out.hist_t, out.hist_count,
                    out.hist_drop,
                )
            )
            dev_ok = unpack_verdicts(verdict_words, n_seeds)
        ok = ok & dev_ok
        # escalation: exactly the flagged (and trustworthy) seeds ship
        # their FULL histories to the host — the Wing–Gong
        # confirmation input, the PR-1 cross-check discipline
        hist_drop_np = np.asarray(view["hist_drop"])
        flagged_idx = np.nonzero(~dev_ok & ~(hist_drop_np > 0))[0]
        w, tt = view["hist_word"], view["hist_t"]
        flagged_history = BatchHistory(
            # device gather + transfer of only the flagged rows on the
            # lockstep path; plain numpy take on the compact path
            # (whose columns arrived prefix-compacted, flagged seeds
            # verbatim-full by construction)
            word=np.asarray(w[flagged_idx]),
            t=np.asarray(tt[flagged_idx]),
            count=np.asarray(view["hist_count"])[flagged_idx],
            drop=hist_drop_np[flagged_idx],
        )
    if history_invariant is not None:
        # imported here: check is a consumer of the engine, not a
        # dependency (engine -> check at module import would be a cycle)
        from ..check.history import BatchHistory

        bh = BatchHistory.from_view(view)
        hist_over = np.asarray(bh.drop) > 0
        if hist_over.any():
            # overflowed seeds reach the invariant as EMPTY histories:
            # their verdicts are discarded by the quarantine below, and
            # a strict per-seed checker (BatchHistory.ops) must not
            # crash the whole sweep on a seed it will never judge. The
            # raw truncated columns stay available on the result view.
            bh = BatchHistory(
                word=bh.word, t=bh.t,
                count=np.where(hist_over, 0, np.asarray(bh.count)).astype(
                    np.int32
                ),
                drop=np.zeros_like(np.asarray(bh.drop)),
            )
        hok = np.asarray(history_invariant(bh), dtype=bool)
        if hok.shape != (n_seeds,):
            raise ValueError(
                f"history_invariant must return a ({n_seeds},) boolean "
                f"array, got shape {hok.shape}"
            )
        ok = ok & hok
    hist_dropped = None
    if wl.history is not None:
        # dropped history records void the verdict (loud, like pool
        # overflow) whether or not a history predicate ran
        hist_dropped = np.asarray(view["hist_drop"]) > 0
        overflowed = overflowed | hist_dropped
    halted = view["halted"]
    if require_halt:
        ok = ok & halted
    if timeline_cap:
        from types import SimpleNamespace

        tl = SimpleNamespace(**{
            f: np.asarray(view[f])
            for f in ("tl_count", "tl_drop", "tl_t", "tl_meta",
                      "tl_args", "tl_pay", "tl_emit")
            + (("tl_seq", "tl_parent", "tl_lam") if causal else ())
        })
        tl_dropped = tl.tl_drop > 0
    else:
        tl, tl_dropped = None, None
    return SearchReport(
        workload=wl.name,
        config_hash=cfg.hash(),
        seeds=seeds,
        ok=ok,
        halted=halted,
        overflowed=overflowed,
        traces=view["trace"],
        steps=int(np.asarray(out.step).max()),
        plan_hash=plan_hash or "",
        build_wall_s=getattr(run, "last_build_s", 0.0),
        cov=np.asarray(view["cov"]) if cov_words else None,
        halt_times=np.asarray(view["halt_time"]),
        met=np.asarray(view["met"]) if metrics else None,
        timeline=tl,
        pool_overflowed=pool_overflowed,
        hist_dropped=hist_dropped,
        tl_dropped=tl_dropped,
        lat_hist=np.asarray(view["lat_hist"]) if latency is not None else None,
        lat_count=(
            np.asarray(view["lat_count"]) if latency is not None else None
        ),
        lat_dropped=(
            np.asarray(view["lat_drop"]) > 0 if latency is not None else None
        ),
        screen_ok=dev_ok,
        verdict_words=verdict_words,
        flagged_idx=flagged_idx,
        flagged_history=flagged_history,
        hist_fold=(
            np.asarray(view["hist_fold"])
            if screens is not None and compact else None
        ),
        lam=np.asarray(view["lam"]) if causal else None,
    )
