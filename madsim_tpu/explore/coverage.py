"""Coverage accounting for the exploration loop.

The engine's coverage taps (engine/core.py, ``cov_words``) hand back one
AFL-style bitmap per seed: a set bit is a behavior feature the seed
exhibited (a per-node event-kind transition, a chaos kind in a time
phase, a history-record word). This module turns those per-seed bitmaps
into the two quantities the corpus loop needs:

* **admission** — for each entry of a generation, IN BATCH ORDER, how
  many bits it sets that neither the global map nor any earlier entry of
  the same generation set. Sequential semantics matter: two mutants that
  discover the same new behavior must not both be admitted. The scan
  runs on device (``lax.scan`` + popcount over uint32 words), so raw
  trace data never crosses to the host — only the (B,) new-bit counts
  and the merged (CW,) map do.
* **merging / counting** — plain OR-folds and popcounts, used by the
  equal-budget uniform-baseline comparison (tools/explore_soak.py) and
  the sharded form in madsim_tpu.parallel.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["admit", "merge", "popcount"]


def popcount(bitmap) -> int:
    """Total set bits of a coverage bitmap (any shape of uint32 words)."""
    words = np.ascontiguousarray(np.asarray(bitmap, np.uint32))
    return int(np.unpackbits(words.view(np.uint8)).sum())


def merge(bitmaps) -> np.ndarray:
    """OR-fold (S, CW) per-seed bitmaps into one (CW,) global map."""
    return np.bitwise_or.reduce(np.asarray(bitmaps, np.uint32), axis=0)


@jax.jit
def _admit_scan(global_map, cov_batch):
    def body(carry, row):
        fresh = jnp.sum(lax.population_count(row & ~carry)).astype(jnp.int32)
        return carry | row, fresh

    return lax.scan(body, global_map, cov_batch)


def admit(cov_batch, global_map):
    """Sequential-admission pass over one generation.

    ``cov_batch`` is the (B, CW) uint32 bitmaps of the generation in
    batch order; ``global_map`` the (CW,) map before this generation.
    Returns ``(new_bits, merged)``: ``new_bits[j]`` counts bits entry j
    set that neither the global map nor entries 0..j-1 set (the corpus
    keeps entry j iff ``new_bits[j] > 0``), and ``merged`` is the
    global map with the whole generation folded in.
    """
    merged, news = _admit_scan(
        jnp.asarray(global_map, jnp.uint32), jnp.asarray(cov_batch, jnp.uint32)
    )
    return np.asarray(news), np.asarray(merged)
