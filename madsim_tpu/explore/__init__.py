"""madsim_tpu.explore — coverage-guided schedule exploration.

MadSim finds rare interleavings by brute chaos: sweep enough random
seeds and hope. The exploration subsystem upgrades the batched engine
from that blind sweep into an AFL-style greybox fuzzer over
distributed-protocol state space:

* **on-device coverage** (engine/core.py ``cov_words``) — every seed
  folds behavior features (per-node event-kind transitions, chaos kind
  x time-phase markers, history-record words) into a per-seed bitmap;
  only bitmaps and popcount deltas cross to the host, never raw traces;
* **a corpus** of interesting ``(seed, LiteralPlan)`` entries — kept
  iff they set new bits in the global coverage map (or violate);
* **a mutation engine** (explore/mutate.py) — retime / retarget /
  drop / add over the plan's slots, every draw threefry-keyed from the
  campaign's root seed;
* **the driver** (explore/driver.py) — each generation is ONE vmapped
  batch through the engine's compiled-run cache; violations carry a
  complete ``(root seed, generation, entry id)`` repro key and feed
  ``chaos.shrink_plan`` directly.

Evidence artifact: ``tools/explore_soak.py`` (EXPLORE_r08.txt) — at
equal simulation budget the guided loop reaches more coverage and
multiplies violation counts over the uniform nemesis sweep.
"""

from .coverage import admit, merge, popcount  # noqa: F401
from .device import run_device  # noqa: F401
from .driver import (  # noqa: F401
    CorpusEntry,
    ExploreReport,
    replay_entry,
    run,
)
from .mutate import (  # noqa: F401
    HostStream,
    PlanSpace,
    mutate_plan,
    mutation_table,
)
from .persist import (  # noqa: F401
    CampaignState,
    load_campaign,
    save_campaign,
)

__all__ = [
    "CampaignState",
    "CorpusEntry",
    "ExploreReport",
    "HostStream",
    "PlanSpace",
    "admit",
    "load_campaign",
    "merge",
    "mutate_plan",
    "mutation_table",
    "popcount",
    "replay_entry",
    "run",
    "run_device",
    "save_campaign",
]
