"""Campaign corpus save / resume.

A coverage-guided hunt is an investment: the corpus and the global
coverage map ARE the knowledge a campaign has accumulated, and before
this module they died with the process (``LiteralPlan.to_dict``
serialized single entries, but nothing carried a whole campaign). A
:class:`CampaignState` checkpoints exactly the loop state the driver
threads between generations — corpus entries (each an exact-replay
``(seed, LiteralPlan)`` pair), violations, the coverage map, the
dedup set and the id/generation counters — as one JSON document, so

    rep = explore.run(wl, cfg, space, generations=4, batch=256,
                      checkpoint_path="hunt.json")
    # ... later, a different session ...
    rep2 = explore.run(wl, cfg, space, generations=4, batch=256,
                       resume="hunt.json")

continues the SAME campaign: because every draw is keyed by the
absolute generation index (driver ``_derive_keys``), a resumed run is
bit-identical to the uninterrupted one — corpus, coverage map and
violation set all match (the test pins it). Python ints serialize
losslessly in JSON, so uint64 seeds and trace hashes round-trip exact.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

from ..chaos.plan import LiteralPlan
from .driver import CorpusEntry

__all__ = ["CampaignState", "resolve_resume", "save_campaign",
           "load_campaign"]

_FORMAT = 1


def _entry_to_dict(e: CorpusEntry) -> dict:
    return {
        "id": e.id,
        "generation": e.generation,
        "parent": e.parent,
        "seed": int(e.seed),
        "plan": e.plan.to_dict(),
        "trace": int(e.trace),
        "cov": [int(w) for w in np.asarray(e.cov, np.uint32)],
        "new_bits": e.new_bits,
        "violating": e.violating,
        "halt_t": int(e.halt_t),
    }


def _entry_from_dict(d: dict) -> CorpusEntry:
    return CorpusEntry(
        id=int(d["id"]),
        generation=int(d["generation"]),
        parent=int(d["parent"]),
        seed=int(d["seed"]),
        plan=LiteralPlan.from_dict(d["plan"]),
        trace=int(d["trace"]),
        cov=np.asarray(d["cov"], np.uint32),
        new_bits=int(d["new_bits"]),
        violating=bool(d["violating"]),
        halt_t=int(d.get("halt_t", 0)),
    )


@dataclasses.dataclass
class CampaignState:
    """Everything ``explore.run`` threads between generations.

    ``corpus`` and ``violations`` may share entries (a violating entry
    is usually admitted too); serialization stores each entry once and
    reconstitutes the sharing by id.
    """

    workload: str
    config_hash: str
    plan_hash: str
    root_seed: int
    batch: int
    cov_words: int
    cov_hitcount: bool
    generations_done: int
    next_id: int
    sims: int
    curve: list
    viol_curve: list
    cov_map: np.ndarray  # (CW,) uint32
    corpus: list  # list[CorpusEntry], admission order
    violations: list  # list[CorpusEntry] (includes corpus-capped finds)

    @classmethod
    def from_report(cls, report) -> "CampaignState":
        """Snapshot a finished campaign from its ExploreReport."""
        return cls(
            workload=report.workload,
            config_hash=report.config_hash,
            plan_hash=report.plan_hash,
            root_seed=report.root_seed,
            batch=report.batch,
            cov_words=report.cov_words,
            cov_hitcount=getattr(report, "cov_hitcount", False),
            generations_done=report.generations,
            next_id=report.next_id,
            sims=report.sims,
            curve=list(report.curve),
            viol_curve=list(report.viol_curve),
            cov_map=np.asarray(report.cov_map, np.uint32),
            corpus=list(report.corpus),
            violations=list(report.violations),
        )

    def to_dict(self) -> dict:
        entries: dict[int, CorpusEntry] = {}
        for e in list(self.corpus) + list(self.violations):
            entries[e.id] = e
        return {
            "format": _FORMAT,
            "workload": self.workload,
            "config_hash": self.config_hash,
            "plan_hash": self.plan_hash,
            "root_seed": int(self.root_seed),
            "batch": self.batch,
            "cov_words": self.cov_words,
            "cov_hitcount": self.cov_hitcount,
            "generations_done": self.generations_done,
            "next_id": self.next_id,
            "sims": self.sims,
            "curve": list(self.curve),
            "viol_curve": list(self.viol_curve),
            "cov_map": [int(w) for w in np.asarray(self.cov_map, np.uint32)],
            "entries": [
                _entry_to_dict(entries[i]) for i in sorted(entries)
            ],
            "corpus_ids": [e.id for e in self.corpus],
            "violation_ids": [e.id for e in self.violations],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CampaignState":
        if d.get("format") != _FORMAT:
            raise ValueError(
                f"unknown campaign checkpoint format {d.get('format')}"
            )
        entries = {
            int(ed["id"]): _entry_from_dict(ed) for ed in d["entries"]
        }
        return cls(
            workload=d["workload"],
            config_hash=d["config_hash"],
            plan_hash=d["plan_hash"],
            root_seed=int(d["root_seed"]),
            batch=int(d["batch"]),
            cov_words=int(d["cov_words"]),
            cov_hitcount=bool(d.get("cov_hitcount", False)),
            generations_done=int(d["generations_done"]),
            next_id=int(d["next_id"]),
            sims=int(d["sims"]),
            curve=list(d["curve"]),
            viol_curve=list(d["viol_curve"]),
            cov_map=np.asarray(d["cov_map"], np.uint32),
            corpus=[entries[int(i)] for i in d["corpus_ids"]],
            violations=[entries[int(i)] for i in d["violation_ids"]],
        )

    def save(self, path: str) -> None:
        # write-then-rename: the checkpoint is overwritten after every
        # generation, and a kill mid-dump must not destroy the only
        # copy of the campaign it exists to preserve
        tmp = f"{path}.tmp"
        with open(tmp, "w") as fh:
            json.dump(self.to_dict(), fh)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "CampaignState":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))


def resolve_resume(resume, wl, space, cfg, root_seed: int, batch: int,
                   cov_words: int, cov_hitcount: bool) -> CampaignState:
    """Load (path or state) and validate a campaign checkpoint against
    this run's arguments — shared by BOTH campaign drivers
    (explore.run and explore.run_device), so a field added to the
    identity tuple cannot be validated on one path and silently
    accepted on the other."""
    st = CampaignState.load(resume) if isinstance(resume, str) else resume
    for what, got, want in (
        ("workload", st.workload, wl.name),
        ("plan-space hash", st.plan_hash, space.hash()),
        ("config hash", st.config_hash, cfg.hash()),
        ("root seed", st.root_seed, int(root_seed)),
        ("batch", st.batch, batch),
        ("cov_words", st.cov_words, cov_words),
        ("cov_hitcount", st.cov_hitcount, cov_hitcount),
    ):
        if got != want:
            raise ValueError(
                f"campaign checkpoint {what} mismatch: saved {got!r}, "
                f"this run has {want!r} — resuming would break the "
                f"pure-function-of-root-seed contract"
            )
    return st


def save_campaign(path: str, report) -> CampaignState:
    """Checkpoint a finished campaign's ExploreReport to ``path``."""
    st = CampaignState.from_report(report)
    st.save(path)
    return st


def load_campaign(path: str) -> CampaignState:
    return CampaignState.load(path)
